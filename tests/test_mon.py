"""Monitor tests: elections, Paxos replication, EC profile CRUD, pool
create, subscriptions, failure quorum.

Models the mon behaviors in SURVEY.md §2.7: OSDMonitor.cc:6859-6915 profile
commands, :7437 stripe_unit validation, :2791 failure quorum; Paxos.cc
collect/begin/accept/commit; ElectionLogic rank elections.
"""

import asyncio
import json

import pytest

from ceph_tpu.mon import MonMap, Monitor
from ceph_tpu.mon.client import MonClient
from ceph_tpu.msg.messages import MOSDBoot, MOSDFailure
from ceph_tpu.osd.osdmap import OSDMap


def free_port_addrs(n):
    import socket

    addrs = {}
    socks = []
    for i in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        addrs[chr(ord("a") + i)] = f"127.0.0.1:{s.getsockname()[1]}"
    for s in socks:
        s.close()
    return addrs


async def start_mons(n, timeout=0.3):
    monmap = MonMap(addrs=free_port_addrs(n))
    mons = [Monitor(name, monmap, election_timeout=timeout) for name in monmap.addrs]
    mons.sort(key=lambda m: m.rank)  # ranks follow sorted address order
    for m in mons:
        await m.start()
    for m in mons:
        await m.wait_for_quorum()
    return monmap, mons


async def stop_mons(mons):
    for m in mons:
        await m.stop()
    await asyncio.sleep(0.05)


class TestSingleMon:
    def test_bootstrap_and_commands(self):
        async def run():
            monmap, mons = await start_mons(1)
            mon = mons[0]
            assert mon.is_leader()
            client = MonClient("client.test", monmap)
            # EC profile CRUD
            rv, rs, _ = await client.command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "p42",
                    "profile": ["k=4", "m=2", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            rv, _, out = await client.command(
                {"prefix": "osd erasure-code-profile get", "name": "p42"}
            )
            assert rv == 0
            prof = json.loads(out)
            assert prof["k"] == "4" and prof["m"] == "2"
            rv, _, out = await client.command(
                {"prefix": "osd erasure-code-profile ls"}
            )
            assert "p42" in json.loads(out)
            # pool create with stripe_unit validation
            rv, rs, _ = await client.command(
                {
                    "prefix": "osd pool create",
                    "pool": "ecpool",
                    "pool_type": "erasure",
                    "erasure_code_profile": "p42",
                }
            )
            assert rv == 0, rs
            rv, _, out = await client.command({"prefix": "osd dump"})
            dump = json.loads(out)
            pool = next(p for p in dump["pools"].values() if p["name"] == "ecpool")
            assert pool["size"] == 6
            assert pool["stripe_width"] == 4 * 4096
            # profile in use cannot be removed
            rv, rs, _ = await client.command(
                {"prefix": "osd erasure-code-profile rm", "name": "p42"}
            )
            assert rv < 0 and "in use" in rs
            await client.msgr.shutdown()
            await stop_mons(mons)

        asyncio.run(run())

    def test_bad_profile_rejected(self):
        async def run():
            monmap, mons = await start_mons(1)
            client = MonClient("client.test", monmap)
            rv, rs, _ = await client.command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "bad",
                    "profile": ["k=0", "m=2"],
                }
            )
            assert rv < 0
            await client.msgr.shutdown()
            await stop_mons(mons)

        asyncio.run(run())

    def test_osd_boot_and_subscription(self):
        async def run():
            monmap, mons = await start_mons(1)
            mon = mons[0]
            client = MonClient("osd.0", monmap)
            maps = []
            client.on_osdmap = maps.append
            await client.subscribe("osdmap", 0)
            await asyncio.sleep(0.1)
            assert maps, "initial map not delivered"
            # boot three osds
            for osd in range(3):
                await client.msgr.send_to(
                    monmap.addr_of_rank(0),
                    MOSDBoot(osd=osd, addr=f"127.0.0.1:{7000+osd}", epoch=0),
                )
            await asyncio.sleep(0.3)
            m = mon.osdmon.osdmap
            assert m.num_up_osds() == 3
            # subscriber saw the new epochs
            assert len(maps) >= 2
            # decode the latest published map
            last = maps[-1]
            if last.maps:
                decoded = OSDMap.frombytes(last.maps[max(last.maps)])
            else:
                decoded = None
            if decoded is not None:
                assert decoded.epoch == m.epoch
            await client.msgr.shutdown()
            await stop_mons(mons)

        asyncio.run(run())


class TestMultiMon:
    def test_election_and_replication(self):
        async def run():
            monmap, mons = await start_mons(3)
            leader = [m for m in mons if m.is_leader()]
            assert len(leader) == 1
            assert leader[0].rank == 0  # lowest rank wins
            client = MonClient("client.test", monmap)
            rv, rs, _ = await client.command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "rep3",
                    "profile": ["k=2", "m=1"],
                }
            )
            assert rv == 0, rs
            await asyncio.sleep(0.3)
            # committed state replicated to all quorum members
            for m in mons:
                assert "rep3" in m.osdmon.osdmap.erasure_code_profiles, m.name
            await client.msgr.shutdown()
            await stop_mons(mons)

        asyncio.run(run())

    def test_leader_failover(self):
        async def run():
            monmap, mons = await start_mons(3, timeout=0.2)
            assert mons[0].is_leader()
            client = MonClient("client.test", monmap)
            rv, _, _ = await client.command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "before",
                    "profile": ["k=2", "m=1"],
                }
            )
            assert rv == 0
            await asyncio.sleep(0.2)
            # leader dies; survivors elect rank 1
            await mons[0].stop()
            mons[1].elector.start()
            await asyncio.sleep(0.8)
            assert mons[1].is_leader()
            # new leader serves reads and accepts writes
            client._cur_rank = 1
            rv, _, out = await client.command(
                {"prefix": "osd erasure-code-profile ls"}
            )
            assert rv == 0 and "before" in json.loads(out)
            rv, rs, _ = await client.command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "after",
                    "profile": ["k=3", "m=2"],
                }
            )
            assert rv == 0, rs
            await asyncio.sleep(0.3)
            assert "after" in mons[2].osdmon.osdmap.erasure_code_profiles
            await client.msgr.shutdown()
            await stop_mons(mons[1:])

        asyncio.run(run())

    def test_failure_report_quorum(self):
        async def run():
            monmap, mons = await start_mons(1)
            mon = mons[0]
            client = MonClient("osd.9", monmap)
            for osd in range(3):
                await client.msgr.send_to(
                    monmap.addr_of_rank(0),
                    MOSDBoot(osd=osd, addr=f"127.0.0.1:{7100+osd}", epoch=0),
                )
            await asyncio.sleep(0.3)
            assert mon.osdmon.osdmap.num_up_osds() == 3
            # one reporter is not enough (min_down_reporters=2)
            fail = MOSDFailure(target=2, target_addr="", failed_for=25.0, epoch=0)
            fail.src = "osd.0"
            mon.osdmon.prepare_failure(fail, reporter="osd.0")
            await asyncio.sleep(0.2)
            assert mon.osdmon.osdmap.is_up(2)
            # second reporter crosses the quorum
            mon.osdmon.prepare_failure(fail, reporter="osd.1")
            await asyncio.sleep(0.2)
            assert not mon.osdmon.osdmap.is_up(2)
            await client.msgr.shutdown()
            await stop_mons(mons)

        asyncio.run(run())


class TestMonAdminSocket:
    def test_status_and_paxos_dumps(self, tmp_path):
        """Mon admin socket (Monitor::_add_admin_socket_commands):
        mon_status / quorum_status / paxosinfo over the unix socket."""

        async def run():
            from ceph_tpu.common.admin_socket import admin_command

            monmap = MonMap(addrs=free_port_addrs(3))
            path = str(tmp_path / "mon.a.asok")
            mons = []
            for i, name in enumerate(monmap.addrs):
                mons.append(
                    Monitor(
                        name, monmap, election_timeout=0.3,
                        admin_socket=path if i == 0 else "",
                    )
                )
            for m in mons:
                await m.start()
            for m in mons:
                await m.wait_for_quorum()
            loop = asyncio.get_event_loop()
            # Poll until this mon's view settles: peons learn the quorum
            # from the victory message, so leader AND peons report the
            # full member list.
            deadline = loop.time() + 8.0
            while True:
                st = await loop.run_in_executor(
                    None, lambda: admin_command(path, "mon_status")
                )
                if st["state"] in ("leader", "peon") and st["quorum"] == [0, 1, 2]:
                    break
                assert loop.time() < deadline, f"mon never settled: {st}"
                await asyncio.sleep(0.05)
            assert st["name"] == mons[0].name
            assert st["rank"] in st["quorum"]
            q = await loop.run_in_executor(
                None, lambda: admin_command(path, "quorum_status")
            )
            # same payload shape as the MMonCommand quorum_status handler
            assert q["leader"] is not None and q["quorum"] == [0, 1, 2]
            assert q["epoch"] >= 1
            p = await loop.run_in_executor(
                None, lambda: admin_command(path, "paxosinfo")
            )
            assert p["last_committed"] >= 0
            for m in mons:
                await m.stop()

        asyncio.run(run())


class TestStatusHealth:
    def test_health_summary_reflects_down_osds(self):
        """`ceph status` carries a mon-side health line: HEALTH_OK with
        everything up, HEALTH_WARN naming down OSDs after a failure."""

        async def run():
            from test_cluster import start_cluster, stop_cluster, wait_until
            from ceph_tpu.client import Rados

            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            rv, _, out = await client.mon_command({"prefix": "status"})
            assert rv == 0
            st = json.loads(out.decode())
            assert st["health"]["status"] == "HEALTH_OK"
            assert st["quorum"] == [0]
            await osds[2].stop()
            await wait_until(
                lambda: not mons[0].osdmon.osdmap.is_up(2), 8.0, "mark down"
            )
            rv, _, out = await client.mon_command({"prefix": "status"})
            st = json.loads(out.decode())
            assert st["health"]["status"] == "HEALTH_WARN"
            assert "osd.2" in st["health"]["checks"]["OSD_DOWN"]
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestMonRestart:
    def test_restarted_mon_catches_up_via_paxos(self):
        """A monitor restarting with an EMPTY store rejoins quorum and
        catches up every committed version from its peers (Paxos
        collect/LAST catch-up — the recovery path the reference drives
        from MonitorDBStore + sync)."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.mon import Monitor

            from test_cluster import start_cluster, stop_cluster, wait_until

            monmap, mons, osds = await start_cluster(3, 2)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("before", "replicated", size=2)
            # stop a PEON (killing the leader also works but re-elects)
            victim = next(m for m in mons if not m.is_leader())
            vname = victim.name
            await victim.stop()
            # state advances while it is gone
            await client.pool_create("while-down", "replicated", size=2)
            # restart with a FRESH Monitor (empty paxos store)
            revived = Monitor(vname, monmap, election_timeout=0.3)
            await revived.start()
            mons[mons.index(victim)] = revived
            await revived.wait_for_quorum()
            await wait_until(
                lambda: revived.osdmon.osdmap.get_pool("while-down")
                is not None,
                10.0,
                "revived mon catching up committed state",
            )
            assert revived.osdmon.osdmap.get_pool("before") is not None
            # and it participates in NEW commits
            await client.pool_create("after", "replicated", size=2)
            await wait_until(
                lambda: revived.osdmon.osdmap.get_pool("after") is not None,
                10.0,
                "revived mon applying new commits",
            )
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())
