"""Independent scalar re-derivation of ISA-L's erasure-code math.

This module is the "foreign" oracle for byte-parity tests
(tests/test_isal_golden.py): it implements GF(2^8) arithmetic and the
ISA-L matrix/encode algorithms from the PUBLISHED spec (isa-l ec_base.c:
gf_mul/gf_inv/gf_gen_rs_matrix/gf_gen_cauchy1_matrix/gf_invert_matrix/
ec_encode_data) using a deliberately different mechanism from
ceph_tpu.gf — carry-less "Russian peasant" polynomial multiplication and
pure-Python scalar loops, no log/exp tables, no numpy — so a systematic
error in the production tables cannot hide by matching itself.

No code is shared with ceph_tpu; importing it here would defeat the
point.  The reference plugin's contract is that its chunks equal
ISA-L's (/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:129
ec_encode_data); these vectors stand in for an ISA-L build, which this
image does not have.
"""

POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1, isa-l ec_base's field


def gf_mul(a: int, b: int) -> int:
    """Carry-less multiply + reduction (peasant algorithm)."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        a <<= 1
        if a & 0x100:
            a ^= POLY
        b >>= 1
    return r


def gf_pow(a: int, n: int) -> int:
    r = 1
    for _ in range(n):
        r = gf_mul(r, a)
    return r


def gf_inv(a: int) -> int:
    """Exhaustive inverse — O(256) but unarguable."""
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    for b in range(1, 256):
        if gf_mul(a, b) == 1:
            return b
    raise AssertionError("field element without inverse")


def gen_rs_matrix(k: int, m: int) -> list[list[int]]:
    """isa-l gf_gen_rs_matrix(a, k+m, k): identity over geometric rows of
    gen = 2^i (row 0 of the parity block is all ones)."""
    a = [[1 if i == j else 0 for j in range(k)] for i in range(k)]
    gen = 1
    for _ in range(m):
        p, row = 1, []
        for _ in range(k):
            row.append(p)
            p = gf_mul(p, gen)
        a.append(row)
        gen = gf_mul(gen, 2)
    return a


def gen_cauchy1_matrix(k: int, m: int) -> list[list[int]]:
    """isa-l gf_gen_cauchy1_matrix: parity[i][j] = 1 / ((k+i) ^ j)."""
    a = [[1 if i == j else 0 for j in range(k)] for i in range(k)]
    for i in range(k, k + m):
        a.append([gf_inv(i ^ j) for j in range(k)])
    return a


def invert_matrix(mat: list[list[int]]) -> list[list[int]] | None:
    """isa-l gf_invert_matrix: Gauss-Jordan with partial pivot."""
    n = len(mat)
    aug = [list(row) + [1 if i == j else 0 for j in range(n)]
           for i, row in enumerate(mat)]
    for col in range(n):
        pivot = next((r for r in range(col, n) if aug[r][col]), None)
        if pivot is None:
            return None
        aug[col], aug[pivot] = aug[pivot], aug[col]
        inv_p = gf_inv(aug[col][col])
        aug[col] = [gf_mul(x, inv_p) for x in aug[col]]
        for r in range(n):
            if r != col and aug[r][col]:
                f = aug[r][col]
                aug[r] = [x ^ gf_mul(f, y) for x, y in zip(aug[r], aug[col])]
    return [row[n:] for row in aug]


def encode(coding_rows: list[list[int]], data: list[bytes]) -> list[bytes]:
    """isa-l ec_encode_data, scalar: parity[p][x] = XOR_j c[p][j]*d[j][x]."""
    out = []
    for row in coding_rows:
        buf = bytearray(len(data[0]))
        for coeff, chunk in zip(row, data):
            if coeff == 0:
                continue
            if coeff == 1:
                for x, byte in enumerate(chunk):
                    buf[x] ^= byte
            else:
                for x, byte in enumerate(chunk):
                    buf[x] ^= gf_mul(coeff, byte)
        out.append(bytes(buf))
    return out


def decode_matrix(
    dist: list[list[int]], erasures: list[int], k: int
) -> tuple[list[list[int]], list[int]]:
    """ErasureCodeIsa.cc:255-297 decode assembly: invert the survivor
    submatrix; erased-data rows come straight from the inverse, erased-
    parity rows re-encode through it."""
    erased = set(erasures)
    survivors = [r for r in range(len(dist)) if r not in erased][:k]
    sub = [dist[r] for r in survivors]
    inv = invert_matrix(sub)
    if inv is None:
        raise AssertionError("singular survivor matrix")
    rows = []
    for e in erasures:
        if e < k:
            rows.append(inv[e])
        else:
            # erased parity: its dist row applied to the decoded data
            row = [0] * k
            for j in range(k):
                acc = 0
                for x in range(k):
                    acc ^= gf_mul(dist[e][x], inv[x][j])
                row[j] = acc
            rows.append(row)
    return rows, survivors


def lcg_bytes(n: int, seed: int) -> bytes:
    """Deterministic test data with no numpy dependency (musl LCG)."""
    out = bytearray(n)
    state = seed & 0xFFFFFFFF
    for i in range(n):
        state = (state * 1103515245 + 12345) & 0xFFFFFFFF
        out[i] = (state >> 16) & 0xFF
    return bytes(out)
