"""Full-cluster integration tests — the tier-3 standalone analog.

Models qa/standalone/erasure-code/test-erasure-code.sh (SURVEY.md §4.3):
boot real mon+OSD daemons on localhost loopback sockets, create pools
through mon commands, and exercise put/get round trips, failure
detection, degraded reads, and recovery — the whole §3.1/§3.2 call stack
over real (TCP) messengers instead of a pumped queue.
"""

import asyncio

import pytest

from ceph_tpu.client import Rados, RadosError
from ceph_tpu.common.config import Config
from ceph_tpu.mon import MonMap, Monitor
from ceph_tpu.osd.osd import OSD

from test_mon import free_port_addrs


def fast_conf(whoami: int) -> Config:
    return Config(
        {
            "name": f"osd.{whoami}",
            "osd_heartbeat_interval": 0.1,
            "osd_heartbeat_grace": 0.6,
        },
        env=False,
    )


async def start_cluster(n_mons: int, n_osds: int):
    monmap = MonMap(addrs=free_port_addrs(n_mons))
    mons = [Monitor(name, monmap, election_timeout=0.3) for name in monmap.addrs]
    for m in mons:
        await m.start()
    for m in mons:
        await m.wait_for_quorum()
    osds = [OSD(i, monmap, conf=fast_conf(i)) for i in range(n_osds)]
    for o in osds:
        await o.start()
    for o in osds:
        await o.wait_for_up()
    return monmap, mons, osds


async def stop_cluster(mons, osds):
    for o in osds:
        if o._running:
            await o.stop()
    for m in mons:
        await m.stop()
    await asyncio.sleep(0.05)


async def wait_until(pred, timeout: float, what: str = "") -> None:
    deadline = asyncio.get_event_loop().time() + timeout
    while not pred():
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(f"timed out waiting for {what}")
        await asyncio.sleep(0.02)


class TestReplicatedCluster:
    def test_put_get_roundtrip(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("rbdpool", "replicated", size=3, pg_num=4)
            ioctx = await client.open_ioctx("rbdpool")

            payload = bytes(range(256)) * 16
            await ioctx.write_full("obj1", payload)
            assert await ioctx.read("obj1") == payload
            assert await ioctx.stat("obj1") == len(payload)

            await ioctx.append("obj1", b"tail")
            assert await ioctx.read("obj1") == payload + b"tail"

            await ioctx.setxattr("obj1", "user.k", b"v1")
            assert await ioctx.getxattr("obj1", "user.k") == b"v1"

            await ioctx.remove("obj1")
            with pytest.raises(RadosError):
                await ioctx.stat("obj1")

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_replica_consistency(self):
        """Every replica OSD holds the object bytes (fan-out committed)."""

        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("rp", "replicated", size=3, pg_num=2)
            ioctx = await client.open_ioctx("rp")
            await ioctx.write_full("rep-obj", b"replicated-bytes")

            def replicas_have_it():
                holders = 0
                for o in osds:
                    for coll in o.store.list_collections():
                        try:
                            if b"replicated-bytes" in o.store.read(
                                coll, "rep-obj", 0, 0
                            ):
                                holders += 1
                                break
                        except Exception:
                            continue
                return holders == 3

            await wait_until(replicas_have_it, 3.0, "3 replicas")
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestErasureCodedCluster:
    def test_ec_pool_put_get(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 4)
            client = Rados(monmap)
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "k2m1",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("ecpool", "erasure", profile="k2m1", pg_num=4)
            ioctx = await client.open_ioctx("ecpool")

            # Multi-stripe object: 3 stripes of 2x4K + a partial tail.
            payload = bytes((i * 7 + 3) % 256 for i in range(3 * 8192 + 1000))
            await ioctx.write_full("big", payload)
            assert await ioctx.read("big") == payload
            assert await ioctx.stat("big") == len(payload)
            # ranged read crossing a stripe boundary
            assert await ioctx.read("big", 5000, 7000) == payload[7000:12000]

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_osd_failure_degraded_read_and_recovery(self):
        """Kill an OSD: heartbeat quorum marks it down, EC reads
        reconstruct, and the restarted OSD recovers via peering+push —
        the §3.2 decode path end to end over the wire."""

        async def run():
            monmap, mons, osds = await start_cluster(1, 4)
            client = Rados(monmap)
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "k2m1f",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("ecf", "erasure", profile="k2m1f", pg_num=2)
            ioctx = await client.open_ioctx("ecf")

            objs = {f"o{i}": bytes([i]) * (8192 + 100 * i) for i in range(4)}
            for oid, data in objs.items():
                await ioctx.write_full(oid, data)

            # Kill osd.3; survivors report it, mon needs 2 reporters.
            victim = osds[3]
            victim_store = victim.store
            await victim.stop()
            await wait_until(
                lambda: not mons[0].osdmon.osdmap.is_up(3),
                8.0,
                "mon marking osd.3 down",
            )

            # Degraded reads: every object still fully readable (k=2 of 3).
            for oid, data in objs.items():
                assert await ioctx.read(oid) == data, f"degraded read {oid}"

            # Write while degraded (a new object lands on remaining shards).
            await ioctx.write_full("during", b"D" * 8192)
            assert await ioctx.read("during") == b"D" * 8192

            # Restart osd.3 on its old store; peering computes the missing
            # set from the log delta and recovery pushes rebuilt shards.
            revived = OSD(3, monmap, conf=fast_conf(3), store=victim_store)
            await revived.start()
            await revived.wait_for_up()
            osds[3] = revived

            def all_recovered():
                return all(
                    pg.is_clean
                    for o in osds
                    if o._running
                    for pg in o.pgs.values()
                    if pg.peering.is_primary()
                )

            await wait_until(all_recovered, 10.0, "recovery to clean")
            for oid, data in objs.items():
                assert await ioctx.read(oid) == data
            assert await ioctx.read("during") == b"D" * 8192

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestAdminSocketIntrospection:
    def test_daemon_dumps_perf_config_and_traces(self, tmp_path):
        """The OSD admin socket (AdminSocket::init) serves perf counters,
        config, in-flight ops, and the EC data-path trace spans."""

        async def run():
            from ceph_tpu.common.admin_socket import admin_command
            from ceph_tpu.mon import MonMap, Monitor

            monmap = MonMap(addrs=free_port_addrs(1))
            mons = [Monitor(n, monmap, election_timeout=0.3) for n in monmap.addrs]
            for m in mons:
                await m.start()
                await m.wait_for_quorum()

            def conf(i):
                return Config(
                    {
                        "name": f"osd.{i}",
                        "osd_heartbeat_interval": 0.1,
                        "osd_heartbeat_grace": 0.6,
                        "admin_socket": str(tmp_path / f"osd.{i}.asok"),
                        "jaeger_tracing_enable": True,
                    },
                    env=False,
                )

            from ceph_tpu.osd.osd import OSD

            osds = [OSD(i, monmap, conf=conf(i)) for i in range(3)]
            for o in osds:
                await o.start()
            for o in osds:
                await o.wait_for_up()

            client = Rados(monmap)
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "ask21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("asok", "erasure", profile="ask21", pg_num=1)
            ioctx = await client.open_ioctx("asok")
            await ioctx.write_full("traced", b"T" * 8192)
            assert await ioctx.read("traced") == b"T" * 8192

            # find the PG's primary OSD: its tracer holds the write span
            primary = next(
                o
                for o in osds
                if any(p.peering.is_primary() for p in o.pgs.values())
            )
            sock = str(tmp_path / f"osd.{primary.whoami}.asok")

            # run the blocking unix-socket client off the event loop
            loop = asyncio.get_event_loop()
            dump = await loop.run_in_executor(
                None, lambda: admin_command(sock, "dump_tracer")
            )
            names = [s["name"] for s in dump["spans"]]
            assert "ec:write" in names and "ec:read" in names

            perf = await loop.run_in_executor(
                None, lambda: admin_command(sock, "perf dump")
            )
            assert perf["op"] >= 2

            cfg = await loop.run_in_executor(
                None, lambda: admin_command(sock, "config show")
            )
            assert cfg["jaeger_tracing_enable"] is True

            ops = await loop.run_in_executor(
                None, lambda: admin_command(sock, "dump_ops_in_flight")
            )
            assert ops["num_ops"] == 0  # everything committed

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestOpTracking:
    def test_historic_ops_dumped(self):
        """The OpTracker surfaces completed client ops (descriptions,
        events, durations) — dump_historic_ops' data source."""

        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("trackp", "replicated", pg_num=2)
            io = await client.open_ioctx("trackp")
            await io.write_full("tracked", b"x" * 512)
            assert await io.read("tracked") == b"x" * 512
            dumps = [o.op_tracker.dump_historic() for o in osds]
            ops = [op for d in dumps for op in d["ops"]]
            assert any("tracked" in op["description"] for op in ops)
            assert all(op["duration"] is not None for op in ops)
            assert any(
                e["event"] == "dequeued"
                for op in ops
                for e in op["type_data"]["events"]
            )
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestLostUnfound:
    def test_mark_unfound_lost_releases_blocked_ops(self):
        """The lost/unfound escape hatch (PrimaryLogPG
        mark_all_unfound_lost; qa ec_lost_unfound analog): an object
        missing with NO live source blocks every op touching it; the
        operator's mark_unfound_lost strikes it from the missing sets,
        deletes remnants, and blocked ops re-run to ENOENT.

        The unfound condition is FORGED on the primary (missing entries
        injected on every acting member) — producing it organically needs
        a multi-failure choreography the thrash tier doesn't model; the
        machinery under test (predicate, command, waiter release, delete
        fan-out) is the real path either way."""

        async def run():
            from ceph_tpu.osd.pg_log import Eversion

            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("lostp", "replicated", pg_num=2)
            io = await client.open_ioctx("lostp")
            await io.write_full("doomed", b"gone soon")
            assert await io.read("doomed") == b"gone soon"

            # find the primary PG and forge "missing everywhere"
            pool_id = client.objecter.osdmap.get_pool("lostp").id
            primary_pg = None
            for o in osds:
                for (pid, ps), pg in o.pgs.items():
                    if pid == pool_id and pg.peering.is_primary() and (
                        pg._object_exists("doomed")
                    ):
                        primary_pg = pg
                        break
            assert primary_pg is not None
            # destroy every replica's bytes UNDER the op path, then mark
            # the object missing everywhere: recovery now has no source
            from ceph_tpu.os.transaction import Transaction as StoreTxn
            from ceph_tpu.osd.pg_backend import shard_coll

            coll = shard_coll(primary_pg.pgid, -1)
            for o in osds:
                if o.store.exists(coll, "doomed"):
                    o.store.queue_transaction(StoreTxn().remove(coll, "doomed"))
            need = Eversion(1, 999)
            primary_pg.peering.missing.add("doomed", need)
            for m in primary_pg.peering.peer_missing.values():
                m.add("doomed", need)
            assert primary_pg.list_unfound() == ["doomed"]

            # ops on the object now queue behind (never-completing) recovery
            read_task = asyncio.get_event_loop().create_task(
                io.read("doomed")
            )
            await asyncio.sleep(0.3)
            assert not read_task.done(), "op should block on the unfound object"

            # blocked-op introspection names the stuck object + queue
            blocked = primary_pg.blocked_ops_summary()
            assert blocked.get("waiting_for_degraded", {}).get("doomed") == 1
            lost = primary_pg.mark_unfound_lost("delete")
            assert lost == ["doomed"]
            with pytest.raises(RadosError) as ei:
                await read_task
            assert ei.value.errno == -2  # ENOENT after the lost-delete
            assert primary_pg.list_unfound() == []
            # revert mode is explicitly unsupported
            with pytest.raises(ValueError):
                primary_pg.mark_unfound_lost("revert")
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())
