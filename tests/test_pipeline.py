"""ISSUE 11 tentpole contracts: the depth-N asynchronous launch
pipeline, the refcounted donation pool, and the device-resident chunk
cache.

Acceptance shape: launches dispatch into a bounded in-flight ring whose
records witness depth > 1; a wedge at depth > 1 host-fallbacks every
ticket byte-identically without losing the other in-flight groups; the
donation pool never recycles a live buffer (invariant gauge 0); and a
device-cache hit serves a degraded read with NO decode launch and a
flight record whose only span is the D2H copy (h2d_s == 0)."""

import time

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import DonationPool, EncodeAggregator
from ceph_tpu.common.fault_injector import global_injector
from ceph_tpu.ops import dispatch as ec_dispatch
from ceph_tpu.ops.device_cache import DeviceChunkCache, device_chunk_cache
from ceph_tpu.ops.flight_recorder import flight_recorder
from ceph_tpu.ops.guard import device_guard
from ceph_tpu.stripe import StripeInfo
from ceph_tpu.stripe import stripe as stripe_mod


@pytest.fixture(autouse=True)
def _clean_state():
    flight_recorder().reset()
    yield
    global_injector().clear()
    device_guard().mark_healthy()
    device_guard().configure(timeout_ms=20000, probe_interval_ms=2000)
    flight_recorder().reset()


def make_rs(k=4, m=2):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


def batches(n, shape=(2, 4, 512), seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, shape, dtype=np.uint8) for _ in range(n)]


class TestPipelineRing:
    def test_inflight_bounded_and_depth_witnessed(self):
        """window=2 / depth=2: the third launch drains the first, the
        ring never grows past depth+1, and the records carry the
        inflight_depth the pipeline actually reached."""
        ec = make_rs()
        agg = EncodeAggregator(window=2, pipeline_depth=2)
        pipe0 = ec_dispatch.PIPELINE.snapshot()
        data = batches(8)
        tickets = [agg.submit(ec, d) for d in data]
        agg.flush()
        for t, d in zip(tickets, data):
            assert np.array_equal(
                np.asarray(t), np.asarray(ec.encode_array_host(d))
            )
        pipe1 = ec_dispatch.PIPELINE.snapshot()
        assert pipe1["drains"] > pipe0["drains"], "ring never drained"
        recs = [
            r for r in flight_recorder().records()
            if r["kind"] == "encode" and r["group"] != "#raw"
        ]
        assert max(r["inflight_depth"] for r in recs) >= 2, recs
        # everything settled: nothing left in flight from this suite
        assert not agg._live

    def test_depth_zero_disables_ring(self):
        ec = make_rs()
        agg = EncodeAggregator(window=2, pipeline_depth=0)
        pipe0 = ec_dispatch.PIPELINE.snapshot()["drains"]
        tickets = [agg.submit(ec, d) for d in batches(8, seed=1)]
        agg.flush()
        for t in tickets:
            t.result()
        assert ec_dispatch.PIPELINE.snapshot()["drains"] == pipe0

    def test_configure_updates_depth(self):
        agg = EncodeAggregator(window=2, pipeline_depth=2)
        agg.configure(pipeline_depth=5)
        assert agg.pipeline_depth == 5
        assert ec_dispatch.PIPELINE.snapshot()["depth"] == 5

    def test_overlap_flag_on_already_finished_launch(self):
        """A launch whose device work completed before its reap is
        flagged `overlap` with a completion timestamp — the per-launch
        witness the bench overlap fraction aggregates."""
        ec = make_rs()
        agg = EncodeAggregator(window=0, pipeline_depth=0)
        pend = agg.submit(
            ec, batches(1, shape=(4, 4, 4096), seed=2)[0]
        )
        time.sleep(0.05)  # CPU backend: the async dispatch finishes
        np.asarray(pend)
        rec = [
            r for r in flight_recorder().records() if r["group"] != "#raw"
        ][-1]
        assert rec["complete_ts"] >= rec["dispatch_ts"], rec
        assert rec["flags"]["overlap"], rec


class TestWedgeAtDepth:
    def test_wedged_launches_at_depth_pay_one_deadline(self):
        """Every in-flight launch wedges AFTER dispatch (the runtime
        died under a full ring): the FIRST reap pays the deadline and
        marks DEGRADED; every other in-flight group's settle sees
        degraded + not-ready and goes straight to the host oracle — one
        deadline total, no ticket lost, no live buffer recycled."""
        ec = make_rs()
        agg = EncodeAggregator(window=1, pipeline_depth=8)
        device_guard().configure(timeout_ms=100, probe_interval_ms=10_000_000)
        real = ec.encode_array
        data = batches(4, shape=(2, 4, 512), seed=3)

        class _Wedged:
            """A device-array stand-in that never becomes ready."""

            def __init__(self, shape):
                self.shape = shape
                self.dtype = np.uint8

            def is_ready(self):
                return False

            def block_until_ready(self):
                time.sleep(3600)

        def wedge(arr, out=None):
            return _Wedged((arr.shape[0], 2, arr.shape[2]))

        ec.encode_array = wedge
        pipe0 = ec_dispatch.PIPELINE.snapshot()
        try:
            tickets = [agg.submit(ec, d) for d in data]
            # all four dispatched (depth 8 ring never forces a settle);
            # reaping the first trips the deadline -> DEGRADED
            t0 = time.monotonic()
            for t, d in zip(tickets, data):
                assert np.array_equal(
                    np.asarray(t), np.asarray(ec.encode_array_host(d))
                )
            elapsed = time.monotonic() - t0
        finally:
            ec.encode_array = real
        # one deadline for the first wedge, near-zero for the rest
        assert elapsed < 2.0, elapsed
        assert device_guard().degraded
        recs = [
            r for r in flight_recorder().records() if r["group"] != "#raw"
        ]
        timeouts = [r for r in recs if r["flags"]["timeout"]]
        assert len(timeouts) == 1, recs
        fallbacks = [r for r in recs if r["flags"]["fallback"]]
        assert len(fallbacks) == len(data), recs
        # the spared groups are marked degraded_bypass, not timeout
        assert sum(
            1 for r in recs if r["flags"]["degraded_bypass"]
        ) == len(data) - 1, recs
        pipe1 = ec_dispatch.PIPELINE.snapshot()
        assert (
            pipe1["donation_recycled_live"]
            == pipe0["donation_recycled_live"]
        )
        assert not agg._live

    def test_finished_coriders_keep_their_device_results(self):
        """A wedge that degrades the backend must NOT discard other
        in-flight launches whose device work already completed: a ready
        buffer settles from the device (no fallback flag), because
        re-running finished work on the host would only add latency."""
        ec = make_rs()
        agg = EncodeAggregator(window=1, pipeline_depth=8)
        data = batches(2, shape=(2, 4, 512), seed=4)
        tickets = [agg.submit(ec, d) for d in data]
        time.sleep(0.05)  # CPU backend: both launches finish
        device_guard().mark_degraded("test wedge elsewhere")
        try:
            for t, d in zip(tickets, data):
                assert np.array_equal(
                    np.asarray(t), np.asarray(ec.encode_array_host(d))
                )
        finally:
            device_guard().mark_healthy()
        recs = [
            r for r in flight_recorder().records() if r["group"] != "#raw"
        ]
        assert not any(r["flags"]["fallback"] for r in recs), recs


class TestDonationPool:
    def test_live_buffer_never_recycled(self):
        pool = DonationPool()
        buf = object()
        pool.hold(buf)
        pool.put((1, 2), buf)  # refused: still live
        assert pool.take((1, 2)) is None
        pool.release(buf)
        pool.put((1, 2), buf)
        assert pool.take((1, 2)) is buf
        assert pool.take((1, 2)) is None  # pool is empty again

    def test_slot_cap_bounds_per_shape_buffers(self):
        pool = DonationPool()
        bufs = [object() for _ in range(10)]
        for b in bufs:
            pool.put((3,), b)
        taken = []
        while (b := pool.take((3,))) is not None:
            taken.append(b)
        assert len(taken) == DonationPool.SLOT_CAP

    def test_pool_cap_follows_pipeline_depth(self):
        """Retention tracks the ring depth (ceilinged at SLOT_CAP):
        pooling more dead device buffers than launches that can be in
        flight would only pin HBM."""
        agg = EncodeAggregator(window=2, pipeline_depth=2)
        assert agg._donate_pool.cap == 2
        agg.configure(pipeline_depth=1)
        assert agg._donate_pool.cap == 1
        agg.configure(pipeline_depth=64)
        assert agg._donate_pool.cap == DonationPool.SLOT_CAP
        # a runtime cap shrink trims pooled slots on the next put
        pool = DonationPool(cap=3)
        for _ in range(3):
            pool.put((2,), object())
        pool.cap = 1
        pool.put((2,), object())
        taken = 0
        while pool.take((2,)) is not None:
            taken += 1
        assert taken == 1

    def test_aggregated_rounds_reuse_buffers(self):
        """Two same-shape aggregated rounds: the second round's launch
        consumes the first's pooled output (donation_reuses advances)
        and bytes stay correct."""
        ec = make_rs()
        agg = EncodeAggregator(window=2, pipeline_depth=2)
        pipe0 = ec_dispatch.PIPELINE.snapshot()["donation_reuses"]
        for seed in (5, 6):
            data = batches(2, shape=(2, 4, 8192), seed=seed)
            tickets = [agg.submit(ec, d) for d in data]
            agg.flush()
            for t, d in zip(tickets, data):
                assert np.array_equal(
                    np.asarray(t), np.asarray(ec.encode_array_host(d))
                )
        assert ec_dispatch.PIPELINE.snapshot()["donation_reuses"] > pipe0


class TestDeviceChunkCache:
    def test_put_get_generation_and_eviction(self):
        cc = DeviceChunkCache(max_bytes=4096)
        a = np.arange(1024, dtype=np.uint8)
        assert cc.put("o1", 0, 1, a)
        assert np.array_equal(np.asarray(cc.get("o1", 0, 1)), a)
        assert cc.get("o1", 0, 2) is None  # generation mismatch
        assert cc.get("o1", 1, 1) is None  # shard mismatch
        # eviction: four 1 KiB entries fill the 4 KiB bound; the fifth
        # evicts the LRU (o1 was touched most recently by the get above)
        for i in range(2, 7):
            assert cc.put(f"o{i}", 0, 1, a)
        dump = cc.perf_dump()
        assert dump["evictions"] >= 1
        assert dump["resident_bytes"] <= 4096

    def test_disabled_and_oversized_put_refused(self):
        cc = DeviceChunkCache(max_bytes=0)
        assert not cc.enabled
        assert not cc.put("o", 0, 1, np.zeros(16, np.uint8))
        cc2 = DeviceChunkCache(max_bytes=64)
        assert not cc2.put("o", 0, 1, np.zeros(128, np.uint8))

    def test_invalidate_object_drops_all_shards(self):
        cc = DeviceChunkCache(max_bytes=1 << 20)
        for s in range(3):
            cc.put("obj", s, 1, np.zeros(64, np.uint8))
        cc.put("other", 0, 1, np.zeros(64, np.uint8))
        assert cc.invalidate_object("obj") == 3
        assert cc.get("obj", 0, 1) is None
        assert cc.get("other", 0, 1) is not None

    def test_degraded_transition_clears_and_gates_put(self):
        cc = device_chunk_cache()
        old_max = cc.max_bytes
        cc.configure(max_bytes=1 << 20)
        try:
            assert cc.put("deg-obj", 0, 1, np.zeros(64, np.uint8))
            device_guard().mark_degraded("test wedge")
            assert cc.get("deg-obj", 0, 1) is None, "clear on DEGRADED"
            assert not cc.put("deg-obj", 0, 1, np.zeros(64, np.uint8))
            device_guard().mark_healthy()
            assert cc.put("deg-obj", 0, 1, np.zeros(64, np.uint8))
        finally:
            cc.invalidate_object("deg-obj")
            cc.configure(max_bytes=old_max)

    def test_fetch_many_hit_record_skips_h2d(self):
        """The acceptance criterion, at the flight-record level: a
        cache-served read's record is flagged cache_hit with ZERO h2d
        and kernel spans — only the D2H copy."""
        cc = DeviceChunkCache(max_bytes=1 << 20)
        a = np.arange(2048, dtype=np.uint8)
        cc.put("obj", 1, 7, a)
        cc.put("obj", 3, 7, a[::-1].copy())
        got = cc.fetch_many("obj", [1, 3], 7, length=2048)
        assert got is not None
        assert np.array_equal(got[1], a)
        rec = [
            r for r in flight_recorder().records()
            if r["flags"].get("cache_hit")
        ][-1]
        assert rec["h2d_s"] == 0.0 and rec["kernel_s"] == 0.0, rec
        assert rec["d2h_s"] >= 0.0
        assert cc.fetch_many("obj", [1, 2], 7) is None  # partial -> miss

    def test_degraded_read_hit_skips_decode_launch(self):
        """End to end through the stripe decode launcher: the second
        same-generation degraded read serves from HBM — no new decode
        launch, byte-identical logical bytes, cache_hit record."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 512, 512)
        rng = np.random.default_rng(9)
        data = rng.integers(0, 256, 2 * sinfo.stripe_width, dtype=np.uint8)
        shards = stripe_mod.encode(sinfo, ec, data)
        have = {i: shards[i] for i in range(6) if i != 1}
        cc = DeviceChunkCache(max_bytes=1 << 20)
        key = (("t", "obj"), 5)
        first = stripe_mod.decode_concat_launch(
            sinfo, ec, have, chunk_cache=cc, cache_key=key
        ).result()
        d0 = ec_dispatch.DECODE_LAUNCHES.snapshot()["launches"]
        second = stripe_mod.decode_concat_launch(
            sinfo, ec, have, chunk_cache=cc, cache_key=key
        ).result()
        assert ec_dispatch.DECODE_LAUNCHES.snapshot()["launches"] == d0
        assert np.array_equal(first, data)
        assert np.array_equal(second, data)
        assert any(
            r["flags"].get("cache_hit") for r in flight_recorder().records()
        )
        # a generation bump (overwrite) misses again
        third = stripe_mod.decode_concat_launch(
            sinfo, ec, have, chunk_cache=cc, cache_key=(key[0], 6)
        ).result()
        assert ec_dispatch.DECODE_LAUNCHES.snapshot()["launches"] == d0 + 1
        assert np.array_equal(third, data)

    def test_recovery_hit_through_decode_shards(self):
        ec = make_rs()
        sinfo = StripeInfo(4 * 512, 512)
        rng = np.random.default_rng(10)
        data = rng.integers(0, 256, 2 * sinfo.stripe_width, dtype=np.uint8)
        shards = stripe_mod.encode(sinfo, ec, data)
        have = {i: shards[i] for i in range(6) if i not in (1, 5)}
        cc = DeviceChunkCache(max_bytes=1 << 20)
        key = (("t", "obj2"), 3)
        first = stripe_mod.decode_shards_launch(
            sinfo, ec, have, {1, 5}, chunk_cache=cc, cache_key=key
        ).result()
        d0 = ec_dispatch.DECODE_LAUNCHES.snapshot()["launches"]
        second = stripe_mod.decode_shards_launch(
            sinfo, ec, have, {1, 5}, chunk_cache=cc, cache_key=key
        ).result()
        assert ec_dispatch.DECODE_LAUNCHES.snapshot()["launches"] == d0
        for s in (1, 5):
            assert np.array_equal(first[s], shards[s].reshape(-1))
            assert np.array_equal(second[s], first[s])


class TestRmwCacheConsult:
    def test_degraded_rmw_read_leg_hits_cache(self):
        """The RMW read leg reads exactly the committed pre-write bytes,
        so a prior degraded read's cached reconstruction must serve it
        from HBM.  Regression: submit_transaction used to project (and
        eagerly invalidate) BEFORE the read leg ran, making the
        advertised RMW consult unreachable — the submit-time generation
        capture plus encode-time invalidation make it real."""
        from test_ec_backend import (
            FLAG_EC_OVERWRITES,
            PG_NONE,
            Cluster,
            ec_pool,
            payload,
        )

        cc = device_chunk_cache()
        cc.configure(max_bytes=1 << 22)
        cc.clear()
        # disarm the on-device RMW delta path (ISSUE 18): with it on, a
        # warm cache makes the RMW bump generations IN PLACE instead of
        # invalidating — this test pins the MATERIALIZE path's
        # generation-capture + encode-time-invalidation contract
        from ceph_tpu.osd import ec_backend as ec_backend_mod

        ec_backend_mod.configure_rmw_delta(False)
        try:
            pool, profiles = ec_pool(4, 2, flags=FLAG_EC_OVERWRITES)
            c = Cluster(pool, profiles)
            base = payload(2 * pool.stripe_width)
            c.write("obj", 0, base)
            # a data shard goes dark: every read of obj now reconstructs
            c.acting[1] = PG_NONE
            assert c.read("obj", 0, len(base)) == base  # fills the cache
            assert cc.perf_dump()["entries"] >= 1
            h0 = cc.perf_dump()["hits"]
            d0 = ec_dispatch.DECODE_LAUNCHES.snapshot()["launches"]
            # partial-stripe overwrite: the RMW read leg reconstructs the
            # modified stripe — from the cache, not a decode launch
            patch = payload(300, seed=9)
            c.write("obj", 1000, patch)
            assert cc.perf_dump()["hits"] > h0, (
                "RMW read leg never consulted the device cache"
            )
            assert ec_dispatch.DECODE_LAUNCHES.snapshot()["launches"] == d0
            # encode-time invalidation dropped the now-stale entries
            assert cc.perf_dump()["entries"] == 0
            expect = bytearray(base)
            expect[1000:1300] = patch
            assert c.read("obj", 0, len(base)) == bytes(expect)
        finally:
            from ceph_tpu.common.options import OPTIONS

            ec_backend_mod.configure_rmw_delta(
                bool(OPTIONS["ec_tpu_rmw_delta"].default)
            )
            cc.clear()
            cc.configure(
                max_bytes=int(OPTIONS["ec_tpu_device_cache_bytes"].default)
            )


class TestPerfDumpFamilies:
    def test_pipeline_and_cache_keys_on_perf_dump(self):
        dump = ec_dispatch.perf_dump()
        for key in (
            "pipeline.depth", "pipeline.inflight", "pipeline.inflight_peak",
            "pipeline.drains", "pipeline.donation_reuses",
            "pipeline.donation_recycled_live",
            "cache.hits", "cache.misses", "cache.insertions",
            "cache.evictions", "cache.invalidations", "cache.served_bytes",
            "cache.resident_bytes", "cache.entries",
        ):
            assert key in dump, key
        # NOTE: no ==0 assertion on donation_recycled_live here — the
        # DonationPool unit tests above exercise the violation path on
        # purpose, which counts on the process-wide gauge; the clean-run
        # invariant is asserted as a DELTA by the chaos pipelined-wedge
        # phase and TestWedgeAtDepth
        assert dump["pipeline.donation_recycled_live"] >= 0
