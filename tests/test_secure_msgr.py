"""msgr2 secure mode + on-wire compression.

Models the reference's crypto_onwire/compression_onwire coverage
(src/test/msgr tests with ms_mode=secure): AES-GCM session records keyed
from the cephx handshake, replay/tamper rejection, feature negotiation
(a secure endpoint never falls back to cleartext), and a full cluster —
mons, OSDs, client — running ms_secure + compression end to end.
"""

import asyncio

import pytest

from ceph_tpu.msg.crypto import AESGCM

# secure mode needs AES-GCM; without the cryptography package only the
# plaintext/compress paths exist (crypto.py gates the import the same way)
needs_aesgcm = pytest.mark.skipif(
    AESGCM is None, reason="cryptography package not installed"
)

from ceph_tpu.auth import CephxAuth, KeyRing
from ceph_tpu.client import Rados
from ceph_tpu.common.config import Config
from ceph_tpu.mon import MonMap, Monitor
from ceph_tpu.msg.crypto import OnWireError, OnWireSession, derive_session_key
from ceph_tpu.msg.messenger import Dispatcher, Messenger
from ceph_tpu.msg.messages import MPing
from ceph_tpu.osd.osd import OSD

from test_cluster import wait_until
from test_mon import free_port_addrs


class TestOnWireSession:
    def _pair(self, secure=True, compress=False):
        key = derive_session_key(b"k" * 16, b"sc", b"cc")
        a = OnWireSession(key, secure=secure, compress=compress, initiator=True)
        b = OnWireSession(key, secure=secure, compress=compress, initiator=False)
        return a, b

    @needs_aesgcm
    def test_secure_roundtrip(self):
        a, b = self._pair()
        for payload in (b"x", b"frame bytes " * 100):
            rec = a.wrap(payload)
            if len(payload) >= 8:
                # A short payload (1 byte) can appear in random ciphertext by
                # chance (~10%/run for 1 byte in ~29 random bytes); only the
                # long payload is a meaningful non-containment probe.
                assert payload not in rec  # actually encrypted
            body = rec[8:]
            assert b.unwrap(body) == payload
        empty = a.wrap(b"")  # zero-length frames still authenticate
        assert b.unwrap(empty[8:]) == b""

    def test_compressed_roundtrip_shrinks(self):
        a, b = self._pair(secure=False, compress=True)
        payload = b"A" * 4096
        rec = a.wrap(payload)
        assert len(rec) < len(payload) // 2
        assert b.unwrap(rec[8:]) == payload

    @needs_aesgcm
    def test_secure_plus_compressed(self):
        a, b = self._pair(secure=True, compress=True)
        payload = b"Z" * 8192
        rec = a.wrap(payload)
        assert len(rec) < len(payload) // 2  # compressed before encryption
        assert b.unwrap(rec[8:]) == payload

    @needs_aesgcm
    def test_tampered_record_rejected(self):
        a, b = self._pair()
        rec = bytearray(a.wrap(b"secret payload"))
        rec[-1] ^= 0x01
        with pytest.raises(OnWireError):
            b.unwrap(bytes(rec[8:]))

    @needs_aesgcm
    def test_replayed_record_rejected(self):
        a, b = self._pair()
        body = a.wrap(b"once")[8:]
        assert b.unwrap(body) == b"once"
        with pytest.raises(OnWireError):
            b.unwrap(body)  # same nonce counter again

    @needs_aesgcm
    def test_wrong_key_rejected(self):
        a, _ = self._pair()
        other = OnWireSession(b"0" * 16, secure=True, compress=False)
        with pytest.raises(OnWireError):
            other.unwrap(a.wrap(b"payload")[8:])

    def test_secure_requires_key(self):
        with pytest.raises(OnWireError):
            OnWireSession(b"", secure=True, compress=False)

    @needs_aesgcm
    def test_reflected_record_rejected(self):
        """Per-direction keys: a MITM replaying the sender's own record
        back at it must fail authentication, not parse as peer traffic."""
        a, _b = self._pair()
        own = a.wrap(b"my own bytes")[8:]
        with pytest.raises(OnWireError):
            a.unwrap(own)

    def test_truncated_inner_frame_is_frame_error(self):
        from ceph_tpu.msg.frames import Frame, FrameError, frame_from_bytes

        packed = Frame(2, [b"env", b"payload"]).pack(True)
        with pytest.raises(FrameError):
            frame_from_bytes(packed[:-3])


class _Sink(Dispatcher):
    def __init__(self):
        self.got = []

    def ms_dispatch(self, conn, msg):
        self.got.append((conn, msg))
        return True


def _cluster_keyring(n_osds, mon_names):
    kr = KeyRing()
    for name in mon_names:
        kr.add(f"mon.{name}")
    for i in range(n_osds):
        kr.add(f"osd.{i}")
    secret = kr.add("client.admin")
    return kr, secret


class TestSecureMessenger:
    @needs_aesgcm
    def test_secure_session_delivers_and_is_encrypted(self):
        async def run():
            kr, _ = _cluster_keyring(2, [])
            srv_auth = CephxAuth.for_daemon("osd.0", kr)
            cli_auth = CephxAuth.for_daemon("osd.1", kr)
            srv = Messenger("osd.0", auth=srv_auth, secure=True)
            sink = _Sink()
            srv.add_dispatcher_head(sink)
            await srv.bind("127.0.0.1:0")
            cli = Messenger("osd.1", auth=cli_auth, secure=True)
            await cli.send_to(srv.addr, MPing(stamp=1.5))
            await asyncio.sleep(0.1)
            assert len(sink.got) == 1
            conn, msg = sink.got[0]
            assert msg.stamp == 1.5
            assert conn._onwire is not None and conn._onwire.secure
            assert conn.auth_entity == "osd.1"
            await cli.shutdown()
            await srv.shutdown()

        asyncio.run(run())

    def test_secure_endpoint_rejects_plain_peer(self):
        async def run():
            kr, _ = _cluster_keyring(2, [])
            srv = Messenger(
                "osd.0", auth=CephxAuth.for_daemon("osd.0", kr), secure=True
            )
            srv.add_dispatcher_head(_Sink())
            await srv.bind("127.0.0.1:0")
            plain = Messenger("osd.1", auth=CephxAuth.for_daemon("osd.1", kr))
            with pytest.raises(ConnectionError):
                await plain.send_to(srv.addr, MPing(stamp=1.0))
            await plain.shutdown()
            await srv.shutdown()

        asyncio.run(run())

    def test_secure_requires_auth_at_construction(self):
        with pytest.raises(ValueError):
            Messenger("osd.0", secure=True)


class TestSecureCluster:
    @needs_aesgcm
    def test_ec_cluster_end_to_end_with_ms_secure(self):
        """mons + OSDs + client all on ms_secure (+ compression): quorum,
        pool create, EC put/get, failure detection — everything riding
        AES-GCM sessions."""

        async def run():
            monmap = MonMap(addrs=free_port_addrs(1))
            kr, client_secret = _cluster_keyring(4, list(monmap.addrs))
            mons = [
                Monitor(
                    n, monmap, election_timeout=0.3,
                    keyring=kr, secure=True, compress=True,
                )
                for n in monmap.addrs
            ]
            for m in mons:
                await m.start()
                await m.wait_for_quorum()

            def conf(i):
                return Config(
                    {
                        "name": f"osd.{i}",
                        "osd_heartbeat_interval": 0.1,
                        "osd_heartbeat_grace": 0.6,
                        "ms_secure": True,
                        "ms_compress": True,
                    },
                    env=False,
                )

            osds = [
                OSD(i, monmap, conf=conf(i), auth=CephxAuth.for_daemon(f"osd.{i}", kr))
                for i in range(4)
            ]
            for o in osds:
                await o.start()
            for o in osds:
                await o.wait_for_up()

            client = Rados(
                monmap, secret=client_secret, secure=True, compress=True
            )
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "sec21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("securepool", "erasure", profile="sec21", pg_num=2)
            ioctx = await client.open_ioctx("securepool")

            payload = bytes((i * 17 + 3) % 256 for i in range(3 * 8192 + 500))
            await ioctx.write_full("sec-obj", payload)
            assert await ioctx.read("sec-obj") == payload
            assert await ioctx.read("sec-obj", 4096, 5000) == payload[5000:9096]

            # every accepted session on the mon really negotiated secure
            assert mons[0].msgr._accepted, "no sessions?"
            for conn in mons[0].msgr._accepted:
                assert conn._onwire is not None and conn._onwire.secure

            # kill an OSD: heartbeats + failure reports also ride secure
            await osds[3].stop()
            await wait_until(
                lambda: not mons[0].osdmon.osdmap.is_up(3),
                8.0,
                "secure-mode failure detection",
            )
            assert await ioctx.read("sec-obj") == payload  # degraded read

            await client.shutdown()
            for o in osds:
                if o._running:
                    await o.stop()
            for m in mons:
                await m.stop()
            await asyncio.sleep(0.05)

        asyncio.run(run())
