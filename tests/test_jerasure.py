"""jerasure-compat codec tests — tier-1 pattern per technique."""

import itertools

import numpy as np
import pytest

from ceph_tpu.codec.interface import EcError
from ceph_tpu.codec.jerasure import TECHNIQUES, ErasureCodeJerasure
from ceph_tpu.codec.registry import ErasureCodePluginRegistry
from ceph_tpu.gf import gf_matmul


def make(technique, k, m, **extra):
    ec = ErasureCodeJerasure(technique=technique)
    ec.init({"k": str(k), "m": str(m), **extra})
    return ec


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n).astype(np.uint8).tobytes()


@pytest.mark.parametrize("technique", TECHNIQUES)
def test_roundtrip_all_erasures(technique):
    k, m = (6, 2) if technique == "reed_sol_r6_op" else (6, 3)
    ec = make(technique, k, m)
    raw = payload(k * 128 + 31)
    encoded = ec.encode(set(range(k + m)), raw)
    for nerr in range(1, m + 1):
        for erasures in itertools.combinations(range(k + m), nerr):
            avail = {i: encoded[i] for i in range(k + m) if i not in erasures}
            decoded = ec.decode(set(erasures), avail)
            for e in erasures:
                assert np.array_equal(decoded[e], encoded[e]), (technique, erasures)


@pytest.mark.parametrize("technique", TECHNIQUES)
def test_parity_matches_matrix(technique):
    k, m = (5, 2) if technique == "reed_sol_r6_op" else (5, 3)
    ec = make(technique, k, m)
    raw = payload(k * 128, seed=2)
    encoded = ec.encode(set(range(k + m)), raw)
    data = np.stack([encoded[i] for i in range(k)])
    expect = gf_matmul(ec.distribution_matrix()[k:], data)
    for i in range(m):
        assert np.array_equal(encoded[k + i], expect[i])


def test_r6_p_is_xor():
    ec = make("reed_sol_r6_op", 6, 2)
    raw = payload(6 * 128, seed=3)
    encoded = ec.encode(set(range(8)), raw)
    p = np.bitwise_xor.reduce(np.stack([encoded[i] for i in range(6)]), axis=0)
    assert np.array_equal(encoded[6], p)


def test_defaults_and_validation():
    ec = ErasureCodeJerasure()
    ec.init({})
    assert (ec.k, ec.m, ec.w) == (7, 3, 8)
    with pytest.raises(EcError):
        make("reed_sol_van", 4, 2, w="16")  # only w=8 supported
    with pytest.raises(EcError):
        make("reed_sol_r6_op", 4, 3)  # r6 needs m=2
    with pytest.raises(EcError):
        ErasureCodeJerasure(technique="liberation")  # not implemented
    with pytest.raises(EcError):
        make("reed_sol_van", 250, 8)  # k+m > 256 exceeds GF(2^8)
    # packetsize accepted and defaulted for profile compat
    prof = {"k": "4", "m": "2"}
    ec = ErasureCodeJerasure(technique="cauchy_good")
    ec.init(prof)
    assert prof["packetsize"] == "2048"


def test_plugin_registration():
    r = ErasureCodePluginRegistry()
    ec = r.factory("jerasure", {"k": "4", "m": "2", "technique": "cauchy_orig"})
    raw = payload(4 * 128, seed=4)
    encoded = ec.encode(set(range(6)), raw)
    decoded = ec.decode({0, 5}, {i: encoded[i] for i in (1, 2, 3, 4)})
    assert np.array_equal(decoded[0], encoded[0])
    assert np.array_equal(decoded[5], encoded[5])


# -- liberation / blaum_roth / liber8tion (packetized GF(2) bit-matrix) ------


def make_bm(technique, k, w=None, packetsize=8):
    from ceph_tpu.codec.jerasure import ErasureCodeJerasureBitmatrix

    profile = {"k": str(k), "m": "2", "packetsize": str(packetsize)}
    if w is not None:
        profile["w"] = str(w)
    ec = ErasureCodeJerasureBitmatrix(technique)
    ec.init(profile)
    return ec


@pytest.mark.parametrize(
    "technique,k,w",
    [
        ("liberation", 2, 3),
        ("liberation", 5, 5),
        ("liberation", 7, 7),
        ("blaum_roth", 4, 4),
        ("blaum_roth", 6, 6),
        ("blaum_roth", 7, 10),  # w+1 = 11 prime
        ("liber8tion", 2, 8),
        ("liber8tion", 6, 8),
        ("liber8tion", 8, 8),
    ],
)
def test_bitmatrix_roundtrip_all_erasures(technique, k, w):
    ec = make_bm(technique, k, w=w)
    raw = payload(k * w * 8 * 2 + 13, seed=3)
    n = k + 2
    encoded = ec.encode(set(range(n)), raw)
    chunk_size = ec.get_chunk_size(len(raw))
    assert chunk_size % (ec.w * ec.packetsize) == 0
    for nerr in (1, 2):
        for erasures in itertools.combinations(range(n), nerr):
            avail = {i: encoded[i] for i in range(n) if i not in erasures}
            decoded = ec.decode(set(erasures), avail)
            for e in erasures:
                assert np.array_equal(decoded[e], encoded[e]), (technique, erasures)


def test_blaum_roth_legacy_w7_single_erasure_only():
    # The reference tolerates w=7 (Firefly default) even though w+1=8 is
    # not prime (ErasureCodeJerasure.cc:459-472).  In that ring the modulus
    # is (x-1)^7, so every data-pair decode matrix shares a (1+x) factor
    # and is singular: the code is single-erasure-strength only.  Accept
    # the profile, round-trip single erasures, and surface EIO for pairs.
    ec = make_bm("blaum_roth", 4, w=7)
    raw = payload(4 * 7 * 8, seed=6)
    encoded = ec.encode(set(range(6)), raw)
    for e in range(6):
        avail = {i: encoded[i] for i in range(6) if i != e}
        decoded = ec.decode({e}, avail)
        assert np.array_equal(decoded[e], encoded[e])
    with pytest.raises(EcError):
        ec.decode({0, 1}, {i: encoded[i] for i in range(2, 6)})


def test_bitmatrix_p_drive_is_xor():
    # The first coding drive of every RAID-6 bit-matrix code is the plain
    # XOR of the data drives (identity blocks).
    ec = make_bm("liberation", 4, w=5)
    raw = payload(4 * 5 * 8, seed=4)
    encoded = ec.encode(set(range(6)), raw)
    expect = encoded[0].copy()
    for i in range(1, 4):
        expect ^= encoded[i]
    assert np.array_equal(encoded[4], expect)


def test_bitmatrix_profile_validation():
    from ceph_tpu.codec.jerasure import ErasureCodeJerasureBitmatrix

    # m != 2
    with pytest.raises(EcError):
        make = ErasureCodeJerasureBitmatrix("liberation")
        make.init({"k": "3", "m": "3", "w": "5"})
    # w not prime (liberation)
    with pytest.raises(EcError):
        ErasureCodeJerasureBitmatrix("liberation").init({"k": "3", "m": "2", "w": "6"})
    # k > w
    with pytest.raises(EcError):
        ErasureCodeJerasureBitmatrix("liberation").init({"k": "6", "m": "2", "w": "5"})
    # blaum_roth: w+1 must be prime (w=8 -> 9 not prime)
    with pytest.raises(EcError):
        ErasureCodeJerasureBitmatrix("blaum_roth").init({"k": "3", "m": "2", "w": "8"})
    # liber8tion: w pinned to 8
    with pytest.raises(EcError):
        ErasureCodeJerasureBitmatrix("liber8tion").init({"k": "3", "m": "2", "w": "7"})
    # packetsize must be a positive multiple of 4
    with pytest.raises(EcError):
        ErasureCodeJerasureBitmatrix("liberation").init(
            {"k": "3", "m": "2", "w": "5", "packetsize": "6"}
        )


def test_bitmatrix_defaults_match_reference():
    # ErasureCodeJerasure.h: liberation/blaum_roth default k=2 m=2 w=7;
    # liber8tion defaults k=2 m=2 w=8.
    from ceph_tpu.codec.jerasure import ErasureCodeJerasureBitmatrix

    lib = ErasureCodeJerasureBitmatrix("liberation")
    lib.init({})
    assert (lib.k, lib.m, lib.w, lib.packetsize) == (2, 2, 7, 2048)
    l8 = ErasureCodeJerasureBitmatrix("liber8tion")
    l8.init({})
    assert (l8.k, l8.m, l8.w) == (2, 2, 8)


def test_bitmatrix_via_registry():
    r = ErasureCodePluginRegistry.instance()
    for technique, w in (("liberation", "5"), ("blaum_roth", "6"), ("liber8tion", "8")):
        ec = r.factory(
            "jerasure",
            {"technique": technique, "k": "4", "m": "2", "w": w, "packetsize": "8"},
        )
        raw = payload(4 * int(w) * 8, seed=5)
        encoded = ec.encode(set(range(6)), raw)
        avail = {i: encoded[i] for i in range(6) if i not in (0, 5)}
        decoded = ec.decode({0, 5}, avail)
        assert np.array_equal(decoded[0], encoded[0])
        assert np.array_equal(decoded[5], encoded[5])


def test_bitmatrix_respects_chunk_mapping():
    # mapping= remaps logical chunk positions (ErasureCode.cc:260-279); the
    # bit-matrix class must route through chunk_index like the GF(2^8) one.
    ec = make_bm("liberation", 4, w=5)
    ec.init({"k": "4", "m": "2", "w": "5", "packetsize": "8",
             "mapping": "_DDDD_"})
    raw = payload(4 * 5 * 8, seed=7)
    n = 6
    encoded = ec.encode(set(range(n)), raw)
    # data lives at remapped positions; round-trip through two erasures
    avail = {i: encoded[i] for i in range(n) if i not in (1, 2)}
    decoded = ec.decode({1, 2}, avail)
    assert np.array_equal(decoded[1], encoded[1])
    assert np.array_equal(decoded[2], encoded[2])


def test_bitmatrix_decode_rejects_misaligned_chunks():
    ec = make_bm("liberation", 3, w=5, packetsize=8)  # w*P = 40
    bad = {i: np.zeros(100, dtype=np.uint8) for i in range(1, 5)}
    with pytest.raises(EcError):
        ec.decode({0}, bad)
