"""jerasure-compat codec tests — tier-1 pattern per technique."""

import itertools

import numpy as np
import pytest

from ceph_tpu.codec.interface import EcError
from ceph_tpu.codec.jerasure import TECHNIQUES, ErasureCodeJerasure
from ceph_tpu.codec.registry import ErasureCodePluginRegistry
from ceph_tpu.gf import gf_matmul


def make(technique, k, m, **extra):
    ec = ErasureCodeJerasure(technique=technique)
    ec.init({"k": str(k), "m": str(m), **extra})
    return ec


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n).astype(np.uint8).tobytes()


@pytest.mark.parametrize("technique", TECHNIQUES)
def test_roundtrip_all_erasures(technique):
    k, m = (6, 2) if technique == "reed_sol_r6_op" else (6, 3)
    ec = make(technique, k, m)
    raw = payload(k * 128 + 31)
    encoded = ec.encode(set(range(k + m)), raw)
    for nerr in range(1, m + 1):
        for erasures in itertools.combinations(range(k + m), nerr):
            avail = {i: encoded[i] for i in range(k + m) if i not in erasures}
            decoded = ec.decode(set(erasures), avail)
            for e in erasures:
                assert np.array_equal(decoded[e], encoded[e]), (technique, erasures)


@pytest.mark.parametrize("technique", TECHNIQUES)
def test_parity_matches_matrix(technique):
    k, m = (5, 2) if technique == "reed_sol_r6_op" else (5, 3)
    ec = make(technique, k, m)
    raw = payload(k * 128, seed=2)
    encoded = ec.encode(set(range(k + m)), raw)
    data = np.stack([encoded[i] for i in range(k)])
    expect = gf_matmul(ec.distribution_matrix()[k:], data)
    for i in range(m):
        assert np.array_equal(encoded[k + i], expect[i])


def test_r6_p_is_xor():
    ec = make("reed_sol_r6_op", 6, 2)
    raw = payload(6 * 128, seed=3)
    encoded = ec.encode(set(range(8)), raw)
    p = np.bitwise_xor.reduce(np.stack([encoded[i] for i in range(6)]), axis=0)
    assert np.array_equal(encoded[6], p)


def test_defaults_and_validation():
    ec = ErasureCodeJerasure()
    ec.init({})
    assert (ec.k, ec.m, ec.w) == (7, 3, 8)
    with pytest.raises(EcError):
        make("reed_sol_van", 4, 2, w="16")  # only w=8 supported
    with pytest.raises(EcError):
        make("reed_sol_r6_op", 4, 3)  # r6 needs m=2
    with pytest.raises(EcError):
        ErasureCodeJerasure(technique="liberation")  # not implemented
    with pytest.raises(EcError):
        make("reed_sol_van", 250, 8)  # k+m > 256 exceeds GF(2^8)
    # packetsize accepted and defaulted for profile compat
    prof = {"k": "4", "m": "2"}
    ec = ErasureCodeJerasure(technique="cauchy_good")
    ec.init(prof)
    assert prof["packetsize"] == "2048"


def test_plugin_registration():
    r = ErasureCodePluginRegistry()
    ec = r.factory("jerasure", {"k": "4", "m": "2", "technique": "cauchy_orig"})
    raw = payload(4 * 128, seed=4)
    encoded = ec.encode(set(range(6)), raw)
    decoded = ec.decode({0, 5}, {i: encoded[i] for i in (1, 2, 3, 4)})
    assert np.array_equal(decoded[0], encoded[0])
    assert np.array_equal(decoded[5], encoded[5])
