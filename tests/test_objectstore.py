"""ObjectStore tests: transactions, MemStore, FileStore persistence +
journal replay, FileKV torn-tail recovery.

Modeled on the reference's src/test/objectstore/store_test.cc (run
against MemStore and BlueStore alike) and its KV tests.
"""

import os
import struct

import pytest

from ceph_tpu.os import (
    BlueStore,
    FileKV,
    FileStore,
    MemKV,
    MemStore,
    StoreError,
    Transaction,
)


STORES = ["mem", "file", "bluestore", "bluestore-mem"]


@pytest.fixture(params=STORES)
def store(request, tmp_path):
    if request.param == "mem":
        s = MemStore()
    elif request.param == "bluestore":
        s = BlueStore(str(tmp_path / "bstore"))
    elif request.param == "bluestore-mem":
        s = BlueStore()  # in-memory dev variant
    else:
        s = FileStore(str(tmp_path / "store"))
    s.mount()
    yield s
    s.umount()


class TestTransactionCodec:
    def test_roundtrip(self):
        t = Transaction()
        t.create_collection("pg_1.0s0")
        t.write("pg_1.0s0", "obj", 4096, b"hello", hints=1)
        t.setattr("pg_1.0s0", "obj", "hinfo_key", b"\x01\x02")
        t.omap_setkeys("pg_1.0s0", "obj", {"k": b"v"})
        t.append("pg_1.0s0", "obj", b"tail")
        t2 = Transaction.frombytes(t.tobytes())
        assert len(t2) == 5
        assert t2.ops[1].off == 4096 and t2.ops[1].data == b"hello"
        assert t2.ops[2].name == "hinfo_key"
        assert t2.ops[4].hints != 0

    def test_append_txn(self):
        a = Transaction().touch("c", "x")
        b = Transaction().touch("c", "y")
        a.append_txn(b)
        assert len(a) == 2


class TestStore:
    def test_write_read_roundtrip(self, store):
        t = Transaction().create_collection("c")
        t.write("c", "obj", 0, b"0123456789")
        store.queue_transaction(t)
        assert store.read("c", "obj") == b"0123456789"
        assert store.read("c", "obj", 2, 3) == b"234"
        assert store.stat("c", "obj") == 10

    def test_sparse_write_zero_fills(self, store):
        store.queue_transaction(
            Transaction().create_collection("c").write("c", "o", 8, b"xy")
        )
        assert store.read("c", "o") == b"\x00" * 8 + b"xy"

    def test_append_op(self, store):
        t = Transaction().create_collection("c")
        t.append("c", "o", b"aaa")
        t.append("c", "o", b"bbb")
        store.queue_transaction(t)
        assert store.read("c", "o") == b"aaabbb"

    def test_zero_truncate_remove(self, store):
        store.queue_transaction(
            Transaction().create_collection("c").write("c", "o", 0, b"x" * 16)
        )
        store.queue_transaction(Transaction().zero("c", "o", 4, 4))
        assert store.read("c", "o", 4, 4) == b"\x00" * 4
        store.queue_transaction(Transaction().truncate("c", "o", 8))
        assert store.stat("c", "o") == 8
        store.queue_transaction(Transaction().remove("c", "o"))
        assert not store.exists("c", "o")

    def test_xattrs(self, store):
        t = Transaction().create_collection("c").touch("c", "o")
        t.setattr("c", "o", "hinfo", b"\x07")
        store.queue_transaction(t)
        assert store.getattr("c", "o", "hinfo") == b"\x07"
        assert store.getattrs("c", "o") == {"hinfo": b"\x07"}
        store.queue_transaction(Transaction().rmattr("c", "o", "hinfo"))
        with pytest.raises(StoreError):
            store.getattr("c", "o", "hinfo")

    def test_omap(self, store):
        t = Transaction().create_collection("c").touch("c", "o")
        t.omap_setkeys("c", "o", {"a": b"1", "b": b"2"})
        store.queue_transaction(t)
        assert store.omap_get("c", "o") == {"a": b"1", "b": b"2"}
        store.queue_transaction(Transaction().omap_rmkeys("c", "o", ["a"]))
        assert store.omap_get("c", "o") == {"b": b"2"}

    def test_clone(self, store):
        t = Transaction().create_collection("c").write("c", "o", 0, b"data")
        t.setattr("c", "o", "v", b"9")
        store.queue_transaction(t)
        store.queue_transaction(Transaction().clone("c", "o", "o2"))
        assert store.read("c", "o2") == b"data"
        assert store.getattr("c", "o2", "v") == b"9"

    def test_collections(self, store):
        store.queue_transaction(Transaction().create_collection("pg_1.0s0"))
        store.queue_transaction(Transaction().create_collection("pg_1.0s1"))
        assert store.list_collections() == ["pg_1.0s0", "pg_1.0s1"]
        with pytest.raises(StoreError):
            store.queue_transaction(Transaction().create_collection("pg_1.0s0"))
        store.queue_transaction(Transaction().remove_collection("pg_1.0s1"))
        assert store.list_collections() == ["pg_1.0s0"]

    def test_missing_object_enoent(self, store):
        store.queue_transaction(Transaction().create_collection("c"))
        with pytest.raises(StoreError) as ei:
            store.read("c", "nope")
        assert ei.value.errno == -2

    def test_missing_collection_enoent(self, store):
        with pytest.raises(StoreError):
            store.read("nope", "obj")

    def test_commit_callback(self, store):
        fired = []
        store.queue_transaction(
            Transaction().create_collection("c"), on_commit=lambda: fired.append(1)
        )
        assert fired == [1]


class TestFileStorePersistence:
    def test_survives_remount(self, tmp_path):
        path = str(tmp_path / "s")
        s = FileStore(path)
        s.mount()
        t = Transaction().create_collection("c").write("c", "obj", 0, b"persist")
        t.setattr("c", "obj", "a", b"1")
        s.queue_transaction(t)
        s.umount()
        s2 = FileStore(path)
        s2.mount()
        assert s2.read("c", "obj") == b"persist"
        assert s2.getattr("c", "obj", "a") == b"1"
        s2.umount()

    def test_journal_replay_applies_unfinished_txn(self, tmp_path):
        path = str(tmp_path / "s")
        s = FileStore(path)
        s.mount()
        s.queue_transaction(Transaction().create_collection("c"))
        # Simulate a crash after journaling but before apply: jam the txn
        # into the journal directly.
        t = Transaction().write("c", "obj", 0, b"replayed")
        s._journal.set("txn", f"{99:016d}", t.tobytes())
        s.umount()
        s2 = FileStore(path)
        s2.mount()  # replay
        assert s2.read("c", "obj") == b"replayed"
        # journal drained
        assert list(s2._journal.iterate("txn")) == []
        s2.umount()


class TestFileStoreCrashSemantics:
    def test_append_replay_is_idempotent(self, tmp_path):
        # A crash after apply but before journal-rm must not double-append:
        # appends are resolved to absolute offsets before journaling.
        path = str(tmp_path / "s")
        s = FileStore(path)
        s.mount()
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction().append("c", "o", b"aaa")
        resolved = s._resolve_appends(t)
        s._journal.set("txn", f"{98:016d}", resolved.tobytes())
        for op in resolved.ops:
            s._apply_op(op)  # applied, but journal entry left behind
        s.umount()
        s2 = FileStore(path)
        s2.mount()  # replays the same txn
        assert s2.read("c", "o") == b"aaa"  # not 'aaaaaa'
        s2.umount()

    def test_aborted_txn_not_replayed(self, tmp_path):
        path = str(tmp_path / "s")
        s = FileStore(path)
        s.mount()
        with pytest.raises(StoreError):
            s.queue_transaction(Transaction().write("missing", "o", 0, b"x"))
        assert list(s._journal.iterate("txn")) == []
        s.umount()
        s2 = FileStore(path)
        s2.mount()  # must not raise
        s2.umount()

    def test_clone_truncates_longer_target(self, tmp_path):
        s = FileStore(str(tmp_path / "s"))
        s.mount()
        t = Transaction().create_collection("c")
        t.write("c", "o", 0, b"data")
        t.write("c", "o2", 0, b"0123456789")
        s.queue_transaction(t)
        s.queue_transaction(Transaction().clone("c", "o", "o2"))
        assert s.read("c", "o2") == b"data"
        s.umount()

    def test_rmcoll_clears_object_metadata(self, tmp_path):
        s = FileStore(str(tmp_path / "s"))
        s.mount()
        t = Transaction().create_collection("c").touch("c", "o")
        t.setattr("c", "o", "k", b"old")
        s.queue_transaction(t)
        s.queue_transaction(Transaction().remove_collection("c"))
        s.queue_transaction(Transaction().create_collection("c").touch("c", "o"))
        with pytest.raises(StoreError):
            s.getattr("c", "o", "k")
        s.umount()

    def test_setattr_creates_object_like_memstore(self, tmp_path):
        s = FileStore(str(tmp_path / "s"))
        s.mount()
        t = Transaction().create_collection("c")
        t.setattr("c", "o", "k", b"v")
        s.queue_transaction(t)
        assert s.exists("c", "o")
        assert s.getattr("c", "o", "k") == b"v"
        s.umount()


class TestKV:
    def test_memkv(self):
        kv = MemKV()
        kv.set("p", "b", b"2")
        kv.set("p", "a", b"1")
        kv.set("q", "c", b"3")
        assert kv.get("p", "a") == b"1"
        assert list(kv.iterate("p")) == [("a", b"1"), ("b", b"2")]
        kv.rm("p", "a")
        assert kv.get("p", "a") is None

    def test_filekv_persistence(self, tmp_path):
        path = str(tmp_path / "kv.log")
        kv = FileKV(path)
        kv.set("p", "x", b"1")
        kv.set("p", "y", b"2")
        kv.rm("p", "x")
        kv.close()
        kv2 = FileKV(path)
        assert kv2.get("p", "x") is None
        assert kv2.get("p", "y") == b"2"
        kv2.close()

    def test_filekv_torn_tail_truncated(self, tmp_path):
        path = str(tmp_path / "kv.log")
        kv = FileKV(path)
        kv.set("p", "good", b"1")
        kv.close()
        # append garbage (a torn record)
        with open(path, "ab") as f:
            f.write(struct.pack("<BII", 1, 100, 100) + b"partial")
        kv2 = FileKV(path)
        assert kv2.get("p", "good") == b"1"
        kv2.set("p", "after", b"2")  # log still writable after truncation
        kv2.close()
        kv3 = FileKV(path)
        assert kv3.get("p", "after") == b"2"
        kv3.close()

    def test_filekv_compaction_preserves_data(self, tmp_path):
        path = str(tmp_path / "kv.log")
        kv = FileKV(path)
        for i in range(300):
            kv.set("p", "hot", str(i).encode())
        size = os.path.getsize(path)
        assert size < 300 * 20  # compaction kicked in
        assert kv.get("p", "hot") == b"299"
        kv.close()
