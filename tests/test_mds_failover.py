"""MDSMonitor / FSMap: mon-managed MDS ranks, standby failover, replay.

Models the reference's MDSMonitor coverage (src/mon/MDSMonitor.cc beacon
→ rank assignment, mds_beacon_grace failover; qa/tasks/cephfs
test_failover.py): two daemons boot via vstart, the fsmap names rank 0,
killing the active promotes the standby, and the promoted daemon's
journal REPLAY makes every acknowledged mutation visible again.
"""

import asyncio

import pytest

from ceph_tpu.client import Rados
from ceph_tpu.mds.client import CephFSClient
from ceph_tpu.mon.mds_monitor import BEACON_GRACE
from ceph_tpu.tools.vstart import DevCluster

from test_cluster import wait_until


def test_fs_new_requires_pools():
    async def run():
        cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=False)
        await cluster.start()
        client = Rados(cluster.monmap)
        await client.connect()
        rv, rs, _ = await client.mon_command(
            {"prefix": "fs new", "fs_name": "x", "metadata": "nope",
             "data": "nope2"}
        )
        assert rv != 0 and "does not exist" in rs
        # fs rm guards: a name is required, and a typo'd name must not
        # remove anything
        rv, rs, _ = await client.mon_command({"prefix": "fs rm"})
        assert rv != 0
        rv, rs, _ = await client.mon_command(
            {"prefix": "fs rm", "fs_name": "no-such-fs"}
        )
        assert rv != 0 and "does not exist" in rs
        await client.shutdown()
        await cluster.stop()

    asyncio.run(run())


def test_fsmap_ranks_and_status():
    async def run():
        cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=False, with_mds=True)
        await cluster.start()
        assert len(cluster.mds_daemons) == 2
        states = sorted(d.state for d in cluster.mds_daemons)
        assert states == ["active", "standby"]
        client = Rados(cluster.monmap)
        await client.connect()
        rv, _, out = await client.mon_command({"prefix": "fs status"})
        assert rv == 0
        import json

        st = json.loads(out)
        fs = st["filesystems"][0]
        assert fs["name"] == "cephfs"
        assert fs["rank0"] == cluster.mds.name
        assert len(fs["standbys"]) == 1
        assert fs["state"] == "up:active"
        # `ceph status` carries the fsmap line
        rv, _, out = await client.mon_command({"prefix": "status"})
        assert rv == 0
        assert json.loads(out)["fsmap"]["filesystems"][0]["name"] == "cephfs"
        await client.shutdown()
        await cluster.stop()

    asyncio.run(run())


def test_active_mds_failover_with_journal_replay():
    """Kill rank 0 WITHOUT flushing (a crash): the mon fails it over on
    beacon timeout, the standby replays the journal, and a monmap-driven
    client re-resolves and reads every acknowledged file back."""

    async def run():
        cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=False, with_mds=True)
        await cluster.start()
        rados = Rados(cluster.monmap)
        await rados.connect()
        data_io = await rados.open_ioctx("cephfs_data")
        fsc = CephFSClient(data_ioctx=data_io, monmap=cluster.monmap)
        await fsc.connect()
        await fsc.mkdir("/dir")
        for i in range(3):
            await fsc.write_file(f"/dir/f{i}", f"payload {i}".encode() * 20)
        old_active = cluster.mds
        standby = next(d for d in cluster.mds_daemons if d is not old_active)
        # crash the active: no flush — the journal must carry the state
        await old_active.stop(flush=False)
        await wait_until(
            lambda: standby.state == "active",
            BEACON_GRACE + 10.0,
            "standby promoted to rank 0",
        )
        # acknowledged namespace + data survive via journal replay
        assert sorted(await fsc.listdir("/dir")) == ["f0", "f1", "f2"]
        for i in range(3):
            got = await fsc.read_file(f"/dir/f{i}")
            assert got == f"payload {i}".encode() * 20
        # and the fs keeps working on the new active
        await fsc.write_file("/dir/after", b"post-failover")
        assert await fsc.read_file("/dir/after") == b"post-failover"
        rv, _, out = await rados.mon_command({"prefix": "fs status"})
        import json

        assert json.loads(out)["filesystems"][0]["rank0"] == standby.name
        cluster.mds_daemons.remove(old_active)
        cluster.mds = standby
        await fsc.shutdown()
        await rados.shutdown()
        await cluster.stop()

    asyncio.run(run())
