"""MDSMonitor / FSMap: mon-managed MDS ranks, standby failover, replay.

Models the reference's MDSMonitor coverage (src/mon/MDSMonitor.cc beacon
→ rank assignment, mds_beacon_grace failover; qa/tasks/cephfs
test_failover.py): two daemons boot via vstart, the fsmap names rank 0,
killing the active promotes the standby, and the promoted daemon's
journal REPLAY makes every acknowledged mutation visible again.
"""

import asyncio

import pytest

from ceph_tpu.client import Rados
from ceph_tpu.mds.client import CephFSClient
from ceph_tpu.mon.mds_monitor import BEACON_GRACE
from ceph_tpu.tools.vstart import DevCluster

from test_cluster import wait_until


def test_fs_new_requires_pools():
    async def run():
        cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=False)
        await cluster.start()
        client = Rados(cluster.monmap)
        await client.connect()
        rv, rs, _ = await client.mon_command(
            {"prefix": "fs new", "fs_name": "x", "metadata": "nope",
             "data": "nope2"}
        )
        assert rv != 0 and "does not exist" in rs
        # fs rm guards: a name is required, and a typo'd name must not
        # remove anything
        rv, rs, _ = await client.mon_command({"prefix": "fs rm"})
        assert rv != 0
        rv, rs, _ = await client.mon_command(
            {"prefix": "fs rm", "fs_name": "no-such-fs"}
        )
        assert rv != 0 and "does not exist" in rs
        await client.shutdown()
        await cluster.stop()

    asyncio.run(run())


def test_fsmap_ranks_and_status():
    async def run():
        cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=False, with_mds=True)
        await cluster.start()
        assert len(cluster.mds_daemons) == 2
        states = sorted(d.state for d in cluster.mds_daemons)
        assert states == ["active", "standby"]
        client = Rados(cluster.monmap)
        await client.connect()
        rv, _, out = await client.mon_command({"prefix": "fs status"})
        assert rv == 0
        import json

        st = json.loads(out)
        fs = st["filesystems"][0]
        assert fs["name"] == "cephfs"
        assert fs["rank0"] == cluster.mds.name
        assert len(st["standbys"]) == 1  # shared standby pool (FSMap.h)
        assert fs["state"] == "up:active"
        # `ceph status` carries the fsmap line
        rv, _, out = await client.mon_command({"prefix": "status"})
        assert rv == 0
        assert json.loads(out)["fsmap"]["filesystems"][0]["name"] == "cephfs"
        await client.shutdown()
        await cluster.stop()

    asyncio.run(run())


def test_multiple_filesystems_independent_namespaces():
    """FSMap multi-fs (src/mds/FSMap.h filesystems map): two `fs new`
    filesystems each get their own rank 0 from the shared standby pool,
    serve DISJOINT namespaces from their own pools, and `fs rm` of one
    returns its daemon to the standby pool without touching the other."""

    async def run():
        import json

        from ceph_tpu.mds.mds import MDS

        cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=False, with_mds=True)
        await cluster.start()
        rados = Rados(cluster.monmap)
        await rados.connect()
        # a second filesystem over its own pools; the standby takes it
        await rados.pool_create("fs2_meta", "replicated", size=2, pg_num=2)
        await rados.pool_create("fs2_data", "replicated", size=2, pg_num=2)
        rv, rs, _ = await rados.mon_command(
            {"prefix": "fs new", "fs_name": "fs2", "metadata": "fs2_meta",
             "data": "fs2_data"}
        )
        assert rv == 0, rs
        await wait_until(
            lambda: sum(d.state == "active" for d in cluster.mds_daemons) == 2,
            10.0,
            "both filesystems get a rank 0",
        )
        assert {d.fs_name for d in cluster.mds_daemons} == {"cephfs", "fs2"}
        # duplicate fs name rejected
        rv, _, _ = await rados.mon_command(
            {"prefix": "fs new", "fs_name": "fs2", "metadata": "fs2_meta",
             "data": "fs2_data"}
        )
        assert rv != 0
        # disjoint namespaces through fs_name-addressed clients
        d1 = await rados.open_ioctx("cephfs_data")
        d2 = await rados.open_ioctx("fs2_data")
        c1 = CephFSClient(data_ioctx=d1, monmap=cluster.monmap,
                          fs_name="cephfs", name="client.c1")
        c2 = CephFSClient(data_ioctx=d2, monmap=cluster.monmap,
                          fs_name="fs2", name="client.c2")
        await c1.connect()
        await c2.connect()
        await c1.write_file("/one", b"fs one")
        await c2.write_file("/two", b"fs two")
        assert await c1.listdir("/") == ["one"]
        assert await c2.listdir("/") == ["two"]
        assert await c2.read_file("/two") == b"fs two"
        # fs status lists both
        rv, _, out = await rados.mon_command({"prefix": "fs status"})
        st = json.loads(out)
        assert [f["name"] for f in st["filesystems"]] == ["cephfs", "fs2"]
        # removing fs2 frees its daemon back into the standby pool
        rv, _, _ = await rados.mon_command(
            {"prefix": "fs rm", "fs_name": "fs2"}
        )
        assert rv == 0
        await wait_until(
            lambda: sum(d.state == "standby" for d in cluster.mds_daemons) == 1,
            10.0,
            "fs2's daemon demoted to standby",
        )
        assert await c1.read_file("/one") == b"fs one"  # cephfs untouched
        await c1.shutdown()
        await c2.shutdown()
        await rados.shutdown()
        await cluster.stop()

    asyncio.run(run())


def test_zombie_active_is_fenced_before_standby_promotion():
    """STALL rank 0 (partition its beacons, leave the daemon — flush
    loop, sessions, RADOS client — running): the mon must blocklist the
    zombie's RADOS client via the OSDMonitor BEFORE promoting the
    standby (MDSMonitor::fail_mds_gid), so the zombie's in-flight
    metadata writes bounce at every OSD instead of racing the promoted
    standby's journal — the split-brain corruption window (ADVICE round
    5, high)."""

    async def run():
        import json

        from ceph_tpu.client.rados import RadosError

        cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=False, with_mds=True)
        await cluster.start()
        rados = Rados(cluster.monmap)
        await rados.connect()
        data_io = await rados.open_ioctx("cephfs_data")
        fsc = CephFSClient(data_ioctx=data_io, monmap=cluster.monmap)
        await fsc.connect()
        await fsc.write_file("/pre", b"before the stall")
        zombie = cluster.mds
        standby = next(d for d in cluster.mds_daemons if d is not zombie)
        zombie_client = zombie.rados.objecter.reqid_name
        # stall, don't stop: beacons cease (the partition) but the
        # daemon's flush loop and RADOS client stay alive — a zombie
        zombie._beacon_task.cancel()
        await wait_until(
            lambda: standby.state == "active",
            BEACON_GRACE + 10.0,
            "standby promoted to rank 0",
        )
        # the fence must already be committed: the zombie's client is in
        # the blocklist and every OSD has applied the epoch
        rv, _, out = await rados.mon_command({"prefix": "osd blocklist ls"})
        assert rv == 0
        assert zombie_client in json.loads(out), "zombie was never fenced"
        await wait_until(
            lambda: all(
                zombie_client in o.osdmap.blocklist for o in cluster.osds
            ),
            10.0,
            "blocklist epoch reaching the OSDs",
        )
        # the zombie's writes into the metadata pool now bounce — the
        # split-brain write is dead even though the process is alive
        with pytest.raises((RadosError, TimeoutError)):
            await zombie.meta.write_full("zombie_marker", b"stale active")
        # the promoted standby serves: old data visible, new writes land
        assert await fsc.read_file("/pre") == b"before the stall"
        await fsc.write_file("/post", b"after failover")
        assert await fsc.read_file("/post") == b"after failover"
        rv, _, out = await rados.mon_command({"prefix": "fs status"})
        assert json.loads(out)["filesystems"][0]["rank0"] == standby.name
        cluster.mds_daemons.remove(zombie)
        cluster.mds = standby
        # direct teardown of the zombie (its stop() would try to flush
        # through the fenced client and hang)
        for t in (zombie._flush_task, zombie._activate_task):
            if t is not None:
                t.cancel()
        await zombie.msgr.shutdown()
        await fsc.shutdown()
        await rados.shutdown()
        await cluster.stop()

    asyncio.run(run())


def test_active_mds_failover_with_journal_replay():
    """Kill rank 0 WITHOUT flushing (a crash): the mon fails it over on
    beacon timeout, the standby replays the journal, and a monmap-driven
    client re-resolves and reads every acknowledged file back."""

    async def run():
        cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=False, with_mds=True)
        await cluster.start()
        rados = Rados(cluster.monmap)
        await rados.connect()
        data_io = await rados.open_ioctx("cephfs_data")
        fsc = CephFSClient(data_ioctx=data_io, monmap=cluster.monmap)
        await fsc.connect()
        await fsc.mkdir("/dir")
        for i in range(3):
            await fsc.write_file(f"/dir/f{i}", f"payload {i}".encode() * 20)
        old_active = cluster.mds
        standby = next(d for d in cluster.mds_daemons if d is not old_active)
        # crash the active: no flush — the journal must carry the state
        await old_active.stop(flush=False)
        await wait_until(
            lambda: standby.state == "active",
            BEACON_GRACE + 10.0,
            "standby promoted to rank 0",
        )
        # acknowledged namespace + data survive via journal replay
        assert sorted(await fsc.listdir("/dir")) == ["f0", "f1", "f2"]
        for i in range(3):
            got = await fsc.read_file(f"/dir/f{i}")
            assert got == f"payload {i}".encode() * 20
        # and the fs keeps working on the new active
        await fsc.write_file("/dir/after", b"post-failover")
        assert await fsc.read_file("/dir/after") == b"post-failover"
        rv, _, out = await rados.mon_command({"prefix": "fs status"})
        import json

        assert json.loads(out)["filesystems"][0]["rank0"] == standby.name
        cluster.mds_daemons.remove(old_active)
        cluster.mds = standby
        await fsc.shutdown()
        await rados.shutdown()
        await cluster.stop()

    asyncio.run(run())
