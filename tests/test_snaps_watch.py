"""Server-side object features: snapshots, watch/notify, copy-from.

Models the reference's coverage of PrimaryLogPG's op switch
(src/osd/PrimaryLogPG.cc:5960): make_writeable clone-on-write, snap
reads/rollback/trim, watch/notify with timeout, and OSD-to-OSD
copy-from — all over live clusters (replicated AND erasure pools).
"""

import asyncio
import json

import pytest

from ceph_tpu.client import Rados, RadosError
from ceph_tpu.osd.snaps import SnapSet

from test_cluster import start_cluster, stop_cluster, wait_until


class TestSnapSet:
    def test_clone_bookkeeping(self):
        ss = SnapSet()
        assert ss.needs_clone(1, [1]) == [1]
        cid = ss.add_clone([1], 100)
        assert cid == 1 and ss.seq == 1
        assert ss.needs_clone(1, [1]) == []  # already cloned for snap 1
        cid = ss.add_clone([2, 3], 200)
        assert cid == 3
        # resolution: oldest clone with id >= snap
        assert ss.resolve(1) == 1
        assert ss.resolve(2) == 3
        assert ss.resolve(3) == 3
        assert ss.resolve(4) is None  # head
        # encode round trip
        ss2 = SnapSet.decode(ss.encode())
        assert ss2.seq == ss.seq and ss2.clones == ss.clones

    def test_drop_snap(self):
        ss = SnapSet()
        ss.add_clone([1], 10)
        ss.add_clone([2, 3], 20)
        assert ss.drop_snap(2) is None  # clone 3 still covers snap 3
        assert ss.drop_snap(3) == 3  # now unreferenced: delete clone 3
        assert ss.drop_snap(1) == 1
        assert ss.clones == []


def _snap_workout(pool_kind):
    async def run():
        monmap, mons, osds = await start_cluster(1, 4)
        client = Rados(monmap)
        await client.connect()
        if pool_kind == "erasure":
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "snapec",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("snapp", "erasure", profile="snapec", pg_num=4)
        else:
            await client.pool_create("snapp", "replicated", size=3, pg_num=4)
        ioctx = await client.open_ioctx("snapp")

        v1 = b"version-one " * 700
        await ioctx.write_full("obj", v1)

        # --- snap 1, then overwrite: first write clones the head
        s1 = await client.selfmanaged_snap_create("snapp")
        ioctx.set_snap_context(s1, [s1])
        v2 = b"version-TWO " * 650
        await ioctx.write_full("obj", v2)

        assert await ioctx.read("obj") == v2
        assert await ioctx.read("obj", snap=s1) == v1
        assert await ioctx.stat("obj", snap=s1) == len(v1)
        snapset = await ioctx.list_snaps("obj")
        assert [c["id"] for c in snapset["clones"]] == [s1]

        # --- snap 2 with NO subsequent write: head serves the snap read
        s2 = await client.selfmanaged_snap_create("snapp")
        ioctx.set_snap_context(s2, [s2, s1])
        assert await ioctx.read("obj", snap=s2) == v2

        # --- snap 3 + write: clone covers (s2..s3]
        s3 = await client.selfmanaged_snap_create("snapp")
        ioctx.set_snap_context(s3, [s3, s2, s1])
        v3 = b"v3 bytes " * 900
        await ioctx.write_full("obj", v3)
        assert await ioctx.read("obj", snap=s1) == v1
        assert await ioctx.read("obj", snap=s2) == v2
        assert await ioctx.read("obj", snap=s3) == v2
        assert await ioctx.read("obj") == v3

        # --- object created after s1: reading it at s1 is ENOENT
        await ioctx.write_full("late", b"late bytes")
        with pytest.raises(RadosError):
            await ioctx.read("late", snap=s1)
        assert await ioctx.read("late") == b"late bytes"

        # --- rollback to s1: head becomes v1; v3 (written after the newest
        # snap, so covered by none) is discarded — rollback semantics.
        await ioctx.rollback("obj", s1)
        assert await ioctx.read("obj") == v1
        assert await ioctx.read("obj", snap=s3) == v2
        assert await ioctx.read("obj", snap=s1) == v1

        # --- snap trim: dropping s1's coverage deletes its clone
        before = set(await ioctx.list_objects())
        assert "obj" in before and not any("@" in o for o in before)
        await ioctx.snap_trim("obj", s1)
        ss = await ioctx.list_snaps("obj")
        assert s1 not in [s for c in ss["clones"] for s in c["snaps"]]

        # clones are invisible to pool listings
        assert not any("@" in o for o in await ioctx.list_objects())

        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())


class TestSnapshots:
    def test_replicated_pool_snaps(self):
        _snap_workout("replicated")

    def test_erasure_pool_snaps(self):
        _snap_workout("erasure")


class TestWatchNotify:
    def test_notify_reaches_watchers_with_acks(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            w1 = Rados(monmap, name="client.w1")
            w2 = Rados(monmap, name="client.w2")
            notifier = Rados(monmap, name="client.n")
            for c in (w1, w2, notifier):
                await c.connect()
            await notifier.pool_create("wn", "replicated", size=2, pg_num=2)
            io_n = await notifier.open_ioctx("wn")
            io_1 = await w1.open_ioctx("wn")
            io_2 = await w2.open_ioctx("wn")
            await io_n.write_full("watched", b"content")

            got1, got2 = [], []
            c1 = await io_1.watch(
                "watched", lambda nid, p: (got1.append(p), b"ack-from-w1")[1]
            )
            c2 = await io_2.watch(
                "watched", lambda nid, p: (got2.append(p), b"")[1]
            )

            res = await io_n.notify("watched", b"hello watchers")
            assert got1 == [b"hello watchers"]
            assert got2 == [b"hello watchers"]
            assert res["timeouts"] == []
            # watcher keys carry the client's per-instance identity
            # (entity + nonce, the reference's name.global_id shape)
            k1 = f"{w1.objecter.reqid_name}/{c1}"
            k2 = f"{w2.objecter.reqid_name}/{c2}"
            assert set(res["acks"]) == {k1, k2}
            assert bytes.fromhex(res["acks"][k1]) == b"ack-from-w1"

            # listwatchers sees both registrations
            watchers = await io_n.list_watchers("watched")
            assert {w["watcher"] for w in watchers} == {
                w1.objecter.reqid_name, w2.objecter.reqid_name
            }
            # unwatch: w2 no longer hears notifies
            await io_2.unwatch("watched", c2)
            res = await io_n.notify("watched", b"again")
            assert got2 == [b"hello watchers"]
            assert set(res["acks"]) == {k1}
            assert len(await io_n.list_watchers("watched")) == 1

            for c in (w1, w2, notifier):
                await c.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_unresponsive_watcher_times_out(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            watcher = Rados(monmap, name="client.dead")
            notifier = Rados(monmap, name="client.n")
            for c in (watcher, notifier):
                await c.connect()
            await notifier.pool_create("wt", "replicated", size=2, pg_num=1)
            io_w = await watcher.open_ioctx("wt")
            io_n = await notifier.open_ioctx("wt")
            await io_n.write_full("o", b"x")

            cookie = await io_w.watch("o", lambda nid, p: b"")
            # Wedge the watcher: it swallows every message, so the push is
            # never acked — the notify must complete via its timeout.
            watcher.objecter.ms_dispatch = lambda conn, msg: True

            res = await io_n.notify("o", b"anyone there?", timeout_ms=500)
            assert res["timeouts"] == [
                f"{watcher.objecter.reqid_name}/{cookie}"
            ]
            assert res["acks"] == {}

            for c in (watcher, notifier):
                await c.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestCopyFrom:
    def test_copy_within_pool_and_from_snapshot(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("cp", "replicated", size=2, pg_num=8)
            ioctx = await client.open_ioctx("cp")

            payload = bytes((i * 31 + 7) % 256 for i in range(50_000))
            await ioctx.write_full("src", payload)

            # server-side copy (src and dst hash to arbitrary PGs/primaries)
            await ioctx.copy_from("dst", "src")
            assert await ioctx.read("dst") == payload

            # copy from a snapshot of src after src moved on
            s1 = await client.selfmanaged_snap_create("cp")
            ioctx.set_snap_context(s1, [s1])
            await ioctx.write_full("src", b"moved on")
            await ioctx.copy_from("dst2", "src", src_snap=s1)
            assert await ioctx.read("dst2") == payload
            assert await ioctx.read("src") == b"moved on"

            # missing source surfaces an error, not a hang
            with pytest.raises(RadosError):
                await ioctx.copy_from("dst3", "no-such-object")

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())
