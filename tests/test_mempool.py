"""HBM mempool ledger (ISSUE 13): accounting, reconciliation, pressure
staging, and the health pipeline.

Acceptance shape: every HBM holder (donation pool, pipeline in-flight
ring, device chunk cache, sharded placements) accounts its bytes in the
process-wide ledger; ledger totals reconcile against the sum of live
tracked-buffer nbytes under the 8-concurrent-submitter harness at
pipeline depth 4 with faults armed (host-fallback and sticky-error
settles release their holds); the pressure layer trims cache → donation
retention → pipeline depth in order and raises/clears TPU_HBM_PRESSURE
through the mon; and the device cache's cap-shrink observer recomputes
resident bytes from the entry index instead of trusting a drifted
counter."""

import asyncio
import gc
import threading

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import (
    DecodeAggregator,
    DonationPool,
    EncodeAggregator,
    VerifyAggregator,
)
from ceph_tpu.common.fault_injector import global_injector
from ceph_tpu.common.mempool import (
    POOLS,
    MempoolLedger,
    ledger,
    track_buffer,
)
from ceph_tpu.ops.device_cache import DeviceChunkCache, device_chunk_cache
from ceph_tpu.ops.guard import device_guard


@pytest.fixture(autouse=True)
def _clean_state():
    yield
    global_injector().clear()
    device_guard().mark_healthy()
    led = ledger()
    led.configure(target_bytes=0)
    led.check_pressure()  # releases any caps a pressure test armed


def make_rs(k=4, m=2):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


class TestLedgerCore:
    def test_alloc_resize_free_and_peaks(self):
        led = MempoolLedger()
        h = led.alloc("ec_donation", 1000)
        assert led.current_bytes("ec_donation") == 1000
        h.resize(4000)
        assert led.current_bytes("ec_donation") == 4000
        snap = led.snapshot()["ec_donation"]
        assert snap["peak_bytes"] == 4000 and snap["buffers"] == 1
        h.free()
        h.free()  # idempotent: the finalizer double-release shape
        assert led.current_bytes("ec_donation") == 0
        assert led.snapshot()["ec_donation"]["peak_bytes"] == 4000
        led.reset_peaks()
        assert led.snapshot()["ec_donation"]["peak_bytes"] == 0

    def test_predeclared_pools_and_dynamic_creation(self):
        led = MempoolLedger()
        assert set(led.snapshot()) == set(POOLS)
        led.alloc("experimental", 10)
        assert led.snapshot()["experimental"]["bytes"] == 10

    def test_track_buffer_frees_on_gc(self):
        import jax.numpy as jnp

        led = ledger()
        base = led.current_bytes("scratch")
        buf = track_buffer(jnp.zeros(2048, dtype=jnp.uint8), "scratch")
        assert led.current_bytes("scratch") == base + 2048
        del buf
        gc.collect()
        assert led.current_bytes("scratch") == base

    def test_track_buffer_skips_host_arrays(self):
        led = ledger()
        base = led.current_bytes("scratch")
        arr = np.zeros(4096, dtype=np.uint8)
        assert track_buffer(arr, "scratch") is arr
        assert led.current_bytes("scratch") == base

    def test_debug_mode_shards_by_call_site(self):
        led = MempoolLedger(debug=True)
        h = led.alloc("scratch", 512)
        dump = led.dump()
        assert dump["debug"]
        (site,) = [s for s in dump["by_site"] if s.startswith("scratch@")]
        assert "test_mempool.py" in site
        assert dump["by_site"][site]["bytes"] == 512
        h.free()
        assert not led.dump()["by_site"]

    def test_finalizer_reentrancy_under_lock(self):
        """A cyclic-GC pass can fire a tracked buffer's finalizer (which
        frees its handle through the ledger lock) INSIDE an accounting
        call that already holds the lock — the free must re-enter, not
        self-deadlock (the tier-1 hang this pins down)."""
        led = MempoolLedger()
        h = led.alloc("scratch", 10)
        with led._lock:  # what alloc/_resize hold when GC strikes
            h.free()
        assert led.current_bytes("scratch") == 0

    def test_gc_finalizers_defer_instead_of_locking(self):
        """Buffer finalizers fire in GC context, where acquiring ANY
        lock can self-deadlock the interrupted thread (under lockdep
        every instrumented acquire shares one plain registry mutex
        whose critical sections allocate).  The finalizer must only
        enqueue; the books close on the next accounting call."""
        import jax.numpy as jnp

        led = MempoolLedger()
        buf = jnp.zeros(512, dtype=jnp.uint8)
        led.alloc("scratch", 512, buf=buf)
        del buf
        gc.collect()
        # the finalizer ran but took no lock: the handle is parked on
        # the deferred queue, counters untouched
        assert led._deferred, "finalizer freed inline (GC-context lock)"
        assert led._pools["scratch"].bytes == 512
        # first accounting read drains it
        assert led.current_bytes("scratch") == 0
        assert not led._deferred

    def test_alloc_drains_deferred_so_peaks_track_concurrency(self):
        """Transient tracked buffers in an allocate-only loop (the
        bench hbm_peak_bytes shape: no accounting READ between
        iterations) must not pile up as deferred dead bytes — alloc
        drains first, so peaks reflect true concurrent residency."""
        import jax.numpy as jnp

        led = MempoolLedger()
        for i in range(20):
            buf = jnp.zeros(1024, dtype=jnp.uint8) + i
            led.alloc("scratch", 1024, buf=buf)
            del buf
            gc.collect()
        # at most the newest allocation is still counted (its buffer
        # just died; the NEXT accounting call collects it)
        assert led.total_device_bytes() <= 1024
        assert led.peak_total_bytes() <= 3 * 1024, led.peak_total_bytes()

    def test_explicit_free_detaches_the_finalizer(self):
        """A recycled buffer (donation pool) gets a fresh handle per
        cycle; the explicit free must detach the old finalizer or the
        buffer pins one dead handle per cycle for its lifetime."""
        import jax.numpy as jnp

        led = MempoolLedger()
        buf = jnp.zeros(256, dtype=jnp.uint8)
        for _ in range(5):
            led.alloc("scratch", 256, buf=buf).free()
        del buf
        gc.collect()
        # every finalizer was detached at free: nothing enqueued
        assert not led._deferred
        assert led.current_bytes("scratch") == 0

    def test_reconcile_exposes_counter_drift(self):
        led = MempoolLedger()
        led.alloc("device_cache", 1000)
        assert led.reconcile()["device_cache"]["drift"] == 0
        # simulate a subsystem decrementing its counter wrongly (the
        # drift class the device-cache fix addresses)
        with led._lock:
            led._pools["device_cache"].bytes -= 400
        assert led.reconcile()["device_cache"]["drift"] == -400

    def test_per_device_breakdown(self):
        import jax.numpy as jnp

        led = MempoolLedger()
        buf = jnp.zeros(4096, dtype=jnp.uint8)  # held: alive through the dump
        led.alloc("scratch", 4096, buf=buf)
        led.alloc("scratch", 100)  # no placement known
        per = led.per_device()
        assert sum(per.values()) == 4196
        assert per.get("unplaced") == 100
        del buf
        # a byte count that does not divide the device set still sums
        # exactly (the remainder lands on the first device)
        led2 = MempoolLedger()
        h = led2.alloc("scratch", 100)
        h.devices = ("a", "b", "c")
        assert sum(led2.per_device().values()) == 100


class TestDonationPoolAccounting:
    def test_put_take_and_overflow_track_ec_donation(self):
        import jax.numpy as jnp

        led = ledger()
        base = led.current_bytes("ec_donation")
        pool = DonationPool(cap=2)
        bufs = [jnp.zeros(1024, dtype=jnp.uint8) + i for i in range(3)]
        pool.put((1024,), bufs[0])
        pool.put((1024,), bufs[1])
        assert led.current_bytes("ec_donation") == base + 2048
        pool.put((1024,), bufs[2])  # overflow: oldest out
        assert led.current_bytes("ec_donation") == base + 2048
        assert pool.take((1024,)) is not None
        assert led.current_bytes("ec_donation") == base + 1024
        assert pool.drop_free() == 1024
        assert led.current_bytes("ec_donation") == base

    def test_dead_pool_cannot_leak(self):
        import jax.numpy as jnp

        led = ledger()
        base = led.current_bytes("ec_donation")
        pool = DonationPool(cap=2)
        pool.put((512,), jnp.zeros(512, dtype=jnp.uint8))
        assert led.current_bytes("ec_donation") == base + 512
        del pool
        gc.collect()  # buffer finalizer closes the handle
        assert led.current_bytes("ec_donation") == base


class TestDeviceCacheAccounting:
    def test_ledger_tracks_entry_lifecycle(self):
        led = ledger()
        base = led.current_bytes("device_cache")
        cc = DeviceChunkCache(max_bytes=1 << 20)
        cc.put("a", 0, 1, np.zeros(4096, dtype=np.uint8))
        cc.put("b", 0, 1, np.zeros(8192, dtype=np.uint8))
        assert led.current_bytes("device_cache") == base + 12288
        assert cc.perf_dump()["resident_bytes"] == 12288
        cc.invalidate_object("a")
        assert led.current_bytes("device_cache") == base + 8192
        cc.clear()
        assert led.current_bytes("device_cache") == base

    def test_cap_shrink_recomputes_resident_bytes(self):
        """The ISSUE 13 satellite fix: the runtime cap-shrink observer
        must recompute resident bytes from the entry index — a drifted
        (stale-low) counter would otherwise evict too little and leave
        the cache over its new cap forever."""
        led = ledger()
        base = led.current_bytes("device_cache")
        cc = DeviceChunkCache(max_bytes=1 << 20)
        for i in range(4):
            cc.put(f"o{i}", 0, 1, np.full(65536, i, dtype=np.uint8))
        # inject historical counter drift: the counter reads 100000 low
        with cc._lock:
            cc._bytes -= 100000
        cc.configure(max_bytes=128 << 10)
        dump = cc.perf_dump()
        with cc._lock:
            index_bytes = sum(e.nbytes for e in cc._entries.values())
        assert dump["resident_bytes"] == index_bytes
        assert dump["resident_bytes"] <= 128 << 10, (
            "cap shrink trusted the drifted counter and under-evicted"
        )
        # the ledger agreed with the index all along (per-entry handles)
        assert led.current_bytes("device_cache") == base + index_bytes
        cc.clear()

    def test_trim_for_pressure_evicts_lru_first(self):
        cc = DeviceChunkCache(max_bytes=1 << 20)
        cc.put("old", 0, 1, np.zeros(4096, dtype=np.uint8))
        cc.put("new", 0, 1, np.zeros(4096, dtype=np.uint8))
        assert cc.get("new", 0, 1) is not None  # refresh LRU position
        freed = cc.trim_for_pressure(1)
        assert freed == 4096
        assert cc.get("old", 0, 1) is None
        assert cc.get("new", 0, 1) is not None
        cc.clear()


class TestReconciliationUnderLoad:
    def test_8_submitters_depth4_with_faults(self):
        """The acceptance harness: 8 concurrent submitters driving
        encode+decode+verify through depth-4 pipelines WITH launch
        faults armed (1-in-3 dispatches fail to the host oracle).  After
        drain the in-flight pools read zero — the host-fallback path
        released its holds — the handle registry reconciles against the
        counters with zero drift, and the donation pools' ledger bytes
        equal the sum of the actually-pooled buffers' nbytes."""
        led = ledger()
        base_donation = led.current_bytes("ec_donation")
        ec = make_rs(4, 2)
        agg = EncodeAggregator(window=4, pipeline_depth=4)
        dagg = DecodeAggregator(window=4, pipeline_depth=4)
        vagg = VerifyAggregator(window=4, pipeline_depth=4)
        inj = global_injector()
        inj.inject_probabilistic("codec.launch", 3)
        errors: list[BaseException] = []

        def submitter(tid: int) -> None:
            rng = np.random.default_rng(1000 + tid)
            try:
                for i in range(5):
                    # >= PACKED_MIN_BYTES so the coalesced launches take
                    # the donatable packed path — the donation pool and
                    # its ledger accounting are part of what reconciles
                    data = rng.integers(0, 256, (4, 4, 4096), dtype=np.uint8)
                    par = np.asarray(agg.submit(ec, data))
                    assert np.array_equal(
                        par, np.asarray(ec.encode_array_host(data))
                    )
                    full = np.concatenate([data, par], axis=1)
                    erasures = [int(rng.integers(0, 6))]
                    idx = ec.decode_index(erasures)
                    rec = np.asarray(
                        dagg.submit(ec, erasures, full[:, idx, :])
                    )
                    assert np.array_equal(rec, full[:, erasures, :])
                    bitmap = np.asarray(vagg.submit(ec, full))
                    assert not bitmap.any()
            except BaseException as e:  # surfaced after join
                errors.append(e)

        threads = [
            threading.Thread(target=submitter, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        inj.clear()
        for a in (agg, dagg, vagg):
            a.drain()
        assert not errors, errors
        assert led.current_bytes("ec_pipeline_inflight") == 0
        assert led.current_bytes("verify") == 0
        drift = {
            k: v["drift"] for k, v in led.reconcile().items() if v["drift"]
        }
        assert not drift, drift
        pooled = 0
        for a in (agg, dagg, vagg):
            with a._lock:
                pooled += sum(
                    int(b.nbytes)
                    for slot in a._donate_pool._free.values()
                    for b in slot
                )
        assert led.current_bytes("ec_donation") - base_donation == pooled

    def test_donatable_settle_moves_hold_without_double_count(self):
        """A donatable launch's output moves from the in-flight pool to
        ec_donation at settle — the in-flight hold must release BEFORE
        the donation charge, or the same bytes count twice and inflate
        the peak gauges."""
        led = ledger()
        ec = make_rs(4, 2)
        agg = EncodeAggregator(window=2, pipeline_depth=1)
        data = np.zeros((4, 4, 4096), dtype=np.uint8)  # >= packed min
        assert ec.encode_donatable((8, 4, 4096))
        base_total = led.total_device_bytes()
        base_donation = led.current_bytes("ec_donation")
        led.reset_peaks()
        tickets = [agg.submit(ec, data), agg.submit(ec, data)]
        for t in tickets:
            np.asarray(t)
        agg.flush()
        agg.drain()
        assert led.current_bytes("ec_pipeline_inflight") == 0
        parity_nbytes = 8 * 2 * 4096
        assert led.current_bytes("ec_donation") - base_donation == \
            parity_nbytes
        # peak saw ONE accounting of the output (plus small scratch) —
        # a double count at the settle handoff would have spiked it to
        # ~2x the parity size
        assert led.peak_total_bytes() - base_total < int(1.5 * parity_nbytes)
        with agg._lock:
            agg._donate_pool.drop_free()

    def test_sticky_error_settle_releases_hold(self, monkeypatch):
        """A launch that fails on the device AND on the host recompute
        goes sticky — the historical leak shape.  Its settle must still
        zero the in-flight pool."""
        led = ledger()
        base = led.current_bytes("ec_pipeline_inflight")
        ec = make_rs(4, 2)
        agg = EncodeAggregator(window=1, pipeline_depth=2)

        def broken_host(self, data):
            raise RuntimeError("host oracle down too")

        monkeypatch.setattr(
            type(ec), "encode_array_host", broken_host
        )
        global_injector().inject("codec.launch", 5, hits=1)
        t = agg.submit(
            ec, np.zeros((2, 4, 512), dtype=np.uint8)
        )
        global_injector().clear()
        with pytest.raises(Exception):
            np.asarray(t)
        agg.drain()
        assert led.current_bytes("ec_pipeline_inflight") == base


class TestPressureStaging:
    def test_trim_order_cache_then_donation_then_depth(self):
        """One evaluation with an un-trimmable hold big enough that no
        stage relieves it: the cache gives its bytes back first, then
        donation retention caps, then the effective depth clamps — and
        relief releases everything."""
        import jax.numpy as jnp

        led = ledger()
        cc = device_chunk_cache()
        old_max = cc.max_bytes
        pin = None
        pool = DonationPool(cap=2)
        try:
            cc.configure(max_bytes=1 << 20)
            cc.put("press/obj", 0, 1, np.zeros(64 << 10, dtype=np.uint8))
            cache_before = cc.perf_dump()["resident_bytes"]
            assert cache_before >= 64 << 10
            # the pin dwarfs any residual residency earlier suites left
            # in the process-wide ledger, so freeing it guarantees the
            # post-relief ratio lands under the clear threshold
            pin = led.alloc(
                "scratch", max(64 << 20, 50 * led.total_device_bytes())
            )
            untrimmable = (
                led.total_device_bytes()
                - led.current_bytes("device_cache")
                - led.current_bytes("ec_donation")
            )
            led.configure(target_bytes=max(1, untrimmable // 2))
            st = led.check_pressure()
            assert st["pressure"] and st["stage"] == 3, st
            # stage 1 ran: the cache was trimmed to relieve first
            assert st["actions"]["cache_trimmed_bytes"] >= cache_before
            assert cc.perf_dump()["resident_bytes"] == 0
            # stage 2: retention capped — a put no longer pools
            assert led.donation_capped
            pool.put((512,), jnp.zeros(512, dtype=jnp.uint8))
            assert len(pool) == 0
            # stage 3: depth clamped
            assert led.depth_clamped
            # relief: free the hold, next evaluation clears everything
            pin.free()
            pin = None
            st = led.check_pressure()
            assert not st["pressure"] and st["stage"] == 0, st
            assert not led.donation_capped and not led.depth_clamped
            pool.put((512,), jnp.zeros(512, dtype=jnp.uint8))
            assert len(pool) == 1
        finally:
            if pin is not None:
                pin.free()
            pool.drop_free()
            led.configure(target_bytes=0)
            led.check_pressure()
            cc.configure(max_bytes=old_max)

    def test_depth_clamp_bounds_inflight_ring(self):
        """With the clamp armed, a depth-4 aggregator behaves like
        depth 1: at most one launch stays unsettled after a submit."""
        led = ledger()
        ec = make_rs(4, 2)
        agg = EncodeAggregator(window=1, pipeline_depth=4)
        try:
            led.depth_clamped = True
            for i in range(4):
                agg.submit(ec, np.zeros((2, 4, 512), dtype=np.uint8))
            with agg._lock:
                assert len(agg._live) <= 1
        finally:
            led.depth_clamped = False
            agg.drain()

    def test_concurrent_evaluations_never_strand_the_caps(self):
        """Racing check_pressure calls must serialize: an evaluation
        arming the caps interleaved with one clearing the raised state
        would leave donation retention silently disabled with no health
        check raised.  Invariant on every snapshot: caps armed implies
        pressure raised."""
        led = MempoolLedger(target_bytes=1000)
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                led.check_pressure()

        t = threading.Thread(target=hammer)
        t.start()
        try:
            for _ in range(300):
                h = led.alloc("scratch", 2000)  # ratio 2.0: raise
                led.check_pressure()
                h.free()                        # ratio 0.0: clear
                led.check_pressure()
                st = led.pressure_status()
                assert st["pressure"] or not (
                    st["donation_capped"] or st["depth_clamped"]
                ), st
        finally:
            stop.set()
            t.join()

    def test_pressure_status_is_json_safe(self):
        import json

        json.dumps(ledger().check_pressure())
        json.dumps(ledger().dump())


class TestPressureHealthPipeline:
    def test_raise_and_clear_through_mon_health(self):
        """The integration gate: an un-trimmable HBM hold over the
        runtime-set target raises TPU_HBM_PRESSURE at the mon (with
        per-daemon detail) and on the mgr healthcheck surface; freeing
        the hold clears both."""

        async def run():
            from ceph_tpu.mgr import Mgr

            from test_cluster import start_cluster, stop_cluster, wait_until

            led = ledger()
            monmap, mons, osds = await start_cluster(1, 2)
            mgr = Mgr("x", monmap)
            mgr.beacon_interval = 0.1
            await mgr.start()
            await mgr.wait_for_active()
            pin = None
            try:
                # dwarf any residual residency from earlier suites (the
                # ledger is process-wide) so freeing the pin guarantees
                # relief under the clear threshold
                pin = led.alloc(
                    "scratch", max(64 << 20, 50 * led.total_device_bytes())
                )
                untrimmable = (
                    led.total_device_bytes()
                    - led.current_bytes("device_cache")
                    - led.current_bytes("ec_donation")
                )
                # the runtime-observer path IS under test: the config
                # set must reach the live ledger through the OSD's
                # ec_tpu_hbm_target_bytes observer
                osds[0].conf.set(
                    "ec_tpu_hbm_target_bytes", max(1, untrimmable // 2)
                )
                assert led.target_bytes == max(1, untrimmable // 2)

                def raised():
                    checks, _ = mons[0].health_checks()
                    return "TPU_HBM_PRESSURE" in checks
                await wait_until(raised, 10.0, "TPU_HBM_PRESSURE raised")
                checks, details = mons[0].health_checks()
                assert "HBM memory pressure" in checks["TPU_HBM_PRESSURE"]
                assert any(
                    "bytes resident vs" in line
                    for line in details["TPU_HBM_PRESSURE"]
                )
                assert "TPU_HBM_PRESSURE" in mgr.health_checks()
                # the staged response engaged all the way (the hold is
                # un-trimmable, so cache trim + donation cap could not
                # relieve it)
                assert led.depth_clamped
                # relief: free the hold; the next beacons re-evaluate
                # and both surfaces clear
                pin.free()
                pin = None

                def cleared():
                    checks, _ = mons[0].health_checks()
                    return "TPU_HBM_PRESSURE" not in checks
                await wait_until(cleared, 10.0, "TPU_HBM_PRESSURE cleared")
                assert "TPU_HBM_PRESSURE" not in mgr.health_checks()
                assert not led.depth_clamped and not led.donation_capped
            finally:
                if pin is not None:
                    pin.free()
                led.configure(target_bytes=0)
                led.check_pressure()
                await mgr.stop()
                await stop_cluster(mons, osds)

        asyncio.run(run())


class TestSurfacing:
    def test_flight_records_carry_resident_bytes(self):
        from ceph_tpu.ops.flight_recorder import flight_recorder

        led = ledger()
        ec = make_rs(4, 2)
        agg = EncodeAggregator(window=1, pipeline_depth=1)
        h = led.alloc("scratch", 12345)
        try:
            fr = flight_recorder()
            fr.reset()  # the ring is bounded: slicing by index misleads
            np.asarray(agg.submit(ec, np.zeros((2, 4, 512), np.uint8)))
            recs = fr.records()
            assert recs and all(
                r.get("hbm_bytes", 0) >= 12345 for r in recs
            ), recs
        finally:
            h.free()
            agg.drain()
            flight_recorder().reset()

    def test_trace_export_emits_hbm_counter_track(self):
        from ceph_tpu.ops.flight_recorder import flight_recorder
        from ceph_tpu.tools.trace_export import (
            export_chrome_trace,
            validate_chrome_trace,
        )

        ec = make_rs(4, 2)
        agg = EncodeAggregator(window=1, pipeline_depth=1)
        flight_recorder().reset()
        np.asarray(agg.submit(ec, np.zeros((2, 4, 512), np.uint8)))
        trace = export_chrome_trace(flight_recorder().records())
        validate_chrome_trace(trace)
        counters = [
            e for e in trace["traceEvents"] if e.get("ph") == "C"
        ]
        assert counters and all(
            e["name"] == "hbm_resident_bytes" and "bytes" in e["args"]
            for e in counters
        ), counters
        # pre-ledger records (old dumps) must not fabricate a counter
        legacy = [dict(r) for r in flight_recorder().records()]
        for r in legacy:
            r.pop("hbm_bytes", None)
        trace = export_chrome_trace(legacy)
        validate_chrome_trace(trace)
        assert not [e for e in trace["traceEvents"] if e.get("ph") == "C"]
        flight_recorder().reset()

    def test_dump_mempools_reconciles_with_holders(self):
        """The acceptance equality on the asok payload: with cache,
        donation, and scratch holders live, dump_mempools pool totals
        equal the holders' own live-buffer nbytes."""
        import jax.numpy as jnp

        led = ledger()
        base_cache = led.current_bytes("device_cache")
        base_scratch = led.current_bytes("scratch")
        cc = DeviceChunkCache(max_bytes=1 << 20)
        cc.put("x", 0, 1, np.zeros(4096, dtype=np.uint8))
        buf = track_buffer(jnp.zeros(2048, dtype=jnp.uint8), "scratch")
        try:
            pools = led.dump()["pools"]
            assert pools["device_cache"]["bytes"] - base_cache == \
                cc.perf_dump()["resident_bytes"]
            assert pools["scratch"]["bytes"] - base_scratch == buf.nbytes
            rec = led.reconcile()
            assert all(v["drift"] == 0 for v in rec.values()), rec
        finally:
            cc.clear()
            del buf
