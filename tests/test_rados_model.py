"""Model-based random-op stress — the ceph_test_rados / RadosModel analog
(src/test/osd/RadosModel.cc; SURVEY.md §4 tier 4).

A seeded random sequence of weighted ops (write/append/truncate/remove/
snap/rollback/copy_from/xattr) runs against a live cluster while an
in-memory model tracks expected state — head bytes, xattrs, and
snapshot clones with their own covering rule (implemented independently
of the OSD's SnapSet so the two can disagree).  Every few ops the
harness verifies reads (head + every live snap) against the model;
a final sweep checks everything.  Runs over replicated AND EC pools,
matching the reference's ec-rados-plugin=*.yaml op_weights coverage
(write/snap/rollback/copy_from on EC pools).
"""

import asyncio
import random

import pytest

from ceph_tpu.client import Rados, RadosError

from test_cluster import start_cluster, stop_cluster


class ObjModel:
    """Expected state of one object."""

    def __init__(self):
        self.head: bytes | None = None  # None = does not exist
        self.xattrs: dict[str, bytes] = {}
        # snapshot clones: clone_id -> (bytes, covered snap ids) captured
        # when the first write AFTER those snaps' creation cloned the head
        self.clones: dict[int, tuple[bytes, frozenset]] = {}
        self.covered: set[int] = set()  # union of all clones' coverage
        self.born_after: int = 0  # newest snap id predating creation

    def at_snap(self, snap: int) -> bytes | None:
        """State visible at snapshot `snap`: the clone whose coverage set
        contains it; else the head, IF the object's current incarnation
        existed when the snap was taken (snap newer than born_after);
        else ENOENT."""
        for cid in sorted(self.clones):
            data, cov = self.clones[cid]
            if snap in cov:
                return data
        if self.head is not None and snap > self.born_after:
            return self.head
        return None


class Model:
    """Cluster-side expected state + snap bookkeeping."""

    def __init__(self):
        self.objects: dict[str, ObjModel] = {}
        self.snaps: list[int] = []  # live snap ids, ascending
        self.snap_seq = 0

    def obj(self, oid: str) -> ObjModel:
        return self.objects.setdefault(oid, ObjModel())

    def note_snap(self, snap_id: int) -> None:
        self.snaps.append(snap_id)
        self.snap_seq = snap_id

    def pre_write_clone(self, o: ObjModel) -> None:
        """make_writeable: the first mutation after new snaps exist clones
        the current head, covering every live snap the object existed at
        that no earlier clone covers (SnapSet.needs_clone); a new object
        instead records that those snaps must answer ENOENT (born)."""
        if not self.snaps:
            return
        newest = self.snaps[-1]
        if o.head is None:
            o.born_after = max(o.born_after, newest)
            return
        need = {
            c for c in self.snaps
            if c > o.born_after and c not in o.covered
        }
        if need:
            o.clones[newest] = (o.head, frozenset(need))
            o.covered |= need


def _snapc(model: Model):
    return (model.snap_seq, sorted(model.snaps, reverse=True))


async def _apply_random_op(rng, io, client, model: Model, oids, pool):
    op = rng.choices(
        ["write", "write_full", "append", "truncate", "remove",
         "snap_create", "rollback", "copy_from", "setxattr"],
        weights=[20, 15, 10, 5, 5, 8, 5, 8, 8],
    )[0]
    oid = rng.choice(oids)
    o = model.obj(oid)
    data = bytes([rng.randrange(256)]) * rng.randrange(1, 2048)
    snapc = _snapc(model)
    if op == "write":
        off = rng.randrange(0, 4096)
        model.pre_write_clone(o)
        await io.write(oid, data, off=off, snapc=snapc)
        head = o.head or b""
        if len(head) < off:
            head = head + b"\x00" * (off - len(head))
        o.head = head[:off] + data + head[off + len(data):]
    elif op == "write_full":
        model.pre_write_clone(o)
        await io.write_full(oid, data, snapc=snapc)
        o.head = data
    elif op == "append":
        model.pre_write_clone(o)
        await io.append(oid, data, snapc=snapc)
        o.head = (o.head or b"") + data
    elif op == "truncate":
        if o.head is None:
            return  # creation-via-truncate semantics differ; not modeled
        ln = rng.randrange(0, 2048)
        model.pre_write_clone(o)
        await io.truncate(oid, ln, snapc=snapc)
        head = o.head
        o.head = head[:ln] + b"\x00" * max(0, ln - len(head))
    elif op == "remove":
        if o.head is None:
            return
        model.pre_write_clone(o)
        await io.remove(oid, snapc=snapc)
        o.head = None
        o.xattrs.clear()
    elif op == "snap_create":
        snap_id = await client.selfmanaged_snap_create(pool)
        model.note_snap(snap_id)
    elif op == "rollback":
        if not model.snaps or o.head is None:
            return
        snap = rng.choice(model.snaps)
        want = o.at_snap(snap)
        if want is None:
            return  # object absent at that snap; OSD answers ENOENT
        model.pre_write_clone(o)
        await io.rollback(oid, snap, snapc=snapc)
        o.head = want
    elif op == "copy_from":
        src = rng.choice(oids)
        s = model.obj(src)
        if s.head is None or src == oid:
            return
        model.pre_write_clone(o)
        await io.copy_from(oid, src, snapc=snapc)
        o.head = s.head
        # the copy replaces the destination wholesale: client xattrs
        # come from the source (do_copy_get carries the attr map)
        o.xattrs = dict(s.xattrs)
    elif op == "setxattr":
        if o.head is None:
            return  # xattr on missing object would create it
        # SETXATTR is a write-class op: it triggers clone-on-write too.
        # The client xattr path sends no snap context, but the model must
        # mirror whatever the wire carries; IoCtx.setxattr sends the
        # handle's ambient snapc (none here), so no clone either side.
        name = f"k{rng.randrange(4)}"
        await io.setxattr(oid, name, data[:32])
        o.xattrs[name] = data[:32]


async def _verify(io, model: Model, oids, *, snaps=True):
    for oid in oids:
        o = model.objects.get(oid)
        head = o.head if o else None
        if head is None:
            with pytest.raises(RadosError):
                await io.read(oid)
        else:
            got = await io.read(oid)
            assert got == head, f"{oid}: head mismatch ({len(got)} vs {len(head)})"
            for name, val in (o.xattrs if o else {}).items():
                assert await io.getxattr(oid, name) == val
        if not snaps or o is None:
            continue
        for snap in model.snaps:
            want = o.at_snap(snap)
            if want is None:
                with pytest.raises(RadosError):
                    await io.read(oid, snap=snap)
            else:
                got = await io.read(oid, snap=snap)
                assert got == want, (
                    f"{oid}@{snap}: {len(got)} bytes vs model {len(want)}"
                )


def _run_model(pool_kind: str, seed: int, n_ops: int = 120):
    async def run():
        monmap, mons, osds = await start_cluster(1, 4)
        client = Rados(monmap)
        await client.connect()
        pool = "modelp"
        if pool_kind == "erasure":
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "model21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create(
                pool, "erasure", profile="model21", pg_num=4,
                allow_ec_overwrites=True,  # partial overwrites via RMW
            )
        else:
            await client.pool_create(pool, "replicated", pg_num=4)
        io = await client.open_ioctx(pool)
        rng = random.Random(seed)
        model = Model()
        oids = [f"m{i}" for i in range(6)]
        for step in range(n_ops):
            await _apply_random_op(rng, io, client, model, oids, pool)
            if step % 20 == 19:
                await _verify(io, model, oids)
        await _verify(io, model, oids)
        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())


class TestRadosModel:
    @pytest.mark.parametrize("seed", [1, 7])
    def test_replicated(self, seed):
        _run_model("replicated", seed)

    @pytest.mark.parametrize("seed", [3])
    def test_erasure(self, seed):
        _run_model("erasure", seed)


class TestThrashModel:
    """RadosModel + thrasher (qa/tasks/ceph_manager.py thrashers over the
    rados task): random ops continue while an OSD is killed mid-sequence
    and revived later; model verification runs degraded AND after
    recovery converges."""

    def test_replicated_with_osd_thrash(self):
        async def run():
            from test_cluster import fast_conf, wait_until
            from ceph_tpu.osd.osd import OSD

            monmap, mons, osds = await start_cluster(1, 4)
            client = Rados(monmap)
            await client.connect()
            pool = "thrashp"
            await client.pool_create(pool, "replicated", pg_num=4)
            io = await client.open_ioctx(pool)
            rng = random.Random(42)
            model = Model()
            oids = [f"t{i}" for i in range(6)]

            for _ in range(30):
                await _apply_random_op(rng, io, client, model, oids, pool)
            await _verify(io, model, oids)

            # thrash: kill osd.3, keep operating degraded
            victim = osds[3]
            store = victim.store
            await victim.stop()
            await wait_until(
                lambda: not mons[0].osdmon.osdmap.is_up(3),
                8.0,
                "mon marking osd.3 down",
            )
            for _ in range(30):
                await _apply_random_op(rng, io, client, model, oids, pool)
            await _verify(io, model, oids)

            # revive on the old store; recovery must converge, then the
            # model must still hold (no lost or resurrected state)
            revived = OSD(3, monmap, conf=fast_conf(3), store=store)
            await revived.start()
            await revived.wait_for_up()
            osds[3] = revived

            await wait_until(
                lambda: all(o.all_clean() for o in osds if o._running),
                15.0,
                "recovery to clean",
            )
            for _ in range(20):
                await _apply_random_op(rng, io, client, model, oids, pool)
            await _verify(io, model, oids)
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())
