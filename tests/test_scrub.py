"""Scrub tests over a live cluster (src/osd/scrubber mirror).

Models qa's scrub/repair behaviors: a clean deep scrub reports zero
errors; silent shard corruption (flipped bytes on one OSD's store — the
reference's EIO/corruption injection in test-erasure-eio.sh) is caught
by the hinfo digest check; repair marks the shard missing and recovery
rebuilds it byte-identically.
"""

import asyncio

from ceph_tpu.client import Rados
from ceph_tpu.osd.pg_backend import shard_coll
from ceph_tpu.os.transaction import Transaction

from test_cluster import start_cluster, stop_cluster, wait_until


async def make_ec_cluster(objects: dict[str, bytes]):
    monmap, mons, osds = await start_cluster(1, 4)
    client = Rados(monmap)
    await client.connect()
    rv, rs, _ = await client.mon_command(
        {
            "prefix": "osd erasure-code-profile set",
            "name": "sk2m1",
            "profile": ["k=2", "m=1", "plugin=tpu"],
        }
    )
    assert rv == 0, rs
    await client.pool_create("spool", "erasure", profile="sk2m1", pg_num=1)
    ioctx = await client.open_ioctx("spool")
    for oid, data in objects.items():
        await ioctx.write_full(oid, data)
    return monmap, mons, osds, client, ioctx


def find_primary_pg(osds, pool_name="spool"):
    for o in osds:
        for pg in o.pgs.values():
            if pg.pool.name == pool_name and pg.peering.is_primary():
                return o, pg
    raise AssertionError("no primary pg")


async def run_scrub(pg, deep=False, repair=False, timeout=5.0):
    done = asyncio.get_event_loop().create_future()
    assert pg.scrub(deep=deep, repair=repair, on_done=lambda r: done.set_result(r))
    return await asyncio.wait_for(done, timeout)


class TestScrub:
    def test_clean_deep_scrub(self):
        async def run():
            objs = {f"s{i}": bytes([i + 1]) * (4096 * (i + 1)) for i in range(5)}
            monmap, mons, osds, client, ioctx = await make_ec_cluster(objs)
            osd, pg = find_primary_pg(osds)
            res = await run_scrub(pg, deep=True)
            assert res.clean, res.inconsistent
            assert res.objects_scrubbed == len(objs)
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_detects_and_repairs_corruption(self):
        async def run():
            payload = bytes(range(256)) * 64  # 16 KiB = 2 stripes
            monmap, mons, osds, client, ioctx = await make_ec_cluster(
                {"victim": payload}
            )
            osd, pg = find_primary_pg(osds)
            # Corrupt a non-primary shard's chunk bytes directly on disk
            # (the scrub must catch what the write path never sees).
            acting = pg.acting()
            bad_shard = 1
            bad_osd = next(o for o in osds if o.whoami == acting[bad_shard])
            coll = shard_coll(pg.pgid, bad_shard)
            good = bad_osd.store.read(coll, "victim", 0, 0)
            corrupted = bytes([good[0] ^ 0xFF]) + good[1:]
            bad_osd.store.queue_transaction(
                Transaction().write(coll, "victim", 0, corrupted)
            )

            res = await run_scrub(pg, deep=True)
            assert not res.clean
            assert "victim" in res.inconsistent
            assert acting[bad_shard] in res.inconsistent["victim"]

            # Shallow scrub does NOT read data: corruption stays hidden
            res_shallow = await run_scrub(pg, deep=False)
            assert res_shallow.clean

            # Repair: mark missing + recover, then the shard is clean again
            res2 = await run_scrub(pg, deep=True, repair=True)
            assert res2.repaired == 1
            await wait_until(lambda: pg.is_clean, 5.0, "repair recovery")
            assert bad_osd.store.read(coll, "victim", 0, 0) == good
            res3 = await run_scrub(pg, deep=True)
            assert res3.clean, res3.inconsistent
            # and the object still reads back correctly
            assert await ioctx.read("victim") == payload
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_chunked_scrub_covers_many_objects(self):
        async def run():
            objs = {f"m{i:03d}": bytes([i % 256]) * 4096 for i in range(60)}
            monmap, mons, osds, client, ioctx = await make_ec_cluster(objs)
            osd, pg = find_primary_pg(osds)
            res = await run_scrub(pg, deep=True, timeout=15.0)
            assert res.objects_scrubbed == 60  # > CHUNK_MAX forces chunking
            assert res.clean
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestScrubRobustness:
    def test_scrub_aborts_when_shard_dies(self):
        """A crashed replica mid-gather aborts the scrub via the tick
        timeout instead of wedging the scrubber forever."""

        async def run():
            objs = {"a": b"A" * 8192}
            monmap, mons, osds, client, ioctx = await make_ec_cluster(objs)
            osd, pg = find_primary_pg(osds)
            pg.scrubber.gather_timeout = 0.3
            # Kill a replica, then scrub before the mon notices it's down.
            victim = next(o for o in osds if o.whoami != osd.whoami
                          and o.whoami in pg.acting())
            await victim.stop()
            done = asyncio.get_event_loop().create_future()
            assert pg.scrub(deep=True, on_done=lambda r: done.set_result(r))
            res = await asyncio.wait_for(done, 10.0)
            assert res.aborted and not res.clean
            assert not pg.scrubber.active  # can scrub again later
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_replicated_repair_pulls_good_copy(self):
        """A corrupt PRIMARY copy in a size-3 replicated pool is repaired
        from a replica, not re-pushed (majority picks the good copy)."""
        from test_cluster import start_cluster

        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("rp3", "replicated", size=3, pg_num=1)
            ioctx = await client.open_ioctx("rp3")
            payload = b"good-bytes" * 100
            await ioctx.write_full("robj", payload)

            await wait_until(
                lambda: sum(
                    1 for o in osds
                    for coll in o.store.list_collections()
                    if o.store.exists(coll, "robj")
                ) == 3,
                3.0,
                "3 replicas",
            )
            osd, pg = find_primary_pg(osds, "rp3")
            coll = shard_coll(pg.pgid, -1)
            # corrupt the PRIMARY's copy
            bad = b"EVIL" + payload[4:]
            osd.store.queue_transaction(Transaction().write(coll, "robj", 0, bad))

            res = await run_scrub(pg, deep=True, repair=True)
            assert not res.clean
            assert osd.whoami in res.inconsistent["robj"]
            await wait_until(lambda: pg.is_clean, 5.0, "repair recovery")
            assert osd.store.read(coll, "robj", 0, 0) == payload
            assert await ioctx.read("robj") == payload
            res2 = await run_scrub(pg, deep=True)
            assert res2.clean
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_deep_scrub_detects_and_repairs_omap_divergence(self):
        """Deep scrub covers omap (be_deep_scrub omap_digest): a replica
        whose omap silently diverges is flagged and repair restores it
        (recovery pushes carry omap since round 5)."""
        from test_cluster import start_cluster

        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("rpo", "replicated", size=3, pg_num=1)
            ioctx = await client.open_ioctx("rpo")
            await ioctx.write_full("oobj", b"bytes")
            good = {"k1": b"v1", "k2": b"v2"}
            await ioctx.omap_set("oobj", good)
            await wait_until(
                lambda: sum(
                    1 for o in osds
                    for coll in o.store.list_collections()
                    if o.store.exists(coll, "oobj")
                ) == 3,
                3.0,
                "3 replicas",
            )
            osd, pg = find_primary_pg(osds, "rpo")
            coll = shard_coll(pg.pgid, -1)
            # a NON-primary replica's omap diverges (majority must win)
            victim = next(o for o in osds if o is not osd and any(
                o.store.exists(c, "oobj") for c in o.store.list_collections()
            ))
            victim.store.queue_transaction(
                Transaction().omap_setkeys(coll, "oobj", {"k1": b"EVIL"})
            )
            # shallow scrub cannot see it; deep flags exactly the victim
            res_shallow = await run_scrub(pg, deep=False)
            assert res_shallow.clean
            res = await run_scrub(pg, deep=True)
            assert not res.clean
            assert list(res.inconsistent["oobj"]) == [victim.whoami]
            assert "omap" in res.inconsistent["oobj"][victim.whoami]
            res2 = await run_scrub(pg, deep=True, repair=True)
            assert res2.repaired == 1
            await wait_until(lambda: pg.is_clean, 5.0, "repair recovery")
            assert victim.store.omap_get(coll, "oobj") == good
            res3 = await run_scrub(pg, deep=True)
            assert res3.clean
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestScrubOffloadAndHealth:
    """ISSUE 9: TPU-offloaded deep-scrub verify + the scrub-errors →
    health pipeline (OSD status blob → mgr digest → mon HEALTH_ERR)."""

    def test_deep_scrub_verifies_on_device_in_aggregated_launches(self):
        """A multi-object deep scrub routes parity verification through
        the VerifyAggregator: VERIFY_LAUNCHES advances by ~one launch
        per chunk, covering every object (the acceptance criterion's
        one-launch-many-objects witness)."""
        from ceph_tpu.ops import dispatch as ec_dispatch

        async def run():
            objs = {f"v{i}": bytes([i + 1]) * 8192 for i in range(8)}
            monmap, mons, osds, client, ioctx = await make_ec_cluster(objs)
            osd, pg = find_primary_pg(osds)
            v0 = ec_dispatch.VERIFY_LAUNCHES.snapshot()
            res = await run_scrub(pg, deep=True, timeout=15.0)
            assert res.clean and res.objects_scrubbed == 8
            after = ec_dispatch.VERIFY_LAUNCHES.snapshot()
            launches = after["launches"] - v0["launches"]
            stripes = after["stripes"] - v0["stripes"]
            assert launches >= 1, "deep scrub never reached the verify kernel"
            assert launches < 8, (
                f"verify did not aggregate: {launches} launches for 8 objects"
            )
            # every object's stripes rode the launches (one stripe each
            # at 8 KiB / k=2 / 4 KiB chunks, plus padding)
            assert stripes >= 8, (launches, stripes)
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_parity_verify_catches_hinfo_consistent_corruption(self):
        """The case the offload exists for: a shard whose hinfo was
        rewritten consistently with its corrupt bytes passes the
        digest-vs-hinfo check, but the parity equation still breaks —
        only the device recompute can see it."""
        from ceph_tpu.osd.ec_transaction import HINFO_ATTR
        from ceph_tpu.stripe import HashInfo
        from ceph_tpu.utils.crc32c import crc32c

        async def run():
            payload = bytes(range(256)) * 32  # 8 KiB
            monmap, mons, osds, client, ioctx = await make_ec_cluster(
                {"sneaky": payload}
            )
            osd, pg = find_primary_pg(osds)
            acting = pg.acting()
            bad_shard = 1
            bad_osd = next(o for o in osds if o.whoami == acting[bad_shard])
            coll = shard_coll(pg.pgid, bad_shard)
            good = bad_osd.store.read(coll, "sneaky", 0, 0)
            corrupted = bytes([good[0] ^ 0xFF]) + good[1:]
            # forge the hinfo so the digest check passes on the corrupt
            # bytes — a digest-only scrub is blind to this
            hinfo = HashInfo.decode(
                bad_osd.store.getattr(coll, "sneaky", HINFO_ATTR)
            )
            hinfo.cumulative_shard_hashes[bad_shard] = crc32c(
                corrupted, HashInfo.SEED
            )
            bad_osd.store.queue_transaction(
                Transaction()
                .write(coll, "sneaky", 0, corrupted)
                .setattr(coll, "sneaky", HINFO_ATTR, hinfo.encode())
            )
            res = await run_scrub(pg, deep=True)
            assert not res.clean, "hinfo-consistent corruption slipped through"
            assert "sneaky" in res.inconsistent, res.inconsistent
            reasons = " ".join(res.inconsistent["sneaky"].values())
            assert "parity recompute mismatch" in reasons, reasons
            assert "sneaky" in res.unrepairable
            # auto-repair must REFUSE an unlocalized mismatch: rebuilding
            # parity from the (corrupt) data shard would cement the
            # damage and silently clear the health check.  The corrupt
            # bytes stay on disk and the object stays inconsistent.
            res2 = await run_scrub(pg, deep=True, repair=True)
            assert res2.repaired == 0, res2
            assert bad_osd.store.read(coll, "sneaky", 0, 0) == corrupted
            res3 = await run_scrub(pg, deep=True)
            assert not res3.clean, "refused repair must keep flagging"
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_scrub_errors_reach_mon_health_and_clear_on_repair(self):
        """Satellite: ScrubResult errors flow OSD status blob → mgr
        digest → mon OSD_SCRUB_ERRORS / PG_DAMAGED HEALTH_ERR with
        per-PG detail — and clear after repair + recovery + a clean
        re-scrub."""
        from ceph_tpu.common.health import overall_status
        from ceph_tpu.mgr import Mgr

        async def run():
            payload = bytes(range(256)) * 64
            monmap, mons, osds, client, ioctx = await make_ec_cluster(
                {"victim": payload}
            )
            mgr = Mgr("x", monmap)
            mgr.beacon_interval = 0.1
            await mgr.start()
            await mgr.wait_for_active()
            osd, pg = find_primary_pg(osds)
            acting = pg.acting()
            bad_shard = 1
            bad_osd = next(o for o in osds if o.whoami == acting[bad_shard])
            coll = shard_coll(pg.pgid, bad_shard)
            good = bad_osd.store.read(coll, "victim", 0, 0)
            bad_osd.store.queue_transaction(
                Transaction().write(
                    coll, "victim", 0, bytes([good[0] ^ 0xFF]) + good[1:]
                )
            )
            res = await run_scrub(pg, deep=True)
            assert not res.clean

            def damage_raised():
                checks, details = mons[0].health_checks()
                return (
                    "OSD_SCRUB_ERRORS" in checks
                    and "PG_DAMAGED" in checks
                    and any("victim" in line
                            for line in details.get("PG_DAMAGED", []))
                )

            await wait_until(damage_raised, 10.0,
                             "scrub errors to reach mon health")
            checks, _ = mons[0].health_checks()
            assert overall_status(checks) == "HEALTH_ERR"
            assert "scrub errors" in checks["OSD_SCRUB_ERRORS"]
            assert "inconsistent" in checks["PG_DAMAGED"]
            # the mgr-side checks agree (prometheus healthcheck gauge)
            assert (
                mgr.health_checks()
                .get("OSD_SCRUB_ERRORS", {})
                .get("severity")
                == "HEALTH_ERR"
            )

            # repair: recovery rebuilds the shard; the repaired result
            # suppresses the check, and the confirming clean scrub (plus
            # a fresh mgr report cycle) keeps it clear
            res2 = await run_scrub(pg, deep=True, repair=True)
            assert res2.repaired == 1
            await wait_until(lambda: pg.is_clean, 5.0, "repair recovery")
            res3 = await run_scrub(pg, deep=True)
            assert res3.clean

            def damage_cleared():
                checks, _ = mons[0].health_checks()
                return (
                    "OSD_SCRUB_ERRORS" not in checks
                    and "PG_DAMAGED" not in checks
                )

            await wait_until(damage_cleared, 10.0,
                             "health to clear after repair")
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_authority_pick_is_deterministic(self):
        """Satellite: modal-metadata ties break by highest version, then
        lowest shard — never by set iteration order."""
        from ceph_tpu.osd.scrubber import PgScrubber

        class _Pool:
            type = 0

        class _PG:
            pool = _Pool()
            pgid = "t"

            class peering:
                @staticmethod
                def osds_missing(oid):
                    return set()

        scrubber = PgScrubber(_PG())
        scrubber._deep = False
        # two-way tie at count 1: (size 10, v 3) on shard 0 vs
        # (size 10, v 7) on shard 1 — highest version must win, so the
        # shard 0 copy is the odd one out, on EVERY run
        for _ in range(8):
            scrubber._maps = {
                100: {"o": {"size": 10, "oi_size": 10, "version": 3}},
                101: {"o": {"size": 10, "oi_size": 10, "version": 7}},
                102: {"o": {"size": 10, "oi_size": 10, "version": 7}},
            }
            bad = scrubber._compare_ec_object("o", [100, 101, 102])
            assert list(bad) == [100], bad
            # exact tie in count AND version: lowest shard is authority
            scrubber._maps = {
                100: {"o": {"size": 10, "oi_size": 10, "version": 5}},
                101: {"o": {"size": 12, "oi_size": 12, "version": 5}},
            }
            bad = scrubber._compare_ec_object("o", [100, 101])
            assert list(bad) == [101], bad
