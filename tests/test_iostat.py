"""Workload attribution at scale (ISSUE 10): per-pool/per-client IO
accounting, the mgr iostat module (rates / top clients / windowed p99),
SLO burn-rate health, and budgeted trace sampling.

The acceptance test boots an 8-OSD multi-pool cluster under mixed load
and checks the whole spine end to end: per-pool IOPS/bytes/p99 in mon
`status` and on the prometheus scrape whose totals reconcile with the
OSD-side op counters; driving one pool past its latency target raises
``SLO_LATENCY_BREACH`` with that pool in the detail and clears when the
load stops; and with a 1% sample rate under the same load, span
retention stays within the token-bucket budget while every
complaint-age-exceeding op keeps its full trace.
"""

import asyncio
import json
import time
from types import SimpleNamespace

from ceph_tpu.common import tracer as tracer_mod
from ceph_tpu.common.io_accounting import OTHER_CLIENT, IOAccountant
from ceph_tpu.mgr.iostat import IostatModule


class TestIOAccountant:
    def test_per_pool_per_class_accumulation(self):
        acc = IOAccountant()
        for _ in range(10):
            acc.account(1, "client.a", "write", 4096, 0.002)
        for _ in range(5):
            acc.account(1, "client.b", "read", 8192, 0.01)
        acc.account(2, "recovery", "recovery", 65536)
        pools = acc.dump_pools()
        assert pools["1"]["write"]["ops"] == 10
        assert pools["1"]["write"]["bytes"] == 10 * 4096
        assert pools["1"]["read"]["ops"] == 5
        assert pools["2"]["recovery"]["bytes"] == 65536
        # latency histograms are the standard cumulative dump shape
        h = pools["1"]["write"]["lat"]["histogram"]
        assert h["count"] == 10
        assert h["buckets"][-1][0] == "+Inf"
        assert h["buckets"][-1][1] == 10
        clients = acc.dump_clients()
        assert clients["1"]["client.a"]["ops"] == 10
        assert clients["1"]["client.b"]["ops"] == 5
        assert acc.totals() == {
            "ops": 16, "bytes": 10 * 4096 + 5 * 8192 + 65536,
        }

    def test_idle_tracked_client_evicted_for_new_one(self):
        """Client churn must not saturate the tracked slice forever:
        an idle tracked client is folded into _other to admit a new
        one, while an all-active slice never churns."""
        acc = IOAccountant(max_clients_per_pool=2)
        acc.account(1, "client.old", "write", 100, 0.001)
        acc.account(1, "client.hot", "write", 100, 0.001)
        # client.old departs (idle past the eviction bound)
        acc._clients[1]["client.old"].last -= 2 * IOAccountant.IDLE_EVICT_SEC
        acc.account(1, "client.new", "write", 100, 0.001)
        clients = acc.dump_clients()["1"]
        assert "client.new" in clients
        assert "client.old" not in clients
        assert clients[OTHER_CLIENT]["ops"] == 1  # folded, not lost
        assert sum(c["ops"] for c in clients.values()) == 3
        # everyone tracked is active: the next new client overflows
        # into _other instead of displacing a live one
        acc.account(1, "client.newer", "write", 100, 0.001)
        clients = acc.dump_clients()["1"]
        assert "client.newer" not in clients
        assert "client.hot" in clients and "client.new" in clients
        assert sum(c["ops"] for c in clients.values()) == 4

    def test_client_slice_is_bounded(self):
        acc = IOAccountant(max_clients_per_pool=4)
        for i in range(32):
            acc.account(1, f"client.{i}", "write", 100, 0.001)
        clients = acc.dump_clients()["1"]
        assert len(clients) <= 5  # 4 tracked + the overflow bucket
        assert OTHER_CLIENT in clients
        # nothing lost: the fold preserves totals
        assert sum(c["ops"] for c in clients.values()) == 32
        assert acc.totals()["ops"] == 32


class _FakeMgr:
    """The MgrModule surface the iostat module consumes."""

    def __init__(self):
        self.daemons: dict[str, dict] = {}
        self.osdmap = SimpleNamespace(pools={})

    def list_daemons(self):
        return sorted(self.daemons)

    def get_daemon_status(self, daemon):
        return self.daemons[daemon]


def _feed(mod, mgr, acc, daemon="osd.0"):
    mgr.daemons[daemon] = {
        "pool_io": acc.dump_pools(),
        "client_io": acc.dump_clients(),
    }
    mod.tick()


class TestIostatModule:
    def test_rates_p99_and_totals(self):
        mod = IostatModule(window_sec=5.0)
        mgr = _FakeMgr()
        mgr.osdmap.pools = {1: SimpleNamespace(id=1, name="rbd")}
        mod.mgr = mgr
        acc = IOAccountant()
        for _ in range(50):
            acc.account(1, "client.a", "write", 4096, 0.004)
        _feed(mod, mgr, acc)
        time.sleep(0.05)
        for _ in range(50):
            acc.account(1, "client.a", "write", 4096, 0.004)
        _feed(mod, mgr, acc)
        view = mod.iostat()
        rec = view["1"]
        assert rec["pool"] == "rbd"
        assert rec["write_ops"] == 100
        assert rec["write_bytes"] == 100 * 4096
        assert rec["ops_total"] == 100
        assert rec["write_ops_per_sec"] > 0
        assert rec["write_bytes_per_sec"] > 0
        # 4 ms samples land in the (4.096, 8.192] ms log2 bucket
        assert rec["p99_ms"] is not None
        assert 4.0 <= rec["p99_ms"] <= 10.0

    def test_restart_rebases_instead_of_negative_rates(self):
        mod = IostatModule(window_sec=5.0)
        mgr = _FakeMgr()
        mod.mgr = mgr
        acc = IOAccountant()
        for _ in range(100):
            acc.account(1, "client.a", "write", 1000, 0.001)
        _feed(mod, mgr, acc)
        assert mod.iostat()["1"]["write_ops"] == 100
        # the daemon restarts: fresh accountant, counters rebase to 0
        acc2 = IOAccountant()
        for _ in range(3):
            acc2.account(1, "client.a", "write", 1000, 0.001)
        time.sleep(0.02)
        _feed(mod, mgr, acc2)
        rec = mod.iostat()["1"]
        # the regression re-anchored: no double count, no negative delta
        assert rec["write_ops"] == 100
        assert rec["write_ops_per_sec"] >= 0.0
        # post-restart deltas resume from the new baseline
        for _ in range(7):
            acc2.account(1, "client.a", "write", 1000, 0.001)
        time.sleep(0.02)
        _feed(mod, mgr, acc2)
        assert mod.iostat()["1"]["write_ops"] == 107

    def test_first_sight_import_does_not_seed_ema_rates(self):
        """A fresh module (mgr failover) imports each OSD's boot-to-now
        cumulative history as one first-sight delta; the totals want it
        but the EMA rates must NOT — hours of ops divided by one tick
        would report absurd IOPS until the 0.7-EMA decays (the same
        failover hazard the window-delta warm-up anchor fixes for the
        SLO/p99 path)."""
        mod = IostatModule(window_sec=5.0)
        mgr = _FakeMgr()
        mod.mgr = mgr
        acc = IOAccountant()
        # long-running cluster history: 100k ops before the failover
        for _ in range(100_000):
            acc.account(1, "client.a", "write", 1000, 0.001)
        mod.tick()  # dt-anchor tick (a fresh module's first tick)
        time.sleep(0.02)
        _feed(mod, mgr, acc)  # first sight: full cumulative import
        rec = mod.iostat()["1"]
        assert rec["write_ops"] == 100_000  # totals keep the import
        assert rec["write_ops_per_sec"] == 0.0, rec  # rates do not
        # genuine post-import deltas seed the rate normally
        for _ in range(10):
            acc.account(1, "client.a", "write", 1000, 0.001)
        time.sleep(0.02)
        _feed(mod, mgr, acc)
        rec = mod.iostat()["1"]
        assert rec["write_ops"] == 100_010
        # the rate reflects the 10-op delta, not the 100k import
        assert 0.0 < rec["write_ops_per_sec"] < 10_000, rec

    def test_top_clients_ranks_and_bounds(self):
        mod = IostatModule(window_sec=5.0, top_n=2)
        mgr = _FakeMgr()
        mod.mgr = mgr
        acc = IOAccountant()
        _feed(mod, mgr, acc)
        time.sleep(0.05)
        for i, n in (("a", 30), ("b", 10), ("c", 3)):
            for _ in range(n):
                acc.account(1, f"client.{i}", "write", 1000, 0.001)
        _feed(mod, mgr, acc)
        top = mod.top_clients()
        assert len(top) == 2  # bounded by the pinned top_n
        assert top[0]["client"] == "client.a"
        assert top[0]["ops_per_sec"] >= top[1]["ops_per_sec"]
        by_bytes = mod.top_clients(n=3, by="bytes_rate")
        assert [r["client"] for r in by_bytes] == [
            "client.a", "client.b", "client.c",
        ]

    def test_idle_client_expires_and_does_not_resurrect(self):
        """OSDs keep reporting an expired client's (unchanged)
        cumulative record forever; the zero delta must not resurrect
        the series as a permanent zero row that can never expire."""
        mod = IostatModule(window_sec=5.0)
        mod.CLIENT_IDLE_EXPIRE_SEC = 0.05
        mgr = _FakeMgr()
        mod.mgr = mgr
        acc = IOAccountant()
        acc.account(1, "client.gone", "write", 1000, 0.001)
        _feed(mod, mgr, acc)
        assert ("1", "client.gone") in mod.clients
        time.sleep(0.08)
        _feed(mod, mgr, acc)  # idle past the expiry bound
        assert ("1", "client.gone") not in mod.clients
        # ...and STAYS gone while the OSD keeps re-reporting the record
        for _ in range(3):
            _feed(mod, mgr, acc)
            assert ("1", "client.gone") not in mod.clients
        assert mod.top_clients() == []
        # a genuinely returning client re-tracks from its reappearance
        acc.account(1, "client.gone", "write", 1000, 0.001)
        _feed(mod, mgr, acc)
        assert mod.clients[("1", "client.gone")].ops == 1

    def test_prev_anchor_pruned_for_dropped_keys_only(self):
        """The _prev delta anchors must not grow forever under client
        churn: a key a LIVE daemon stopped reporting (evicted OSD-side)
        is pruned after the grace period, while a DOWN daemon's anchors
        survive so a partition heal resumes deltas instead of
        re-importing boot-to-now history as one double-counting
        delta."""
        mod = IostatModule(window_sec=5.0)
        mod.PREV_PRUNE_SEC = 0.05
        mgr = _FakeMgr()
        mod.mgr = mgr
        acc = IOAccountant()
        for _ in range(10):
            acc.account(1, "client.churn", "write", 1000, 0.001)
        _feed(mod, mgr, acc)
        assert ("osd.0", "client", "1", "client.churn") in mod._prev
        # the OSD evicts the client (key leaves the blob) but keeps
        # reporting its pool counters
        dump = {"pool_io": acc.dump_pools(), "client_io": {"1": {}}}
        mgr.daemons["osd.0"] = dump
        time.sleep(0.08)
        mod.tick()
        assert ("osd.0", "client", "1", "client.churn") not in mod._prev
        # ...while the still-reported pool anchor survives
        assert ("osd.0", "pool", "1", "write") in mod._prev
        # now the daemon goes dark: its anchors must NOT age out
        mgr._daemon_report_live = lambda d: False
        time.sleep(0.08)
        mod.tick()
        assert ("osd.0", "pool", "1", "write") in mod._prev
        # the partition heals with 5 more cumulative ops: the preserved
        # anchor yields a delta of 5, not a re-import of all 15
        for _ in range(5):
            acc.account(1, "client.churn", "write", 1000, 0.001)
        mgr._daemon_report_live = lambda d: True
        _feed(mod, mgr, acc)
        assert mod.pools[("1", "write")].ops == 15

    def test_multi_osd_merge_reconciles(self):
        mod = IostatModule(window_sec=5.0)
        mgr = _FakeMgr()
        mod.mgr = mgr
        accs = [IOAccountant() for _ in range(3)]
        for i, acc in enumerate(accs):
            for _ in range(10 * (i + 1)):
                acc.account(1, "client.a", "write", 500, 0.002)
        for i, acc in enumerate(accs):
            mgr.daemons[f"osd.{i}"] = {
                "pool_io": acc.dump_pools(),
                "client_io": acc.dump_clients(),
            }
        mod.tick()
        rec = mod.iostat()["1"]
        assert rec["write_ops"] == 60  # 10 + 20 + 30 across the OSDs
        assert rec["write_bytes"] == 60 * 500
        # the merged histogram count reconciles too
        series = mod.pools[("1", "write")]
        assert series.lat_count == 60


class TestSLOBurnRate:
    def _module(self, target_ms=10.0):
        mod = IostatModule(
            window_sec=2.0,
            slo_target_ms=target_ms,
            slo_fast_window_sec=0.4,
            slo_slow_window_sec=0.8,
            slo_burn_threshold=1.0,
        )
        mgr = _FakeMgr()
        mgr.osdmap.pools = {1: SimpleNamespace(id=1, name="rbd")}
        mod.mgr = mgr
        return mod, mgr

    def test_breach_raises_and_clears(self):
        mod, mgr = self._module(target_ms=10.0)
        acc = IOAccountant()
        _feed(mod, mgr, acc)
        # saturate both windows with over-target (50 ms) ops
        for _round in range(3):
            time.sleep(0.05)
            for _ in range(40):
                acc.account(1, "client.a", "write", 1000, 0.05)
            _feed(mod, mgr, acc)
        assert "1" in mod.breaches, mod.breaches
        assert "SLO_LATENCY_BREACH" in mod.health_checks
        detail = mod.breaches["1"]
        assert detail["pool"] == "rbd"
        assert detail["burn_fast"] > 1.0 and detail["burn_slow"] > 1.0
        assert mod.worst_burn_rate("slow") > 1.0
        # load stops: the windows drain and the check clears (the slow
        # window outlives the breach clearing — the check drops as soon
        # as EITHER window recovers)
        deadline = time.monotonic() + 5.0
        while (
            mod.breaches or mod.worst_burn_rate("slow") > 0.0
        ) and time.monotonic() < deadline:
            time.sleep(0.1)
            _feed(mod, mgr, acc)
        assert not mod.breaches
        assert "SLO_LATENCY_BREACH" not in mod.health_checks
        assert mod.worst_burn_rate("slow") == 0.0

    def test_straddling_bucket_does_not_breach(self):
        """A pool fully MEETING its target must not breach: 9 ms ops
        land in the (8.192, 16.384] ms log2 bucket, and counting that
        straddling bucket as bad would snap a 10 ms target down to an
        effective 8.192 ms — every op "slow", burn rate 100x, spurious
        SLO_LATENCY_BREACH.  Only buckets entirely past the target
        count."""
        mod, mgr = self._module(target_ms=10.0)
        acc = IOAccountant()
        _feed(mod, mgr, acc)
        for _round in range(3):
            time.sleep(0.05)
            for _ in range(40):
                acc.account(1, "client.a", "write", 1000, 0.009)
            _feed(mod, mgr, acc)
        assert not mod.breaches, mod.breaches
        assert mod.worst_burn_rate("fast") == 0.0
        # 17 ms ops sit in (16.384, 32.768] — entirely past 10 ms: bad
        for _round in range(3):
            time.sleep(0.05)
            for _ in range(40):
                acc.account(1, "client.a", "write", 1000, 0.017)
            _feed(mod, mgr, acc)
        assert "1" in mod.breaches, mod.breaches

    def test_mgr_restart_does_not_burn_imported_history(self):
        """A fresh module (mgr failover) imports each OSD's entire
        boot-to-now cumulative history as one first-sight delta; the
        burn-rate windows must anchor past it, not treat hours of old
        incident as if it happened inside a seconds-wide window."""
        mod, mgr = self._module(target_ms=10.0)
        acc = IOAccountant()
        # an old incident: 500 ops way over target, long before failover
        for _ in range(500):
            acc.account(1, "client.a", "write", 1000, 0.5)
        _feed(mod, mgr, acc)  # first sight: full cumulative import
        assert not mod.breaches, mod.breaches
        # healthy traffic keeps it clear right through warm-up
        for _ in range(3):
            time.sleep(0.05)
            for _ in range(20):
                acc.account(1, "client.a", "write", 1000, 0.001)
            _feed(mod, mgr, acc)
            assert not mod.breaches, mod.breaches
        # ...while the cumulative totals still reconcile with the OSD
        assert mod.pools[("1", "write")].ops == 560

    def test_top_clients_p99_overflow_ranks_slowest_first(self):
        """A client whose p99 lands in the +Inf overflow bucket is the
        SLOWEST client — `iostat top by=p99` must rank it first, not
        sort its None p99 as 0.0 and bury it."""
        mod = IostatModule(window_sec=5.0)
        mgr = _FakeMgr()
        mod.mgr = mgr
        acc = IOAccountant()
        # birth feed: the windowed p99 cannot see a series' first-sight
        # import (the blind spot), so the measured ops come after it
        for c in ("client.slow", "client.ok"):
            acc.account(1, c, "write", 100, 0.005)
        _feed(mod, mgr, acc)
        time.sleep(0.02)
        for _ in range(5):
            acc.account(1, "client.slow", "write", 100, 20.0)  # > 8.4 s
        for _ in range(5):
            acc.account(1, "client.ok", "write", 100, 0.005)
        _feed(mod, mgr, acc)
        top = mod.top_clients(n=2, by="p99")
        assert [r["client"] for r in top] == ["client.slow", "client.ok"]
        assert top[0]["p99_ms"] is None  # overflow renders unbounded

    def test_top_clients_p99_is_windowed_not_lifetime(self):
        """`iostat top by=p99` answers "who is slow NOW": a startup blip
        (or a failover's boot-to-now import) must not keep a busy,
        now-fast client ranked slowest forever — the ranking uses the
        same windowed delta as the pool p99, not the lifetime cumulative
        histogram."""
        mod = IostatModule(window_sec=0.2)
        mgr = _FakeMgr()
        mod.mgr = mgr
        acc = IOAccountant()
        for c in ("client.a", "client.b"):  # birth feed (blind spot)
            acc.account(1, c, "write", 100, 0.001)
        _feed(mod, mgr, acc)
        time.sleep(0.02)
        # old incident: client.a very slow, client.b mildly slow
        for _ in range(50):
            acc.account(1, "client.a", "write", 100, 2.0)
        for _ in range(50):
            acc.account(1, "client.b", "write", 100, 0.1)
        _feed(mod, mgr, acc)
        assert [r["client"] for r in mod.top_clients(n=2, by="p99")][0] \
            == "client.a"
        # the incident ages out of the window; NOW client.b is slower
        time.sleep(0.3)
        for _ in range(20):
            acc.account(1, "client.a", "write", 100, 0.001)
        for _ in range(20):
            acc.account(1, "client.b", "write", 100, 0.1)
        _feed(mod, mgr, acc)
        top = mod.top_clients(n=2, by="p99")
        assert [r["client"] for r in top] == ["client.b", "client.a"], top
        # and the rendered p99 reflects the window, not the 2 s history
        assert top[1]["p99_ms"] is not None and top[1]["p99_ms"] < 100

    def test_under_target_load_never_breaches(self):
        mod, mgr = self._module(target_ms=1000.0)
        acc = IOAccountant()
        _feed(mod, mgr, acc)
        for _round in range(3):
            time.sleep(0.05)
            for _ in range(40):
                acc.account(1, "client.a", "write", 1000, 0.002)
            _feed(mod, mgr, acc)
        assert not mod.breaches
        assert mod.worst_burn_rate("slow") == 0.0

    def test_per_pool_override_wins(self):
        mod, mgr = self._module(target_ms=1000.0)
        mod._pins["mgr_slo_pool_latency_targets"] = "rbd:5"
        mod._conf["mgr_slo_pool_latency_targets"] = "rbd:5"
        # name-matched override: 5 ms for pool "rbd" (id 1)
        assert abs(mod.slo_target_sec("1") - 0.005) < 1e-9
        # id-matched syntax works too
        mod._conf["mgr_slo_pool_latency_targets"] = "1:7"
        assert abs(mod.slo_target_sec("1") - 0.007) < 1e-9
        # unlisted pools use the default
        assert abs(mod.slo_target_sec("9") - 1.0) < 1e-9


class TestTraceSampling:
    def test_head_rate_zero_drops_everything(self):
        t = tracer_mod.Tracer("x", enabled=True, sample_rate=0.0)
        root = t.start_span("client:op")
        child = root.child("osd:op")
        child.finish()
        root.finish()
        assert t.export() == []
        stats = t.sampling_stats()
        assert stats["unsampled"] == 1
        assert stats["dropped_tail"] == 1
        assert stats["pending_traces"] == 0

    def test_tail_keep_retains_full_trace(self):
        t = tracer_mod.Tracer("x", enabled=True, sample_rate=0.0)
        root = t.start_span("client:op")
        child = root.child("osd:op")
        child.event("reached_pg")
        t.mark_keep(child)  # complaint-age / error verdict
        child.finish()
        root.finish()
        names = sorted(s["name"] for s in t.export())
        assert names == ["client:op", "osd:op"]
        # the rescued spans kept their collected events
        osd = next(s for s in t.export() if s["name"] == "osd:op")
        assert [e["name"] for e in osd["events"]] == ["reached_pg"]
        assert t.sampling_stats()["kept_tail"] == 1

    def test_token_bucket_budget_bounds_retention(self):
        t = tracer_mod.Tracer(
            "x", enabled=True, sample_rate=1.0, budget_per_sec=3.0
        )
        for _ in range(20):
            t.start_span("r").finish()
        stats = t.sampling_stats()
        # burst = one second's refill: exactly 3 head-sampled through
        assert stats["sampled"] == 3, stats
        assert stats["dropped_budget"] == 17, stats
        assert len(t.export()) == 3

    def test_budget_rejected_still_tail_keepable(self):
        t = tracer_mod.Tracer(
            "x", enabled=True, sample_rate=1.0, budget_per_sec=1.0
        )
        t.start_span("a").finish()  # consumes the only token
        slow = t.start_span("slow-op")
        assert slow.provisional
        t.mark_keep(slow)
        slow.finish()
        assert {s["name"] for s in t.export()} == {"a", "slow-op"}

    def test_enabling_budget_at_runtime_starts_with_full_burst(self):
        """Raising op_trace_budget_per_sec from 0 (disabled) must start
        the token bucket at the documented one-second burst — not empty,
        which would count the first traces dropped_budget."""
        t = tracer_mod.Tracer(
            "x", enabled=True, sample_rate=1.0, budget_per_sec=0.0
        )
        t.configure_sampling(budget_per_sec=2.0)
        for _ in range(3):
            t.start_span("r").finish()
        stats = t.sampling_stats()
        assert stats["sampled"] == 2, stats
        assert stats["dropped_budget"] == 1, stats
        # lowering still clamps the bucket to the new capacity
        t.configure_sampling(budget_per_sec=0.5)
        assert t._tokens <= t._budget_cap()

    def test_fractional_budget_still_admits_traces(self):
        """0 < op_trace_budget_per_sec < 1 means "one trace every
        1/budget seconds", not "none": the bucket capacity must hold at
        least one whole token or a fractional budget silently drops
        every head-sampled trace forever."""
        t = tracer_mod.Tracer(
            "x", enabled=True, sample_rate=1.0, budget_per_sec=0.5
        )
        t.start_span("first").finish()
        stats = t.sampling_stats()
        assert stats["sampled"] == 1, stats
        assert stats["dropped_budget"] == 0, stats
        # the next trace waits for refill (~2s away), it is not admitted
        # immediately — the budget still bounds the rate
        t.start_span("second").finish()
        stats = t.sampling_stats()
        assert stats["sampled"] == 1, stats
        assert stats["dropped_budget"] == 1, stats
        # ...and a runtime enable of a fractional budget bursts to one
        # whole token, not a forever-starved fraction
        t2 = tracer_mod.Tracer(
            "y", enabled=True, sample_rate=1.0, budget_per_sec=0.0
        )
        t2.configure_sampling(budget_per_sec=0.25)
        t2.start_span("r").finish()
        assert t2.sampling_stats()["sampled"] == 1

    def test_envelope_carries_one_decision(self):
        class Msg:
            pass

        cli = tracer_mod.Tracer("client", enabled=True, sample_rate=0.0)
        root = cli.start_span("client:op")
        msg = Msg()
        tracer_mod.inject(root, msg)
        assert msg.trace_sampled == tracer_mod.SAMPLED_DROP
        # the receiving daemon samples at 100% locally, but honors the
        # envelope: no re-rolling the decision downstream
        osd = tracer_mod.Tracer("osd", enabled=True)
        ctx = tracer_mod.extract(msg)
        assert ctx.sampled == tracer_mod.SAMPLED_DROP
        span = osd.start_span("osd:op", remote=ctx)
        assert span.provisional
        span.finish()
        assert osd.export() == []
        # a KEEP decision (from a sampling-ACTIVE sender that head-kept
        # the trace) flows through untouched
        cli2 = tracer_mod.Tracer(
            "client", enabled=True, budget_per_sec=100.0
        )
        msg2 = Msg()
        tracer_mod.inject(cli2.start_span("client:op"), msg2)
        assert msg2.trace_sampled == tracer_mod.SAMPLED_KEEP
        span2 = osd.start_span("osd:op", remote=tracer_mod.extract(msg2))
        assert not span2.provisional
        assert len(osd.export()) == 1

    def test_unconfigured_client_defers_decision_to_osd(self):
        """A tracing client WITHOUT the sampling knobs must not stamp
        KEEP — that would silently bypass the OSD's head sampling and
        span budget.  It stamps NONE; the first sampling-configured
        daemon downstream makes the head decision."""

        class Msg:
            pass

        cli = tracer_mod.Tracer("client", enabled=True)  # no knobs
        msg = Msg()
        tracer_mod.inject(cli.start_span("client:op"), msg)
        assert msg.trace_sampled == tracer_mod.SAMPLED_NONE
        # a sampling-configured OSD decides for itself
        osd = tracer_mod.Tracer("osd", enabled=True, sample_rate=0.0)
        span = osd.start_span("osd:op", remote=tracer_mod.extract(msg))
        assert span.provisional
        assert osd.sampling_stats()["unsampled"] == 1
        # an unconfigured receiver keeps — the pre-sampling behavior
        osd2 = tracer_mod.Tracer("osd2", enabled=True)
        span2 = osd2.start_span("osd:op", remote=tracer_mod.extract(msg))
        assert not span2.provisional

    def test_none_envelope_decision_memoized_per_trace(self):
        """The objecter re-injects the SAME context on every resend: a
        NONE-stamped trace must get ONE head decision at the receiver —
        not a fresh roll (and a fresh budget charge) per delivery that
        could split the trace keep/drop."""

        class Msg:
            pass

        cli = tracer_mod.Tracer("client", enabled=True)  # no knobs
        msg = Msg()
        tracer_mod.inject(cli.start_span("client:op"), msg)
        assert msg.trace_sampled == tracer_mod.SAMPLED_NONE
        ctx = tracer_mod.extract(msg)
        # a keeping receiver charges its budget once for the whole trace
        osd = tracer_mod.Tracer(
            "osd", enabled=True, sample_rate=1.0, budget_per_sec=100.0
        )
        spans = [osd.start_span("osd:op", remote=ctx) for _ in range(10)]
        assert not any(s.provisional for s in spans)
        assert osd.sampling_stats()["sampled"] == 1
        # a dropping receiver rejects once, and every delivery agrees
        osd2 = tracer_mod.Tracer("osd2", enabled=True, sample_rate=0.0)
        spans2 = [osd2.start_span("osd:op", remote=ctx) for _ in range(10)]
        assert all(s.provisional for s in spans2)
        assert osd2.sampling_stats()["unsampled"] == 1

    def test_pending_eviction_prefers_nonkeep_and_commits_keep(self):
        """The MAX_PENDING memory bound must not silently drop traces
        mark_keep already rescued: eviction picks the oldest NON-keep
        trace, and when everything pending is keep-flagged the evictee
        is committed to the export ring instead of dropped."""
        t = tracer_mod.Tracer("x", enabled=True, sample_rate=0.0)
        t.MAX_PENDING = 4
        spans = [t.start_span(f"s{i}") for i in range(4)]
        t.mark_keep(spans[0])  # the oldest is a rescued slow op
        s4 = t.start_span("s4")  # 5th trace forces an eviction
        assert t.sampling_stats()["dropped_tail"] == 1
        assert spans[0].trace_id in t._pending  # keep survived
        assert spans[1].trace_id not in t._pending  # non-keep evicted
        # all-keep: the next eviction commits rather than drops
        for sp in (spans[2], spans[3], s4):
            t.mark_keep(sp)
        t.start_span("s5")
        assert any(s["name"] == "s0" for s in t.export())
        assert t.sampling_stats()["kept_tail"] == 1

    def test_legacy_envelope_defaults_to_keep(self):
        class Msg:
            trace_id = 42
            span_id = 7  # no trace_sampled attribute at all

        ctx = tracer_mod.extract(Msg())
        assert ctx.sampled == tracer_mod.SAMPLED_KEEP

    def test_envelope_field_survives_the_wire(self):
        from ceph_tpu.msg.message import decode_message, encode_message
        from ceph_tpu.msg.messages import MPing

        msg = MPing(stamp=1.0)
        msg.trace_id = 99
        msg.span_id = 5
        msg.trace_sampled = tracer_mod.SAMPLED_DROP
        env, payload = encode_message(msg)
        back = decode_message(env, payload)
        assert back.trace_id == 99
        assert back.trace_sampled == tracer_mod.SAMPLED_DROP

    def test_defaults_behave_like_pre_sampling(self):
        t = tracer_mod.Tracer("x", enabled=True)
        span = t.start_span("a")
        assert not span.provisional
        assert len(t.export()) == 1  # retained at start, as before


class TestSlowOpsUnderSampling:
    def test_one_percent_sampling_still_raises_slow_ops(self):
        """The ISSUE 10 bugfix regression: sampling gates span
        retention, NOT OpTracker registration — a 1% sample rate must
        not silence the PR 1 SLOW_OPS health warning."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.mgr import Mgr

            from test_cluster import start_cluster, stop_cluster, wait_until

            monmap, mons, osds = await start_cluster(1, 1)
            mgr = Mgr("x", monmap)
            mgr.beacon_interval = 0.1
            await mgr.start()
            await mgr.wait_for_active()
            client = Rados(monmap)
            await client.connect()

            osd = osds[0]
            osd.conf.set("jaeger_tracing_enable", True)
            osd.conf.set("op_trace_sample_rate", 0.01)
            osd.op_tracker.complaint_time = 0.05
            token = osd.op_tracker.create(
                "artificially stuck op", pool_id=1,
                client="client.stuck", op_class="write",
            )

            def mon_sees_slow():
                slow = mons[0].pg_digest.get("slow_ops") or {}
                return bool(slow.get("osd.0", {}).get("count"))

            await wait_until(mon_sees_slow, 5.0, "slow op reaching the mon")
            rv, rs, out = await client.mon_command(
                {"prefix": "health", "detail": True}
            )
            assert rv == 0, rs
            payload = json.loads(out)
            assert "SLOW_OPS" in payload["checks"]
            # the stuck op's attribution tags are visible in-flight
            dump = osd.op_tracker.dump_in_flight()
            assert any(
                op["client"] == "client.stuck" and op["op_class"] == "write"
                for op in dump["ops"]
            )
            osd.op_tracker.finish(token)
            await wait_until(
                lambda: not mon_sees_slow(), 5.0, "slow op draining"
            )
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestZeroPayloadWriteAccounting:
    def test_delete_accounts_zero_bytes(self):
        """Zero-payload write-class ops (delete/create/truncate) must
        account their real payload (0 bytes) — not the 4096 QoS cost
        floor, which would add phantom bytes to the pool/client views."""

        async def run():
            from ceph_tpu.client import Rados

            from test_cluster import start_cluster, stop_cluster

            monmap, mons, osds = await start_cluster(1, 1)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("zp", "replicated", size=1, pg_num=1)
            io = await client.open_ioctx("zp")
            await io.write_full("o", b"x" * 1024)
            await io.remove("o")
            pools = {}
            for o in osds:
                for pid, classes in o.io_accountant.dump_pools().items():
                    rec = pools.setdefault(pid, {"ops": 0, "bytes": 0})
                    w = classes.get("write") or {}
                    rec["ops"] += w.get("ops", 0)
                    rec["bytes"] += w.get("bytes", 0)
            (rec,) = pools.values()
            assert rec["ops"] == 2, rec  # write_full + remove
            assert rec["bytes"] == 1024, rec  # the delete added nothing
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestMgrAsokIostat:
    def test_mgr_asok_serves_iostat_and_top(self, tmp_path):
        """The operator path: `ceph tell mgr.x iostat` / `iostat top`
        over the mgr's admin socket, plus the OSD-side raw dump."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.common.admin_socket import admin_command
            from ceph_tpu.common.config import Config
            from ceph_tpu.mgr import Mgr
            from ceph_tpu.mgr.iostat import IostatModule

            from test_cluster import start_cluster, stop_cluster, wait_until

            monmap, mons, osds = await start_cluster(1, 2)
            sock = str(tmp_path / "mgr.x.asok")
            mgr = Mgr(
                "x", monmap,
                conf=Config({"name": "mgr.x", "admin_socket": sock},
                            env=False),
            )
            mgr.beacon_interval = 0.1
            await mgr.start()
            await mgr.wait_for_active()
            iostat = IostatModule(window_sec=3.0)
            mgr.register_module(iostat)

            client = Rados(monmap)
            await client.connect()
            await client.pool_create("asokp", "replicated", size=2, pg_num=2)
            io = await client.open_ioctx("asokp")
            for i in range(8):
                await io.write_full(f"o{i}", b"x" * 2048)
            await wait_until(
                lambda: any(s.ops for s in iostat.pools.values()),
                10.0, "iostat module consuming reports",
            )
            loop = asyncio.get_event_loop()
            view = await loop.run_in_executor(
                None, lambda: admin_command(sock, "iostat")
            )
            pools = {rec["pool"]: rec for rec in view["pools"].values()}
            assert pools["asokp"]["write_ops"] == 8
            top = await loop.run_in_executor(
                None,
                lambda: admin_command(sock, "iostat top", n=3, by="ops_rate"),
            )
            assert top["clients"]
            assert top["clients"][0]["ops"] >= 1
            # the OSD-side raw accountant dump pairs with it
            osd_sock = osds[0].conf.get("admin_socket")
            if osd_sock:
                raw = await loop.run_in_executor(
                    None,
                    lambda: admin_command(osd_sock, "dump_io_accounting"),
                )
                assert "pools" in raw and "totals" in raw
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestWorkloadAttributionAcceptance:
    def test_eight_osd_multi_pool_accounting_slo_and_sampling(self):
        """The ISSUE 10 acceptance run: 8 OSDs, an EC pool + a
        replicated pool under mixed two-client load."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.mgr import Mgr
            from ceph_tpu.mgr.iostat import IostatModule
            from ceph_tpu.mgr.prometheus import PrometheusModule

            from test_cluster import start_cluster, stop_cluster, wait_until
            from test_metrics_lint import lint_exposition

            monmap, mons, osds = await start_cluster(1, 8)
            mgr = Mgr("x", monmap)
            mgr.beacon_interval = 0.1
            await mgr.start()
            await mgr.wait_for_active()
            prom = PrometheusModule()
            mgr.register_module(prom)
            # short pinned windows; SLO targets track the mgr's live
            # config so the test can flip them at runtime
            iostat = IostatModule(
                window_sec=3.0,
                slo_fast_window_sec=0.5,
                slo_slow_window_sec=1.0,
            )
            mgr.register_module(iostat)

            client_a = Rados(monmap, name="client.alpha")
            await client_a.connect()
            client_b = Rados(monmap, name="client.beta")
            await client_b.connect()
            rv, rs, _ = await client_a.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "attr21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client_a.pool_create(
                "attrib_ec", "erasure", profile="attr21", pg_num=4
            )
            await client_b.pool_create(
                "attrib_rep", "replicated", size=2, pg_num=4
            )
            io_ec = await client_a.open_ioctx("attrib_ec")
            io_rep = await client_b.open_ioctx("attrib_rep")

            # mixed load: alpha writes EC, beta writes + reads replicated
            for i in range(24):
                await io_ec.write_full(f"e{i}", b"a" * 8192)
            for i in range(16):
                await io_rep.write_full(f"r{i}", b"b" * 4096)
            for i in range(16):
                assert await io_rep.read(f"r{i}") == b"b" * 4096

            # --- totals reconcile: OSD-side counters == mgr merge -----
            def osd_total_ops():
                return sum(o.io_accountant.totals()["ops"] for o in osds)

            def mgr_total_ops():
                return sum(s.ops for s in iostat.pools.values())

            await wait_until(
                lambda: mgr_total_ops() == osd_total_ops()
                and osd_total_ops() >= 56,
                10.0,
                "iostat merge catching up to the OSD counters",
            )
            view = iostat.iostat()
            pools_by_name = {rec["pool"]: rec for rec in view.values()}
            assert pools_by_name["attrib_ec"]["write_ops"] == 24
            assert pools_by_name["attrib_ec"]["write_bytes"] == 24 * 8192
            assert pools_by_name["attrib_rep"]["write_ops"] == 16
            assert pools_by_name["attrib_rep"]["read_ops"] == 16
            assert pools_by_name["attrib_rep"]["read_bytes"] == 16 * 4096

            # --- mon `status` carries the iostat slice ----------------
            def status_iostat():
                return (
                    mons[0].pg_digest.get("iostat") or {}
                ).get("pools") or {}

            await wait_until(
                lambda: any(
                    rec.get("ops_total", 0) > 0
                    for rec in status_iostat().values()
                ),
                10.0,
                "pool rates reaching mon status",
            )
            rv, _rs, out = await client_a.mon_command({"prefix": "status"})
            assert rv == 0
            status = json.loads(out)
            spools = {
                rec["pool"]: rec
                for rec in status["iostat"]["pools"].values()
            }
            assert spools["attrib_ec"]["write_ops"] == 24
            assert "top_clients" in status["iostat"]
            top = iostat.top_clients(by="bytes_rate")
            top_clients = {r["client"] for r in top}
            assert any(c.startswith("client.alpha") for c in top_clients)
            assert any(c.startswith("client.beta") for c in top_clients)

            # --- scrape reconciles with the same totals ---------------
            families = lint_exposition(prom.scrape())
            pool_ops = families["ceph_tpu_pool_ops"]["samples"]
            assert sum(v for _n, _l, v in pool_ops) == osd_total_ops()
            assert families["ceph_tpu_pool_latency_seconds"]["samples"]

            # --- SLO breach: drive a pool past its target -------------
            mgr.conf.set("mgr_slo_latency_target_ms", 0.0001)
            for _round in range(4):
                for i in range(10):
                    await io_ec.write_full(f"slo{i}", b"c" * 8192)
                await asyncio.sleep(0.15)

            def breach_at_mon():
                checks, details = mons[0].health_checks()
                if "SLO_LATENCY_BREACH" not in checks:
                    return False
                return any(
                    "attrib_ec" in line
                    for line in details["SLO_LATENCY_BREACH"]
                )

            await wait_until(
                breach_at_mon, 15.0, "SLO breach reaching mon health"
            )
            rv, _rs, out = await client_a.mon_command(
                {"prefix": "health", "detail": True}
            )
            payload = json.loads(out)
            assert payload["status"] == "HEALTH_WARN"
            assert "burning their latency SLO" in payload["checks"][
                "SLO_LATENCY_BREACH"
            ]
            # the scrape carries the burn gauges while breached
            text = prom.scrape()
            assert "ceph_tpu_pool_slo_burn_rate{" in text
            # load stops -> the windows drain -> the check clears
            await wait_until(
                lambda: "SLO_LATENCY_BREACH"
                not in mons[0].health_checks()[0],
                15.0,
                "SLO breach clearing after load stops",
            )
            mgr.conf.set("mgr_slo_latency_target_ms", 0.0)

            # --- budgeted sampling under the same load ----------------
            budget = 5.0
            for o in osds:
                o.conf.set("jaeger_tracing_enable", True)
                o.conf.set("op_trace_sample_rate", 0.01)
                o.conf.set("op_trace_budget_per_sec", budget)
            t0 = time.monotonic()
            for i in range(30):
                await io_ec.write_full(f"tr{i}", b"d" * 4096)
                await io_rep.write_full(f"tr{i}", b"d" * 2048)
            # complaint-age ops ALWAYS keep their trace: with the
            # window at zero every finishing op counts as slow
            for o in osds:
                o.op_tracker.complaint_time = 0.0
            await io_ec.write_full("tr-slow", b"e" * 4096)
            await io_rep.write_full("tr-slow", b"e" * 2048)
            for o in osds:
                o.op_tracker.complaint_time = 30.0
            elapsed = time.monotonic() - t0
            stats = [o.tracer.sampling_stats() for o in osds]
            agg = {
                k: sum(s[k] for s in stats)
                for k in ("sampled", "unsampled", "dropped_budget",
                          "kept_tail", "retained_spans")
            }
            # retention stayed inside the per-daemon token budget
            bound = len(osds) * (budget * elapsed + budget + 1)
            assert agg["sampled"] <= bound, (agg, elapsed)
            # a 1% head rate really sampled ops out...
            assert agg["unsampled"] >= 1, agg
            # ...while the complaint-age ops were always retained
            assert agg["kept_tail"] >= 2, agg
            assert agg["retained_spans"] >= agg["kept_tail"], agg
            kept_names = {
                s["name"]
                for o in osds
                for s in o.tracer.export()
            }
            assert "osd:op" in kept_names, kept_names
            for o in osds:
                o.conf.set("jaeger_tracing_enable", False)
                o.conf.set("op_trace_sample_rate", 1.0)
                o.conf.set("op_trace_budget_per_sec", 0.0)

            await client_a.shutdown()
            await client_b.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())
