"""Object classes (src/objclass + src/cls): registry/runtime units plus
live-cluster exec of the in-tree classes (lock, version, numops,
refcount) — the reference's third plugin family."""

import asyncio
import json

import pytest

from ceph_tpu.client import Rados, RadosError
from ceph_tpu.cls import client as cls_client
from ceph_tpu.cls.objclass import (
    RD,
    WR,
    ClsError,
    HCtx,
    MethodNotFound,
    cls_method,
    get_method,
)

from test_cluster import start_cluster, stop_cluster


class TestRuntime:
    def test_registry_and_lazy_load(self):
        flags, fn = get_method("numops", "add")  # lazy import
        assert flags & WR and callable(fn)
        with pytest.raises(MethodNotFound):
            get_method("numops", "nope")
        with pytest.raises(MethodNotFound):
            get_method("no_such_class", "m")

    def test_hctx_overlay_and_rd_guard(self):
        ctx = HCtx(
            exists=True,
            read_fn=lambda: b"disk",
            getattr_fn=lambda n: b"old" if n == "a" else None,
            entity="client.x",
            writable=True,
        )
        assert ctx.read() == b"disk"
        assert ctx.getxattr("a") == b"old"
        ctx.setxattr("a", b"new")
        ctx.write_full(b"staged")
        # read-your-writes overlay
        assert ctx.getxattr("a") == b"new"
        assert ctx.read() == b"staged"
        ro = HCtx(
            exists=True, read_fn=lambda: b"", getattr_fn=lambda n: None,
            writable=False,
        )
        with pytest.raises(ClsError):
            ro.setxattr("x", b"1")

    def test_method_decorator_registers(self):
        @cls_method("testcls_xyz", "echo", RD)
        def echo(ctx, indata):
            return indata[::-1]

        flags, fn = get_method("testcls_xyz", "echo")
        assert flags == RD and fn(None, b"abc") == b"cba"


def _cluster_test(body):
    async def run():
        monmap, mons, osds = await start_cluster(1, 3)
        client = Rados(monmap)
        await client.connect()
        await client.pool_create("clsp", "replicated", pg_num=4)
        io = await client.open_ioctx("clsp")
        try:
            await body(client, io)
        finally:
            await client.shutdown()
            await stop_cluster(mons, osds)

    asyncio.run(run())


class TestNumops:
    def test_server_side_arithmetic(self):
        async def body(client, io):
            assert await cls_client.numops_add(io, "counter", "n", 5) == 5
            assert await cls_client.numops_add(io, "counter", "n", 2.5) == 7.5
            out = await io.exec(
                "counter", "numops", "mul",
                json.dumps({"key": "n", "value": 2}).encode(),
            )
            assert float(out.decode()) == 15
            with pytest.raises(RadosError):
                await io.exec(
                    "counter", "numops", "div",
                    json.dumps({"key": "n", "value": 0}).encode(),
                )
            # the stored value is a plain xattr, interoperable
            assert await io.getxattr("counter", "n") == b"15"

        _cluster_test(body)


class TestLock:
    def test_exclusive_lock_contention_and_break(self):
        async def body(client, io):
            await cls_client.lock(io, "obj", "guard", cookie="c1")
            # renewal by the same (entity, cookie) succeeds
            await cls_client.lock(io, "obj", "guard", cookie="c1")
            # a second client contends -> EBUSY
            other = Rados(client.objecter.monc.monmap, name="client.other")
            await other.connect()
            oio = await other.open_ioctx("clsp")
            with pytest.raises(RadosError):
                await cls_client.lock(oio, "obj", "guard", cookie="c2")
            info = await cls_client.get_lock_info(io, "obj", "guard")
            assert info["type"] == "exclusive" and len(info["holders"]) == 1
            # break the holder's lock from the other client, then acquire
            # (the holder entity carries the instance nonce: read it back)
            holder_entity = info["holders"][0][0]
            await cls_client.break_lock(
                oio, "obj", "guard", entity=holder_entity, cookie="c1"
            )
            await cls_client.lock(oio, "obj", "guard", cookie="c2")
            await other.shutdown()

        _cluster_test(body)

    def test_shared_locks_coexist(self):
        async def body(client, io):
            await cls_client.lock(io, "s", "l", cookie="a", lock_type="shared")
            other = Rados(client.objecter.monc.monmap, name="client.o2")
            await other.connect()
            oio = await other.open_ioctx("clsp")
            await cls_client.lock(oio, "s", "l", cookie="b", lock_type="shared")
            info = await cls_client.get_lock_info(io, "s", "l")
            assert len(info["holders"]) == 2
            # unlock by non-holder cookie -> ENOENT
            with pytest.raises(RadosError):
                await cls_client.unlock(io, "s", "l", cookie="zz")
            await cls_client.unlock(io, "s", "l", cookie="a")
            await cls_client.unlock(oio, "s", "l", cookie="b")
            await other.shutdown()

        _cluster_test(body)


class TestVersion:
    def test_inc_read_check(self):
        async def body(client, io):
            assert await cls_client.version_inc(io, "v") == 1
            assert await cls_client.version_inc(io, "v") == 2
            assert await cls_client.version_read(io, "v") == 2
            await cls_client.version_check(io, "v", 2, "eq")
            await cls_client.version_check(io, "v", 1, "gt")
            with pytest.raises(RadosError):
                await cls_client.version_check(io, "v", 3, "eq")

        _cluster_test(body)


class TestRefcount:
    def test_tags_and_last_put(self):
        async def body(client, io):
            await io.write_full("shared", b"tail bytes")
            await cls_client.refcount_get(io, "shared", "u1")
            await cls_client.refcount_get(io, "shared", "u2")
            assert await cls_client.refcount_put(io, "shared", "u1") is False
            assert await cls_client.refcount_put(io, "shared", "u2") is True
            with pytest.raises(RadosError):
                await cls_client.refcount_put(io, "shared", "u3")

        _cluster_test(body)


class TestClsLog:
    """cls_log (src/cls/log/cls_log.cc): omap-backed timestamped log —
    also the end-to-end proof of the cls_cxx_map_* surface."""

    def test_add_list_trim(self):
        async def body(client, io):
            entries = [
                {"ts": 100.0 + i, "section": "meta", "name": f"e{i}",
                 "data": f"payload{i}"}
                for i in range(5)
            ]
            await io.exec(
                "logobj", "log", "add",
                json.dumps({"entries": entries}).encode(),
            )
            out = json.loads(
                await io.exec(
                    "logobj", "log", "list", json.dumps({"max": 3}).encode()
                )
            )
            assert [e["name"] for e in out["entries"]] == ["e0", "e1", "e2"]
            assert out["truncated"]
            # paging continues from the marker
            out2 = json.loads(
                await io.exec(
                    "logobj", "log", "list",
                    json.dumps({"max": 10, "marker": out["marker"]}).encode(),
                )
            )
            assert [e["name"] for e in out2["entries"]] == ["e3", "e4"]
            assert not out2["truncated"]
            # window query: from/to bound the page
            win = json.loads(
                await io.exec(
                    "logobj", "log", "list",
                    json.dumps({"from": 101.0, "to": 103.0}).encode(),
                )
            )
            assert [e["name"] for e in win["entries"]] == ["e1", "e2"]
            # trim everything at or before ts 102; the rest survives
            await io.exec(
                "logobj", "log", "trim", json.dumps({"to": 102.0}).encode()
            )
            left = json.loads(
                await io.exec("logobj", "log", "list", b"{}")
            )
            assert [e["name"] for e in left["entries"]] == ["e3", "e4"]
            # entries live in plain omap, interoperable with client KV ops
            assert len(await io.omap_get_keys("logobj")) == 2
            with pytest.raises(RadosError):  # nothing left to trim
                await io.exec(
                    "logobj", "log", "trim", json.dumps({"to": 102.0}).encode()
                )

        _cluster_test(body)


class TestErrors:
    def test_unknown_class_is_eopnotsupp(self):
        async def body(client, io):
            with pytest.raises(RadosError) as ei:
                await io.exec("o", "nonexistent", "m", b"")
            assert ei.value.errno == -95

        _cluster_test(body)

    def test_failed_method_aborts_whole_transaction(self):
        async def body(client, io):
            # numops add on a non-numeric xattr fails -> nothing may land
            await io.write_full("t", b"x")
            await io.setxattr("t", "n", b"not a number")
            with pytest.raises(RadosError):
                await io.exec(
                    "t", "numops", "add",
                    json.dumps({"key": "n", "value": 1}).encode(),
                )
            assert await io.getxattr("t", "n") == b"not a number"

        _cluster_test(body)


class TestReviewRegressions:
    def test_malformed_input_errors_instead_of_hanging(self):
        """A method raising an unexpected exception (KeyError on a
        malformed request) must map to an errno reply, not a leaked
        exception that leaves the client waiting forever."""

        async def body(client, io):
            with pytest.raises(RadosError) as ei:
                await io.exec("o", "lock", "lock", b"{}")  # missing "name"
            assert ei.value.errno == -22

        _cluster_test(body)

    def test_shared_to_exclusive_escalation_refused(self):
        async def body(client, io):
            await cls_client.lock(io, "e", "l", cookie="a", lock_type="shared")
            other = Rados(client.objecter.monc.monmap, name="client.e2")
            await other.connect()
            oio = await other.open_ioctx("clsp")
            await cls_client.lock(oio, "e", "l", cookie="b", lock_type="shared")
            # A cannot escalate while B shares
            with pytest.raises(RadosError):
                await cls_client.lock(io, "e", "l", cookie="a",
                                      lock_type="exclusive")
            # after B releases, escalation as sole holder succeeds
            await cls_client.unlock(oio, "e", "l", cookie="b")
            await cls_client.lock(io, "e", "l", cookie="a",
                                  lock_type="exclusive")
            await other.shutdown()

        _cluster_test(body)

    def test_call_and_plain_ops_honor_order(self):
        """Mutations fold in op order: a plain SETXATTR after a CALL in
        the same compound op wins, and a CALL reads attrs staged by an
        earlier CALL."""
        from ceph_tpu.msg.messages import OSDOp

        async def body(client, io):
            rep = await io._op(
                "ord",
                [
                    OSDOp(op=OSDOp.CALL, name="version.set",
                          data=json.dumps({"ver": 5}).encode()),
                    OSDOp(op=OSDOp.SETXATTR, name="ver", data=b"plain"),
                ],
            )
            assert rep.result == 0
            assert await io.getxattr("ord", "ver") == b"plain"
            # and the reverse: CALL after SETXATTR sees + overrides it
            rep = await io._op(
                "ord2",
                [
                    OSDOp(op=OSDOp.SETXATTR, name="n", data=b"7"),
                    OSDOp(op=OSDOp.CALL, name="numops.add",
                          data=json.dumps({"key": "n", "value": 3}).encode()),
                ],
            )
            assert rep.result == 0
            assert await io.getxattr("ord2", "n") == b"10"

        _cluster_test(body)
