"""Tool tests: vstart DevCluster, rados/ceph CLI plumbing, PGLS object
listing, objectstore tool (src/vstart.sh, src/tools mirrors)."""

import asyncio
import json
import subprocess
import sys

import pytest

from ceph_tpu.client import Rados
from ceph_tpu.os.filestore import FileStore
from ceph_tpu.os.transaction import Transaction
from ceph_tpu.tools.ceph_cli import build_cmd
from ceph_tpu.tools.objectstore_tool import main as ost_main
from ceph_tpu.tools.vstart import DevCluster, load_monmap


class TestDevCluster:
    def test_boot_write_read(self):
        async def run():
            cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=True)
            await cluster.start()
            assert cluster.mgr.active
            client = Rados(cluster.monmap)
            await client.connect()
            await client.pool_create("vp", "replicated", size=3, pg_num=4)
            ioctx = await client.open_ioctx("vp")
            await ioctx.write_full("hello", b"world")
            assert await ioctx.read("hello") == b"world"
            await client.shutdown()
            await cluster.stop()

        asyncio.run(run())

    def test_cluster_file_roundtrip(self, tmp_path):
        async def run():
            cluster = DevCluster(n_mons=1, n_osds=1, with_mgr=False)
            await cluster.start()
            path = str(tmp_path / "cluster.json")
            cluster.write_cluster_file(path)
            monmap = load_monmap(path)
            assert monmap.addrs == cluster.monmap.addrs
            await cluster.stop()

        asyncio.run(run())


class TestPgls:
    def test_rados_ls(self):
        async def run():
            cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=False)
            await cluster.start()
            client = Rados(cluster.monmap)
            await client.connect()
            await client.pool_create("lsp", "replicated", size=2, pg_num=4)
            ioctx = await client.open_ioctx("lsp")
            names = [f"obj-{i}" for i in range(12)]
            for n in names:
                await ioctx.write_full(n, n.encode())
            assert await ioctx.list_objects() == sorted(names)
            await ioctx.remove("obj-0")
            assert "obj-0" not in await ioctx.list_objects()
            await client.shutdown()
            await cluster.stop()

        asyncio.run(run())


class TestCephCliCmdBuilder:
    def test_build_cmds(self):
        assert build_cmd(["status"]) == {"prefix": "status"}
        assert build_cmd(["osd", "dump"]) == {"prefix": "osd dump"}
        cmd = build_cmd(["osd", "pool", "create", "p1", "erasure", "prof"])
        assert cmd == {
            "prefix": "osd pool create",
            "pool": "p1",
            "pool_type": "erasure",
            "erasure_code_profile": "prof",
        }
        cmd = build_cmd(
            ["osd", "erasure-code-profile", "set", "p1", "k=4", "m=2"]
        )
        assert cmd["name"] == "p1" and cmd["profile"] == ["k=4", "m=2"]
        assert build_cmd(["osd", "reweight", "3", "0.5"]) == {
            "prefix": "osd reweight",
            "id": "3",
            "weight": "0.5",
        }
        assert build_cmd(["osd", "pool", "get", "p1", "size"]) == {
            "prefix": "osd pool get", "pool": "p1", "var": "size",
        }
        assert build_cmd(
            ["osd", "pool", "set-quota", "p1", "max_objects", "10"]
        ) == {
            "prefix": "osd pool set-quota", "pool": "p1",
            "field": "max_objects", "val": "10",
        }
        assert build_cmd(["fs", "new", "cephfs", "m", "d"]) == {
            "prefix": "fs new", "fs_name": "cephfs",
            "metadata": "m", "data": "d",
        }
        assert build_cmd(["fs", "rm", "cephfs"]) == {
            "prefix": "fs rm", "fs_name": "cephfs",
        }


class TestObjectstoreTool:
    def _mkstore(self, path) -> None:
        store = FileStore(str(path))
        store.mount()
        txn = (
            Transaction()
            .create_collection("1.0s0")
            .touch("1.0s0", "objA")
            .write("1.0s0", "objA", 0, b"AAAA")
            .setattr("1.0s0", "objA", "_", b"\x01\x02")
            .touch("1.0s0", "objB")
            .write("1.0s0", "objB", 0, b"BBBB")
        )
        store.queue_transaction(txn)
        store.umount()

    def test_list_dump_export_import(self, tmp_path, capsys):
        src = tmp_path / "osd0"
        self._mkstore(src)

        assert ost_main(["--data-path", str(src), "--op", "list"]) == 0
        out = capsys.readouterr().out
        assert json.loads(out.splitlines()[0]) == ["1.0s0", "objA"]

        assert (
            ost_main(
                ["--data-path", str(src), "--op", "dump",
                 "--coll", "1.0s0", "--oid", "objA"]
            )
            == 0
        )
        dump = json.loads(capsys.readouterr().out)
        assert dump["size"] == 4
        assert "_" in dump["attrs"]

        export_file = str(tmp_path / "pg.export")
        assert (
            ost_main(
                ["--data-path", str(src), "--op", "export",
                 "--coll", "1.0s0", "--file", export_file]
            )
            == 0
        )
        # import into a fresh store — disaster-recovery round trip
        dst = tmp_path / "osd1"
        assert (
            ost_main(
                ["--data-path", str(dst), "--op", "import", "--file", export_file]
            )
            == 0
        )
        store = FileStore(str(dst))
        store.mount()
        assert store.read("1.0s0", "objA", 0, 0) == b"AAAA"
        assert store.read("1.0s0", "objB", 0, 0) == b"BBBB"
        assert store.getattr("1.0s0", "objA", "_") == b"\x01\x02"
        store.umount()

    def test_get_set_bytes(self, tmp_path, capsys):
        src = tmp_path / "osd0"
        self._mkstore(src)
        out_file = str(tmp_path / "obj.bin")
        assert (
            ost_main(
                ["--data-path", str(src), "--op", "get-bytes",
                 "--coll", "1.0s0", "--oid", "objA", "--file", out_file]
            )
            == 0
        )
        assert open(out_file, "rb").read() == b"AAAA"
        with open(out_file, "wb") as f:
            f.write(b"PATCHED")
        assert (
            ost_main(
                ["--data-path", str(src), "--op", "set-bytes",
                 "--coll", "1.0s0", "--oid", "objA", "--file", out_file]
            )
            == 0
        )
        store = FileStore(str(src))
        store.mount()
        assert store.read("1.0s0", "objA", 0, 0) == b"PATCHED"
        store.umount()


class TestCliSubprocess:
    def test_vstart_plus_rados_cli_end_to_end(self, tmp_path):
        """The CLIs work from a separate process against a live cluster —
        the qa-standalone shape (daemons + shell tools)."""

        async def run():
            cluster = DevCluster(n_mons=1, n_osds=3, with_mgr=False)
            await cluster.start()
            cfile = str(tmp_path / "cluster.json")
            cluster.write_cluster_file(cfile)
            # create a pool via the ceph CLI (subprocess)
            loop = asyncio.get_event_loop()

            def tool(mod):
                def run_tool(*argv):
                    return subprocess.run(
                        [sys.executable, "-m", f"ceph_tpu.tools.{mod}",
                         "--cluster-file", cfile, *argv],
                        capture_output=True, timeout=60, cwd="/root/repo",
                        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
                             "PYTHONPATH": "/root/repo"},
                    )

                return run_tool

            ceph, rados, rbd = tool("ceph_cli"), tool("rados_cli"), tool("rbd_cli")

            r = await loop.run_in_executor(
                None, lambda: ceph("osd", "pool", "create", "clip")
            )
            assert r.returncode == 0, r.stderr
            src = tmp_path / "payload.bin"
            src.write_bytes(b"cli-payload" * 100)
            r = await loop.run_in_executor(
                None, lambda: rados("-p", "clip", "put", "obj1", str(src))
            )
            assert r.returncode == 0, r.stderr
            r = await loop.run_in_executor(
                None, lambda: rados("-p", "clip", "get", "obj1")
            )
            assert r.returncode == 0 and r.stdout == b"cli-payload" * 100
            r = await loop.run_in_executor(None, lambda: rados("-p", "clip", "ls"))
            assert r.returncode == 0 and b"obj1" in r.stdout
            r = await loop.run_in_executor(None, lambda: ceph("status"))
            assert r.returncode == 0 and b"num_up_osds" in r.stdout

            # rbd CLI: create/snap/protect/clone/info/children round trip
            async def sh(fn):
                return await loop.run_in_executor(None, fn)

            r = await sh(lambda: rbd(
                "-p", "clip", "--size", "1048576", "--order", "16",
                "create", "vol1",
            ))
            assert r.returncode == 0, r.stderr
            for words in (
                ["snap", "create", "vol1@s1"],
                ["snap", "protect", "vol1@s1"],
                ["clone", "vol1@s1", "vol2"],
            ):
                r = await sh(lambda w=words: rbd("-p", "clip", *w))
                assert r.returncode == 0, (words, r.stderr)
            r = await sh(lambda: rbd("-p", "clip", "children", "vol1@s1"))
            assert r.returncode == 0 and b"vol2" in r.stdout
            r = await sh(lambda: rbd("-p", "clip", "info", "vol2"))
            assert r.returncode == 0 and b"vol1@s1" in r.stdout
            # protected snap refuses removal through the CLI too
            r = await sh(lambda: rbd("-p", "clip", "snap", "rm", "vol1@s1"))
            assert r.returncode == 1
            r = await sh(lambda: rbd("-p", "clip", "ls"))
            assert r.stdout.split() == [b"vol1", b"vol2"]

            # radosgw-admin: user + bucket admin against the same pool
            rgwadm = tool("rgw_admin")
            r = await sh(lambda: rgwadm(
                "-p", "clip", "--uid", "alice", "user", "create"
            ))
            assert r.returncode == 0 and b"access_key" in r.stdout, r.stderr
            r = await sh(lambda: rgwadm("-p", "clip", "user", "list"))
            assert r.stdout.split() == [b"alice"]
            r = await sh(lambda: rgwadm(
                "-p", "clip", "--uid", "alice", "user", "create"
            ))
            assert r.returncode == 1  # UserAlreadyExists -> clean error
            await cluster.stop()

        asyncio.run(run())


class TestVstartMds:
    def test_dev_cluster_with_mds(self):
        """vstart's MDS=1 topology: pools bootstrapped, MDS serving, and
        a CephFS client round trip against the written cluster file."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.mds import CephFSClient
            from ceph_tpu.tools.vstart import DevCluster

            cluster = DevCluster(1, 3, with_mgr=False, with_mds=True)
            await cluster.start()
            assert cluster.mds is not None and cluster.mds.addr

            rados = Rados(cluster.monmap)
            await rados.connect()
            assert {"cephfs_metadata", "cephfs_data"} <= set(
                await rados.pool_list()
            )
            data = await rados.open_ioctx("cephfs_data")
            fsc = CephFSClient(cluster.mds.addr, data)
            await fsc.mkdir("/vstart")
            await fsc.write_file("/vstart/hello", b"from the dev cluster")
            assert await fsc.read_file("/vstart/hello") == b"from the dev cluster"
            await fsc.shutdown()
            await rados.shutdown()
            await cluster.stop()

        asyncio.run(run())


class TestCephTell:
    def test_tell_routes_to_daemon_admin_sockets(self, tmp_path):
        """`ceph tell <daemon> <cmd>` (ceph.in's tell path): the CLI
        resolves the daemon's admin socket from the cluster file and
        returns the hook's JSON — covering OSD op dumps and mon status
        end to end through a subprocess."""

        async def run():
            cluster = DevCluster(
                n_mons=1, n_osds=2, with_mgr=False,
                asok_dir=str(tmp_path / "asok"),
            )
            await cluster.start()
            cf = str(tmp_path / "cluster.json")
            cluster.write_cluster_file(cf)
            client = Rados(cluster.monmap)
            await client.connect()
            await client.pool_create("tellp", "replicated", size=2, pg_num=2)
            io = await client.open_ioctx("tellp")
            await io.write_full("seen", b"by the tracker")

            def tell(*words):
                out = subprocess.run(
                    [sys.executable, "-m", "ceph_tpu.tools.ceph_cli",
                     "--cluster-file", cf, "tell", *words],
                    capture_output=True, timeout=60,
                )
                assert out.returncode == 0, out.stderr.decode()
                return json.loads(out.stdout.decode())

            loop = asyncio.get_event_loop()
            mon_name = next(iter(cluster.monmap.addrs))
            st = await loop.run_in_executor(
                None, lambda: tell(f"mon.{mon_name}", "mon_status")
            )
            assert st["state"] == "leader" and st["rank"] == 0
            ops = await loop.run_in_executor(
                None, lambda: tell("osd.0", "dump_historic_ops")
            )
            assert "ops" in ops
            perf = await loop.run_in_executor(
                None, lambda: tell("osd.1", "perf dump")
            )
            assert "op" in perf
            # unknown daemon is a clean error
            out = subprocess.run(
                [sys.executable, "-m", "ceph_tpu.tools.ceph_cli",
                 "--cluster-file", cf, "tell", "osd.99", "perf dump"],
                capture_output=True, timeout=60,
            )
            assert out.returncode == 1
            assert b"no admin socket" in out.stderr
            await client.shutdown()
            await cluster.stop()

        asyncio.run(run())


class TestVstartRgw:
    def test_rgw_topology_and_mds_admin_socket(self, tmp_path):
        """vstart RGW=1: S3+Swift endpoints served and recorded in the
        cluster file; the MDS daemons expose admin sockets reachable via
        `ceph tell mds.<x> status` semantics."""

        async def run():
            import urllib.request

            from ceph_tpu.common.admin_socket import admin_command

            cluster = DevCluster(
                n_mons=1, n_osds=3, with_mgr=False, with_mds=True,
                with_rgw=True, asok_dir=str(tmp_path / "asok"),
            )
            await cluster.start()
            cfile = str(tmp_path / "cluster.json")
            cluster.write_cluster_file(cfile)
            info = json.load(open(cfile))
            assert info["rgw_s3_endpoint"] and info["rgw_swift_endpoint"]
            assert any(k.startswith("mds.") for k in info["admin_sockets"])
            # the recorded S3 endpoint serves (service-level list)
            loop = asyncio.get_event_loop()
            body = await loop.run_in_executor(
                None,
                lambda: urllib.request.urlopen(
                    f"http://{info['rgw_s3_endpoint']}/", timeout=5
                ).read(),
            )
            assert b"ListAllMyBucketsResult" in body
            # MDS admin socket: status names the active's filesystem
            active = cluster.mds
            st = await loop.run_in_executor(
                None,
                lambda: admin_command(
                    info["admin_sockets"][f"mds.{active.name}"], "status"
                ),
            )
            assert st["state"] == "up:active" and st["fs"] == "cephfs"
            sessions = await loop.run_in_executor(
                None,
                lambda: admin_command(
                    info["admin_sockets"][f"mds.{active.name}"], "session ls"
                ),
            )
            assert isinstance(sessions, list)
            await cluster.stop()

        asyncio.run(run())
