"""ISSUE 9: the compare-only verify kernel, the VerifyAggregator, and
the unified launch scheduler's QoS ordering.

Three contracts:

1. **Bitmap fidelity** — `verify_array` (device kernel) and
   `verify_array_host` (pure-numpy oracle) are byte-identical across
   RS(4,2) and RS(8,3), for clean codewords, for a corrupted shard at
   EVERY position, and for ragged final chunks (the scrubber's
   zero-padding: linear code, encode(0) == 0, so padding preserves the
   parity equation exactly).
2. **Aggregation** — a scrub chunk's worth of submissions coalesces
   into one VERIFY_LAUNCHES dispatch, and the DEGRADED/fault fallback
   reproduces the identical bitmap on the host oracle.
3. **QoS ordering** — with a deterministic clock, queued client
   launches dequeue ahead of a saturating background verify stream
   (clients never starve behind scrub), and a background-only queue
   drains completely when the device is otherwise idle (scrub never
   starves either).
"""

import threading

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import VerifyAggregator
from ceph_tpu.ops import dispatch as ec_dispatch
from ceph_tpu.ops.launch_scheduler import LaunchScheduler, lane_name
from ceph_tpu.osd.scheduler import ClientProfile, SchedClass


def make_rs(k: int, m: int) -> ErasureCodeTpuRs:
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


def codewords(ec, stripes: int, L: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, (stripes, ec.k, L), dtype=np.uint8)
    parity = np.asarray(ec.encode_array(data))
    return np.concatenate([data, parity], axis=1)


GEOMETRIES = [(4, 2), (8, 3)]


class TestVerifyKernel:
    @pytest.mark.parametrize("k,m", GEOMETRIES)
    def test_clean_codewords_bitmap_zero_and_matches_host(self, k, m):
        ec = make_rs(k, m)
        cw = codewords(ec, 5, 512, seed=k)
        dev = np.asarray(ec.verify_array(cw))
        host = ec.verify_array_host(cw)
        assert np.array_equal(dev, host)
        assert not dev.any(), "clean codewords must verify clean"
        assert dev.shape == (5,) and dev.dtype == np.uint8

    @pytest.mark.parametrize("k,m", GEOMETRIES)
    def test_corrupted_shard_at_every_position(self, k, m):
        """A single flipped byte in ANY of the k+m shards must flag
        exactly the corrupted stripe, identically on device and host."""
        ec = make_rs(k, m)
        cw = codewords(ec, 4, 256, seed=10 * k + m)
        for shard in range(k + m):
            bad = cw.copy()
            bad[2, shard, 13] ^= 0x5A
            dev = np.asarray(ec.verify_array(bad))
            host = ec.verify_array_host(bad)
            assert np.array_equal(dev, host), (shard, dev, host)
            assert dev[2] != 0, f"corrupt shard {shard} not flagged"
            clean = [i for i in range(4) if i != 2]
            assert not dev[clean].any(), f"shard {shard} over-flagged"
            if shard >= k:
                # a corrupt PARITY shard flags exactly its own row
                assert dev[2] == 1 << (shard - k), (shard, dev[2])

    @pytest.mark.parametrize("k,m", GEOMETRIES)
    def test_ragged_final_chunk_zero_padding(self, k, m):
        """The scrubber pads a ragged final chunk with zeros on data AND
        parity rows.  encode(0) == 0 for a linear code, so the padded
        stripe must verify clean — and corruption INSIDE the ragged tail
        must still be caught, identically on device and host."""
        ec = make_rs(k, m)
        L = 512
        ragged = 137  # final chunk occupies 137 of 512 bytes
        full = codewords(ec, 3, L, seed=k + m)
        # rebuild the last stripe from a ragged tail: zero-pad the data,
        # re-encode, keep only the ragged prefix of data + parity (what
        # the shards actually store), zero-pad both back to L
        tail_data = np.zeros((1, k, L), dtype=np.uint8)
        tail_data[0, :, :ragged] = full[2, :k, :ragged]
        tail_parity = np.asarray(ec.encode_array(tail_data))
        padded = np.concatenate([tail_data, tail_parity], axis=1)
        cw = np.concatenate([full[:2], padded])
        dev = np.asarray(ec.verify_array(cw))
        host = ec.verify_array_host(cw)
        assert np.array_equal(dev, host)
        assert not dev.any(), "zero-padded ragged chunk must verify clean"
        bad = cw.copy()
        bad[2, k - 1, ragged - 1] ^= 0xFF  # inside the ragged tail
        dev = np.asarray(ec.verify_array(bad))
        assert np.array_equal(dev, ec.verify_array_host(bad))
        assert dev[2] != 0, "corruption in the ragged tail missed"


class TestVerifyAggregator:
    def test_chunk_of_objects_coalesces_into_one_launch(self):
        ec = make_rs(4, 2)
        agg = VerifyAggregator(window=16)
        v0 = ec_dispatch.VERIFY_LAUNCHES.snapshot()
        cw = codewords(ec, 12, 1024, seed=3)
        # 6 "objects" of 2 stripes each, submitted like one scrub chunk
        tickets = [agg.submit(ec, cw[i : i + 2]) for i in range(0, 12, 2)]
        bitmaps = [np.asarray(t) for t in tickets]
        after = ec_dispatch.VERIFY_LAUNCHES.snapshot()
        assert after["launches"] - v0["launches"] == 1, (
            "a chunk's verifies must coalesce into ONE device launch"
        )
        assert after["stripes"] - v0["stripes"] >= 12
        for bm in bitmaps:
            assert bm.shape == (2,) and not bm.any()

    def test_fault_fallback_bitmap_is_byte_identical(self):
        """An injected launch fault re-runs the verify on the host
        oracle: the reaped bitmap must be identical, and the scrub must
        still detect the corruption."""
        from ceph_tpu.common.fault_injector import global_injector
        from ceph_tpu.ops.guard import device_guard

        ec = make_rs(4, 2)
        agg = VerifyAggregator(window=4)
        cw = codewords(ec, 3, 512, seed=9)
        cw[1, 2, 5] ^= 0x77
        want = ec.verify_array_host(cw)
        inj = global_injector()
        inj.inject("codec.launch", 5, hits=1)
        try:
            ticket = agg.submit(ec, cw)
            got = np.asarray(ticket)
        finally:
            inj.clear("codec.launch")
            device_guard().mark_healthy()
        assert np.array_equal(got, want)
        assert got[1] != 0 and not got[0] and not got[2]
        assert agg.perf.get("host_fallbacks") >= 1


def make_sched(clock) -> LaunchScheduler:
    return LaunchScheduler(
        profiles={
            SchedClass.CLIENT: ClientProfile(reservation=1.0, weight=2.0),
            SchedClass.RECOVERY: ClientProfile(weight=1.0),
            SchedClass.SCRUB: ClientProfile(weight=0.5),
            SchedClass.BEST_EFFORT: ClientProfile(weight=0.5),
        },
        clock=clock,
    )


class TestLaunchSchedulerOrdering:
    def test_client_dequeues_ahead_of_saturating_background(self):
        """A saturating background verify stream is queued FIRST; client
        launches enqueued after it must still dequeue ahead of (all but
        the already-matured head of) the background backlog."""
        sched = make_sched(clock=lambda: 0.0)
        order: list[str] = []
        for i in range(20):
            sched.submit_async(
                SchedClass.SCRUB, lambda i=i: order.append(f"bg{i}"),
                cost=1 << 20,
            )
        for i in range(4):
            sched.submit_async(
                SchedClass.CLIENT, lambda i=i: order.append(f"client{i}"),
                cost=4096,
            )
        assert sched.queue_depths() == {
            "client": 4, "recovery": 0, "background": 20,
        }
        ran = sched.drain()
        assert ran == 24
        client_pos = [order.index(f"client{i}") for i in range(4)]
        # every client launch runs before the background backlog's tail:
        # at most the head background item (whose proportional tag had
        # already matured) may precede them
        assert max(client_pos) < 5, order[:8]
        assert order.index("client0") < order.index("bg1"), order[:6]
        # FIFO within the class
        assert client_pos == sorted(client_pos)
        counters = sched.perf_dump()
        assert counters["client.dequeued"] == 4
        assert counters["background.dequeued"] == 20
        assert counters["background.queue_depth"] == 0

    def test_background_drains_when_idle(self):
        """No starvation the other way: with nothing else queued, the
        background lane drains at full speed (work-conserving — limits
        deprioritize, never idle the device)."""
        now = [0.0]
        sched = make_sched(clock=lambda: now[0])
        done: list[int] = []
        for i in range(10):
            sched.submit_async(
                SchedClass.SCRUB, lambda i=i: done.append(i), cost=1 << 20
            )
        assert sched.drain() == 10
        assert done == list(range(10)), "idle background must drain FIFO"
        assert sched.queue_depths()["background"] == 0

    def test_limited_background_still_drains(self):
        """Even with a hard limit configured, the scheduler serves the
        nearest limit tag rather than idling (the work-conserving
        clause) — scrub slows under contention but never wedges."""
        sched = make_sched(clock=lambda: 0.0)
        sched.configure(background=ClientProfile(weight=0.5, limit=1.0))
        done: list[int] = []
        for i in range(5):
            sched.submit_async(
                SchedClass.SCRUB, lambda i=i: done.append(i), cost=1 << 20
            )
        assert sched.drain() == 5
        assert done == list(range(5))

    def test_submit_blocks_until_own_launch_ran_cross_thread(self):
        """A submitter whose launch is executed by ANOTHER thread's
        drain still gets its own result (the cross-thread rendezvous),
        and a raising launch surfaces at its own submitter."""
        sched = make_sched(clock=lambda: 0.0)
        release = threading.Event()
        started = threading.Event()

        def blocker():
            started.set()
            release.wait(5.0)
            return "bg-done"

        results: dict[str, object] = {}

        def bg():
            results["bg"] = sched.submit(SchedClass.SCRUB, blocker, cost=4096)

        def client_ok():
            results["ok"] = sched.submit(
                SchedClass.CLIENT, lambda: "client-done", cost=4096
            )

        def client_raise():
            try:
                sched.submit(
                    SchedClass.CLIENT,
                    lambda: (_ for _ in ()).throw(RuntimeError("boom")),
                    cost=4096,
                )
            except RuntimeError as e:
                results["err"] = str(e)

        threads = [threading.Thread(target=bg)]
        threads[0].start()
        assert started.wait(5.0), "background launch never started"
        threads += [
            threading.Thread(target=client_ok),
            threading.Thread(target=client_raise),
        ]
        for t in threads[1:]:
            t.start()
        release.set()
        for t in threads:
            t.join(10.0)
            assert not t.is_alive(), "scheduler deadlocked a submitter"
        assert results == {
            "bg": "bg-done", "ok": "client-done", "err": "boom"
        }

    def test_reservation_knob_works_after_zero_reservation_history(self):
        """Runtime-raising a lane's reservation must take effect even
        after the lane enqueued under reservation=0: enqueue stores
        r = inf as the class's last tag, and without the
        update_profile tag reset the knob would be permanently inert
        (max(now, inf + 1/res) stays inf forever)."""
        now = [100.0]
        sched = make_sched(clock=lambda: now[0])
        # poison: background enqueues (and drains) with no reservation —
        # the class's last R tag is stored as inf
        sched.submit_async(SchedClass.SCRUB, lambda: None)
        sched.drain()
        # operator raises the background reservation at runtime
        sched.configure(
            background=ClientProfile(reservation=2.0, weight=0.5)
        )
        now[0] = 200.0
        sched.submit_async(SchedClass.SCRUB, lambda: None)
        tags = sched._mclock._queues[SchedClass.SCRUB][0][0]
        assert tags.r != float("inf"), (
            "reservation knob inert: last.r = inf survived update_profile"
        )
        assert tags.r <= now[0], "raised reservation must mature immediately"
        sched.drain()

    def test_lane_names(self):
        assert lane_name(SchedClass.CLIENT) == "client"
        assert lane_name(SchedClass.RECOVERY) == "recovery"
        assert lane_name(SchedClass.SCRUB) == "background"
        assert lane_name(SchedClass.BEST_EFFORT) == "background"


class TestVerifyFlightRecords:
    def test_verify_launch_record_carries_background_class(self):
        """Aggregated verify launches stamp kind=verify and
        sched_class=background on their flight records, and the trace
        export renders the per-class lane (satellite: priority
        inversions visible in Perfetto)."""
        from ceph_tpu.ops.flight_recorder import flight_recorder
        from ceph_tpu.tools.trace_export import (
            export_chrome_trace,
            validate_chrome_trace,
        )

        fr = flight_recorder()
        fr.reset()
        ec = make_rs(4, 2)
        agg = VerifyAggregator(window=4)
        np.asarray(agg.submit(ec, codewords(ec, 2, 256, seed=1)))
        recs = [r for r in fr.records() if r["kind"] == "verify"]
        assert recs, "verify launch left no flight record"
        assert recs[-1]["sched_class"] == "background"
        trace = export_chrome_trace(fr.records())
        validate_chrome_trace(trace)
        lanes = {
            (e["pid"], e["tid"])
            for e in trace["traceEvents"]
            if e["pid"] == "sched class"
        }
        assert ("sched class", "background") in lanes, lanes
