"""Device-offload runtime (ISSUE 20): the service registry that names
every LaunchAggregator-backed offload service, the refactor guard
pinning `codec/matrix_codec` to its `ops/offload_runtime` re-exports
(the promotion must be a pure move — same objects, same behavior), and
the device crc32c service's byte-identity against `utils/crc32c` across
block sizes, ragged tails, fault injection and the DEGRADED bypass."""

import numpy as np
import pytest

from ceph_tpu.common.fault_injector import global_injector
from ceph_tpu.ops.checksum_offload import (
    CSUM_OFFLOAD_MIN_BYTES,
    ChecksumAggregator,
    checksum_blocks,
    crc32c_device,
    crc32c_host_rows,
    default_csum_aggregator,
)
from ceph_tpu.ops.guard import device_guard
from ceph_tpu.utils.crc32c import crc32c


@pytest.fixture(autouse=True)
def _clean_state():
    yield
    global_injector().clear()
    device_guard().mark_healthy()
    device_guard().configure(timeout_ms=20000, probe_interval_ms=2000)


class TestServiceRegistry:
    def test_builtin_services_present_in_registration_order(self):
        from ceph_tpu.ops.offload_runtime import offload_services

        names = offload_services()
        # the EC trio registered first (the refactor's zero-behavior
        # seam), the ISSUE 20 services after them
        for want in ("encode", "decode", "verify", "compress", "csum"):
            assert want in names, names

    def test_service_resolves_the_default_aggregators(self):
        from ceph_tpu.codec.matrix_codec import default_encode_aggregator
        from ceph_tpu.compressor.device import default_compress_aggregator
        from ceph_tpu.ops.offload_runtime import service, service_aggregator

        assert service("csum").aggregator() is default_csum_aggregator()
        assert service("encode").aggregator() is default_encode_aggregator()
        assert service_aggregator("compress") is default_compress_aggregator()

    def test_register_is_idempotent(self):
        from ceph_tpu.ops.offload_runtime import (
            offload_services,
            register_service,
        )

        before = offload_services()
        register_service(
            "csum", default_csum_aggregator, lane="background",
            oracle="utils/crc32c.crc32c", doc="re-registration no-op",
        )
        assert offload_services() == before

    def test_perf_dump_is_flat_and_names_every_service(self):
        from ceph_tpu.ops.offload_runtime import (
            offload_perf_dump,
            offload_services,
        )

        dump = offload_perf_dump()
        names = offload_services()
        assert dump["services"] == len(names)
        for name in names:
            assert f"{name}.pending" in dump, sorted(dump)
        # flat values only — scalars plus the histogram payload shape
        # the prometheus exporter already renders; nothing nested deeper
        assert all(
            isinstance(v, (int, float))
            or (isinstance(v, dict) and "histogram" in v)
            for v in dump.values()
        )

    def test_service_lanes_match_their_qos_class(self):
        from ceph_tpu.ops.offload_runtime import service

        # checksums and compression must never head-of-line-block
        # client encodes: both ride the background lane
        assert service("csum").lane == "background"
        assert service("compress").lane == "background"
        assert service("csum").aggregator().SCHED_CLASS == "background"


class TestRefactorGuard:
    def test_matrix_codec_reexports_are_the_runtime_objects(self):
        """The promotion to ops/offload_runtime was a pure move: every
        name matrix_codec still exports must BE the runtime's object,
        not a copy — two class objects would mean two donation pools,
        two aggregator registries, two drain scopes."""
        from ceph_tpu.codec import matrix_codec as mc
        from ceph_tpu.ops import offload_runtime as rt

        assert mc.LaunchAggregator is rt.LaunchAggregator
        assert mc.AggTicket is rt.AggTicket
        assert mc.DonationPool is rt.DonationPool
        assert mc._AggGroup is rt._AggGroup
        assert mc.drain_all_aggregators is rt.drain_all_aggregators
        assert mc.drop_donation_retention is rt.drop_donation_retention

    def test_every_service_aggregator_subclasses_the_runtime_base(self):
        from ceph_tpu.codec.matrix_codec import (
            DecodeAggregator,
            EncodeAggregator,
            VerifyAggregator,
        )
        from ceph_tpu.compressor.device import CompressAggregator
        from ceph_tpu.ops.offload_runtime import LaunchAggregator

        for cls in (EncodeAggregator, DecodeAggregator, VerifyAggregator,
                    ChecksumAggregator, CompressAggregator):
            assert issubclass(cls, LaunchAggregator), cls

    def test_drain_all_reaches_the_new_services(self):
        from ceph_tpu.ops.offload_runtime import drain_all_aggregators

        agg = default_csum_aggregator()
        blocks = np.arange(2 * 512, dtype=np.uint8).reshape(2, 512) % 251
        ticket = agg.submit_blocks(blocks)
        drain_all_aggregators()
        assert agg.pending() == 0
        assert np.array_equal(
            np.asarray(ticket.result()), crc32c_host_rows(blocks)
        )


class TestDeviceCrc32c:
    @pytest.mark.parametrize("L", [1, 4, 63, 64, 512, 1000, 4096])
    def test_device_digests_byte_identical_across_lengths(self, L):
        rng = np.random.default_rng(L)
        blocks = rng.integers(0, 256, (5, L), dtype=np.uint8)
        got = np.asarray(crc32c_device(blocks))
        assert np.array_equal(got, crc32c_host_rows(blocks)), L

    def test_host_rows_is_the_utils_oracle(self):
        rng = np.random.default_rng(7)
        blocks = rng.integers(0, 256, (3, 200), dtype=np.uint8)
        want = [crc32c(row.tobytes()) for row in blocks]
        assert list(crc32c_host_rows(blocks)) == want

    def test_checksum_blocks_matches_host_below_and_above_threshold(self):
        rng = np.random.default_rng(11)
        small = [rng.bytes(100) for _ in range(3)]  # host loop
        assert checksum_blocks(small) == [crc32c(c) for c in small]
        # ragged population: three length groups, one above threshold
        big = [rng.bytes(4096) for _ in range(6)]
        mixed = big + [rng.bytes(1000), b"", rng.bytes(1000)]
        assert sum(len(c) for c in mixed) >= CSUM_OFFLOAD_MIN_BYTES
        assert checksum_blocks(mixed) == [crc32c(c) for c in mixed]

    def test_fault_injected_launch_falls_back_byte_identical(self):
        agg = ChecksumAggregator(window=4)
        rng = np.random.default_rng(13)
        blocks = rng.integers(0, 256, (4, 4096), dtype=np.uint8)
        global_injector().inject("codec.launch", 5, hits=1)
        fb0 = agg.perf.get("host_fallbacks")
        ticket = agg.submit_blocks(blocks)
        assert np.array_equal(
            np.asarray(ticket.result()), crc32c_host_rows(blocks)
        )
        assert agg.perf.get("host_fallbacks") == fb0 + 1
        assert device_guard().degraded  # the failed launch marked it

    def test_degraded_bypass_stays_byte_identical(self):
        device_guard().configure(probe_interval_ms=10 * 60 * 1000)
        device_guard().mark_degraded("test: forced")
        try:
            rng = np.random.default_rng(17)
            chunks = [rng.bytes(4096) for _ in range(8)]
            assert checksum_blocks(chunks) == [crc32c(c) for c in chunks]
        finally:
            device_guard().mark_healthy()

    def test_matrix_cache_is_bounded(self):
        from ceph_tpu.ops import checksum_offload as co

        for L in range(1, 2 * co._MATRIX_CACHE_CAP):
            co._contribution_matrix(L)
            co._zero_const(L)
        assert len(co._HOST_MATRICES) <= co._MATRIX_CACHE_CAP
        assert len(co._CONSTS) <= co._MATRIX_CACHE_CAP
