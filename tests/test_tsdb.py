"""The mgr-resident time-series store (ISSUE 14, common/tsdb.py):
downsample correctness against a brute-force oracle, LRU cardinality-cap
eviction under daemon/client churn, bounded memory over a long synthetic
run, resolution selection, and runtime reconfiguration."""

import random

import pytest

from ceph_tpu.common.tsdb import (
    BYTES_PER_BUCKET,
    BYTES_PER_SERIES,
    TimeSeriesStore,
)


def _oracle_buckets(samples, width, agg):
    """Brute-force downsample: {bucket_start: aggregate} from raw
    (t, v) samples."""
    buckets = {}
    for t, v in samples:
        start = (t // width) * width
        buckets.setdefault(start, []).append(v)
    out = {}
    for start, vals in buckets.items():
        if agg == "min":
            out[start] = min(vals)
        elif agg == "max":
            out[start] = max(vals)
        elif agg == "last":
            out[start] = vals[-1]
        elif agg == "sum":
            out[start] = sum(vals)
        else:
            out[start] = sum(vals) / len(vals)
    return out


class TestDownsampleOracle:
    @pytest.mark.parametrize("agg", ["avg", "min", "max", "last", "sum"])
    def test_every_level_matches_brute_force(self, agg):
        """Raw samples folded into 1 s / 5 s / 25 s buckets must agree
        with the oracle at every level and aggregate."""
        rng = random.Random(0x14)
        widths = (1.0, 5.0, 25.0)
        slots = 4096  # roomy: no wraparound in this test
        store = TimeSeriesStore(max_series=4, slots=slots,
                                resolutions=widths)
        t0 = 1000.0
        samples = []
        t = t0
        for _ in range(500):
            t += rng.random() * 0.7
            v = rng.uniform(-5, 50)
            samples.append((t, v))
            store.append("f", {"daemon": "osd.0"}, t, v)
        span = t - t0 + 30
        for width in widths:
            oracle = _oracle_buckets(samples, width, agg)
            # query pinned to this level: window covers everything and
            # step == width returns the level's buckets re-folded 1:1
            q = store.query(
                "f", {"daemon": "osd.0"}, window=span, step=width,
                aggregate=agg, now=t,
            )
            got = {s: v for s, v in q["points"]}
            assert q["resolution"] <= width
            assert set(got) == set(oracle)
            for start in oracle:
                assert got[start] == pytest.approx(oracle[start]), (
                    width, start,
                )

    def test_bucket_dump_carries_all_aggregates(self):
        store = TimeSeriesStore(slots=8, resolutions=(10.0,))
        for i, v in enumerate([3.0, 1.0, 7.0, 5.0]):
            store.append("f", {}, 100.0 + i, v)
        for agg, want in (
            ("min", 1.0), ("max", 7.0), ("last", 5.0),
            ("sum", 16.0), ("avg", 4.0),
        ):
            assert store.window_value("f", {}, 50, 0, aggregate=agg,
                                      now=110.0) == pytest.approx(want)

    def test_out_of_order_sample_folds_instead_of_corrupting(self):
        store = TimeSeriesStore(slots=8, resolutions=(1.0,))
        store.append("f", {}, 105.0, 1.0)
        store.append("f", {}, 103.0, 9.0)  # clock-skewed report
        q = store.query("f", {}, window=100, now=106.0)
        starts = [s for s, _ in q["points"]]
        assert starts == sorted(starts)
        # ...and must not REWIND the newest-sample anchor: a
        # default-anchored query (now=None) still sees the t=105 data
        q = store.query("f", {}, window=2.0)
        assert any(s == 105.0 for s, _ in q["points"]), q


class TestCardinalityCap:
    def test_lru_eviction_under_daemon_churn(self):
        """Churned daemons (each restart a new label) must age out the
        way iostat expires idle clients: the store holds max_series,
        counts evictions, and keeps the most recently WRITTEN."""
        store = TimeSeriesStore(max_series=8, slots=16)
        for i in range(100):
            store.append("op_rate", {"daemon": f"osd.{i}"}, 1000.0 + i, 1.0)
        stats = store.stats()
        assert stats["series"] == 8
        assert stats["evictions"] == 92
        survivors = {s["labels"]["daemon"] for s in store.series_ls()}
        assert survivors == {f"osd.{i}" for i in range(92, 100)}

    def test_hot_series_survives_churn(self):
        """A continuously-written series must never be the LRU victim,
        whatever churn happens around it."""
        store = TimeSeriesStore(max_series=4, slots=16)
        for i in range(50):
            store.append("f", {"daemon": "osd.hot"}, 1000.0 + i, 1.0)
            store.append("f", {"daemon": f"client.{i}"}, 1000.0 + i, 1.0)
        names = {s["labels"]["daemon"] for s in store.series_ls()}
        assert "osd.hot" in names
        assert len(names) == 4

    def test_configure_shrink_evicts_immediately(self):
        store = TimeSeriesStore(max_series=16, slots=16)
        for i in range(10):
            store.append("f", {"daemon": f"osd.{i}"}, 1000.0 + i, 1.0)
        store.configure(max_series=3)
        assert store.stats()["series"] == 3
        assert store.stats()["evictions"] == 7


class TestBoundedMemory:
    def test_long_run_stays_inside_the_structural_bound(self):
        """100k appends into one series: retained buckets (and with
        them the byte estimate) must stay at the ring-geometry bound —
        levels x slots — however long the run."""
        slots = 32
        widths = (1.0, 10.0, 60.0)
        store = TimeSeriesStore(max_series=4, slots=slots,
                                resolutions=widths)
        t = 0.0
        for i in range(100_000):
            t += 0.25
            store.append("f", {"daemon": "osd.0"}, t, float(i % 97))
        stats = store.stats()
        bound = len(widths) * slots
        assert stats["points"] <= bound
        assert stats["bytes"] <= (
            stats["series"] * BYTES_PER_SERIES + bound * BYTES_PER_BUCKET
        )
        assert stats["appends"] == 100_000
        # the coarsest ring retains the longest history
        q = store.query("f", {"daemon": "osd.0"}, window=60.0 * slots,
                        now=t)
        assert q["resolution"] == 60.0
        assert len(q["points"]) <= slots
        # the inventory reports retention from the COARSEST ring: the
        # wrapped fine ring reaches back ~slots seconds, the 60 s ring
        # much further — `perf history ls` must not understate it
        row = next(s for s in store.series_ls()
                   if s["labels"] == {"daemon": "osd.0"})
        assert t - row["oldest_t"] > slots * 1.0

    def test_many_series_bound_scales_linearly(self):
        store = TimeSeriesStore(max_series=64, slots=8,
                                resolutions=(1.0, 10.0))
        for d in range(64):
            for i in range(1000):
                store.append("f", {"daemon": f"osd.{d}"},
                             1000.0 + i, 1.0)
        stats = store.stats()
        assert stats["points"] <= 64 * 2 * 8


class TestQuerySurface:
    def test_step_rebucketing(self):
        store = TimeSeriesStore(slots=64, resolutions=(1.0,))
        for i in range(20):
            store.append("f", {}, 100.0 + i, float(i))
        q = store.query("f", {}, window=20, step=5.0, aggregate="max",
                        now=119.0)
        # 1 s buckets folded into 5 s output points: max of each span
        got = {s: v for s, v in q["points"]}
        assert got[100.0] == 4.0
        assert got[115.0] == 19.0

    def test_unknown_series_returns_empty(self):
        store = TimeSeriesStore()
        q = store.query("nope", {"daemon": "osd.9"})
        assert q["points"] == []
        assert q["resolution"] is None
        assert store.window_value("nope", {}, 10, 0) is None

    def test_bad_aggregate_rejected(self):
        store = TimeSeriesStore()
        with pytest.raises(ValueError):
            store.query("f", {}, aggregate="p99")

    def test_young_series_prefers_finest_resolution(self):
        """A series younger than the window must answer at the finest
        resolution (every level holds the same since-birth span), not
        fall back to an artificially coarse view."""
        store = TimeSeriesStore(slots=16, resolutions=(1.0, 60.0))
        for i in range(4):
            store.append("f", {}, 100.0 + i, float(i))
        q = store.query("f", {}, window=3600.0, now=104.0)
        assert q["resolution"] == 1.0
        assert len(q["points"]) == 4

    def test_geometry_change_restarts_history(self):
        store = TimeSeriesStore(slots=8, resolutions=(1.0,))
        store.append("f", {}, 100.0, 1.0)
        store.configure(resolutions="2,20")
        assert store.stats()["series"] == 0
        assert store.resolutions == (2.0, 20.0)
        store.append("f", {}, 100.0, 1.0)
        assert store.query("f", {}, window=10, now=101.0)["resolution"] == 2.0
