"""Swift API tests (rgw_swift coverage): TempAuth tokens, account/
container/object round trips, metadata headers, JSON listings — over the
same gateway the S3 personality uses."""

import asyncio
import json
import urllib.error
import urllib.request

import pytest

from ceph_tpu.rgw import ObjectGateway, SwiftServer

from test_access_layers import make_client
from test_cluster import stop_cluster


def _req(base, method, path, data=None, headers=None):
    r = urllib.request.Request(
        base + path, data=data, method=method, headers=headers or {}
    )
    return urllib.request.urlopen(r, timeout=5)


class TestSwiftApi:
    def test_full_swift_round_trip(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_client("swiftp")
            gw = ObjectGateway(ioctx)
            user = await gw.create_user("acct", "Swift Account")
            server = SwiftServer(gw)
            base = f"http://{await server.serve()}"
            loop = asyncio.get_event_loop()

            def call(method, path, data=None, headers=None):
                return loop.run_in_executor(
                    None, lambda: _req(base, method, path, data, headers)
                )

            # --- TempAuth: bad key 401, good key mints a token
            bad = False
            try:
                await call("GET", "/auth/v1.0", headers={
                    "X-Auth-User": "acct:swift", "X-Auth-Key": "wrong"})
            except urllib.error.HTTPError as e:
                bad = e.code == 401
            assert bad
            auth = await call("GET", "/auth/v1.0", headers={
                "X-Auth-User": "acct:swift",
                "X-Auth-Key": user["secret_key"]})
            token = auth.headers["X-Auth-Token"]
            assert token and auth.headers["X-Storage-Url"].endswith("AUTH_acct")
            tok = {"X-Auth-Token": token}

            # --- tokenless requests are rejected
            denied = False
            try:
                await call("GET", "/v1/AUTH_acct")
            except urllib.error.HTTPError as e:
                denied = e.code == 401
            assert denied

            # --- container lifecycle
            assert (await call("PUT", "/v1/AUTH_acct/photos", headers=tok)).status == 201
            assert (await call("PUT", "/v1/AUTH_acct/photos", headers=tok)).status == 202
            acct = await call("GET", "/v1/AUTH_acct?format=json", headers=tok)
            assert [c["name"] for c in json.loads(acct.read())] == ["photos"]

            # --- object with metadata
            put = await call(
                "PUT", "/v1/AUTH_acct/photos/cat.jpg", data=b"meow bytes",
                headers={**tok, "X-Object-Meta-Kind": "feline"})
            assert put.status == 201 and put.headers["ETag"]
            got = await call("GET", "/v1/AUTH_acct/photos/cat.jpg", headers=tok)
            assert got.read() == b"meow bytes"
            assert got.headers["X-Object-Meta-Kind"] == "feline"
            head = await call("HEAD", "/v1/AUTH_acct/photos/cat.jpg", headers=tok)
            assert head.headers["Content-Length"] == "10"

            # --- listings: plain + json + prefix
            await call("PUT", "/v1/AUTH_acct/photos/dog.jpg", data=b"woof",
                       headers=tok)
            plain = await call("GET", "/v1/AUTH_acct/photos", headers=tok)
            assert plain.read() == b"cat.jpg\ndog.jpg\n"
            js = await call(
                "GET", "/v1/AUTH_acct/photos?format=json&prefix=cat",
                headers=tok)
            rows = json.loads(js.read())
            assert [r["name"] for r in rows] == ["cat.jpg"]
            assert rows[0]["bytes"] == 10

            # --- delete semantics: non-empty container 409, then clean up
            conflict = False
            try:
                await call("DELETE", "/v1/AUTH_acct/photos", headers=tok)
            except urllib.error.HTTPError as e:
                conflict = e.code == 409
            assert conflict
            for o in ("cat.jpg", "dog.jpg"):
                assert (
                    await call("DELETE", f"/v1/AUTH_acct/photos/{o}", headers=tok)
                ).status == 204
            assert (await call("DELETE", "/v1/AUTH_acct/photos", headers=tok)).status == 204
            missing = False
            try:
                await call("GET", "/v1/AUTH_acct/photos/cat.jpg", headers=tok)
            except urllib.error.HTTPError as e:
                missing = e.code == 404
            assert missing

            await server.shutdown()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_s3_and_swift_share_one_gateway(self):
        """rgw's dual-personality model: an object PUT via S3 is readable
        via Swift and vice versa (same RGWRados core)."""

        async def run():
            from ceph_tpu.rgw import S3Server

            monmap, mons, osds, client, ioctx = await make_client("dualp")
            gw = ObjectGateway(ioctx)
            user = await gw.create_user("acct")
            s3 = S3Server(gw)
            swift = SwiftServer(gw)
            s3_base = f"http://{await s3.serve()}"
            sw_base = f"http://{await swift.serve()}"
            loop = asyncio.get_event_loop()

            auth = await loop.run_in_executor(None, lambda: _req(
                sw_base, "GET", "/auth/v1.0", None,
                {"X-Auth-User": "acct:swift",
                 "X-Auth-Key": user["secret_key"]}))
            tok = {"X-Auth-Token": auth.headers["X-Auth-Token"]}

            # S3 PUT -> Swift GET
            await loop.run_in_executor(
                None, lambda: _req(s3_base, "PUT", "/shared"))
            await loop.run_in_executor(
                None, lambda: _req(s3_base, "PUT", "/shared/obj", b"cross-api"))
            got = await loop.run_in_executor(None, lambda: _req(
                sw_base, "GET", "/v1/AUTH_acct/shared/obj", None, tok))
            assert got.read() == b"cross-api"

            # Swift PUT -> S3 GET
            await loop.run_in_executor(None, lambda: _req(
                sw_base, "PUT", "/v1/AUTH_acct/shared/back", b"returned", tok))
            got = await loop.run_in_executor(
                None, lambda: _req(s3_base, "GET", "/shared/back"))
            assert got.read() == b"returned"

            for s in (s3, swift):
                await s.shutdown()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestSwiftContainerAcls:
    def test_cross_account_acls(self):
        """X-Container-Read ACLs (rgw_swift read/write ACL model): a
        second account is denied until the owner grants read — and still
        cannot write; .r:* grants the world read."""

        async def run():
            monmap, mons, osds, client, ioctx = await make_client("swacl")
            gw = ObjectGateway(ioctx)
            alice = await gw.create_user("alice")
            bob = await gw.create_user("bob")
            server = SwiftServer(gw)
            base = f"http://{await server.serve()}"
            loop = asyncio.get_event_loop()

            def call(method, path, data=None, headers=None):
                return loop.run_in_executor(
                    None, lambda: _req(base, method, path, data, headers)
                )

            async def token(user, uid):
                auth = await call("GET", "/auth/v1.0", headers={
                    "X-Auth-User": f"{uid}:swift",
                    "X-Auth-Key": user["secret_key"]})
                return {"X-Auth-Token": auth.headers["X-Auth-Token"]}

            ta, tb = await token(alice, "alice"), await token(bob, "bob")
            assert (
                await call("PUT", "/v1/AUTH_alice/priv", headers=ta)
            ).status == 201
            assert (
                await call("PUT", "/v1/AUTH_alice/priv/o", b"secret", headers=ta)
            ).status == 201
            # bob (cross-account) is denied read and write
            for method, path, data in (
                ("GET", "/v1/AUTH_alice/priv/o", None),
                ("PUT", "/v1/AUTH_alice/priv/mine", b"x"),
                ("GET", "/v1/AUTH_alice/priv", None),
            ):
                try:
                    await call(method, path, data, headers=tb)
                    raise AssertionError(f"bob {method} {path} allowed")
                except urllib.error.HTTPError as e:
                    assert e.code == 403, (method, path)
            # the owner grants bob read via POST X-Container-Read
            assert (
                await call("POST", "/v1/AUTH_alice/priv",
                           headers={**ta, "X-Container-Read": "bob"})
            ).status == 204
            got = await call("GET", "/v1/AUTH_alice/priv/o", headers=tb)
            assert got.read() == b"secret"
            # ...but not write
            try:
                await call("PUT", "/v1/AUTH_alice/priv/mine", b"x", headers=tb)
                raise AssertionError("read grant allowed a write")
            except urllib.error.HTTPError as e:
                assert e.code == 403
            # a WRITE-ONLY grant (drop box) must not disclose reads
            assert (
                await call("POST", "/v1/AUTH_alice/priv",
                           headers={**ta, "X-Container-Read": "",
                                    "X-Container-Write": "bob"})
            ).status == 204
            assert (
                await call("PUT", "/v1/AUTH_alice/priv/drop", b"d", headers=tb)
            ).status == 201
            try:
                await call("GET", "/v1/AUTH_alice/priv/o", headers=tb)
                raise AssertionError("write-only grant disclosed a read")
            except urllib.error.HTTPError as e:
                assert e.code == 403
            # referrer tokens are read-only: .r:* in the WRITE header -> 400
            try:
                await call("POST", "/v1/AUTH_alice/priv",
                           headers={**ta, "X-Container-Write": ".r:*"})
                raise AssertionError("world-WRITE accepted")
            except urllib.error.HTTPError as e:
                assert e.code == 400
            # bob cannot create containers under alice's account URL
            try:
                await call("PUT", "/v1/AUTH_alice/squat", headers=tb)
                raise AssertionError("cross-account container create allowed")
            except urllib.error.HTTPError as e:
                assert e.code == 403
            # .r:* at create time = world-readable container, even
            # ANONYMOUSLY (no token at all)
            assert (
                await call("PUT", "/v1/AUTH_alice/pub",
                           headers={**ta, "X-Container-Read": ".r:*"})
            ).status == 201
            await call("PUT", "/v1/AUTH_alice/pub/p", b"open", headers=ta)
            got = await call("GET", "/v1/AUTH_alice/pub/p", headers=tb)
            assert got.read() == b"open"
            got = await call("GET", "/v1/AUTH_alice/pub/p")  # tokenless
            assert got.read() == b"open"
            # ...but anonymous writes still need a token
            try:
                await call("PUT", "/v1/AUTH_alice/pub/w", b"x")
                raise AssertionError("anonymous write accepted")
            except urllib.error.HTTPError as e:
                assert e.code == 401
            await server.shutdown()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())
