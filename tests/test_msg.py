"""Messenger tests: frames, typed messages, loopback dispatch, policies,
fault injection.

Modeled on src/test/msgr/test_msgr.cc (SimpleMessenger/AsyncMessenger
exchange tests) and the frames_v2 unit tests (src/test/msgr/test_frames_v2.cc).
"""

import asyncio

import pytest

from ceph_tpu.msg import Dispatcher, Messenger, Policy
from ceph_tpu.msg.frames import (
    Frame,
    FrameError,
    TAG_MESSAGE,
    preamble_info,
    PREAMBLE_SIZE,
)
from ceph_tpu.msg.message import decode_message, encode_message
from ceph_tpu.msg.messages import (
    MOSDECSubOpRead,
    MOSDECSubOpReadReply,
    MOSDOp,
    MOSDPing,
    MPing,
    OSDOp,
    PgId,
    ReqId,
)


# --- frames ------------------------------------------------------------------


class TestFrames:
    def test_pack_and_parse_preamble(self):
        f = Frame(TAG_MESSAGE, [b"header", b"payload-bytes"])
        wire = f.pack()
        tag, flags, lens = preamble_info(wire[:PREAMBLE_SIZE])
        assert tag == TAG_MESSAGE
        assert lens == [6, 13]

    def test_corrupt_preamble_detected(self):
        wire = bytearray(Frame(TAG_MESSAGE, [b"x"]).pack())
        wire[3] ^= 0xFF
        with pytest.raises(FrameError):
            preamble_info(bytes(wire[:PREAMBLE_SIZE]))

    def test_corrupt_segment_detected(self):
        async def run():
            wire = bytearray(Frame(TAG_MESSAGE, [b"header", b"payload"]).pack())
            wire[PREAMBLE_SIZE + 2] ^= 0x01  # flip a bit in segment 0
            reader = asyncio.StreamReader()
            reader.feed_data(bytes(wire))
            reader.feed_eof()
            from ceph_tpu.msg.frames import read_frame

            with pytest.raises(FrameError, match="crc mismatch"):
                await read_frame(reader)

        asyncio.run(run())


# --- message codec -----------------------------------------------------------


class TestMessages:
    def test_mosdop_roundtrip(self):
        msg = MOSDOp(
            reqid=ReqId("client.1", 42),
            pgid=PgId(3, 7, -1),
            oid="obj-1",
            ops=[
                OSDOp(OSDOp.WRITE, off=4096, len=3, data=b"abc"),
                OSDOp(OSDOp.READ, off=0, len=100),
            ],
            epoch=9,
        )
        msg.src = "client.1"
        msg.seq = 5
        env, payload = encode_message(msg)
        back = decode_message(env, payload)
        assert isinstance(back, MOSDOp)
        assert back.src == "client.1" and back.seq == 5
        assert back.reqid.key() == ("client.1", 42)
        assert back.pgid == PgId(3, 7, -1)
        assert back.ops[0].data == b"abc"
        assert back.ops[1].op == OSDOp.READ

    def test_ec_subread_roundtrip(self):
        msg = MOSDECSubOpRead(
            pgid=PgId(1, 2, 4),
            from_osd=0,
            tid=77,
            to_read={"o1": [[0, 4096], [8192, 4096]]},
            subchunks={"o1": [[0, 2]]},
            attrs_to_read=["hinfo_key"],
        )
        env, payload = encode_message(msg)
        back = decode_message(env, payload)
        assert back.to_read["o1"][1] == [8192, 4096]
        assert back.subchunks["o1"] == [[0, 2]]

    def test_reply_with_buffers(self):
        msg = MOSDECSubOpReadReply(
            pgid=PgId(1, 2, 0),
            from_osd=3,
            tid=1,
            buffers={"o1": [[0, b"\x00" * 16]]},
            attrs={"o1": {"hinfo_key": b"hi"}},
            errors={"o2": -5},
        )
        env, payload = encode_message(msg)
        back = decode_message(env, payload)
        assert back.buffers["o1"][0][1] == b"\x00" * 16
        assert back.errors["o2"] == -5


# --- messenger loopback ------------------------------------------------------


class Collector(Dispatcher):
    def __init__(self, fast_types=()):
        self.messages = []
        self.fast = []
        self.resets = 0
        self.fast_types = fast_types
        self.got = asyncio.Event()

    def ms_can_fast_dispatch(self, msg):
        return isinstance(msg, self.fast_types)

    def ms_fast_dispatch(self, conn, msg):
        self.fast.append(msg)
        self.got.set()

    def ms_dispatch(self, conn, msg):
        self.messages.append((conn, msg))
        self.got.set()
        return True

    def ms_handle_reset(self, conn):
        self.resets += 1


async def make_pair(**server_kw):
    server = Messenger("osd.0", **server_kw)
    coll = Collector(fast_types=(MOSDPing,))
    server.add_dispatcher_tail(coll)
    await server.bind("127.0.0.1:0")
    client = Messenger("client.1")
    return server, coll, client


class TestMessenger:
    def test_send_and_dispatch(self):
        async def run():
            server, coll, client = await make_pair()
            await client.send_to(server.addr, MPing(stamp=1.5))
            await asyncio.wait_for(coll.got.wait(), 5)
            conn, msg = coll.messages[0]
            assert isinstance(msg, MPing) and msg.stamp == 1.5
            assert msg.src == "client.1"
            assert conn.peer_name == "client.1"
            await client.shutdown()
            await server.shutdown()

        asyncio.run(run())

    def test_fast_dispatch_path(self):
        async def run():
            server, coll, client = await make_pair()
            await client.send_to(
                server.addr, MOSDPing(op=MOSDPing.PING, stamp=0.0, epoch=1, from_osd=4)
            )
            await asyncio.wait_for(coll.got.wait(), 5)
            assert len(coll.fast) == 1 and not coll.messages
            await client.shutdown()
            await server.shutdown()

        asyncio.run(run())

    def test_bidirectional_over_accepted_conn(self):
        # The primary "replies" over the accepted connection — the pattern
        # every sub-op reply uses.
        async def run():
            server, coll, client = await make_pair()
            client_coll = Collector()
            client.add_dispatcher_tail(client_coll)
            await client.send_to(server.addr, MPing(stamp=1.0))
            await asyncio.wait_for(coll.got.wait(), 5)
            conn, _ = coll.messages[0]
            await conn.send_message(MPing(stamp=2.0))
            await asyncio.wait_for(client_coll.got.wait(), 5)
            _, reply = client_coll.messages[0]
            assert reply.stamp == 2.0 and reply.src == "osd.0"
            await client.shutdown()
            await server.shutdown()

        asyncio.run(run())

    def test_seq_numbers_increase(self):
        async def run():
            server, coll, client = await make_pair()
            for i in range(3):
                coll.got.clear()
                await client.send_to(server.addr, MPing(stamp=float(i)))
                await asyncio.wait_for(coll.got.wait(), 5)
            seqs = [m.seq for _, m in coll.messages]
            assert seqs == [1, 2, 3]
            await client.shutdown()
            await server.shutdown()

        asyncio.run(run())

    def test_lossless_reconnects_after_server_restart(self):
        async def run():
            server, coll, client = await make_pair()
            addr = server.addr
            conn = client.get_connection(addr, Policy.lossless_peer())
            await conn.send_message(MPing(stamp=1.0))
            await asyncio.wait_for(coll.got.wait(), 5)
            # kill and rebind the server on the same port
            await server.shutdown()
            server2 = Messenger("osd.0")
            coll2 = Collector()
            server2.add_dispatcher_tail(coll2)
            await server2.bind(addr)
            # allow the client read loop to observe the reset
            await asyncio.sleep(0.1)
            await conn.send_message(MPing(stamp=2.0))
            await asyncio.wait_for(coll2.got.wait(), 5)
            assert coll2.messages[0][1].stamp == 2.0
            await client.shutdown()
            await server2.shutdown()

        asyncio.run(run())

    def test_injected_socket_failures_surface_as_connection_errors(self):
        # Lossy policy: injected faults surface to the caller (lossless
        # connections now transparently resend instead — covered by
        # TestLosslessResend).
        async def run():
            server, coll, client = await make_pair()
            client.inject_socket_failures = 2  # 1-in-2 sends fail
            failures = 0
            for i in range(20):
                try:
                    conn = client.get_connection(server.addr, Policy.lossy_client())
                    await conn.send_message(MPing(stamp=float(i)))
                except ConnectionError:
                    failures += 1
            assert failures > 2
            await client.shutdown()
            await server.shutdown()

        asyncio.run(run())

    def test_lossless_resend_no_loss_no_dup_under_probabilistic_faults(self):
        """ISSUE 7 satellite contract: with the `msgr.send` faultpoint
        armed probabilistically (ms_inject_socket_failures semantics), a
        lossless connection transparently reconnects and resends — across
        N forced reconnects no message is lost and none is duplicated
        (the injection fires before any bytes hit the wire)."""

        async def run():
            from ceph_tpu.common.fault_injector import global_injector

            server, coll, client = await make_pair()
            conn = client.get_connection(server.addr, Policy.lossless_peer())
            global_injector().inject_probabilistic("msgr.send", 3)
            try:
                for i in range(40):
                    coll.got.clear()
                    await conn.send_message(MPing(stamp=float(i)))
            finally:
                global_injector().clear("msgr.send")

            def all_delivered():
                return len(coll.messages) >= 40

            deadline = asyncio.get_event_loop().time() + 5.0
            while not all_delivered():
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.02)
            stamps = [m.stamp for _, m in coll.messages]
            assert sorted(stamps) == [float(i) for i in range(40)]  # no loss
            assert len(stamps) == len(set(stamps)) == 40  # no duplicates
            # the faults actually forced reconnect+resend cycles
            assert client.resends > 0
            await client.shutdown()
            await server.shutdown()

        asyncio.run(run())

    def test_lossy_connection_stays_dead(self):
        async def run():
            server, coll, client = await make_pair()
            conn = client.get_connection(server.addr, Policy.lossy_client())
            await conn.send_message(MPing(stamp=1.0))
            await conn.close()
            with pytest.raises(ConnectionError):
                await conn.send_message(MPing(stamp=2.0))
            # but the messenger hands out a fresh connection
            conn2 = client.get_connection(server.addr)
            assert conn2 is not conn
            await client.shutdown()
            await server.shutdown()

        asyncio.run(run())
