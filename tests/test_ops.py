"""Device ops tests: jnp XOR-matmul path and Pallas kernel (interpret mode)."""

import numpy as np
import pytest

from ceph_tpu.gf import (
    expand_matrix,
    gf_matmul,
    isa_cauchy_matrix,
    isa_decode_matrix,
    isa_rs_vandermonde_matrix,
)
from ceph_tpu.ops.pallas_gf import CodingPlan, pick_geometry, schedule_from_matrix
from ceph_tpu.ops.xor_mm import xor_matmul, xor_reduce


def test_xor_matmul_matches_gf():
    rng = np.random.default_rng(0)
    for k, m in [(4, 2), (8, 3)]:
        mat = isa_cauchy_matrix(k, m)[k:]
        bm = expand_matrix(mat)
        data = rng.integers(0, 256, (k, 256)).astype(np.uint8)
        out = np.asarray(xor_matmul(bm, data))
        assert np.array_equal(out, gf_matmul(mat, data))


def test_xor_matmul_batched():
    rng = np.random.default_rng(1)
    k, m = 8, 3
    mat = isa_rs_vandermonde_matrix(k, m)[k:]
    bm = expand_matrix(mat)
    data = rng.integers(0, 256, (4, k, 128)).astype(np.uint8)
    out = np.asarray(xor_matmul(bm, data))
    for s in range(4):
        assert np.array_equal(out[s], gf_matmul(mat, data[s]))


def test_xor_reduce():
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, (5, 64)).astype(np.uint8)
    assert np.array_equal(
        np.asarray(xor_reduce(data)), np.bitwise_xor.reduce(data, axis=0)
    )
    batched = rng.integers(0, 256, (3, 5, 64)).astype(np.uint8)
    assert np.array_equal(
        np.asarray(xor_reduce(batched)), np.bitwise_xor.reduce(batched, axis=1)
    )


def test_pick_geometry():
    # every multiple of 128 has a tile; rows % 4 == 0 always
    for L in (128, 256, 384, 512, 2048, 4096, 128 * 1024, 1 << 20, 3 * 128):
        geom = pick_geometry(L)
        assert geom is not None, L
        rows, cols = geom
        assert rows % 4 == 0 and L % (rows * cols) == 0
    assert pick_geometry(128 * 1024) == (128, 256)  # full-size lane tiles
    assert pick_geometry(100) is None  # not 128-aligned -> jnp fallback


class TestPallasInterpret:
    """Pallas kernel in interpreter mode (runs on CPU; exact same program)."""

    def test_encode_matches_gf(self):
        rng = np.random.default_rng(3)
        k, m = 8, 3
        mat = isa_rs_vandermonde_matrix(k, m)[k:]
        plan = CodingPlan(mat, interpret=True)
        data = rng.integers(0, 256, (2, k, 256)).astype(np.uint8)
        out = np.asarray(plan(data))
        for s in range(2):
            assert np.array_equal(out[s], gf_matmul(mat, data[s]))

    def test_decode_matrix_roundtrip(self):
        rng = np.random.default_rng(4)
        k, m = 8, 3
        coeff = isa_cauchy_matrix(k, m)
        data = rng.integers(0, 256, (1, k, 128)).astype(np.uint8)
        full = np.stack([gf_matmul(coeff, data[s]) for s in range(1)])
        erasures = [0, 9]
        plan_info = isa_decode_matrix(coeff, erasures, k)
        assert plan_info is not None
        c, idx = plan_info
        dec_plan = CodingPlan(c, interpret=True)
        rebuilt = np.asarray(dec_plan(full[:, idx, :]))
        assert np.array_equal(rebuilt, full[:, erasures, :])

    def test_many_rows(self):
        # m > 8 runs as one dense matmul (no row-group splitting needed).
        rng = np.random.default_rng(5)
        k, m = 4, 10
        mat = rng.integers(0, 256, (m, k)).astype(np.uint8)
        plan = CodingPlan(mat, interpret=True)
        assert plan.bm.shape == (8 * m, 8 * k)
        data = rng.integers(0, 256, (1, k, 128)).astype(np.uint8)
        out = np.asarray(plan(data))
        assert np.array_equal(out[0], gf_matmul(mat, data[0]))

    def test_odd_k(self):
        # k not a multiple of 8: concat pieces are partial sublane tiles.
        rng = np.random.default_rng(6)
        k, m = 5, 3
        mat = rng.integers(0, 256, (m, k)).astype(np.uint8)
        plan = CodingPlan(mat, interpret=True)
        data = rng.integers(0, 256, (2, k, 256)).astype(np.uint8)
        out = np.asarray(plan(data))
        for s in range(2):
            assert np.array_equal(out[s], gf_matmul(mat, data[s]))


def test_schedule_from_matrix_layout():
    mat = isa_cauchy_matrix(4, 2)[4:]
    sched = schedule_from_matrix(mat)
    plain = expand_matrix(mat)
    m, k = mat.shape
    assert len(sched) == 8 * m  # one term list per output bit-row
    for o, row in enumerate(sched):
        want = [(c // 8, c % 8) for c in range(8 * k) if plain[o, c]]
        assert list(row) == want
