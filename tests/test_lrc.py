"""LRC tests — kml expansion, layered encode/decode, locality-aware minimums.

Models /root/reference/src/test/erasure-code/TestErasureCodeLrc.cc.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.codec.interface import EcError
from ceph_tpu.codec.lrc import ErasureCodeLrc
from ceph_tpu.codec.registry import ErasureCodePluginRegistry


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n).astype(np.uint8).tobytes()


def make_kml(k=4, m=2, l=3):
    ec = ErasureCodeLrc()
    ec.init({"k": str(k), "m": str(m), "l": str(l)})
    return ec


class TestKml:
    def test_kml_expansion_geometry(self):
        ec = make_kml(4, 2, 3)
        # groups=(k+m)/l=2, chunk per group = l+1 -> 8 chunks, 4 data.
        assert ec.get_chunk_count() == 8
        assert ec.get_data_chunk_count() == 4
        assert len(ec.layers) == 3  # 1 global + 2 local
        assert ec.layers[0].chunks_map == "DDc_DDc_"
        assert ec.layers[1].chunks_map == "DDDc____"
        assert ec.layers[2].chunks_map == "____DDDc"

    def test_kml_validation(self):
        with pytest.raises(EcError):
            make_kml(4, 2, 4)  # k+m not multiple of l
        with pytest.raises(EcError):
            ErasureCodeLrc().init({"k": "4", "m": "2"})  # l missing
        with pytest.raises(EcError):
            ErasureCodeLrc().init({"k": "4", "m": "2", "l": "3", "mapping": "x"})

    def test_kml_hides_generated_params(self):
        ec = make_kml()
        assert "mapping" not in ec.get_profile()
        assert "layers" not in ec.get_profile()


class TestRoundtrip:
    def test_all_single_and_double_erasures(self):
        ec = make_kml(4, 2, 3)
        n = ec.get_chunk_count()
        raw = payload(4 * 128 + 5)
        encoded = ec.encode(set(range(n)), raw)
        # Every single erasure must be locally repairable.
        for e in range(n):
            avail = {i: encoded[i] for i in range(n) if i != e}
            decoded = ec.decode({e}, avail)
            assert np.array_equal(decoded[e], encoded[e]), e
        # Double erasures: all pairs are recoverable for this profile.
        for pair in itertools.combinations(range(n), 2):
            avail = {i: encoded[i] for i in range(n) if i not in pair}
            decoded = ec.decode(set(pair), avail)
            for e in pair:
                assert np.array_equal(decoded[e], encoded[e]), pair

    def test_decode_concat(self):
        ec = make_kml(4, 2, 3)
        raw = payload(4 * 256, seed=3)
        n = ec.get_chunk_count()
        encoded = ec.encode(set(range(n)), raw)
        avail = {i: encoded[i] for i in range(n) if i not in (0, 5)}
        out = ec.decode_concat(avail)
        assert out[: len(raw)].tobytes() == raw

    def test_explicit_layers_profile(self):
        ec = ErasureCodeLrc()
        ec.init(
            {
                "mapping": "DD__DD__",
                "layers": (
                    '[ [ "DDc_DDc_", "" ],'
                    '  [ "DDDc____", "" ],'
                    '  [ "____DDDc", "" ] ]'
                ),
            }
        )
        assert ec.get_chunk_count() == 8
        assert ec.get_data_chunk_count() == 4
        raw = payload(4 * 128, seed=4)
        encoded = ec.encode(set(range(8)), raw)
        avail = {i: encoded[i] for i in range(8) if i not in (1, 6)}
        decoded = ec.decode({1, 6}, avail)
        assert np.array_equal(decoded[1], encoded[1])
        assert np.array_equal(decoded[6], encoded[6])

    def test_layer_profile_with_plugin_spec(self):
        ec = ErasureCodeLrc()
        ec.init(
            {
                "mapping": "DD__DD__",
                "layers": (
                    '[ [ "DDc_DDc_", "plugin=tpu technique=cauchy" ],'
                    '  [ "DDDc____", "" ],'
                    '  [ "____DDDc", "" ] ]'
                ),
            }
        )
        raw = payload(4 * 128, seed=5)
        encoded = ec.encode(set(range(8)), raw)
        avail = {i: encoded[i] for i in range(8) if i != 4}
        decoded = ec.decode({4}, avail)
        assert np.array_equal(decoded[4], encoded[4])


class TestLocality:
    def test_local_repair_reads_fewer_chunks(self):
        ec = make_kml(4, 2, 3)
        n = ec.get_chunk_count()
        # chunk 0 lost: the local layer (DDDc____) covers it with chunks
        # {0,1,2,3}; minimum must avoid the other group entirely.
        available = set(range(n)) - {0}
        minimum = ec.minimum_to_decode({0}, available)
        assert set(minimum) <= {1, 2, 3}, minimum
        # Compare: a global-only code would need k=4 chunks across groups.

    def test_want_available_reads_want_only(self):
        ec = make_kml(4, 2, 3)
        minimum = ec.minimum_to_decode({1, 5}, set(range(8)))
        assert set(minimum) == {1, 5}

    def test_undecodable_raises_eio(self):
        ec = make_kml(4, 2, 3)
        # Lose an entire local group (4 chunks) — unrecoverable.
        available = {4, 5, 6, 7}
        with pytest.raises(EcError):
            ec.minimum_to_decode({0}, available)


def test_plugin_registration():
    r = ErasureCodePluginRegistry()
    ec = r.factory("lrc", {"k": "4", "m": "2", "l": "3"})
    assert ec.get_chunk_count() == 8
