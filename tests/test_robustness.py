"""ISSUE 7 tentpole contracts: the device-launch watchdog + host
fallback, degraded-state health plumbing, and aggregator backpressure.

Acceptance shape: with `codec.launch` armed to fail (or the dispatch
wedged past the deadline), writes and recoveries complete BYTE-IDENTICAL
via the host oracle, the backend marks DEGRADED (gauge + health check),
and a probe self-heals it back to device dispatch."""

import itertools
import time

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import DecodeAggregator, EncodeAggregator
from ceph_tpu.common.fault_injector import global_injector
from ceph_tpu.ops import dispatch as ec_dispatch
from ceph_tpu.ops.guard import DeviceGuard, DeviceTimeout, device_guard
from ceph_tpu.stripe import StripeInfo
from ceph_tpu.stripe import stripe as stripe_mod


@pytest.fixture(autouse=True)
def _clean_guard_and_injector():
    """Guard state and the process-global injector must never leak
    across tests: a stray DEGRADED flag would silently reroute every
    later launch through the host path."""
    yield
    global_injector().clear()
    device_guard().mark_healthy()
    device_guard().configure(timeout_ms=20000, probe_interval_ms=2000)


def make_rs(k=4, m=2):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


def payload(sinfo, stripes, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, stripes * sinfo.stripe_width, dtype=np.uint8)


class TestHostOracle:
    """encode_array_host / decode_array_host are byte-identical to the
    device dispatch — the precondition for transparent fallback."""

    def test_encode_host_matches_device_rs42(self):
        ec = make_rs(4, 2)
        sinfo = StripeInfo(4 * 512, 512)
        data = payload(sinfo, 3, seed=1).reshape(3, 4, 512)
        dev = np.asarray(ec.encode_array(data))
        host = ec.encode_array_host(data)
        assert np.array_equal(dev, host)

    def test_encode_host_matches_device_xor_path(self):
        ec = make_rs(2, 1)  # m=1 all-ones row: the xor_reduce fast path
        sinfo = StripeInfo(2 * 512, 512)
        data = payload(sinfo, 2, seed=2).reshape(2, 2, 512)
        dev = np.asarray(ec.encode_array(data))
        host = ec.encode_array_host(data)
        assert np.array_equal(dev, host)

    def test_decode_host_matches_device_all_rs42_patterns(self):
        ec = make_rs(4, 2)
        sinfo = StripeInfo(4 * 512, 512)
        data = payload(sinfo, 2, seed=3).reshape(2, 4, 512)
        shards = np.concatenate(
            [data, np.asarray(ec.encode_array(data))], axis=1
        )  # (stripes, 6, 512)
        for r in (1, 2):
            for erasures in itertools.combinations(range(6), r):
                idx = ec.decode_index(list(erasures))
                survivors = shards[:, idx, :]
                dev = np.asarray(ec.decode_array(list(erasures), survivors))
                host = ec.decode_array_host(list(erasures), survivors)
                assert np.array_equal(dev, host), erasures


class TestLaunchFallback:
    """codec.launch armed to fail -> the aggregated launch completes on
    the host oracle, byte-identical, and the backend marks DEGRADED."""

    def setup_method(self):
        self.ec = make_rs(4, 2)
        self.sinfo = StripeInfo(4 * 4096, 4096)

    def test_encode_fallback_byte_identical_and_degraded(self):
        data = payload(self.sinfo, 2, seed=10)
        direct = stripe_mod.encode(self.sinfo, self.ec, data)
        before = ec_dispatch.FALLBACK_LAUNCHES.snapshot()["launches"]
        global_injector().inject("codec.launch", 5, hits=1)
        agg = EncodeAggregator(window=0)
        pend = stripe_mod.encode_launch(
            self.sinfo, self.ec, data, aggregator=agg
        )
        out = pend.result()
        for i in direct:
            assert np.array_equal(direct[i], out[i]), i
        assert (
            ec_dispatch.FALLBACK_LAUNCHES.snapshot()["launches"] == before + 1
        )
        assert device_guard().degraded
        assert agg.perf.get("host_fallbacks") == 1

    def test_decode_fallback_all_rs42_patterns_byte_identical(self):
        """The acceptance-criteria sweep: every RS(4,2) erasure pattern
        reconstructs byte-identically through the host-fallback path with
        codec.launch armed to fail."""
        data = payload(self.sinfo, 2, seed=11)
        shards = stripe_mod.encode(self.sinfo, self.ec, data)
        agg = DecodeAggregator(window=0)
        for r in (1, 2):
            for erasures in itertools.combinations(range(6), r):
                have = {
                    i: shards[i] for i in range(6) if i not in erasures
                }
                global_injector().inject("codec.launch", 5, hits=1)
                pend = stripe_mod.decode_shards_launch(
                    self.sinfo, self.ec, have, set(erasures), aggregator=agg
                )
                out = pend.result()
                for e in erasures:
                    assert np.array_equal(out[e], shards[e]), (erasures, e)
                device_guard().mark_healthy()
        assert agg.perf.get("host_fallbacks") == 21  # C(6,1)+C(6,2)

    def test_wedged_dispatch_times_out_to_fallback(self):
        """A dispatch that BLOCKS past ec_tpu_launch_timeout_ms (the
        round-4/5 hang shape) is abandoned by the watchdog and the launch
        completes on the host — in-flight writes no longer chain-stall
        behind a wedged backend."""
        data = payload(self.sinfo, 1, seed=12)
        direct = stripe_mod.encode(self.sinfo, self.ec, data)
        real = self.ec.encode_array

        def wedge(arr, out=None):
            time.sleep(0.5)  # well past the 50 ms deadline below
            return real(arr, out=out)

        device_guard().configure(timeout_ms=50)
        self.ec.encode_array = wedge
        try:
            agg = EncodeAggregator(window=0)
            pend = stripe_mod.encode_launch(
                self.sinfo, self.ec, data, aggregator=agg
            )
            out = pend.result()
        finally:
            self.ec.encode_array = real
        for i in direct:
            assert np.array_equal(direct[i], out[i]), i
        assert device_guard().degraded
        assert "deadline" in device_guard().reason

    def test_degraded_bypass_then_probe_self_heal(self):
        """While DEGRADED, launches bypass the device (no new device
        dispatches); once the probe interval elapses a successful probe
        heals the backend and dispatch returns to the device path."""
        data = payload(self.sinfo, 1, seed=13)
        agg = EncodeAggregator(window=0)
        device_guard().configure(probe_interval_ms=10_000_000)
        device_guard().mark_degraded("test")
        # burn the immediate post-degrade probe with a still-dead device,
        # so the long interval now gates re-probing
        assert not device_guard().maybe_probe(
            lambda: (_ for _ in ()).throw(RuntimeError("still dead"))
        )
        launches_before = ec_dispatch.LAUNCHES.snapshot()["launches"]
        pend = stripe_mod.encode_launch(
            self.sinfo, self.ec, data, aggregator=agg
        )
        pend.result()
        # bypass: no device dispatch happened
        assert (
            ec_dispatch.LAUNCHES.snapshot()["launches"] == launches_before
        )
        # shorten the interval: the next launch probes and self-heals
        device_guard().configure(probe_interval_ms=1)
        time.sleep(0.005)
        pend = stripe_mod.encode_launch(
            self.sinfo, self.ec, data, aggregator=agg
        )
        pend.result()
        assert not device_guard().degraded
        assert device_guard().probes >= 1
        assert (
            ec_dispatch.LAUNCHES.snapshot()["launches"] > launches_before
        )

    def test_perf_dump_exports_gauge_and_fallback_counters(self):
        dump = ec_dispatch.perf_dump()
        for key in (
            "backend_degraded",
            "backend_degraded_total",
            "backend_probes",
            "fallback_launches",
        ):
            assert key in dump, key
        device_guard().mark_degraded("gauge test")
        assert ec_dispatch.perf_dump()["backend_degraded"] == 1
        device_guard().mark_healthy()
        assert ec_dispatch.perf_dump()["backend_degraded"] == 0


class TestDeviceGuardUnit:
    def test_call_enforces_deadline(self):
        g = DeviceGuard(timeout_ms=50, probe_interval_ms=0)
        with pytest.raises(DeviceTimeout):
            g.call(lambda: time.sleep(1.0))

    def test_call_inline_when_disabled(self):
        g = DeviceGuard(timeout_ms=0, probe_interval_ms=0)
        assert g.call(lambda: 42) == 42

    def test_call_reraises_worker_exception(self):
        g = DeviceGuard(timeout_ms=1000, probe_interval_ms=0)
        with pytest.raises(RuntimeError, match="boom"):
            g.call(lambda: (_ for _ in ()).throw(RuntimeError("boom")))

    def test_probe_interval_gates_reprobes(self):
        g = DeviceGuard(timeout_ms=1000, probe_interval_ms=10_000_000)
        g.mark_degraded("x")
        # immediately after degrading, the first probe IS allowed (the
        # probe clock resets so a transient error heals fast)...
        assert g.maybe_probe(lambda: None)
        assert not g.degraded
        g.mark_degraded("y")
        g.maybe_probe(lambda: (_ for _ in ()).throw(RuntimeError("dead")))
        # ...but after a failed probe the interval gates the next one
        assert not g.maybe_probe(lambda: None)
        assert g.degraded
        assert g.probe_failures == 1

    def test_probe_disabled_means_sticky_degraded(self):
        g = DeviceGuard(timeout_ms=1000, probe_interval_ms=0)
        g.mark_degraded("x")
        assert not g.maybe_probe(lambda: None)
        assert g.degraded


class TestBackpressure:
    """ec_tpu_inflight_max_bytes bounds admitted-but-unsettled bytes:
    over the bound, submitters settle older launches first."""

    def setup_method(self):
        self.ec = make_rs(4, 2)
        self.sinfo = StripeInfo(4 * 4096, 4096)

    def test_admission_settles_older_groups(self):
        stripe_bytes = self.sinfo.stripe_width  # 16 KiB per submission
        agg = EncodeAggregator(
            window=64, inflight_max_bytes=2 * stripe_bytes
        )
        pends = [
            stripe_mod.encode_launch(
                self.sinfo, self.ec, payload(self.sinfo, 1, seed=i),
                aggregator=agg,
            )
            for i in range(6)
        ]
        # the throttle pushed back at least once and never let admitted
        # credit exceed the bound by more than one submission
        assert agg.perf.get("throttle_stalls") >= 1
        assert agg.inflight.current <= 3 * stripe_bytes
        oracle = [
            stripe_mod.encode(
                self.sinfo, self.ec, payload(self.sinfo, 1, seed=i)
            )
            for i in range(6)
        ]
        for pend, want in zip(pends, oracle):
            got = pend.result()
            for i in want:
                assert np.array_equal(want[i], got[i])
        # all credit returned once everything settled
        assert agg.inflight.current == 0

    def test_oversized_submission_is_admitted(self):
        agg = EncodeAggregator(window=0, inflight_max_bytes=1024)
        pend = stripe_mod.encode_launch(
            self.sinfo, self.ec, payload(self.sinfo, 4, seed=1),
            aggregator=agg,
        )
        pend.result()  # larger than the whole bound: must not wedge
        assert agg.inflight.current == 0

    def test_credit_released_on_sticky_failure(self):
        from ceph_tpu.codec.interface import EcError

        agg = EncodeAggregator(window=0, inflight_max_bytes=1 << 20)
        real, real_host = self.ec.encode_array, self.ec.encode_array_host

        def boom(*a, **kw):
            raise RuntimeError("both paths dead")

        self.ec.encode_array = boom
        self.ec.encode_array_host = boom
        try:
            pend = stripe_mod.encode_launch(
                self.sinfo, self.ec, payload(self.sinfo, 1, seed=2),
                aggregator=agg,
            )
            with pytest.raises(EcError):
                pend.result()
        finally:
            self.ec.encode_array = real
            self.ec.encode_array_host = real_host
        assert agg.inflight.current == 0  # failed groups leak no credit


class TestDegradedHealthPlumbing:
    """OSD status -> mgr digest -> mon HEALTH_WARN, and the mgr's own
    healthcheck gauge surface — built from one common/health.py shape."""

    def _mgr_with_degraded_osd(self):
        from ceph_tpu.mgr.mgr import DaemonState, Mgr
        from ceph_tpu.mon.monmap import MonMap

        mgr = Mgr("hx", MonMap(addrs={"a": "127.0.0.1:1"}))
        st = DaemonState()
        st.status = {
            "tpu_backend": {
                "degraded": True,
                "degraded_for_sec": 3.2,
                "reason": "encode launch failed: DeviceTimeout",
                "fallback_launches": 7,
            }
        }
        mgr.daemons["osd.0"] = st
        return mgr

    def test_mgr_health_check_and_digest_slice(self):
        mgr = self._mgr_with_degraded_osd()
        checks = mgr.health_checks()
        assert "TPU_BACKEND_DEGRADED" in checks
        assert checks["TPU_BACKEND_DEGRADED"]["severity"] == "HEALTH_WARN"
        assert "osd.0" in checks["TPU_BACKEND_DEGRADED"]["summary"]
        digest = mgr.pg_digest()
        assert digest["tpu_degraded"]["osd.0"]["fallback_launches"] == 7

    def test_mgr_check_clears_when_healthy(self):
        mgr = self._mgr_with_degraded_osd()
        mgr.daemons["osd.0"].status["tpu_backend"]["degraded"] = False
        assert "TPU_BACKEND_DEGRADED" not in mgr.health_checks()

    def test_mon_health_from_digest(self):
        from ceph_tpu.mon import MonMap, Monitor

        mon = Monitor("a", MonMap(addrs={"a": "127.0.0.1:1"}))
        mon.pg_digest = {
            "tpu_degraded": {
                "osd.1": {
                    "degraded_for_sec": 12.0,
                    "reason": "encode launch failed",
                    "fallback_launches": 3,
                }
            }
        }
        checks, details = mon.health_checks()
        assert "TPU_BACKEND_DEGRADED" in checks
        assert "osd.1" in checks["TPU_BACKEND_DEGRADED"]
        assert any("osd.1" in line for line in details["TPU_BACKEND_DEGRADED"])
        mon.pg_digest = {}
        checks, _ = mon.health_checks()
        assert "TPU_BACKEND_DEGRADED" not in checks

    def test_osd_status_carries_backend_verdict(self):
        from ceph_tpu.osd.osd import _tpu_backend_status

        device_guard().mark_degraded("status test")
        st = _tpu_backend_status()
        assert st["degraded"] is True
        assert st["reason"] == "status test"
        device_guard().mark_healthy()
        assert _tpu_backend_status()["degraded"] is False


class TestObjecterBackoff:
    """Resend pacing satellite: bounded exponential backoff + jitter,
    resends counted in a PerfCounter."""

    def _objecter(self):
        from ceph_tpu.client.objecter import Objecter
        from ceph_tpu.mon.monmap import MonMap

        return Objecter("client.bk", MonMap(addrs={"a": "127.0.0.1:1"}))

    def test_backoff_grows_and_caps(self):
        ob = self._objecter()
        delays = [ob._backoff_delay(a) for a in range(12)]
        # jittered into [0.5, 1.0) of the nominal value, capped at ~1 s
        assert 0.025 <= delays[0] < 0.05
        assert all(d <= 1.0 for d in delays)
        assert delays[10] >= 0.5  # capped region: still >= cap * 0.5
        # nominal (de-jittered) schedule is monotone non-decreasing
        noms = [min(1.0, 0.05 * (1 << min(a, 16))) for a in range(12)]
        assert noms == sorted(noms)

    def test_backoff_is_jittered_across_instances(self):
        a, b = self._objecter(), self._objecter()
        # two clients virtually never produce identical 8-delay runs —
        # the desynchronization that prevents retry storms
        run_a = [a._backoff_delay(i) for i in range(8)]
        run_b = [b._backoff_delay(i) for i in range(8)]
        assert run_a != run_b

    def test_backoff_fails_fast_when_deadline_inside_delay(self):
        """ISSUE 17 bugfix regression: an op whose deadline lands inside
        the next backoff window must raise NOW — the old shape slept the
        remaining budget away and failed only at the top of the loop."""
        import time

        from ceph_tpu.msg.messages import ReqId

        ob = self._objecter()
        span = ob.tracer.start_span("t")
        reqid = ReqId("client.bk", 1)
        with pytest.raises(TimeoutError, match="inside resend backoff"):
            # backoff floor is 0.0125 s; 1 ms of budget sits inside it
            ob._backoff_or_timeout(
                time.monotonic() + 0.001, 0, reqid, "oid", span
            )
        assert ob.perf.get("op_timeout") == 1
        # ample budget: the jittered delay comes back, nothing counted
        d = ob._backoff_or_timeout(
            time.monotonic() + 60.0, 0, reqid, "oid", span
        )
        assert 0.0 < d <= 1.0
        assert ob.perf.get("op_timeout") == 1
        span.finish()

    def test_resends_counted_in_perfcounter(self):
        import asyncio

        async def run():
            from ceph_tpu.msg.messages import PgId
            from ceph_tpu.osd.osdmap import OsdInfo

            ob = self._objecter()
            # a target whose OSD is unreachable: every send fails and the
            # resend loop backs off until the op deadline (CRUSH bypassed;
            # this tests the retry loop, not placement)
            ob._calc_target = lambda pool_id, oid: (PgId(1, 0, -1), 0)
            ob.osdmap.osds[0] = OsdInfo(addr="127.0.0.1:1", up=True)
            ob.osdmap.epoch = 1
            with pytest.raises(TimeoutError):
                await ob.op_submit(1, "oid", [], timeout=0.4)
            assert ob.perf.get("op") == 1
            assert ob.perf.get("op_timeout") == 1
            assert ob.perf.get("op_resend") >= 1
            await ob.stop()

        asyncio.run(run())


class TestInjectargsAsok:
    """The injectargs-style asok command arms the SAME process-global
    hooks the data path checks — the harness/tests contract."""

    def test_arm_codec_launch_over_asok_drives_host_fallback(self, tmp_path):
        import asyncio

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.common.admin_socket import admin_command
            from ceph_tpu.common.config import Config
            from ceph_tpu.mon import MonMap, Monitor
            from ceph_tpu.osd.osd import OSD

            from test_mon import free_port_addrs

            monmap = MonMap(addrs=free_port_addrs(1))
            mons = [
                Monitor(n, monmap, election_timeout=0.3) for n in monmap.addrs
            ]
            for m in mons:
                await m.start()
                await m.wait_for_quorum()

            def conf(i):
                return Config(
                    {
                        "name": f"osd.{i}",
                        "osd_heartbeat_interval": 0.1,
                        "osd_heartbeat_grace": 0.6,
                        "admin_socket": str(tmp_path / f"osd.{i}.asok"),
                    },
                    env=False,
                )

            osds = [OSD(i, monmap, conf=conf(i)) for i in range(3)]
            for o in osds:
                await o.start()
            for o in osds:
                await o.wait_for_up()
            client = Rados(monmap)
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "ia21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("iap", "erasure", profile="ia21", pg_num=1)
            io = await client.open_ioctx("iap")
            sock = str(tmp_path / "osd.0.asok")
            loop = asyncio.get_event_loop()

            def asok(**kw):
                # the sync client must not block the loop the asok
                # server runs on (test_cluster.py's executor pattern)
                return loop.run_in_executor(
                    None, lambda: admin_command(sock, "injectargs", **kw)
                )

            # arm through the asok, exactly as an operator would
            out = await asok(point="codec.launch", error=5, hits=1)
            assert "codec.launch" in out["armed"]
            before = ec_dispatch.FALLBACK_LAUNCHES.snapshot()["launches"]
            data = bytes(range(256)) * 64
            await io.write_full("armed-obj", data)
            assert await io.read("armed-obj") == data  # fallback, not EIO
            assert (
                ec_dispatch.FALLBACK_LAUNCHES.snapshot()["launches"] > before
            )
            # perf dump surfaces the degraded gauge + fallback counters
            dump = await loop.run_in_executor(
                None, lambda: admin_command(sock, "perf dump")
            )
            assert "fallback_launches" in dump["ec_dispatch"]
            # clear + runtime config set through the same command
            out = await asok(clear=True, conf={"ec_tpu_probe_interval_ms": 1})
            assert out["armed"] == []
            assert osds[0].conf.get("ec_tpu_probe_interval_ms") == 1
            # unknown names are rejected by the catalog
            with pytest.raises(RuntimeError, match="unregistered"):
                await asok(point="no.such.point")

            await client.shutdown()
            for o in osds:
                await o.stop()
            for m in mons:
                await m.stop()
            await asyncio.sleep(0.05)

        asyncio.run(run())
