"""Fault-point lint — the injection catalog stays wired and documented
(the CI satellite of ISSUE 7, mirroring test_metrics_lint.py).

Every `faultpoint("...")` call site in the tree must use a name
registered in common/fault_injector.py's FAULT_POINTS catalog; every
catalog entry must have at least one call site (no dead hooks a harness
could arm in vain) and must be documented in docs/ROBUSTNESS.md — so a
typo can neither create a hook that never fires nor a doc that lies."""

import re
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "ceph_tpu"

# faultpoint("name") / _faultpoint("name", ...) / faultpoint_delay("name")
# — the spellings the seams use (objectstore routes through
# ObjectStore._faultpoint so the InjectedFailure -> StoreError mapping
# lives in one place; faultpoint_delay is the ISSUE 17 latency twin)
_CALL = re.compile(
    r"""\b_?faultpoint(?:_delay)?\(\s*["']([a-z0-9_.]+)["']"""
)


def _call_sites() -> dict[str, list[str]]:
    """point name -> [relative file paths using it]."""
    found: dict[str, list[str]] = {}
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text()
        for m in _CALL.finditer(text):
            found.setdefault(m.group(1), []).append(
                str(path.relative_to(REPO))
            )
    return found


class TestFaultPointCatalog:
    def test_every_call_site_is_registered(self):
        from ceph_tpu.common.fault_injector import FAULT_POINTS

        sites = _call_sites()
        unregistered = {
            p: files for p, files in sites.items() if p not in FAULT_POINTS
        }
        assert not unregistered, (
            f"faultpoint() call sites using unregistered names: "
            f"{unregistered} — add them to FAULT_POINTS"
        )

    def test_every_registered_point_is_wired(self):
        """A catalog entry nothing checks is a trap: the harness arms it
        and the fault never fires."""
        from ceph_tpu.common.fault_injector import FAULT_POINTS

        sites = _call_sites()
        dead = sorted(set(FAULT_POINTS) - set(sites))
        assert not dead, (
            f"FAULT_POINTS entries with no faultpoint() call site: {dead}"
        )

    def test_every_point_documented_in_robustness_md(self):
        from ceph_tpu.common.fault_injector import FAULT_POINTS

        doc = (REPO / "docs" / "ROBUSTNESS.md").read_text()
        undocumented = sorted(
            p for p in FAULT_POINTS if f"`{p}`" not in doc
        )
        assert not undocumented, (
            f"fault points missing from docs/ROBUSTNESS.md: {undocumented}"
        )

    def test_catalog_descriptions_nonempty(self):
        from ceph_tpu.common.fault_injector import FAULT_POINTS

        for name, desc in FAULT_POINTS.items():
            assert desc.strip(), f"{name}: empty catalog description"

    def test_unregistered_name_raises_eagerly(self):
        import pytest

        from ceph_tpu.common.fault_injector import faultpoint

        with pytest.raises(ValueError, match="unregistered"):
            faultpoint("no.such.point")

    def test_counted_hits_drain_and_disarm(self):
        """Armed hit budgets drain per check and disarm at zero — the
        property the chaos harness's deterministic bursts rely on."""
        import pytest

        from ceph_tpu.common.fault_injector import (
            FaultInjector,
            InjectedFailure,
        )

        inj = FaultInjector()
        inj.inject("os.read", 5, hits=2)
        for _ in range(2):
            with pytest.raises(InjectedFailure):
                inj.check("os.read")
        inj.check("os.read")  # budget drained: no longer armed
        assert not inj.armed("os.read")

    def test_delay_mode_reports_seconds_and_drains_hits(self):
        """delay_ms mode (ISSUE 17): the seam stays functionally correct
        but slow — check_delay reports seconds, spends the hit budget
        like check(), and clear()/armed() cover delayed points too."""
        from ceph_tpu.common.fault_injector import FaultInjector

        inj = FaultInjector()
        inj.inject_delay("ec.sub_read", 250.0, hits=2)
        assert inj.armed("ec.sub_read")
        assert inj.check_delay("ec.sub_read") == 0.25
        assert inj.check_delay("ec.sub_read") == 0.25
        assert inj.check_delay("ec.sub_read") == 0.0  # budget drained
        assert not inj.armed("ec.sub_read")
        inj.inject_delay("msgr.send", 100.0)
        assert inj.armed("msgr.send")
        inj.clear("msgr.send")
        assert inj.check_delay("msgr.send") == 0.0

    def test_delay_scoped_to_one_daemon(self):
        """A gray failure is ONE slow daemon among healthy ones: a
        who-scoped delay fires (and spends hits) only for the matching
        caller identity, so the chaos harness can slow a single victim
        through the process-global injector."""
        from ceph_tpu.common.fault_injector import FaultInjector

        inj = FaultInjector()
        inj.inject_delay("ec.sub_read", 100.0, hits=1, who="osd.3")
        assert inj.check_delay("ec.sub_read", who="osd.1") == 0.0
        assert inj.check_delay("ec.sub_read") == 0.0
        assert inj.armed("ec.sub_read")  # mismatches spent no hits
        assert inj.check_delay("ec.sub_read", who="osd.3") == 0.1
        assert not inj.armed("ec.sub_read")

    def test_faultpoint_delay_rejects_unregistered_names(self):
        import pytest

        from ceph_tpu.common.fault_injector import faultpoint_delay

        with pytest.raises(ValueError, match="unregistered"):
            faultpoint_delay("no.such.point")
