"""Fault-point lint — the injection catalog stays wired and documented
(the CI satellite of ISSUE 7, mirroring test_metrics_lint.py).

Every `faultpoint("...")` call site in the tree must use a name
registered in common/fault_injector.py's FAULT_POINTS catalog; every
catalog entry must have at least one call site (no dead hooks a harness
could arm in vain) and must be documented in docs/ROBUSTNESS.md — so a
typo can neither create a hook that never fires nor a doc that lies."""

import re
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "ceph_tpu"

# faultpoint("name") / _faultpoint("name", ...) — the two spellings the
# seams use (objectstore routes through ObjectStore._faultpoint so the
# InjectedFailure -> StoreError mapping lives in one place)
_CALL = re.compile(r"""\b_?faultpoint\(\s*["']([a-z0-9_.]+)["']""")


def _call_sites() -> dict[str, list[str]]:
    """point name -> [relative file paths using it]."""
    found: dict[str, list[str]] = {}
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text()
        for m in _CALL.finditer(text):
            found.setdefault(m.group(1), []).append(
                str(path.relative_to(REPO))
            )
    return found


class TestFaultPointCatalog:
    def test_every_call_site_is_registered(self):
        from ceph_tpu.common.fault_injector import FAULT_POINTS

        sites = _call_sites()
        unregistered = {
            p: files for p, files in sites.items() if p not in FAULT_POINTS
        }
        assert not unregistered, (
            f"faultpoint() call sites using unregistered names: "
            f"{unregistered} — add them to FAULT_POINTS"
        )

    def test_every_registered_point_is_wired(self):
        """A catalog entry nothing checks is a trap: the harness arms it
        and the fault never fires."""
        from ceph_tpu.common.fault_injector import FAULT_POINTS

        sites = _call_sites()
        dead = sorted(set(FAULT_POINTS) - set(sites))
        assert not dead, (
            f"FAULT_POINTS entries with no faultpoint() call site: {dead}"
        )

    def test_every_point_documented_in_robustness_md(self):
        from ceph_tpu.common.fault_injector import FAULT_POINTS

        doc = (REPO / "docs" / "ROBUSTNESS.md").read_text()
        undocumented = sorted(
            p for p in FAULT_POINTS if f"`{p}`" not in doc
        )
        assert not undocumented, (
            f"fault points missing from docs/ROBUSTNESS.md: {undocumented}"
        )

    def test_catalog_descriptions_nonempty(self):
        from ceph_tpu.common.fault_injector import FAULT_POINTS

        for name, desc in FAULT_POINTS.items():
            assert desc.strip(), f"{name}: empty catalog description"

    def test_unregistered_name_raises_eagerly(self):
        import pytest

        from ceph_tpu.common.fault_injector import faultpoint

        with pytest.raises(ValueError, match="unregistered"):
            faultpoint("no.such.point")

    def test_counted_hits_drain_and_disarm(self):
        """Armed hit budgets drain per check and disarm at zero — the
        property the chaos harness's deterministic bursts rely on."""
        import pytest

        from ceph_tpu.common.fault_injector import (
            FaultInjector,
            InjectedFailure,
        )

        inj = FaultInjector()
        inj.inject("os.read", 5, hits=2)
        for _ in range(2):
            with pytest.raises(InjectedFailure):
                inj.check("os.read")
        inj.check("os.read")  # budget drained: no longer armed
        assert not inj.armed("os.read")
