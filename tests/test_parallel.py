"""Multi-device tests for ceph_tpu.parallel on the 8-device virtual CPU mesh.

Sharding/collective correctness is validated the way the driver's multi-chip
dry-run does it — `--xla_force_host_platform_device_count=8` (conftest.py) —
mirroring the reference's many-daemons-one-host standalone tier
(/root/reference/qa/standalone/erasure-code/test-erasure-code.sh:35-43).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ceph_tpu.gf import (
    expand_matrix,
    isa_decode_matrix,
    isa_rs_vandermonde_matrix,
    xor_matmul_host,
)
from ceph_tpu.parallel.mesh import LANE_AXIS, STRIPE_AXIS, make_mesh
from ceph_tpu.parallel.sharded import (
    _encode_executable,
    scrub_step,
    shard_batch,
    sharded_decode,
    sharded_encode,
)


def _bit_matrix(k: int, m: int) -> jnp.ndarray:
    return jnp.asarray(
        expand_matrix(isa_rs_vandermonde_matrix(k, m)[k:]), dtype=jnp.uint8
    )


def _host_parity(k: int, m: int, data: np.ndarray) -> np.ndarray:
    bm = np.asarray(expand_matrix(isa_rs_vandermonde_matrix(k, m)[k:]))
    return np.stack([xor_matmul_host(bm, stripe) for stripe in data])


def _batch(S: int, k: int, L: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (S, k, L), dtype=np.uint8)


class TestMesh:
    def test_default_axes(self):
        mesh = make_mesh(8)
        assert mesh.shape[STRIPE_AXIS] * mesh.shape[LANE_AXIS] == 8
        # largest power-of-two <= isqrt(8)=2 dividing 8 -> lane=2, stripe=4
        assert mesh.shape[LANE_AXIS] == 2
        assert mesh.shape[STRIPE_AXIS] == 4

    @pytest.mark.parametrize("lane", [1, 2, 4, 8])
    def test_lane_override(self, lane):
        mesh = make_mesh(8, lane_parallelism=lane)
        assert mesh.shape[LANE_AXIS] == lane
        assert mesh.shape[STRIPE_AXIS] == 8 // lane

    def test_subset_of_devices(self):
        mesh = make_mesh(4)
        assert mesh.shape[STRIPE_AXIS] * mesh.shape[LANE_AXIS] == 4


class TestShardedEncodeDecode:
    def test_encode_matches_host(self):
        k, m = 8, 3
        mesh = make_mesh(8)
        data = _batch(8, k, 1024)
        sharded = shard_batch(jnp.asarray(data), mesh)
        parity = sharded_encode(_bit_matrix(k, m), sharded, mesh)
        assert np.array_equal(np.asarray(parity), _host_parity(k, m, data))

    def test_encode_uneven_stripe_shards(self):
        # 5 stripes over a 4-way stripe axis: GSPMD pads, bytes must still
        # match the host oracle exactly.
        k, m = 4, 2
        mesh = make_mesh(8)  # stripe=4, lane=2
        data = _batch(5, k, 512, seed=1)
        sharded = shard_batch(jnp.asarray(data), mesh)
        parity = sharded_encode(_bit_matrix(k, m), sharded, mesh)
        assert np.array_equal(np.asarray(parity)[:5], _host_parity(k, m, data))

    def test_encode_uneven_lane_shards(self):
        # chunk length not divisible by the lane axis
        k, m = 4, 2
        mesh = make_mesh(8, lane_parallelism=4)
        data = _batch(4, k, 250, seed=2)
        sharded = shard_batch(jnp.asarray(data), mesh)
        parity = sharded_encode(_bit_matrix(k, m), sharded, mesh)
        assert np.array_equal(
            np.asarray(parity)[:, :, :250], _host_parity(k, m, data)
        )

    def test_lane_only_mesh(self):
        # all parallelism on the byte axis (the sequence-parallel analog)
        k, m = 8, 3
        mesh = make_mesh(8, lane_parallelism=8)
        data = _batch(2, k, 4096, seed=3)
        sharded = shard_batch(jnp.asarray(data), mesh)
        parity = sharded_encode(_bit_matrix(k, m), sharded, mesh)
        assert np.array_equal(np.asarray(parity), _host_parity(k, m, data))

    def test_m_exceeds_row_group(self):
        # m=5 -> a (40, 32) bit-matrix, spanning >1 8-row fold group
        k, m = 4, 5
        mesh = make_mesh(8)
        data = _batch(8, k, 256, seed=4)
        sharded = shard_batch(jnp.asarray(data), mesh)
        parity = sharded_encode(_bit_matrix(k, m), sharded, mesh)
        assert np.array_equal(np.asarray(parity), _host_parity(k, m, data))

    def test_decode_rebuilds_erasures(self):
        k, m = 8, 3
        mesh = make_mesh(8)
        coeff = isa_rs_vandermonde_matrix(k, m)
        data = _batch(8, k, 1024, seed=5)
        parity = _host_parity(k, m, data)
        chunks = np.concatenate([data, parity], axis=1)

        erasures = [1, 9]
        plan = isa_decode_matrix(coeff, erasures, k)
        assert plan is not None
        c, decode_index = plan
        dec_bm = jnp.asarray(expand_matrix(c), dtype=jnp.uint8)
        survivors = shard_batch(jnp.asarray(chunks[:, decode_index, :]), mesh)
        rebuilt = sharded_decode(dec_bm, survivors, mesh)
        assert np.array_equal(np.asarray(rebuilt), chunks[:, erasures, :])


class TestScrub:
    def test_clean_batch(self):
        k, m = 4, 2
        mesh = make_mesh(8)
        data = _batch(8, k, 512, seed=6)
        chunks = np.concatenate([data, _host_parity(k, m, data)], axis=1)
        count, mask = scrub_step(
            _bit_matrix(k, m), shard_batch(jnp.asarray(chunks), mesh), k, mesh
        )
        assert int(count) == 0
        assert not np.asarray(mask).any()

    def test_detects_corrupt_stripe(self):
        k, m = 4, 2
        mesh = make_mesh(8)
        data = _batch(8, k, 512, seed=7)
        chunks = np.concatenate([data, _host_parity(k, m, data)], axis=1)
        chunks[3, k + 1, 100] ^= 0xFF  # silent parity corruption
        count, mask = scrub_step(
            _bit_matrix(k, m), shard_batch(jnp.asarray(chunks), mesh), k, mesh
        )
        assert int(count) == 1
        mask = np.asarray(mask)
        assert mask[3] and mask.sum() == 1

    def test_detects_corrupt_data_chunk(self):
        # corrupting *data* also flips recomputed parity vs stored
        k, m = 4, 2
        mesh = make_mesh(8)
        data = _batch(4, k, 256, seed=8)
        chunks = np.concatenate([data, _host_parity(k, m, data)], axis=1)
        chunks[0, 2, 0] ^= 0x01
        count, _ = scrub_step(
            _bit_matrix(k, m), shard_batch(jnp.asarray(chunks), mesh), k, mesh
        )
        assert int(count) == 1


class TestCompiledExecutableHeld:
    def test_encode_wrapper_is_cached_per_mesh(self):
        mesh = make_mesh(8)
        assert _encode_executable(mesh) is _encode_executable(mesh)

    def test_no_retrace_across_calls(self):
        # Steady-state launches must hit the held executable's trace cache:
        # same shapes -> cache size stays at 1 (VERDICT round 1, weak #7).
        k, m = 4, 2
        mesh = make_mesh(8)
        bm = _bit_matrix(k, m)
        data = shard_batch(jnp.asarray(_batch(8, k, 256, seed=9)), mesh)
        fn = _encode_executable(mesh)
        sharded_encode(bm, data, mesh)
        size_after_first = fn._cache_size()
        for _ in range(3):
            sharded_encode(bm, data, mesh)
        assert fn._cache_size() == size_after_first


class TestClayMeshRepair:
    def test_repair_planes_sharded_over_mesh(self, monkeypatch):
        """CLAY single-chunk repair with the inner-MDS decode launched
        mesh-sharded: repair planes are the batch axis, data-parallel over
        `stripe`, sub-chunk bytes over `lane` — the layout the bulk-rebuild
        path uses on a pod.  Bytes must match the originally encoded chunk
        (repair plan per ErasureCodeClay.cc:462-642)."""
        from ceph_tpu.codec import matrix_codec as mc_mod
        from ceph_tpu.codec.registry import instance

        mesh = make_mesh(8)
        calls = {"n": 0}

        def mesh_xor_matmul(bm, data):
            calls["n"] += 1
            sharded = shard_batch(jnp.asarray(data, dtype=jnp.uint8), mesh)
            return sharded_decode(jnp.asarray(bm, dtype=jnp.uint8), sharded, mesh)

        # Reroute the coder's device launch itself (not just the jnp
        # fallback): on a TPU backend cached coders would otherwise take the
        # Pallas plan path and bypass an xor_matmul patch.
        monkeypatch.setattr(
            mc_mod._DeviceCoder,
            "__call__",
            lambda self, data: mesh_xor_matmul(self.bm, data),
        )

        ec = instance().factory("clay", {"k": "4", "m": "2", "d": "5"})
        k, m = 4, 2
        rng = np.random.default_rng(10)
        raw = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
        encoded = ec.encode(set(range(k + m)), raw)
        chunk_size = ec.get_chunk_size(len(raw))
        sc = chunk_size // ec.sub_chunk_no

        lost = 2
        minimum = ec.minimum_to_decode({lost}, set(range(k + m)) - {lost})
        helper_chunks = {}
        for node, runs in minimum.items():
            frags = [
                encoded[node][off * sc : (off + count) * sc] for off, count in runs
            ]
            helper_chunks[node] = np.concatenate(frags)
        repaired = ec.decode({lost}, helper_chunks, chunk_size=chunk_size)
        assert np.array_equal(repaired[lost], encoded[lost])
        assert calls["n"] > 0, "repair did not go through the mesh-sharded path"


class TestPlanSharded:
    """shard_map fan-out of the production Pallas kernel (interpret mode on
    the CPU mesh: the exact kernel program, per-device tiles)."""

    def test_plan_encode_matches_host(self):
        from ceph_tpu.ops.pallas_gf import CodingPlan
        from ceph_tpu.parallel.sharded import sharded_plan_encode

        k, m = 8, 3
        mesh = make_mesh(8)  # stripe=4, lane=2
        plan = CodingPlan(isa_rs_vandermonde_matrix(k, m)[k:], interpret=True)
        data = _batch(8, k, 1024)
        placed = shard_batch(jnp.asarray(data), mesh)
        parity = np.asarray(sharded_plan_encode(plan, placed, mesh))
        assert np.array_equal(parity, _host_parity(k, m, data))

    def test_plan_decode_rebuilds(self):
        from ceph_tpu.ops.pallas_gf import CodingPlan
        from ceph_tpu.parallel.sharded import sharded_plan_decode

        k, m = 8, 3
        mesh = make_mesh(8)
        coeff = isa_rs_vandermonde_matrix(k, m)
        data = _batch(4, k, 1024, seed=7)
        full = np.concatenate([data, _host_parity(k, m, data)], axis=1)
        erasures = [1, 9]
        c, idx = isa_decode_matrix(coeff, erasures, k)
        plan = CodingPlan(c, interpret=True)
        survivors = shard_batch(jnp.asarray(full[:, idx, :]), mesh)
        rebuilt = np.asarray(sharded_plan_decode(plan, survivors, mesh))
        assert np.array_equal(rebuilt, full[:, erasures, :])

    def test_plan_small_tile_falls_back(self):
        # Lane shard of 64 bytes has no kernel geometry -> jnp fallback
        # inside the plan; results still exact.
        from ceph_tpu.ops.pallas_gf import CodingPlan, pick_geometry
        from ceph_tpu.parallel.sharded import sharded_plan_encode

        k, m = 4, 2
        mesh = make_mesh(8, lane_parallelism=2)
        assert pick_geometry(64) is None
        plan = CodingPlan(isa_rs_vandermonde_matrix(k, m)[k:], interpret=True)
        data = _batch(4, k, 128)
        placed = shard_batch(jnp.asarray(data), mesh)
        parity = np.asarray(sharded_plan_encode(plan, placed, mesh))
        assert np.array_equal(parity, _host_parity(k, m, data))


class TestPodMesh:
    """Multi-pod (DCN) meshes: stripes shard over (pod, stripe); bulk bytes
    never cross the pod boundary."""

    def test_pod_mesh_axes(self):
        from ceph_tpu.parallel.mesh import POD_AXIS

        mesh = make_mesh(8, pods=2)
        assert mesh.shape[POD_AXIS] == 2
        assert mesh.shape[STRIPE_AXIS] * mesh.shape[LANE_AXIS] == 4

    def test_pod_encode_matches_host(self):
        k, m = 8, 3
        mesh = make_mesh(8, pods=2)
        data = _batch(8, k, 512)
        placed = shard_batch(jnp.asarray(data), mesh)
        parity = np.asarray(sharded_encode(_bit_matrix(k, m), placed, mesh))
        assert np.array_equal(parity, _host_parity(k, m, data))

    def test_pod_plan_encode_matches_host(self):
        from ceph_tpu.ops.pallas_gf import CodingPlan
        from ceph_tpu.parallel.sharded import sharded_plan_encode

        k, m = 8, 3
        mesh = make_mesh(8, pods=2)
        plan = CodingPlan(isa_rs_vandermonde_matrix(k, m)[k:], interpret=True)
        data = _batch(8, k, 1024)
        placed = shard_batch(jnp.asarray(data), mesh)
        parity = np.asarray(sharded_plan_encode(plan, placed, mesh))
        assert np.array_equal(parity, _host_parity(k, m, data))

    def test_pod_scrub_detects_corruption(self):
        k, m = 4, 2
        mesh = make_mesh(8, pods=2)
        data = _batch(8, k, 512, seed=3)
        chunks = np.concatenate([data, _host_parity(k, m, data)], axis=1)
        chunks[5, 1, 17] ^= 0xFF
        placed = shard_batch(jnp.asarray(chunks), mesh)
        count, mask = scrub_step(_bit_matrix(k, m), placed, k, mesh)
        assert int(count) == 1
        assert bool(np.asarray(mask)[5])


def test_plan_executable_cache_content_keyed():
    """Equal matrices reuse one shard_map executable even across distinct
    CodingPlan instances (content-keyed, not identity-keyed)."""
    from ceph_tpu.ops.pallas_gf import CodingPlan
    from ceph_tpu.parallel import sharded

    mesh = make_mesh(8)
    mat = isa_rs_vandermonde_matrix(4, 2)[4:]
    p1 = CodingPlan(mat, interpret=True)
    p2 = CodingPlan(mat.copy(), interpret=True)
    e1 = sharded._plan_encode_executable(mesh, p1)
    e2 = sharded._plan_encode_executable(mesh, p2)
    assert e1 is e2


class TestPlanScrub:
    """Multi-chip scrub with the production Pallas kernel recompute."""

    @pytest.mark.parametrize("pods", [1, 2])
    def test_plan_scrub_detects_corruption(self, pods):
        from ceph_tpu.ops.pallas_gf import CodingPlan
        from ceph_tpu.parallel.sharded import plan_scrub_step

        k, m = 4, 2
        mesh = make_mesh(8, pods=pods)
        plan = CodingPlan(isa_rs_vandermonde_matrix(k, m)[k:], interpret=True)
        data = _batch(8, k, 1024, seed=11)
        chunks = np.concatenate([data, _host_parity(k, m, data)], axis=1)
        placed = shard_batch(jnp.asarray(chunks), mesh)
        count, mask = plan_scrub_step(plan, placed, k, mesh)
        assert int(count) == 0 and not np.asarray(mask).any()
        # corrupt one byte in a parity chunk AND one in a data chunk
        chunks[2, k, 77] ^= 0x5A
        chunks[6, 1, 900] ^= 0x01
        placed = shard_batch(jnp.asarray(chunks), mesh)
        count, mask = plan_scrub_step(plan, placed, k, mesh)
        assert int(count) == 2
        assert np.asarray(mask)[2] and np.asarray(mask)[6]
