"""mClock / WPQ op scheduler tests (src/osd/scheduler mirror).

Models the dmClock properties that matter: reservations are honored
ahead of weights, weights split spare capacity proportionally, limits
cap background classes, and WPQ is strict-priority FIFO.
"""

from ceph_tpu.osd.scheduler import (
    ClientProfile,
    MClockScheduler,
    SchedClass,
    WorkItem,
    WPQScheduler,
    make_scheduler,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def drain_classes(sched, n):
    out = []
    for _ in range(n):
        item = sched.dequeue()
        if item is None:
            break
        out.append(item.klass)
    return out


class TestMClock:
    def test_fifo_within_class(self):
        clock = FakeClock()
        s = MClockScheduler(clock=clock)
        seen = []
        for i in range(5):
            s.enqueue(WorkItem(run=lambda i=i: seen.append(i), klass=SchedClass.CLIENT))
        clock.t = 100.0
        while (item := s.dequeue()) is not None:
            item.run()
        assert seen == [0, 1, 2, 3, 4]

    def test_reservation_beats_weight(self):
        clock = FakeClock()
        s = MClockScheduler(
            profiles={
                SchedClass.CLIENT: ClientProfile(reservation=1000.0, weight=1.0),
                SchedClass.RECOVERY: ClientProfile(weight=100.0),
            },
            clock=clock,
        )
        clock.t = 1.0
        s.enqueue(WorkItem(run=lambda: None, klass=SchedClass.RECOVERY))
        s.enqueue(WorkItem(run=lambda: None, klass=SchedClass.CLIENT))
        clock.t = 2.0
        # client's R tag matured -> served first despite recovery's weight
        assert s.dequeue().klass is SchedClass.CLIENT

    def test_weights_share_capacity(self):
        clock = FakeClock()
        s = MClockScheduler(
            profiles={
                SchedClass.CLIENT: ClientProfile(weight=2.0),
                SchedClass.RECOVERY: ClientProfile(weight=1.0),
            },
            clock=clock,
        )
        clock.t = 1.0
        for _ in range(30):
            s.enqueue(WorkItem(run=lambda: None, klass=SchedClass.CLIENT))
            s.enqueue(WorkItem(run=lambda: None, klass=SchedClass.RECOVERY))
        clock.t = 1.000001  # freeze: only P tags matter now
        first12 = drain_classes(s, 12)
        # 2:1 split (client tags advance half as fast)
        assert first12.count(SchedClass.CLIENT) == 8
        assert first12.count(SchedClass.RECOVERY) == 4

    def test_work_conserving_under_limit(self):
        clock = FakeClock()
        s = MClockScheduler(
            profiles={SchedClass.SCRUB: ClientProfile(weight=1.0, limit=1.0)},
            clock=clock,
        )
        clock.t = 1.0
        for _ in range(5):
            s.enqueue(WorkItem(run=lambda: None, klass=SchedClass.SCRUB))
        # even with every class over its limit, dequeue never idles
        got = drain_classes(s, 5)
        assert len(got) == 5
        assert len(s) == 0

    def test_cost_scales_tags(self):
        clock = FakeClock()
        s = MClockScheduler(
            profiles={
                SchedClass.CLIENT: ClientProfile(weight=1.0),
                SchedClass.RECOVERY: ClientProfile(weight=1.0),
            },
            clock=clock,
        )
        clock.t = 1.0
        # expensive client items vs cheap recovery items, equal weights:
        # recovery should get more slots
        for _ in range(10):
            s.enqueue(
                WorkItem(run=lambda: None, klass=SchedClass.CLIENT, cost=64 * 4096)
            )
            s.enqueue(WorkItem(run=lambda: None, klass=SchedClass.RECOVERY, cost=4096))
        clock.t = 1.000001
        first10 = drain_classes(s, 10)
        assert first10.count(SchedClass.RECOVERY) > first10.count(SchedClass.CLIENT)


class TestWPQ:
    def test_strict_priority_then_fifo(self):
        s = WPQScheduler()
        s.enqueue(WorkItem(run=lambda: None, priority=1))
        s.enqueue(WorkItem(run=lambda: None, priority=63))
        s.enqueue(WorkItem(run=lambda: None, priority=63))
        first = s.dequeue()
        assert first.priority == 63
        assert s.dequeue().priority == 63
        assert s.dequeue().priority == 1
        assert s.dequeue() is None


def test_make_scheduler_selection():
    assert isinstance(make_scheduler("wpq"), WPQScheduler)
    assert isinstance(make_scheduler("mclock_scheduler"), MClockScheduler)
