"""Round-over-round trajectory gating (ISSUE 14, tools/perf_compare.py):
the committed BENCH_r*.json corpus stays schema-valid in tier-1 (pure
parsing, no device), and the comparator judges a round against the
trailing same-platform best — the 23.4 GB/s story cannot silently
reset."""

import json
import os
import subprocess
import sys

from ceph_tpu.tools.perf_compare import (
    check_corpus,
    compare,
    compare_round,
    default_rounds_dir,
    load_rounds,
    metric_slice,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestCommittedCorpus:
    """The tier-1 CI gate: a malformed bench JSON or a silent schema
    drift in the committed rounds fails the suite."""

    def test_default_rounds_dir_is_the_repo_root(self):
        assert default_rounds_dir() == REPO

    def test_check_passes_over_committed_rounds(self):
        problems = check_corpus(REPO)
        assert problems == [], problems

    def test_committed_rounds_load_with_known_trajectory(self):
        rounds = load_rounds(REPO)
        assert [r["round"] for r in rounds] == sorted(
            r["round"] for r in rounds
        )
        assert len(rounds) >= 5
        # the round-3 TPU measurement is the story perf_compare exists
        # to defend: it must parse out of the committed corpus
        by_round = {r["round"]: r for r in rounds}
        assert by_round[3]["platform"] == "tpu"
        assert by_round[3]["metrics"][
            "rs_8_3_encode_GBps_per_chip"] > 20.0

    def test_cli_check_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "ceph_tpu.tools.perf_compare",
             "--check", "--rounds-dir", REPO],
            capture_output=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout.decode()
        payload = json.loads(proc.stdout.decode())
        assert payload["ok"] is True
        assert payload["checked"] >= 5
        assert payload["trajectory"]

    def test_check_fails_on_malformed_round(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text("{not json")
        problems = check_corpus(str(tmp_path))
        assert problems and "not JSON" in problems[0]

    def test_check_fails_on_schema_drift(self, tmp_path):
        # rc=0 round whose parsed slice lost the metric contract
        (tmp_path / "BENCH_r01.json").write_text(json.dumps({
            "n": 1, "rc": 0, "parsed": {"speed": 3},
        }))
        problems = check_corpus(str(tmp_path))
        assert any("metric" in p for p in problems), problems
        # non-finite value
        (tmp_path / "BENCH_r01.json").write_text(json.dumps({
            "n": 1, "rc": 0,
            "parsed": {"metric": "m", "value": None, "unit": "GB/s"},
        }))
        problems = check_corpus(str(tmp_path))
        assert any("finite" in p for p in problems), problems

    def test_empty_dir_is_a_problem(self, tmp_path):
        assert check_corpus(str(tmp_path))


class TestMetricSlice:
    def test_legacy_single_metric_shape(self):
        assert metric_slice({
            "metric": "rs_8_3_encode_GBps_per_chip", "value": 0.069,
            "unit": "GB/s", "platform": "cpu",
        }) == {"rs_8_3_encode_GBps_per_chip": 0.069}

    def test_rich_shape_flattens_every_known_metric(self):
        parsed = {
            "metric": "rs_8_3_encode_GBps_per_chip", "value": 2.5,
            "platform": "cpu",
            "decode": {"metric": "rs_8_3_decode_GBps_per_chip",
                       "value": 4.2},
            "verify": {"metric": "rs_8_3_verify_GBps_per_chip",
                       "value": 3.0},
            "pipelined": {
                "metric": "rs_8_3_encode_GBps_per_chip_pipelined",
                "value": 17.17,
            },
            "multichip": {
                "metric": "rs_8_3_encode_GBps_aggregate", "value": 9.0,
                "decode": {"metric": "rs_8_3_decode_GBps_aggregate",
                           "value": 8.0},
            },
            "chaos": {"chaos_p99_ms": 120.5, "recovery_occupancy": 2.0,
                      "rebuild_seconds": 6.25, "storm_p99_ms": 240.0},
        }
        got = metric_slice(parsed)
        assert got == {
            "rs_8_3_encode_GBps_per_chip": 2.5,
            "rs_8_3_decode_GBps_per_chip": 4.2,
            "rs_8_3_verify_GBps_per_chip": 3.0,
            "rs_8_3_encode_GBps_per_chip_pipelined": 17.17,
            "rs_8_3_encode_GBps_aggregate": 9.0,
            "rs_8_3_decode_GBps_aggregate": 8.0,
            "chaos_p99_ms": 120.5,
            "recovery_occupancy": 2.0,
            "chaos_rebuild_seconds": 6.25,
            "chaos_storm_p99_ms": 240.0,
        }

    def test_mislabeled_and_nonfinite_values_ignored(self):
        assert metric_slice({
            "metric": "something_else", "value": 1.0,
            "decode": {"metric": "rs_8_3_decode_GBps_per_chip",
                       "value": float("inf")},
        }) == {}
        assert metric_slice(None) == {}


def _rounds():
    """A synthetic trailing corpus mirroring the real trajectory shape:
    CPU rounds, one TPU round at 23.374, CPU fallbacks after."""
    return [
        {"round": 2, "rc": 0, "platform": "cpu",
         "metrics": {"rs_8_3_encode_GBps_per_chip": 0.069}},
        {"round": 3, "rc": 0, "platform": "tpu",
         "metrics": {"rs_8_3_encode_GBps_per_chip": 23.374}},
        {"round": 4, "rc": 0, "platform": "cpu",
         "metrics": {"rs_8_3_encode_GBps_per_chip": 0.048,
                     "chaos_p99_ms": 100.0}},
    ]


class TestCompare:
    def test_next_tpu_round_judged_against_23_4(self):
        """THE acceptance story: a TPU round at 10 GB/s is flagged
        against round 3's 23.374, not silently accepted because the
        recent CPU rounds were slower."""
        out = compare(
            {"metric": "rs_8_3_encode_GBps_per_chip", "value": 10.0,
             "platform": "tpu"},
            _rounds(),
        )
        base = out["baselines"]["rs_8_3_encode_GBps_per_chip"]
        assert base == {"value": 23.374, "round": 3, "platform": "tpu"}
        assert out["count"] == 1
        flag = out["flagged"][0]
        assert flag["metric"] == "rs_8_3_encode_GBps_per_chip"
        assert flag["baseline_round"] == 3
        assert flag["vs_baseline"] < 0.5

    def test_healthy_tpu_round_passes(self):
        out = compare(
            {"metric": "rs_8_3_encode_GBps_per_chip", "value": 25.0,
             "platform": "tpu"},
            _rounds(),
        )
        assert out["flagged"] == []

    def test_cpu_fallback_not_judged_against_tpu(self):
        """Platform scoping: a CPU fallback round compares against the
        CPU best (0.069), never the TPU 23.374 — a fallback is a
        fallback, not a 99.7% regression."""
        out = compare(
            {"metric": "rs_8_3_encode_GBps_per_chip", "value": 0.06,
             "platform": "cpu"},
            _rounds(),
        )
        base = out["baselines"]["rs_8_3_encode_GBps_per_chip"]
        assert base["value"] == 0.069 and base["platform"] == "cpu"
        assert out["flagged"] == []  # 0.06 is within 0.8x of 0.069

    def test_lower_is_better_metric_flags_inflation(self):
        out = compare(
            {"platform": "cpu", "chaos": {"chaos_p99_ms": 500.0}},
            _rounds(),
        )
        assert out["count"] == 1
        assert out["flagged"][0]["metric"] == "chaos_p99_ms"
        assert out["flagged"][0]["direction"] == "lower"
        out = compare(
            {"platform": "cpu", "chaos": {"chaos_p99_ms": 90.0}},
            _rounds(),
        )
        assert out["flagged"] == []

    def test_storm_rebuild_keys_gate_lower_is_better(self):
        """ISSUE 15: rebuild time and under-storm p99 fold from the
        chaos JSON and flag when a round slows the whole-OSD rebuild
        (or lets it eat client latency) past the ratio."""
        rounds = _rounds() + [{
            "round": 5, "rc": 0, "platform": "cpu",
            "metrics": {"chaos_rebuild_seconds": 5.0,
                        "chaos_storm_p99_ms": 200.0},
        }]
        out = compare(
            {"platform": "cpu",
             "chaos": {"rebuild_seconds": 9.0, "storm_p99_ms": 190.0}},
            rounds,
        )
        flagged = {f["metric"] for f in out["flagged"]}
        assert flagged == {"chaos_rebuild_seconds"}, out["flagged"]
        out = compare(
            {"platform": "cpu",
             "chaos": {"rebuild_seconds": 5.5, "storm_p99_ms": 190.0}},
            rounds,
        )
        assert out["flagged"] == []
        # baselines carry the best (lowest) committed values
        assert out["baselines"]["chaos_rebuild_seconds"]["value"] == 5.0
        assert out["baselines"]["chaos_storm_p99_ms"]["value"] == 200.0

    def test_no_baseline_no_flag(self):
        """First round / new metric / platform switch: nothing to judge
        against, by design."""
        out = compare(
            {"metric": "rs_8_3_encode_GBps_per_chip", "value": 0.001,
             "platform": "gpu"},
            _rounds(),
        )
        # platform-scoped metrics have no gpu history; the unscoped
        # chaos baseline may exist but the current round carries no
        # chaos slice — nothing flags either way
        assert "rs_8_3_encode_GBps_per_chip" not in out["baselines"]
        assert out["flagged"] == []

    def test_compare_round_against_committed_corpus(self):
        """The bench.py fold path over the real committed files: a
        hypothetical collapsed TPU round flags against round 3."""
        out = compare_round(
            {"metric": "rs_8_3_encode_GBps_per_chip", "value": 1.0,
             "platform": "tpu"},
            REPO,
        )
        assert out["rounds_compared"]
        assert any(
            f["metric"] == "rs_8_3_encode_GBps_per_chip"
            and f["baseline"] > 20.0
            for f in out["flagged"]
        ), out
