"""Metrics history (ISSUE 14): the mgr metrics-history module — rate
derivation from cumulative MMgrReport counters, trend-sentinel
raise/clear end to end through mon `status` + health, mgr-failover
warm-start (no spurious TPU_THROUGHPUT_REGRESSION on imported
boot-to-now counters), the asok/dashboard query surfaces, and the
telemetry perf-envelope privacy contract."""

import asyncio
import json
import time
from types import SimpleNamespace

import pytest

from ceph_tpu.mgr.metrics_history import (
    SENTINEL_CODES,
    MetricsHistoryModule,
)

GB = 10**9


class _FakeMgr:
    """The MgrModule surface the metrics-history module consumes: one
    synthetic OSD whose cumulative dispatch counters the test advances
    between ticks."""

    def __init__(self):
        self.conf = None
        self.modules = []
        self.daemons = {}
        self.perf = {
            "osd.0": {
                "ec_dispatch.bytes": 0,
                "ec_dispatch.decode_bytes": 0,
                "ec_dispatch.launches": 0,
                "ec_dispatch.fallback_launches": 0,
                "op": 0,
                "ec_dispatch.device_occupancy": 0.0,
                "ec_dispatch.flight_mean_queue_wait_ms": 0.0,
            }
        }
        self.status = {"osd.0": {"slow_ops": {"count": 0}}}

    def list_daemons(self):
        return sorted(self.perf)

    def get_daemon_perf(self, daemon):
        return self.perf.get(daemon, {})

    def get_daemon_status(self, daemon):
        return self.status.get(daemon, {})


def _mk(**pins) -> tuple[MetricsHistoryModule, _FakeMgr]:
    pins.setdefault("resolutions", "0.05,0.5")
    mod = MetricsHistoryModule(**pins)
    mgr = _FakeMgr()
    mod.mgr = mgr
    return mod, mgr


def _advance(
    mgr,
    gbps=1.0,
    launches=10,
    occupancy=0.8,
    queue_wait_ms=0.1,
    dt=0.05,
):
    """One synthetic beacon interval: sleep dt, bump the cumulative
    counters as a `gbps` workload would, set the level gauges."""
    time.sleep(dt)
    p = mgr.perf["osd.0"]
    p["ec_dispatch.bytes"] += int(gbps * GB * dt)
    p["ec_dispatch.decode_bytes"] += int(gbps * GB * dt / 2)
    p["ec_dispatch.launches"] += launches
    p["op"] += launches
    p["ec_dispatch.device_occupancy"] = occupancy
    p["ec_dispatch.flight_mean_queue_wait_ms"] = queue_wait_ms


class TestRateDerivation:
    def test_rates_derive_from_counter_deltas(self):
        mod, mgr = _mk()
        mod.tick()  # anchor
        for _ in range(4):
            _advance(mgr, gbps=2.0)
            mod.tick()
        cur = mod.store.window_value("encode_gbps", {}, 10, 0)
        assert cur == pytest.approx(2.0, rel=0.35)
        # per-daemon series exist alongside the cluster aggregate
        q = mod.history_get("encode_gbps", daemon="osd.0", window=10)
        assert q["points"]
        # gauges copied as levels (`last`: the anchor tick legitimately
        # sampled the pre-load occupancy of 0.0)
        assert mod.store.window_value(
            "occupancy", {}, 10, 0, aggregate="last"
        ) == pytest.approx(0.8)

    def test_first_sight_import_never_becomes_a_rate(self):
        """A fresh module (mgr failover) imports boot-to-now cumulative
        counters: the import must anchor, not record hours of history
        as one tick's GB/s."""
        mod, mgr = _mk()
        mgr.perf["osd.0"]["ec_dispatch.bytes"] = 500 * GB  # boot-to-now
        mgr.perf["osd.0"]["ec_dispatch.launches"] = 10**6
        mod.tick()  # first sight
        for _ in range(3):
            _advance(mgr, gbps=1.0)
            mod.tick()
        peak = mod.store.window_value(
            "encode_gbps", {}, 10, 0, aggregate="max"
        )
        assert peak is not None and peak < 10.0, peak

    def test_counter_regression_reanchors(self):
        """A daemon restart rebases its counters to zero: no negative
        rate, no sample — the next genuine delta resumes."""
        mod, mgr = _mk()
        mod.tick()
        _advance(mgr, gbps=1.0)
        mod.tick()
        mgr.perf["osd.0"]["ec_dispatch.bytes"] = 0  # restart
        time.sleep(0.05)
        mod.tick()
        low = mod.store.window_value(
            "encode_gbps", {}, 10, 0, aggregate="min"
        )
        assert low is not None and low >= 0.0

    def test_down_daemon_not_sampled(self):
        mod, mgr = _mk()
        mgr._daemon_report_live = lambda d: False
        mod.tick()
        _advance(mgr, gbps=1.0)
        mod.tick()
        assert mod.store.stats()["series"] == 0

    def test_churned_daemon_anchors_pruned(self):
        """The rate-anchor dict must not grow one entry per daemon ever
        seen: anchors stale past the prune window drop (the tsdb store
        LRU-caps its series; the anchors must stay bounded too)."""
        from ceph_tpu.mgr import metrics_history as mh

        mod, mgr = _mk()
        mod.tick()
        assert mod._prev  # live daemon anchored
        # the daemon churns away: age its anchors past the window
        mod._prev = {
            k: (t - mh._ANCHOR_PRUNE_SEC - 1, v)
            for k, (t, v) in mod._prev.items()
        }
        del mgr.perf["osd.0"]
        mod.tick()
        assert mod._prev == {}


def _warm_up(mod, mgr, rounds=16, **kw):
    """Healthy load long enough to pass the sentinel warm-up window
    (window 0.2 + baseline 0.4 at 50 ms ticks)."""
    for _ in range(rounds):
        _advance(mgr, **kw)
        mod.tick()


def _sentinel_pins():
    return dict(
        window_sec=0.2,
        baseline_sec=0.4,
        regression_ratio=0.5,
        occupancy_ratio=0.5,
        queue_wait_factor=5.0,
        min_launch_rate=1.0,
    )


class TestSentinels:
    def test_throughput_regression_raises_and_clears(self):
        """Replay a throughput collapse: GB/s falls to ~2% of baseline
        while launch volume persists -> TPU_THROUGHPUT_REGRESSION; the
        trend recovering clears it."""
        mod, mgr = _mk(**_sentinel_pins())
        _warm_up(mod, mgr, gbps=2.0)
        assert "TPU_THROUGHPUT_REGRESSION" not in mod.health_checks
        for _ in range(8):  # collapse: same launches, ~no bytes
            _advance(mgr, gbps=0.04)
            mod.tick()
            if "TPU_THROUGHPUT_REGRESSION" in mod.health_checks:
                break
        assert "TPU_THROUGHPUT_REGRESSION" in mod.health_checks
        assert mod.sentinels_fired >= 1
        check = mod.health_checks["TPU_THROUGHPUT_REGRESSION"]
        assert "baseline" in check["summary"]
        assert check["detail"], check
        digest = mod.history_digest()
        assert "TPU_THROUGHPUT_REGRESSION" in digest["sentinels"]
        # recovery: back at baseline-rate load, the recent window
        # catches up (and the collapsed period ages into the baseline)
        deadline = time.monotonic() + 5.0
        while (
            "TPU_THROUGHPUT_REGRESSION" in mod.health_checks
            and time.monotonic() < deadline
        ):
            _advance(mgr, gbps=2.0)
            mod.tick()
        assert "TPU_THROUGHPUT_REGRESSION" not in mod.health_checks
        assert mod.history_digest()["sentinels"] == {}

    def test_load_drop_is_not_a_regression(self):
        """The launch-volume gate: bytes AND launches dropping together
        is the cluster going idle — no sentinel."""
        mod, mgr = _mk(**_sentinel_pins())
        _warm_up(mod, mgr, gbps=2.0, launches=10)
        for _ in range(8):
            _advance(mgr, gbps=0.02, launches=0)
            mod.tick()
        assert "TPU_THROUGHPUT_REGRESSION" not in mod.health_checks
        assert mod.sentinels_fired == 0

    def test_occupancy_collapse_raises(self):
        mod, mgr = _mk(**_sentinel_pins())
        _warm_up(mod, mgr, occupancy=0.8)
        for _ in range(10):
            _advance(mgr, occupancy=0.01)
            mod.tick()
            if "TPU_OCCUPANCY_COLLAPSE" in mod.health_checks:
                break
        assert "TPU_OCCUPANCY_COLLAPSE" in mod.health_checks
        assert "occupancy" in \
            mod.health_checks["TPU_OCCUPANCY_COLLAPSE"]["summary"]

    def test_queue_wait_inflation_raises(self):
        mod, mgr = _mk(**_sentinel_pins())
        _warm_up(mod, mgr, queue_wait_ms=0.5)
        for _ in range(10):
            _advance(mgr, queue_wait_ms=80.0)
            mod.tick()
            if "TPU_QUEUE_WAIT_INFLATION" in mod.health_checks:
                break
        assert "TPU_QUEUE_WAIT_INFLATION" in mod.health_checks

    def test_idle_baseline_never_alarms_on_busy_start(self):
        """An idle-to-busy transition is NOT inflation/regression: the
        baseline carried no launch volume, so there is nothing to
        regress from — without the baseline-volume gate the first busy
        window after an idle spell would trip TPU_QUEUE_WAIT_INFLATION
        with a fabricated ~2000x factor."""
        mod, mgr = _mk(**_sentinel_pins())
        # idle well past warm-up: zero launches, zero queue wait
        _warm_up(mod, mgr, gbps=0.0, launches=0, queue_wait_ms=0.0,
                 occupancy=0.0)
        # a normal workload starts: healthy 2 ms waits, decent volume
        for _ in range(8):
            _advance(mgr, gbps=2.0, launches=10, queue_wait_ms=2.0,
                     occupancy=0.8)
            mod.tick()
            assert mod.health_checks == {}, mod.health_checks
        assert mod.sentinels_fired == 0

    def test_queue_wait_floor_suppresses_noise(self):
        """Sub-millisecond inflation (0.02 -> 0.09 ms) is noise, not a
        backlog: the absolute floor keeps the sentinel quiet."""
        mod, mgr = _mk(**_sentinel_pins())
        _warm_up(mod, mgr, queue_wait_ms=0.02)
        for _ in range(10):
            _advance(mgr, queue_wait_ms=0.09)
            mod.tick()
        assert "TPU_QUEUE_WAIT_INFLATION" not in mod.health_checks

    def test_failover_warm_start_holds_fire(self):
        """The acceptance case: a fresh module importing boot-to-now
        counters (mgr failover) must not raise
        TPU_THROUGHPUT_REGRESSION during warm-up — baselines seed from
        the first snapshot and sentinels hold fire until a FULL
        evaluation window of genuine history exists."""
        mod, mgr = _mk(**_sentinel_pins())
        p = mgr.perf["osd.0"]
        p["ec_dispatch.bytes"] = 10**14  # hours of history
        p["ec_dispatch.launches"] = 10**8
        p["op"] = 10**8
        mod.tick()  # the import
        assert mod.health_checks == {}
        # modest-but-steady post-failover load, right through warm-up
        # and well past it: never a spurious sentinel
        for _ in range(20):
            _advance(mgr, gbps=0.5)
            mod.tick()
            assert mod.health_checks == {}, mod.health_checks
        assert mod.sentinels_fired == 0


class TestMonSurfaces:
    """Mon renders the digest's history slice: sentinel checks in
    `health` (summary + detail, the wording common/health.py built
    mgr-side) and the machine-readable slice in `status`."""

    def _mon(self):
        from ceph_tpu.mon import MonMap, Monitor

        async def build():
            monmap = MonMap(addrs={"a": "127.0.0.1:0"})
            return Monitor("a", monmap, election_timeout=0.3)

        return asyncio.new_event_loop().run_until_complete(build())

    def _collapse_digest(self):
        """A real module's digest after a replayed collapse — not a
        hand-written fixture, so the shapes cannot drift."""
        mod, mgr = _mk(**_sentinel_pins())
        _warm_up(mod, mgr, gbps=2.0)
        for _ in range(8):
            _advance(mgr, gbps=0.04)
            mod.tick()
            if mod.sentinels:
                break
        assert "TPU_THROUGHPUT_REGRESSION" in mod.sentinels
        return mod.history_digest()

    def test_sentinel_reaches_mon_health_and_status(self):
        mon = self._mon()
        mon.pg_digest = {"history": self._collapse_digest()}
        checks, details = mon.health_checks()
        assert "TPU_THROUGHPUT_REGRESSION" in checks
        assert "baseline" in checks["TPU_THROUGHPUT_REGRESSION"]
        assert details["TPU_THROUGHPUT_REGRESSION"]
        assert "GB/s" in details["TPU_THROUGHPUT_REGRESSION"][0]
        handler = mon._mon_command_handler("status")
        captured = {}
        handler({}, lambda rv, rs, outbl: captured.update(outbl=outbl))
        payload = json.loads(captured["outbl"].decode())
        assert "TPU_THROUGHPUT_REGRESSION" in payload["health"]["checks"]
        hist = payload["history"]
        assert hist["sentinels"]["TPU_THROUGHPUT_REGRESSION"]["data"]
        assert hist["stats"]["series"] >= 1
        # the health command serves the detail lines too
        handler = mon._mon_command_handler("health")
        captured = {}
        handler({"detail": True},
                lambda rv, rs, outbl: captured.update(outbl=outbl))
        payload = json.loads(captured["outbl"].decode())
        assert payload["detail"]["TPU_THROUGHPUT_REGRESSION"]

    def test_clear_digest_raises_nothing(self):
        mon = self._mon()
        mon.pg_digest = {"history": {"sentinels": {}, "stats": {}}}
        checks, _ = mon.health_checks()
        assert not any(code in checks for code in SENTINEL_CODES)


class TestTelemetryEnvelope:
    def _telemetry_with_history(self):
        from ceph_tpu.mgr.telemetry import TelemetryModule

        mod, mgr = _mk()
        mod.tick()
        for _ in range(4):
            _advance(mgr, gbps=3.0, occupancy=0.7)
            mod.tick()
        tel = TelemetryModule()
        tel.mgr = SimpleNamespace(
            osdmap=SimpleNamespace(
                pools={}, osds={}, erasure_code_profiles={}, fsid="f00d",
            ),
            daemons={"osd.0": object()},
            modules=[mod, tel],
            conf=None,
        )
        return tel, mod

    def test_perf_envelope_carries_shapes_and_counts(self):
        tel, mod = self._telemetry_with_history()
        report = tel.compile_report()
        env = report["perf_envelope"]
        assert env["history_series"] == mod.store.stats()["series"]
        assert env["sentinels_fired"] == 0
        assert env["peak_encode_gbps"] == pytest.approx(3.0, rel=0.4)
        assert env["peak_occupancy"] == pytest.approx(0.7)

    def test_no_label_values_leak(self):
        """The privacy contract: the report must carry counts and
        shapes only — no daemon names, pool names, or per-daemon series
        labels from the history store."""
        tel, _mod = self._telemetry_with_history()
        blob = json.dumps(tel.compile_report())
        assert "osd.0" not in blob
        assert "daemon\\\"" not in blob and '"daemon"' not in blob

    def test_envelope_empty_without_module(self):
        from ceph_tpu.mgr.telemetry import TelemetryModule

        tel = TelemetryModule()
        tel.mgr = SimpleNamespace(
            osdmap=SimpleNamespace(
                pools={}, osds={}, erasure_code_profiles={}, fsid="",
            ),
            daemons={},
            modules=[tel],
            conf=None,
        )
        assert tel.compile_report()["perf_envelope"] == {}


class TestDashboardSurfaces:
    def test_api_health_full_detail_and_severity(self):
        """The satellite fix: api_health must surface the full check
        set with detail lines AND derive status from the real
        HEALTH_WARN/HEALTH_ERR severities (the old merge compared
        against literal 'warning'/'error' no check ever used, so the
        banner always read HEALTH_OK)."""
        from ceph_tpu.mgr.dashboard import DashboardModule

        dash = DashboardModule()
        mod, _mgr = _mk(**_sentinel_pins())
        mod.set_health_check(
            "TPU_THROUGHPUT_REGRESSION", "HEALTH_WARN",
            "encode throughput regressed", ["encode: 0.1 vs 2.0 GB/s"],
        )
        dash.mgr = SimpleNamespace(
            osdmap=SimpleNamespace(
                osds={}, pools={}, epoch=3, num_up_osds=lambda: 0,
            ),
            modules=[mod, dash],
            health_checks=lambda: dict(mod.health_checks),
        )
        payload = dash.api_health()
        assert payload["status"] == "HEALTH_WARN"
        check = payload["checks"]["TPU_THROUGHPUT_REGRESSION"]
        assert check["summary"] == "encode throughput regressed"
        assert check["detail"] == ["encode: 0.1 vs 2.0 GB/s"]
        # ERR-severity checks escalate the banner
        mod.set_health_check("PG_DAMAGED", "HEALTH_ERR", "damage", [])
        assert dash.api_health()["status"] == "HEALTH_ERR"
        mod.health_checks.clear()
        assert dash.api_health()["status"] == "HEALTH_OK"

    def test_digest_derived_checks_carry_detail(self):
        """api_health's per-entity detail promise holds for the
        digest-derived checks too (SLOW_OPS et al.), not just module
        checks — Mgr.health_checks() ships the same detail lines mon
        `health detail` prints."""
        import asyncio as aio

        from ceph_tpu.mgr import Mgr
        from ceph_tpu.mgr.mgr import DaemonState
        from ceph_tpu.mon.monmap import MonMap

        async def build():
            return Mgr("x", MonMap(addrs={"a": "127.0.0.1:0"}))

        mgr = aio.new_event_loop().run_until_complete(build())
        st = DaemonState()
        st.status = {"slow_ops": {"count": 2, "oldest_sec": 40.0}}
        mgr.daemons["osd.0"] = st
        checks = mgr.health_checks()
        assert "SLOW_OPS" in checks
        assert any("osd.0" in line for line in checks["SLOW_OPS"]["detail"])

    def test_api_perf_history_route(self):
        from ceph_tpu.mgr.dashboard import DashboardModule

        dash = DashboardModule()
        mod, mgr = _mk()
        mod.tick()
        for _ in range(3):
            _advance(mgr)
            mod.tick()
        dash.mgr = SimpleNamespace(modules=[mod, dash])
        status, ctype, body = dash.render("/api/perf_history")
        assert status == 200 and ctype == "application/json"
        payload = json.loads(body)
        assert payload["stats"]["series"] >= 1
        assert any(
            s["family"] == "encode_gbps" for s in payload["series"]
        )
        assert payload["sentinels"] == {}

    def test_map_errors_exported(self):
        from ceph_tpu.mgr.dashboard import DashboardModule

        dash = DashboardModule()
        dash.map_errors = 7
        fams = {name: rows for name, _t, _h, rows in
                dash.prometheus_metrics()}
        assert fams["ceph_tpu_dashboard_map_errors"] == [
            "ceph_tpu_dashboard_map_errors 7"
        ]


class TestMgrAsokPerfHistory:
    def test_mgr_asok_serves_perf_history(self, tmp_path):
        """The operator path: `ceph tell mgr.x perf history ls/get`
        over the mgr's admin socket, fed by real OSD MMgrReports."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.common.admin_socket import admin_command
            from ceph_tpu.common.config import Config
            from ceph_tpu.mgr import Mgr, MetricsHistoryModule

            from test_cluster import start_cluster, stop_cluster, wait_until

            monmap, mons, osds = await start_cluster(1, 2)
            sock = str(tmp_path / "mgr.x.asok")
            mgr = Mgr(
                "x", monmap,
                conf=Config({"name": "mgr.x", "admin_socket": sock},
                            env=False),
            )
            mgr.beacon_interval = 0.1
            await mgr.start()
            await mgr.wait_for_active()
            hist = MetricsHistoryModule(resolutions="0.2,2")
            mgr.register_module(hist)

            client = Rados(monmap)
            await client.connect()
            await client.pool_create("histp", "replicated", size=2, pg_num=2)
            io = await client.open_ioctx("histp")
            for i in range(6):
                await io.write_full(f"o{i}", b"x" * 2048)
            await wait_until(
                lambda: hist.store.stats()["series"] > 0,
                10.0, "metrics-history module consuming reports",
            )
            # a second burst AFTER the module anchored the cumulative
            # counters: rate series need two snapshots with a genuine
            # delta between them (the first sight never samples)
            for i in range(6):
                await io.write_full(f"p{i}", b"y" * 2048)

            def op_rate_present():
                return any(
                    s["family"] == "op_rate"
                    for s in hist.store.series_ls()
                )

            await wait_until(
                op_rate_present, 15.0, "op_rate series from report deltas"
            )
            loop = asyncio.get_event_loop()
            ls = await loop.run_in_executor(
                None, lambda: admin_command(sock, "perf history ls")
            )
            assert ls["stats"]["series"] >= 1
            families = {s["family"] for s in ls["series"]}
            assert "op_rate" in families
            got = await loop.run_in_executor(
                None,
                lambda: admin_command(
                    sock, "perf history get", series="op_rate",
                    window="30", step="1", aggregate="max",
                ),
            )
            assert got["family"] == "op_rate"
            assert got["aggregate"] == "max"
            assert isinstance(got["points"], list)
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())
