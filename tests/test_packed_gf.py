"""Packed-bitplane kernel equivalence suite (ISSUE 3 tentpole contract).

The packed-plane kernel (ceph_tpu.ops.packed_gf) must be byte-identical to
the bitsliced XOR-matmul (ceph_tpu.ops.xor_mm.xor_matmul) AND to the host
oracle (gf.bitslice.xor_matmul_host) for every geometry — it is an exact
refactoring of the same GF(2) linear map, so any diverging byte is a bug,
not a tolerance."""

import itertools

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.gf import isa_decode_matrix, isa_rs_vandermonde_matrix
from ceph_tpu.gf.bitslice import expand_matrix, xor_matmul_host
from ceph_tpu.ops.dispatch import LAUNCHES
from ceph_tpu.ops.packed_gf import PackedPlan, _packed_code_into, plane_schedule
from ceph_tpu.ops.xor_mm import xor_matmul


def rs_matrix(k, m):
    return isa_rs_vandermonde_matrix(k, m)[k:]


def rand_data(shape, seed=0):
    return np.random.default_rng(seed).integers(0, 256, shape, dtype=np.uint8)


class TestParityEquivalence:
    @pytest.mark.parametrize("k", [2, 4, 8, 12])
    @pytest.mark.parametrize("m", [1, 2, 3, 4])
    def test_geometry_grid_vs_matmul_and_host_oracle(self, k, m):
        gfm = rs_matrix(k, m)
        plan = PackedPlan(gfm)
        bm = expand_matrix(gfm)
        # lane-aligned (128-multiple) and ragged chunk lengths
        for L in (128, 100):
            data = rand_data((k, L), seed=k * 16 + m)
            got = np.asarray(plan(data))
            want_host = xor_matmul_host(bm, data)
            want_mm = np.asarray(xor_matmul(bm, data))
            assert np.array_equal(got, want_host), (k, m, L)
            assert np.array_equal(got, want_mm), (k, m, L)

    def test_batched_matches_per_stripe(self):
        gfm = rs_matrix(4, 2)
        plan = PackedPlan(gfm)
        bm = expand_matrix(gfm)
        data = rand_data((7, 4, 256), seed=9)
        got = np.asarray(plan(data))
        for s in range(7):
            assert np.array_equal(got[s], xor_matmul_host(bm, data[s])), s

    def test_random_matrix_with_zero_coefficients(self):
        rng = np.random.default_rng(11)
        gfm = rng.integers(0, 256, (3, 5), dtype=np.uint8)
        gfm[1] = 0  # all-zero output row must produce zero bytes
        gfm[0, 2] = 0
        plan = PackedPlan(gfm)
        data = rand_data((5, 160), seed=12)
        got = np.asarray(plan(data))
        assert np.array_equal(got, xor_matmul_host(expand_matrix(gfm), data))
        assert not got[1].any()

    def test_plane_schedule_is_coefficient_bits(self):
        gfm = np.array([[1, 2], [0, 255]], dtype=np.uint8)
        sched = plane_schedule(gfm)
        assert sched[0] == ((0, 0), (1, 1))  # 1 -> bit 0; 2 -> bit 1
        assert sched[1] == tuple((1, b) for b in range(8))  # 255 -> all bits

    def test_donating_variant_identical_bytes(self):
        import jax.numpy as jnp

        gfm = rs_matrix(4, 2)
        plan = PackedPlan(gfm)
        data = rand_data((3, 4, 128), seed=5)
        plain = np.asarray(plan(data))
        dead = jnp.zeros((3, 2, 128), jnp.uint8)
        donated = np.asarray(
            _packed_code_into(dead, jnp.asarray(data), sched=plan.sched, k=4, m=2)
        )
        assert np.array_equal(plain, donated)

    def test_plan_out_kwarg_shape_mismatch_ignored(self):
        import jax.numpy as jnp

        gfm = rs_matrix(2, 1)
        plan = PackedPlan(gfm)
        data = rand_data((2, 128), seed=6)
        wrong = jnp.zeros((4, 4), jnp.uint8)
        got = np.asarray(plan(data, out=wrong))
        assert np.array_equal(got, xor_matmul_host(expand_matrix(gfm), data))

    def test_launch_counter_one_dispatch_per_batch(self):
        gfm = rs_matrix(4, 2)
        plan = PackedPlan(gfm)
        data = rand_data((16, 4, 128), seed=7)
        before = LAUNCHES.snapshot()
        plan(data)
        after = LAUNCHES.snapshot()
        assert after["launches"] - before["launches"] == 1
        assert after["stripes"] - before["stripes"] == 16


class TestDecodeRoundTrips:
    """Every erasure pattern of RS(4,2): production chunk round-trip plus
    packed-kernel equivalence on the inverted decode matrices."""

    def _codec(self):
        ec = ErasureCodeTpuRs()
        ec.init({"k": "4", "m": "2"})
        return ec

    def all_patterns(self):
        for r in (1, 2):
            yield from itertools.combinations(range(6), r)

    def test_chunk_roundtrip_every_pattern(self):
        ec = self._codec()
        payload = rand_data(4 * 512, seed=21).tobytes()
        chunks = ec.encode(set(range(6)), payload)
        for pattern in self.all_patterns():
            have = {i: chunks[i] for i in range(6) if i not in pattern}
            decoded = ec.decode(set(pattern), have)
            for e in pattern:
                assert np.array_equal(decoded[e], chunks[e]), pattern

    def test_packed_plan_on_decode_matrices(self):
        ec = self._codec()
        dist = ec.distribution_matrix()
        for pattern in self.all_patterns():
            plan = isa_decode_matrix(dist, list(pattern), 4)
            assert plan is not None, pattern
            c, decode_index = plan
            survivors = rand_data((4, 128), seed=sum(pattern))
            got = np.asarray(PackedPlan(c)(survivors))
            want = xor_matmul_host(expand_matrix(c), survivors)
            assert np.array_equal(got, want), pattern
            assert len(decode_index) == 4
