"""Compressor plugin family (src/compressor) + BlueStore blob compression
(BlueStore _do_alloc_write compression path): registry units, compressed
round trips through remounts, required-ratio gating, csum-over-stored-form
corruption detection."""

import pytest

from ceph_tpu.compressor import get_compressor
from ceph_tpu.os import BlueStore, StoreError, Transaction
from ceph_tpu.os.bluestore import BLOCK

try:
    import zstandard  # noqa: F401

    HAVE_ZSTD = True
except ImportError:  # optional dep; zlib exercises the same BlueStore paths
    HAVE_ZSTD = False

needs_zstd = pytest.mark.skipif(not HAVE_ZSTD, reason="zstandard not installed")


def mkc(path, algo="zstd" if HAVE_ZSTD else "zlib", ratio=0.875):
    s = BlueStore(str(path), compression=algo, compression_required_ratio=ratio)
    s.mount()
    return s


class TestRegistry:
    @pytest.mark.parametrize(
        "name", ["none", "zlib", pytest.param("zstd", marks=needs_zstd)]
    )
    def test_round_trip(self, name):
        c = get_compressor(name)
        data = b"compress me " * 500 + b"\x00" * 100
        assert c.decompress(c.compress(data)) == data
        if name != "none":
            assert len(c.compress(data)) < len(data)

    def test_unknown_is_loud(self):
        with pytest.raises(ValueError):
            get_compressor("snappy")  # not in this environment: no fallback

    def test_instances_cached(self):
        assert get_compressor("zlib") is get_compressor("zlib")


class TestBlueStoreCompression:
    def test_compressed_blocks_survive_remount(self, tmp_path):
        s = mkc(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        payload = b"ABCD" * (BLOCK // 2)  # 2 blocks, highly compressible
        t = Transaction()
        t.write("c", "o", 0, payload)
        s.queue_transaction(t)
        # stored form really is compressed (clen recorded per block)
        onode = s._peek_onode("c", "o")
        assert all(clen > 0 and clen < BLOCK for _p, _c, clen in onode.blocks.values())
        assert s.read("c", "o") == payload
        s.umount()
        s2 = mkc(tmp_path / "b")
        assert s2.read("c", "o") == payload  # clens persisted in the onode
        s2.umount()

    def test_incompressible_stays_raw(self, tmp_path):
        import os as _os

        s = mkc(tmp_path / "r")
        s.queue_transaction(Transaction().create_collection("c"))
        payload = _os.urandom(BLOCK)
        t = Transaction()
        t.write("c", "o", 0, payload)
        s.queue_transaction(t)
        assert [clen for _p, _c, clen in s._peek_onode("c", "o").blocks.values()] == [0]
        assert s.read("c", "o") == payload
        s.umount()

    def test_required_ratio_gates_compression(self, tmp_path):
        # ratio 0: nothing ever qualifies, even zeros
        s = mkc(tmp_path / "g", ratio=0.0)
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"\x00" * BLOCK)
        s.queue_transaction(t)
        assert [clen for _p, _c, clen in s._peek_onode("c", "o").blocks.values()] == [0]
        s.umount()

    def test_corrupt_compressed_block_is_eio(self, tmp_path):
        s = mkc(tmp_path / "x")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"Z" * BLOCK)
        s.queue_transaction(t)
        poff, _crc, clen = s._peek_onode("c", "o").blocks[0]
        assert clen > 0
        s.umount()
        with open(tmp_path / "x" / "block", "r+b") as f:
            f.seek(poff + 3)
            b = f.read(1)
            f.seek(poff + 3)
            f.write(bytes([b[0] ^ 0xFF]))
        s2 = mkc(tmp_path / "x")
        with pytest.raises(StoreError) as ei:
            s2.read("c", "o")
        assert ei.value.errno == -5  # csum over the STORED form catches it
        s2.umount()

    def test_partial_overwrite_of_compressed_block(self, tmp_path):
        s = mkc(tmp_path / "p")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"A" * BLOCK)
        s.queue_transaction(t)
        t = Transaction()
        t.write("c", "o", 100, b"B" * 50)  # RMW reads+decompresses, rewrites
        s.queue_transaction(t)
        want = b"A" * 100 + b"B" * 50 + b"A" * (BLOCK - 150)
        assert s.read("c", "o") == want
        s.umount()


class TestDeviceCompressor:
    """The device compressor plugin (ISSUE 20): byte-plane transpose +
    zero-run elision batched through the offload runtime, with a
    byte-identical host transform as the fallback oracle."""

    @pytest.fixture(autouse=True)
    def _clean_state(self):
        yield
        from ceph_tpu.common.fault_injector import global_injector
        from ceph_tpu.ops.guard import device_guard

        global_injector().clear()
        device_guard().mark_healthy()

    def test_registry_resolves_lazily_and_caches(self):
        c = get_compressor("device")
        assert c.name == "device"
        assert get_compressor("device") is c

    @pytest.mark.parametrize("n", [1, 63, 64, 100, BLOCK, BLOCK + 7])
    def test_round_trip_across_ragged_lengths(self, n):
        import numpy as np

        c = get_compressor("device")
        rng = np.random.default_rng(n)
        for data in (bytes(n), rng.bytes(n), b"\x07" * n):
            assert c.decompress(c.compress(data)) == data

    def test_sparse_block_compresses_dense_block_does_not(self):
        import os as _os

        c = get_compressor("device")
        # columnar pattern (one byte per 64-wide record): the stride-64
        # transpose lands every nonzero byte in ONE plane -> one cell
        columnar = bytearray(BLOCK)
        columnar[0::64] = bytes(range(1, BLOCK // 64 + 1))
        assert len(c.compress(bytes(columnar))) < BLOCK // 8
        # a short contiguous run dirties one cell per byte offset —
        # still far under a block
        sparse = bytearray(BLOCK)
        sparse[10:20] = b"0123456789"
        blob = c.compress(bytes(sparse))
        assert len(blob) < BLOCK // 4
        dense = _os.urandom(BLOCK)
        # every cell nonzero: the blob exceeds the input (header +
        # bitmap overhead) — BlueStore's required-ratio gate stores raw
        assert len(c.compress(dense)) > BLOCK

    def test_compress_batch_matches_single_compress(self):
        import numpy as np

        from ceph_tpu.compressor.device import COMPRESS_OFFLOAD_MIN_BYTES

        c = get_compressor("device")
        rng = np.random.default_rng(3)
        small = [rng.bytes(100), bytes(200)]  # under threshold: host loop
        assert sum(len(b) for b in small) < COMPRESS_OFFLOAD_MIN_BYTES
        assert c.compress_batch(small) == [c.compress(b) for b in small]
        big = []
        for i in range(12):  # over threshold, two length groups
            buf = bytearray(BLOCK if i % 2 else BLOCK // 2)
            buf[i * 3: i * 3 + 5] = b"hello"
            big.append(bytes(buf))
        assert sum(len(b) for b in big) >= COMPRESS_OFFLOAD_MIN_BYTES
        assert c.compress_batch(big) == [c.compress(b) for b in big]

    def test_fault_injected_batch_falls_back_byte_identical(self):
        from ceph_tpu.common.fault_injector import global_injector
        from ceph_tpu.compressor.device import default_compress_aggregator

        c = get_compressor("device")
        blocks = []
        for i in range(10):
            buf = bytearray(BLOCK)
            buf[64 * i: 64 * i + 8] = bytes(range(8))
            blocks.append(bytes(buf))
        agg = default_compress_aggregator()
        fb0 = agg.perf.get("host_fallbacks")
        global_injector().inject("codec.launch", 5, hits=1)
        blobs = c.compress_batch(blocks)
        assert agg.perf.get("host_fallbacks") == fb0 + 1
        assert blobs == [c.compress(b) for b in blocks]
        assert all(c.decompress(x) == b for x, b in zip(blobs, blocks))

    def test_truncated_blob_is_loud(self):
        c = get_compressor("device")
        blob = c.compress(b"\x01" + bytes(BLOCK - 1))
        with pytest.raises(ValueError):
            c.decompress(blob[:-1])
        with pytest.raises(ValueError):
            c.decompress(b"nope" + blob[4:])

    def test_bluestore_device_compression_round_trips(self, tmp_path):
        s = mkc(tmp_path / "d", algo="device")
        s.queue_transaction(Transaction().create_collection("c"))
        sparse = bytearray(2 * BLOCK)
        sparse[100:116] = b"record-0 payload"
        sparse[BLOCK + 200: BLOCK + 216] = b"record-1 payload"
        t = Transaction()
        t.write("c", "o", 0, bytes(sparse))
        s.queue_transaction(t)
        onode = s._peek_onode("c", "o")
        assert all(
            0 < clen < BLOCK for _p, _c, clen in onode.blocks.values()
        )
        assert s.read("c", "o") == bytes(sparse)
        s.umount()
        s2 = mkc(tmp_path / "d", algo="device")
        assert s2.read("c", "o") == bytes(sparse)
        s2.umount()
