"""Compressor plugin family (src/compressor) + BlueStore blob compression
(BlueStore _do_alloc_write compression path): registry units, compressed
round trips through remounts, required-ratio gating, csum-over-stored-form
corruption detection."""

import pytest

from ceph_tpu.compressor import get_compressor
from ceph_tpu.os import BlueStore, StoreError, Transaction
from ceph_tpu.os.bluestore import BLOCK

try:
    import zstandard  # noqa: F401

    HAVE_ZSTD = True
except ImportError:  # optional dep; zlib exercises the same BlueStore paths
    HAVE_ZSTD = False

needs_zstd = pytest.mark.skipif(not HAVE_ZSTD, reason="zstandard not installed")


def mkc(path, algo="zstd" if HAVE_ZSTD else "zlib", ratio=0.875):
    s = BlueStore(str(path), compression=algo, compression_required_ratio=ratio)
    s.mount()
    return s


class TestRegistry:
    @pytest.mark.parametrize(
        "name", ["none", "zlib", pytest.param("zstd", marks=needs_zstd)]
    )
    def test_round_trip(self, name):
        c = get_compressor(name)
        data = b"compress me " * 500 + b"\x00" * 100
        assert c.decompress(c.compress(data)) == data
        if name != "none":
            assert len(c.compress(data)) < len(data)

    def test_unknown_is_loud(self):
        with pytest.raises(ValueError):
            get_compressor("snappy")  # not in this environment: no fallback

    def test_instances_cached(self):
        assert get_compressor("zlib") is get_compressor("zlib")


class TestBlueStoreCompression:
    def test_compressed_blocks_survive_remount(self, tmp_path):
        s = mkc(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        payload = b"ABCD" * (BLOCK // 2)  # 2 blocks, highly compressible
        t = Transaction()
        t.write("c", "o", 0, payload)
        s.queue_transaction(t)
        # stored form really is compressed (clen recorded per block)
        onode = s._peek_onode("c", "o")
        assert all(clen > 0 and clen < BLOCK for _p, _c, clen in onode.blocks.values())
        assert s.read("c", "o") == payload
        s.umount()
        s2 = mkc(tmp_path / "b")
        assert s2.read("c", "o") == payload  # clens persisted in the onode
        s2.umount()

    def test_incompressible_stays_raw(self, tmp_path):
        import os as _os

        s = mkc(tmp_path / "r")
        s.queue_transaction(Transaction().create_collection("c"))
        payload = _os.urandom(BLOCK)
        t = Transaction()
        t.write("c", "o", 0, payload)
        s.queue_transaction(t)
        assert [clen for _p, _c, clen in s._peek_onode("c", "o").blocks.values()] == [0]
        assert s.read("c", "o") == payload
        s.umount()

    def test_required_ratio_gates_compression(self, tmp_path):
        # ratio 0: nothing ever qualifies, even zeros
        s = mkc(tmp_path / "g", ratio=0.0)
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"\x00" * BLOCK)
        s.queue_transaction(t)
        assert [clen for _p, _c, clen in s._peek_onode("c", "o").blocks.values()] == [0]
        s.umount()

    def test_corrupt_compressed_block_is_eio(self, tmp_path):
        s = mkc(tmp_path / "x")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"Z" * BLOCK)
        s.queue_transaction(t)
        poff, _crc, clen = s._peek_onode("c", "o").blocks[0]
        assert clen > 0
        s.umount()
        with open(tmp_path / "x" / "block", "r+b") as f:
            f.seek(poff + 3)
            b = f.read(1)
            f.seek(poff + 3)
            f.write(bytes([b[0] ^ 0xFF]))
        s2 = mkc(tmp_path / "x")
        with pytest.raises(StoreError) as ei:
            s2.read("c", "o")
        assert ei.value.errno == -5  # csum over the STORED form catches it
        s2.umount()

    def test_partial_overwrite_of_compressed_block(self, tmp_path):
        s = mkc(tmp_path / "p")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"A" * BLOCK)
        s.queue_transaction(t)
        t = Transaction()
        t.write("c", "o", 100, b"B" * 50)  # RMW reads+decompresses, rewrites
        s.queue_transaction(t)
        want = b"A" * 100 + b"B" * 50 + b"A" * (BLOCK - 150)
        assert s.read("c", "o") == want
        s.umount()
