"""ISSUE 18 tier-1 coverage: super-launch fusion, bucketed pad
specialization, and the on-device RMW delta path.

- Byte-identity of fused multi-window launches vs the host oracle
  across RS(4,2)/RS(8,3) with ragged per-ticket batch sizes.
- The all-wedged fault matrix with fusion armed: a fused group must
  split byte-identically per ticket through the host oracle whatever
  way the device dies (dispatch fault, wedged timeout, pre-degraded).
- The RMW delta program vs the full-encode oracle (host and device
  forms), and the end-to-end delta write path on an EC-overwrites pool
  including ragged tails and armed `codec.launch` faults.
- Leak gates: `pipeline.donation_recycled_live` and the EC in-flight
  mempool stay clean with fusion + delta enabled, and pad-bucket churn
  cannot pin donated buffers in the mempool ledger.
"""

import time

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import EncodeAggregator, drain_all_aggregators
from ceph_tpu.common.fault_injector import global_injector
from ceph_tpu.common.mempool import ledger as hbm_ledger
from ceph_tpu.ops import dispatch as ec_dispatch
from ceph_tpu.ops.device_cache import device_chunk_cache
from ceph_tpu.ops.flight_recorder import flight_recorder
from ceph_tpu.ops.guard import device_guard


@pytest.fixture(autouse=True)
def _clean_guard_and_injector():
    """Injector and guard state must never leak across tests: a stray
    DEGRADED flag would reroute every later launch through the host."""
    yield
    global_injector().clear()
    device_guard().mark_healthy()
    device_guard().configure(timeout_ms=20000, probe_interval_ms=2000)


def make_rs(k, m):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


def _backlogged_aggregator():
    """window=2 / depth=1 / fuse=4: the first window trip launches and
    fills the ring, every later trip defers — deterministic fusion."""
    return EncodeAggregator(
        window=2,
        max_bytes=1 << 30,
        inflight_max_bytes=1 << 30,
        pipeline_depth=1,
        fuse_max_windows=4,
    )


def _submit_all(agg, ec, batches):
    tickets = [agg.submit(ec, h) for h in batches]
    agg.flush()
    return tickets


class TestFusedByteIdentity:
    """Fused multi-window launches are byte-identical to per-ticket host
    encodes — fusion is just a bigger group, not a different program."""

    @pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
    def test_fused_multiwindow_launch_byte_identical(self, k, m):
        ec = make_rs(k, m)
        rng = np.random.default_rng(21)
        agg = _backlogged_aggregator()
        # ragged per-ticket stripe counts: the pad machinery zero-fills
        # around these, and the settle slices must cut exactly at the
        # ticket boundaries inside the fused batch
        sizes = (1, 2, 3, 1, 2, 3, 5, 1, 2, 1)
        batches = [
            rng.integers(0, 256, (s, k, 2048), dtype=np.uint8) for s in sizes
        ]
        f0 = agg.perf.get("fused_launches")
        tickets = _submit_all(agg, ec, batches)
        for h, t in zip(batches, tickets):
            assert np.array_equal(np.asarray(t), ec.encode_array_host(h))
        assert agg.perf.get("fused_launches") - f0 >= 1, (
            "backlogged window trips never fused"
        )

    def test_fused_flight_record_flags_and_window_count(self):
        ec = make_rs(4, 2)
        rng = np.random.default_rng(22)
        agg = _backlogged_aggregator()
        batches = [
            rng.integers(0, 256, (1, 4, 2048), dtype=np.uint8)
            for _ in range(10)
        ]
        tickets = _submit_all(agg, ec, batches)
        for t in tickets:
            np.asarray(t)
        fused = [
            r for r in flight_recorder().records()
            if r["flags"].get("fused")
        ]
        assert fused, "no fused flight record committed"
        rec = fused[-1]
        assert rec["fused_windows"] >= 2
        assert rec["tickets"] >= 2 * 2  # at least two whole windows

    def test_fused_counters_reach_perf_dump(self):
        ec = make_rs(4, 2)
        rng = np.random.default_rng(23)
        agg = _backlogged_aggregator()
        d0 = ec_dispatch.perf_dump()
        tickets = _submit_all(agg, ec, [
            rng.integers(0, 256, (1, 4, 2048), dtype=np.uint8)
            for _ in range(10)
        ])
        for t in tickets:
            np.asarray(t)
        d1 = ec_dispatch.perf_dump()
        assert d1["fused_launches"] > d0["fused_launches"]
        assert d1["fused_windows"] >= d0["fused_windows"] + 2
        assert "padding_waste_ratio" in d1


class TestFusedWedgedFaultMatrix:
    """All-wedged fault matrix with fusion armed: however the device
    dies, a fused multi-window group completes on the host oracle and
    splits byte-identically per ticket."""

    @pytest.mark.parametrize(
        "mode", ["dispatch_fault", "wedged_timeout", "pre_degraded"]
    )
    def test_fused_group_host_fallback_byte_identical(self, mode):
        ec = make_rs(4, 2)
        rng = np.random.default_rng(31)
        agg = _backlogged_aggregator()
        sizes = (1, 3, 2, 2, 1, 1, 2, 3)
        batches = [
            rng.integers(0, 256, (s, 4, 2048), dtype=np.uint8) for s in sizes
        ]
        hf0 = agg.perf.get("host_fallbacks")
        f0 = agg.perf.get("fused_launches")
        real = ec.encode_array
        if mode == "dispatch_fault":
            global_injector().inject("codec.launch", 5, hits=100)
        elif mode == "wedged_timeout":

            def wedge(arr, out=None):
                time.sleep(0.3)  # well past the 50 ms deadline below
                return real(arr, out=out)

            device_guard().configure(timeout_ms=50)
            ec.encode_array = wedge
        else:  # pre_degraded: the backend is already down, probe gated
            device_guard().configure(probe_interval_ms=10_000_000)
            device_guard().mark_degraded("test: all wedged")
            assert not device_guard().maybe_probe(
                lambda: (_ for _ in ()).throw(RuntimeError("still dead"))
            )
        try:
            tickets = _submit_all(agg, ec, batches)
            for h, t in zip(batches, tickets):
                assert np.array_equal(
                    np.asarray(t), ec.encode_array_host(h)
                ), mode
        finally:
            ec.encode_array = real
            global_injector().clear()
        assert agg.perf.get("host_fallbacks") > hf0, mode
        assert agg.perf.get("fused_launches") - f0 >= 1, (
            f"{mode}: the fault matrix never exercised a FUSED launch"
        )
        if mode != "pre_degraded":
            assert device_guard().degraded, mode


class TestDeltaProgramByteIdentity:
    """parity_new == parity_old ^ Encode(data_old ^ data_new): the delta
    program (host and device forms) against the full-encode oracle."""

    @pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
    @pytest.mark.parametrize("chunk", [512, 1536])  # 1536: ragged, non-pow2
    def test_delta_matches_full_encode(self, k, m, chunk):
        import jax.numpy as jnp

        ec = make_rs(k, m)
        rng = np.random.default_rng(41)
        stripes = 3
        old = rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8)
        new = old.copy()
        # ragged-tail mutations: only slices of some chunks change
        new[1, 2, 100 : min(700, chunk)] ^= 0x5A
        new[2, 0, : chunk // 3] ^= 0xFF
        new[0, k - 1, chunk // 2 :] ^= 0x11
        old_par = ec.encode_array_host(old)
        want = ec.encode_array_host(new)
        host = ec.encode_delta_host(old, new, old_par)
        assert np.array_equal(host, want)
        # device form, fed the cache's flat per-shard buffer layout
        old_bufs = [jnp.asarray(old[:, i, :].reshape(-1)) for i in range(k)]
        new_bufs = [jnp.asarray(new[:, i, :].reshape(-1)) for i in range(k)]
        par_bufs = [
            jnp.asarray(old_par[:, i, :].reshape(-1)) for i in range(m)
        ]
        dev = np.asarray(
            ec.encode_delta_device(old_bufs, new_bufs, par_bufs, chunk)
        )
        assert np.array_equal(dev, want)

    def test_delta_device_launch_is_counted_once(self):
        import jax.numpy as jnp

        ec = make_rs(4, 2)
        rng = np.random.default_rng(42)
        old = rng.integers(0, 256, (2, 4, 512), dtype=np.uint8)
        new = old ^ np.uint8(3)
        old_par = ec.encode_array_host(old)
        ec.encode_delta_device(  # warm
            [jnp.asarray(old[:, i, :].reshape(-1)) for i in range(4)],
            [jnp.asarray(new[:, i, :].reshape(-1)) for i in range(4)],
            [jnp.asarray(old_par[:, i, :].reshape(-1)) for i in range(2)],
            512,
        )
        before = ec_dispatch.LAUNCHES.snapshot()
        ec.encode_delta_device(
            [jnp.asarray(old[:, i, :].reshape(-1)) for i in range(4)],
            [jnp.asarray(new[:, i, :].reshape(-1)) for i in range(4)],
            [jnp.asarray(old_par[:, i, :].reshape(-1)) for i in range(2)],
            512,
        )
        after = ec_dispatch.LAUNCHES.snapshot()
        assert after["launches"] - before["launches"] == 1
        assert after["stripes"] - before["stripes"] == 2


class TestRmwDeltaEndToEnd:
    """The delta write path on an EC-overwrites pool: byte-identical to
    the host-computed expected object across ragged tails, interleaved
    with materialize fallbacks, and under armed codec.launch faults."""

    def _setup_cache(self):
        cc = device_chunk_cache()
        cc.configure(max_bytes=1 << 24)
        cc.clear()
        return cc

    def _teardown_cache(self, cc):
        from ceph_tpu.common.options import OPTIONS

        cc.clear()
        cc.configure(
            max_bytes=int(OPTIONS["ec_tpu_device_cache_bytes"].default)
        )

    @pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
    def test_delta_rmw_byte_identical_incl_ragged_tail(self, k, m):
        from test_ec_backend import (
            FLAG_EC_OVERWRITES,
            Cluster,
            ec_pool,
            payload,
        )

        cc = self._setup_cache()
        try:
            pool, profiles = ec_pool(k, m, flags=FLAG_EC_OVERWRITES)
            c = Cluster(pool, profiles)
            sw = pool.stripe_width
            base = payload(2 * sw + 1234)  # ragged tail stripe
            c.write("obj", 0, base)
            expect = bytearray(base)
            d0 = cc.perf_dump()["delta_updates"]
            # interior overwrite (delta), stripe-crossing overwrite
            # (generation skew -> materialize), ragged-tail overwrite
            # (delta again off the reseeded cache)
            for off, ln, seed in (
                (100, 600, 6),
                (sw - 50, 300, 7),
                (2 * sw + 1000, 200, 8),
            ):
                patch = payload(ln, seed=seed)
                c.write("obj", off, patch)
                expect[off : off + ln] = patch
                assert c.read("obj", 0, len(expect)) == bytes(expect), (
                    off,
                    ln,
                )
            assert cc.perf_dump()["delta_updates"] > d0, (
                "the RMW delta path never fired"
            )
        finally:
            self._teardown_cache(cc)

    def test_delta_rmw_byte_identical_under_codec_launch_faults(self):
        """hits=1 kills only the delta dispatch (falls back to the
        materialize encode); hits=2 kills that too (host oracle) — the
        committed bytes must be identical either way."""
        from test_ec_backend import (
            FLAG_EC_OVERWRITES,
            Cluster,
            ec_pool,
            payload,
        )

        cc = self._setup_cache()
        try:
            pool, profiles = ec_pool(4, 2, flags=FLAG_EC_OVERWRITES)
            c = Cluster(pool, profiles)
            sw = pool.stripe_width
            base = payload(2 * sw, seed=11)
            c.write("obj", 0, base)
            expect = bytearray(base)
            for hits, off, ln, seed in (
                (1, 40, 500, 12),
                (2, sw + 10, 700, 13),
            ):
                global_injector().inject("codec.launch", 5, hits=hits)
                patch = payload(ln, seed=seed)
                c.write("obj", off, patch)
                expect[off : off + ln] = patch
                global_injector().clear()
                device_guard().mark_healthy()
                assert c.read("obj", 0, len(expect)) == bytes(expect), hits
        finally:
            self._teardown_cache(cc)


class TestFusionDeltaLeakGates:
    """The ISSUE 18 chaos invariant at tier-1 scope: with fusion and the
    delta path both exercised, donation_recycled_live does not move and
    the EC in-flight mempool drains to zero."""

    def test_recycled_live_and_inflight_ledger_stay_clean(self):
        from test_ec_backend import (
            FLAG_EC_OVERWRITES,
            Cluster,
            ec_pool,
            payload,
        )

        d0 = ec_dispatch.perf_dump()["pipeline.donation_recycled_live"]
        cc = device_chunk_cache()
        cc.configure(max_bytes=1 << 24)
        cc.clear()
        try:
            # fused workload
            ec = make_rs(4, 2)
            rng = np.random.default_rng(51)
            agg = _backlogged_aggregator()
            batches = [
                rng.integers(0, 256, (s, 4, 2048), dtype=np.uint8)
                for s in (1, 2, 3, 2, 1, 2, 2, 3)
            ]
            for t in _submit_all(agg, ec, batches):
                np.asarray(t)
            assert agg.perf.get("fused_launches") >= 1
            # delta workload
            pool, profiles = ec_pool(4, 2, flags=FLAG_EC_OVERWRITES)
            c = Cluster(pool, profiles)
            base = payload(2 * pool.stripe_width, seed=52)
            c.write("obj", 0, base)
            c.write("obj", 123, payload(456, seed=53))
            assert cc.perf_dump()["delta_updates"] >= 1
        finally:
            from ceph_tpu.common.options import OPTIONS

            cc.clear()
            cc.configure(
                max_bytes=int(OPTIONS["ec_tpu_device_cache_bytes"].default)
            )
        drain_all_aggregators()
        led = hbm_ledger()
        assert (
            ec_dispatch.perf_dump()["pipeline.donation_recycled_live"] == d0
        ), "fusion/delta recycled a LIVE donated buffer"
        assert led.current_bytes("ec_pipeline_inflight") == 0, (
            led.reconcile()
        )


class TestDonationBucketChurn:
    """Bucket churn cannot pin HBM (ISSUE 18 satellite): shrinking the
    learned bucket set must trim the evicted shapes' pooled outputs out
    of the mempool ledger immediately."""

    def test_bucket_shrink_trims_pooled_shapes_from_ledger(self):
        ec = make_rs(4, 2)
        rng = np.random.default_rng(61)
        agg = EncodeAggregator(
            window=2,
            max_bytes=1 << 30,
            inflight_max_bytes=1 << 30,
            pipeline_depth=2,
            fuse_max_windows=1,
            pad_buckets=4,
        )
        led = hbm_ledger()
        pooled0 = led.current_bytes("ec_donation")
        # recurring ragged group sizes (6 and 10 stripes): the learner
        # promotes both to exact-fit targets, and the donation pool
        # retains parity outputs at those geometries.  Chunk length 4096
        # keeps even the exact-fit launches above PACKED_MIN_BYTES, so
        # they stay on the donatable packed path.
        for _ in range(5):
            for s in (3, 5):
                t1 = agg.submit(
                    ec, rng.integers(0, 256, (s, 4, 4096), dtype=np.uint8)
                )
                t2 = agg.submit(
                    ec, rng.integers(0, 256, (s, 4, 4096), dtype=np.uint8)
                )
                agg.flush()
                np.asarray(t1)
                np.asarray(t2)
        drain_all_aggregators()
        learned = {s[0] for s in agg._donate_pool}
        assert {6, 10} & learned, (
            f"no exact-fit shapes pooled (got {learned}); the bucket "
            "learner or the donation pool regressed"
        )
        pooled = led.current_bytes("ec_donation")
        assert pooled > pooled0, "no donated bytes pooled; premise broken"
        # retire every learned bucket: the evicted targets' pooled
        # outputs must leave the ledger NOW, not at process exit
        agg.configure(pad_buckets=0)
        assert led.current_bytes("ec_donation") < pooled
        remaining = {s[0] for s in agg._donate_pool}
        assert not ({6, 10} & remaining), (
            f"evicted bucket shapes still pooled: {remaining}"
        )
