"""CLAY tests — layered encode/decode, sub-chunk repair bandwidth.

Models /root/reference/src/test/erasure-code/TestErasureCodeClay.cc.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.codec.clay import ErasureCodeClay
from ceph_tpu.codec.interface import EcError
from ceph_tpu.codec.registry import ErasureCodePluginRegistry


def make(k=4, m=2, d=None, **extra):
    ec = ErasureCodeClay()
    prof = {"k": str(k), "m": str(m), **extra}
    if d is not None:
        prof["d"] = str(d)
    ec.init(prof)
    return ec


def payload(ec, seed=0):
    size = ec.get_chunk_size(1) * ec.k  # one aligned stripe
    return np.random.default_rng(seed).integers(0, 256, size).astype(np.uint8).tobytes()


class TestGeometry:
    def test_params(self):
        ec = make(4, 2)  # d defaults to k+m-1=5 -> q=2, t=3, S=8
        assert (ec.q, ec.t, ec.nu, ec.sub_chunk_no) == (2, 3, 0, 8)
        assert ec.get_sub_chunk_count() == 8
        ec = make(4, 3, d=6)  # q=3, k+m=7 -> nu=2, t=3, S=27
        assert (ec.q, ec.t, ec.nu, ec.sub_chunk_no) == (3, 3, 2, 27)

    def test_d_validation(self):
        with pytest.raises(EcError):
            make(4, 2, d=3)  # d < k
        with pytest.raises(EcError):
            make(4, 2, d=6)  # d > k+m-1
        with pytest.raises(EcError):
            make(4, 2, scalar_mds="shec")

    def test_chunk_size_alignment(self):
        ec = make(4, 2)
        cs = ec.get_chunk_size(1)
        assert cs % ec.sub_chunk_no == 0
        assert ec.get_chunk_size(4 * cs) == cs


class TestEncodeDecode:
    @pytest.mark.parametrize("k,m,d", [(4, 2, 5), (3, 3, 5), (4, 3, 6)])
    def test_roundtrip_all_erasures(self, k, m, d):
        ec = make(k, m, d=d)
        n = k + m
        raw = payload(ec)
        encoded = ec.encode(set(range(n)), raw)
        chunk_size = ec.get_chunk_size(len(raw))
        data = np.frombuffer(raw, dtype=np.uint8)
        for i in range(k):
            assert np.array_equal(
                encoded[i], data[i * chunk_size : (i + 1) * chunk_size]
            )
        for nerr in range(1, m + 1):
            for erasures in itertools.combinations(range(n), nerr):
                avail = {i: encoded[i] for i in range(n) if i not in erasures}
                decoded = ec.decode(set(erasures), avail)
                for e in erasures:
                    assert np.array_equal(decoded[e], encoded[e]), (
                        (k, m, d),
                        erasures,
                    )

    def test_decode_concat(self):
        ec = make(4, 2)
        raw = payload(ec, seed=1)
        encoded = ec.encode(set(range(6)), raw)
        avail = {i: encoded[i] for i in (0, 2, 3, 5)}
        out = ec.decode_concat(avail)
        assert out[: len(raw)].tobytes() == raw


class TestRepair:
    @pytest.mark.parametrize("k,m,d", [(4, 2, 5), (4, 3, 6)])
    def test_repair_reads_fraction_and_matches(self, k, m, d):
        ec = make(k, m, d=d)
        n = k + m
        raw = payload(ec, seed=2)
        encoded = ec.encode(set(range(n)), raw)
        chunk_size = ec.get_chunk_size(len(raw))
        sc = chunk_size // ec.sub_chunk_no
        for lost in range(n):
            avail = set(range(n)) - {lost}
            assert ec.is_repair({lost}, avail)
            minimum = ec.minimum_to_decode({lost}, avail)
            assert len(minimum) == d
            # every helper reads exactly sub_chunk_no/q sub-chunks
            for _, runs in minimum.items():
                total = sum(count for _, count in runs)
                assert total == ec.sub_chunk_no // ec.q
            # build helper fragments exactly as ECBackend would (fragmented
            # sub-chunk reads, ECBackend.cc:1047-1068)
            helper_chunks = {}
            for node, runs in minimum.items():
                frags = [
                    encoded[node][off * sc : (off + count) * sc]
                    for off, count in runs
                ]
                helper_chunks[node] = np.concatenate(frags)
            repaired = ec.decode({lost}, helper_chunks, chunk_size=chunk_size)
            assert np.array_equal(repaired[lost], encoded[lost]), lost

    def test_is_repair_false_cases(self):
        ec = make(4, 2)
        # multiple wanted -> not a repair
        assert not ec.is_repair({0, 1}, {2, 3, 4, 5})
        # wanted chunk available -> not a repair
        assert not ec.is_repair({0}, {0, 1, 2, 3, 4})
        # missing same-column helper -> not a repair
        # (lost 0's column group is {0, 1} for q=2: needs 1 available)
        assert not ec.is_repair({0}, {2, 3, 4})

    def test_repair_bandwidth_savings(self):
        # The headline CLAY property: repair reads d * (1/q) chunks' worth
        # instead of k full chunks.
        ec = make(4, 2, d=5)
        frac = ec.d / ec.q  # chunks' worth of data read
        assert frac < ec.k  # 2.5 < 4


def test_plugin_registration():
    r = ErasureCodePluginRegistry()
    ec = r.factory("clay", {"k": "4", "m": "2"})
    assert ec.get_chunk_count() == 6
    assert ec.get_sub_chunk_count() == 8


def test_scalar_mds_isa():
    ec = make(4, 2, scalar_mds="isa", technique="cauchy")
    raw = payload(ec, seed=3)
    encoded = ec.encode(set(range(6)), raw)
    decoded = ec.decode({1, 4}, {i: encoded[i] for i in (0, 2, 3, 5)})
    assert np.array_equal(decoded[1], encoded[1])
    assert np.array_equal(decoded[4], encoded[4])
