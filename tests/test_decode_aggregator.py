"""DecodeAggregator semantics + batched recovery/degraded-read parity
(ISSUE 5 contracts).

Covers: every RS(4,2) and RS(8,3) erasure pattern decoded through the
aggregated path byte-identical to the host GF oracle; ticket ordering and
flush triggers mirroring tests/test_aggregator.py; sticky per-group error
containment; the "N same-pattern objects recovered in one window = O(1)
decode dispatches" launch-counter invariant through a full ECBackend
recovery flow; multi-object degraded reads sharing one launch; and the
prometheus export of the decode occupancy/launch-size histograms."""

import itertools

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import DecodeAggregator
from ceph_tpu.common.perf_counters import PerfCountersCollection
from ceph_tpu.gf.bitslice import expand_matrix, xor_matmul_host
from ceph_tpu.ops.dispatch import DECODE_LAUNCHES, LAUNCHES
from ceph_tpu.osd.osdmap import PG_NONE
from ceph_tpu.stripe import StripeInfo
from ceph_tpu.stripe import stripe as stripe_mod


def make_rs(k=4, m=2):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


def payload(sinfo, stripes, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, stripes * sinfo.stripe_width, dtype=np.uint8)


def oracle_shards(ec, data, sinfo):
    """Host-oracle per-shard streams (data + parity) for a whole object."""
    k, m = ec.k, ec.m
    shaped = data.reshape(-1, k, sinfo.chunk_size)
    bm = expand_matrix(ec.distribution_matrix()[k:])
    parity = np.stack([xor_matmul_host(bm, s) for s in shaped])
    out = {i: np.ascontiguousarray(shaped[:, i, :]).reshape(-1) for i in range(k)}
    for j in range(m):
        out[k + j] = np.ascontiguousarray(parity[:, j, :]).reshape(-1)
    return out


def erasure_patterns(n, m):
    """Every erasure pattern of 1..m shards out of n."""
    for r in range(1, m + 1):
        yield from itertools.combinations(range(n), r)


class TestAllErasurePatterns:
    """Batched decode through the aggregated path must be byte-identical
    to the host oracle for EVERY decodable erasure pattern (acceptance
    criterion)."""

    @pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
    def test_recovery_decode_all_patterns(self, k, m):
        ec = make_rs(k, m)
        sinfo = StripeInfo(k * 1024, 1024)
        data = payload(sinfo, 4, seed=k * 100 + m)
        truth = oracle_shards(ec, data, sinfo)
        agg = DecodeAggregator(window=10_000)
        pends = []
        for pat in erasure_patterns(k + m, m):
            have = {i: truth[i] for i in range(k + m) if i not in pat}
            pends.append(
                (
                    pat,
                    stripe_mod.decode_shards_launch(
                        sinfo, ec, have, set(pat), aggregator=agg
                    ),
                )
            )
        agg.flush()
        for pat, pend in pends:
            out = pend.result()
            for e in pat:
                assert np.array_equal(out[e], truth[e]), (pat, e)

    @pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
    def test_degraded_read_decode_all_data_patterns(self, k, m):
        """decode_concat (the client-read path) through the aggregator:
        the logical bytes come back exactly for every erasure pattern."""
        ec = make_rs(k, m)
        sinfo = StripeInfo(k * 1024, 1024)
        data = payload(sinfo, 2, seed=k * 10 + m)
        truth = oracle_shards(ec, data, sinfo)
        agg = DecodeAggregator(window=10_000)
        pends = []
        for pat in erasure_patterns(k + m, m):
            have = {i: truth[i] for i in range(k + m) if i not in pat}
            pends.append(
                stripe_mod.decode_concat_launch(sinfo, ec, have, aggregator=agg)
            )
        agg.flush()
        for pend in pends:
            assert np.array_equal(pend.result(), data)


class TestDecodeAggregatorCore:
    def setup_method(self):
        self.ec = make_rs(4, 2)
        self.sinfo = StripeInfo(4 * 4096, 4096)

    def _launch(self, agg, stripes, seed, lost=(1,)):
        data = payload(self.sinfo, stripes, seed)
        truth = oracle_shards(self.ec, data, self.sinfo)
        have = {i: truth[i] for i in range(6) if i not in lost}
        pend = stripe_mod.decode_shards_launch(
            self.sinfo, self.ec, have, set(lost), aggregator=agg
        )
        return truth, pend

    def test_same_pattern_submitters_coalesce_into_one_dispatch(self):
        agg = DecodeAggregator(window=8)
        subs = [self._launch(agg, 8, seed=i) for i in range(8)]
        before = DECODE_LAUNCHES.snapshot()["launches"]
        agg.flush()
        launches = DECODE_LAUNCHES.snapshot()["launches"] - before
        assert launches <= 2, launches
        # every submitter gets ITS reconstruction back, byte-exact
        for truth, pend in subs:
            assert np.array_equal(pend.result()[1], truth[1])

    def test_window_trigger_and_pending(self):
        agg = DecodeAggregator(window=4)
        pends = [self._launch(agg, 1, seed=i)[1] for i in range(3)]
        assert agg.pending() == 3
        assert not any(p.launched() for p in pends)
        assert not any(p.ready() for p in pends)
        p4 = self._launch(agg, 1, seed=9)[1]
        assert agg.pending() == 0
        assert all(p.launched() for p in pends) and p4.launched()
        assert agg.perf.get("flush_window") == 1

    def test_byte_budget_trigger(self):
        agg = DecodeAggregator(window=1000, max_bytes=3 * self.sinfo.stripe_width)
        self._launch(agg, 1, seed=0)
        assert agg.pending() == 1
        self._launch(agg, 2, seed=1)
        assert agg.pending() == 0
        assert agg.perf.get("flush_bytes") == 1

    def test_reap_forces_launch(self):
        """Materializing a windowed ticket must flush its group rather
        than deadlock (recovery barriers depend on this)."""
        agg = DecodeAggregator(window=100)
        truth, pend = self._launch(agg, 2, seed=3)
        assert not pend.launched()
        out = pend.result()
        assert np.array_equal(out[1], truth[1])
        assert agg.perf.get("flush_reap") == 1

    def test_distinct_patterns_group_separately(self):
        """Interleaved submissions of two erasure patterns: each ticket
        resolves to its own pattern's reconstruction, in order."""
        agg = DecodeAggregator(window=100)
        subs = [
            self._launch(agg, 2, seed=100 + i, lost=((1,) if i % 2 else (2, 4)))
            for i in range(6)
        ]
        assert len(agg._groups) == 2
        agg.flush()
        for i, (truth, pend) in enumerate(subs):
            out = pend.result()
            for e in (1,) if i % 2 else (2, 4):
                assert np.array_equal(out[e], truth[e])

    def test_padding_to_pow2_sliced_back(self):
        agg = DecodeAggregator(window=100)
        truth, pend = self._launch(agg, 3, seed=5)
        agg.flush()
        out = pend.result()
        assert agg.perf.get("pad_stripes") == 1  # 3 -> 4
        assert np.array_equal(out[1], truth[1])
        assert out[1].size == 3 * 4096

    def test_immediate_mode_still_counts_metrics(self):
        agg = DecodeAggregator(window=0)
        truth, pend = self._launch(agg, 2, seed=8)
        assert pend.launched()
        assert np.array_equal(pend.result()[1], truth[1])
        assert agg.perf.get("submits") == 1
        assert agg.perf.get("launches") == 1
        assert agg.perf.get("flush_immediate") == 1
        assert agg.perf.get("pad_stripes") == 0

    def test_failed_launch_is_sticky_and_reported_to_coriders(self):
        from ceph_tpu.codec.interface import EcError

        agg = DecodeAggregator(window=2)
        _, pend1 = self._launch(agg, 1, seed=0)
        real = self.ec.decode_array
        real_host = self.ec.decode_array_host

        def boom(erasures, survivors, out=None):
            # device AND host-oracle failure: only then is the error
            # sticky (a device-only failure now completes on the host)
            raise RuntimeError("injected device OOM")

        self.ec.decode_array = boom
        self.ec.decode_array_host = boom
        try:
            # second submission trips the window; its launch fails, but
            # submit must NOT raise into an arbitrary co-rider — the
            # error is sticky on the group and reported at reap
            _, pend2 = self._launch(agg, 1, seed=1)
        finally:
            self.ec.decode_array = real
            self.ec.decode_array_host = real_host
        for pend in (pend1, pend2):
            assert pend.ready()
            with pytest.raises(EcError):
                pend.result()

    def test_prometheus_export_has_histogram_families(self):
        agg = DecodeAggregator(window=2)
        for i in range(2):
            self._launch(agg, 1, seed=i)
        coll = PerfCountersCollection()
        coll.add(agg.perf)
        text = coll.prometheus_text()
        for family in ("stripes_per_launch", "tickets_per_launch", "launch_bytes"):
            assert f"ceph_tpu_ec_decode_aggregator_{family}_bucket" in text
            assert f"ceph_tpu_ec_decode_aggregator_{family}_count" in text


class TestBackendAggregatedRecovery:
    """Recovery and degraded reads through a full ECBackend cluster with
    the decode window open: correctness survives, and same-pattern
    objects share device launches."""

    def _cluster(self, k=4, m=2, window=64):
        from test_ec_backend import Cluster, ec_pool

        pool, profiles = ec_pool(k, m)
        c = Cluster(pool, profiles)
        agg = DecodeAggregator(window=window)
        for b in c.backends:
            b.decode_aggregator = agg
        return c, agg

    def _deliver_no_flush(self, c):
        """Drain the message queue WITHOUT the pump barrier, so recovery
        decodes stay windowed until an explicit flush."""
        steps = 0
        while c.queue:
            osd, msg = c.queue.pop(0)
            if osd == PG_NONE or not (0 <= osd < len(c.backends)):
                continue
            c.backends[osd].handle_message(msg)
            steps += 1
            assert steps < 100000, "message storm"

    def test_n_objects_one_pattern_one_decode_launch(self):
        from ceph_tpu.osd.pg_backend import shard_coll

        c, agg = self._cluster(window=64)
        n_obj = 6
        datas = {}
        originals = {}
        for i in range(n_obj):
            oid = f"obj{i}"
            datas[oid] = payload(
                StripeInfo(c.pool.stripe_width, c.pool.stripe_width // 4),
                2,
                seed=i,
            ).tobytes()
            c.write(oid, 0, datas[oid])
        lost = 1
        coll = shard_coll(c.pgid, lost)
        for oid in datas:
            originals[oid] = c.stores[lost].read(coll, oid, 0, 0)
            c.stores[lost]._remove(coll, oid)
            c.missing[oid] = {lost}
        res = []
        before = DECODE_LAUNCHES.snapshot()["launches"]
        for oid in datas:
            c.primary.recover_object(oid, {lost}, lambda e: res.append(e))
        # deliver all reads + replies with no barrier: every object's
        # decode lands in the shared window
        self._deliver_no_flush(c)
        assert c.primary._decode_pipe and agg.pending() == n_obj
        assert DECODE_LAUNCHES.snapshot()["launches"] == before
        c.primary.flush_decodes()  # ONE aggregated launch for all objects
        launches = DECODE_LAUNCHES.snapshot()["launches"] - before
        assert launches == 1, launches
        c.pump()  # pushes land
        for oid in datas:
            c.missing.pop(oid)
        assert res == [0] * n_obj
        for oid in datas:
            assert c.stores[lost].read(coll, oid, 0, 0) == originals[oid]

    def test_multi_object_degraded_read_one_decode_launch(self):
        c, agg = self._cluster(window=64)
        n_obj = 4
        datas = {}
        for i in range(n_obj):
            oid = f"d{i}"
            datas[oid] = payload(
                StripeInfo(c.pool.stripe_width, c.pool.stripe_width // 4),
                2,
                seed=10 + i,
            ).tobytes()
            c.write(oid, 0, datas[oid])
        c.acting[1] = PG_NONE  # one shard dark -> every read reconstructs
        out = {}
        before = DECODE_LAUNCHES.snapshot()["launches"]
        c.primary.objects_read_and_reconstruct(
            {oid: [(0, len(d))] for oid, d in datas.items()},
            lambda res: out.update(res),
        )
        c.pump()
        launches = DECODE_LAUNCHES.snapshot()["launches"] - before
        assert launches == 1, launches
        for oid, data in datas.items():
            err, bufs = out[oid]
            assert err == 0
            assert b"".join(bufs) == data

    def test_recovery_all_patterns_through_backend(self):
        """Full-cluster recovery for every RS(4,2) erasure pattern whose
        shards can all be marked missing (parity + data mixes)."""
        from ceph_tpu.osd.pg_backend import shard_coll

        c, agg = self._cluster(window=64)
        sinfo = StripeInfo(c.pool.stripe_width, c.pool.stripe_width // 4)
        for pi, pat in enumerate(erasure_patterns(6, 2)):
            oid = f"p{pi}"
            c.write(oid, 0, payload(sinfo, 2, seed=50 + pi).tobytes())
            snapshots = {}
            for s in pat:
                coll = shard_coll(c.pgid, s)
                snapshots[s] = c.stores[s].read(coll, oid, 0, 0)
                c.stores[s]._remove(coll, oid)
            c.missing[oid] = set(pat)
            res = []
            c.primary.recover_object(oid, set(pat), lambda e: res.append(e))
            c.pump()
            c.missing.pop(oid)
            assert res == [0], (pat, res)
            for s in pat:
                coll = shard_coll(c.pgid, s)
                assert c.stores[s].read(coll, oid, 0, 0) == snapshots[s], pat

    def test_decode_launch_failure_fails_recovery_cleanly(self):
        """A failed aggregated decode launch must fail the affected
        RecoveryOps (negative errno, no recovery_ops leak) and leave the
        backend able to recover the same object afterwards."""
        from ceph_tpu.osd.pg_backend import shard_coll

        c, agg = self._cluster(window=64)
        sinfo = StripeInfo(c.pool.stripe_width, c.pool.stripe_width // 4)
        data = payload(sinfo, 2, seed=77).tobytes()
        c.write("fx", 0, data)
        lost = 2
        coll = shard_coll(c.pgid, lost)
        original = c.stores[lost].read(coll, "fx", 0, 0)
        c.stores[lost]._remove(coll, "fx")
        c.missing["fx"] = {lost}
        primary = c.primary
        real = primary.ec.decode_array
        real_host = primary.ec.decode_array_host

        def boom(erasures, survivors, out=None):
            # fails on the device AND the host oracle: truly unrecoverable
            raise RuntimeError("injected decode launch failure")

        res = []
        primary.ec.decode_array = boom
        primary.ec.decode_array_host = boom
        try:
            primary.recover_object("fx", {lost}, lambda e: res.append(e))
            c.pump()  # barrier reaps the failed launch
        finally:
            primary.ec.decode_array = real
            primary.ec.decode_array_host = real_host
        assert len(res) == 1 and res[0] < 0
        assert not primary.recovery_ops
        assert not primary._decode_pipe
        # the backend recovers: the same object repairs fine afterwards
        primary.recover_object("fx", {lost}, lambda e: res.append(e))
        c.pump()
        c.missing.pop("fx")
        assert res[1] == 0
        assert c.stores[lost].read(coll, "fx", 0, 0) == original
