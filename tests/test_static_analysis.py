"""Static-analysis framework tier (ISSUE 12).

Three layers:

1. Seeded fixtures per pass: a snippet that MUST trip the pass and a
   twin that MUST pass — the linter's own regression suite, so a pass
   that silently stops detecting its bug class fails here, not in a
   production PR.
2. Framework contracts: allowlist round-trip (reason mandatory, stale
   entries fail), CLI exit codes, JSON report shape.
3. The live gate: `run_analysis()` over the real package must be clean
   — a new violation anywhere in ceph_tpu/ fails tier-1 (the CI wiring
   the ISSUE asks for), alongside a dynamic-lockdep regression that
   replays the aggregator→scheduler→pipeline→cache lock stack.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

from ceph_tpu.analysis import (
    ALLOWLIST_DIR,
    SourceTree,
    load_allowlist,
    run_analysis,
)
from ceph_tpu.analysis.passes import ALL_PASSES, PASS_BY_ID
from ceph_tpu.analysis.passes.donation import DonationLifetimePass
from ceph_tpu.analysis.passes.exceptions import ExceptionSwallowPass
from ceph_tpu.analysis.passes.ledger import LedgerDisciplinePass
from ceph_tpu.analysis.passes.locks import LockDisciplinePass
from ceph_tpu.analysis.passes.options_coherence import OptionsCoherencePass
from ceph_tpu.analysis.passes.purity import JitPurityPass

REPO = Path(__file__).resolve().parent.parent


def _tree(tmp_path: Path, files: dict[str, str]) -> SourceTree:
    """Materialize {relpath: source} as a package tree for a pass."""
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return SourceTree(root, repo_root=tmp_path)


def _keys(findings):
    return {f.key for f in findings}


class TestDonationPass:
    def test_read_after_donation_trips(self, tmp_path):
        tree = _tree(tmp_path, {"ops/k.py": """
            import functools, jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(buf):
                return buf + 1

            def caller(buf):
                out = step(buf)
                return buf.sum()  # use-after-donation
        """})
        findings = DonationLifetimePass()(tree)
        assert any("::caller::buf" in k for k in _keys(findings)), findings

    def test_rebind_idiom_passes(self, tmp_path):
        tree = _tree(tmp_path, {"ops/k.py": """
            import functools, jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(buf):
                return buf + 1

            def caller(buf):
                buf = step(buf)   # donated name rebound to the result
                return buf.sum()  # fresh buffer: fine
        """})
        assert DonationLifetimePass()(tree) == []

    def test_sibling_branch_is_not_after(self, tmp_path):
        tree = _tree(tmp_path, {"ops/k.py": """
            import jax

            def caller(f, buf, fast):
                if fast:
                    exe = jax.jit(f, donate_argnums=(0,))
                    out = exe(buf)
                else:
                    out = f(buf)  # other branch: buf not donated here
                return out
        """})
        assert DonationLifetimePass()(tree) == []

    def test_factory_donate_true_trips(self, tmp_path):
        tree = _tree(tmp_path, {"parallel/s.py": """
            def caller(build, placed):
                result = build(donate=True)(placed)
                return placed[0]  # donated via the factory call
        """})
        findings = DonationLifetimePass()(tree)
        assert any("placed" in k for k in _keys(findings)), findings

    def test_offload_runtime_module_in_scope(self, tmp_path):
        """ISSUE 20: the hoisted offload runtime is covered exactly like
        the codec module its machinery came from."""
        tree = _tree(tmp_path, {"ops/offload_runtime.py": """
            import functools, jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def launch(buf):
                return buf + 1

            def reap(buf):
                out = launch(buf)
                return buf.nbytes  # use-after-donation
        """})
        findings = DonationLifetimePass()(tree)
        assert any("::reap::buf" in k for k in _keys(findings)), findings

    def test_compressor_service_module_in_scope(self, tmp_path):
        """ISSUE 20: a compressor-package service module donating into
        its batched transform is covered too."""
        tree = _tree(tmp_path, {"compressor/device.py": """
            import functools, jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def transform(rows):
                return rows + 1

            def compress_batch(rows):
                out = transform(rows)
                return rows.sum()  # use-after-donation
        """})
        findings = DonationLifetimePass()(tree)
        assert any(
            "::compress_batch::rows" in k for k in _keys(findings)
        ), findings


class TestPurityPass:
    @pytest.mark.parametrize("body,what", [
        ("t = time.time()", "clock"),
        ("r = np.random.random()", "RNG"),
        ("lock.acquire()", "lock"),
        ("faultpoint('codec.launch')", "faultpoint"),
        ("counters.inc('launches')", "counter"),
    ])
    def test_impurity_inside_jit_trips(self, tmp_path, body, what):
        tree = _tree(tmp_path, {"ops/k.py": f"""
            import time, jax
            import numpy as np

            @jax.jit
            def kernel(x, lock=None, counters=None):
                {body}
                return x
        """})
        findings = JitPurityPass()(tree)
        assert findings, f"{what} inside @jax.jit not detected"

    def test_pure_jit_passes(self, tmp_path):
        tree = _tree(tmp_path, {"ops/k.py": """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def kernel(x):
                return jnp.sum(x ^ jnp.uint8(3))
        """})
        assert JitPurityPass()(tree) == []

    def test_host_code_outside_scope_dirs_ignored(self, tmp_path):
        # same impurity in mgr/ — the pass is scoped to the kernel dirs
        tree = _tree(tmp_path, {"mgr/m.py": """
            import time, jax

            @jax.jit
            def kernel(x):
                return time.time()
        """})
        assert JitPurityPass()(tree) == []

    def test_wrapped_local_def_trips(self, tmp_path):
        tree = _tree(tmp_path, {"codec/c.py": """
            import time, jax

            def build():
                def local(x):
                    time.monotonic()
                    return x
                return jax.jit(local)
        """})
        assert JitPurityPass()(tree), "jax.jit(local) closure not traced"


class TestExceptionPass:
    def test_silent_swallow_trips(self, tmp_path):
        tree = _tree(tmp_path, {"osd/x.py": """
            def f(store):
                try:
                    return store.read()
                except Exception:
                    pass
        """})
        findings = ExceptionSwallowPass()(tree)
        assert len(findings) == 1
        assert findings[0].key == "pkg/osd/x.py::f"

    @pytest.mark.parametrize("handler", [
        "raise",
        "dout('osd', 1, 'boom')",
        "perf.inc('errors')",
        "errors += 1",
        "return repr(e)",
        "guard.mark_degraded('x')",
    ])
    def test_traced_handlers_pass(self, tmp_path, handler):
        tree = _tree(tmp_path, {"osd/x.py": f"""
            def f(store, perf, guard, dout, errors=0):
                try:
                    return store.read()
                except Exception as e:
                    {handler}
        """})
        assert ExceptionSwallowPass()(tree) == []

    def test_narrow_except_ignored(self, tmp_path):
        tree = _tree(tmp_path, {"osd/x.py": """
            def f(store):
                try:
                    return store.read()
                except KeyError:
                    pass
        """})
        assert ExceptionSwallowPass()(tree) == []


class TestLockPass:
    @pytest.mark.parametrize("ctor", [
        "threading.Lock()", "threading.RLock()", "asyncio.Lock()",
        "threading.Condition()",
    ])
    def test_bare_lock_trips(self, tmp_path, ctor):
        tree = _tree(tmp_path, {"ops/x.py": f"""
            import asyncio, threading

            class C:
                def __init__(self):
                    self._lock = {ctor}
        """})
        assert LockDisciplinePass()(tree), f"bare {ctor} not detected"

    def test_factory_lock_passes(self, tmp_path):
        tree = _tree(tmp_path, {"ops/x.py": """
            from ceph_tpu.common.lockdep import make_lock

            class C:
                def __init__(self):
                    self._lock = make_lock("c")
        """})
        assert LockDisciplinePass()(tree) == []

    def test_condition_wrapping_factory_lock_passes(self, tmp_path):
        tree = _tree(tmp_path, {"ops/x.py": """
            import threading
            from ceph_tpu.common.lockdep import make_lock

            cv = threading.Condition(make_lock("cv"))
        """})
        assert LockDisciplinePass()(tree) == []

    def test_device_wait_under_lock_trips(self, tmp_path):
        tree = _tree(tmp_path, {"ops/x.py": """
            def f(self, buf):
                with self._lock:
                    jax.block_until_ready(buf)
        """})
        findings = LockDisciplinePass()(tree)
        assert any("wait.block_until_ready" in k for k in _keys(findings))


class TestOptionsPass:
    OPTS = {
        "knob_read": {"runtime": False},
        "knob_unread": {"runtime": False},
        "knob_rt_wired": {"runtime": True},
        "knob_rt_initonly": {"runtime": True},
    }

    def _pass(self):
        return OptionsCoherencePass(options=dict(self.OPTS))

    def _files(self):
        return {
            "common/options.py": """
                OPTIONS = {}  # synthetic table injected into the pass
            """,
            "osd/daemon.py": """
                class D:
                    def __init__(self, conf):
                        self.a = conf.get("knob_rt_initonly")
                        self.b = conf.get("knob_rt_wired")
                        conf.add_observer(
                            ["knob_rt_wired"], lambda n, v: None
                        )

                    def serve(self, conf):
                        return conf.get("knob_read")

                    def typo(self, conf):
                        return conf.get("knob_typod")
            """,
        }

    def _docs(self, tmp_path):
        docs = tmp_path / "docs"
        docs.mkdir(exist_ok=True)
        (docs / "OPTIONS.md").write_text(
            "`knob_read` `knob_unread` `knob_rt_wired` `knob_rt_initonly`"
        )

    def test_all_four_checks(self, tmp_path):
        tree = _tree(tmp_path, self._files())
        self._docs(tmp_path)
        keys = _keys(self._pass()(tree))
        assert "unread::knob_unread" in keys
        assert "unwired-runtime::knob_rt_initonly" in keys
        assert "unregistered-read::knob_typod" in keys
        # the observer-wired and live-read knobs are clean
        assert "unwired-runtime::knob_rt_wired" not in keys
        assert "unread::knob_read" not in keys
        # every option IS documented in the synthetic docs page
        assert not any(k.startswith("undocumented::") for k in keys)

    def test_undocumented_trips_without_docs(self, tmp_path):
        tree = _tree(tmp_path, self._files())
        keys = _keys(self._pass()(tree))
        assert "undocumented::knob_read" in keys


class TestLedgerPass:
    """ledger-discipline (ISSUE 13): device_put in the data-path
    packages must be threaded through a mempool-tracked helper."""

    def test_untracked_device_put_trips(self, tmp_path):
        tree = _tree(tmp_path, {"ops/stage.py": """
            import jax

            def stage(arr):
                return jax.device_put(arr)
        """})
        findings = LedgerDisciplinePass()(tree)
        assert any("::stage::device_put" in k for k in _keys(findings)), (
            findings
        )

    def test_offload_runtime_untracked_device_put_trips(self, tmp_path):
        """ISSUE 20: the offload runtime and its service modules are in
        scope — a bare device_put in ops/offload_runtime.py trips."""
        tree = _tree(tmp_path, {"ops/offload_runtime.py": """
            import jax

            def dispatch(batch):
                return jax.device_put(batch)
        """})
        findings = LedgerDisciplinePass()(tree)
        assert any(
            "::dispatch::device_put" in k for k in _keys(findings)
        ), findings

    def test_compressor_untracked_device_put_trips(self, tmp_path):
        """ISSUE 20: compressor/ joined the scoped data-path packages —
        the device plugin's placements must be ledger-tracked."""
        tree = _tree(tmp_path, {"compressor/device.py": """
            import jax

            def transform_rows_device(rows):
                return jax.device_put(rows)
        """})
        findings = LedgerDisciplinePass()(tree)
        assert any(
            "::transform_rows_device::device_put" in k
            for k in _keys(findings)
        ), findings

    def test_track_buffer_wrapper_passes(self, tmp_path):
        tree = _tree(tmp_path, {"parallel/place.py": """
            import jax
            from ceph_tpu.common.mempool import track_buffer

            def place(arr, sharding):
                return track_buffer(
                    jax.device_put(arr, sharding), "sharded_placement"
                )
        """})
        assert not LedgerDisciplinePass()(tree)

    def test_explicit_alloc_handle_passes(self, tmp_path):
        tree = _tree(tmp_path, {"ops/cache.py": """
            import jax

            def put(self, arr):
                buf = jax.device_put(arr)
                self.mem = ledger().alloc("device_cache", arr.nbytes, buf=buf)
                return buf
        """})
        assert not LedgerDisciplinePass()(tree)

    def test_unrelated_alloc_does_not_silence(self, tmp_path):
        """Only a LEDGER alloc counts: an `.alloc` on an arbitrary
        receiver must not excuse a bare device_put."""
        tree = _tree(tmp_path, {"ops/arena.py": """
            import jax

            def stage(self, arr):
                slot = self.arena.alloc(arr.nbytes)
                return jax.device_put(arr)
        """})
        findings = LedgerDisciplinePass()(tree)
        assert any("::stage::device_put" in k for k in _keys(findings)), (
            findings
        )

    def test_untracked_sibling_of_tracked_put_still_trips(self, tmp_path):
        """One wrapped placement must not silence a bare one next to
        it — wrapping is a per-call property."""
        tree = _tree(tmp_path, {"ops/mixed.py": """
            import jax
            from ceph_tpu.common.mempool import track_buffer

            def stage(a, b):
                placed = track_buffer(jax.device_put(a), "scratch")
                return placed, jax.device_put(b)
        """})
        findings = LedgerDisciplinePass()(tree)
        assert any("::stage::device_put" in k for k in _keys(findings)), (
            findings
        )

    def test_keyword_wrapped_device_put_passes(self, tmp_path):
        tree = _tree(tmp_path, {"ops/kw.py": """
            import jax
            from ceph_tpu.common.mempool import track_buffer

            def place(arr):
                return track_buffer(buf=jax.device_put(arr))
        """})
        assert not LedgerDisciplinePass()(tree)

    def test_out_of_scope_packages_ignored(self, tmp_path):
        tree = _tree(tmp_path, {"mgr/module.py": """
            import jax

            def stage(arr):
                return jax.device_put(arr)
        """})
        assert not LedgerDisciplinePass()(tree)


class TestAllowlist:
    def test_reason_mandatory(self, tmp_path):
        p = tmp_path / "x.allow"
        p.write_text("some::key\n")
        with pytest.raises(ValueError, match="no\\s+reason"):
            load_allowlist(p)
        p.write_text("some::key |   \n")
        with pytest.raises(ValueError, match="no\\s+reason"):
            load_allowlist(p)

    def test_round_trip_and_stale_detection(self, tmp_path):
        tree = _tree(tmp_path, {"osd/x.py": """
            def f(store):
                try:
                    return store.read()
                except Exception:
                    pass
        """})
        adir = tmp_path / "allow"
        adir.mkdir()
        # 1) unallowlisted -> finding
        report = run_analysis(tree, passes=[ExceptionSwallowPass()],
                              allowlist_dir=adir)
        assert not report["ok"]
        key = report["findings"][0]["key"]
        # 2) allowlisted with a reason -> clean, and the reason rides
        (adir / "exception-swallowing.allow").write_text(
            f"{key} | fixture: silence is the point\n"
        )
        report = run_analysis(tree, passes=[ExceptionSwallowPass()],
                              allowlist_dir=adir)
        assert report["ok"], report
        assert report["allowlisted"][0]["reason"].startswith("fixture")
        # 3) stale entry (code fixed, suppression left behind) -> fails
        clean = _tree(tmp_path / "clean", {"osd/x.py": "def f():\n    pass\n"})
        report = run_analysis(clean, passes=[ExceptionSwallowPass()],
                              allowlist_dir=adir)
        assert not report["ok"]
        assert report["stale_allowlist"], report

    def test_real_allowlists_parse_with_reasons(self):
        for p in ALL_PASSES:
            path = ALLOWLIST_DIR / f"{p.PASS_ID}.allow"
            entries = load_allowlist(path)
            for key, reason in entries.items():
                assert len(reason) > 20, (
                    f"{p.PASS_ID}: allowlist reason for {key!r} is too "
                    "thin to justify a suppression"
                )


class TestCli:
    def _run(self, *args, cwd=None):
        return subprocess.run(
            [sys.executable, "-m", "ceph_tpu.analysis", *args],
            capture_output=True, text=True, cwd=cwd or REPO, timeout=300,
        )

    def test_list_inventory(self):
        r = self._run("--list")
        assert r.returncode == 0
        for pid in PASS_BY_ID:
            assert pid in r.stdout

    def test_clean_tree_exits_zero_with_json(self, tmp_path):
        out = tmp_path / "report.json"
        r = self._run("--json", str(out))
        assert r.returncode == 0, r.stdout + r.stderr
        report = json.loads(out.read_text())
        assert report["ok"] is True
        assert set(report["passes"]) == set(PASS_BY_ID)

    SEEDS = {
        "lock-discipline": "import threading\nL = threading.Lock()\n",
        "exception-swallowing": (
            "def f(s):\n    try:\n        return s.read()\n"
            "    except Exception:\n        pass\n"
        ),
        "jit-purity": (
            "import time, jax\n\n@jax.jit\ndef k(x):\n"
            "    time.time()\n    return x\n"
        ),
        "donation-lifetime": (
            "import functools, jax\n\n"
            "@functools.partial(jax.jit, donate_argnums=(0,))\n"
            "def step(b):\n    return b\n\n"
            "def caller(b):\n    out = step(b)\n    return b.sum()\n"
        ),
    }

    @pytest.mark.parametrize("pass_id", sorted(SEEDS))
    def test_seeded_violation_exits_nonzero(self, tmp_path, pass_id):
        """The exit-code contract, end to end: `python -m
        ceph_tpu.analysis --root <seeded tree> --pass <id>` exits 1 and
        names the pass."""
        root = tmp_path / "pkg"
        (root / "ops").mkdir(parents=True)
        (root / "ops" / "x.py").write_text(self.SEEDS[pass_id])
        r = self._run("--root", str(root), "--pass", pass_id)
        assert r.returncode == 1, (pass_id, r.stdout, r.stderr)
        assert pass_id in r.stdout

    def test_seeded_config_violation_exits_nonzero(self, tmp_path):
        """config-coherence via the CLI: a typo'd conf.get on a foreign
        tree (no other files, so only the unregistered-read finding plus
        table-side findings can fire)."""
        root = tmp_path / "pkg"
        (root / "osd").mkdir(parents=True)
        (root / "osd" / "x.py").write_text(
            "def f(conf):\n    return conf.get('no_such_knob_xyz')\n"
        )
        r = self._run("--root", str(root), "--pass", "config-coherence")
        assert r.returncode == 1
        assert "unregistered-read::no_such_knob_xyz" in r.stdout


class TestLiveTreeGate:
    """The CI wiring: the real package must stay clean — a new finding
    anywhere in ceph_tpu/ fails tier-1 here."""

    def test_package_runs_clean(self):
        report = run_analysis()
        msgs = [
            f"{f['file']}:{f['line']}: [{f['pass']}] {f['message']}"
            for f in report["findings"]
        ] + [s["message"] for s in report["stale_allowlist"]]
        assert report["ok"], (
            "static analysis found unallowlisted violations:\n"
            + "\n".join(msgs)
        )
        # every pass actually executed against the live tree
        assert set(report["passes"]) == set(PASS_BY_ID)


class TestLockdepStackRegression:
    """Dynamic half of the tentpole: replay the aggregator → launch
    scheduler → pipeline-gauge → device-cache → perf-counter lock stack
    with lockdep ON and assert the ordering graph is acyclic-consistent
    (zero violations) and actually engaged."""

    def test_aggregated_encode_stack_is_clean(self):
        from ceph_tpu.codec import ErasureCodeTpuRs
        from ceph_tpu.codec.matrix_codec import EncodeAggregator
        from ceph_tpu.common import lockdep
        from ceph_tpu.ops.device_cache import device_chunk_cache

        assert lockdep.enabled(), "tier-1 must run with CEPH_TPU_LOCKDEP=1"
        violations0 = lockdep.violations()
        ec = ErasureCodeTpuRs()
        ec.init({"k": "4", "m": "2"})
        agg = EncodeAggregator(window=4, pipeline_depth=2)
        rng = np.random.default_rng(7)
        tickets = [
            agg.submit(
                ec, rng.integers(0, 256, (2, 4, 512)).astype(np.uint8)
            )
            for _ in range(8)
        ]
        agg.flush()
        for t in tickets:
            np.asarray(t.result())
        # touch the device cache (the cache lock participates too)
        cache = device_chunk_cache()
        cache.put("lockdep-oid", 0, 1, np.zeros(256, dtype=np.uint8))
        cache.get("lockdep-oid", 0, 1)
        cache.invalidate_object("lockdep-oid")
        assert lockdep.violations() == violations0, (
            "lock-order violation in the aggregated encode stack"
        )
        edges = lockdep.edges()
        assert edges, "instrumented locks never engaged"
        # the aggregator lock is held around perf accounting — the
        # canonical edge that proves the stack is instrumented end to end
        assert any("ec_aggregator" in src for src in edges), edges

    def test_inverted_order_still_raises_and_counts(self):
        from ceph_tpu.common import lockdep
        from ceph_tpu.common.lockdep import (
            DebugLock,
            LockOrderError,
            make_rlock,
        )

        v0 = lockdep.violations()
        a, b = DebugLock("SA12_A"), DebugLock("SA12_B")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(LockOrderError):
                a.acquire()
        assert lockdep.violations() == v0 + 1
        # make_rlock: reentrant on the same instance, still validated
        # for cross-lock ordering on the outermost acquire
        r = make_rlock("SA12_R")
        with r:
            with r:  # no self-deadlock false positive
                pass
        with b:
            with r:  # establishes SA12_B -> SA12_R
                pass
        with r:
            with pytest.raises(LockOrderError):
                b.acquire()  # inversion: SA12_R -> SA12_B
        assert lockdep.violations() == v0 + 2
