"""bench.py TPU-child failure taxonomy (ISSUE 8 satellite): the parent
collapses rc / deadline / watchdog-stage evidence into one of four
machine-diffable causes, so BENCH_r*.json fallback patterns are
comparable without parsing free-text error strings."""

import json

import pytest

from bench import _failure_info, _parse_result_lines, classify_tpu_failure


class TestClassifier:
    @pytest.mark.parametrize(
        "rc,deadline,stage,want",
        [
            # the watchdog's import stage overran: axon sitecustomize
            # blocking in `import jax`
            (5, False, "import_jax", "import_hang"),
            # jax.devices() overran its ~45 s sub-deadline (the round
            # 4-5 shape; the parent retries this exactly once)
            (6, False, "backend_init", "backend_init_hang"),
            (6, False, None, "backend_init_hang"),
            # a later warm-up/measure stage hung
            (5, False, "warmup_probe", "stage_hang"),
            (5, False, "decode_warmup", "stage_hang"),
            (5, False, None, "stage_hang"),
            # whole-child parent deadline with no stage report
            (None, True, None, "stage_hang"),
            # the child FAILED rather than hung
            (3, False, None, "device_error"),   # no TPU on host
            (4, False, None, "device_error"),   # parity mismatch
            (1, False, None, "device_error"),   # crash
            (0, False, None, "device_error"),   # exited clean, no JSON
        ],
    )
    def test_taxonomy(self, rc, deadline, stage, want):
        assert classify_tpu_failure(rc, deadline, stage) == want


class TestFailureInfo:
    def test_reads_watchdog_stage_line_from_child_stdout(self):
        """The child watchdog prints {"failure_stage": ...} before
        hard-exiting; the parent folds it into the taxonomy record."""
        stdout = (
            b"not json\n"
            + json.dumps({"failure_stage": "backend_init"}).encode()
            + b"\n"
        )
        info = _failure_info("tpu", stdout, 6, False, "tpu child exited rc=6")
        assert info["cause"] == "backend_init_hang"
        assert info["stage"] == "backend_init"
        assert info["rc"] == 6
        assert "rc=6" in info["detail"]

    def test_no_stage_line_classifies_from_rc(self):
        info = _failure_info("tpu", b"", 4, False, "tpu child exited rc=4")
        assert info["cause"] == "device_error"
        assert "stage" not in info

    def test_parse_result_lines_merges_stage_with_salvage(self):
        """A salvaged child that printed its headline AND a later
        watchdog stage line merges both (the parent keeps the result and
        ignores the stage)."""
        stdout = (
            json.dumps({"gbps": 2.0, "platform": "tpu"}).encode() + b"\n"
            + json.dumps({"failure_stage": "multichip_warmup"}).encode()
            + b"\n"
        )
        merged = _parse_result_lines(stdout)
        assert merged["gbps"] == 2.0
        assert merged["failure_stage"] == "multichip_warmup"
