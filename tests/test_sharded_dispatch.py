"""Sharded dispatch mode (ISSUE 6): aggregated coding launches spanning
the 8-device virtual CPU mesh.

CPU CI exercises REAL 8-device meshes the way the driver's multi-chip
dry-run does: the session-wide conftest forces
`--xla_force_host_platform_device_count=8` before jax initializes, so
every test here runs against eight actual XLA devices (no mocks).
Coverage pinned by the ISSUE 6 satellite: byte-identical parity vs the
host oracle through the sharded aggregator path, non-divisible batch
remainder handling, and single-device fallback when the mesh is
degenerate (sharding disabled)."""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import DecodeAggregator, EncodeAggregator
from ceph_tpu.gf import gf_matmul, isa_rs_vandermonde_matrix
from ceph_tpu.ops.dispatch import (
    DECODE_LAUNCHES,
    DEVICES_PER_LAUNCH,
    LAUNCHES,
    SHARDED_LAUNCHES,
)
from ceph_tpu.parallel import dispatch as shard_dispatch


@pytest.fixture(autouse=True)
def _shard_policy():
    """Give every test a known shard policy and restore defaults after
    (the policy is process-wide; leaking a tiny threshold would shard
    unrelated suites' launches)."""
    shard_dispatch.configure(
        min_batch=shard_dispatch.DEFAULT_MIN_BATCH,
        devices=shard_dispatch.DEFAULT_DEVICES,
    )
    yield
    shard_dispatch.configure(
        min_batch=shard_dispatch.DEFAULT_MIN_BATCH,
        devices=shard_dispatch.DEFAULT_DEVICES,
    )


def make_rs(k=8, m=3):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


def _host_parity(ec, data: np.ndarray) -> np.ndarray:
    gfm = isa_rs_vandermonde_matrix(ec.k, ec.m)[ec.k:]
    return np.stack([gf_matmul(gfm, stripe) for stripe in data])


def _batch(S, k, L, seed=0):
    return np.random.default_rng(seed).integers(0, 256, (S, k, L), dtype=np.uint8)


class TestShardedEncode:
    def test_above_threshold_is_one_sharded_launch_spanning_mesh(self):
        """An aggregated-size batch above ec_tpu_shard_min_batch must
        dispatch as ONE launch spanning all 8 devices, byte-identical to
        the host oracle (the ISSUE 6 acceptance invariant)."""
        ec = make_rs()
        shard_dispatch.configure(min_batch=16)
        data = _batch(32, 8, 4096, seed=1)
        t0, s0 = LAUNCHES.snapshot(), SHARDED_LAUNCHES.snapshot()
        d0 = DEVICES_PER_LAUNCH.snapshot()
        parity = np.asarray(ec.encode_array(data))
        t1, s1 = LAUNCHES.snapshot(), SHARDED_LAUNCHES.snapshot()
        assert t1["launches"] - t0["launches"] == 1
        assert s1["launches"] - s0["launches"] == 1
        assert s1["stripes"] - s0["stripes"] == 32
        d1 = DEVICES_PER_LAUNCH.snapshot()
        assert d1.get(8, 0) - d0.get(8, 0) == 1, "launch did not span 8 devices"
        assert np.array_equal(parity, _host_parity(ec, data))

    def test_below_threshold_stays_single_device(self):
        ec = make_rs()
        shard_dispatch.configure(min_batch=64)
        data = _batch(32, 8, 4096, seed=2)
        s0 = SHARDED_LAUNCHES.snapshot()
        parity = np.asarray(ec.encode_array(data))
        assert SHARDED_LAUNCHES.snapshot()["launches"] == s0["launches"]
        assert np.array_equal(parity, _host_parity(ec, data))

    def test_non_divisible_remainder(self):
        """37 stripes over 8 shards: the dispatcher pads with zero
        stripes (exact for GF maps) and slices back — bytes identical."""
        ec = make_rs()
        shard_dispatch.configure(min_batch=16)
        data = _batch(37, 8, 4096, seed=3)
        s0 = SHARDED_LAUNCHES.snapshot()
        parity = np.asarray(ec.encode_array(data))
        s1 = SHARDED_LAUNCHES.snapshot()
        assert s1["launches"] - s0["launches"] == 1
        assert parity.shape == (37, 3, 4096)
        assert np.array_equal(parity, _host_parity(ec, data))

    def test_single_device_fallback_when_degenerate(self):
        """ec_tpu_shard_devices=1 (a degenerate mesh) must keep every
        launch single-device and still byte-exact."""
        ec = make_rs()
        shard_dispatch.configure(min_batch=16, devices=1)
        data = _batch(32, 8, 4096, seed=4)
        s0 = SHARDED_LAUNCHES.snapshot()
        d0 = DEVICES_PER_LAUNCH.snapshot()
        parity = np.asarray(ec.encode_array(data))
        assert SHARDED_LAUNCHES.snapshot()["launches"] == s0["launches"]
        d1 = DEVICES_PER_LAUNCH.snapshot()
        assert d1.get(1, 0) - d0.get(1, 0) == 1
        assert np.array_equal(parity, _host_parity(ec, data))

    def test_lead_dims_collapse_into_stripe_axis(self):
        """N-D batches (CLAY's (planes, S, k+nu, sc) fragment launches)
        collapse their lead dims into one sharded stripe axis; output
        keeps the caller's geometry and bytes stay exact."""
        ec = make_rs(4, 2)
        shard_dispatch.configure(min_batch=16)
        rng = np.random.default_rng(6)
        data = rng.integers(0, 256, (4, 8, 4, 4096), dtype=np.uint8)
        s0 = SHARDED_LAUNCHES.snapshot()
        parity = np.asarray(ec.encode_array(data))
        s1 = SHARDED_LAUNCHES.snapshot()
        assert s1["launches"] - s0["launches"] == 1
        assert s1["stripes"] - s0["stripes"] == 32
        assert parity.shape == (4, 8, 2, 4096)
        flat = data.reshape(-1, 4, 4096)
        want = _host_parity(ec, flat).reshape(4, 8, 2, 4096)
        assert np.array_equal(parity, want)

    def test_small_bytes_never_shard(self):
        """Batches under PACKED_MIN_BYTES stay on the shared small-input
        kernel even when the stripe count crosses the threshold."""
        ec = make_rs(4, 2)
        shard_dispatch.configure(min_batch=16)
        data = _batch(32, 4, 64, seed=5)  # 8 KiB total
        s0 = SHARDED_LAUNCHES.snapshot()
        parity = np.asarray(ec.encode_array(data))
        assert SHARDED_LAUNCHES.snapshot()["launches"] == s0["launches"]
        assert np.array_equal(parity, _host_parity(ec, data))


class TestShardedAggregatorPath:
    """The production route: concurrent submissions coalesce in the
    aggregator, the padded flush crosses the shard threshold, and the
    ONE resulting launch spans the mesh."""

    def test_encode_aggregator_flush_shards(self):
        ec = make_rs()
        shard_dispatch.configure(min_batch=16)
        agg = EncodeAggregator(window=8, max_bytes=1 << 30)
        datas = [_batch(8, 8, 4096, seed=10 + i) for i in range(8)]
        t0, s0 = LAUNCHES.snapshot(), SHARDED_LAUNCHES.snapshot()
        tickets = [agg.submit(ec, d) for d in datas]  # 8th submit flushes
        outs = [np.asarray(t) for t in tickets]
        t1, s1 = LAUNCHES.snapshot(), SHARDED_LAUNCHES.snapshot()
        assert t1["launches"] - t0["launches"] == 1, "window did not coalesce"
        assert s1["launches"] - s0["launches"] == 1, "flush did not shard"
        for d, out in zip(datas, outs):
            assert np.array_equal(out, _host_parity(ec, d))

    def test_encode_aggregator_donation_pool_recycles_sharded_buffers(self):
        """Two same-geometry flush cycles: the second consumes the pooled
        sharded output buffer; bytes stay exact either way."""
        ec = make_rs()
        shard_dispatch.configure(min_batch=16)
        agg = EncodeAggregator(window=4, max_bytes=1 << 30)
        for round_ in range(2):
            datas = [_batch(16, 8, 4096, seed=20 + 4 * round_ + i) for i in range(4)]
            tickets = [agg.submit(ec, d) for d in datas]
            for d, t in zip(datas, tickets):
                assert np.array_equal(np.asarray(t), _host_parity(ec, d))

    def test_decode_aggregator_flush_shards(self):
        """Recovery-shaped decodes (one erasure signature, many objects)
        coalesce into one sharded DECODE launch, reconstructions exact."""
        ec = make_rs()
        shard_dispatch.configure(min_batch=16)
        erasures = [0, 5, 9]
        idx = ec.decode_index(erasures)
        agg = DecodeAggregator(window=4, max_bytes=1 << 30)
        datas = [_batch(8, 8, 4096, seed=30 + i) for i in range(4)]
        fulls = [np.concatenate([d, _host_parity(ec, d)], axis=1) for d in datas]
        d0, s0 = DECODE_LAUNCHES.snapshot(), SHARDED_LAUNCHES.snapshot()
        tickets = [
            agg.submit(ec, erasures, full[:, idx, :].copy()) for full in fulls
        ]
        outs = [np.asarray(t) for t in tickets]
        d1, s1 = DECODE_LAUNCHES.snapshot(), SHARDED_LAUNCHES.snapshot()
        assert d1["launches"] - d0["launches"] == 1
        assert s1["launches"] - s0["launches"] == 1
        for full, out in zip(fulls, outs):
            assert np.array_equal(out, full[:, erasures, :])

    def test_direct_decode_array_shards_and_matches(self):
        ec = make_rs()
        shard_dispatch.configure(min_batch=16)
        erasures = [1, 9]
        idx = ec.decode_index(erasures)
        data = _batch(24, 8, 4096, seed=40)
        full = np.concatenate([data, _host_parity(ec, data)], axis=1)
        s0 = SHARDED_LAUNCHES.snapshot()
        rec = np.asarray(ec.decode_array(erasures, full[:, idx, :].copy()))
        assert SHARDED_LAUNCHES.snapshot()["launches"] - s0["launches"] == 1
        assert np.array_equal(rec, full[:, erasures, :])


class TestShardPolicy:
    def test_device_cap_respected(self):
        """ec_tpu_shard_devices=4 builds a 4-wide mesh even with 8
        visible devices."""
        ec = make_rs()
        shard_dispatch.configure(min_batch=16, devices=4)
        data = _batch(32, 8, 4096, seed=50)
        d0 = DEVICES_PER_LAUNCH.snapshot()
        parity = np.asarray(ec.encode_array(data))
        d1 = DEVICES_PER_LAUNCH.snapshot()
        assert d1.get(4, 0) - d0.get(4, 0) == 1
        assert np.array_equal(parity, _host_parity(ec, data))

    def test_fewer_stripes_than_shards_stays_single_device(self):
        """A mesh wider than the batch would place zero real stripes on
        some devices — the policy declines to shard."""
        ec = make_rs()
        shard_dispatch.configure(min_batch=2)
        data = _batch(4, 8, 8192, seed=51)  # >= PACKED_MIN_BYTES, 4 < 8
        s0 = SHARDED_LAUNCHES.snapshot()
        parity = np.asarray(ec.encode_array(data))
        assert SHARDED_LAUNCHES.snapshot()["launches"] == s0["launches"]
        assert np.array_equal(parity, _host_parity(ec, data))

    def test_settings_roundtrip(self):
        shard_dispatch.configure(min_batch=7, devices=3)
        assert shard_dispatch.settings() == (7, 3)
