"""Race-detection tier: lockdep ordering validation + asyncio debug mode.

Models the reference's sanitizer strategy (src/common/lockdep.{h,cc} in
debug builds; CMakeLists' tsan/helgrind tiers): lock-order cycles are
latent deadlocks and must fail even when the deadly interleaving never
runs.  The cluster tier at the bottom runs real daemons with lockdep
instrumented locks AND the event loop's debug mode on, asserting the
whole stack is ordering-clean.
"""

import asyncio
import threading

import pytest

from ceph_tpu.common import lockdep
from ceph_tpu.common.lockdep import (
    DebugAsyncLock,
    DebugLock,
    LockOrderError,
)


@pytest.fixture(autouse=True)
def _fresh_lockdep():
    # Isolate: swap in a private registry instead of clear()ing the
    # process-wide one.  Tier-1 runs the WHOLE suite with lockdep on
    # (conftest.py CEPH_TPU_LOCKDEP=1), and this file's deterministic
    # unit fixtures must neither erase the ordering edges the rest of
    # the suite has accumulated nor switch validation off afterward.
    was_enabled = lockdep.enabled()
    saved = lockdep._REGISTRY
    lockdep._REGISTRY = lockdep._Registry()
    lockdep.enable()
    yield
    lockdep._REGISTRY = saved
    if not was_enabled:
        lockdep.disable()


class TestThreadLockdep:
    def test_consistent_order_is_clean(self):
        a, b = DebugLock("A"), DebugLock("B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert "B" in lockdep.edges()["A"]

    def test_inverted_order_raises(self):
        a, b = DebugLock("A"), DebugLock("B")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(LockOrderError):
                a.acquire()

    def test_self_deadlock_detected(self):
        a = DebugLock("A")
        with a:
            with pytest.raises(LockOrderError):
                a.acquire()

    def test_three_lock_cycle_detected(self):
        a, b, c = DebugLock("A"), DebugLock("B"), DebugLock("C")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with pytest.raises(LockOrderError):
                a.acquire()  # C -> A closes A -> B -> C

    def test_held_sets_are_per_thread(self):
        a, b = DebugLock("A"), DebugLock("B")
        errors = []

        def t1():
            with a:
                barrier.wait()
                barrier.wait()

        def t2():
            barrier.wait()
            try:
                with b:  # t1 holds A, but THIS thread holds nothing: clean
                    pass
            except LockOrderError as e:  # pragma: no cover
                errors.append(e)
            barrier.wait()

        barrier = threading.Barrier(2)
        ts = [threading.Thread(target=t1), threading.Thread(target=t2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors


class TestAsyncLockdep:
    def test_inverted_order_raises_across_tasks(self):
        async def run():
            a, b = DebugAsyncLock("LA"), DebugAsyncLock("LB")

            async with a:
                async with b:
                    pass

            async with b:
                with pytest.raises(LockOrderError):
                    await a.acquire()

        asyncio.run(run())

    def test_tasks_have_independent_held_sets(self):
        async def run():
            a, b = DebugAsyncLock("LA"), DebugAsyncLock("LB")
            started = asyncio.Event()
            release = asyncio.Event()

            async def holder():
                async with a:
                    started.set()
                    await release.wait()

            t = asyncio.create_task(holder())
            await started.wait()
            async with b:  # this task holds nothing else: no edge from A
                pass
            release.set()
            await t

        asyncio.run(run())


class TestClusterUnderRaceDetection:
    def test_cluster_workload_is_ordering_clean(self, tmp_path):
        """Full stack — mons, OSDs (EC I/O), MDS, client — with lockdep
        instrumenting the plan-cache/messenger/MDS locks AND asyncio debug
        mode on: any lock-order inversion or re-entry anywhere fails the
        tier (the reference's debug-mutex + lockdep CI tier)."""
        from ceph_tpu.client import Rados
        from ceph_tpu.mds import MDS, CephFSClient

        from test_cluster import start_cluster, stop_cluster

        async def run():
            asyncio.get_event_loop().set_debug(True)
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "ld21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("ldec", "erasure", profile="ld21", pg_num=2)
            await client.pool_create("ldfs", "replicated", size=2, pg_num=2)
            ioctx = await client.open_ioctx("ldec")
            fs_io = await client.open_ioctx("ldfs")

            payload = bytes(range(256)) * 64
            await ioctx.write_full("obj", payload)
            assert await ioctx.read("obj") == payload

            mds = MDS(fs_io, fs_io)
            await mds.start()
            fsc = CephFSClient(mds.addr, fs_io)
            await fsc.mkdir("/d")
            await fsc.write_file("/d/f", b"race-free bytes")
            assert await fsc.read_file("/d/f") == b"race-free bytes"
            await fsc.shutdown()
            await mds.stop()

            await client.shutdown()
            await stop_cluster(mons, osds)

        # LockOrderError anywhere in the stack propagates and fails here
        asyncio.run(run())
        assert lockdep.edges()  # the instrumented locks really engaged


class TestReviewedSemantics:
    def test_trylock_never_raises_but_records(self):
        a, b = DebugLock("TA"), DebugLock("TB")
        with a:
            with b:
                pass
        with b:
            # trylock of A under B inverts the order but cannot deadlock:
            # it must succeed (or fail) without raising
            assert a.acquire(blocking=False)
            a.release()
        # the ordering it exhibited is still recorded
        assert "TA" in lockdep.edges().get("TB", set())

    def test_failed_trylock_does_not_pollute_graph(self):
        a = DebugLock("FA")
        b = DebugLock("FB")
        a._lock.acquire()  # someone else holds A
        with b:
            assert not a.acquire(blocking=False)
        a._lock.release()
        assert "FA" not in lockdep.edges().get("FB", set())

    def test_cross_task_release_edits_acquirer_stack(self):
        async def run():
            lock = DebugAsyncLock("XT")
            acquired = asyncio.Event()
            handed_off = asyncio.Event()

            async def acquirer():
                await lock.acquire()
                acquired.set()
                await handed_off.wait()
                # our stack must be clean after the OTHER task released
                other = DebugAsyncLock("XT2")
                async with other:
                    pass
                assert "XT" not in lockdep.edges().get("XT2", set())
                # and re-acquiring is not a false self-deadlock
                await lock.acquire()
                lock.release()

            async def releaser():
                await acquired.wait()
                lock.release()  # legal asyncio.Lock handoff
                handed_off.set()

            await asyncio.gather(acquirer(), releaser())

        asyncio.run(run())

    def test_singleton_lock_instruments_after_late_enable(self):
        """make_lock products created while lockdep is OFF (module-level
        singletons at import time) must still validate once enabled."""
        lockdep.disable()
        lock = lockdep.make_lock("LATE")
        with lock:  # plain behavior while disabled
            pass
        lockdep.enable()
        other = DebugLock("LATE2")
        with lock:
            with other:
                pass
        with other:
            with pytest.raises(LockOrderError):
                lock.acquire()
