"""cephx auth tests (src/auth/cephx mirror).

Models the reference's auth behaviors: keyring file round trip, mutual
challenge/response success, bad-key rejection, unknown-entity rejection
without existence leaks, ticket verification, and the messenger-level
handshake gating real connections.
"""

import asyncio

import pytest

from ceph_tpu.auth import AuthError, CephxAuth, KeyRing, generate_secret
from ceph_tpu.msg.messages import MPing
from ceph_tpu.msg.messenger import Dispatcher, Messenger


class TestKeyRing:
    def test_roundtrip(self, tmp_path):
        kr = KeyRing()
        s1 = kr.add("client.admin")
        s2 = kr.add("osd.0")
        path = str(tmp_path / "keyring")
        kr.save(path)
        loaded = KeyRing.load(path)
        assert loaded.get("client.admin") == s1
        assert loaded.get("osd.0") == s2
        assert loaded.entities() == ["client.admin", "osd.0"]

    def test_ini_format(self):
        kr = KeyRing()
        kr.add("mon.")
        text = kr.dumps()
        assert text.startswith("[mon.]")
        assert "key = " in text


class _Pipe:
    """In-memory frame channel for protocol-level tests."""

    def __init__(self):
        self.a_to_b: asyncio.Queue = asyncio.Queue()
        self.b_to_a: asyncio.Queue = asyncio.Queue()

    def end_a(self):
        async def send(tag, segs):
            await self.a_to_b.put((tag, segs))

        async def recv():
            return await self.b_to_a.get()

        return send, recv

    def end_b(self):
        async def send(tag, segs):
            await self.b_to_a.put((tag, segs))

        async def recv():
            return await self.a_to_b.get()

        return send, recv


def run_handshake(client: CephxAuth, server: CephxAuth):
    async def go():
        pipe = _Pipe()
        c = asyncio.create_task(client.client_auth(*pipe.end_a()))
        s = asyncio.create_task(server.server_auth(*pipe.end_b()))
        return await asyncio.gather(c, s)

    return asyncio.run(go())


class TestCephxProtocol:
    def test_success_and_ticket(self):
        kr = KeyRing()
        secret = kr.add("client.admin")
        server = CephxAuth("mon.a", kr.add("mon.a"), keyring=kr)
        client = CephxAuth.for_client("client.admin", secret)
        (ticket, client_key), (entity, server_key) = run_handshake(
            client, server
        )
        assert entity == "client.admin"
        assert server.verify_ticket(ticket) == "client.admin"
        # both ends derive the SAME connection secret from the transcript
        # (crypto_onwire's session key); 16 bytes = AES-128
        assert client_key == server_key and len(client_key) == 16

    def test_bad_key_rejected(self):
        kr = KeyRing()
        kr.add("client.admin")
        server = CephxAuth("mon.a", kr.add("mon.a"), keyring=kr)
        client = CephxAuth.for_client("client.admin", generate_secret())
        with pytest.raises(AuthError):
            run_handshake(client, server)

    def test_unknown_entity_rejected(self):
        kr = KeyRing()
        server = CephxAuth("mon.a", kr.add("mon.a"), keyring=kr)
        client = CephxAuth.for_client("client.ghost", generate_secret())
        with pytest.raises(AuthError):
            run_handshake(client, server)

    def test_forged_ticket_rejected(self):
        kr = KeyRing()
        server = CephxAuth("mon.a", kr.add("mon.a"), keyring=kr)
        other = CephxAuth("mon.b", generate_secret(), keyring=kr)
        ticket = other.issue_ticket("client.evil")
        assert server.verify_ticket(ticket) is None


class _Sink(Dispatcher):
    def __init__(self):
        self.got = []

    def ms_dispatch(self, conn, msg):
        self.got.append(msg)
        return True


class TestMessengerAuth:
    def test_authenticated_session(self):
        async def run():
            kr = KeyRing()
            kr.add("osd.0")
            kr.add("osd.1")
            server_auth = CephxAuth.for_daemon("osd.0", kr)
            client_auth = CephxAuth.for_daemon("osd.1", kr)
            srv = Messenger("osd.0", auth=server_auth)
            sink = _Sink()
            srv.add_dispatcher_tail(sink)
            await srv.bind("127.0.0.1:0")
            cli = Messenger("osd.1", auth=client_auth)
            await cli.send_to(srv.addr, MPing(stamp=1.0))
            await asyncio.sleep(0.1)
            assert len(sink.got) == 1
            assert srv._accepted[0].auth_entity == "osd.1"
            await cli.shutdown()
            await srv.shutdown()

        asyncio.run(run())

    def test_wrong_key_cannot_connect(self):
        async def run():
            kr = KeyRing()
            kr.add("osd.0")
            kr.add("osd.1")
            server_auth = CephxAuth.for_daemon("osd.0", kr)
            bad = CephxAuth.for_client("osd.1", generate_secret())
            srv = Messenger("osd.0", auth=server_auth)
            sink = _Sink()
            srv.add_dispatcher_tail(sink)
            await srv.bind("127.0.0.1:0")
            cli = Messenger("osd.1", auth=bad)
            with pytest.raises((AuthError, ConnectionError)):
                await cli.send_to(srv.addr, MPing(stamp=1.0))
            await asyncio.sleep(0.1)
            assert not sink.got
            await cli.shutdown()
            await srv.shutdown()

        asyncio.run(run())

    def test_unauthenticated_client_vs_auth_server(self):
        async def run():
            kr = KeyRing()
            kr.add("osd.0")
            server_auth = CephxAuth.for_daemon("osd.0", kr)
            srv = Messenger("osd.0", auth=server_auth)
            sink = _Sink()
            srv.add_dispatcher_tail(sink)
            await srv.bind("127.0.0.1:0")
            cli = Messenger("client.x")  # no auth: sends a message frame
            try:
                await cli.send_to(srv.addr, MPing(stamp=1.0))
            except ConnectionError:
                pass
            await asyncio.sleep(0.1)
            assert not sink.got  # server never dispatched it
            await cli.shutdown()
            await srv.shutdown()

        asyncio.run(run())


class TestTicketFastPath:
    def test_reconnect_skips_challenge(self):
        """A ticket from the first handshake rides the second one
        (CephxTicketManager fast path)."""

        async def run():
            kr = KeyRing()
            secret = kr.add("client.admin")
            server = CephxAuth("mon.a", kr.add("mon.a"), keyring=kr)
            client = CephxAuth.for_client("client.admin", secret)

            class Channel:
                def __init__(self):
                    self.c2s: asyncio.Queue = asyncio.Queue()
                    self.s2c: asyncio.Queue = asyncio.Queue()
                    self.rounds = 0

                def client_end(self):
                    async def send(tag, segs):
                        self.rounds += 1
                        await self.c2s.put((tag, segs))

                    async def recv():
                        return await self.s2c.get()

                    return send, recv

                def server_end(self):
                    async def send(tag, segs):
                        await self.s2c.put((tag, segs))

                    async def recv():
                        return await self.c2s.get()

                    return send, recv

            ch1 = Channel()
            (t1, k1c), (e1, k1s) = await asyncio.gather(
                client.client_auth(*ch1.client_end(), peer="mon-addr"),
                server.server_auth(*ch1.server_end()),
            )
            assert e1 == "client.admin" and ch1.rounds == 2  # full handshake
            assert k1c == k1s

            ch2 = Channel()
            (t2, k2c), (e2, k2s) = await asyncio.gather(
                client.client_auth(*ch2.client_end(), peer="mon-addr"),
                server.server_auth(*ch2.server_end()),
            )
            assert e2 == "client.admin"
            assert ch2.rounds == 1  # ticket accepted: one client frame only
            assert server.verify_ticket(t2) == "client.admin"
            # fresh connection secret per session, agreed by both ends
            assert k2c == k2s and k2c != k1c

        asyncio.run(run())

    def test_mixed_config_does_not_deadlock(self):
        """Auth client vs auth-less server: bounded failure, not a hang
        (the server's read loop silently ignores auth frames)."""

        async def run():
            kr = KeyRing()
            kr.add("osd.1")
            srv = Messenger("osd.0")  # NO auth
            sink = _Sink()
            srv.add_dispatcher_tail(sink)
            await srv.bind("127.0.0.1:0")
            cli = Messenger("osd.1", auth=CephxAuth.for_daemon("osd.1", kr))
            cli_conn = cli.get_connection(srv.addr)
            cli_conn_auth_timeout = 5.0  # messenger clamps the handshake
            with pytest.raises(ConnectionError):
                await asyncio.wait_for(
                    cli.send_to(srv.addr, MPing(stamp=1.0)),
                    cli_conn_auth_timeout + 2.0,
                )
            await cli.shutdown()
            await srv.shutdown()

        asyncio.run(run())
