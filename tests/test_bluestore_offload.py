"""BlueStore device-offload integration (ISSUE 20): per-block checksums
routed through the ChecksumAggregator (`bluestore_csum_offload`), the
EC-transaction csum fusion seam (`Op.csums`), and the identical-content
overwrite skip.  Every path must stay byte-identical to the host
`utils/crc32c` baseline — the device digests ARE the stored csums, so a
divergence would surface as EIO on the next read."""

import os

import numpy as np
import pytest

from ceph_tpu.common.fault_injector import global_injector
from ceph_tpu.ops.guard import device_guard
from ceph_tpu.os import BlueStore, StoreError, Transaction
from ceph_tpu.os.bluestore import BLOCK
from ceph_tpu.utils.crc32c import crc32c


@pytest.fixture(autouse=True)
def _clean_state():
    yield
    global_injector().clear()
    device_guard().mark_healthy()
    device_guard().configure(timeout_ms=20000, probe_interval_ms=2000)


def mko(path=None, **kw):
    s = BlueStore(str(path) if path else None, csum_offload=True, **kw)
    s.mount()
    if "c" not in s.list_collections():
        s.queue_transaction(Transaction().create_collection("c"))
    return s


class TestOffloadWriteRead:
    @pytest.mark.parametrize(
        "nbytes",
        [100, BLOCK, BLOCK + 1, 4 * BLOCK, 8 * BLOCK + 1000, 10000],
    )
    def test_round_trip_across_sizes_and_ragged_tails(self, nbytes):
        s = mko()
        data = os.urandom(nbytes)
        s.queue_transaction(Transaction().write("c", "o", 0, data))
        assert s.read("c", "o") == data
        # the csums it stored are the host oracle's, block by block
        on = s._peek_onode("c", "o")
        for bidx, (poff, crc, clen) in on.blocks.items():
            stored = s._block_read(poff, clen if clen else BLOCK)
            if not clen:
                stored = stored.ljust(BLOCK, b"\x00")
            assert crc32c(stored) == crc, bidx
        s.umount()

    def test_offload_batches_the_write_path(self):
        """A large aligned write must reach the csum service (launches
        advance) and still verify on read back through the same path."""
        from ceph_tpu.ops.checksum_offload import default_csum_aggregator

        agg = default_csum_aggregator()
        s = mko()
        l0 = agg.perf.get("launches")
        data = os.urandom(16 * BLOCK)  # over CSUM_OFFLOAD_MIN_BYTES
        s.queue_transaction(Transaction().write("c", "big", 0, data))
        assert agg.perf.get("launches") > l0
        assert s.read("c", "big") == data
        s.umount()

    def test_fault_injected_write_and_read_stay_identical(self):
        s = mko()
        data = os.urandom(8 * BLOCK)
        global_injector().inject("codec.launch", 5, hits=2)
        s.queue_transaction(Transaction().write("c", "o", 0, data))
        assert s.read("c", "o") == data  # read-verify under faults too
        on = s._peek_onode("c", "o")
        for bidx, (poff, crc, clen) in on.blocks.items():
            assert crc32c(s._block_read(poff, BLOCK).ljust(BLOCK, b"\x00")) \
                == crc, bidx
        s.umount()

    def test_degraded_bypass_stays_identical(self):
        device_guard().configure(probe_interval_ms=10 * 60 * 1000)
        device_guard().mark_degraded("test: forced")
        s = mko()
        data = os.urandom(8 * BLOCK)
        s.queue_transaction(Transaction().write("c", "o", 0, data))
        assert s.read("c", "o") == data
        s.umount()

    def test_corrupt_block_is_still_eio_with_offload(self, tmp_path):
        s = mko(tmp_path / "b")
        data = os.urandom(4 * BLOCK)
        s.queue_transaction(Transaction().write("c", "o", 0, data))
        poff, _crc, _clen = s._peek_onode("c", "o").blocks[2]
        s.umount()
        with open(tmp_path / "b" / "block", "r+b") as f:
            f.seek(poff + 5)
            b = f.read(1)
            f.seek(poff + 5)
            f.write(bytes([b[0] ^ 0xFF]))
        s2 = mko(tmp_path / "b")
        with pytest.raises(StoreError) as ei:
            s2.read("c", "o")
        assert ei.value.errno == -5
        assert "block 2" in str(ei.value)  # the batched verify names it
        s2.umount()

    def test_set_csum_offload_toggles_live(self):
        s = BlueStore(None)
        s.mount()
        s.queue_transaction(Transaction().create_collection("c"))
        assert not s._csum_offload
        s.set_csum_offload(True)
        assert s._csum_offload
        data = os.urandom(8 * BLOCK)
        s.queue_transaction(Transaction().write("c", "o", 0, data))
        s.set_csum_offload(False)
        assert s.read("c", "o") == data  # host verify of offload csums
        s.umount()


class TestCsumSkip:
    def test_identical_overwrite_skips_recompute(self):
        s = mko()
        data = os.urandom(4 * BLOCK)
        s.queue_transaction(Transaction().write("c", "o", 0, data))
        skips0 = s.csum_compute_skips
        # same content again: every whole block below size skips
        s.queue_transaction(Transaction().write("c", "o", 0, data))
        assert s.csum_compute_skips == skips0 + 4
        assert s.read("c", "o") == data
        s.umount()

    def test_changed_block_is_not_skipped(self):
        s = mko()
        data = bytearray(os.urandom(4 * BLOCK))
        s.queue_transaction(Transaction().write("c", "o", 0, bytes(data)))
        skips0 = s.csum_compute_skips
        data[BLOCK + 7] ^= 0xFF
        s.queue_transaction(Transaction().write("c", "o", 0, bytes(data)))
        # blocks 0, 2, 3 identical -> skipped; block 1 changed -> not
        assert s.csum_compute_skips == skips0 + 3
        assert s.read("c", "o") == bytes(data)
        s.umount()

    def test_tail_straddling_block_never_skips(self):
        """A block straddling o.size holds stale stored bytes past the
        logical tail; an identical-content overwrite that also EXTENDS
        the object would expose them if the old csum were reused."""
        s = mko()
        data = os.urandom(2 * BLOCK + 1000)  # block 2 straddles size
        s.queue_transaction(Transaction().write("c", "o", 0, data))
        skips0 = s.csum_compute_skips
        # rewrite the same bytes over the straddling block
        s.queue_transaction(
            Transaction().write("c", "o", 2 * BLOCK, data[2 * BLOCK:])
        )
        assert s.csum_compute_skips == skips0  # no skip for the tail
        # now extend past it: the recomputed csum covers the zeroed tail
        s.queue_transaction(
            Transaction().write("c", "o", 3 * BLOCK, b"x" * 10)
        )
        want = data + b"\x00" * (3 * BLOCK - len(data)) + b"x" * 10
        assert s.read("c", "o") == want
        s.umount()


class TestEcFusion:
    def test_fused_csums_are_trusted_for_aligned_raw_stores(self):
        s = mko()
        data = os.urandom(3 * BLOCK)
        pre = [crc32c(data[i * BLOCK:(i + 1) * BLOCK]) for i in range(3)]
        fused0 = s.csum_fused_blocks
        s.queue_transaction(Transaction().write("c", "o", 0, data, csums=pre))
        assert s.csum_fused_blocks == fused0 + 3
        assert s.read("c", "o") == data
        s.umount()

    def test_ticket_like_csums_resolve_via_result(self):
        class FakeTicket:
            def __init__(self, vals):
                self._vals = np.asarray(vals, dtype=np.uint32)

            def result(self):
                return self._vals

        s = mko()
        data = os.urandom(2 * BLOCK)
        pre = FakeTicket(
            [crc32c(data[:BLOCK]), crc32c(data[BLOCK:])]
        )
        fused0 = s.csum_fused_blocks
        s.queue_transaction(Transaction().write("c", "o", 0, data, csums=pre))
        assert s.csum_fused_blocks == fused0 + 2
        assert s.read("c", "o") == data
        s.umount()

    def test_wrong_fused_digest_surfaces_as_eio(self):
        """The fused digest IS the stored csum: a wrong one must fail
        the next read loudly, never silently pass."""
        s = mko()
        data = os.urandom(BLOCK)
        s.queue_transaction(
            Transaction().write("c", "o", 0, data, csums=[0xDEADBEEF])
        )
        with pytest.raises(StoreError) as ei:
            s.read("c", "o")
        assert ei.value.errno == -5
        s.umount()

    def test_unaligned_writes_never_trust_fused_digests(self):
        s = mko()
        data = os.urandom(BLOCK + 100)  # ragged: csums must be ignored
        fused0 = s.csum_fused_blocks
        s.queue_transaction(
            Transaction().write("c", "o", 0, data, csums=[0xBAD, 0xBAD])
        )
        assert s.csum_fused_blocks == fused0
        assert s.read("c", "o") == data  # real csums computed + verified
        s.umount()

    def test_wire_encode_drops_csums(self):
        """`Op.csums` is a process-local fusion seam, not wire state: a
        transaction that crosses the messenger re-computes csums on the
        applying store, so a stale fused digest can never ride a
        sub-write to a remote shard."""
        data = os.urandom(2 * BLOCK)
        t = Transaction().write("c", "o", 0, data, csums=[0xBAD, 0xBAD])
        t2 = Transaction.frombytes(t.tobytes())
        assert t2.ops[0].csums is None
        s = mko()
        s.queue_transaction(t2)
        assert s.read("c", "o") == data  # honest csums, verified clean
        s.umount()
