"""Native EC plugin tests — mirror of the reference tier-1 pattern:
TestErasureCodeIsa.cc round trips vs the oracle, plus the hostile-plugin
registry fixtures (TestErasureCodePlugin.cc +
ErasureCodePluginFailToInitialize/MissingVersion/MissingEntryPoint.cc)."""

import pathlib
import shutil
import subprocess

import numpy as np
import pytest

from ceph_tpu.codec.interface import EcError
from ceph_tpu.codec.registry import (
    EC_NATIVE_ABI_VERSION,
    instance,
    load_dynamic,
)

NATIVE_DIR = str(pathlib.Path(__file__).resolve().parents[1] / "native")

HAVE_GXX = shutil.which("g++") is not None


def _codec(plugin, k, m, technique="reed_sol_van"):
    profile = {"k": str(k), "m": str(m), "plugin": plugin}
    if technique != "reed_sol_van":
        profile["technique"] = technique
    return instance().factory(plugin, profile)


class TestNativeCodec:
    def test_roundtrip_all_single_erasures(self):
        ec = _codec("native", 4, 2)
        rng = np.random.default_rng(1)
        obj = rng.integers(0, 256, 40_000, dtype=np.uint8).tobytes()
        enc = ec.encode(set(range(6)), obj)
        for lost in range(6):
            avail = {i: enc[i] for i in range(6) if i != lost}
            dec = ec.decode({lost}, avail)
            assert np.array_equal(dec[lost], enc[lost]), f"erasure {lost}"

    def test_double_erasures(self):
        ec = _codec("native", 6, 3, "cauchy")
        rng = np.random.default_rng(2)
        obj = rng.integers(0, 256, 30_000, dtype=np.uint8).tobytes()
        enc = ec.encode(set(range(9)), obj)
        for a in range(9):
            for b in range(a + 1, 9):
                avail = {i: enc[i] for i in range(9) if i not in (a, b)}
                dec = ec.decode({a, b}, avail)
                assert np.array_equal(dec[a], enc[a])
                assert np.array_equal(dec[b], enc[b])

    @pytest.mark.parametrize("technique", ["reed_sol_van", "cauchy"])
    @pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (8, 3), (10, 4)])
    def test_byte_identity_vs_tpu_plugin(self, k, m, technique):
        """The native engine and the TPU bitsliced path must produce
        byte-identical chunks (both mirror ISA-L's math)."""
        if technique == "reed_sol_van" and m == 4 and k > 21:
            pytest.skip("outside the Vandermonde MDS envelope")
        native = _codec("native", k, m, technique)
        tpu = _codec("tpu", k, m, technique)
        rng = np.random.default_rng(k * 100 + m)
        obj = rng.integers(0, 256, 64 * 1024 + 123, dtype=np.uint8).tobytes()
        n = k + m
        enc_n = native.encode(set(range(n)), obj)
        enc_t = tpu.encode(set(range(n)), obj)
        for i in range(n):
            assert np.array_equal(enc_n[i], enc_t[i]), f"chunk {i} differs"

    def test_m1_xor_fast_path(self):
        ec = _codec("native", 5, 1)
        obj = bytes(range(256)) * 100
        enc = ec.encode(set(range(6)), obj)
        expect = np.zeros_like(np.asarray(enc[0]))
        for i in range(5):
            expect ^= np.asarray(enc[i])
        assert np.array_equal(enc[5], expect)

    def test_decode_lru_reuse(self):
        ec = _codec("native", 4, 2)
        obj = b"z" * 8192
        enc = ec.encode(set(range(6)), obj)
        avail = {i: enc[i] for i in range(6) if i not in (0, 5)}
        ec.decode({0, 5}, avail)
        assert len(ec._decode_lru) == 1
        ec.decode({0, 5}, avail)  # same signature: no new entry
        assert len(ec._decode_lru) == 1


class TestNativeInvert:
    def test_invert_matches_python(self):
        import ctypes

        from ceph_tpu.gf import gf_matmul, isa_cauchy_matrix

        lib = load_dynamic("native", NATIVE_DIR)
        mat = np.ascontiguousarray(isa_cauchy_matrix(4, 4)[4:], dtype=np.uint8)
        inv = np.zeros((4, 4), dtype=np.uint8)
        rc = lib.ec_gf_invert_matrix(mat.tobytes(), inv.ctypes.data, 4)
        assert rc == 0
        assert np.array_equal(gf_matmul(mat, inv), np.eye(4, dtype=np.uint8))

    def test_singular_returns_error(self):
        lib = load_dynamic("native", NATIVE_DIR)
        sing = np.ones((3, 3), dtype=np.uint8)  # rank 1
        out = np.zeros((3, 3), dtype=np.uint8)
        assert lib.ec_gf_invert_matrix(sing.tobytes(), out.ctypes.data, 3) == -1


FIXTURES = {
    # reference ErasureCodePluginMissingVersion.cc
    "missingversion": "",
    # reference ErasureCodePluginMissingEntryPoint.cc
    "missingentrypoint": """
extern "C" const char* __erasure_code_version(void) { return "%s"; }
""" % EC_NATIVE_ABI_VERSION,
    # bad version string (the -EXDEV check, ErasureCodePlugin.cc:134-143)
    "badversion": """
extern "C" const char* __erasure_code_version(void) { return "wrong-1"; }
extern "C" int __erasure_code_init(const char*, const char*) { return 0; }
""",
    # reference ErasureCodePluginFailToInitialize.cc
    "failinit": """
extern "C" const char* __erasure_code_version(void) { return "%s"; }
extern "C" int __erasure_code_init(const char*, const char*) { return -22; }
""" % EC_NATIVE_ABI_VERSION,
}


@pytest.mark.skipif(not HAVE_GXX, reason="no g++ for plugin fixtures")
class TestHostilePlugins:
    """Registry failure modes with freshly compiled hostile plugins."""

    @pytest.fixture()
    def fixture_dir(self, tmp_path):
        for name, src in FIXTURES.items():
            cc = tmp_path / f"{name}.cc"
            cc.write_text(src or "// empty: exports nothing\n")
            subprocess.run(
                ["g++", "-shared", "-fPIC", "-o",
                 str(tmp_path / f"libec_{name}.so"), str(cc)],
                check=True, capture_output=True,
            )
        return str(tmp_path)

    def test_missing_library(self, tmp_path):
        with pytest.raises(EcError) as e:
            load_dynamic("nosuch", str(tmp_path))
        assert e.value.errno == -2  # ENOENT

    def test_missing_version_symbol(self, fixture_dir):
        with pytest.raises(EcError) as e:
            load_dynamic("missingversion", fixture_dir)
        assert e.value.errno == -18  # EXDEV

    def test_version_mismatch(self, fixture_dir):
        with pytest.raises(EcError) as e:
            load_dynamic("badversion", fixture_dir)
        assert e.value.errno == -18

    def test_missing_entry_point(self, fixture_dir):
        with pytest.raises(EcError) as e:
            load_dynamic("missingentrypoint", fixture_dir)
        assert e.value.errno == -2

    def test_init_failure(self, fixture_dir):
        with pytest.raises(EcError) as e:
            load_dynamic("failinit", fixture_dir)
        assert e.value.errno == -22  # the init's own errno propagates
