"""ISSUE 8 tentpole contracts: the launch flight recorder and its
Chrome-trace export.

Acceptance shape: aggregated encode AND decode launches leave ring
records carrying queue-wait + h2d/kernel/d2h sub-spans; a DeviceGuard
timeout flags its launch's record (fallback + timeout) and the
degraded-bypass launches that follow are flagged too; the ring stays
bounded under concurrent submitters; and `tools/trace_export.py` emits
valid Chrome trace-event JSON (complete-event keys, monotonic
non-overlapping same-lane slices) from a live run."""

import threading
import time

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import DecodeAggregator, EncodeAggregator
from ceph_tpu.common.fault_injector import global_injector
from ceph_tpu.ops import dispatch as ec_dispatch
from ceph_tpu.ops.flight_recorder import FlightRecorder, flight_recorder
from ceph_tpu.ops.guard import device_guard
from ceph_tpu.stripe import StripeInfo
from ceph_tpu.stripe import stripe as stripe_mod
from ceph_tpu.tools.trace_export import (
    export_chrome_trace,
    validate_chrome_trace,
)


@pytest.fixture(autouse=True)
def _clean_state():
    """Recorder, guard, and injector state must not leak across tests."""
    flight_recorder().reset()
    yield
    global_injector().clear()
    device_guard().mark_healthy()
    device_guard().configure(timeout_ms=20000, probe_interval_ms=2000)
    flight_recorder().reset()


def make_rs(k=4, m=2):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


def payload(sinfo, stripes, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, stripes * sinfo.stripe_width, dtype=np.uint8)


class TestRingBuffer:
    def test_capacity_bound_under_concurrent_submitters(self):
        """8 threads hammering raw records: the ring never exceeds its
        bound, drops are oldest-first, and seq stays unique."""
        fr = FlightRecorder(capacity=64)
        threads = [
            threading.Thread(
                target=lambda: [
                    fr.record_raw("encode", 1, 4096) for _ in range(100)
                ]
            )
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        recs = fr.records()
        assert len(recs) == 64
        seqs = [r["seq"] for r in recs]
        assert len(set(seqs)) == len(seqs), "duplicate seq in ring"
        assert fr.summary()["launches"] == 800
        # newest records survive: the max seq committed is retained
        assert max(seqs) == max(r["seq"] for r in recs)

    def test_configure_resize_keeps_newest(self):
        fr = FlightRecorder(capacity=16)
        for _ in range(16):
            fr.record_raw("encode", 1, 1)
        oldest_before = fr.records()[0]["seq"]
        fr.configure(capacity=4)
        recs = fr.records()
        assert len(recs) == 4
        assert recs[0]["seq"] > oldest_before, "resize must keep the newest"

    def test_reset_rebases_utilization_window(self):
        fr = FlightRecorder(capacity=8)
        rec = fr.record_raw("encode", 1, 1)
        fr.reset()
        assert fr.records() == []
        util = fr.utilization()
        assert util["busy_seconds"] == 0.0
        assert util["span_records"] == 0


class TestAggregatedLaunchRecords:
    """The acceptance surface: dump_flight-visible records for
    aggregated encode and decode launches with queue-wait + sub-spans."""

    def setup_method(self):
        self.ec = make_rs(4, 2)
        self.sinfo = StripeInfo(4 * 512, 512)

    def _agg_records(self):
        return [
            r for r in flight_recorder().records() if r["group"] != "#raw"
        ]

    def test_encode_launch_record_has_queue_wait_and_subspans(self):
        agg = EncodeAggregator(window=4)
        rng = np.random.default_rng(0)
        tickets = [
            agg.submit(
                self.ec, rng.integers(0, 256, (2, 4, 512), dtype=np.uint8)
            )
            for _ in range(4)
        ]
        for t in tickets:
            t.result()
        recs = [r for r in self._agg_records() if r["kind"] == "encode"]
        assert recs, "aggregated encode left no flight record"
        rec = recs[-1]
        assert rec["tickets"] == 4
        assert rec["stripes"] == 8
        assert rec["batch"] == 8  # padded to the pow2 bucket
        assert rec["reason"] == "flush_window"
        # the timeline: submit -> dispatch -> settle, spans derived
        assert rec["dispatch_ts"] >= rec["submit_ts"]
        assert rec["settle_ts"] >= rec["dispatch_ts"]
        assert rec["queue_wait_s"] >= 0.0
        assert rec["h2d_s"] > 0.0, "dispatch span missing"
        # kernel/d2h spans exist (may be ~0 when the device finished
        # under the reap, which is exactly what they measure)
        assert rec["kernel_s"] >= 0.0 and rec["d2h_s"] >= 0.0
        # a clean launch raises no FAILURE flags; overlap is benign —
        # it just means the device finished before the reaper arrived,
        # which depends on host speed, not on correctness
        assert not any(
            v for k, v in rec["flags"].items() if k != "overlap"
        )

    def test_decode_launch_record_has_subspans(self):
        agg = DecodeAggregator(window=2)
        data = payload(self.sinfo, 2, seed=5)
        shards = stripe_mod.encode(self.sinfo, self.ec, data)
        have = {i: shards[i] for i in range(6) if i != 2}
        pends = [
            stripe_mod.decode_shards_launch(
                self.sinfo, self.ec, have, {2}, aggregator=agg
            )
            for _ in range(2)
        ]
        for p in pends:
            p.result()
        recs = [r for r in self._agg_records() if r["kind"] == "decode"]
        assert recs, "aggregated decode left no flight record"
        rec = recs[-1]
        assert rec["tickets"] == 2
        assert rec["h2d_s"] > 0.0
        assert rec["settle_ts"] >= rec["dispatch_ts"] >= rec["submit_ts"]

    def test_injected_launch_fault_flags_fallback(self):
        """codec.launch armed to fail: the record says the launch
        completed on the host (fallback flag), not silently."""
        agg = EncodeAggregator(window=0)
        global_injector().inject("codec.launch", 5, hits=1)
        pend = stripe_mod.encode_launch(
            self.sinfo, self.ec, payload(self.sinfo, 1, seed=6),
            aggregator=agg,
        )
        pend.result()
        recs = self._agg_records()
        assert recs[-1]["flags"]["fallback"]
        assert recs[-1]["kernel_s"] > 0.0, "host compute must bank as kernel_s"

    def test_guard_timeout_flags_timeout_then_bypass(self):
        """A dispatch wedged past ec_tpu_launch_timeout_ms: the launch's
        record carries timeout+fallback; the NEXT launch (backend now
        DEGRADED, probe gated) is flagged degraded_bypass."""
        real = self.ec.encode_array

        def wedge(arr, out=None):
            time.sleep(0.5)
            return real(arr, out=out)

        device_guard().configure(timeout_ms=50, probe_interval_ms=10_000_000)
        # burn the immediate post-degrade probe allowance up front so the
        # bypass launch below cannot self-heal through a probe
        self.ec.encode_array = wedge
        try:
            agg = EncodeAggregator(window=0)
            pend = stripe_mod.encode_launch(
                self.sinfo, self.ec, payload(self.sinfo, 1, seed=7),
                aggregator=agg,
            )
            pend.result()
            wedged = self._agg_records()[-1]
            assert wedged["flags"]["timeout"], wedged
            assert wedged["flags"]["fallback"]
            # the deadline wait on the wedged device is DEAD time, not
            # staging: it must not inflate h2d_s / device busy-seconds
            assert wedged["h2d_s"] == 0.0, wedged
            assert device_guard().degraded
            # burn the post-degrade probe with a dead device
            device_guard().maybe_probe(
                lambda: (_ for _ in ()).throw(RuntimeError("dead"))
            )
            pend = stripe_mod.encode_launch(
                self.sinfo, self.ec, payload(self.sinfo, 1, seed=8),
                aggregator=agg,
            )
            pend.result()
        finally:
            self.ec.encode_array = real
        bypass = self._agg_records()[-1]
        assert bypass["flags"]["degraded_bypass"], bypass
        assert bypass["flags"]["fallback"]
        assert not bypass["flags"]["timeout"]

    def test_sticky_error_flags_error(self):
        """A launch that fails on device AND host leaves an error-flagged
        record (the co-riders' EcError has a timeline entry)."""
        agg = EncodeAggregator(window=0)
        real_dev = self.ec.encode_array
        real_host = self.ec.encode_array_host

        def boom(arr, out=None):
            raise RuntimeError("dev boom")

        def boom_host(arr):
            raise RuntimeError("host boom")

        self.ec.encode_array = boom
        self.ec.encode_array_host = boom_host
        try:
            pend = stripe_mod.encode_launch(
                self.sinfo, self.ec, payload(self.sinfo, 1, seed=9),
                aggregator=agg,
            )
            with pytest.raises(Exception):
                pend.result()
        finally:
            self.ec.encode_array = real_dev
            self.ec.encode_array_host = real_host
        rec = self._agg_records()[-1]
        assert rec["flags"]["error"], rec

    def test_utilization_feeds_perf_dump(self):
        agg = EncodeAggregator(window=0)
        pend = stripe_mod.encode_launch(
            self.sinfo, self.ec, payload(self.sinfo, 2, seed=10),
            aggregator=agg,
        )
        pend.result()
        dump = ec_dispatch.perf_dump()
        for key in (
            "device_busy_seconds",
            "device_occupancy",
            "flight_records",
            "flight_mean_queue_wait_ms",
        ):
            assert key in dump, key
        assert dump["device_busy_seconds"] > 0.0
        assert 0.0 < dump["device_occupancy"] <= 1.0
        assert dump["flight_records"] >= 1


class TestTraceExport:
    def test_live_run_exports_valid_chrome_trace(self):
        """The acceptance criterion: a live aggregated run (encode +
        decode + a fallback-flagged launch) exports Chrome trace JSON
        that passes the contract validator — required keys, integer µs
        timestamps, no overlapping same-lane slices."""
        ec = make_rs(4, 2)
        sinfo = StripeInfo(4 * 512, 512)
        agg = EncodeAggregator(window=2)
        dagg = DecodeAggregator(window=0)
        rng = np.random.default_rng(1)
        tickets = [
            agg.submit(ec, rng.integers(0, 256, (2, 4, 512), dtype=np.uint8))
            for _ in range(4)
        ]
        for t in tickets:
            t.result()
        data = payload(sinfo, 2, seed=11)
        shards = stripe_mod.encode(sinfo, ec, data)
        have = {i: shards[i] for i in range(6) if i != 1}
        stripe_mod.decode_shards_launch(
            sinfo, ec, have, {1}, aggregator=dagg
        ).result()
        global_injector().inject("codec.launch", 5, hits=1)
        stripe_mod.encode_launch(
            sinfo, ec, payload(sinfo, 1, seed=12), aggregator=agg
        ).result()
        records = flight_recorder().records()
        assert len(records) >= 3
        trace = export_chrome_trace(records)
        validate_chrome_trace(trace)
        names = {e["name"] for e in trace["traceEvents"]}
        # the stage sub-spans render as their own slices
        assert {"encode:h2d", "encode:kernel", "encode:d2h"} <= names
        assert any(n.startswith("decode") for n in names)
        # the fallback launch landed on its own lane
        lanes = {e["tid"] for e in trace["traceEvents"]}
        assert "host fallback" in lanes
        # aggregator lanes carry queue_wait slices
        assert "queue_wait" in names

    def test_idle_gaps_are_explicit(self):
        """Two launches separated by a real gap produce an explicit
        `idle` slice between them on the device lane."""
        fr = FlightRecorder(capacity=8)
        t0 = time.monotonic()
        for offset in (0.0, 0.5):
            rec = {
                "seq": 0,
                "kind": "encode",
                "group": "g",
                "tickets": 1,
                "stripes": 1,
                "batch": 1,
                "bytes": 512,
                "devices": 1,
                "reason": "",
                "submit_ts": t0 + offset,
                "dispatch_ts": t0 + offset,
                "settle_ts": t0 + offset + 0.01,
                "queue_wait_s": 0.0,
                "h2d_s": 0.005,
                "kernel_s": 0.004,
                "d2h_s": 0.001,
                "flags": {"sharded": False, "fallback": False,
                          "degraded_bypass": False, "timeout": False,
                          "throttle_stall": False, "error": False},
            }
            fr.commit(rec)
        trace = export_chrome_trace(fr.records())
        validate_chrome_trace(trace)
        idles = [e for e in trace["traceEvents"] if e["name"] == "idle"]
        assert idles, "gap between launches must render an idle slice"
        # the gap is ~490ms of the 500ms offset
        assert idles[0]["dur"] > 400_000

    def test_monotonic_ts_and_args_flags(self):
        fr = FlightRecorder(capacity=8)
        fr.record_raw("encode", 4, 4096, devices=2)
        trace = export_chrome_trace(fr.records())
        validate_chrome_trace(trace)
        ev = [e for e in trace["traceEvents"] if e["pid"] == "devices"][0]
        assert ev["args"]["devices"] == 2
        assert "sharded" in ev["args"].get("flags", "")


class TestDumpFlightAsok:
    def test_ec_write_shows_in_dump_flight_over_asok(self, tmp_path):
        """End to end: an EC client write's aggregated encode launch is
        visible through the OSD asok `dump_flight` with queue-wait +
        sub-spans, and the payload round-trips through trace_export."""
        import asyncio

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.common.admin_socket import admin_command
            from ceph_tpu.common.config import Config
            from ceph_tpu.mon import MonMap, Monitor
            from ceph_tpu.osd.osd import OSD

            from test_mon import free_port_addrs

            monmap = MonMap(addrs=free_port_addrs(1))
            mons = [
                Monitor(n, monmap, election_timeout=0.3)
                for n in monmap.addrs
            ]
            for m in mons:
                await m.start()
                await m.wait_for_quorum()

            def conf(i):
                return Config(
                    {
                        "name": f"osd.{i}",
                        "osd_heartbeat_interval": 0.1,
                        "osd_heartbeat_grace": 0.6,
                        "admin_socket": str(tmp_path / f"osd.{i}.asok"),
                    },
                    env=False,
                )

            osds = [OSD(i, monmap, conf=conf(i)) for i in range(3)]
            for o in osds:
                await o.start()
            for o in osds:
                await o.wait_for_up()
            client = Rados(monmap)
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "fl21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create(
                "flp", "erasure", profile="fl21", pg_num=1
            )
            io = await client.open_ioctx("flp")
            flight_recorder().reset()
            await io.write_full("obj", bytes(range(256)) * 64)
            loop = asyncio.get_event_loop()
            sock = str(tmp_path / "osd.0.asok")
            dump = await loop.run_in_executor(
                None, lambda: admin_command(sock, "dump_flight")
            )
            agg = [
                r for r in dump["records"]
                if r["kind"] == "encode" and r["group"] != "#raw"
            ]
            assert agg, dump["records"]
            rec = agg[-1]
            assert rec["settle_ts"] >= rec["dispatch_ts"] >= rec["submit_ts"]
            assert rec["queue_wait_s"] >= 0.0
            assert rec["h2d_s"] > 0.0
            assert "utilization" in dump
            assert dump["utilization"]["span_records"] >= 1
            # the asok payload feeds trace_export directly
            trace = export_chrome_trace(dump["records"])
            validate_chrome_trace(trace)
            await client.shutdown()
            for o in osds:
                await o.stop()
            for m in mons:
                await m.stop()
            await asyncio.sleep(0.05)

        asyncio.run(run())
