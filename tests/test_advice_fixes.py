"""Regression tests for the round-1 advisor findings (ADVICE.md).

Each class pins one fixed defect so it cannot reappear:
- S3 v2 auth: signature must bind the body (Content-MD5) and the Date must
  be fresh (rgw_auth_s3 canonicalization + RGW_AUTH_GRACE).
- Peering merge_log must rewind divergent entries (PGLog merge_log).
- MgrMonitor must re-baseline beacons on election (MgrMonitor.cc).
- PG dup detection must be rebuilt from the PG log on activation.
"""

from __future__ import annotations

import asyncio
from email.utils import formatdate

from ceph_tpu.mon.mgr_monitor import MgrMonitor
from ceph_tpu.msg.messages import PgId
from ceph_tpu.osd.peering import PeeringState
from ceph_tpu.osd.pg_log import Eversion, LogEntry, PGLog, PgInfo
from ceph_tpu.rgw.http import S3Server, sign_v2


class _FakeGateway:
    async def user_by_access_key(self, access_key):
        return (
            {"uid": "u", "secret_key": "secret"} if access_key == "AK" else None
        )


def _auth(server, method, path, headers, body=b""):
    """True when the request authenticates (round-5: _authenticate now
    returns the identity — uid / None anonymous / _BAD_AUTH failure)."""
    res = asyncio.run(server._authenticate(method, path, headers, body))
    return res is not S3Server._BAD_AUTH


def _signed_headers(method, path, body=b"", date=None, secret="secret"):
    import base64
    import hashlib

    date = date or formatdate(usegmt=True)
    md5 = base64.b64encode(hashlib.md5(body).digest()).decode() if body else ""
    sig = sign_v2(secret, method, path, date, content_md5=md5)
    headers = {"authorization": f"AWS AK:{sig}", "date": date}
    if md5:
        headers["content-md5"] = md5
    return headers


class TestS3AuthV2:
    def test_fresh_signed_request_accepted(self):
        server = S3Server(_FakeGateway(), require_auth=True)
        body = b"hello world"
        headers = _signed_headers("PUT", "/b/k", body)
        assert _auth(server, "PUT", "/b/k", headers, body)

    def test_stale_date_rejected(self):
        server = S3Server(_FakeGateway(), require_auth=True)
        stale = "Tue, 27 Mar 2007 19:36:42 GMT"
        headers = _signed_headers("GET", "/b/k", date=stale)
        assert not _auth(server, "GET", "/b/k", headers)

    def test_tampered_body_rejected(self):
        # Captured signature replayed with a different body must fail: the
        # Content-MD5 in the canonical string no longer matches the bytes.
        server = S3Server(_FakeGateway(), require_auth=True)
        headers = _signed_headers("PUT", "/b/k", b"original")
        assert not _auth(server, "PUT", "/b/k", headers, b"attacker payload")

    def test_body_without_md5_accepted(self):
        # v2 treats Content-MD5 as optional; stock clients omit it on PUT.
        server = S3Server(_FakeGateway(), require_auth=True)
        headers = _signed_headers("PUT", "/b/k")  # signed without a body
        assert _auth(server, "PUT", "/b/k", headers, b"plain v2 client body")

    def test_wrong_md5_rejected(self):
        server = S3Server(_FakeGateway(), require_auth=True)
        headers = _signed_headers("PUT", "/b/k", b"original")
        headers["content-md5"] = "AAAAAAAAAAAAAAAAAAAAAA=="
        assert not _auth(server, "PUT", "/b/k", headers, b"original")

    def test_wrong_secret_rejected(self):
        server = S3Server(_FakeGateway(), require_auth=True)
        headers = _signed_headers("GET", "/b/k", secret="other")
        assert not _auth(server, "GET", "/b/k", headers)

    def test_amz_date_without_date_accepted(self):
        # Clients that send x-amz-date instead of Date sign with an empty
        # Date line and the timestamp in the canonicalized amz headers.
        server = S3Server(_FakeGateway(), require_auth=True)
        now = formatdate(usegmt=True)
        sig = sign_v2("secret", "GET", "/b/k", "", amz_date=now)
        headers = {"authorization": f"AWS AK:{sig}", "x-amz-date": now}
        assert _auth(server, "GET", "/b/k", headers)

    def test_stale_amz_date_rejected(self):
        server = S3Server(_FakeGateway(), require_auth=True)
        stale = "Tue, 27 Mar 2007 19:36:42 GMT"
        sig = sign_v2("secret", "GET", "/b/k", "", amz_date=stale)
        headers = {"authorization": f"AWS AK:{sig}", "x-amz-date": stale}
        assert not _auth(server, "GET", "/b/k", headers)


def _entry(oid, epoch, version, prior=None, reqid=("", 0)):
    return LogEntry(
        oid=oid,
        version=Eversion(epoch, version),
        prior_version=prior or Eversion(),
        reqid=reqid,
    )


def _peering(log):
    return PeeringState(
        PgId(1, 0, -1),
        whoami=0,
        log=log,
        info=PgInfo(),
        send=lambda osd, msg: None,
        on_active=lambda: None,
        list_local_objects=lambda: [],
    )


class TestDivergentRewind:
    def test_divergent_entries_rewound_and_marked_missing(self):
        log = PGLog()
        log.append(_entry("a", 1, 1))
        log.append(_entry("b", 1, 2, prior=Eversion()))
        log.append(_entry("b", 1, 3, prior=Eversion(1, 2)))  # divergent write
        ps = _peering(log)

        # authoritative shard's head is (1,2): entry (1,3) was never seen by
        # the rest of the acting set and must be rewound to prior (1,2).
        ps._merge_log([], auth_last=Eversion(1, 2))
        assert ps.log.head == Eversion(1, 2)
        assert "b" in ps.missing
        assert "a" not in ps.missing

    def test_divergent_object_rewinds_to_prior_version(self):
        log = PGLog()
        log.append(_entry("a", 1, 1))
        log.append(_entry("a", 1, 5, prior=Eversion(1, 1)))
        ps = _peering(log)
        ps._merge_log([], auth_last=Eversion(1, 1))
        assert ps.log.head == Eversion(1, 1)
        need, _have = ps.missing.items["a"]
        assert need == Eversion(1, 1)

    def test_divergence_across_epochs(self):
        # The canonical failover case: old primary A logged an unreplicated
        # write (epoch 1, v7) and crashed; the new primary's head is
        # (2, 8) > (1, 7), so a naive head-vs-auth-head comparison never
        # fires.  The delta's `since` (newest agreed point) + absence from
        # the delta must still identify (1,7) as divergent.
        log = PGLog()
        log.append(_entry("a", 1, 6))
        log.append(_entry("x", 1, 7, prior=Eversion()))  # unreplicated write
        dropped = []
        ps = PeeringState(
            PgId(1, 0, -1),
            whoami=0,
            log=log,
            info=PgInfo(),
            send=lambda osd, msg: None,
            on_active=lambda: None,
            list_local_objects=lambda: [],
            drop_local_object=dropped.append,
        )
        delta = [_entry("b", 2, 8, prior=Eversion())]
        ps._merge_log(delta, auth_last=Eversion(2, 8), since=Eversion(1, 6))
        versions = [e.version for e in ps.log.entries]
        assert Eversion(1, 7) not in versions
        assert Eversion(2, 8) in versions
        assert dropped == ["x"]  # stale on-disk copy dropped -> pull path
        assert "x" not in ps.missing  # created by the divergent write only

    def test_common_point_rewinds_unknown_head(self):
        log = PGLog()
        log.append(_entry("a", 1, 6))
        log.append(_entry("b", 2, 8))
        ps = _peering(log)
        # peer claims (1,7) which we never saw -> newest agreed point (1,6)
        assert ps._common_point(Eversion(1, 7)) == Eversion(1, 6)
        # a head we do have is its own common point
        assert ps._common_point(Eversion(2, 8)) == Eversion(2, 8)

    def test_no_rewind_when_log_matches_auth(self):
        log = PGLog()
        log.append(_entry("a", 1, 1))
        ps = _peering(log)
        ps._merge_log(
            [_entry("c", 1, 2, prior=Eversion())], auth_last=Eversion(1, 2)
        )
        assert ps.log.head == Eversion(1, 2)
        assert "a" not in ps.missing
        assert "c" in ps.missing  # merged entry we don't have on disk yet


class TestDupWindowRebuild:
    def _pg(self):
        from ceph_tpu.os.memstore import MemStore
        from ceph_tpu.osd.osdmap import PgPool
        from ceph_tpu.osd.pg import PG

        class FakeOsd:
            whoami = 0
            store = MemStore()

        FakeOsd.store.mount()
        pool = PgPool(id=1, name="p", size=2, min_size=1)
        return PG(FakeOsd(), pool, 0, profiles={})

    def test_rebuild_from_pg_log_on_activation(self):
        # A new primary must recognize the Objecter's resend (same reqid) of
        # a write that committed under the old primary: the dup window is
        # replayed from the PG log, not kept only in the dead primary's RAM.
        pg = self._pg()
        pg._epoch = 3
        pg.pg_log.append(
            _entry("obj1", 2, 7, prior=Eversion(), reqid=("client.4", 11))
        )
        pg.pg_log.append(
            _entry("obj2", 2, 8, prior=Eversion(), reqid=("client.4", 12))
        )
        pg._rebuild_dup_window()
        rep = pg._reqid_results[("client.4", 11)]
        assert rep.result == 0 and rep.version == 7
        assert ("client.4", 12) in pg._reqid_results

    def test_entries_without_reqid_skipped(self):
        pg = self._pg()
        pg.pg_log.append(_entry("obj1", 2, 7))  # e.g. a recovery/clone entry
        pg._rebuild_dup_window()
        assert pg._reqid_results == {}


class _FakeMon:
    def __init__(self):
        self.proposals = []

    def is_leader(self):
        return True

    def propose(self, service, blob, on_done=None):
        self.proposals.append((service, blob))
        if on_done:
            on_done(1)

    def publish_mgrmap(self):
        pass


class TestMgrBeaconRebaseline:
    def test_new_leader_does_not_failover_healthy_mgr(self):
        mon = _FakeMon()
        mm = MgrMonitor(mon)
        mm.map.active_name = "x"
        mm.map.active_addr = "addr"
        mm.map.standbys = {"y": "addr2"}
        # Newly elected leader: beacon map is empty.  Without re-baselining,
        # tick() compares against 0.0 and instantly fails over.
        mm.on_election_changed()
        mm.tick()
        assert mon.proposals == []
        assert mm.map.active_name == "x"

    def test_failover_still_happens_after_grace(self, monkeypatch):
        import ceph_tpu.mon.mgr_monitor as mod

        mon = _FakeMon()
        mm = MgrMonitor(mon)
        mm.map.active_name = "x"
        mm.map.standbys = {"y": "addr2"}
        mm.on_election_changed()
        # advance time past the grace window
        base = mm._last_beacon["x"]
        monkeypatch.setattr(
            mod.time, "monotonic", lambda: base + mod.BEACON_GRACE + 1
        )
        mm.tick()
        assert mon.proposals, "expected a failover proposal after grace expiry"


class TestAutoscalerEmptyVerification:
    """_pool_verified_empty must not pass vacuously when no OSD reports.

    Round-2 advisor: with osdmap.osds empty (or every OSD down/out) the
    per-OSD loop never ran, so an unverifiable pool was treated as
    verified-empty and pg_num was force-applied.
    """

    @staticmethod
    def _module(osds):
        from types import SimpleNamespace

        from ceph_tpu.mgr.pg_autoscaler import PgAutoscalerModule

        pool = SimpleNamespace(name="p", id=7)
        auto = PgAutoscalerModule(mode="on")
        auto.mgr = SimpleNamespace(
            osdmap=SimpleNamespace(pools={7: pool}, osds=osds),
            get_daemon_status=lambda name: {"pool_objects": {"7": 0}},
        )
        return auto

    def test_no_osds_is_unverifiable(self):
        assert not self._module({})._pool_verified_empty("p")

    def test_all_down_osds_is_unverifiable(self):
        from types import SimpleNamespace

        osds = {0: SimpleNamespace(up=False, in_=False)}
        assert not self._module(osds)._pool_verified_empty("p")

    def test_reporting_empty_pool_is_verified(self):
        from types import SimpleNamespace

        osds = {0: SimpleNamespace(up=True, in_=True)}
        assert self._module(osds)._pool_verified_empty("p")
