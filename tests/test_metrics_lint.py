"""Metrics lint — exposition well-formedness for the mgr's prometheus
module (the CI satellite of ISSUE 1).

Scrapes `PrometheusModule.scrape()` from a running toy cluster and
validates the text-format contract a real Prometheus server (and
`promtool check metrics`) enforces: every family announced exactly once
with HELP + TYPE before its samples, no duplicate families, and
histogram families carrying monotonically non-decreasing cumulative
`le` buckets ending at +Inf with consistent `_sum`/`_count`.
"""

import asyncio
import re

import pytest

_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(?P<labels>[^}]*)\})? (?P<value>\S+)$'
)


def lint_exposition(text: str) -> dict:
    """Parse and validate an exposition payload; returns
    {family: {"type", "help", "samples": [(name, labels, value)]}}.
    Raises AssertionError on any contract violation."""
    assert text.endswith("\n"), "exposition must end with a newline"
    families: dict[str, dict] = {}
    current = None  # family the last HELP/TYPE block opened
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            assert name not in families, f"line {lineno}: duplicate family {name}"
            families[name] = {"type": None, "help": help_, "samples": []}
            current = name
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, ftype = rest.partition(" ")
            assert name == current, (
                f"line {lineno}: TYPE for {name} outside its HELP block"
            )
            assert families[name]["type"] is None, (
                f"line {lineno}: duplicate TYPE for {name}"
            )
            assert ftype in ("counter", "gauge", "histogram", "summary", "untyped")
            families[name]["type"] = ftype
            continue
        assert not line.startswith("#"), f"line {lineno}: unknown comment {line!r}"
        m = _SAMPLE.match(line)
        assert m, f"line {lineno}: malformed sample {line!r}"
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            stripped = name.removesuffix(suffix)
            if stripped != name and stripped in families and families[
                stripped
            ]["type"] == "histogram":
                base = stripped
                break
        assert base in families, f"line {lineno}: sample {name} has no HELP/TYPE"
        assert base == current, (
            f"line {lineno}: sample {name} outside family {current} block"
        )
        float(m.group("value"))  # every value parses as a number
        labels = {}
        for part in (m.group("labels") or "").split(","):
            if part:
                k, _, v = part.partition("=")
                labels[k] = v.strip('"')
        families[base]["samples"].append((name, labels, float(m.group("value"))))
    for name, fam in families.items():
        assert fam["type"] is not None, f"family {name} has HELP but no TYPE"
        assert fam["help"].strip(), f"family {name} has empty HELP"
        if fam["type"] == "histogram":
            _check_histogram(name, fam["samples"])
    return families


def _check_histogram(name: str, samples: list) -> None:
    """Per label-set (minus `le`): buckets cumulative and non-decreasing,
    +Inf last, and _count == the +Inf bucket."""
    series: dict[tuple, dict] = {}
    for sname, labels, value in samples:
        key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        rec = series.setdefault(key, {"buckets": [], "sum": None, "count": None})
        if sname == f"{name}_bucket":
            assert "le" in labels, f"{name}: bucket sample without le"
            le = float("inf") if labels["le"] == "+Inf" else float(labels["le"])
            rec["buckets"].append((le, value))
        elif sname == f"{name}_sum":
            rec["sum"] = value
        elif sname == f"{name}_count":
            rec["count"] = value
    for key, rec in series.items():
        assert rec["buckets"], f"{name}{dict(key)}: histogram without buckets"
        les = [le for le, _ in rec["buckets"]]
        assert les == sorted(les), f"{name}{dict(key)}: le bounds not sorted"
        assert les[-1] == float("inf"), f"{name}{dict(key)}: missing +Inf bucket"
        counts = [c for _, c in rec["buckets"]]
        assert counts == sorted(counts), (
            f"{name}{dict(key)}: cumulative bucket counts decrease"
        )
        assert rec["sum"] is not None and rec["count"] is not None, (
            f"{name}{dict(key)}: missing _sum/_count"
        )
        assert rec["count"] == counts[-1], (
            f"{name}{dict(key)}: _count != +Inf bucket"
        )


class TestLintHelper:
    """The linter itself must catch the failure modes it exists for."""

    def test_accepts_wellformed_histogram(self):
        text = (
            "# HELP h latency\n# TYPE h histogram\n"
            'h_bucket{le="0.1"} 1\nh_bucket{le="+Inf"} 3\n'
            "h_sum 1.5\nh_count 3\n"
        )
        fam = lint_exposition(text)
        assert fam["h"]["type"] == "histogram"

    @pytest.mark.parametrize(
        "text,why",
        [
            ("m 1\n", "sample without HELP/TYPE"),
            ("# HELP m x\n# TYPE m gauge\n# HELP m x\n# TYPE m gauge\nm 1\n",
             "duplicate family"),
            ("# HELP h x\n# TYPE h histogram\n"
             'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\nh_sum 1\nh_count 3\n',
             "decreasing cumulative buckets"),
            ("# HELP h x\n# TYPE h histogram\n"
             'h_bucket{le="1"} 1\nh_sum 1\nh_count 1\n',
             "missing +Inf bucket"),
        ],
    )
    def test_rejects_malformed(self, text, why):
        with pytest.raises(AssertionError):
            lint_exposition(text)


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _docs_metric_tokens() -> set[str]:
    """Backticked `ceph_tpu_*` tokens from docs/OBSERVABILITY.md (labels
    stripped; a trailing `*` marks a documented prefix family)."""
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "OBSERVABILITY.md",
    )
    with open(path) as f:
        text = f.read()
    tokens = set()
    for m in re.finditer(r"`(ceph_tpu_[A-Za-z0-9_.*]+)(?:\{[^`]*\})?`", text):
        tokens.add(m.group(1))
    return tokens


class TestClusterScrapeLint:
    def test_scrape_from_toy_cluster_is_wellformed(self):
        """Boot mon+OSDs+mgr, drive a few ops, and lint the full scrape:
        the histogram families (op_latency et al.) must be real Prometheus
        histograms, every family well-announced, and — the ISSUE 8
        cross-lint — every `ec_dispatch` perf-dump counter, the canonical
        device-utilization families, and the progress gauges present in
        BOTH the scrape and docs/OBSERVABILITY.md, and vice versa."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.mgr import Mgr, ProgressModule
            from ceph_tpu.mgr.prometheus import PrometheusModule
            from ceph_tpu.ops import dispatch as ec_dispatch

            from test_cluster import start_cluster, stop_cluster, wait_until

            from ceph_tpu.mgr.iostat import IostatModule

            monmap, mons, osds = await start_cluster(1, 2)
            mgr = Mgr("x", monmap)
            mgr.beacon_interval = 0.1
            await mgr.start()
            await mgr.wait_for_active()
            prom = PrometheusModule()
            mgr.register_module(prom)
            mgr.register_module(ProgressModule())
            # short windows + a pinned SLO target so the burn/target
            # gauges carry samples within the test's wait budget
            iostat = IostatModule(window_sec=2.0, slo_target_ms=5000.0)
            mgr.register_module(iostat)
            # metrics-history meta-gauges + dashboard map_errors
            # (ISSUE 14): both modules export through the same hook
            from ceph_tpu.mgr import DashboardModule, MetricsHistoryModule

            history = MetricsHistoryModule()
            mgr.register_module(history)
            dashboard = DashboardModule()
            mgr.register_module(dashboard)
            # cluster-event timeline families (ISSUE 16)
            from ceph_tpu.mgr import ClogModule

            clog_mod = ClogModule()
            mgr.register_module(clog_mod)

            client = Rados(monmap)
            await client.connect()
            await client.pool_create("lintp", "replicated", size=2, pg_num=2)
            io = await client.open_ioctx("lintp")
            for i in range(4):
                await io.write_full(f"o{i}", b"x" * 4096)
            # a committed ERROR clog entry for the clog-family cross-lint
            # (the pool create above already produced the audit entry)
            osds[0].clog_error("lint: planted inconsistency probe")

            # one eager encode so the occupancy distribution has a
            # bucket (devices_per_launch.<n> keys exist only once a
            # coding dispatch ran in this process)
            import numpy as np

            from ceph_tpu.codec import ErasureCodeTpuRs

            ec = ErasureCodeTpuRs()
            ec.init({"k": "2", "m": "1"})
            np.asarray(ec.encode_array(
                np.zeros((1, 2, 512), dtype=np.uint8)
            ))

            # ...and one eager csum submit + device-compressor batch so
            # the ISSUE 20 offload services carry their full counter
            # sets (the launch-path keys materialize lazily on first
            # use) before the round-trip snapshot below
            from ceph_tpu.compressor import get_compressor
            from ceph_tpu.ops.checksum_offload import (
                default_csum_aggregator,
            )
            from ceph_tpu.ops.offload_runtime import offload_perf_dump

            np.asarray(default_csum_aggregator().submit_blocks(
                np.zeros((2, 512), dtype=np.uint8)
            ).result())
            get_compressor("device").compress_batch(
                [bytes(65536), bytes(65536)]
            )
            offload_keys = set(offload_perf_dump())
            assert {"services", "csum.pending", "csum.launches",
                    "compress.pending", "compress.launches",
                    "csum.host_fallbacks",
                    "compress.host_fallbacks"} <= offload_keys

            # snapshot the perf-dump key set BEFORE waiting on the
            # scrape: the OSD reports the same process-wide counters, so
            # every key here must round-trip through MMgrReport
            dispatch_keys = set(ec_dispatch.perf_dump())
            # ISSUE 9 cross-lint: the verify counters ride the dispatch
            # namespace, and the launch scheduler's per-class slice
            # round-trips twice — inside ec_dispatch (sched.*) and under
            # its canonical ec_sched prefix on MMgrReport
            from ceph_tpu.ops.launch_scheduler import launch_scheduler

            assert {"verify_launches", "verify_stripes",
                    "verify_bytes"} <= dispatch_keys
            sched_keys = set(launch_scheduler().perf_dump())
            assert {f"sched.{k}" for k in sched_keys} <= dispatch_keys
            # ISSUE 11 cross-lint: the pipeline-ring slice and the
            # device-resident chunk-cache counters ride the dispatch
            # namespace too
            from ceph_tpu.ops.device_cache import device_chunk_cache

            assert {
                f"pipeline.{k}" for k in ec_dispatch.PIPELINE.snapshot()
            } <= dispatch_keys
            assert {
                f"cache.{k}" for k in device_chunk_cache().perf_dump()
            } <= dispatch_keys

            def all_reported():
                text = prom.scrape()
                if "op_latency" not in text or not all(
                    f"ceph_tpu_ec_dispatch_{_sanitize(k)}" in text
                    for k in dispatch_keys
                ):
                    return False
                # ...and the ISSUE 20 offload-service slice arrived
                if not all(
                    f"ceph_tpu_offload_{_sanitize(k)}" in text
                    for k in offload_keys
                ):
                    return False
                # ...and the iostat module consumed a pool_io report:
                # the per-pool attribution families must carry SAMPLES
                # (ISSUE 10), not just announce themselves
                if 'ceph_tpu_pool_ops{pool="' not in text:
                    return False
                # ..and the report carrying op SAMPLES arrived: the
                # dispatch counters are process-wide, so when earlier
                # tests already ran coding dispatches every key exists
                # in the OSD's FIRST report — which may have been sent
                # before the writes above completed.  Waiting on the
                # announcement alone races the next beacon against the
                # count>0 assertion below.
                op_lat = lint_exposition(text)[
                    "ceph_tpu_op_latency"]["samples"]
                return any(n == "ceph_tpu_op_latency_count" and v > 0
                           for n, _, v in op_lat)

            await wait_until(
                all_reported, 5.0, "op_latency samples + ec_dispatch in scrape"
            )
            families = lint_exposition(prom.scrape())

            # the tentpole's promised families are present and typed right
            assert families["ceph_tpu_op_latency"]["type"] == "histogram"
            assert families["ceph_tpu_osd_up"]["type"] == "gauge"
            assert families["ceph_tpu_healthcheck"]["type"] == "gauge"
            # a daemon that sampled ops has a non-empty latency series
            op_lat = families["ceph_tpu_op_latency"]["samples"]
            assert any(n == "ceph_tpu_op_latency_count" and v > 0
                       for n, _, v in op_lat)

            docs = _docs_metric_tokens()
            docs_exact = {t for t in docs if not t.endswith("*")}
            docs_prefix = {t[:-1] for t in docs if t.endswith("*")}

            def documented(name: str) -> bool:
                return name in docs_exact or any(
                    name.startswith(p) for p in docs_prefix
                )

            # direction 1: every ec_dispatch perf-dump counter reaches
            # the scrape AND is documented
            for key in dispatch_keys:
                fam = f"ceph_tpu_ec_dispatch_{_sanitize(key)}"
                assert fam in families, f"{fam} missing from scrape"
                assert documented(fam), (
                    f"{fam} (perf-dump key {key!r}) not in "
                    "docs/OBSERVABILITY.md metrics index"
                )
            # the canonical utilization names + progress gauges too
            for fam in (
                "ceph_tpu_ec_device_busy_seconds",
                "ceph_tpu_ec_device_occupancy",
                "ceph_tpu_progress_fraction",
                "ceph_tpu_progress_rate_objects",
                "ceph_tpu_progress_eta_seconds",
                "ceph_tpu_progress_active",
            ):
                assert fam in families, f"{fam} missing from scrape"
                assert documented(fam), f"{fam} not documented"
            # ...and the canonical ec_sched families (ISSUE 9): every
            # scheduler perf-dump key reaches the scrape under its
            # ceph_tpu_ec_sched_* name AND is documented
            for key in sched_keys:
                fam = f"ceph_tpu_ec_sched_{_sanitize(key)}"
                assert fam in families, f"{fam} missing from scrape"
                assert documented(fam), f"{fam} not documented"
            # the scheduler's queue-depth export must be a gauge — a
            # counter-typed depth would corrupt PromQL rate() queries
            assert (
                families["ceph_tpu_ec_sched_client_queue_depth"]["type"]
                == "gauge"
            )
            # ISSUE 11: the pipeline/cache families have EXPLICIT index
            # rows (the broad `ceph_tpu_ec_dispatch_*` prose token must
            # not be what documents them), and their level exports are
            # gauges while the hit/miss traffic stays counter-typed
            assert "ceph_tpu_ec_dispatch_pipeline_*" in docs, (
                "pipeline family needs its own docs index row"
            )
            assert "ceph_tpu_ec_dispatch_cache_*" in docs, (
                "device-cache family needs its own docs index row"
            )
            for fam in (
                "ceph_tpu_ec_dispatch_pipeline_depth",
                "ceph_tpu_ec_dispatch_pipeline_inflight",
                "ceph_tpu_ec_dispatch_cache_resident_bytes",
                "ceph_tpu_ec_dispatch_cache_entries",
            ):
                assert families[fam]["type"] == "gauge", fam
            assert (
                families["ceph_tpu_ec_dispatch_cache_hits"]["type"]
                == "counter"
            )
            # verify-aggregator families round-trip like the encode/
            # decode aggregators'
            assert any(
                f.startswith("ceph_tpu_ec_verify_aggregator_")
                for f in families
            ), "verify aggregator families missing from scrape"

            # ISSUE 10 cross-lint: every family the iostat module
            # exports reaches the scrape AND the docs index, with the
            # promised gauge-vs-counter-vs-histogram typing
            iostat_fams = {
                name: ftype
                for name, ftype, _h, _r in iostat.prometheus_metrics()
            }
            for fam, ftype in iostat_fams.items():
                assert fam in families, f"{fam} missing from scrape"
                assert families[fam]["type"] == ftype, (
                    f"{fam}: scrape type {families[fam]['type']} != "
                    f"module type {ftype}"
                )
                assert documented(fam), f"{fam} not documented"
            assert iostat_fams["ceph_tpu_pool_ops"] == "counter"
            assert iostat_fams["ceph_tpu_pool_ops_rate"] == "gauge"
            assert iostat_fams["ceph_tpu_pool_slo_burn_rate"] == "gauge"
            assert (
                iostat_fams["ceph_tpu_pool_latency_seconds"] == "histogram"
            )
            # the attribution families carry real per-pool samples whose
            # labels include op class
            pool_ops = families["ceph_tpu_pool_ops"]["samples"]
            assert any(
                labels.get("op") in ("read", "write", "recovery")
                and float(v) > 0
                for _n, labels, v in pool_ops
            ), pool_ops
            # SLO gauges have samples (a target was pinned) and the
            # burn family carries both windows
            burn = families["ceph_tpu_pool_slo_burn_rate"]["samples"]
            assert {l.get("window") for _n, l, _v in burn} >= {
                "fast", "slow",
            }, burn
            assert families["ceph_tpu_pool_slo_target_seconds"]["samples"]

            # ISSUE 13 cross-lint: the HBM mempool ledger families are
            # gauge-typed (residency rises AND falls), pool-labeled
            # strictly from the ledger's own pool set, documented, and
            # carry per-daemon samples once the OSDs reported
            from ceph_tpu.common.mempool import ledger as hbm_ledger

            hbm_pools = set(hbm_ledger().snapshot())
            for fam in (
                "ceph_tpu_mempool_bytes",
                "ceph_tpu_mempool_buffers",
                "ceph_tpu_mempool_peak_bytes",
            ):
                assert fam in families, f"{fam} missing from scrape"
                assert families[fam]["type"] == "gauge", fam
                assert documented(fam), f"{fam} not documented"
                samples = families[fam]["samples"]
                assert samples, f"{fam} announced but carries no samples"
                for _n, labels, _v in samples:
                    assert labels.get("pool") in hbm_pools, (
                        f"{fam} sample labeled with unknown pool "
                        f"{labels.get('pool')!r}"
                    )
                    assert labels.get("daemon", "").startswith("osd."), (
                        labels
                    )
            for fam in (
                "ceph_tpu_hbm_pressure_ratio",
                "ceph_tpu_hbm_target_bytes",
            ):
                assert fam in families, f"{fam} missing from scrape"
                assert families[fam]["type"] == "gauge", fam
                assert documented(fam), f"{fam} not documented"
                assert families[fam]["samples"], fam
            # direction 2: every scraped mempool family maps back to a
            # ledger export (bytes / buffers / peak_bytes only)
            for fam in families:
                if fam.startswith("ceph_tpu_mempool_"):
                    suffix = fam.removeprefix("ceph_tpu_mempool_")
                    assert suffix in (
                        "bytes", "buffers", "peak_bytes",
                    ), f"scraped {fam} has no mempool ledger source"

            # ISSUE 14 cross-lint: every family the metrics-history
            # module exports reaches the scrape AND the docs index
            # with its declared typing, and vice versa — every scraped
            # ceph_tpu_history_* family maps back to a module export.
            # The meta-gauges are the fixed-memory witness: gauges for
            # levels (series/points/bytes/sentinel state), counters
            # for the monotone eviction/append/fired totals.
            history_fams = {
                name: ftype
                for name, ftype, _h, _r in history.prometheus_metrics()
            }
            for fam, ftype in history_fams.items():
                assert fam in families, f"{fam} missing from scrape"
                assert families[fam]["type"] == ftype, (
                    f"{fam}: scrape type {families[fam]['type']} != "
                    f"module type {ftype}"
                )
                assert documented(fam), f"{fam} not documented"
                assert families[fam]["samples"], (
                    f"{fam} announced but carries no samples"
                )
            assert history_fams["ceph_tpu_history_series"] == "gauge"
            assert history_fams["ceph_tpu_history_bytes"] == "gauge"
            assert history_fams["ceph_tpu_history_points"] == "gauge"
            assert (
                history_fams["ceph_tpu_history_sentinel_active"] == "gauge"
            )
            assert history_fams["ceph_tpu_history_evictions"] == "counter"
            assert (
                history_fams["ceph_tpu_history_sentinels_fired"]
                == "counter"
            )
            # the sentinel-activity gauge renders one row per known
            # sentinel code, all quiet on a healthy cluster
            sentinel_rows = families[
                "ceph_tpu_history_sentinel_active"]["samples"]
            assert {
                l.get("sentinel") for _n, l, _v in sentinel_rows
            } == {
                "TPU_THROUGHPUT_REGRESSION",
                "TPU_OCCUPANCY_COLLAPSE",
                "TPU_QUEUE_WAIT_INFLATION",
            }
            assert all(v == 0 for _n, _l, v in sentinel_rows)
            for fam in families:
                if fam.startswith("ceph_tpu_history_"):
                    assert fam in history_fams, (
                        f"scraped {fam} has no metrics_history "
                        "prometheus_metrics() source"
                    )
            # dashboard satellite: map_errors is a real scrape family
            # now, not a module-local counter nobody can see
            assert (
                families["ceph_tpu_dashboard_map_errors"]["type"]
                == "counter"
            )
            assert documented("ceph_tpu_dashboard_map_errors")
            assert families["ceph_tpu_dashboard_map_errors"]["samples"]

            # trace-sampling families (ISSUE 10 layer 3): every
            # sampling_stats() key the OSD reports round-trips onto the
            # scrape as ceph_tpu_trace_<key>, and vice versa; knobs and
            # the pending depth are gauges, the verdicts counters
            trace_keys = set(osds[0].tracer.sampling_stats())
            for key in trace_keys:
                fam = f"ceph_tpu_trace_{_sanitize(key)}"
                assert fam in families, f"{fam} missing from scrape"
                assert documented(fam), f"{fam} not documented"
            for fam in families:
                if fam.startswith("ceph_tpu_trace_"):
                    key = fam.removeprefix("ceph_tpu_trace_")
                    assert key in {_sanitize(k) for k in trace_keys}, (
                        f"scraped {fam} has no sampling_stats() source"
                    )
            assert families["ceph_tpu_trace_sampled"]["type"] == "counter"
            assert families["ceph_tpu_trace_kept_tail"]["type"] == "counter"
            for fam in (
                "ceph_tpu_trace_sample_rate",
                "ceph_tpu_trace_budget_per_sec",
                "ceph_tpu_trace_pending_traces",
            ):
                assert families[fam]["type"] == "gauge", fam

            # recovery-storm families (ISSUE 15): every controller
            # perf-dump key round-trips onto the scrape as
            # ceph_tpu_recovery_storm_<key> AND is documented, and vice
            # versa — every scraped recovery_storm family maps back to
            # a controller export.  Levels (wave size, in-flight depth,
            # engagement, burn rate) are gauges; the wave/shed/ramp/
            # storm totals stay counters.
            storm_keys = set(osds[0].recovery_storm.perf_dump())
            for key in storm_keys:
                fam = f"ceph_tpu_recovery_storm_{_sanitize(key)}"
                assert fam in families, f"{fam} missing from scrape"
                assert documented(fam), f"{fam} not documented"
                assert families[fam]["samples"], (
                    f"{fam} announced but carries no samples"
                )
            for fam in families:
                if fam.startswith("ceph_tpu_recovery_storm_"):
                    key = fam.removeprefix("ceph_tpu_recovery_storm_")
                    assert key in {_sanitize(k) for k in storm_keys}, (
                        f"scraped {fam} has no RecoveryStormController "
                        "perf_dump() source"
                    )
            for fam in (
                "ceph_tpu_recovery_storm_wave_objects",
                "ceph_tpu_recovery_storm_inflight",
                "ceph_tpu_recovery_storm_engaged",
                "ceph_tpu_recovery_storm_burn_rate",
            ):
                assert families[fam]["type"] == "gauge", fam
            for fam in (
                "ceph_tpu_recovery_storm_waves",
                "ceph_tpu_recovery_storm_objects_admitted",
                "ceph_tpu_recovery_storm_sheds",
                "ceph_tpu_recovery_storm_ramps",
                "ceph_tpu_recovery_storm_storms_started",
                "ceph_tpu_recovery_storm_storms_completed",
                "ceph_tpu_recovery_storm_preempted_backfills",
            ):
                assert families[fam]["type"] == "counter", fam

            # ISSUE 20 cross-lint: the device-offload service registry —
            # every offload_perf_dump() key round-trips onto the scrape
            # as ceph_tpu_offload_<service>_<counter> AND is documented,
            # and vice versa.  pending/services are levels (gauges);
            # launch and fallback totals stay counters; the per-service
            # launch-shape distributions render as real histogram
            # families the linter's bucket checks already validated.
            for key in offload_keys:
                fam = f"ceph_tpu_offload_{_sanitize(key)}"
                assert fam in families, f"{fam} missing from scrape"
                assert documented(fam), f"{fam} not documented"
            live_offload = {_sanitize(k) for k in offload_perf_dump()}
            for fam in families:
                if fam.startswith("ceph_tpu_offload_"):
                    key = fam.removeprefix("ceph_tpu_offload_")
                    assert key in live_offload, (
                        f"scraped {fam} has no offload_perf_dump() "
                        "source — update the exporter or the docs"
                    )
            assert families["ceph_tpu_offload_services"]["type"] == "gauge"
            for svc in ("encode", "decode", "verify", "compress", "csum"):
                assert (
                    families[f"ceph_tpu_offload_{svc}_pending"]["type"]
                    == "gauge"
                ), svc
            for fam in (
                "ceph_tpu_offload_csum_launches",
                "ceph_tpu_offload_csum_host_fallbacks",
                "ceph_tpu_offload_compress_launches",
                "ceph_tpu_offload_compress_host_fallbacks",
            ):
                assert families[fam]["type"] == "counter", fam
            assert (
                families["ceph_tpu_offload_csum_stripes_per_launch"][
                    "type"] == "histogram"
            )
            assert (
                families["ceph_tpu_offload_compress_launch_bytes"][
                    "type"] == "histogram"
            )

            # ISSUE 16 cross-lint: the clog module subscribes to the
            # committed log stream and polls the health-event history —
            # every family it exports reaches the scrape with its
            # declared typing AND the docs index, carrying real samples
            # (the planted clog_error + the pool-create audit line),
            # and vice versa: every scraped clog/health-event family
            # maps back to the module.
            def clog_reported():
                text = prom.scrape()
                return (
                    'ceph_tpu_clog_messages_total{channel="cluster",'
                    'severity="error"}' in text
                    and 'channel="audit"' in text
                )

            await wait_until(
                clog_reported, 8.0, "clog families carry samples"
            )
            families = lint_exposition(prom.scrape())
            clog_fams = {
                name: ftype
                for name, ftype, _h, _r in clog_mod.prometheus_metrics()
            }
            for fam, ftype in clog_fams.items():
                assert fam in families, f"{fam} missing from scrape"
                assert families[fam]["type"] == ftype, (
                    f"{fam}: scrape type {families[fam]['type']} != "
                    f"module type {ftype}"
                )
                assert documented(fam), f"{fam} not documented"
            # traffic totals are counters; the mute state is a gauge —
            # a counter-typed mute would corrupt alerting expressions
            assert clog_fams["ceph_tpu_clog_messages_total"] == "counter"
            assert clog_fams["ceph_tpu_health_events_total"] == "counter"
            assert clog_fams["ceph_tpu_health_muted"] == "gauge"
            rows = families["ceph_tpu_clog_messages_total"]["samples"]
            assert rows
            for _n, labels, v in rows:
                assert labels.get("channel") in ("cluster", "audit"), labels
                assert labels.get("severity") in (
                    "debug", "info", "warn", "error",
                ), labels
                assert v > 0, (labels, v)
            assert any(
                l.get("channel") == "cluster"
                and l.get("severity") == "error" and v >= 1
                for _n, l, v in rows
            ), rows
            assert any(
                l.get("channel") == "audit" and v >= 1 for _n, l, v in rows
            ), rows
            assert families["ceph_tpu_health_events_total"]["samples"]
            for fam in families:
                if fam.startswith("ceph_tpu_clog_") or fam in (
                    "ceph_tpu_health_events_total",
                    "ceph_tpu_health_muted",
                ):
                    assert fam in clog_fams, (
                        f"scraped {fam} has no clog module "
                        "prometheus_metrics() source"
                    )

            # direction 2 (vice versa): every documented metric exists
            # in the scrape, and every scraped ec_dispatch/progress
            # family maps back to a perf-dump key / module gauge
            for token in sorted(docs_exact):
                assert any(
                    f == token or f.startswith(token) for f in families
                ), f"documented {token} absent from scrape"
            for token in sorted(docs_prefix):
                assert any(f.startswith(token) for f in families), (
                    f"documented prefix {token}* matches nothing in scrape"
                )
            sanitized_keys = {_sanitize(k) for k in dispatch_keys}
            sanitized_sched = {_sanitize(k) for k in sched_keys}
            for fam in families:
                if fam.startswith("ceph_tpu_ec_dispatch_"):
                    key = fam.removeprefix("ceph_tpu_ec_dispatch_")
                    assert key in sanitized_keys, (
                        f"scraped {fam} has no ops/dispatch.perf_dump() "
                        "source — update the exporter or the docs"
                    )
                if fam.startswith("ceph_tpu_ec_sched_"):
                    key = fam.removeprefix("ceph_tpu_ec_sched_")
                    assert key in sanitized_sched, (
                        f"scraped {fam} has no launch_scheduler "
                        "perf_dump() source — update the exporter or "
                        "the docs"
                    )
                if fam.startswith("ceph_tpu_progress_"):
                    assert documented(fam), f"scraped {fam} undocumented"
                # scraped attribution families map back to the iostat
                # module's export list (the df pool gauges predate the
                # module and keep their own families)
                if fam.startswith("ceph_tpu_top_client_") or (
                    fam.startswith("ceph_tpu_pool_")
                    and fam not in (
                        "ceph_tpu_pool_stored_bytes",
                        "ceph_tpu_pool_objects",
                        "ceph_tpu_pool_used_raw_bytes",
                    )
                ):
                    assert fam in iostat_fams, (
                        f"scraped {fam} has no iostat "
                        "prometheus_metrics() source"
                    )

            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestAuditDiscipline:
    """ISSUE 16 satellite: state-changing admin-socket commands are
    enumerable as mutating and actually land on the committed audit
    channel — the timeline must record every operator action."""

    def test_mutating_asok_commands_audit_to_committed_log(self):
        async def run():
            import os
            import tempfile

            from ceph_tpu.common.admin_socket import admin_command

            from test_cluster import start_cluster, stop_cluster, wait_until

            monmap, mons, osds = await start_cluster(1, 1)
            tmp = tempfile.mkdtemp(prefix="lint-asok-")
            path = os.path.join(tmp, "osd.0.asok")
            osds[0].conf.set("admin_socket", path)
            await osds[0]._start_admin_socket()
            sock = osds[0].admin_socket
            assert sock is not None

            # the state-changing hooks are registered mutating; the
            # read-only introspection surfaces are not
            muts = sock.mutating_prefixes()
            assert "injectargs" in muts, muts
            assert "mark_unfound_lost" in muts, muts
            for ro in ("help", "perf dump", "config show",
                       "dump_ops_in_flight", "dump_historic_ops"):
                assert ro not in muts, f"{ro} must not be mutating"
            # ...and the audit sink is wired (a mutating command with no
            # audit_cb would change state silently)
            assert sock.audit_cb is not None

            # drive a real mutating command over the socket (the sync
            # client runs in a thread so the server coroutine can serve
            # it) and watch the audit entry reach the COMMITTED mon log
            result = await asyncio.to_thread(
                admin_command, path, "injectargs", clear=True
            )
            assert "armed" in result, result
            await wait_until(
                lambda: any(
                    e["channel"] == "audit"
                    and "injectargs" in e["msg"]
                    and e["who"] == "osd.0"
                    for e in mons[0].logmon.entries
                ),
                5.0,
                "asok audit entry committed",
            )
            # a read-only command leaves no audit trace
            before = sum(
                1 for e in mons[0].logmon.entries
                if e["channel"] == "audit"
            )
            await asyncio.to_thread(admin_command, path, "perf dump")
            await asyncio.sleep(0.2)
            after = sum(
                1 for e in mons[0].logmon.entries
                if e["channel"] == "audit"
            )
            assert after == before, "read-only asok command was audited"

            await stop_cluster(mons, osds)

        asyncio.run(run())
