"""Metrics lint — exposition well-formedness for the mgr's prometheus
module (the CI satellite of ISSUE 1).

Scrapes `PrometheusModule.scrape()` from a running toy cluster and
validates the text-format contract a real Prometheus server (and
`promtool check metrics`) enforces: every family announced exactly once
with HELP + TYPE before its samples, no duplicate families, and
histogram families carrying monotonically non-decreasing cumulative
`le` buckets ending at +Inf with consistent `_sum`/`_count`.
"""

import asyncio
import re

import pytest

_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(?P<labels>[^}]*)\})? (?P<value>\S+)$'
)


def lint_exposition(text: str) -> dict:
    """Parse and validate an exposition payload; returns
    {family: {"type", "help", "samples": [(name, labels, value)]}}.
    Raises AssertionError on any contract violation."""
    assert text.endswith("\n"), "exposition must end with a newline"
    families: dict[str, dict] = {}
    current = None  # family the last HELP/TYPE block opened
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            assert name not in families, f"line {lineno}: duplicate family {name}"
            families[name] = {"type": None, "help": help_, "samples": []}
            current = name
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, ftype = rest.partition(" ")
            assert name == current, (
                f"line {lineno}: TYPE for {name} outside its HELP block"
            )
            assert families[name]["type"] is None, (
                f"line {lineno}: duplicate TYPE for {name}"
            )
            assert ftype in ("counter", "gauge", "histogram", "summary", "untyped")
            families[name]["type"] = ftype
            continue
        assert not line.startswith("#"), f"line {lineno}: unknown comment {line!r}"
        m = _SAMPLE.match(line)
        assert m, f"line {lineno}: malformed sample {line!r}"
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            stripped = name.removesuffix(suffix)
            if stripped != name and stripped in families and families[
                stripped
            ]["type"] == "histogram":
                base = stripped
                break
        assert base in families, f"line {lineno}: sample {name} has no HELP/TYPE"
        assert base == current, (
            f"line {lineno}: sample {name} outside family {current} block"
        )
        float(m.group("value"))  # every value parses as a number
        labels = {}
        for part in (m.group("labels") or "").split(","):
            if part:
                k, _, v = part.partition("=")
                labels[k] = v.strip('"')
        families[base]["samples"].append((name, labels, float(m.group("value"))))
    for name, fam in families.items():
        assert fam["type"] is not None, f"family {name} has HELP but no TYPE"
        assert fam["help"].strip(), f"family {name} has empty HELP"
        if fam["type"] == "histogram":
            _check_histogram(name, fam["samples"])
    return families


def _check_histogram(name: str, samples: list) -> None:
    """Per label-set (minus `le`): buckets cumulative and non-decreasing,
    +Inf last, and _count == the +Inf bucket."""
    series: dict[tuple, dict] = {}
    for sname, labels, value in samples:
        key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        rec = series.setdefault(key, {"buckets": [], "sum": None, "count": None})
        if sname == f"{name}_bucket":
            assert "le" in labels, f"{name}: bucket sample without le"
            le = float("inf") if labels["le"] == "+Inf" else float(labels["le"])
            rec["buckets"].append((le, value))
        elif sname == f"{name}_sum":
            rec["sum"] = value
        elif sname == f"{name}_count":
            rec["count"] = value
    for key, rec in series.items():
        assert rec["buckets"], f"{name}{dict(key)}: histogram without buckets"
        les = [le for le, _ in rec["buckets"]]
        assert les == sorted(les), f"{name}{dict(key)}: le bounds not sorted"
        assert les[-1] == float("inf"), f"{name}{dict(key)}: missing +Inf bucket"
        counts = [c for _, c in rec["buckets"]]
        assert counts == sorted(counts), (
            f"{name}{dict(key)}: cumulative bucket counts decrease"
        )
        assert rec["sum"] is not None and rec["count"] is not None, (
            f"{name}{dict(key)}: missing _sum/_count"
        )
        assert rec["count"] == counts[-1], (
            f"{name}{dict(key)}: _count != +Inf bucket"
        )


class TestLintHelper:
    """The linter itself must catch the failure modes it exists for."""

    def test_accepts_wellformed_histogram(self):
        text = (
            "# HELP h latency\n# TYPE h histogram\n"
            'h_bucket{le="0.1"} 1\nh_bucket{le="+Inf"} 3\n'
            "h_sum 1.5\nh_count 3\n"
        )
        fam = lint_exposition(text)
        assert fam["h"]["type"] == "histogram"

    @pytest.mark.parametrize(
        "text,why",
        [
            ("m 1\n", "sample without HELP/TYPE"),
            ("# HELP m x\n# TYPE m gauge\n# HELP m x\n# TYPE m gauge\nm 1\n",
             "duplicate family"),
            ("# HELP h x\n# TYPE h histogram\n"
             'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\nh_sum 1\nh_count 3\n',
             "decreasing cumulative buckets"),
            ("# HELP h x\n# TYPE h histogram\n"
             'h_bucket{le="1"} 1\nh_sum 1\nh_count 1\n',
             "missing +Inf bucket"),
        ],
    )
    def test_rejects_malformed(self, text, why):
        with pytest.raises(AssertionError):
            lint_exposition(text)


class TestClusterScrapeLint:
    def test_scrape_from_toy_cluster_is_wellformed(self):
        """Boot mon+OSDs+mgr, drive a few ops, and lint the full scrape:
        the histogram families (op_latency et al.) must be real Prometheus
        histograms and every family well-announced."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.mgr import Mgr
            from ceph_tpu.mgr.prometheus import PrometheusModule

            from test_cluster import start_cluster, stop_cluster, wait_until

            monmap, mons, osds = await start_cluster(1, 2)
            mgr = Mgr("x", monmap)
            mgr.beacon_interval = 0.1
            await mgr.start()
            await mgr.wait_for_active()
            prom = PrometheusModule()
            mgr.register_module(prom)

            client = Rados(monmap)
            await client.connect()
            await client.pool_create("lintp", "replicated", size=2, pg_num=2)
            io = await client.open_ioctx("lintp")
            for i in range(4):
                await io.write_full(f"o{i}", b"x" * 4096)

            def histograms_reported():
                return "op_latency" in prom.scrape()

            await wait_until(
                histograms_reported, 5.0, "op_latency histogram in scrape"
            )
            families = lint_exposition(prom.scrape())

            # the tentpole's promised families are present and typed right
            assert families["ceph_tpu_op_latency"]["type"] == "histogram"
            assert families["ceph_tpu_osd_up"]["type"] == "gauge"
            assert families["ceph_tpu_healthcheck"]["type"] == "gauge"
            # a daemon that sampled ops has a non-empty latency series
            op_lat = families["ceph_tpu_op_latency"]["samples"]
            assert any(n == "ceph_tpu_op_latency_count" and v > 0
                       for n, _, v in op_lat)

            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())
