"""Backfill machinery tests: log trimming, reservations, chunked scan.

Models the reference's backfill coverage (PeeringState backfill states,
qa/standalone osd-backfill tests): an OSD that rejoins after the PG log
trimmed past its head converges via the cursor-driven chunked scan — not
by enumerating every object into a missing set — while writes keep
flowing, under local+remote reservation slots.
"""

import asyncio

from ceph_tpu.client import Rados
from ceph_tpu.common.config import Config
from ceph_tpu.mon import MonMap, Monitor
from ceph_tpu.osd.osd import OSD
from ceph_tpu.osd.pg_log import Eversion, LogEntry, PGLog
from ceph_tpu.common.perf_counters import PerfCountersBuilder
from ceph_tpu.osd.reserver import Reserver

from test_cluster import stop_cluster, wait_until
from test_mon import free_port_addrs


class TestReserver:
    def test_slots_bound_and_idempotent(self):
        r = Reserver(lambda: 2)
        assert r.try_reserve("a")
        assert r.try_reserve("a")  # idempotent
        assert r.try_reserve("b")
        assert not r.try_reserve("c")  # full
        r.release("a")
        assert r.try_reserve("c")
        r.release("missing")  # no-op

    def test_runtime_slot_growth(self):
        slots = {"n": 1}
        r = Reserver(lambda: slots["n"])
        assert r.try_reserve("a") and not r.try_reserve("b")
        slots["n"] = 2  # config push raised osd_max_backfills
        assert r.try_reserve("b")


class TestLogTrim:
    def test_trim_advances_tail_and_bounds_entries(self):
        log = PGLog()
        for i in range(1, 21):
            log.append(
                LogEntry(
                    oid=f"o{i}", op=1, version=Eversion(1, i),
                    prior_version=Eversion(),
                )
            )
        log.trim(Eversion(1, 15))
        assert log.tail == Eversion(1, 15)
        assert len(log.entries) == 5
        assert not log.can_catch_up(Eversion(1, 10))
        assert log.can_catch_up(Eversion(1, 15))


class _FakeOsd:
    def __init__(self):
        from ceph_tpu.common.perf_counters import PerfCountersBuilder
        from ceph_tpu.os.memstore import MemStore

        self.whoami = 0
        self.store = MemStore()
        self.store.mount()
        self.conf = Config({"osd_backfill_scan_max": 4}, env=False)
        self.local_reserver = Reserver(lambda: self.conf.get("osd_max_backfills"))
        self.remote_reserver = Reserver(lambda: self.conf.get("osd_max_backfills"))
        b = PerfCountersBuilder("osd.0")
        b.add_u64_counter("backfill_pushes")
        self.perf = b.create_perf_counters()
        self.sent = []  # (osd, msg)

    def send_cluster(self, osd, msg):
        self.sent.append((osd, msg))

    def clog_error(self, msg):
        pass


def _backfilling_pg(n_objects=10):
    from ceph_tpu.os import Transaction
    from ceph_tpu.osd.osdmap import PgPool
    from ceph_tpu.osd.peering import PeerState
    from ceph_tpu.osd.pg import PG
    from ceph_tpu.osd.pg_backend import shard_coll

    osd = _FakeOsd()
    pool = PgPool(id=1, name="p", size=2, min_size=1)
    pg = PG(osd, pool, 0, profiles={})
    coll = shard_coll(pg.pgid, -1)
    t = Transaction().create_collection(coll)
    for i in range(n_objects):
        t.write(coll, f"o{i:03d}", 0, b"x")
    osd.store.queue_transaction(t)
    pg._acting = [0, 1]
    pg._epoch = 5
    p = pg.peering
    p.epoch = 5
    p.acting = [0, 1]
    p.primary = 0
    p.state = PeerState.ACTIVE
    p.backfill_targets = {1}
    p.last_backfill = {1: ""}
    # capture pushes; complete them manually
    pg._pending_pushes = []
    pg.backend.recover_object = lambda oid, missing_on, cb: (
        pg._pending_pushes.append((oid, cb))
    )
    return pg, osd


class TestBackfillDriver:
    def test_reject_surrenders_local_slot(self):
        from ceph_tpu.msg.messages import MBackfillReserve

        pg, osd = _backfilling_pg()
        pg._kick_backfill()  # takes local slot, sends REQUEST
        assert pg._bf_local_reserved
        assert any(
            m.op == MBackfillReserve.REQUEST for _, m in osd.sent
        )
        pg.on_backfill_reserve(
            MBackfillReserve(
                pgid=pg.pgid, op=MBackfillReserve.REJECT, epoch=5, from_osd=1
            )
        )
        # local slot released so OTHER PGs can backfill meanwhile
        assert not pg._bf_local_reserved
        assert osd.local_reserver.held() == 0
        # next tick restarts the handshake
        pg._kick_backfill()
        assert pg._bf_local_reserved

    def test_failed_push_caps_cursor_and_retries(self):
        from ceph_tpu.msg.messages import MBackfillReserve

        pg, osd = _backfilling_pg(n_objects=6)  # scan_max=4 -> 2 chunks
        pg._kick_backfill()
        pg.on_backfill_reserve(
            MBackfillReserve(
                pgid=pg.pgid, op=MBackfillReserve.GRANT, epoch=5, from_osd=1
            )
        )
        assert len(pg._pending_pushes) == 4
        for oid, cb in pg._pending_pushes:
            cb(5 if oid == "o001" else 0)  # o001 fails with EIO
        # cursor stops BELOW the failed object; target not complete
        assert pg.peering.last_backfill[1] == "o000"
        assert 1 in pg.peering.backfill_targets
        # next tick re-scans from the barrier and re-pushes o001
        pg._pending_pushes.clear()
        pg._kick_backfill()
        assert [oid for oid, _ in pg._pending_pushes][0] == "o001"
        # drain to completion (completions may spawn the next chunk's
        # pushes, so swap the list out each round instead of clearing)
        guard = 0
        while 1 in pg.peering.backfill_targets:
            guard += 1
            assert guard < 100, "backfill never completed"
            if not pg._pending_pushes:
                pg._kick_backfill()
            pending, pg._pending_pushes = pg._pending_pushes, []
            for oid, cb in pending:
                cb(0)
        assert osd.local_reserver.held() == 0

    def test_stale_grant_sends_release_back(self):
        from ceph_tpu.msg.messages import MBackfillReserve

        pg, osd = _backfilling_pg()
        # GRANT from an interval that no longer exists
        pg.on_backfill_reserve(
            MBackfillReserve(
                pgid=pg.pgid, op=MBackfillReserve.GRANT, epoch=3, from_osd=1
            )
        )
        rel = [m for tgt, m in osd.sent if tgt == 1]
        assert rel and rel[-1].op == MBackfillReserve.RELEASE

    def test_straggler_callback_after_interval_change_is_inert(self):
        from ceph_tpu.msg.messages import MBackfillReserve

        pg, osd = _backfilling_pg()
        pg._kick_backfill()
        pg.on_backfill_reserve(
            MBackfillReserve(
                pgid=pg.pgid, op=MBackfillReserve.GRANT, epoch=5, from_osd=1
            )
        )
        stragglers = list(pg._pending_pushes)
        assert stragglers
        pg._reset_backfill()  # interval change mid-chunk
        pg._pending_pushes.clear()
        for _, cb in stragglers:
            cb(0)  # late completions must not restart an unreserved chunk
        assert not pg._pending_pushes
        assert not pg._bf_local_reserved

    def test_reads_exclude_stale_backfill_shard(self):
        pg, osd = _backfilling_pg()
        pg.peering.last_backfill[1] = "o003"
        # objects at/below the cursor are safe on the target
        assert pg.get_shard_missing("o002") == set()
        assert pg.get_shard_missing("o003") == set()
        # beyond the cursor the target's copy is stale: unavailable for reads
        assert pg.get_shard_missing("o007") == {1}
        # but writes are NOT blocked as degraded
        assert not pg.peering.object_missing_anywhere("o007")


def bf_conf(whoami: int) -> Config:
    return Config(
        {
            "name": f"osd.{whoami}",
            "osd_heartbeat_interval": 0.1,
            "osd_heartbeat_grace": 0.6,
            # tiny log so a rejoining OSD falls behind the tail fast
            "osd_min_pg_log_entries": 5,
            "osd_max_pg_log_entries": 10,
            "osd_backfill_scan_max": 8,
        },
        env=False,
    )


class TestBackfillCluster:
    def test_rejoining_osd_backfills_and_converges_under_write_load(self):
        async def run():
            monmap = MonMap(addrs=free_port_addrs(1))
            mons = [Monitor(n, monmap, election_timeout=0.3) for n in monmap.addrs]
            for m in mons:
                await m.start()
                await m.wait_for_quorum()
            osds = [OSD(i, monmap, conf=bf_conf(i)) for i in range(3)]
            for o in osds:
                await o.start()
            for o in osds:
                await o.wait_for_up()

            client = Rados(monmap)
            await client.connect()
            await client.pool_create("bf", "replicated", size=3, pg_num=1)
            ioctx = await client.open_ioctx("bf")

            objs = {}
            for i in range(30):
                oid = f"pre-{i:03d}"
                objs[oid] = (b"%03d" % i) * 700
                await ioctx.write_full(oid, objs[oid])

            # Kill osd.2; keep writing so the log trims far past its head.
            victim_store = osds[2].store
            await osds[2].stop()
            await wait_until(
                lambda: not mons[0].osdmon.osdmap.is_up(2), 8.0, "osd.2 down"
            )
            for i in range(30):
                oid = f"during-{i:03d}"
                objs[oid] = (b"D%02d" % i) * 700
                await ioctx.write_full(oid, objs[oid])

            primary = next(
                o
                for o in osds[:2]
                for pg in [*o.pgs.values()]
                if pg.peering.is_primary()
            )
            pg = next(iter(primary.pgs.values()))
            assert len(pg.pg_log.entries) <= 10  # the trim actually ran
            assert pg.pg_log.tail.version > 0

            # Revive osd.2 on its old store: its in-memory log is empty and
            # the primary's tail has moved -> it must become a backfill
            # target, with NO synthetic everything-missing set.
            revived = OSD(2, monmap, conf=bf_conf(2), store=victim_store)
            await revived.start()
            await revived.wait_for_up()
            osds[2] = revived

            saw_backfill = {"mark_all": False}

            def observe():
                if 2 in pg.peering.backfill_targets:
                    pm = pg.peering.peer_missing.get(2)
                    if pm is not None and len(pm) > 10:
                        saw_backfill["mark_all"] = True
                return False

            # Mid-backfill write load: these objects land while the scan
            # runs; convergence must include them regardless of cursor
            # position at the time of the write.
            for i in range(10):
                observe()
                oid = f"mid-{i:03d}"
                objs[oid] = (b"M%02d" % i) * 700
                await ioctx.write_full(oid, objs[oid])

            def clean():
                observe()
                return all(
                    p.is_clean
                    for o in osds
                    if o._running
                    for p in o.pgs.values()
                    if p.peering.is_primary()
                )

            await wait_until(clean, 15.0, "backfill to clean")
            # durable signal: sampling backfill_targets mid-run races a
            # fast backfill (it can finish before the first observe()
            # under load); the lifetime counter cannot
            assert pg.peering.backfill_started_total > 0, (
                "osd.2 never became a backfill target"
            )
            assert not saw_backfill["mark_all"], (
                "backfill fell back to mark-all-missing"
            )
            assert primary.perf.get("backfill_pushes") > 0

            # Every object readable, and osd.2's own store holds them all.
            for oid, data in objs.items():
                assert await ioctx.read(oid) == data
            coll = next(iter(revived.store.list_collections()))
            have = set(revived.store.list_objects(coll))
            assert set(objs) <= have

            # Reservations fully released on completion.
            assert primary.local_reserver.held() == 0
            assert revived.remote_reserver.held() == 0

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())
