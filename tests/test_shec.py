"""SHEC tests — shingle structure, c-erasure tolerance, recovery locality.

Models /root/reference/src/test/erasure-code/TestErasureCodeShec*.cc.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.codec.interface import EcError
from ceph_tpu.codec.registry import ErasureCodePluginRegistry
from ceph_tpu.codec.shec import MULTIPLE, SINGLE, ErasureCodeShec, shec_coding_matrix
from ceph_tpu.gf import gf_matmul


def make(k=4, m=3, c=2, technique=MULTIPLE):
    ec = ErasureCodeShec(technique=technique)
    ec.init({"k": str(k), "m": str(m), "c": str(c)})
    return ec


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n).astype(np.uint8).tobytes()


class TestMatrix:
    def test_shingle_sparsity(self):
        # Shingled rows must be sparser than (or equal to) full Vandermonde.
        for technique in (SINGLE, MULTIPLE):
            mat = shec_coding_matrix(4, 3, 2, technique)
            assert mat.shape == (3, 4)
            assert (mat != 0).sum() <= 12
            # Every parity row covers at least one chunk; every data chunk is
            # covered by at least one parity.
            assert ((mat != 0).sum(axis=1) >= 1).all()
            assert ((mat != 0).sum(axis=0) >= 1).all()

    def test_single_band_structure(self):
        # single: one band (m2=m, c2=c); window width ~ k*c/m.
        mat = shec_coding_matrix(6, 3, 2, SINGLE)
        widths = (mat != 0).sum(axis=1)
        assert widths.sum() == 12  # sum of ((rr+c)k/m - rr*k/m) over rr = c*k


class TestParams:
    def test_defaults(self):
        ec = ErasureCodeShec()
        ec.init({})
        assert (ec.k, ec.m, ec.c) == (4, 3, 2)

    def test_envelope(self):
        with pytest.raises(EcError):
            make(13, 3, 2)  # k > 12
        with pytest.raises(EcError):
            make(12, 9, 2)  # k+m > 20
        with pytest.raises(EcError):
            make(4, 3, 4)  # c > m
        with pytest.raises(EcError):
            make(3, 4, 2)  # m > k
        with pytest.raises(EcError):
            ErasureCodeShec().init({"k": "4", "m": "3"})  # c missing


class TestRoundtrip:
    @pytest.mark.parametrize("technique", [SINGLE, MULTIPLE])
    def test_all_c_erasures_recoverable(self, technique):
        k, m, c = 4, 3, 2
        ec = make(k, m, c, technique)
        n = k + m
        raw = payload(k * 128 + 9)
        encoded = ec.encode(set(range(n)), raw)
        # chunk layout: parity = shingled matrix product
        data = np.stack([encoded[i] for i in range(k)])
        expect = gf_matmul(ec.distribution_matrix()[k:], data)
        for i in range(m):
            assert np.array_equal(encoded[k + i], expect[i])
        # any <= c erasures must decode
        for nerr in range(1, c + 1):
            for erasures in itertools.combinations(range(n), nerr):
                avail = {i: encoded[i] for i in range(n) if i not in erasures}
                decoded = ec.decode(set(erasures), avail)
                for e in erasures:
                    assert np.array_equal(decoded[e], encoded[e]), (
                        technique,
                        erasures,
                    )

    def test_decode_concat(self):
        ec = make()
        raw = payload(4 * 256, seed=2)
        n = ec.get_chunk_count()
        encoded = ec.encode(set(range(n)), raw)
        avail = {i: encoded[i] for i in range(n) if i not in (1, 5)}
        out = ec.decode_concat(avail)
        assert out[: len(raw)].tobytes() == raw


class TestLocality:
    def test_single_erasure_reads_fewer_than_k(self):
        # The shingle property: repairing one chunk should read fewer than k
        # chunks for at least some erasures.
        ec = make(8, 4, 2)
        n = ec.get_chunk_count()
        saw_local = False
        for e in range(ec.k):
            minimum = ec.minimum_to_decode({e}, set(range(n)) - {e})
            assert e not in minimum
            if len(minimum) < ec.k:
                saw_local = True
        assert saw_local, "no erasure repaired with fewer than k reads"

    def test_want_available(self):
        ec = make()
        got = ec.minimum_to_decode({0, 1}, {0, 1, 2, 3})
        assert set(got) == {0, 1}


def test_plugin_registration():
    r = ErasureCodePluginRegistry()
    ec = r.factory("shec", {"k": "6", "m": "3", "c": "2"})
    assert ec.get_chunk_count() == 9
    raw = payload(6 * 128, seed=3)
    encoded = ec.encode(set(range(9)), raw)
    decoded = ec.decode({2, 7}, {i: encoded[i] for i in range(9) if i not in (2, 7)})
    assert np.array_equal(decoded[2], encoded[2])
    assert np.array_equal(decoded[7], encoded[7])
