"""CRUSH + OSDMap tests.

Modeled on the reference's src/test/crush/ (CrushWrapper mapping
invariants, straw2 weight proportionality) and src/test/osd/TestOSDMap.cc
(pg→osd mapping, erasure pools keeping stable shard holes).
"""

import collections

import pytest

from ceph_tpu.crush import (
    CRUSH_ITEM_NONE,
    CrushWrapper,
    crush_hash32,
    crush_hash32_2,
    crush_hash32_3,
    str_hash,
)
from ceph_tpu.crush.crush import WEIGHT_ONE, bucket_choose, Bucket
from ceph_tpu.crush.native import hash32_3_native, straw2_choose_native
from ceph_tpu.osd import Incremental, OSDMap, PG_NONE
from ceph_tpu.osd.osdmap import POOL_TYPE_ERASURE, POOL_TYPE_REPLICATED


# --- hashing -----------------------------------------------------------------


class TestHash:
    def test_deterministic(self):
        assert crush_hash32(42) == crush_hash32(42)
        assert crush_hash32_2(1, 2) != crush_hash32_2(2, 1)
        assert str_hash("foo") == str_hash(b"foo")
        assert str_hash("foo") != str_hash("fop")

    def test_distribution(self):
        # Avalanche sanity: low bit of hash over sequential inputs ~ 50/50.
        ones = sum(crush_hash32(i) & 1 for i in range(4000))
        assert 1700 < ones < 2300


# --- straw2 ------------------------------------------------------------------


def make_bucket(weights):
    return Bucket(
        id=-1,
        type_id=1,
        alg="straw2",
        items=list(range(len(weights))),
        weights=[int(w * WEIGHT_ONE) for w in weights],
    )


class TestStraw2:
    def test_weight_proportional(self):
        b = make_bucket([1.0, 1.0, 2.0])
        counts = collections.Counter(
            bucket_choose(b, x, 0) for x in range(8000)
        )
        total = sum(counts.values())
        assert counts[2] / total == pytest.approx(0.5, abs=0.06)
        assert counts[0] / total == pytest.approx(0.25, abs=0.05)

    def test_zero_weight_never_chosen(self):
        b = make_bucket([1.0, 0.0, 1.0])
        assert all(bucket_choose(b, x, 0) != 1 for x in range(500))

    def test_stability_under_weight_add(self):
        # straw2's defining property: adding an item only moves inputs
        # *onto* the new item, never between old items.
        b3 = make_bucket([1.0, 1.0, 1.0])
        b4 = make_bucket([1.0, 1.0, 1.0, 1.0])
        moved_wrong = sum(
            1
            for x in range(3000)
            if bucket_choose(b3, x, 0) != bucket_choose(b4, x, 0)
            and bucket_choose(b4, x, 0) != 3
        )
        assert moved_wrong == 0


class TestNativeAgreement:
    def test_hash_agrees(self):
        if hash32_3_native(1, 2, 3) is None:
            pytest.skip("native library unavailable")
        for a, b, c in [(0, 0, 0), (1, 2, 3), (0xFFFFFFFF, 7, 1 << 31)]:
            assert hash32_3_native(a, b, c) == crush_hash32_3(a, b, c)

    def test_straw2_agrees(self):
        b = make_bucket([1.0, 2.5, 0.5, 3.0, 1.0])
        if straw2_choose_native(0, 0, b.items, b.weights) is None:
            pytest.skip("native library unavailable")
        for x in range(2000):
            py = bucket_choose(b, x, x % 7)
            cc = straw2_choose_native(x, x % 7, b.items, b.weights)
            assert py == cc, f"divergence at x={x}"


# --- rule execution ----------------------------------------------------------


def make_cluster(n_osds=12, per_host=2):
    cw = CrushWrapper()
    cw.build_flat(n_osds, per_host)
    return cw


class TestRules:
    def test_firstn_distinct_hosts(self):
        cw = make_cluster(12, 2)
        rid = cw.add_simple_rule("rep", failure_domain="host", mode="firstn")
        for x in range(300):
            out = cw.do_rule(rid, x, 3)
            assert len(out) == 3
            assert len(set(out)) == 3
            hosts = {o // 2 for o in out}
            assert len(hosts) == 3  # one osd per host

    def test_indep_emits_holes_not_shifts(self):
        cw = make_cluster(12, 2)
        rid = cw.add_simple_rule("ec", failure_domain="host", mode="indep")
        x = 17
        full = cw.do_rule(rid, x, 5)
        assert len(full) == 5 and PG_NONE not in full
        # Zero out the first chosen osd's weight: its position must become
        # a hole or be replaced in place; other positions must not shift.
        gone = full[2]
        rew = {gone: 0}
        degraded = cw.do_rule(rid, x, 5, rew)
        assert len(degraded) == 5
        for i, (a, b) in enumerate(zip(full, degraded)):
            if i != 2:
                assert a == b, f"position {i} shifted on unrelated failure"
        assert degraded[2] != gone

    def test_osd_failure_domain(self):
        cw = make_cluster(6, 6)  # one host: osd-level domains still work
        rid = cw.add_simple_rule("ec", failure_domain="osd", mode="indep")
        out = cw.do_rule(rid, 99, 4)
        assert len(set(out)) == 4

    def test_distribution_across_osds(self):
        cw = make_cluster(8, 2)
        rid = cw.add_simple_rule("rep", failure_domain="host", mode="firstn")
        counts = collections.Counter()
        for x in range(2000):
            counts.update(cw.do_rule(rid, x, 2))
        # Each of 8 equal-weight osds should get ~ 2*2000/8 = 500.
        for osd in range(8):
            assert 300 < counts[osd] < 700


# --- OSDMap ------------------------------------------------------------------


def make_osdmap(n=6, per_host=2):
    m = OSDMap()
    m.fsid = "test-fsid"
    m.epoch = 1
    m.crush.build_flat(n, per_host)
    for o in range(n):
        m.add_osd(o, addr=f"127.0.0.1:{6800 + o}")
    return m


class TestOSDMap:
    def test_replicated_mapping(self):
        m = make_osdmap()
        rid = m.crush.add_simple_rule("rep", mode="firstn")
        m.create_pool("rbd", POOL_TYPE_REPLICATED, size=3, crush_rule=rid)
        pool = m.get_pool("rbd")
        pg = m.object_to_pg(pool.id, "obj1")
        up, primary, acting, _ = m.pg_to_up_acting_osds(*pg)
        assert len(up) == 3 and primary == up[0]

    def test_erasure_mapping_holes(self):
        m = make_osdmap(8, 2)
        rid = m.crush.add_simple_rule("ec", mode="indep", failure_domain="osd")
        m.create_pool("ecpool", POOL_TYPE_ERASURE, size=5, crush_rule=rid)
        pool = m.get_pool("ecpool")
        up, primary, _, _ = m.pg_to_up_acting_osds(pool.id, 3)
        assert len(up) == 5
        victim = next(o for o in up if o != PG_NONE)
        m.set_osd_state(victim, False)
        up2, _, _, _ = m.pg_to_up_acting_osds(pool.id, 3)
        assert up2[up.index(victim)] == PG_NONE
        for a, b in zip(up, up2):
            if a != victim:
                assert a == b

    def test_out_osd_remapped(self):
        m = make_osdmap(8, 2)
        rid = m.crush.add_simple_rule("ec", mode="indep", failure_domain="osd")
        m.create_pool("ecpool", POOL_TYPE_ERASURE, size=4, crush_rule=rid)
        pool = m.get_pool("ecpool")
        up, _, _, _ = m.pg_to_up_acting_osds(pool.id, 5)
        victim = up[1]
        m.set_osd_weight(victim, 0)  # marked out: CRUSH refills the slot
        up2, _, _, _ = m.pg_to_up_acting_osds(pool.id, 5)
        assert up2[1] != victim
        assert up2[1] != PG_NONE

    def test_encode_decode_roundtrip(self):
        m = make_osdmap()
        rid = m.crush.add_simple_rule("ec", mode="indep")
        m.erasure_code_profiles["default"] = {"plugin": "tpu", "k": "4", "m": "2"}
        m.create_pool(
            "ecpool",
            POOL_TYPE_ERASURE,
            size=6,
            crush_rule=rid,
            erasure_code_profile="default",
            stripe_width=16384,
        )
        m2 = OSDMap.frombytes(m.tobytes())
        assert m2.epoch == m.epoch
        assert m2.erasure_code_profiles == m.erasure_code_profiles
        assert m2.get_pool("ecpool").stripe_width == 16384
        # Decoded map must produce identical placements.
        pool = m.get_pool("ecpool")
        for ps in range(pool.pg_num):
            assert m.pg_to_up_acting_osds(pool.id, ps) == m2.pg_to_up_acting_osds(
                pool.id, ps
            )

    def test_incremental_apply(self):
        m = make_osdmap()
        inc = Incremental(epoch=2, new_down=[0], new_weights={1: 0})
        inc2 = Incremental.frombytes(inc.tobytes())
        m = inc2.apply_to(m)
        assert m.epoch == 2
        assert not m.is_up(0)
        assert not m.osds[1].in_

    def test_incremental_full_map(self):
        m = make_osdmap()
        m.epoch = 5
        inc = Incremental(epoch=5, full_map=m.tobytes())
        m2 = inc.apply_to(OSDMap())
        assert m2.epoch == 5 and len(m2.osds) == len(m.osds)
