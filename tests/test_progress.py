"""ISSUE 8 recovery-progress pipeline contracts.

Three layers, tested at their seams:

1. OSD side — PG.progress_status() emits recovery/backfill/scrub events
   (objects/bytes done vs total) on the primary, and completion resets
   the episode.
2. Mgr side — ProgressModule aggregates reports into per-PG bars with a
   smoothed rate + ETA, a cluster-wide aggregate, prometheus gauges,
   and the PG_RECOVERY_STALLED health check (raise on no-advance past
   the window, clear on resumed progress or completion).
3. Mon side — the digest's progress slice renders in `status` and the
   stalled sub-slice raises the mon-side PG_RECOVERY_STALLED check.
"""

import time

from ceph_tpu.mgr.progress import ProgressModule
from ceph_tpu.osd.pg_log import Eversion, Missing


class _FakeMgr:
    def __init__(self):
        self.statuses: dict[str, dict] = {}
        self.modules: list = []

    def list_daemons(self):
        return sorted(self.statuses)

    def get_daemon_status(self, daemon):
        return self.statuses.get(daemon, {})

    def report(self, daemon, pgid, events):
        self.statuses[daemon] = {"progress": {pgid: events}}


def _recovery_ev(done, total, bytes_done=0):
    return {
        "kind": "recovery",
        "objects_done": done,
        "objects_total": total,
        "bytes_done": bytes_done,
        "bytes_total": 0,
    }


class TestPgProgressEvents:
    """PG.progress_status over a fake-OSD PG (the test_backfill rig)."""

    def _pg(self, n_objects=6):
        from test_backfill import _backfilling_pg

        pg, osd = _backfilling_pg(n_objects=n_objects)
        pg.peering.backfill_targets = set()
        pg.peering.last_backfill = {}
        return pg, osd

    def test_recovery_event_counts_missing_and_done(self):
        pg, _osd = self._pg()
        pg.peering.peer_missing[1] = m = Missing()
        m.add("o001", Eversion(1, 1))
        m.add("o002", Eversion(1, 2))
        events = pg.progress_status()
        assert len(events) == 1
        ev = events[0]
        assert ev["kind"] == "recovery"
        assert ev["objects_total"] == 2
        assert ev["objects_done"] == 0
        # backend pipeline depth rides along (ECBackend.recovery_inflight
        # on EC pools; the replicated fake has none — absent is fine)
        # one object recovers: done advances, total holds.  Counting is
        # gated on the recovery driver's in-flight set (backfill pushes
        # share the backend completion hook but must not count)
        pg.recovering.add("o001")
        pg.note_recovery_bytes("o001", 4096)
        pg.on_global_recover("o001")
        ev = pg.progress_status()[0]
        assert ev["objects_done"] == 1
        assert ev["objects_total"] == 2
        assert ev["bytes_done"] == 4096
        # newly discovered missing grows the total, never shrinks done
        m.add("o003", Eversion(1, 3))
        ev = pg.progress_status()[0]
        assert ev["objects_total"] == 3
        assert ev["objects_done"] == 1

    def test_recovery_episode_resets_after_completion(self):
        pg, _osd = self._pg()
        pg.peering.peer_missing[1] = m = Missing()
        m.add("o001", Eversion(1, 1))
        assert pg.progress_status()
        pg.recovering.add("o001")
        pg.on_global_recover("o001")
        # missing drained: the final done==total report (the mgr's
        # completed-vs-expired classification needs it) repeats on a
        # few reports — a one-shot would race the mgr's sampling of the
        # last-write-wins status blob — then silence
        for _ in range(3):
            final = pg.progress_status()
            assert len(final) == 1
            assert final[0]["objects_done"] == final[0]["objects_total"] == 1
        assert pg.progress_status() == []
        assert pg._recovery_total == 0 and pg._recovery_done == 0
        # a NEW episode starts from zero
        pg.peering.peer_missing[1].add("o002", Eversion(1, 2))
        ev = pg.progress_status()[0]
        assert (ev["objects_done"], ev["objects_total"]) == (0, 1)

    def test_backfill_event_tracks_cursor(self):
        pg, _osd = self._pg(n_objects=6)
        pg.peering.backfill_targets = {1}
        pg.peering.last_backfill = {1: ""}
        ev = [e for e in pg.progress_status() if e["kind"] == "backfill"][0]
        assert ev["objects_total"] == 6
        assert ev["objects_done"] == 0
        pg.peering.last_backfill[1] = "o002"  # cursor passed o000..o002
        ev = [e for e in pg.progress_status() if e["kind"] == "backfill"][0]
        assert ev["objects_done"] == 3

    def test_scrub_event_reports_chunk_progress(self):
        pg, _osd = self._pg(n_objects=4)
        pg.scrubber.active = True
        pg.scrubber._total_objects = 4
        from ceph_tpu.osd.scrubber import ScrubResult

        pg.scrubber._result = ScrubResult()
        pg.scrubber._result.objects_scrubbed = 2
        ev = [e for e in pg.progress_status() if "scrub" in e["kind"]][0]
        assert ev["objects_done"] == 2
        assert ev["objects_total"] == 4

    def test_interval_change_resets_episode_counters(self):
        """A demoted primary's progress_status goes silent before its
        completion-reset branch can run; the interval change itself must
        zero the episode counters or the next primaryship starts with a
        pre-filled bar."""
        pg, _osd = self._pg()
        pg._recovery_total = 12
        pg._recovery_done = 10
        pg._recovery_done_bytes = 4096
        pg.on_new_interval(7, [1, 0])  # acting changed: new interval
        assert pg._recovery_total == 0
        assert pg._recovery_done == 0
        assert pg._recovery_done_bytes == 0

    def test_backfill_pushes_do_not_count_as_recovery(self):
        """Backfill rides backend.recover_object and its completion hook
        calls on_global_recover — but it must not pollute the recovery
        done counters (a later real recovery would render 98% complete
        before it started)."""
        pg, _osd = self._pg()
        for oid in ("o000", "o001", "o002"):
            pg.on_global_recover(oid)       # backfill-push completions
            pg.note_recovery_bytes(oid, 4096)
        assert pg._recovery_done == 0
        assert pg._recovery_done_bytes == 0
        pg.peering.peer_missing[1] = m = Missing()
        m.add("o003", Eversion(1, 4))
        ev = pg.progress_status()[0]
        assert (ev["objects_done"], ev["objects_total"]) == (0, 1)

    def test_double_completion_counts_once(self):
        """The backend AND _recover_one's callback both invoke
        on_global_recover for one recovered object; done advances by
        exactly one."""
        pg, _osd = self._pg()
        pg.peering.peer_missing[1] = m = Missing()
        m.add("o001", Eversion(1, 1))
        pg.progress_status()
        pg.recovering.add("o001")
        pg.on_global_recover("o001")  # backend _finish_recovery
        pg.on_global_recover("o001")  # _recover_one on_complete
        assert pg._recovery_done == 1

    def test_non_primary_reports_nothing(self):
        pg, _osd = self._pg()
        pg.peering.peer_missing[1] = m = Missing()
        m.add("o001", Eversion(1, 1))
        pg.peering.primary = 1  # not us
        assert pg.progress_status() == []


class TestProgressModule:
    def _module(self, stall_sec=10.0):
        m = ProgressModule(stall_sec=stall_sec)
        m.mgr = _FakeMgr()
        return m

    def test_rate_and_eta_math(self):
        m = self._module()
        m.mgr.report("osd.0", "1.0", [_recovery_ev(2, 10)])
        m.tick()
        time.sleep(0.1)
        m.mgr.report("osd.0", "1.0", [_recovery_ev(4, 10)])
        m.tick()
        ev = m.progress_digest()["events"][0]
        # ~2 objects over ~0.1s -> ~20 obj/s; 6 remaining -> ~0.3s ETA
        assert 10 < ev["rate_objects_per_sec"] < 40, ev
        assert 0.1 < ev["eta_seconds"] < 0.7, ev
        assert ev["fraction"] == 0.4

    def test_duplicate_same_tick_reports_never_explode_rate(self):
        """A stale blob from the old primary next to the new primary's
        fresh one observes the same event twice with dt ~ 0: counts
        update, but no rate sample is taken (dividing by ~0 would EMA
        the rate to millions of objects/sec)."""
        m = self._module()
        m.mgr.report("osd.0", "1.0", [_recovery_ev(2, 10)])
        m.tick()
        # two daemons carry the same pgid event in one tick
        m.mgr.report("osd.0", "1.0", [_recovery_ev(2, 10)])
        m.mgr.report("osd.1", "1.0", [_recovery_ev(5, 10)])
        m.tick()
        ev = m.progress_digest()["events"][0]
        assert ev["objects_done"] == 5
        assert ev["rate_objects_per_sec"] < 1000, ev

    def test_stale_regressing_report_does_not_mask_stall(self):
        """Failover overlap: the old primary's stale blob (lower done,
        same total) must not lower the baseline — the next fresh report
        would otherwise register a fake advance and re-arm the stall
        clock forever."""
        m = self._module(stall_sec=0.15)
        m.mgr.report("osd.1", "1.0", [_recovery_ev(50, 100)])
        m.tick()
        for _ in range(3):
            time.sleep(0.08)
            # stale old-primary blob then fresh (but unadvancing) one
            m.mgr.statuses["osd.0"] = {
                "progress": {"1.0": [_recovery_ev(30, 100)]}
            }
            m.mgr.statuses["osd.1"] = {
                "progress": {"1.0": [_recovery_ev(50, 100)]}
            }
            m.tick()
        ev = m.progress_digest()["events"][0]
        assert ev["objects_done"] == 50  # baseline never regressed
        assert ev["rate_objects_per_sec"] == 0.0  # no fake samples
        assert "PG_RECOVERY_STALLED" in m.health_checks

    def test_stale_lower_bytes_does_not_mask_stall(self):
        """A stale blob with equal done but LOWER bytes must not lower
        the baseline — the next fresh (unchanged) report would register
        a fake advance and re-arm the stall clock on every flap."""
        m = self._module(stall_sec=0.15)

        def ev(bytes_done):
            e = _recovery_ev(2, 10)
            e["bytes_done"] = bytes_done
            return e

        m.mgr.report("osd.1", "1.0", [ev(100)])
        m.tick()
        for _ in range(3):
            time.sleep(0.08)
            m.mgr.statuses["osd.0"] = {"progress": {"1.0": [ev(50)]}}
            m.mgr.statuses["osd.1"] = {"progress": {"1.0": [ev(100)]}}
            m.tick()
        assert m.progress_digest()["events"][0]["bytes_done"] == 100
        assert "PG_RECOVERY_STALLED" in m.health_checks

    def test_stalled_prometheus_gauges_match_render(self):
        """The scrape must agree with render(): a stalled event exports
        rate 0 and no ETA (not the frozen last EMA)."""
        m = self._module(stall_sec=0.05)
        m.mgr.report("osd.0", "1.0", [_recovery_ev(2, 10)])
        m.tick()
        time.sleep(0.1)
        m.mgr.report("osd.0", "1.0", [_recovery_ev(4, 10)])
        m.tick()  # a rate now exists
        time.sleep(0.1)
        m.tick()  # ...and the event stalls
        fams = {name: rows for name, _t, _h, rows in m.prometheus_metrics()}
        rates = [
            float(r.rsplit(" ", 1)[1])
            for r in fams["ceph_tpu_progress_rate_objects"]
        ]
        assert rates == [0.0], rates
        assert fams["ceph_tpu_progress_eta_seconds"] == []

    def test_lower_done_with_new_total_starts_fresh_episode(self):
        """A genuinely new episode on the same (pgid, kind) key (lower
        done, different total) rebases instead of being dropped."""
        m = self._module()
        m.mgr.report("osd.0", "1.0", [_recovery_ev(5, 5)])
        m.tick()
        m.mgr.report("osd.0", "1.0", [_recovery_ev(0, 2)])
        m.tick()
        ev = m.progress_digest()["events"][0]
        assert (ev["objects_done"], ev["objects_total"]) == (0, 2)

    def test_persistent_same_total_regression_rebases(self, monkeypatch):
        """A new episode reusing the previous episode's total must not
        be frozen forever by the stale-blob guard: once the regression
        persists past the failover-overlap window, it rebases (else the
        bar shows the OLD episode complete and a FALSE stall raises)."""
        from ceph_tpu.mgr import progress as progress_mod

        monkeypatch.setattr(progress_mod, "_REGRESS_WINDOW", 0.05)
        m = self._module()
        m.mgr.report("osd.0", "1.0", [_recovery_ev(12, 12)])
        m.tick()
        # episode 2, same total, before the old event expired
        m.mgr.report("osd.0", "1.0", [_recovery_ev(1, 12)])
        m.tick()  # first regressing report: treated as stale, dropped
        assert m.progress_digest()["events"][0]["objects_done"] == 12
        time.sleep(0.08)
        m.mgr.report("osd.0", "1.0", [_recovery_ev(2, 12)])
        m.tick()  # persisted past the window: rebased as a new episode
        ev = m.progress_digest()["events"][0]
        assert (ev["objects_done"], ev["objects_total"]) == (2, 12)

    def test_first_report_has_no_rate(self):
        """One report = no elapsed baseline: rate 0, ETA None (a
        fabricated dt~0 rate would render an absurd instant ETA)."""
        m = self._module()
        m.mgr.report("osd.0", "1.0", [_recovery_ev(5, 10)])
        m.tick()
        ev = m.progress_digest()["events"][0]
        assert ev["rate_objects_per_sec"] == 0.0
        assert ev["eta_seconds"] is None

    def test_cluster_aggregate(self):
        m = self._module()
        m.mgr.report("osd.0", "1.0", [_recovery_ev(1, 4)])
        m.mgr.report("osd.1", "1.1", [_recovery_ev(3, 4)])
        m.tick()
        cluster = m.progress_digest()["cluster"]
        assert cluster["objects_done"] == 4
        assert cluster["objects_total"] == 8
        assert cluster["fraction"] == 0.5

    def test_stall_raises_and_clears_on_resume(self):
        m = self._module(stall_sec=0.15)
        m.mgr.report("osd.0", "1.0", [_recovery_ev(2, 10)])
        m.tick()
        assert "PG_RECOVERY_STALLED" not in m.health_checks
        time.sleep(0.2)
        m.tick()  # same counts -> no advance past the window
        assert "PG_RECOVERY_STALLED" in m.health_checks
        assert "1.0" in m.health_checks["PG_RECOVERY_STALLED"]["summary"]
        stalled = m.progress_digest()["stalled"]
        assert stalled["1.0:recovery"]["kind"] == "recovery"
        assert stalled["1.0:recovery"]["pgid"] == "1.0"
        # progress resumes: the check clears
        m.mgr.report("osd.0", "1.0", [_recovery_ev(3, 10)])
        m.tick()
        assert "PG_RECOVERY_STALLED" not in m.health_checks
        assert m.progress_digest()["stalled"] == {}

    def test_stall_clears_on_completion(self):
        m = self._module(stall_sec=0.1)
        m.mgr.report("osd.0", "1.0", [_recovery_ev(2, 10)])
        m.tick()
        time.sleep(0.15)
        m.tick()
        assert "PG_RECOVERY_STALLED" in m.health_checks
        # the OSD stops reporting the event (reporter went away at
        # 2/10: dropped mid-flight, so it counts as expired — only a
        # done >= total disappearance counts as completed)
        m.mgr.statuses["osd.0"] = {"progress": {}}
        m.events[("1.0", "recovery")].last_seen -= 10  # past expiry
        m.tick()
        assert "PG_RECOVERY_STALLED" not in m.health_checks
        assert m.progress_digest()["events"] == []
        assert m.completed == 0
        assert m.expired == 1

    def test_down_daemon_report_does_not_pin_event(self):
        """A down OSD's frozen status blob must not keep refreshing its
        events: the liveness filter (Mgr._daemon_report_live) drops it,
        the event expires as completed, and no permanent stall sticks."""
        m = self._module(stall_sec=0.05)
        m.mgr.report("osd.0", "1.0", [_recovery_ev(2, 10)])
        m.mgr._daemon_report_live = lambda daemon: daemon != "osd.0"
        m.tick()  # frozen report filtered: event never tracked
        assert m.progress_digest()["events"] == []
        time.sleep(0.1)
        m.tick()
        assert "PG_RECOVERY_STALLED" not in m.health_checks

    def test_recovery_and_backfill_stall_report_both(self):
        """One PG with BOTH a stalled recovery and a stalled backfill:
        the slice keys by (pgid, kind) so neither hides the other."""
        m = self._module(stall_sec=0.05)
        m.mgr.report("osd.0", "1.0", [
            _recovery_ev(2, 10),
            {"kind": "backfill", "objects_done": 1, "objects_total": 5,
             "bytes_done": 0, "bytes_total": 0},
        ])
        m.tick()
        time.sleep(0.1)
        m.tick()
        stalled = m.progress_digest()["stalled"]
        assert set(stalled) == {"1.0:recovery", "1.0:backfill"}
        assert "2 pg event(s)" in (
            m.health_checks["PG_RECOVERY_STALLED"]["summary"]
        )

    def test_stall_window_tracks_mgr_config(self):
        """mgr_progress_stall_sec is runtime-mutable: an un-pinned
        module re-reads the mgr's Config every tick."""
        from ceph_tpu.common.config import Config

        m = ProgressModule()  # no constructor pin
        m.mgr = _FakeMgr()
        m.mgr.conf = Config({"name": "mgr.x"}, env=False)
        m.mgr.conf.set("mgr_progress_stall_sec", 0.07)
        m.mgr.report("osd.0", "1.0", [_recovery_ev(2, 10)])
        m.tick()
        assert m.stall_sec == 0.07
        time.sleep(0.12)
        m.tick()
        assert "PG_RECOVERY_STALLED" in m.health_checks

    def test_finished_recovery_classifies_completed_not_expired(self):
        """The PG's final done==total report lets the module tell a
        finished recovery (completed) from a reporter that died
        mid-flight (expired)."""
        m = self._module()
        m.mgr.report("osd.0", "1.0", [_recovery_ev(9, 10)])
        m.tick()
        m.mgr.report("osd.0", "1.0", [_recovery_ev(10, 10)])
        m.tick()
        m.mgr.statuses["osd.0"] = {"progress": {}}
        m.events[("1.0", "recovery")].last_seen -= 10
        m.tick()
        assert m.completed == 1
        assert m.expired == 0

    def test_scrub_never_stalls(self):
        m = self._module(stall_sec=0.05)
        m.mgr.report("osd.0", "1.0", [{
            "kind": "scrub", "objects_done": 1, "objects_total": 9,
            "bytes_done": 0, "bytes_total": 0,
        }])
        m.tick()
        time.sleep(0.1)
        m.tick()
        assert "PG_RECOVERY_STALLED" not in m.health_checks

    def test_prometheus_gauges(self):
        m = self._module()
        m.mgr.report("osd.0", "1.0", [_recovery_ev(2, 10)])
        m.tick()
        fams = {name: (ftype, rows)
                for name, ftype, _h, rows in m.prometheus_metrics()}
        assert fams["ceph_tpu_progress_fraction"][0] == "gauge"
        assert any('pgid="1.0"' in r
                   for r in fams["ceph_tpu_progress_fraction"][1])
        assert fams["ceph_tpu_progress_active"][1] == [
            "ceph_tpu_progress_active 1"
        ]


class TestMonSurfaces:
    """The mon renders the digest's progress slice in `status` and the
    stalled sub-slice as PG_RECOVERY_STALLED."""

    def _mon(self):
        import asyncio

        from ceph_tpu.mon import MonMap, Monitor

        async def build():
            monmap = MonMap(addrs={"a": "127.0.0.1:0"})
            return Monitor("a", monmap, election_timeout=0.3)

        return asyncio.new_event_loop().run_until_complete(build())

    def test_status_carries_progress_and_stalled_check(self):
        mon = self._mon()
        mon.pg_digest = {
            "progress": {
                "events": [{
                    "pgid": "1.0", "kind": "recovery", "objects_done": 3,
                    "objects_total": 9, "fraction": 0.3333,
                    "rate_objects_per_sec": 2.0, "eta_seconds": 3.0,
                    "stalled": True,
                }],
                "cluster": {"objects_done": 3, "objects_total": 9,
                            "fraction": 0.3333},
                "stalled": {
                    "1.0:recovery": {
                        "pgid": "1.0", "kind": "recovery",
                        "stalled_for_sec": 75.0,
                        "objects_done": 3, "objects_total": 9,
                    },
                },
            },
        }
        checks, details = mon.health_checks()
        assert "PG_RECOVERY_STALLED" in checks
        assert "1.0" in checks["PG_RECOVERY_STALLED"]
        assert any("3/9 objects" in line
                   for line in details["PG_RECOVERY_STALLED"])
        # the status command payload carries the bars
        handler = mon._mon_command_handler("status")
        captured = {}

        def reply(rv, rs, outbl):
            captured.update(rv=rv, outbl=outbl)

        handler({}, reply)
        import json

        payload = json.loads(captured["outbl"].decode())
        assert payload["progress"]["events"][0]["pgid"] == "1.0"
        assert payload["progress"]["events"][0]["eta_seconds"] == 3.0
        assert "PG_RECOVERY_STALLED" in payload["health"]["checks"]

    def test_clear_digest_raises_nothing(self):
        mon = self._mon()
        mon.pg_digest = {"progress": {"events": [], "stalled": {}}}
        checks, _ = mon.health_checks()
        assert "PG_RECOVERY_STALLED" not in checks
