"""Common substrate tests (config, logging, perf counters, encoding,
throttle, fault injection, tracer, admin socket).

Modeled on the reference's src/test/common/ unit tests (e.g.
test_config.cc, perf_counters.cc, test_fault_injector.cc).
"""

import asyncio
import threading
import time

import pytest

from ceph_tpu.common import (
    Config,
    Decoder,
    Encoder,
    FaultInjector,
    OPTIONS,
    PerfCountersBuilder,
    PerfCountersCollection,
    Throttle,
    Tracer,
)
from ceph_tpu.common.admin_socket import AdminSocket, admin_command
from ceph_tpu.common.encoding import DecodeError
from ceph_tpu.common.fault_injector import InjectedFailure
from ceph_tpu.common.log import Log, LogClient, LogEntry, SubsystemMap


# --- config ------------------------------------------------------------------


class TestConfig:
    def test_defaults(self):
        cfg = Config(env=False)
        assert cfg.get("osd_op_num_shards") == OPTIONS["osd_op_num_shards"].default

    def test_overrides_and_types(self):
        cfg = Config({"osd_op_num_shards": "8", "osd_fast_read": "true"}, env=False)
        assert cfg.get("osd_op_num_shards") == 8
        assert cfg.get("osd_fast_read") is True

    def test_unknown_option_raises(self):
        cfg = Config(env=False)
        with pytest.raises(KeyError):
            cfg.get("nope")
        with pytest.raises(KeyError):
            cfg.set("nope", 1)

    def test_observer_notified_on_runtime_set(self):
        cfg = Config(env=False)
        seen = []
        cfg.add_observer(["osd_heartbeat_grace"], lambda k, v: seen.append((k, v)))
        cfg.set("osd_heartbeat_grace", "12.5")
        assert seen == [("osd_heartbeat_grace", 12.5)]

    def test_diff_only_changed(self):
        cfg = Config({"mon_lease": 2.0}, env=False)
        assert cfg.diff() == {"mon_lease": 2.0}

    def test_conf_file(self, tmp_path):
        p = tmp_path / "ceph.conf"
        p.write_text("[global]\n# comment\nmon lease = 3.5\nosd_op_num_shards = 2\n")
        cfg = Config(conf_file=str(p), env=False)
        assert cfg.get("mon_lease") == 3.5
        assert cfg.get("osd_op_num_shards") == 2

    def test_debug_levels(self):
        cfg = Config({"debug_osd": "10/20"}, env=False)
        assert cfg.debug_levels("osd") == (10, 20)


# --- logging -----------------------------------------------------------------


class TestLog:
    def test_gather_vs_emit(self, tmp_path):
        path = tmp_path / "out.log"
        lc = LogClient(Log(str(path), max_recent=100), SubsystemMap())
        lc.subsys.set_log_level("osd", 1, 10)
        lc.dout("osd", 0, "emitted")
        lc.dout("osd", 5, "gathered only")
        lc.dout("osd", 20, "dropped")
        lc.log.flush()
        lc.log.stop()
        text = path.read_text()
        assert "emitted" in text
        assert "gathered only" not in text
        recent = "\n".join(lc.log.dump_recent())
        assert "gathered only" in recent
        assert "dropped" not in recent

    def test_from_config(self):
        cfg = Config({"debug_osd": "7/9"}, env=False)
        lc = LogClient.from_config(cfg)
        assert lc.subsys.levels("osd") == (7, 9)
        lc.log.stop()


# --- perf counters -----------------------------------------------------------


class TestPerfCounters:
    def test_counter_types_and_dump(self):
        pc = (
            PerfCountersBuilder("osd")
            .add_u64_counter("op_w", "writes")
            .add_u64("numpg", "pg count")
            .add_time_avg("op_w_lat", "write latency")
            .create_perf_counters()
        )
        pc.inc("op_w")
        pc.inc("op_w", 2)
        pc.set("numpg", 13)
        pc.tinc("op_w_lat", 0.5)
        pc.tinc("op_w_lat", 1.5)
        d = pc.dump()
        assert d["op_w"] == 3
        assert d["numpg"] == 13
        assert d["op_w_lat"] == {"avgcount": 2, "sum": 2.0}

    def test_collection_and_prometheus(self):
        coll = PerfCountersCollection()
        pc = PerfCountersBuilder("ec.rs").add_u64_counter("encode_ops").create_perf_counters()
        pc.inc("encode_ops", 7)
        coll.add(pc)
        assert coll.dump()["ec.rs"]["encode_ops"] == 7
        text = coll.prometheus_text()
        assert "ceph_tpu_ec_rs_encode_ops 7" in text


# --- encoding ----------------------------------------------------------------


class TestEncoding:
    def test_roundtrip_primitives(self):
        e = (
            Encoder()
            .u8(7)
            .u16(300)
            .u32(1 << 20)
            .u64(1 << 40)
            .i64(-5)
            .f64(2.5)
            .boolean(True)
            .string("héllo")
            .bytes_(b"\x00\x01")
        )
        d = Decoder(e.tobytes())
        assert d.u8() == 7
        assert d.u16() == 300
        assert d.u32() == 1 << 20
        assert d.u64() == 1 << 40
        assert d.i64() == -5
        assert d.f64() == 2.5
        assert d.boolean() is True
        assert d.string() == "héllo"
        assert d.bytes_() == b"\x00\x01"
        assert d.remaining() == 0

    def test_containers(self):
        e = Encoder()
        e.list_([1, 2, 3], lambda enc, v: enc.u32(v))
        e.map_({"a": 1, "b": 2}, lambda enc, k: enc.string(k), lambda enc, v: enc.u64(v))
        d = Decoder(e.tobytes())
        assert d.list_(lambda dec: dec.u32()) == [1, 2, 3]
        assert d.map_(lambda dec: dec.string(), lambda dec: dec.u64()) == {"a": 1, "b": 2}

    def test_versioned_frame_skips_new_fields(self):
        # A v2 encoder writes an extra field; a v1-aware decoder skips it
        # via DECODE_FINISH — the rolling-upgrade property
        # (encoding.h:188 struct_compat contract).
        e = Encoder().start(2, 1).u32(42).string("newfield").finish().u32(99)
        d = Decoder(e.tobytes())
        v = d.start(1)
        assert v == 2
        assert d.u32() == 42
        d.finish()  # skips "newfield"
        assert d.u32() == 99

    def test_incompatible_version_raises(self):
        e = Encoder().start(3, 3).u32(1).finish()
        with pytest.raises(DecodeError):
            Decoder(e.tobytes()).start(2)

    def test_underrun_raises(self):
        with pytest.raises(DecodeError):
            Decoder(b"\x01").u32()

    def test_truncated_versioned_frame_raises(self):
        # A frame whose length header overruns the actual buffer must fail
        # at start(), not silently "succeed" at finish().
        full = Encoder().start(1, 1).u32(42).string("payload").finish().tobytes()
        with pytest.raises(DecodeError):
            Decoder(full[:8]).start(1)


# --- throttle ----------------------------------------------------------------


class TestThrottle:
    def test_get_or_fail(self):
        t = Throttle("t", 10)
        assert t.get_or_fail(8)
        assert not t.get_or_fail(5)
        t.put(8)
        assert t.get_or_fail(5)

    def test_oversized_request_admitted_when_drained(self):
        # Reference _should_wait semantics: a request larger than the limit
        # must not deadlock — it goes through once usage drains to zero.
        t = Throttle("t", 10)
        t.get(150)
        assert t.current == 150
        t.put(150)

    def test_blocking_get_wakes(self):
        t = Throttle("t", 1)
        t.get(1)
        acquired = threading.Event()

        def taker():
            t.get(1)
            acquired.set()

        th = threading.Thread(target=taker)
        th.start()
        time.sleep(0.05)
        assert not acquired.is_set()
        t.put(1)
        th.join(timeout=2)
        assert acquired.is_set()


# --- fault injection ---------------------------------------------------------


class TestFaultInjector:
    def test_armed_point_fires_n_times(self):
        fi = FaultInjector()
        fi.inject("ec.read", 5, hits=2)
        for _ in range(2):
            with pytest.raises(InjectedFailure) as ei:
                fi.check("ec.read")
            assert ei.value.errno == -5
        fi.check("ec.read")  # budget exhausted

    def test_clear(self):
        fi = FaultInjector()
        fi.inject("x", 5)
        fi.clear("x")
        fi.check("x")

    def test_probabilistic_eventually_fires(self):
        fi = FaultInjector()
        fi.inject_probabilistic("sock", 2)
        fired = 0
        for _ in range(100):
            try:
                fi.check("sock")
            except InjectedFailure:
                fired += 1
        assert 20 < fired < 80


# --- tracer ------------------------------------------------------------------


class TestTracer:
    def test_span_tree_and_events(self):
        tr = Tracer("osd")
        with tr.start_span("ec write") as root:
            root.event("start ec write")
            with root.child("encode") as child:
                child.keyval("stripes", 4)
        spans = tr.export()
        assert len(spans) == 2
        root_d = next(s for s in spans if s["parent_id"] is None)
        child_d = next(s for s in spans if s["parent_id"] is not None)
        assert child_d["parent_id"] == root_d["span_id"]
        assert root_d["events"][0]["name"] == "start ec write"
        assert child_d["tags"] == {"stripes": "4"}
        assert root_d["end"] is not None

    def test_disabled_tracer_records_nothing(self):
        tr = Tracer("osd", enabled=False)
        with tr.start_span("x") as s:
            s.event("e")
        assert tr.export() == []


# --- admin socket ------------------------------------------------------------


class TestAdminSocket:
    def test_commands(self, tmp_path):
        path = str(tmp_path / "osd.asok")
        result = {}

        async def run():
            sock = AdminSocket(path)
            coll = PerfCountersCollection()
            pc = PerfCountersBuilder("osd").add_u64_counter("ops").create_perf_counters()
            pc.inc("ops", 5)
            coll.add(pc)
            sock.register("perf dump", lambda cmd: coll.dump(), "dump perfcounters")
            await sock.start()
            loop = asyncio.get_running_loop()
            result["perf"] = await loop.run_in_executor(
                None, lambda: admin_command(path, "perf dump")
            )
            result["help"] = await loop.run_in_executor(
                None, lambda: admin_command(path, "help")
            )
            try:
                await loop.run_in_executor(
                    None, lambda: admin_command(path, "bogus")
                )
            except RuntimeError as e:
                result["err"] = str(e)
            await sock.stop()

        asyncio.run(run())
        assert result["perf"]["osd"]["ops"] == 5
        assert "perf dump" in result["help"]
        assert "unknown command" in result["err"]


class TestOpTracker:
    def test_inflight_history_and_slow(self):
        """OpTracker (src/common/TrackedOp.h): in-flight registry, event
        marks, bounded history, slowest-retained ring."""
        import time as _time

        from ceph_tpu.common.op_tracker import OpTracker

        t = OpTracker(history_size=3, slow_size=2)
        a = t.create("osd_op(a)")
        b = t.create("osd_op(b)")
        t.mark_event(a, "queued")
        d = t.dump_in_flight()
        assert d["num_ops"] == 2
        assert d["ops"][0]["description"] == "osd_op(a)"
        assert any(e["event"] == "queued" for e in d["ops"][0]["type_data"]["events"])
        t.finish(a)
        _time.sleep(0.01)
        t.finish(b)  # slower (finished later from same-ish start)
        assert t.dump_in_flight()["num_ops"] == 0
        h = t.dump_historic()
        assert h["num_ops"] == 2
        assert h["ops"][0]["description"] == "osd_op(b)"  # most recent first
        assert all(o["duration"] is not None for o in h["ops"])
        # history ring is bounded
        for i in range(5):
            t.finish(t.create(f"osd_op(x{i})"))
        assert t.dump_historic()["num_ops"] == 3
        # slow ring keeps the slowest two
        s = t.dump_slow()
        assert s["num_ops"] == 2
        assert s["ops"][0]["duration"] >= s["ops"][1]["duration"]
        # finishing an unknown token is a no-op
        t.finish(99999)

    def test_historic_ops_carry_per_stage_durations(self):
        """ISSUE 8 satellite: dump_historic_ops renders the gap between
        consecutive event marks as named stage durations, so a slow
        historic op is attributable without diffing timestamps."""
        import time as _time

        from ceph_tpu.common.op_tracker import OpTracker

        t = OpTracker(history_size=4)
        tok = t.create("osd_op(staged)")
        _time.sleep(0.02)
        t.mark_event(tok, "queued")
        _time.sleep(0.01)
        t.mark_event(tok, "reached_pg")
        t.finish(tok)
        op = t.dump_historic()["ops"][0]
        stages = op["type_data"]["stages"]
        names = [s["stage"] for s in stages]
        assert names == ["queued", "reached_pg", "done"]
        assert stages[0]["duration"] >= 0.015  # initiated -> queued
        assert stages[1]["duration"] >= 0.005  # queued -> reached_pg
        assert all(s["duration"] >= 0.0 for s in stages)
        # stages sum to the op duration (within rounding)
        assert abs(sum(s["duration"] for s in stages) - op["duration"]) < 0.01
