"""Observability-layer tests (docs/OBSERVABILITY.md): trace-context
propagation across messenger round-trips, PerfHistogram bucket math, the
end-to-end op trace on a toy cluster, and the OSD→mgr→mon SLOW_OPS
health pipeline (appearing in `health detail`, clearing on drain, and
refusing spoofed mgr digests)."""

import asyncio
import json

import pytest

from ceph_tpu.common import tracer as tracer_mod
from ceph_tpu.common.perf_counters import (
    PerfCountersBuilder,
    PerfHistogram,
    PerfHistogram2D,
    PerfHistogramAxis,
)
from ceph_tpu.common.tracer import Tracer
from ceph_tpu.msg.message import decode_message, encode_message
from ceph_tpu.msg.messages import MMonMgrReport, MPing
from ceph_tpu.msg.messenger import Messenger

from test_msg import Collector, make_pair


# --- histogram bucket math ----------------------------------------------------


class TestHistogramMath:
    def test_log2_axis_bounds_and_index(self):
        axis = PerfHistogramAxis(lowest=1.0, buckets=4)
        # bucket i covers (lowest*2^(i-1), lowest*2^i]; last bucket +Inf
        assert axis.bounds == [1.0, 2.0, 4.0]
        assert axis.index(0.5) == 0
        assert axis.index(1.0) == 0  # boundary value lands in its bucket
        assert axis.index(1.5) == 1
        assert axis.index(2.0) == 1
        assert axis.index(3.0) == 2
        assert axis.index(4.0) == 2
        assert axis.index(100.0) == 3  # overflow -> +Inf bucket

    def test_histogram_dump_is_cumulative_with_inf(self):
        h = PerfHistogram(PerfHistogramAxis(lowest=1.0, buckets=4))
        for v in (0.5, 3.0, 100.0):
            h.sample(v)
        d = h.dump()["histogram"]
        assert d["buckets"] == [[1.0, 1], [2.0, 1], [4.0, 2], ["+Inf", 3]]
        assert d["count"] == 3
        assert d["sum"] == pytest.approx(103.5)

    def test_2d_histogram_cells(self):
        h = PerfHistogram2D(
            PerfHistogramAxis(lowest=10.0, buckets=3),
            PerfHistogramAxis(lowest=1.0, buckets=2),
        )
        h.sample(5.0, 0.5)    # x bucket 0, y bucket 0
        h.sample(15.0, 99.0)  # x bucket 1, y overflow
        d = h.dump()["histogram2d"]
        assert d["counts"][0][0] == 1
        assert d["counts"][1][1] == 1
        assert d["count"] == 2
        assert d["x_le"][-1] == "+Inf" and d["y_le"][-1] == "+Inf"

    def test_builder_hinc_and_dump_histograms(self):
        b = PerfCountersBuilder("osd")
        b.add_u64_counter("op")
        b.add_histogram("op_latency", lowest=1e-3, buckets=5)
        b.add_histogram_2d("op_size_latency")
        pc = b.create_perf_counters()
        pc.inc("op")
        pc.hinc("op_latency", 0.004)
        pc.hinc2("op_size_latency", 8192, 0.004)
        dump = pc.dump()
        assert dump["op"] == 1
        assert dump["op_latency"]["histogram"]["count"] == 1
        # dump_histograms returns ONLY the histogram counters
        hists = pc.dump_histograms()
        assert set(hists) == {"op_latency", "op_size_latency"}


# --- trace-context propagation ------------------------------------------------


class TestTraceContextPropagation:
    def test_envelope_roundtrip_carries_context(self):
        msg = MPing(stamp=1.0)
        msg.trace_id, msg.span_id = 0x1234, 0x5678
        env, payload = encode_message(msg)
        out = decode_message(env, payload)
        assert (out.trace_id, out.span_id) == (0x1234, 0x5678)

    def test_untraced_message_extracts_none(self):
        msg = MPing(stamp=1.0)
        env, payload = encode_message(msg)
        assert tracer_mod.extract(decode_message(env, payload)) is None

    def test_inject_extract_recorded_only(self):
        t = Tracer("client", enabled=True)
        span = t.start_span("client:op")
        msg = MPing(stamp=0.0)
        tracer_mod.inject(span, msg)
        ctx = tracer_mod.extract(msg)
        assert ctx is not None
        assert ctx.trace_id == span.trace_id and ctx.span_id == span.span_id
        # a disabled tracer's span must NOT leak a context
        off = Tracer("client", enabled=False).start_span("client:op")
        msg2 = MPing(stamp=0.0)
        tracer_mod.inject(off, msg2)
        assert tracer_mod.extract(msg2) is None

    def test_remote_context_links_trace_across_tracers(self):
        a = Tracer("client", enabled=True)
        b = Tracer("osd.0", enabled=True)
        root = a.start_span("client:op")
        child = b.start_span("osd:op", remote=root.context())
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id
        assert child.span_id != root.span_id  # per-tracer random id bases
        # local parent wins over remote
        local = b.start_span("sub", parent=child, remote=root.context())
        assert local.parent_id == child.span_id

    def test_span_scope_contextvar(self):
        t = Tracer("x", enabled=True)
        assert tracer_mod.current_span() is None
        with tracer_mod.span_scope(t.start_span("outer")) as sp:
            assert tracer_mod.current_span() is sp
            with tracer_mod.span_scope(sp.child("inner")) as inner:
                assert tracer_mod.current_span() is inner
            assert tracer_mod.current_span() is sp
        assert tracer_mod.current_span() is None

    def test_messenger_roundtrip_joins_trace(self):
        """A trace-carrying message delivered through a real (TCP)
        messenger records a msgr span on the receiver, parent-linked to
        the sender's span and sharing its trace id."""

        async def run():
            server, coll, client = await make_pair()
            server.tracer = Tracer("osd.0", enabled=True)
            sender = Tracer("client.1", enabled=True)
            span = sender.start_span("client:op")
            msg = MPing(stamp=1.0)
            tracer_mod.inject(span, msg)
            await client.send_to(server.addr, msg)
            await asyncio.wait_for(coll.got.wait(), 5)
            span.finish()
            hops = [s for s in server.tracer.export() if s["name"] == "msgr:MPing"]
            assert len(hops) == 1
            assert hops[0]["trace_id"] == span.trace_id
            assert hops[0]["parent_id"] == span.span_id
            # untraced messages must not create spans
            coll.got.clear()
            await client.send_to(server.addr, MPing(stamp=2.0))
            await asyncio.wait_for(coll.got.wait(), 5)
            assert len(server.tracer.export()) == 1
            await client.shutdown()
            await server.shutdown()

        asyncio.run(run())


# --- end-to-end op trace on a toy cluster ------------------------------------


class TestEndToEndTrace:
    def test_ec_write_yields_one_parent_linked_trace(self, tmp_path):
        """One client EC write = ONE trace: client:op → msgr:MOSDOp →
        osd:op → ec:write → codec stages, parent-linked across the
        client and OSD processes, retrievable via `dump_tracing`."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.common.admin_socket import admin_command
            from ceph_tpu.common.config import Config
            from ceph_tpu.mon import MonMap, Monitor
            from ceph_tpu.osd.osd import OSD

            from test_mon import free_port_addrs
            from test_cluster import stop_cluster

            monmap = MonMap(addrs=free_port_addrs(1))
            mons = [Monitor(n, monmap, election_timeout=0.3) for n in monmap.addrs]
            for m in mons:
                await m.start()
                await m.wait_for_quorum()

            def conf(i):
                return Config(
                    {
                        "name": f"osd.{i}",
                        "osd_heartbeat_interval": 0.1,
                        "osd_heartbeat_grace": 0.6,
                        "admin_socket": str(tmp_path / f"osd.{i}.asok"),
                        "jaeger_tracing_enable": True,
                    },
                    env=False,
                )

            osds = [OSD(i, monmap, conf=conf(i)) for i in range(3)]
            for o in osds:
                await o.start()
            for o in osds:
                await o.wait_for_up()

            client = Rados(monmap)
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "tr21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("trpool", "erasure", profile="tr21", pg_num=1)
            ioctx = await client.open_ioctx("trpool")
            # trace exactly ONE op: the EC write
            client.objecter.tracer.enabled = True
            await ioctx.write_full("traced", b"T" * 8192)
            client.objecter.tracer.enabled = False

            roots = [
                s
                for s in client.objecter.tracer.export()
                if s["name"] == "client:op"
            ]
            assert len(roots) == 1, roots
            trace_id = roots[0]["trace_id"]
            client_spans = [
                s
                for s in client.objecter.tracer.export()
                if s["trace_id"] == trace_id
            ]

            primary = next(
                o
                for o in osds
                if any(p.peering.is_primary() for p in o.pgs.values())
            )
            loop = asyncio.get_event_loop()
            dump = await loop.run_in_executor(
                None,
                lambda: admin_command(
                    str(tmp_path / f"osd.{primary.whoami}.asok"), "dump_tracing"
                ),
            )
            osd_spans = dump["traces"].get(str(trace_id), [])
            names = {s["name"] for s in osd_spans}
            assert "msgr:MOSDOp" in names
            assert "osd:op" in names
            assert "ec:write" in names
            assert any(n.startswith("codec:") for n in names), names

            # every span is parent-linked into the one trace
            ids = {s["span_id"] for s in client_spans} | {
                s["span_id"] for s in osd_spans
            }
            for s in list(client_spans) + list(osd_spans):
                assert s["parent_id"] is None or s["parent_id"] in ids
            # durations sum sensibly: children start at/after their parent
            # (comparable within one process's monotonic clock)
            by_id = {s["span_id"]: s for s in osd_spans}
            for s in osd_spans:
                parent = by_id.get(s["parent_id"])
                if parent is not None:
                    assert s["start"] >= parent["start"]

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


# --- SLOW_OPS health pipeline -------------------------------------------------


class TestSlowOpsHealth:
    def test_slow_ops_raise_and_clear_in_health_detail(self):
        """An in-flight op older than osd_op_complaint_time flows OSD →
        MMgrReport → mgr digest → MMonMgrReport → mon SLOW_OPS, shows a
        per-daemon breakdown under `health detail`, surfaces in the
        prometheus healthcheck gauge, and clears once the op drains."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.mgr import Mgr
            from ceph_tpu.mgr.prometheus import PrometheusModule

            from test_cluster import start_cluster, stop_cluster, wait_until

            monmap, mons, osds = await start_cluster(1, 1)
            mgr = Mgr("x", monmap)
            mgr.beacon_interval = 0.1
            await mgr.start()
            await mgr.wait_for_active()
            prom = PrometheusModule()
            mgr.register_module(prom)

            client = Rados(monmap)
            await client.connect()

            osd = osds[0]
            osd.op_tracker.complaint_time = 0.05
            token = osd.op_tracker.create("artificially stuck op")

            async def health(detail=False):
                cmd = {"prefix": "health"}
                if detail:
                    cmd["detail"] = True
                rv, rs, out = await client.mon_command(cmd)
                assert rv == 0, rs
                return json.loads(out)

            def mon_sees_slow():
                slow = mons[0].pg_digest.get("slow_ops") or {}
                return bool(slow.get("osd.0", {}).get("count"))

            await wait_until(mon_sees_slow, 5.0, "slow op reaching the mon")
            payload = await health(detail=True)
            assert payload["status"] == "HEALTH_WARN"
            assert "SLOW_OPS" in payload["checks"]
            assert "1 slow ops" in payload["checks"]["SLOW_OPS"]
            assert any(
                line.startswith("osd.0:") for line in payload["detail"]["SLOW_OPS"]
            )
            # the mgr-side gauge mirrors the check while it is raised
            assert 'ceph_tpu_healthcheck{name="SLOW_OPS"' in prom.scrape()

            osd.op_tracker.finish(token)
            await wait_until(
                lambda: not mon_sees_slow(), 5.0, "slow op draining"
            )
            payload = await health(detail=True)
            assert "SLOW_OPS" not in payload["checks"]
            assert payload["status"] == "HEALTH_OK"

            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_mon_drops_digest_from_non_active_mgr(self):
        """Satellite fix: only the mgrmap's ACTIVE mgr may supply the
        PGMap digest — a spoofed MMonMgrReport (standby or impostor) must
        not flip mon-side state like SLOW_OPS or pool quotas."""

        async def run():
            from ceph_tpu.mgr import Mgr

            from test_cluster import start_cluster, stop_cluster, wait_until

            monmap, mons, osds = await start_cluster(1, 1)
            mgr = Mgr("x", monmap)
            mgr.beacon_interval = 0.1
            await mgr.start()
            await mgr.wait_for_active()
            await wait_until(
                lambda: "slow_ops" in mons[0].pg_digest, 5.0, "real digest"
            )

            evil = Messenger("mgr.evil")
            evil.add_dispatcher_tail(Collector())
            spoof = {
                "pools": {},
                "osds": {},
                "total_used_raw": 0,
                "slow_ops": {"osd.9": {"count": 99, "oldest_sec": 999.0}},
            }
            mon_addr = next(iter(monmap.addrs.values()))
            await evil.send_to(
                mon_addr, MMonMgrReport(digest=json.dumps(spoof).encode())
            )
            await asyncio.sleep(0.5)  # several beacon intervals
            assert "osd.9" not in (mons[0].pg_digest.get("slow_ops") or {})
            checks, _ = mons[0].health_checks()
            assert "SLOW_OPS" not in checks

            await evil.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())
