"""Access-layer tests over a live cluster: striper, rbd, rgw, fs
(src/libradosstriper, src/librbd, src/rgw, src/mds+client mirrors)."""

import asyncio
import urllib.request
import urllib.error

import pytest

from ceph_tpu.client import Rados
from ceph_tpu.fs import FileSystem, FsError
from ceph_tpu.rbd import RBD, RbdError
from ceph_tpu.rgw import ObjectGateway, RgwError, S3Server
from ceph_tpu.rgw.http import sign_v2
from ceph_tpu.striper import StripedObject, StripePolicy

from test_cluster import start_cluster, stop_cluster


async def make_client(pool="p", size=2, pg_num=4, n_osds=3):
    monmap, mons, osds = await start_cluster(1, n_osds)
    client = Rados(monmap)
    await client.connect()
    await client.pool_create(pool, "replicated", size=size, pg_num=pg_num)
    ioctx = await client.open_ioctx(pool)
    return monmap, mons, osds, client, ioctx


class TestStripePolicy:
    def test_extent_math_roundtrip(self):
        """map_extent must partition any range exactly once
        (Striper::file_to_extents invariants)."""
        p = StripePolicy(stripe_unit=4096, stripe_count=3, object_size=16384)
        covered = set()
        for objno, obj_off, ln in p.map_extent(0, 200_000):
            for b in range(ln):
                key = (objno, obj_off + b)
                assert key not in covered
                covered.add(key)
        assert len(covered) == 200_000
        # logical order: walking extents in order covers bytes in order
        total = sum(ln for _o, _off, ln in p.map_extent(1000, 99_000))
        assert total == 99_000

    def test_round_robin_layout(self):
        p = StripePolicy(stripe_unit=10, stripe_count=2, object_size=20)
        # units: u0->obj0, u1->obj1, u2->obj0, u3->obj1, u4->obj2 (set 2)...
        assert p.map_extent(0, 10) == [(0, 0, 10)]
        assert p.map_extent(10, 10) == [(1, 0, 10)]
        assert p.map_extent(20, 10) == [(0, 10, 10)]
        assert p.map_extent(30, 10) == [(1, 10, 10)]
        assert p.map_extent(40, 10) == [(2, 0, 10)]


class TestStriper:
    def test_write_read_truncate(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_client()
            policy = StripePolicy(stripe_unit=4096, stripe_count=2, object_size=8192)
            so = StripedObject(ioctx, "striped", policy=policy)
            payload = bytes(i % 251 for i in range(50_000))
            await so.write(payload)
            assert await so.size() == len(payload)
            assert await so.read() == payload
            # partial read across object boundaries
            assert await so.read(9000, 3000) == payload[3000:12000]
            # overwrite in the middle
            await so.write(b"X" * 1000, 5000)
            expect = payload[:5000] + b"X" * 1000 + payload[6000:]
            assert await so.read() == expect
            # shrink
            await so.truncate(10_000)
            assert await so.size() == 10_000
            assert await so.read() == expect[:10_000]
            await so.remove()
            assert not await so.exists()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestRbd:
    def test_image_lifecycle(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rbdp")
            rbd = RBD(ioctx)
            await rbd.create("vol1", 1 << 22, order=20)  # 4 MiB, 1 MiB objects
            assert await rbd.list() == ["vol1"]
            img = await rbd.open("vol1")
            assert img.size == 1 << 22

            block = bytes(range(256)) * 16  # 4 KiB
            await img.write(0, block)
            await img.write((1 << 20) - 2048, block)  # straddles objects
            assert await img.read(0, 4096) == block
            assert await img.read((1 << 20) - 2048, 4096) == block
            # unwritten space reads as zeros
            assert await img.read(1 << 21, 4096) == b"\x00" * 4096

            with pytest.raises(RbdError):
                await img.write(img.size, b"x")  # past the end

            await rbd.remove("vol1")
            assert await rbd.list() == []
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_clone_layering_copyup_flatten(self):
        """librbd layering: clone from a protected snap, COW read-through,
        copy-up on first write, children accounting, flatten severs the
        parent (librbd::clone / ObjectRequest copy-up)."""

        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rbdc")
            rbd = RBD(ioctx)
            await rbd.create("base", 1 << 18, order=16)  # 4 x 64 KiB objects
            base = await rbd.open("base")
            golden = bytes([7]) * 65536 + bytes([9]) * 65536
            await base.write(0, golden)
            await base.snap_create("gold")
            # clone requires protection
            with pytest.raises(RbdError):
                await rbd.clone("base", "gold", "child")
            await base.snap_protect("gold")
            assert await base.snap_is_protected("gold")
            await rbd.clone("base", "gold", "child")
            assert await rbd.children("base", "gold") == ["child"]
            # protected snap can be neither removed nor unprotected
            with pytest.raises(RbdError):
                await base.snap_remove("gold")
            with pytest.raises(RbdError):
                await base.snap_unprotect("gold")
            # the parent keeps changing; the child still sees the snap
            await base.write(0, bytes([1]) * 65536)
            child = await rbd.open("child")
            assert await child.read(0, len(golden)) == golden
            # copy-up: child write diverges, parent snap untouched
            await child.write(100, b"CHILD")
            got = await child.read(0, len(golden))
            assert got[100:105] == b"CHILD"
            assert got[:100] == golden[:100] and got[105:] == golden[105:]
            assert await base.read(0, 65536, snap_name="gold") == bytes([7]) * 65536
            # second object still parent-backed (no copy-up happened there)
            assert (await child.read(65536, 65536)) == bytes([9]) * 65536
            # flatten: child stands alone, snap becomes unprotectable
            await child.flatten()
            assert await rbd.children("base", "gold") == []
            await base.snap_unprotect("gold")
            await base.snap_remove("gold")
            assert (await child.read(65536, 65536)) == bytes([9]) * 65536
            assert (await child.read(0, 105))[100:105] == b"CHILD"
            # clone removal unregisters cleanly
            await rbd.remove("child")
            assert await rbd.list() == ["base"]
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_export_import_roundtrip(self):
        """rbd export/import: the full image (and a snapshot's view)
        round-trips byte-exactly through a flat blob."""

        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rbde")
            rbd = RBD(ioctx)
            size = (1 << 17) + 4096  # not object-aligned on purpose
            await rbd.create("src", size, order=16)
            img = await rbd.open("src")
            v1 = bytes([5]) * size
            await img.import_bytes(v1)
            await img.snap_create("s1")
            await img.write(0, bytes([6]) * 4096)
            blob = await img.export()
            assert len(blob) == size
            assert blob[:4096] == bytes([6]) * 4096 and blob[4096:] == v1[4096:]
            # the snapshot's view exports the pre-write bytes
            assert await img.export(snap_name="s1") == v1
            # import as a new image
            await rbd.create("dst", len(blob), order=16)
            dst = await rbd.open("dst")
            await dst.import_bytes(blob)
            assert await dst.export() == blob
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_snapshots_cow(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rbds")
            rbd = RBD(ioctx)
            await rbd.create("snapvol", 1 << 20, order=16)  # 64 KiB objects
            img = await rbd.open("snapvol")

            v1 = b"1" * 65536
            await img.write(0, v1)
            await img.snap_create("s1")
            v2 = b"2" * 65536
            await img.write(0, v2)  # COW preserves v1 under s1
            await img.snap_create("s2")
            v3 = b"3" * 65536
            await img.write(0, v3)

            assert await img.read(0, 65536) == v3
            assert await img.read(0, 65536, snap_name="s1") == v1
            assert await img.read(0, 65536, snap_name="s2") == v2
            assert await img.snap_list() == ["s1", "s2"]

            # removing the middle snapshot must not corrupt s1
            await img.snap_remove("s2")
            assert await img.read(0, 65536, snap_name="s1") == v1

            # rollback to s1
            await img.snap_rollback("s1")
            assert await img.read(0, 65536) == v1
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_rollback_preserves_newer_snapshots(self):
        """snap_rollback's writes COW like any write: a snapshot taken
        after the target must keep its content."""

        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rbro")
            rbd = RBD(ioctx)
            await rbd.create("rb", 1 << 17, order=16)
            img = await rbd.open("rb")
            a, b = b"A" * 65536, b"B" * 65536
            await img.write(0, a)
            await img.snap_create("s1")
            await img.write(0, b)
            await img.snap_create("s2")
            await img.snap_rollback("s1")  # head back to A
            assert await img.read(0, 65536) == a
            assert await img.read(0, 65536, snap_name="s2") == b  # not lost
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_shrink_preserves_snapshots(self):
        """resize-shrink COW-preserves dropped objects so snapshot reads
        of the shrunk region survive (librbd keeps clones across shrink)."""

        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rbsh")
            rbd = RBD(ioctx)
            await rbd.create("sv", 1 << 18, order=16)  # 4 objects
            img = await rbd.open("sv")
            data = bytes(range(256)) * 1024  # 256 KiB
            await img.write(0, data)
            await img.snap_create("before")
            await img.resize(1 << 16)  # drop 3 of 4 objects
            await img.resize(1 << 18)
            assert await img.read(1 << 16, 1 << 16, snap_name="before") == (
                data[1 << 16 : 1 << 17]
            )
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_resize(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rbdr")
            rbd = RBD(ioctx)
            await rbd.create("rvol", 1 << 20, order=16)
            img = await rbd.open("rvol")
            await img.write(0, b"A" * (1 << 20))
            await img.resize(1 << 19)
            assert img.size == 1 << 19
            await img.resize(1 << 20)
            assert await img.read(0, 1 << 19) == b"A" * (1 << 19)
            # the shrunk-then-grown region is zeros, not stale data
            assert await img.read(1 << 19, 4096) == b"\x00" * 4096
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestRgw:
    def test_bucket_and_object_ops(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rgwp")
            gw = ObjectGateway(ioctx)
            user = await gw.create_user("alice")
            assert user["access_key"] and user["secret_key"]

            await gw.create_bucket("photos", owner="alice")
            with pytest.raises(RgwError):
                await gw.create_bucket("photos")
            assert await gw.list_buckets() == ["photos"]

            body = b"jpegdata" * 1000
            etag, _ = await gw.put_object(
                "photos", "2026/cat.jpg", body, actor="alice"
            )
            import hashlib

            assert etag == hashlib.md5(body).hexdigest()
            # owned bucket, anonymous caller: every op is AccessDenied
            with pytest.raises(RgwError):
                await gw.get_object("photos", "2026/cat.jpg")
            assert (
                await gw.get_object("photos", "2026/cat.jpg", actor="alice")
                == body
            )
            meta = await gw.head_object("photos", "2026/cat.jpg", actor="alice")
            assert meta["size"] == len(body)

            await gw.put_object("photos", "2026/dog.jpg", b"d", actor="alice")
            await gw.put_object("photos", "2025/old.jpg", b"o", actor="alice")
            listing = await gw.list_objects(
                "photos", prefix="2026/", actor="alice"
            )
            assert [c["key"] for c in listing["contents"]] == [
                "2026/cat.jpg",
                "2026/dog.jpg",
            ]
            # delimiter rollup
            listing = await gw.list_objects("photos", delimiter="/", actor="alice")
            assert listing["common_prefixes"] == ["2025/", "2026/"]
            assert listing["contents"] == []

            with pytest.raises(RgwError):
                await gw.delete_bucket("photos")  # not empty
            for k in ("2026/cat.jpg", "2026/dog.jpg", "2025/old.jpg"):
                await gw.delete_object("photos", k, actor="alice")
            await gw.delete_bucket("photos")
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_multipart(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rgwm")
            gw = ObjectGateway(ioctx)
            await gw.create_bucket("big")
            upload = await gw.initiate_multipart("big", "huge.bin")
            p1, p2 = b"a" * 700_000, b"b" * 300_000
            await gw.upload_part(upload, 1, p1)
            await gw.upload_part(upload, 2, p2)
            etag = await gw.complete_multipart(upload)
            assert etag.endswith("-2")
            assert await gw.get_object("big", "huge.bin") == p1 + p2
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_s3_http_endpoint(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rgwh")
            gw = ObjectGateway(ioctx)
            server = S3Server(gw)
            addr = await server.serve()
            base = f"http://{addr}"

            def req(method, path, data=None):
                r = urllib.request.Request(base + path, data=data, method=method)
                return urllib.request.urlopen(r, timeout=5)

            loop = asyncio.get_event_loop()
            # create bucket, put, get, list, delete — full S3 round trip
            assert (await loop.run_in_executor(None, req, "PUT", "/b1")).status == 200
            put = await loop.run_in_executor(
                None, lambda: req("PUT", "/b1/hello.txt", b"hello world")
            )
            assert put.status == 200 and put.headers["ETag"]
            got = await loop.run_in_executor(None, req, "GET", "/b1/hello.txt")
            assert got.read() == b"hello world"
            listing = await loop.run_in_executor(None, req, "GET", "/b1")
            assert b"<Key>hello.txt</Key>" in listing.read()
            missing_is_404 = False
            try:
                await loop.run_in_executor(None, req, "GET", "/b1/ghost")
            except urllib.error.HTTPError as e:
                missing_is_404 = e.code == 404
            assert missing_is_404
            await server.shutdown()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_object_level_acls(self):
        """Per-object ACLs (verify_object_permission): an object policy
        overrides the bucket's — a public-read object in a private
        bucket serves to others, and the bucket owner retains control."""

        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rgwoa")
            gw = ObjectGateway(ioctx)
            await gw.create_user("alice")
            await gw.create_user("bob")
            await gw.create_bucket("priv", owner="alice")
            await gw.put_object("priv", "open.txt", b"shared", actor="alice")
            await gw.put_object("priv", "closed.txt", b"secret", actor="alice")
            with pytest.raises(RgwError):
                await gw.get_object("priv", "open.txt", actor="bob")
            await gw.set_object_acl(
                "priv", "open.txt", {"*": "READ"}, actor="alice"
            )
            assert await gw.get_object("priv", "open.txt", actor="bob") == b"shared"
            # the sibling object stays private
            with pytest.raises(RgwError):
                await gw.get_object("priv", "closed.txt", actor="bob")
            # a grantee cannot administer the ACL
            with pytest.raises(RgwError):
                await gw.set_object_acl(
                    "priv", "open.txt", {"*": ["READ", "WRITE"]}, actor="bob"
                )
            acl = await gw.get_object_acl("priv", "open.txt", actor="alice")
            assert acl["owner"] == "alice" and acl["grants"] == {"*": "READ"}
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_s3_multipart_and_meta_over_http(self):
        """REST multipart (initiate/part/list/complete/abort) + stored
        Content-Type and x-amz-meta-* round-tripping (RGWInitMultipart /
        RGWCompleteMultipart / rgw_rest_s3 meta attrs)."""

        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rgwmp")
            gw = ObjectGateway(ioctx)
            server = S3Server(gw)
            addr = await server.serve()
            base = f"http://{addr}"

            def req(method, path, data=None, headers=None):
                r = urllib.request.Request(
                    base + path, data=data, method=method, headers=headers or {}
                )
                return urllib.request.urlopen(r, timeout=5)

            loop = asyncio.get_event_loop()

            async def go(method, path, data=None, headers=None):
                return await loop.run_in_executor(
                    None, lambda: req(method, path, data, headers)
                )

            await go("PUT", "/mb")
            # content-type + user meta stored and served back
            await go(
                "PUT", "/mb/doc.json", b"{}",
                headers={"Content-Type": "application/json",
                         "x-amz-meta-owner": "alice"},
            )
            got = await go("GET", "/mb/doc.json")
            assert got.headers["Content-Type"] == "application/json"
            assert got.headers["x-amz-meta-owner"] == "alice"
            # multipart: initiate -> parts -> list -> complete
            init = (await go("POST", "/mb/big.bin?uploads")).read()
            import re

            upload_id = re.search(
                rb"<UploadId>(.*?)</UploadId>", init
            ).group(1).decode()
            p1, p2 = b"a" * 600_000, b"b" * 400_000
            r1 = await go(
                "PUT", f"/mb/big.bin?uploadId={upload_id}&partNumber=1", p1
            )
            assert r1.headers["ETag"]
            await go(
                "PUT", f"/mb/big.bin?uploadId={upload_id}&partNumber=2", p2
            )
            parts = (await go(
                "GET", f"/mb/big.bin?uploadId={upload_id}"
            )).read()
            assert parts.count(b"<Part>") == 2
            ups = (await go("GET", "/mb?uploads")).read()
            assert upload_id.encode() in ups
            done = (await go(
                "POST", f"/mb/big.bin?uploadId={upload_id}"
            )).read()
            assert b"-2&quot;" in done or b"-2\"" in done or b"-2<" in done
            got = await go("GET", "/mb/big.bin")
            assert got.read() == p1 + p2
            # completed upload disappears from the pending list
            assert upload_id.encode() not in (await go("GET", "/mb?uploads")).read()
            # abort drops a fresh upload's parts
            init2 = (await go("POST", "/mb/tmp?uploads")).read()
            up2 = re.search(rb"<UploadId>(.*?)</UploadId>", init2).group(1).decode()
            await go("PUT", f"/mb/tmp?uploadId={up2}&partNumber=1", b"x" * 100)
            assert (await go("DELETE", f"/mb/tmp?uploadId={up2}")).status == 204
            assert up2.encode() not in (await go("GET", "/mb?uploads")).read()
            await server.shutdown()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_v2_signature(self):
        sig = sign_v2("secret", "GET", "/b/k", "Tue, 27 Mar 2007 19:36:42 +0000")
        assert sign_v2("secret", "GET", "/b/k", "Tue, 27 Mar 2007 19:36:42 +0000") == sig
        assert sign_v2("other", "GET", "/b/k", "Tue, 27 Mar 2007 19:36:42 +0000") != sig

    def test_s3_auth_acl_and_versioning(self):
        """VERDICT r4 item 8: signed requests resolve to an identity,
        bucket ACLs deny the other tenant, and versioned buckets serve
        versionId GETs + delete markers over HTTP."""

        async def run():
            from email.utils import formatdate

            monmap, mons, osds, client, ioctx = await make_client("rgwa")
            gw = ObjectGateway(ioctx)
            alice = await gw.create_user("alice")
            bob = await gw.create_user("bob")
            server = S3Server(gw, require_auth=True)
            addr = await server.serve()
            base = f"http://{addr}"

            def req(method, path, data=None, user=None, headers=None):
                hdrs = dict(headers or {})
                if data is not None:
                    # urllib injects a Content-Type on bodied requests;
                    # pin it so the signature covers the real header
                    hdrs.setdefault("Content-Type", "application/octet-stream")
                if user is not None:
                    date = formatdate(usegmt=True)
                    sig = sign_v2(
                        user["secret_key"], method, path.partition("?")[0], date,
                        content_type=hdrs.get("Content-Type", ""),
                    )
                    hdrs["Date"] = date
                    hdrs["Authorization"] = f"AWS {user['access_key']}:{sig}"
                r = urllib.request.Request(
                    base + path, data=data, method=method, headers=hdrs
                )
                return urllib.request.urlopen(r, timeout=5)

            loop = asyncio.get_event_loop()

            async def go(method, path, data=None, user=None, headers=None):
                return await loop.run_in_executor(
                    None, lambda: req(method, path, data, user, headers)
                )

            def code(exc):
                return exc.code if isinstance(exc, urllib.error.HTTPError) else 0

            # unauthenticated: rejected at the door
            try:
                await go("PUT", "/priv")
                raise AssertionError("anonymous PUT accepted")
            except urllib.error.HTTPError as e:
                assert e.code == 403
            # alice creates a private bucket and writes
            assert (await go("PUT", "/priv", user=alice)).status == 200
            assert (
                await go("PUT", "/priv/secret.txt", b"alice data", user=alice)
            ).status == 200
            # bob is denied read AND write (AccessDenied, not NoSuchKey)
            for method, path, data in [
                ("GET", "/priv/secret.txt", None),
                ("PUT", "/priv/mine.txt", b"bob data"),
                ("GET", "/priv", None),
            ]:
                try:
                    await go(method, path, data, user=bob)
                    raise AssertionError(f"bob {method} {path} accepted")
                except urllib.error.HTTPError as e:
                    assert e.code == 403, (method, path)
            # alice grants public-read via the ?acl subresource: bob reads
            assert (
                await go("PUT", "/priv?acl", user=alice,
                         headers={"x-amz-acl": "public-read"})
            ).status == 200
            got = await go("GET", "/priv/secret.txt", user=bob)
            assert got.read() == b"alice data"
            acl_xml = (await go("GET", "/priv?acl", user=alice)).read()
            assert b"<ID>alice</ID>" in acl_xml and b"READ" in acl_xml
            # ...but still not write
            try:
                await go("PUT", "/priv/mine.txt", b"bob data", user=bob)
                raise AssertionError("grantee READ allowed a write")
            except urllib.error.HTTPError as e:
                assert e.code == 403

            # -- versioning over HTTP --
            vc = b"<VersioningConfiguration><Status>Enabled</Status></VersioningConfiguration>"
            assert (await go("PUT", "/priv?versioning", vc, user=alice)).status == 200
            st = (await go("GET", "/priv?versioning", user=alice)).read()
            assert b"<Status>Enabled</Status>" in st
            v1 = await go("PUT", "/priv/doc", b"version one", user=alice)
            vid1 = v1.headers["x-amz-version-id"]
            v2 = await go("PUT", "/priv/doc", b"version two", user=alice)
            vid2 = v2.headers["x-amz-version-id"]
            assert vid1 and vid2 and vid1 != vid2
            # latest wins on a plain GET; versionId addresses history
            assert (await go("GET", "/priv/doc", user=alice)).read() == b"version two"
            old = await go("GET", f"/priv/doc?versionId={vid1}", user=alice)
            assert old.read() == b"version one"
            # plain DELETE lays a marker: GET -> 404, old version still GETtable
            dele = await go("DELETE", "/priv/doc", user=alice)
            assert dele.headers["x-amz-delete-marker"] == "true"
            try:
                await go("GET", "/priv/doc", user=alice)
                raise AssertionError("GET served a delete marker")
            except urllib.error.HTTPError as e:
                assert e.code == 404
            again = await go("GET", f"/priv/doc?versionId={vid1}", user=alice)
            assert again.read() == b"version one"
            # ?versions lists doc's two versions + its marker (secret.txt
            # appears once as the "null" version of an unversioned put)
            lv = (await go("GET", "/priv?versions", user=alice)).read()
            assert lv.count(b"<Key>doc</Key>") == 3
            assert b"<DeleteMarker>" in lv
            assert lv.count(b"<Version>") == 3  # doc x2 + secret.txt
            await server.shutdown()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestRgwLifecycle:
    def test_expiration_rules_and_versioned_expiry(self):
        """PUT ?lifecycle rules, run an LC pass: matching keys past Days
        expire; on a versioned bucket expiry lays a delete marker with
        history intact (RGWLC::process)."""

        async def run():
            import time as _time

            monmap, mons, osds, client, ioctx = await make_client("rgwl")
            gw = ObjectGateway(ioctx)
            await gw.create_bucket("b", owner="alice")
            await gw.put_object("b", "logs/a", b"1", actor="alice")
            await gw.put_object("b", "logs/b", b"2", actor="alice")
            await gw.put_object("b", "keep/c", b"3", actor="alice")
            await gw.set_lifecycle(
                "b", [{"id": "r1", "prefix": "logs/", "days": 0}], actor="alice"
            )
            assert (await gw.get_lifecycle("b", actor="alice"))[0]["prefix"] == "logs/"
            n = await gw.process_lifecycle(now=_time.time() + 1)
            assert n == 2
            listing = await gw.list_objects("b", actor="alice")
            assert [c["key"] for c in listing["contents"]] == ["keep/c"]
            # versioned bucket: expiry is a delete marker, history stays
            await gw.set_versioning("b", "Enabled", actor="alice")
            _etag, vid = await gw.put_object("b", "logs/v", b"vv", actor="alice")
            n = await gw.process_lifecycle(now=_time.time() + 1)
            assert n == 1
            with pytest.raises(RgwError):
                await gw.get_object("b", "logs/v", actor="alice")
            assert (
                await gw.get_object("b", "logs/v", actor="alice", version_id=vid)
                == b"vv"
            )
            # a fresh object under an old-age rule survives the pass
            await gw.set_lifecycle(
                "b", [{"id": "r2", "prefix": "", "days": 30}], actor="alice"
            )
            assert await gw.process_lifecycle() == 0
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_lifecycle_http_subresource(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_client("rgwlh")
            gw = ObjectGateway(ioctx)
            server = S3Server(gw)
            addr = await server.serve()
            base = f"http://{addr}"

            def req(method, path, data=None):
                r = urllib.request.Request(base + path, data=data, method=method)
                return urllib.request.urlopen(r, timeout=5)

            loop = asyncio.get_event_loop()
            await loop.run_in_executor(None, req, "PUT", "/lb")
            lc = (
                b"<LifecycleConfiguration><Rule><ID>exp</ID>"
                b"<Prefix>tmp/</Prefix><Status>Enabled</Status>"
                b"<Expiration><Days>7</Days></Expiration></Rule>"
                b"</LifecycleConfiguration>"
            )
            put = await loop.run_in_executor(
                None, lambda: req("PUT", "/lb?lifecycle", lc)
            )
            assert put.status == 200
            got = await loop.run_in_executor(None, req, "GET", "/lb?lifecycle")
            xml = got.read()
            assert b"<Prefix>tmp/</Prefix>" in xml and b"<Days>7</Days>" in xml
            # DELETE drops the config; GET then answers 404
            await loop.run_in_executor(None, req, "DELETE", "/lb?lifecycle")
            try:
                await loop.run_in_executor(None, req, "GET", "/lb?lifecycle")
                raise AssertionError("lifecycle survived DELETE")
            except urllib.error.HTTPError as e:
                assert e.code == 404
            await server.shutdown()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestFileSystem:
    def test_namespace_and_io(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("fsmeta", "replicated", size=2, pg_num=2)
            await client.pool_create("fsdata", "replicated", size=2, pg_num=4)
            meta = await client.open_ioctx("fsmeta")
            data = await client.open_ioctx("fsdata")
            fs = FileSystem(meta, data)
            await fs.mkfs()

            await fs.mkdir("/home")
            await fs.mkdir("/home/user")
            assert await fs.listdir("/") == ["home"]
            assert await fs.listdir("/home") == ["user"]
            with pytest.raises(FsError):
                await fs.mkdir("/home")  # EEXIST
            with pytest.raises(FsError):
                await fs.listdir("/ghost")

            content = b"data " * 50_000  # multi-object file
            await fs.write_file("/home/user/notes.txt", content)
            assert await fs.read_file("/home/user/notes.txt") == content
            assert await fs.read_file("/home/user/notes.txt", 10, 5) == content[5:15]
            st = await fs.stat("/home/user/notes.txt")
            assert st["type"] == "file" and st["size"] == len(content)

            await fs.rename("/home/user/notes.txt", "/home/notes-v2.txt")
            assert await fs.listdir("/home/user") == []
            assert await fs.read_file("/home/notes-v2.txt") == content

            # rename over an existing file replaces it (POSIX), over a
            # directory fails
            await fs.write_file("/home/other.txt", b"other")
            await fs.rename("/home/other.txt", "/home/notes-v2.txt")
            assert await fs.read_file("/home/notes-v2.txt") == b"other"
            await fs.write_file("/home/f.txt", b"f")
            with pytest.raises(FsError):
                await fs.rename("/home/f.txt", "/home/user")  # dir target
            await fs.unlink("/home/f.txt")
            await fs.truncate_file("/home/notes-v2.txt", 100)
            await fs.write_file("/home/notes-v2.txt", content)

            await fs.truncate_file("/home/notes-v2.txt", 100)
            assert await fs.read_file("/home/notes-v2.txt") == content[:100]

            await fs.unlink("/home/notes-v2.txt")
            with pytest.raises(FsError):
                await fs.read_file("/home/notes-v2.txt")
            await fs.rmdir("/home/user")
            assert await fs.listdir("/home") == []
            with pytest.raises(FsError):
                await fs.rmdir("/home/ghost")
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


async def make_ec_client(pool="ecap", k=2, m=1, pg_num=4, n_osds=4):
    """EC pool with overwrites enabled — the pool type the reference runs
    RBD and RGW data on (FLAG_EC_OVERWRITES required for block/file)."""
    monmap, mons, osds = await start_cluster(1, n_osds)
    client = Rados(monmap)
    await client.connect()
    rv, rs, _ = await client.mon_command(
        {
            "prefix": "osd erasure-code-profile set",
            "name": f"ap{k}{m}",
            "profile": [f"k={k}", f"m={m}", "plugin=tpu"],
        }
    )
    assert rv == 0, rs
    await client.pool_create(
        pool, "erasure", profile=f"ap{k}{m}", pg_num=pg_num,
        allow_ec_overwrites=True,
    )
    ioctx = await client.open_ioctx(pool)
    return monmap, mons, osds, client, ioctx


class TestAccessLayersOnEC:
    """Block and object layers over EC pools with overwrites — the
    reference's flagship EC consumers (rbd/cephfs/rgw on EC requires
    FLAG_EC_OVERWRITES; the RMW pipeline serves every partial write)."""

    def test_rbd_image_on_ec_pool(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_ec_client()
            rbd = RBD(ioctx)
            await rbd.create("ecdisk", 8 << 20, order=20)  # 1 MiB objects
            img = await rbd.open("ecdisk")
            # unaligned partial writes exercise the EC RMW path
            await img.write(1 << 20, b"A" * 5000)
            await img.write((1 << 20) + 2500, b"B" * 2500)
            got = await img.read(1 << 20, 5000)
            assert got == b"A" * 2500 + b"B" * 2500
            await img.resize(2 << 20)
            assert (await img.read(0, 100)) == b"\x00" * 100
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_s3_objects_on_ec_pool(self):
        async def run():
            monmap, mons, osds, client, ioctx = await make_ec_client()
            gw = ObjectGateway(ioctx)
            await gw.create_bucket("ecbucket")
            body = bytes(range(256)) * 512  # 128 KiB
            etag, _ = await gw.put_object("ecbucket", "obj", body)
            import hashlib

            assert etag == hashlib.md5(body).hexdigest()
            assert await gw.get_object("ecbucket", "obj") == body
            # degraded read: kill one OSD, object still reconstructs
            from test_cluster import wait_until

            await osds[3].stop()
            await wait_until(
                lambda: not mons[0].osdmon.osdmap.is_up(3), 8.0, "mark down"
            )
            assert await gw.get_object("ecbucket", "obj") == body
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())
