"""RADOS omap: client KV ops, replication, recovery, EC rejection.

Models the reference's omap surface (CEPH_OSD_OP_OMAP*; librados
rados_omap_* / ObjectWriteOperation omap ops) over a live cluster:
set/get/rm/clear round trips, op-order within a compound transaction,
omap riding recovery pushes to a revived OSD, and the EC-pool rejection
(-EOPNOTSUPP) the reference enforces.
"""

import asyncio

import pytest

from ceph_tpu.client import Rados, RadosError
from ceph_tpu.osd.osd import OSD

from test_cluster import fast_conf, start_cluster, stop_cluster, wait_until


def test_omap_roundtrip_and_clear():
    async def run():
        monmap, mons, osds = await start_cluster(1, 3)
        client = Rados(monmap)
        await client.connect()
        await client.pool_create("om", "replicated", size=2, pg_num=4)
        io = await client.open_ioctx("om")
        await io.write_full("obj", b"payload")
        kv = {"alpha": b"1", "beta": b"\x00\xffraw", "gamma": b""}
        await io.omap_set("obj", kv)
        assert await io.omap_get_vals("obj") == kv
        assert await io.omap_get_keys("obj") == ["alpha", "beta", "gamma"]
        await io.omap_rm_keys("obj", ["beta", "ghost"])
        assert await io.omap_get_keys("obj") == ["alpha", "gamma"]
        await io.omap_set("obj", {"alpha": b"2"})
        assert (await io.omap_get_vals("obj"))["alpha"] == b"2"
        await io.omap_clear("obj")
        assert await io.omap_get_vals("obj") == {}
        # omap on a bare (never-written) object creates it
        await io.omap_set("idx", {"k": b"v"})
        assert await io.omap_get_vals("idx") == {"k": b"v"}
        # data bytes are untouched by omap traffic
        assert await io.read("obj") == b"payload"
        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())


def test_cmpxattr_guarded_compound_ops():
    """CMPXATTR guards (rados_cmpxattr / ObjectOperation::cmpxattr):
    a failed compare aborts the whole compound with -ECANCELED and
    nothing staged lands — the atomic check-and-mutate primitive."""

    async def run():
        from ceph_tpu.msg.messages import OSDOp

        monmap, mons, osds = await start_cluster(1, 3)
        client = Rados(monmap)
        await client.connect()
        await client.pool_create("gx", "replicated", size=2, pg_num=2)
        io = await client.open_ioctx("gx")
        await io.write_full("obj", b"v1-bytes")
        await io.setxattr("obj", "ver", b"1")
        # matching guard: the compound write lands
        await io.operate(
            "obj",
            [
                io.cmpxattr_op("ver", b"1"),
                OSDOp(op=OSDOp.WRITEFULL, data=b"v2-bytes"),
                OSDOp(op=OSDOp.SETXATTR, name="ver", data=b"2"),
            ],
        )
        assert await io.read("obj") == b"v2-bytes"
        # stale guard: ECANCELED, and NEITHER the write nor the xattr land
        with pytest.raises(RadosError) as ei:
            await io.operate(
                "obj",
                [
                    io.cmpxattr_op("ver", b"1"),
                    OSDOp(op=OSDOp.WRITEFULL, data=b"v3-bytes"),
                    OSDOp(op=OSDOp.SETXATTR, name="ver", data=b"3"),
                ],
            )
        assert ei.value.errno == -125  # ECANCELED
        assert await io.read("obj") == b"v2-bytes"
        assert await io.getxattr("obj", "ver") == b"2"
        # read-class standalone compare + guard sees EARLIER staged attrs
        await io.cmpxattr("obj", "ver", b"2")
        with pytest.raises(RadosError):
            await io.cmpxattr("obj", "ver", b"9")
        await io.operate(
            "obj",
            [
                OSDOp(op=OSDOp.SETXATTR, name="ver", data=b"5"),
                io.cmpxattr_op("ver", b"5"),  # sees the staged value
                OSDOp(op=OSDOp.WRITEFULL, data=b"v5"),
            ],
        )
        assert await io.read("obj") == b"v5"
        # missing xattr compares as empty
        await io.cmpxattr("obj", "ghost", b"", op="eq")
        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())


def test_omap_rejected_on_ec_pool():
    async def run():
        monmap, mons, osds = await start_cluster(1, 4)
        client = Rados(monmap)
        await client.connect()
        rv, rs, _ = await client.mon_command(
            {
                "prefix": "osd erasure-code-profile set",
                "name": "omk2m1",
                "profile": ["k=2", "m=1", "plugin=tpu"],
            }
        )
        assert rv == 0, rs
        await client.pool_create("ecp", "erasure", profile="omk2m1", pg_num=2)
        io = await client.open_ioctx("ecp")
        with pytest.raises(RadosError):
            await io.omap_set("o", {"k": b"v"})
        with pytest.raises(RadosError):
            await io.omap_get_vals("o")
        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())


def test_omap_survives_osd_restart_via_recovery():
    """Write omap while an OSD is down; its recovery push must carry the
    omap (PushOp.omap) so the revived replica serves identical KV."""

    async def run():
        monmap, mons, osds = await start_cluster(1, 3)
        client = Rados(monmap)
        await client.connect()
        await client.pool_create("rec", "replicated", size=3, pg_num=1)
        io = await client.open_ioctx("rec")
        await io.write_full("obj", b"bytes")
        await io.omap_set("obj", {"site": b"a"})
        victim = osds[2]
        victim_store = victim.store
        await victim.stop()
        await wait_until(
            lambda: not mons[0].osdmon.osdmap.is_up(2), 10.0,
            "victim marked down",
        )
        await io.omap_set("obj", {"site": b"b", "extra": b"x"})
        revived = OSD(2, monmap, conf=fast_conf(2), store=victim_store)
        await revived.start()
        await revived.wait_for_up()
        osds[2] = revived

        def recovered():
            store = victim_store
            for coll in store.list_collections():
                try:
                    if store.omap_get(coll, "obj") == {
                        "site": b"b", "extra": b"x"
                    }:
                        return True
                except Exception:
                    pass
            return False

        await wait_until(recovered, 10.0, "omap recovered on revived osd")
        assert await io.omap_get_vals("obj") == {"site": b"b", "extra": b"x"}
        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())


def test_zero_and_writesame_ops():
    """CEPH_OSD_OP_ZERO / WRITESAME: extent zeroing (no size extension)
    and tiled writes (replicated pool; EC pools route these through the
    same staged-write path under FLAG_EC_OVERWRITES)."""

    async def run():
        monmap, mons, osds = await start_cluster(1, 4)
        client = Rados(monmap)
        await client.connect()
        await client.pool_create("zw", "replicated", size=2, pg_num=2)
        io = await client.open_ioctx("zw")
        await io.write_full("o", b"A" * 1000)
        await io.zero("o", 100, 200)
        got = await io.read("o")
        assert got[:100] == b"A" * 100
        assert got[100:300] == b"\x00" * 200
        assert got[300:] == b"A" * 700 and len(got) == 1000
        # zero past the end neither extends nor errors
        await io.zero("o", 900, 500)
        assert await io.stat("o") == 1000
        assert (await io.read("o"))[900:] == b"\x00" * 100
        # writesame tiles and extends
        await io.writesame("o", b"xy", 1000, 10)
        assert (await io.read("o"))[1000:] == b"xy" * 5
        with pytest.raises(RadosError):
            await io.writesame("o", b"xyz", 0, 10)  # len % data != 0
        with pytest.raises(RadosError):
            await io.writesame("o", b"", 0, 10)
        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())


def test_client_blocklist_fencing():
    """osd blocklist (OSDMap blocklist): a fenced client instance's ops
    bounce with -EBLOCKLISTED while other clients are untouched; rm
    restores access — the fencing primitive failover flows build on."""

    async def run():
        monmap, mons, osds = await start_cluster(1, 3)
        victim = Rados(monmap, name="client.victim")
        other = Rados(monmap, name="client.other")
        for c in (victim, other):
            await c.connect()
        await other.pool_create("bl", "replicated", size=2, pg_num=2)
        vio = await victim.open_ioctx("bl")
        oio = await other.open_ioctx("bl")
        await vio.write_full("o", b"pre-fence")
        entity = victim.objecter.reqid_name
        rv, rs, _ = await other.mon_command(
            {"prefix": "osd blocklist add", "entity": entity}
        )
        assert rv == 0, rs
        await wait_until(
            lambda: all(
                entity in o.osdmap.blocklist for o in osds
            ),
            10.0,
            "blocklist reaching the OSDs",
        )
        with pytest.raises((RadosError, TimeoutError)):
            await vio.write_full("o", b"post-fence", )
        # reads from the fenced instance bounce too
        with pytest.raises((RadosError, TimeoutError)):
            await vio.read("o")
        # other clients unaffected; fenced bytes never landed
        assert await oio.read("o") == b"pre-fence"
        rv, _, out = await other.mon_command({"prefix": "osd blocklist ls"})
        import json

        assert entity in json.loads(out)
        rv, _, _ = await other.mon_command(
            {"prefix": "osd blocklist rm", "entity": entity}
        )
        assert rv == 0
        await wait_until(
            lambda: all(
                entity not in o.osdmap.blocklist for o in osds
            ),
            10.0,
            "un-blocklist reaching the OSDs",
        )
        await vio.write_full("o", b"restored")
        assert await oio.read("o") == b"restored"
        for c in (victim, other):
            await c.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())
