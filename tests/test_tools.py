"""Tool tests — the byte-parity harness pattern.

Models /root/reference/src/test/ceph-erasure-code-tool/
test_ceph-erasure-code-tool.sh: encode a file to chunks, remove some, decode,
`cmp` byte-identity with the original.
"""

import os

import numpy as np
import pytest

from ceph_tpu.tools import ec_benchmark, ec_tool


@pytest.fixture
def workfile(tmp_path):
    path = tmp_path / "obj"
    data = np.random.default_rng(0).integers(0, 256, 4 * 1024 + 37, dtype=np.uint8)
    path.write_bytes(data.tobytes())
    return str(path), data.tobytes()


PROFILE = "plugin=tpu,technique=reed_sol_van,k=4,m=2"


class TestEcTool:
    def test_plugin_exists(self, capsys):
        assert ec_tool.main(["test-plugin-exists", "tpu"]) == 0
        assert ec_tool.main(["test-plugin-exists", "nonexistent"]) == 1

    def test_validate_profile(self, capsys):
        assert ec_tool.main(["validate-profile", PROFILE]) == 0
        assert ec_tool.main(["validate-profile", PROFILE, "chunk_count"]) == 0
        assert capsys.readouterr().out.strip() == "6"
        assert ec_tool.main(["validate-profile", PROFILE, "data_chunk_count"]) == 0
        assert capsys.readouterr().out.strip() == "4"
        assert ec_tool.main(["validate-profile", "plugin=tpu,k=99,m=9"]) == 1

    def test_calc_chunk_size(self, capsys):
        assert ec_tool.main(["calc-chunk-size", PROFILE, "4096"]) == 0
        assert int(capsys.readouterr().out.strip()) == 1024

    def test_encode_decode_roundtrip(self, workfile):
        """The reference harness's full round-trip + cmp byte-identity."""
        path, original = workfile
        assert ec_tool.main(["encode", PROFILE, "1024", "", path]) == 0
        for i in range(6):
            assert os.path.exists(f"{path}.{i}")
        # erase two chunks
        os.unlink(f"{path}.1")
        os.unlink(f"{path}.4")
        assert ec_tool.main(["decode", PROFILE, "1024", "", path]) == 0
        with open(f"{path}.decoded", "rb") as f:
            out = f.read()
        assert out[: len(original)] == original

    def test_decode_specific_chunks(self, workfile):
        path, _ = workfile
        assert ec_tool.main(["encode", PROFILE, "1024", "", path]) == 0
        with open(f"{path}.2", "rb") as f:
            chunk2 = f.read()
        os.unlink(f"{path}.2")
        assert ec_tool.main(["decode", PROFILE, "1024", "2", path]) == 0
        with open(f"{path}.2.decoded", "rb") as f:
            assert f.read() == chunk2

    def test_too_many_erasures_fails(self, workfile):
        path, _ = workfile
        assert ec_tool.main(["encode", PROFILE, "1024", "", path]) == 0
        for i in (0, 1, 2):
            os.unlink(f"{path}.{i}")
        assert ec_tool.main(["decode", PROFILE, "1024", "", path]) == 1


class TestBenchmark:
    def test_encode_output_format(self, capsys):
        rc = ec_benchmark.main(
            ["-p", "tpu", "-P", "k=4", "-P", "m=2", "-S", "4096", "-i", "3"]
        )
        assert rc == 0
        out = capsys.readouterr().out.strip()
        elapsed, kib = out.split("\t")
        assert float(elapsed) > 0
        assert float(kib) == 3 * 4096 / 1024

    def test_decode_exhaustive_verifies(self, capsys):
        rc = ec_benchmark.main(
            [
                "-p", "tpu", "-P", "k=4", "-P", "m=2", "-S", "4096",
                "-i", "8", "-w", "decode", "-e", "2",
                "--erasures-generation", "exhaustive",
            ]
        )
        assert rc == 0

    def test_decode_fixed_erasures(self, capsys):
        rc = ec_benchmark.main(
            [
                "-p", "jerasure", "-P", "k=4", "-P", "m=2", "-S", "4096",
                "-i", "2", "-w", "decode", "--erased", "0", "--erased", "5",
            ]
        )
        assert rc == 0


class TestRepairWorkload:
    def test_clay_repair_reads_fraction(self, capsys):
        from ceph_tpu.tools import ec_benchmark

        rc = ec_benchmark.main(
            ["-w", "repair", "-p", "clay", "-P", "k=4", "-P", "m=2",
             "-P", "d=5", "-S", "16384", "-i", "2"]
        )
        assert rc == 0
        parts = capsys.readouterr().out.strip().split("\t")
        assert len(parts) == 4
        bytes_read, bytes_repaired = int(parts[2]), int(parts[3])
        # CLAY(4,2,d=5): q=2 -> reads d/q = 2.5 chunks' worth, not k=4
        assert bytes_read == int(2.5 * bytes_repaired)

    def test_rs_repair_reads_k_chunks(self, capsys):
        from ceph_tpu.tools import ec_benchmark

        rc = ec_benchmark.main(
            ["-w", "repair", "-p", "tpu", "-P", "k=4", "-P", "m=2",
             "-S", "16384", "-i", "2"]
        )
        assert rc == 0
        parts = capsys.readouterr().out.strip().split("\t")
        assert int(parts[2]) == 4 * int(parts[3])  # k full chunks read


class TestBaselineSweep:
    def test_baseline_mode_emits_all_configs(self, capsys):
        from ceph_tpu.tools import bench_sweep

        rc = bench_sweep.main(["--baseline", "--iterations", "1"])
        assert rc == 0
        import json

        lines = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        names = {r["config"] for r in lines}
        assert len(names) == len(bench_sweep.BASELINE_CONFIGS)
        by_name = {r["config"]: r for r in lines}
        clay = by_name["clay_8_4_d11_subchunk_repair"]
        assert "error" not in clay, clay
        assert clay["read_amplification"] == 2.75  # d/(d-k+1) = 11/4
        for r in lines:
            assert "error" not in r, r
