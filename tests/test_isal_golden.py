"""ISA-L golden-vector parity: the tpu plugin's bytes vs an independent
scalar re-derivation of the ISA-L math (tests/isal_reference.py).

The north star (BASELINE.json) claims byte-identical output vs the
reference `isa` plugin; no ISA-L build exists in this image, so these
vectors are the stand-in — a second implementation with disjoint
mechanics (peasant-multiply scalar loops vs log-table numpy vs bitsliced
device matmuls) that all three paths must agree with.  SHA-256 digests of
key vectors are additionally frozen as literals so both implementations
drifting together is also caught.
"""

import hashlib

import numpy as np
import pytest

import isal_reference as isal

from ceph_tpu.codec.registry import instance
from ceph_tpu.gf import (
    GF_MUL_TABLE,
    isa_cauchy_matrix,
    isa_rs_vandermonde_matrix,
)


class TestFieldCore:
    def test_mul_table_matches_peasant_multiply(self):
        # full 256x256 cross-check of the production table
        for a in range(256):
            row = GF_MUL_TABLE[a]
            for b in range(0, 256, 7):  # stride keeps it fast; a-loop is full
                assert row[b] == isal.gf_mul(a, b), (a, b)

    def test_mul_table_digest_frozen(self):
        # literal digest: even BOTH implementations drifting together
        # (e.g. a synchronized polynomial change) fails review-visibly
        frozen = "003d1a609783d2740b9b3f00b0cd9e43e42c4f3eedc5ff54ec1709996d52e1e0"
        digest = hashlib.sha256(np.ascontiguousarray(GF_MUL_TABLE)).hexdigest()
        assert digest == frozen
        independent = bytes(
            isal.gf_mul(a, b) for a in range(256) for b in range(256)
        )
        assert hashlib.sha256(independent).hexdigest() == frozen


class TestMatrices:
    @pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (7, 3), (8, 3), (10, 4)])
    def test_rs_matrix_matches(self, k, m):
        ours = isa_rs_vandermonde_matrix(k, m)
        theirs = isal.gen_rs_matrix(k, m)
        assert ours.tolist() == theirs

    @pytest.mark.parametrize("k,m", [(2, 2), (6, 4), (8, 3), (12, 4)])
    def test_cauchy_matrix_matches(self, k, m):
        ours = isa_cauchy_matrix(k, m)
        theirs = isal.gen_cauchy1_matrix(k, m)
        assert ours.tolist() == theirs


def _plugin_chunks(technique, k, m, data: bytes):
    ec = instance().factory(
        "tpu", {"k": str(k), "m": str(m), "technique": technique}
    )
    chunks = ec.encode(set(range(k + m)), data)
    return ec, chunks


CONFIGS = [
    ("reed_sol_van", 8, 3),
    ("reed_sol_van", 4, 2),
    ("cauchy", 6, 3),
]


class TestEncodeParity:
    @pytest.mark.parametrize("technique,k,m", CONFIGS)
    def test_parity_bytes_match_foreign_oracle(self, technique, k, m):
        ec, chunks = _plugin_chunks(
            technique, k, m, isal.lcg_bytes(k * 512, seed=0xCE9B)
        )
        chunk_size = len(chunks[0])
        dist = (
            isal.gen_rs_matrix(k, m)
            if technique == "reed_sol_van"
            else isal.gen_cauchy1_matrix(k, m)
        )
        data = [bytes(chunks[ec.chunk_index(i)]) for i in range(k)]
        want_parity = isal.encode(dist[k:], data)
        for i in range(m):
            got = bytes(chunks[ec.chunk_index(k + i)])
            assert got == want_parity[i], f"parity chunk {i} diverges"
            assert len(got) == chunk_size

    def test_frozen_digest_rs_8_3(self):
        """Belt and braces: the RS(8,3) parity digest is pinned as a
        literal, so even a synchronized change of both implementations
        fails review-visibly."""
        _ec, chunks = _plugin_chunks(
            "reed_sol_van", 8, 3, isal.lcg_bytes(8 * 512, seed=1234567)
        )
        parity = b"".join(bytes(chunks[i]) for i in range(8, 11))
        assert (
            hashlib.sha256(parity).hexdigest()
            == "24e833dd9859b8dc6a3ea5e8abe86548c5f17ccf62f7019096674a0a60ad279d"
        )


class TestCompiledForeignVectors:
    """Golden chunks from COMPILED foreign code (native/isal_scalar.c —
    clean-room C of ISA-L's published ec_base semantics, log/antilog
    mechanism): a third implementation that the production plugin AND
    the Python oracle must both match byte-for-byte (VERDICT r4 item 7)."""

    @pytest.fixture(scope="class")
    def vectors_bin(self):
        import pathlib
        import subprocess

        native = pathlib.Path(__file__).resolve().parent.parent / "native"
        r = subprocess.run(
            ["make", "-C", str(native), "isal_vectors"],
            capture_output=True, text=True,
        )
        if r.returncode != 0:
            pytest.skip(f"no C toolchain: {r.stderr[-200:]}")
        return str(native / "isal_vectors")

    @pytest.mark.parametrize(
        "technique,k,m",
        [("reed_sol_van", 8, 3), ("reed_sol_van", 4, 2),
         ("cauchy", 6, 3), ("cauchy", 10, 4)],
    )
    def test_plugin_matches_compiled_vectors(self, vectors_bin, technique, k, m):
        import subprocess

        chunk, seed = 512, 0xCE9B
        tech_c = "rs" if technique == "reed_sol_van" else "cauchy"
        out = subprocess.run(
            [vectors_bin, str(k), str(m), tech_c, str(chunk), str(seed)],
            capture_output=True,
        )
        assert out.returncode == 0, out.stderr
        blob = out.stdout
        assert len(blob) == (k + m) * k + (k + m) * chunk
        mat = np.frombuffer(blob[: (k + m) * k], np.uint8).reshape(k + m, k)
        body = blob[(k + m) * k :]
        c_chunks = [
            body[i * chunk : (i + 1) * chunk] for i in range(k + m)
        ]
        # 1. the compiled matrix equals the production one
        ours = (
            isa_rs_vandermonde_matrix(k, m)
            if technique == "reed_sol_van"
            else isa_cauchy_matrix(k, m)
        )
        assert mat.tolist() == ours.tolist()
        # 2. the C generator's LCG input equals the Python oracle's (the
        #    two harnesses drive identical bytes)
        assert b"".join(c_chunks[:k]) == isal.lcg_bytes(k * chunk, seed=seed)
        # 3. the production plugin's parity over that input equals the
        #    compiled encoder's, byte for byte
        ec, chunks = _plugin_chunks(
            technique, k, m, b"".join(c_chunks[:k])
        )
        for i in range(m):
            got = bytes(chunks[ec.chunk_index(k + i)])
            assert got == c_chunks[k + i], f"parity {i} diverges from C"
        # 4. and the Python oracle agrees with the compiled encoder too
        py_parity = isal.encode(
            [[int(x) for x in r] for r in mat[k:]],
            [bytes(c) for c in c_chunks[:k]],
        )
        for i in range(m):
            assert py_parity[i] == c_chunks[k + i]


class TestDecodeParity:
    @pytest.mark.parametrize("technique,k,m", CONFIGS)
    @pytest.mark.parametrize("nerr", [1, 2])
    def test_decode_matches_foreign_oracle(self, technique, k, m, nerr):
        if nerr > m:
            pytest.skip("more erasures than parities")
        data = isal.lcg_bytes(k * 256, seed=42 + k + nerr)
        ec, chunks = _plugin_chunks(technique, k, m, data)
        erasures = list(range(1, 1 + nerr))  # erase data chunks 1..nerr
        dist = (
            isal.gen_rs_matrix(k, m)
            if technique == "reed_sol_van"
            else isal.gen_cauchy1_matrix(k, m)
        )
        rows, survivors = isal.decode_matrix(dist, erasures, k)
        survivor_bytes = [bytes(chunks[ec.chunk_index(r)]) for r in survivors]
        want = isal.encode(rows, survivor_bytes)

        avail = {
            ec.chunk_index(i): chunks[ec.chunk_index(i)]
            for i in range(k + m)
            if i not in erasures
        }
        decoded = ec.decode(
            {ec.chunk_index(e) for e in erasures}, avail
        )
        for pos, e in enumerate(erasures):
            got = bytes(decoded[ec.chunk_index(e)])
            assert got == want[pos], f"recovered chunk {e} diverges"
            assert got == bytes(chunks[ec.chunk_index(e)])
