"""Stripe engine tests — offset algebra, batched encode/decode, hinfo.

Models /root/reference/src/test/osd/TestECBackend.cc (ECUtil stripe logic)
plus the hinfo verification done in handle_sub_read.
"""

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.interface import EcError
from ceph_tpu.codec.lrc import ErasureCodeLrc
from ceph_tpu.stripe import (
    HashInfo,
    StripeInfo,
    decode_concat,
    decode_shards,
    encode,
)
from ceph_tpu.utils.crc32c import crc32c


def make_rs(k=4, m=2):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


class TestStripeInfo:
    def test_offset_algebra(self):
        s = StripeInfo(stripe_width=4 * 1024, chunk_size=1024)
        assert s.k == 4
        assert s.logical_to_prev_stripe_offset(5000) == 4096
        assert s.logical_to_next_stripe_offset(5000) == 8192
        assert s.logical_to_prev_chunk_offset(5000) == 1024
        assert s.logical_to_next_chunk_offset(5000) == 2048
        assert s.aligned_logical_offset_to_chunk_offset(8192) == 2048
        assert s.aligned_chunk_offset_to_logical_offset(2048) == 8192
        assert s.offset_len_to_stripe_bounds(5000, 100) == (4096, 4096)
        assert s.offset_len_to_stripe_bounds(4096, 8192) == (4096, 8192)
        # byte B lives in chunk (B/chunk_size)%k of stripe B/stripe_width
        assert s.logical_to_chunk_position(5000) == (1, 0, 904)
        assert s.logical_to_chunk_position(4096 + 1024 * 2 + 7) == (1, 2, 7)


class TestBatchedCodec:
    def test_encode_matches_per_stripe(self):
        ec = make_rs(4, 2)
        cs = 256
        sinfo = StripeInfo(4 * cs, cs)
        stripes = 8
        rng = np.random.default_rng(0)
        obj = rng.integers(0, 256, stripes * sinfo.stripe_width, dtype=np.uint8)
        shards = encode(sinfo, ec, obj)
        assert set(shards) == set(range(6))
        # per-stripe oracle through the chunk-level interface
        for s in range(stripes):
            stripe = obj[s * sinfo.stripe_width : (s + 1) * sinfo.stripe_width]
            chunks = ec.encode(set(range(6)), stripe.tobytes())
            for i in range(6):
                assert np.array_equal(
                    shards[i][s * cs : (s + 1) * cs], chunks[i]
                ), (s, i)

    def test_decode_concat_roundtrip(self):
        ec = make_rs(4, 2)
        cs = 128
        sinfo = StripeInfo(4 * cs, cs)
        rng = np.random.default_rng(1)
        obj = rng.integers(0, 256, 16 * sinfo.stripe_width, dtype=np.uint8)
        shards = encode(sinfo, ec, obj)
        # lose two shards
        avail = {i: shards[i] for i in (0, 2, 3, 5)}
        out = decode_concat(sinfo, ec, avail)
        assert np.array_equal(out, obj)

    def test_decode_shards_rebuilds_parity(self):
        ec = make_rs(4, 2)
        cs = 128
        sinfo = StripeInfo(4 * cs, cs)
        rng = np.random.default_rng(2)
        obj = rng.integers(0, 256, 4 * sinfo.stripe_width, dtype=np.uint8)
        shards = encode(sinfo, ec, obj)
        avail = {i: shards[i] for i in (0, 1, 3, 4)}  # lost data 2, parity 5
        rebuilt = decode_shards(sinfo, ec, avail, need={2, 5})
        assert np.array_equal(rebuilt[2], shards[2])
        assert np.array_equal(rebuilt[5], shards[5])

    def test_non_matrix_codec_fallback(self):
        ec = ErasureCodeLrc()
        ec.init({"k": "4", "m": "2", "l": "3"})
        cs = ec.get_chunk_size(4 * 128)
        sinfo = StripeInfo(4 * cs, cs)
        rng = np.random.default_rng(3)
        obj = rng.integers(0, 256, 4 * sinfo.stripe_width, dtype=np.uint8)
        shards = encode(sinfo, ec, obj)
        assert set(shards) == set(range(8))
        avail = {i: shards[i] for i in range(8) if i != 1}
        out = decode_concat(sinfo, ec, avail)
        assert np.array_equal(out, obj)

    def test_unaligned_rejected(self):
        ec = make_rs(4, 2)
        sinfo = StripeInfo(4 * 128, 128)
        with pytest.raises(EcError):
            encode(sinfo, ec, b"x" * 100)


class TestHashInfo:
    def test_append_and_verify(self):
        ec = make_rs(4, 2)
        cs = 128
        sinfo = StripeInfo(4 * cs, cs)
        rng = np.random.default_rng(4)
        hi = HashInfo(6)
        parts = []
        for step in range(3):
            obj = rng.integers(0, 256, 2 * sinfo.stripe_width, dtype=np.uint8)
            shards = encode(sinfo, ec, obj)
            hi.append(hi.get_total_chunk_size(), shards)
            parts.append(shards)
        assert hi.get_total_chunk_size() == 3 * 2 * cs
        for i in range(6):
            full = np.concatenate([p[i] for p in parts])
            assert hi.verify_chunk(i, full)
            corrupted = full.copy()
            corrupted[0] ^= 1
            assert not hi.verify_chunk(i, corrupted)

    def test_append_must_be_sequential(self):
        hi = HashInfo(2)
        hi.append(0, {0: b"ab", 1: b"cd"})
        with pytest.raises(AssertionError):
            hi.append(0, {0: b"x", 1: b"y"})

    def test_encode_decode_roundtrip(self):
        hi = HashInfo(3)
        hi.append(0, {0: b"aaa", 1: b"bbb", 2: b"ccc"})
        blob = hi.encode()
        hi2 = HashInfo.decode(blob)
        assert hi2.cumulative_shard_hashes == hi.cumulative_shard_hashes
        assert hi2.get_total_chunk_size() == 3

    def test_cumulative_matches_onepass(self):
        a, b = b"hello ", b"world"
        assert crc32c(b, crc32c(a)) == crc32c(a + b)
