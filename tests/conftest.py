"""Test harness config: force an 8-device virtual CPU mesh.

Tests never assume real TPU hardware; sharding/collective paths are validated
on `--xla_force_host_platform_device_count=8` exactly as the driver's
multi-chip dry-run does.  The axon sitecustomize pre-registers the TPU
platform before pytest starts, so overriding the platform must go through
jax.config (env vars alone are too late / overridden).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# The axon plugin's discovery runs at `import jax` when this gate variable
# is set, and a wedged TPU tunnel then hangs the import forever — even
# with JAX_PLATFORMS=cpu.  Tests are CPU-only by design, so dropping the
# gate keeps the suite runnable whatever state the tunnel is in.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Dynamic lock-order validation ON for the whole tier-1 suite (ISSUE 12):
# every make_lock/make_rlock/make_async_lock acquisition across the
# aggregator/scheduler/pipeline/cache stack validates against the
# observed ordering graph, so a latent deadlock introduced anywhere
# fails the suite even if the losing interleaving never runs — the
# reference's -DCEPH_DEBUG_MUTEX lockdep tier (PAPER.md layer 1).
# Set CEPH_TPU_LOCKDEP=0 explicitly to debug with validation off.
if os.environ.get("CEPH_TPU_LOCKDEP", "") != "0":
    os.environ["CEPH_TPU_LOCKDEP"] = "1"
    from ceph_tpu.common import lockdep  # noqa: E402

    lockdep.enable()

# HBM leak gate ON for the whole tier-1 suite (ISSUE 13, like lockdep):
# every test must leave the EC launch pipelines drained — the
# `ec_pipeline_inflight` and `verify` mempool pools read zero at
# teardown, or the test leaked a device hold (the host-fallback /
# sticky-error shapes the ledger exists to expose).  The drain step
# first settles anything legitimately still in flight (a depth-N ring
# the test simply didn't reap), so only holds that survive a full
# settle count as leaks.
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _hbm_leak_gate():
    yield
    from ceph_tpu.common.mempool import ledger

    led = ledger()

    def _held() -> int:
        return (
            led.current_bytes("ec_pipeline_inflight")
            + led.current_bytes("verify")
            + led.current_bytes("offload_inflight")
        )

    leaked = _held()
    if leaked:
        from ceph_tpu.codec.matrix_codec import drain_all_aggregators

        try:
            drain_all_aggregators()
        except Exception:
            pass  # sticky launch errors still settle; re-measure below
        leaked = _held()
    assert leaked == 0, (
        f"HBM ledger leak: {leaked} bytes still held in the EC launch "
        f"pools after drain (reconcile: {led.reconcile()})"
    )
