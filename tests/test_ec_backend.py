"""ECBackend / ReplicatedBackend engine tests.

Models the reference's TestECBackend + the standalone put/get/recovery flows
(SURVEY.md §4): an in-process cluster of MemStore-backed backends wired
through a queued transport (the primary "sends to itself" exactly as
ECBackend.h:336-338), exercising the write pipeline, reconstructing reads,
redundant-read escalation on corruption, and the recovery state machine.
"""

import numpy as np
import pytest

from ceph_tpu.msg.messages import PgId, ReqId
from ceph_tpu.os.memstore import MemStore
from ceph_tpu.os.transaction import Transaction
from ceph_tpu.osd.ec_transaction import HINFO_ATTR, OI_ATTR, PGTransaction
from ceph_tpu.osd.osdmap import (
    FLAG_EC_OVERWRITES,
    PG_NONE,
    POOL_TYPE_ERASURE,
    POOL_TYPE_REPLICATED,
    PgPool,
)
from ceph_tpu.osd.pg_backend import PGListener, build_pg_backend, shard_coll
from ceph_tpu.osd.pg_log import Eversion


class Listener(PGListener):
    def __init__(self, cluster, osd, shard, pgid):
        self.cluster = cluster
        self.osd = osd
        self.shard = shard
        self.pgid = pgid
        self.version = 0
        self.log = []
        self.recovered_local = []
        self.recovered_global = []
        self.clog = []

    def whoami(self):
        return self.osd

    def whoami_shard(self):
        return self.shard

    def acting(self):
        return self.cluster.acting

    def epoch(self):
        return 1

    def next_version(self):
        self.version += 1
        return Eversion(1, self.version)

    def send_shard(self, osd, msg):
        self.cluster.queue.append((osd, msg))

    def append_log(self, entry):
        self.log.append(entry)

    def get_shard_missing(self, oid):
        return self.cluster.missing.get(oid, set())

    def on_local_recover(self, oid):
        self.recovered_local.append(oid)

    def on_global_recover(self, oid):
        self.recovered_global.append(oid)

    def clog_error(self, msg):
        self.clog.append(msg)


class Cluster:
    """n_osds backends over MemStores with a pumped message queue."""

    def __init__(self, pool: PgPool, profiles=None, n_osds=None):
        self.pool = pool
        if pool.type == POOL_TYPE_ERASURE:
            n = pool.size
            self.pgid = PgId(pool.id, 0, -1)
        else:
            n = n_osds or pool.size
            self.pgid = PgId(pool.id, 0, -1)
        self.acting = list(range(n))
        self.queue = []
        self.missing = {}
        self.stores = []
        self.listeners = []
        self.backends = []
        for osd in range(n):
            store = MemStore()
            store.mount()
            shard = osd if pool.type == POOL_TYPE_ERASURE else -1
            listener = Listener(self, osd, shard, self.pgid)
            backend = build_pg_backend(pool, profiles or {}, listener, store)
            # every OSD hosts its shard's collection
            coll = shard_coll(self.pgid, shard)
            store.queue_transaction(Transaction().create_collection(coll))
            self.stores.append(store)
            self.listeners.append(listener)
            self.backends.append(backend)

    @property
    def primary(self):
        return self.backends[self.acting_primary()]

    def acting_primary(self):
        return next(o for o in self.acting if o != PG_NONE)

    def pump(self):
        """Deliver queued messages until quiescent (the network).  There
        is no event loop here, so launched encodes are reaped explicitly
        (the OSD's asyncio loop does this via _schedule_drain)."""
        steps = 0
        while True:
            for b in self.backends:
                b.flush_encodes()
            if not self.queue:
                break
            osd, msg = self.queue.pop(0)
            if osd == PG_NONE or not (0 <= osd < len(self.backends)):
                continue
            self.backends[osd].handle_message(msg)
            steps += 1
            assert steps < 100000, "message storm"
        return steps

    def write(self, oid, off, data, pump=True):
        done = []
        pgt = PGTransaction(oid).write(off, data)
        self.primary.submit_transaction(pgt, ReqId("client", 1), lambda: done.append(1))
        if pump:
            self.pump()
            assert done, "write did not commit"
        return done

    def read(self, oid, off, length):
        out = {}
        self.primary.objects_read_and_reconstruct(
            {oid: [(off, length)]}, lambda res: out.update(res)
        )
        self.pump()
        assert oid in out, "read did not complete"
        err, bufs = out[oid]
        assert err == 0, f"read failed: {err}"
        return bufs[0]


def ec_pool(k=4, m=2, stripe_unit=4096, flags=0, plugin="tpu", **profile_extra):
    profile = {"plugin": plugin, "k": str(k), "m": str(m), **profile_extra}
    pool = PgPool(
        id=1,
        name="ecpool",
        type=POOL_TYPE_ERASURE,
        size=k + m,
        pg_num=1,
        erasure_code_profile="prof",
        stripe_width=k * stripe_unit,
        flags=flags,
    )
    return pool, {"prof": profile}


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n).astype(np.uint8).tobytes()


class TestEcWriteRead:
    def test_append_and_read(self):
        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        data = payload(3 * pool.stripe_width)
        c.write("obj", 0, data)
        assert c.read("obj", 0, len(data)) == data
        # unaligned sub-reads hit the stripe decode path
        assert c.read("obj", 100, 5000) == data[100:5100]

    def test_shard_layout_and_hinfo(self):
        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        data = payload(2 * pool.stripe_width)
        c.write("obj", 0, data)
        # each shard object holds its chunk stream; hinfo digests verify
        from ceph_tpu.stripe import HashInfo

        for s in range(6):
            coll = shard_coll(c.pgid, s)
            chunk = c.stores[s].read(coll, "obj", 0, 0)
            assert len(chunk) == 2 * pool.stripe_width // 4
            hi = HashInfo.decode(c.stores[s].getattr(coll, "obj", HINFO_ATTR))
            assert hi.verify_chunk(s, chunk)

    def test_sequential_appends_chain_hinfo(self):
        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        d1 = payload(pool.stripe_width, 1)
        d2 = payload(2 * pool.stripe_width, 2)
        c.write("obj", 0, d1)
        c.write("obj", pool.stripe_width, d2)
        assert c.read("obj", 0, 3 * pool.stripe_width) == d1 + d2

    def test_full_rewrite_restarts_hinfo_chain(self):
        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        d1 = payload(pool.stripe_width, 1)
        d2 = payload(pool.stripe_width, 2)
        c.write("obj", 0, d1)
        c.write("obj", 0, d2)  # full rewrite: fresh digest chain
        assert c.read("obj", 0, pool.stripe_width) == d2
        from ceph_tpu.stripe import HashInfo

        coll = shard_coll(c.pgid, 0)
        hi = HashInfo.decode(c.stores[0].getattr(coll, "obj", HINFO_ATTR))
        assert hi.verify_chunk(0, c.stores[0].read(coll, "obj", 0, 0))

    def test_unaligned_append_rejected_without_overwrites(self):
        from ceph_tpu.codec.interface import EcError

        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        with pytest.raises(EcError):
            c.write("obj", 17, b"x" * 100, pump=False)

    def test_degraded_read(self):
        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        data = payload(2 * pool.stripe_width)
        c.write("obj", 0, data)
        # two shards go dark (holes in the acting set)
        c.acting[1] = PG_NONE
        c.acting[5] = PG_NONE
        assert c.read("obj", 0, len(data)) == data

    def test_too_many_failures_is_eio(self):
        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        data = payload(pool.stripe_width)
        c.write("obj", 0, data)
        for s in (0, 1, 2):
            c.acting[s] = PG_NONE
        out = {}
        c.primary2 = c.backends[3]  # osd 3 is the new primary
        c.backends[3].objects_read_and_reconstruct(
            {"obj": [(0, len(data))]}, lambda res: out.update(res)
        )
        c.pump()
        err, _ = out["obj"]
        assert err < 0

    def test_corrupt_shard_escalates_to_redundant_read(self):
        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        data = payload(pool.stripe_width)
        c.write("obj", 0, data)
        # flip bytes in shard 0's chunk; whole-shard read fails hinfo crc,
        # escalation reads a parity shard instead
        coll = shard_coll(c.pgid, 0)
        good = c.stores[0].read(coll, "obj", 0, 0)
        c.stores[0]._write(coll, "obj", 0, bytes([good[0] ^ 0xFF]) + good[1:])
        assert c.read("obj", 0, len(data)) == data
        assert any("crc mismatch" in e for e in c.listeners[0].clog)


class TestEcOverwrites:
    def test_rmw_partial_stripe(self):
        pool, profiles = ec_pool(4, 2, flags=FLAG_EC_OVERWRITES)
        c = Cluster(pool, profiles)
        base = payload(2 * pool.stripe_width)
        c.write("obj", 0, base)
        patch = payload(300, seed=9)
        c.write("obj", 1000, patch)
        expect = bytearray(base)
        expect[1000:1300] = patch
        assert c.read("obj", 0, len(base)) == bytes(expect)
        # hinfo dropped on overwrite (reference bypasses it)
        coll = shard_coll(c.pgid, 0)
        from ceph_tpu.os.objectstore import StoreError

        with pytest.raises(StoreError):
            c.stores[0].getattr(coll, "obj", HINFO_ATTR)

    def test_overwrite_spanning_stripes(self):
        pool, profiles = ec_pool(4, 2, flags=FLAG_EC_OVERWRITES)
        c = Cluster(pool, profiles)
        base = payload(4 * pool.stripe_width)
        c.write("obj", 0, base)
        patch = payload(2 * pool.stripe_width + 777, seed=3)
        off = pool.stripe_width - 123
        c.write("obj", off, patch)
        expect = bytearray(base)
        expect[off : off + len(patch)] = patch
        assert c.read("obj", 0, len(base)) == bytes(expect)

    def test_pipelined_overlapping_writes(self):
        pool, profiles = ec_pool(4, 2, flags=FLAG_EC_OVERWRITES)
        c = Cluster(pool, profiles)
        base = payload(pool.stripe_width)
        c.write("obj", 0, base)
        # two overlapping RMWs submitted back-to-back without pumping:
        # the second must see the first's pending bytes via the ExtentCache
        done = []
        p1 = payload(200, seed=5)
        p2 = payload(200, seed=6)
        c.primary.submit_transaction(
            PGTransaction("obj").write(100, p1), ReqId("c", 1), lambda: done.append(1)
        )
        c.primary.submit_transaction(
            PGTransaction("obj").write(200, p2), ReqId("c", 2), lambda: done.append(2)
        )
        c.pump()
        assert done == [1, 2]
        expect = bytearray(base)
        expect[100:300] = p1
        expect[200:400] = p2
        assert c.read("obj", 0, len(base)) == bytes(expect)
        assert c.primary.extent_cache.empty()

    def test_encode_pipeline_overlaps_launch_with_commit(self):
        """VERDICT r4 item 5: the encode pipeline must LAUNCH the second
        write's device encode before the first write's commit — sub-writes
        fan out only when the pipeline reaps (flush/drain), so between
        submits both ops sit launched-but-uncommitted."""
        pool, profiles = ec_pool(4, 2, flags=FLAG_EC_OVERWRITES)
        c = Cluster(pool, profiles)
        base = payload(pool.stripe_width)
        c.write("obj", 0, base)
        done = []
        p1 = payload(pool.stripe_width, seed=7)  # full stripe: no RMW read
        c.primary.submit_transaction(
            PGTransaction("obj").write(0, p1), ReqId("c", 10), lambda: done.append(1)
        )
        c.primary.submit_transaction(
            PGTransaction("obj2").write(0, payload(pool.stripe_width, seed=9)),
            ReqId("c", 11),
            lambda: done.append(2),
        )
        # both encodes LAUNCHED (second's launch precedes first's commit)...
        launched = [op.pgt.oid for op in c.primary._encode_pipe]
        assert launched == ["obj", "obj2"]
        assert all(op.encoded for op in c.primary._encode_pipe)
        # ...while neither has committed nor even fanned out sub-writes
        assert done == []
        assert all(not op.pending_commits for op in c.primary._encode_pipe)
        c.pump()  # reap + deliver
        assert done == [1, 2]
        assert c.read("obj", 0, len(base)) == p1

    def test_truncate_unaligned(self):
        pool, profiles = ec_pool(4, 2, flags=FLAG_EC_OVERWRITES)
        c = Cluster(pool, profiles)
        base = payload(2 * pool.stripe_width)
        c.write("obj", 0, base)
        t = pool.stripe_width + 500
        done = []
        c.primary.submit_transaction(
            PGTransaction("obj", truncate=t), ReqId("c", 3), lambda: done.append(1)
        )
        c.pump()
        assert done
        got = c.read("obj", 0, t)
        assert got == base[:t]


class TestEcRecovery:
    def _lose_and_recover(self, c, pool, oid, lost):
        # snapshot lost shards' bytes, wipe them, mark missing
        snapshots = {}
        for s in lost:
            coll = shard_coll(c.pgid, s)
            snapshots[s] = (
                c.stores[s].read(coll, oid, 0, 0),
                c.stores[s].getattrs(coll, oid),
            )
            c.stores[s]._remove(coll, oid)
        c.missing[oid] = set(lost)
        res = []
        c.primary.recover_object(oid, set(lost), lambda err: res.append(err))
        c.pump()
        assert res == [0]
        c.missing.pop(oid)
        for s in lost:
            coll = shard_coll(c.pgid, s)
            data, attrs = snapshots[s]
            assert c.stores[s].read(coll, oid, 0, 0) == data
            got_attrs = c.stores[s].getattrs(coll, oid)
            assert got_attrs[OI_ATTR] == attrs[OI_ATTR]

    def test_recover_one_data_shard(self):
        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        c.write("obj", 0, payload(3 * pool.stripe_width))
        self._lose_and_recover(c, pool, "obj", [1])
        assert "obj" in c.listeners[1].recovered_local
        assert "obj" in c.listeners[0].recovered_global

    def test_recover_parity_and_data(self):
        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        c.write("obj", 0, payload(2 * pool.stripe_width))
        self._lose_and_recover(c, pool, "obj", [2, 5])

    def test_recover_when_primary_missing(self):
        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        c.write("obj", 0, payload(pool.stripe_width))
        self._lose_and_recover(c, pool, "obj", [0])


class TestClayRepair:
    def test_clay_single_shard_repair_reads_fragments(self):
        pool, profiles = ec_pool(
            4, 2, plugin="clay", stripe_unit=4096
        )
        c = Cluster(pool, profiles)
        ec = c.primary.ec
        assert ec.get_sub_chunk_count() > 1
        # clay chunk alignment: use one full stripe of its preferred size
        obj = payload(pool.stripe_width)
        c.write("obj", 0, obj)
        assert c.read("obj", 0, len(obj)) == obj
        # single lost shard repairs from subchunk fragments
        lost = 1
        coll = shard_coll(c.pgid, lost)
        before = c.stores[lost].read(coll, "obj", 0, 0)
        c.stores[lost]._remove(coll, "obj")
        c.missing["obj"] = {lost}
        res = []
        c.primary.recover_object("obj", {lost}, lambda e: res.append(e))
        c.pump()
        assert res == [0]
        assert c.stores[lost].read(coll, "obj", 0, 0) == before


class TestReplicatedBackend:
    def _pool(self):
        return PgPool(
            id=2, name="rep", type=POOL_TYPE_REPLICATED, size=3, pg_num=1
        )

    def test_write_read(self):
        c = Cluster(self._pool())
        data = payload(10000)
        c.write("obj", 0, data)
        assert c.read("obj", 0, len(data)) == data
        # all three replicas hold the full object
        coll = shard_coll(c.pgid, -1)
        for s in range(3):
            assert c.stores[s].read(coll, "obj", 0, 0) == data

    def test_recover_replica(self):
        c = Cluster(self._pool())
        data = payload(5000)
        c.write("obj", 0, data)
        coll = shard_coll(c.pgid, -1)
        c.stores[2]._remove(coll, "obj")
        res = []
        c.primary.recover_object("obj", {2}, lambda e: res.append(e))
        c.pump()
        assert res == [0]
        assert c.stores[2].read(coll, "obj", 0, 0) == data

    def test_recover_primary_via_pull(self):
        c = Cluster(self._pool())
        data = payload(5000)
        c.write("obj", 0, data)
        coll = shard_coll(c.pgid, -1)
        c.stores[0]._remove(coll, "obj")
        res = []
        c.primary.recover_object("obj", {0}, lambda e: res.append(e))
        c.pump()
        # pull completes the primary, which was also the only target
        assert c.stores[0].read(coll, "obj", 0, 0) == data


class TestTracing:
    """The tracer threaded through the EC data path (ECBackend.h:64-87):
    every op carries a span; a degraded read must leave a complete tree —
    read span, shard events, and a reconstruct child."""

    def _traced_cluster(self):
        from ceph_tpu.common.tracer import Tracer

        pool, profiles = ec_pool(2, 1)
        cluster = Cluster(pool, profiles)
        tracer = Tracer("osd.test")
        cluster.listeners[cluster.acting_primary()].tracer = tracer
        return cluster, tracer

    def test_degraded_read_span_tree(self):
        cluster, tracer = self._traced_cluster()
        data = bytes(range(256)) * 64
        cluster.write("obj", 0, data)
        tracer.clear()

        # shard 1 lost: the read must reconstruct
        cluster.missing["obj"] = {1}
        out = {}
        cluster.primary.objects_read_and_reconstruct(
            {"obj": [(0, len(data))]}, lambda r: out.update(r)
        )
        cluster.pump()
        assert out["obj"][0] == 0 and out["obj"][1][0] == data

        spans = {s["span_id"]: s for s in tracer.export()}
        reads = [s for s in spans.values() if s["name"] == "ec:read"]
        assert len(reads) == 1
        read = reads[0]
        assert read["end"] is not None  # finished
        events = [e["name"] for e in read["events"]]
        assert any(e.startswith("sub-reads to shards") for e in events)
        assert any(e.startswith("reply from shard") for e in events)
        assert "read complete" in events
        # the decode ran under a child span linked to the read
        recon = [s for s in spans.values() if s["name"] == "ec:reconstruct"]
        assert len(recon) == 1
        assert recon[0]["parent_id"] == read["span_id"]
        assert recon[0]["end"] is not None
        assert "1" not in recon[0]["tags"]["have"].split(",")

    def test_write_span_commits_per_shard(self):
        cluster, tracer = self._traced_cluster()
        cluster.write("w", 0, b"x" * 8192)
        spans = [s for s in tracer.export() if s["name"] == "ec:write"]
        assert len(spans) == 1
        events = [e["name"] for e in spans[0]["events"]]
        assert "start ec write" in events
        assert sum(1 for e in events if e.startswith("commit from shard")) == 3
        assert "all shards committed" in events
        assert spans[0]["end"] is not None

    def test_recovery_span(self):
        cluster, tracer = self._traced_cluster()
        data = b"r" * 16384
        cluster.write("rec", 0, data)
        # wipe shard 2's store copy, then recover it
        coll = shard_coll(cluster.pgid, 2)
        cluster.stores[2].queue_transaction(Transaction().remove(coll, "rec"))
        tracer.clear()
        done = []
        cluster.primary.recover_object("rec", {2}, done.append)
        cluster.pump()
        assert done == [0]
        spans = [s for s in tracer.export() if s["name"] == "ec:recover"]
        assert len(spans) == 1
        events = [e["name"] for e in spans[0]["events"]]
        assert "gather surviving shards" in events
        assert any(e.startswith("decoded; pushing") for e in events)
        assert "all pushes acked; recovered" in events
        assert spans[0]["end"] is not None
