"""Mgr tests: beacon/active election, failover, DaemonServer
aggregation, prometheus exposition, balancer planning, pg_autoscaler
recommendations (src/mgr + src/pybind/mgr mirrors)."""

import asyncio
import json
import urllib.request

import pytest

from ceph_tpu.client import Rados
from ceph_tpu.mgr import Mgr
from ceph_tpu.mgr.balancer import BalancerModule
from ceph_tpu.mgr.pg_autoscaler import PgAutoscalerModule, TARGET_PG_PER_OSD
from ceph_tpu.mgr.prometheus import PrometheusModule

from test_cluster import start_cluster, stop_cluster, wait_until


async def start_mgr(monmap, name="x"):
    mgr = Mgr(name, monmap)
    mgr.beacon_interval = 0.1
    await mgr.start()
    return mgr


class TestMgrDaemon:
    def test_active_election_and_reports(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            assert mons[0].mgrmon.map.active_name == "x"

            # OSDs learn the mgr address and report perf counters
            await wait_until(
                lambda: len(mgr.daemons) == 3, 5.0, "3 daemon reports"
            )
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("mp", "replicated", size=3, pg_num=4)
            ioctx = await client.open_ioctx("mp")
            await ioctx.write_full("o", b"x" * 4096)
            await wait_until(
                lambda: any(
                    mgr.get_daemon_perf(d).get("op", 0) > 0
                    for d in mgr.list_daemons()
                ),
                5.0,
                "op counters reaching mgr",
            )
            status = mgr.get_daemon_status(mgr.list_daemons()[0])
            assert status.get("up") is True
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_pg_digest_feeds_ceph_df(self):
        """mgr aggregates the OSDs' pool stats into a PGMap digest and
        ships it to the mons (MMonMgrReport): `ceph df` serves STORED
        (logical, once) vs USED (raw, xreplication)."""

        async def run():
            import json

            monmap, mons, osds = await start_cluster(1, 3)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("dfp", "replicated", size=3, pg_num=4)
            io = await client.open_ioctx("dfp")
            for i in range(4):
                await io.write_full(f"o{i}", b"z" * 10_000)

            def df():
                return mons[0].pg_digest.get("pools", {}).get("dfp")

            # every OSD's periodic report must land post-write: replicas'
            # raw bytes arrive on their own report cadence
            await wait_until(
                lambda: df() is not None
                and df()["objects"] == 4
                and df()["used_raw"] == 120_000,
                10.0,
                "df digest reaching the mon",
            )
            stats = df()
            assert stats["stored"] == 40_000
            # raw usage counts every replica (size=3)
            assert stats["used_raw"] == 120_000
            # and the command surface serves the same digest
            rv, _, out = await client.mon_command({"prefix": "df"})
            assert rv == 0
            parsed = json.loads(out)
            assert parsed["pools"]["dfp"]["stored"] == 40_000
            assert parsed["total_used_raw"] >= 120_000
            # `ceph osd df`: per-OSD raw usage sums to the pool total
            rv, _, out = await client.mon_command({"prefix": "osd df"})
            assert rv == 0
            per_osd = json.loads(out)
            assert set(per_osd) == {"osd.0", "osd.1", "osd.2"}
            assert sum(per_osd.values()) >= 120_000
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_df_deleted_pool_keeps_id_keyed_record(self):
        """A pool deleted mid-report (stats still arriving from OSDs
        that have not dropped its PGs, but no name in the osdmap) must
        surface as an id-keyed record flagged `deleted: true` — not
        under a fabricated `pool<N>` name that could collide with (or
        masquerade as) a real pool (ISSUE 10 satellite)."""
        from types import SimpleNamespace

        from ceph_tpu.mgr.mgr import DaemonState
        from ceph_tpu.mon.monmap import MonMap

        mgr = Mgr("x", MonMap(addrs={"a": "127.0.0.1:6789"}))
        # pool names are arbitrary strings: a live pool literally named
        # "7" must NOT merge with the deleted pool id 7's stale stats
        mgr.osdmap.pools = {
            1: SimpleNamespace(id=1, name="rbd"),
            2: SimpleNamespace(id=2, name="7"),
        }
        st = DaemonState()
        st.status = {
            "pool_stored": {"1": 1000, "7": 123, "2": 50},
            "pool_heads": {"1": 2, "7": 1, "2": 1},
            "pool_bytes": {"1": 3000, "7": 369, "2": 150},
        }
        mgr.daemons["osd.0"] = st
        pools = mgr.pg_digest()["pools"]
        # the live pools key by name, unflagged
        assert pools["rbd"] == {"stored": 1000, "objects": 2, "used_raw": 3000}
        assert pools["7"] == {"stored": 50, "objects": 1, "used_raw": 150}
        # the deleted pool keys by id in its own namespace + flag
        assert "pool7" not in pools
        assert pools["id:7"]["deleted"] is True
        assert pools["id:7"]["id"] == 7
        assert pools["id:7"]["stored"] == 123
        assert pools["id:7"]["used_raw"] == 369

    def test_standby_failover(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 1)
            mgr_a = await start_mgr(monmap, "a")
            await mgr_a.wait_for_active()
            mgr_b = await start_mgr(monmap, "b")
            await asyncio.sleep(0.3)
            assert not mgr_b.active
            assert mons[0].mgrmon.map.standbys == {"b": mgr_b.msgr.addr}

            # active dies; standby's beacons trigger the grace failover
            import ceph_tpu.mon.mgr_monitor as mm

            mons[0].mgrmon._last_beacon["a"] = -1000.0  # expire instantly
            await mgr_a.stop()
            await mgr_b.wait_for_active(timeout=10.0)
            assert mons[0].mgrmon.map.active_name == "b"
            await mgr_b.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestPrometheus:
    def test_scrape_over_http(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 2)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            prom = PrometheusModule()
            mgr.register_module(prom)
            addr = await prom.serve()
            await wait_until(lambda: len(mgr.daemons) == 2, 5.0, "reports")

            text = await asyncio.get_event_loop().run_in_executor(
                None,
                lambda: urllib.request.urlopen(
                    f"http://{addr}/metrics", timeout=5
                ).read().decode(),
            )
            assert 'ceph_tpu_osd_up{osd="0"} 1' in text
            assert 'ceph_tpu_osd_up{osd="1"} 1' in text
            assert "ceph_tpu_osdmap_epoch" in text
            assert 'ceph_tpu_op{daemon="osd.0"}' in text
            assert "ceph_tpu_pool_stored_bytes" in text
            await prom.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestBalancer:
    def test_even_cluster_has_no_plan(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("bp", "replicated", size=3, pg_num=8)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            bal = BalancerModule()
            mgr.register_module(bal)
            # size==n_osds: every OSD holds every PG; perfectly even
            assert abs(bal.score() - 1.0) < 1e-9
            assert bal.optimize() == []
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_uneven_cluster_plans_reweight(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 4)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("bp", "replicated", size=2, pg_num=16)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            bal = BalancerModule(threshold=1.01, max_adjustments=1)
            mgr.register_module(bal)
            counts = bal.pg_counts()
            assert sum(counts.values()) == 32  # 16 pgs x size 2
            plan = bal.optimize()
            if max(counts.values()) / (sum(counts.values()) / len(counts)) > 1.01:
                assert plan, counts
                assert plan[0]["to"] < plan[0]["from"]
                # applying the plan through the mon moves the map
                bal.active_mode = True
                await bal.tick()
                await wait_until(
                    lambda: any(
                        i.weight < 0x10000
                        for i in mons[0].osdmon.osdmap.osds.values()
                    ),
                    5.0,
                    "reweight commit",
                )
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestPgAutoscaler:
    def test_recommends_power_of_two_target(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("tiny", "replicated", size=3, pg_num=2)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            auto = PgAutoscalerModule(mode="warn")
            mgr.register_module(auto)
            recs = auto.recommend()
            assert "tiny" in recs
            r = recs["tiny"]
            # 3 osds * 100 target / 3 replicas / 1 pool = 100 -> 128
            assert r["ideal"] == 128
            assert r["should_adjust"]  # 2 -> 128 is >3x off
            await auto.tick()
            assert "POOL_PG_NUM" in auto.health_checks
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_on_mode_applies_to_empty_pool(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("grow", "replicated", size=3, pg_num=2)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            auto = PgAutoscalerModule(mode="on")
            mgr.register_module(auto)
            await auto.tick()
            await wait_until(
                lambda: mons[0].osdmon.osdmap.get_pool("grow").pg_num == 128,
                5.0,
                "pg_num applied",
            )
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestTelemetry:
    def test_opt_in_report_shapes_only(self):
        """The telemetry module (pybind/mgr/telemetry): disabled by
        default, explicit opt-in, and reports carry cluster SHAPE only —
        a salted-hash id, counts, pool geometry — never names."""

        async def run():
            import json as _json

            from ceph_tpu.mgr.telemetry import TelemetryModule

            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "t21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("tec", "erasure", profile="t21", pg_num=4)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            tel = TelemetryModule()
            mgr.register_module(tel)

            # off by default: ticks never compile a report
            tel.tick()
            assert tel.reports == [] and not tel.enabled

            tel.on()
            tel.tick()
            assert len(tel.reports) == 1
            report = tel.reports[0]
            assert report["osd"]["count"] == 3 and report["osd"]["up"] == 3
            kinds = {p["type"] for p in report["pools"]}
            assert "erasure" in kinds
            ec_pool = next(p for p in report["pools"] if p["type"] == "erasure")
            assert ("k", "2") in ec_pool["erasure_code_profile"]
            # privacy: no pool NAMES, osd addresses, or object keys anywhere
            blob = _json.dumps(report)
            assert "tec" not in blob and "127.0.0.1" not in blob
            assert len(report["cluster_id"]) == 16

            # interval gating: an immediate second tick does not re-send
            tel.tick()
            assert len(tel.reports) == 1
            assert _json.loads(tel.show())["osd"]["count"] == 3

            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestTelemetrySalt:
    def test_salt_from_config_is_failover_stable(self):
        """With telemetry_salt configured (the central-config path), two
        module instances — the failover scenario — produce the SAME
        cluster_id; without it, ids are per-instance random."""
        from ceph_tpu.common.config import Config
        from ceph_tpu.mgr.telemetry import TelemetryModule

        class FakeMgr:
            def __init__(self, conf):
                self.conf = conf
                self.osdmap = type("M", (), {"fsid": "abc-123"})()

        conf = Config({"name": "mgr.x", "telemetry_salt": "s3cret"})
        a, b = TelemetryModule(), TelemetryModule()
        a.mgr, b.mgr = FakeMgr(conf), FakeMgr(conf)
        assert a._cluster_id() == b._cluster_id()
        # and it is a salted hash, not the raw fsid
        assert "abc-123" not in a._cluster_id()

        unconf = Config({"name": "mgr.y"})
        c, d = TelemetryModule(), TelemetryModule()
        c.mgr, d.mgr = FakeMgr(unconf), FakeMgr(unconf)
        assert c._cluster_id() != d._cluster_id()  # random per instance
        assert c._cluster_id() == c._cluster_id()  # but stable within one


class TestDashboard:
    def test_rest_api_over_http(self):
        """The dashboard module (pybind/mgr/dashboard): REST endpoints
        reflecting live cluster state, served from the active mgr."""

        async def run():
            import json as _json

            from ceph_tpu.mgr.dashboard import DashboardModule

            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("dpool", "replicated", pg_num=4)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            dash = DashboardModule()
            mgr.register_module(dash)
            addr = await dash.serve()
            host, port = addr.rsplit(":", 1)

            async def get(path):
                reader, writer = await asyncio.open_connection(host, int(port))
                writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
                await writer.drain()
                raw = await reader.read()
                writer.close()
                head, _, body = raw.partition(b"\r\n\r\n")
                return head.split()[1].decode(), body

            status, body = await get("/api/health")
            assert status == "200"
            health = _json.loads(body)
            assert health["num_osds"] == 3 and health["num_up_osds"] == 3

            status, body = await get("/api/pools")
            assert status == "200"
            pools = _json.loads(body)
            assert any(p["name"] == "dpool" for p in pools)

            status, body = await get("/api/osds")
            assert all(o["up"] for o in _json.loads(body))

            status, body = await get("/api/pgs")
            pgs = _json.loads(body)
            assert any(pg["pgid"].endswith(".0") for pg in pgs)

            status, body = await get("/")
            assert status == "200" and b"Cluster" in body

            status, _ = await get("/nope")
            assert status == "404"

            await dash.shutdown()
            await mgr.stop()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestOrchestrator:
    def test_apply_scales_osds_through_backend(self):
        """The orchestrator module (pybind/mgr/orchestrator): `apply`
        records desired state; the reconcile loop realizes it through a
        backend — here an in-process backend that boots real OSD daemons
        (the cephadm analog for this test harness)."""

        async def run():
            from ceph_tpu.mgr.orchestrator import (
                OrchBackend,
                OrchestratorModule,
                ServiceSpec,
            )
            from test_cluster import fast_conf
            from ceph_tpu.osd.osd import OSD

            monmap, mons, osds = await start_cluster(1, 2)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            orch = OrchestratorModule()
            mgr.register_module(orch)

            spawned = []

            class LocalBackend(OrchBackend):
                async def scale(self, service_type, current, target):
                    assert service_type == "osd"
                    while current < target:
                        osd = OSD(current, monmap, conf=fast_conf(current))
                        await osd.start()
                        spawned.append(osd)
                        current += 1

                def inventory(self):
                    return [
                        {"host": "localhost", "device": f"mem-{o}", "osd": o}
                        for o in sorted(mgr.osdmap.osds)
                    ]

            orch.set_backend(LocalBackend())
            assert orch.observed_count("osd") == 2
            msg = orch.apply(ServiceSpec("osd", count=4))
            assert "Scheduled" in msg
            await orch.reconcile()
            for o in spawned:
                await o.wait_for_up()

            def four_up():
                return sum(1 for i in mgr.osdmap.osds.values() if i.up) >= 4

            await wait_until(four_up, 5.0, "orchestrated OSDs boot")
            ps = orch.ps()
            assert sum(1 for d in ps if d["daemon_type"] == "osd"
                       and d["status"] == "running") >= 4
            assert len(orch.device_ls()) >= 4
            assert orch.events  # scaling recorded
            await mgr.stop()
            await stop_cluster(mons, osds + spawned)

        asyncio.run(run())


class TestPoolQuota:
    def test_quota_full_flag_bounces_writes(self):
        """`osd pool set-quota` + the mgr digest: exceeding the quota
        flips FLAG_FULL_QUOTA via paxos and client writes bounce with
        -EDQUOT until the quota is raised (OSDMonitor pool-full loop)."""

        async def run():
            from ceph_tpu.client.rados import RadosError
            from ceph_tpu.osd.osdmap import FLAG_FULL_QUOTA

            monmap, mons, osds = await start_cluster(1, 3)
            mgr = await start_mgr(monmap)
            await mgr.wait_for_active()
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("qp", "replicated", size=2, pg_num=2)
            rv, rs, _ = await client.mon_command(
                {"prefix": "osd pool set-quota", "pool": "qp",
                 "field": "max_objects", "val": "2"}
            )
            assert rv == 0, rs
            io = await client.open_ioctx("qp")
            await io.write_full("a", b"1")
            await io.write_full("b", b"2")

            def pool_full():
                p = client.objecter.osdmap.get_pool("qp")
                return p is not None and bool(p.flags & FLAG_FULL_QUOTA)

            await wait_until(pool_full, 15.0, "quota-full flag reaching client")
            with pytest.raises(RadosError) as ei:
                await io.write_full("c", b"3")
            assert ei.value.errno == -122  # EDQUOT
            # reads still work on a full pool
            assert await io.read("a") == b"1"
            # raising the quota unfulls and writes resume
            rv, _, _ = await client.mon_command(
                {"prefix": "osd pool set-quota", "pool": "qp",
                 "field": "max_objects", "val": "100"}
            )
            assert rv == 0
            await wait_until(lambda: not pool_full(), 15.0, "unfull")
            await io.write_full("c", b"3")
            await client.shutdown()
            await mgr.stop()
            await stop_cluster(mons, osds)

        asyncio.run(run())
