"""Seed-fixed chaos smoke in tier-1 (ISSUE 7 acceptance): a real
mon+mgr+OSD cluster under mixed load survives socket faults, shard-read
EIO bursts, a gray OSD (ISSUE 17: one daemon's shard reads delayed ~50x
— hedged reads bound client p99, the laggy detector raises and clears
OSD_SLOW_PEER on exactly the victim), device-launch failures (host
fallback), a deep scrub under
client load with planted shard corruption (ISSUE 9: detected via
aggregated TPU verify launches, client p99 inside the QoS bound), an
OSD flap, a whole-OSD recovery storm (ISSUE 15: kill + dampened
auto-out + wave-batched rebuild under load with simultaneous
rebuild-time and p99 bounds), and a flapping-OSD phase (mon dampening
keeps the map stable while a genuinely dead OSD still rebuilds) —
converging to all-PGs-clean with ZERO lost writes and health clear of
SLOW_OPS / TPU_BACKEND_DEGRADED.

The full-size variant lives in `python -m ceph_tpu.tools.chaos`; this is
the `--smoke` configuration run in-process so tier-1 exercises the same
code path the operator harness does."""

from ceph_tpu.tools.chaos import run_chaos


class TestChaosSmoke:
    def test_smoke_converges_with_zero_lost_writes(self):
        report = run_chaos(seed=0xC405, smoke=True)
        assert report["converged"], report
        assert report["lost_writes"] == 0, report
        # every chaos phase actually ran
        assert len(report["events"]) == 13, report["events"]
        # ISSUE 17: the gray-OSD phase — one OSD's shard reads delayed
        # ~50x while its heartbeats stayed on time.  Hedged/re-planned
        # reads kept client p99 under the injected delay, the victim
        # (and only the victim) raised OSD_SLOW_PEER and cleared after
        # the delay lifted (asserted inside the phase), hedge spend
        # stayed within the token-bucket budget, and the healthy
        # control window hedged ~never
        assert report["gray_p99_ms"] is not None, report
        assert 0.0 < report["gray_p99_ms"] <= 2000.0, report
        assert report["gray_p99_ms"] < report["gray_delay_ms"], report
        assert report["gray_hedges"] >= 1, report
        assert report["gray_hedge_wins"] >= 1, report
        assert 0.0 < report["hedge_rate"], report
        assert report["control_hedges"] <= 2, report
        assert report["gray_victim"] >= 0, report
        assert report["gray_reads"] >= 1, report
        # ISSUE 10: the mixed-load phase attributed the load per pool
        # (windowed p99 keys ride the report for the bench fold), held
        # the SLO burn rate under bound, and kept trace retention
        # inside the token-bucket budget while complaint-age ops were
        # always retained (the bound assertions live inside the phase —
        # a violation fails the run, not just this check)
        assert "slo_worst_burn_rate" in report, report
        assert report["slo_worst_burn_rate"] <= 1.0, report
        assert "pool_p99_ms" in report and report["pool_p99_ms"], report
        ts = report["trace_sampling"]
        assert ts["kept_tail"] >= 1, report
        assert ts["unsampled"] >= 1, report
        assert ts["retained_spans"] >= 1, report
        # the launch-fault phase really drove the host fallback
        assert report["degraded_entered"], report
        assert report["fallback_launches"] >= 1, report
        # ISSUE 11: the pipelined-wedge phase armed launch faults while
        # depth>1 launches were in flight — every ticket recovered
        # byte-identically (asserted inside the phase), the ring
        # provably ran deeper than one launch, and the donation pool
        # never recycled a live buffer
        assert report["pipeline_wedge_tickets"] >= 4, report
        assert report["pipeline_max_inflight_depth"] >= 2, report
        assert report["donation_recycled_live"] == 0, report
        # 4 launches through a depth-2 ring MUST overflow it: a zero here
        # means _drain_pipeline silently stopped bounding the ring
        assert report["pipeline_drains"] >= 1, report
        # ISSUE 20: the offload-fallback phase armed launch faults while
        # the csum and compressor services had launches in flight under
        # mixed load — directly-submitted tickets matched the host
        # oracle and compressed blobs round-tripped (asserted inside the
        # phase), the csums BlueStore actually STORED under fire equal
        # utils/crc32c of the stored form, both services really fell
        # back at least once, the offload_inflight mempool drained to
        # zero, and client p99 stayed inside the bound
        assert report["offload_csum_launches"] >= 1, report
        assert report["offload_csum_fallbacks"] >= 1, report
        assert report["offload_compress_fallbacks"] >= 1, report
        assert report["offload_stored_blocks"] >= 8, report
        assert report["offload_leaked_bytes"] == 0, report
        assert 0.0 <= report["offload_p99_ms"] <= 2000.0, report
        # ISSUE 9: the deep-scrub-under-load phase detected the planted
        # corruption through aggregated device verify launches (fewer
        # launches than objects = one launch covered many), and client
        # writes stayed inside the QoS bound while the scrub ran (the
        # bound itself is asserted inside the phase — a violation fails
        # the run, not just this check)
        assert report["scrub_errors_detected"] >= 1, report
        assert report["verify_launches"] >= 1, report
        assert report["verify_launches"] < report["scrub_objects"], report
        assert report["scrub_p99_ms"] >= 0.0, report
        # ISSUE 13: the HBM mempool ledger metered the run — a nonzero
        # peak (launches held device memory) and ZERO leaked bytes once
        # the pipelines drained (also asserted inside the run; these
        # keys are what bench folds alongside the throughput numbers)
        assert report["hbm_peak_bytes"] > 0, report
        assert report["hbm_leaked_bytes"] == 0, report
        # ISSUE 12: the whole run executed under dynamic lockdep — zero
        # lock-order violations across the concurrent aggregator/
        # scheduler/pipeline/cache stack, and the observed ordering
        # graph rides the report (non-empty: instrumented locks engaged)
        assert report["lockdep_violations"] == 0, report
        assert report["lockdep_graph"], report
        # ISSUE 14: the metrics-history module sampled real MMgrReports
        # the whole run with trend windows short enough to genuinely
        # evaluate — a healthy converged run keeps every trend sentinel
        # quiet (also asserted inside the run), and the store's
        # fixed-memory meta-stats ride the report
        assert report["history_sentinels_fired"] == 0, report
        assert report["history_sentinels_active"] == [], report
        assert report["history_store"]["series"] >= 1, report
        assert report["history_store"]["bytes"] > 0, report
        # ...and the perf_compare regressions slice folded into the
        # tracked JSON (no committed chaos baselines yet, so the slice
        # documents the comparison rather than flagging)
        assert "regressions" in report, report
        assert "flagged" in report["regressions"], report
        # ISSUE 15: the recovery-storm phase's keys are present and
        # bounded — the dead OSD rebuilt via wave-batched decode
        # launches (launches < objects recovered, witnessed by flight
        # records) inside the rebuild-time bound while client p99 held
        # (both bounds also asserted inside the phase)
        assert report["rebuild_seconds"] > 0.0, report
        assert report["rebuild_seconds"] <= 30.0, report
        assert report["storm_p99_ms"] >= 0.0, report
        assert report["storm_p99_ms"] <= 2000.0, report
        assert report["storm_waves"] >= 1, report
        assert report["storm_wave_flight_records"] >= 1, report
        assert report["storm_objects"] >= 5, report
        assert (
            report["storm_decode_launches"] < report["storm_objects"]
        ), report
        # ...and the flap-dampening phase: zero auto-outs while the OSD
        # bounced, markdown history retained, the dampened grace grew,
        # and the genuinely dead flapper still got outed (later) so its
        # data rebuilt
        assert report["flap_auto_outs"] == 0, report
        assert report["flap_markdowns"] >= 2, report
        assert report["flap_grace_sec"] >= 4.0, report
        assert report["flap_dead_out_wait_sec"] >= 3.0, report
        # ISSUE 16: the cluster-event timeline — the committed clog tail
        # was non-empty, carried no unexpected ERR entries (asserted
        # inside the run), every armed fault point audited, and BOTH
        # failure stories read straight out of `log last` in order
        assert report["clog_entries"] >= 1, report
        assert report["clog_errors"] >= 1, report  # planted corruption
        assert report["audit_entries"] >= 1, report
        assert report["storm_timeline"] == [
            "down", "out", "storm_engaged", "wave", "storm_complete",
        ], report
        assert report["flap_timeline"] == ["down", "dampened", "out"], report
        # health settled: no stuck SLOW_OPS, no lingering degraded check
        assert "SLOW_OPS" not in report["health_checks"], report
        assert "TPU_BACKEND_DEGRADED" not in report["health_checks"], report
        # machine-readable metrics came from the histogram substrate.
        # Both p99 keys are None when the tail spilled past the
        # histogram range (kept JSON-valid), so guard before comparing.
        assert report["p99_op_latency_sec"] is not None, report
        assert report["p99_op_latency_sec"] > 0.0, report
        assert report["recovery_decode_launches"] >= 0
        # ISSUE 8: the tracked-metric keys ROADMAP item 4 promotes into
        # PROGRESS/bench reporting ride the chaos JSON
        assert report.get("chaos_p99_ms") is not None, report
        assert report["chaos_p99_ms"] > 0.0, report
        assert "recovery_occupancy" in report, report
        assert report["recovery_occupancy"] >= 0.0, report
        # ...alongside a flight-recorder summary (launches + occupancy)
        assert "flight" in report, report
        assert report["flight"]["launches"] >= 1, report
        assert 0.0 <= report["flight"]["occupancy"] <= 1.0, report
        assert "progress_events_seen" in report, report
