"""Gray-failure tolerance tests (ISSUE 17): adaptive hedged EC reads,
end-to-end deadline propagation, and the late-loser RTT ledger.

The unit tier drives ECBackend through test_ec_backend's pumped-queue
cluster — there is no event loop, so hedge timers are inert and the
hedge check fires explicitly (`_hedge_fire`), which is exactly what
makes the race windows deterministic: a "slow" peer is one whose
messages the pump holds back.  The integration tier boots a real
mon+OSD cluster to witness admission-time deadline shedding and the
laggy-peer detector end to end.
"""

import asyncio
import time

import pytest

from ceph_tpu.common.errs import EIO
from ceph_tpu.osd.osdmap import PG_NONE

from test_ec_backend import Cluster, ec_pool, payload


def attach_perf(c: Cluster) -> dict:
    """Wire every listener's perf_inc hook into one shared counter dict
    (the harness Listener has none; ECBackend drops counts without it)."""
    counters: dict[str, int] = {}

    def inc(name, n=1):
        counters[name] = counters.get(name, 0) + n

    for listener in c.listeners:
        listener.perf_inc = inc
    return counters


def start_read(c: Cluster, oid: str, length: int, deadline: float = 0.0) -> dict:
    """Queue a read WITHOUT pumping; the caller owns message delivery."""
    out: dict = {}
    c.primary.objects_read_and_reconstruct(
        {oid: [(0, length)]}, lambda res: out.update(res), deadline=deadline
    )
    return out


def pump_except(c: Cluster, holdback: set[int]) -> list:
    """Deliver queued messages, HOLDING anything addressed to an OSD in
    `holdback` — the pump-level model of a slow peer.  Returns the held
    (osd, msg) pairs so the test can deliver the late replies later."""
    held = []
    steps = 0
    while True:
        for b in c.backends:
            b.flush_encodes()
        if not c.queue:
            break
        osd, msg = c.queue.pop(0)
        if osd in holdback:
            held.append((osd, msg))
            continue
        if osd == PG_NONE or not (0 <= osd < len(c.backends)):
            continue
        c.backends[osd].handle_message(msg)
        steps += 1
        assert steps < 100000, "message storm"
    return held


def deliver(c: Cluster, held: list) -> None:
    """Hand held messages to their targets, then drain the fallout."""
    for osd, msg in held:
        c.backends[osd].handle_message(msg)
    c.pump()


class TestHedgedEcReads:
    """Tentpole tier 2: the hedge fires on a slow outstanding sub-read,
    first-k-wins, the budget gates spend, and late losers are reaped
    into the RTT ledger instead of double-counting."""

    def _slow_shard1_read(self, k=2, m=2):
        pool, profiles = ec_pool(k, m)
        c = Cluster(pool, profiles)
        data = payload(pool.stripe_width)
        c.write("obj", 0, data)
        counters = attach_perf(c)
        out = start_read(c, "obj", len(data))
        held = pump_except(c, {1})  # shard 1's source answers... never
        assert not out, "read completed without shard 1"
        prim = c.primary
        ((tid, rop),) = prim.read_ops.items()
        # age shard 1's sub-read past any threshold (floor is 10 ms)
        rop.send_ts[1] -= 1.0
        return c, prim, tid, rop, out, held, counters, data

    def test_hedge_winner_first_k_wins_byte_identical(self):
        c, prim, tid, rop, out, held, counters, data = self._slow_shard1_read()
        prim._hedge_fire(tid)
        assert counters.get("ec_hedge_reads") == 1
        assert rop.hedge_shards and rop.hedge_shards <= {2, 3}
        # the speculative read answers; shard 1 still dark — first k win
        pump_except(c, {1})
        assert out["obj"][0] == 0
        assert out["obj"][1][0] == data
        assert counters.get("ec_hedge_wins") == 1
        assert not prim.read_ops  # retired; a loser reply cannot re-enter

    def test_late_loser_feeds_rtt_ledger_then_is_reaped(self):
        c, prim, tid, rop, out, held, counters, data = self._slow_shard1_read()
        rtts = []
        c.listeners[0].note_peer_rtt = lambda peer, rtt: rtts.append((peer, rtt))
        prim._hedge_fire(tid)
        pump_except(c, {1})
        assert out["obj"][0] == 0
        # the op retired with shard 1 outstanding: the ledger remembers
        # where that sub-read went so the eventual reply is attributable
        assert tid in prim._late_sends
        before = counters.get("ec_hedge_wins", 0)
        deliver(c, held)  # the slow peer finally answers
        assert tid not in prim._late_sends
        # the late reply landed ONE rtt sample >= the 1 s we aged it by
        # (hedging must not mask the slowness the laggy detector needs)
        assert any(peer == 1 and rtt >= 1.0 for peer, rtt in rtts), rtts
        assert prim._peer_ewma[1] >= 0.2  # EWMA pulled up by the sample
        # ...and nothing else: no double completion, no second hedge win
        assert counters.get("ec_hedge_wins", 0) == before
        assert out["obj"][1][0] == data

    def test_budget_exhaustion_means_plain_waiting(self):
        c, prim, tid, rop, out, held, counters, data = self._slow_shard1_read()
        prim._hedge_tokens = 0.0  # bucket drained (after earlier earns)
        prim._hedge_fire(tid)
        assert counters.get("ec_hedge_denied") == 1
        assert not rop.hedge_shards
        assert not c.queue, "denied hedge must send nothing"
        assert tid in prim.read_ops  # still waiting, not failed
        deliver(c, held)  # the slow reply eventually arrives
        assert out["obj"][0] == 0
        assert out["obj"][1][0] == data
        assert "ec_hedge_wins" not in counters

    def test_hedge_never_spends_on_doomed_read(self):
        c, prim, tid, rop, out, held, counters, data = self._slow_shard1_read()
        rop.deadline = time.monotonic() - 0.01  # budget spent in flight
        tokens = prim._hedge_tokens
        prim._hedge_fire(tid)
        assert not rop.hedge_shards
        assert prim._hedge_tokens == tokens
        assert "ec_hedge_reads" not in counters

    def test_hedge_with_eio_peer_same_readop(self):
        """The escalation matrix: one peer answers EIO while another is
        slow — the hedge and the error path compose in one ReadOp and
        the decode still comes back byte-identical."""
        from ceph_tpu.common.fault_injector import global_injector

        pool, profiles = ec_pool(2, 2)
        c = Cluster(pool, profiles)
        data = payload(pool.stripe_width)
        c.write("obj", 0, data)
        counters = attach_perf(c)
        inj = global_injector()
        inj.inject("ec.sub_read", EIO, hits=1)
        try:
            out = start_read(c, "obj", len(data))
            # shard 0's sub-read (queued first) eats the EIO; shard 1 held
            held = pump_except(c, {1})
        finally:
            inj.clear("ec.sub_read")
        prim = c.primary
        ((tid, rop),) = prim.read_ops.items()
        assert 0 in rop.errors, "shard 0 should have answered EIO"
        rop.send_ts[1] -= 1.0
        prim._hedge_fire(tid)
        assert counters.get("ec_hedge_reads") == 1
        pump_except(c, {1})
        # one good shard (the hedge) is short of k=2: still waiting
        assert not out and tid in prim.read_ops
        deliver(c, held)  # the slow peer completes the decode set
        assert out["obj"][0] == 0
        assert out["obj"][1][0] == data
        assert counters.get("ec_hedge_wins") == 1

    def test_ledger_prunes_stale_entries(self):
        pool, profiles = ec_pool(2, 1)
        c = Cluster(pool, profiles)
        prim = c.primary
        prim._late_sends[999] = (
            time.monotonic() - prim.LATE_SEND_TTL - 1.0,
            {1: (1, 0.0)},
        )
        prim._prune_late_sends()
        assert 999 not in prim._late_sends


class TestLaggyReadPlanning:
    """Tentpole tier 3, primary side: reads route around peers the
    heartbeat subsystem flags laggy, hedging preemptively when a laggy
    source is unavoidable."""

    def test_laggy_source_deprioritized_in_plan(self):
        pool, profiles = ec_pool(2, 2)
        c = Cluster(pool, profiles)
        data = payload(pool.stripe_width)
        c.write("obj", 0, data)
        c.listeners[0].laggy_peers = lambda: {1}
        out = start_read(c, "obj", len(data))
        ((_tid, rop),) = c.primary.read_ops.items()
        assert 1 not in set(rop.sources.values()), rop.sources
        c.pump()
        assert out["obj"][0] == 0
        assert out["obj"][1][0] == data

    def test_unavoidable_laggy_source_hedged_preemptively(self):
        pool, profiles = ec_pool(2, 2)
        c = Cluster(pool, profiles)
        data = payload(pool.stripe_width)
        c.write("obj", 0, data)
        counters = attach_perf(c)
        # shard 0 is gone and every source of a clean stripe is laggy:
        # the plan cannot avoid laggy peers, so it hedges up front
        c.missing["obj"] = {0}
        c.listeners[0].laggy_peers = lambda: {1, 2}
        out = start_read(c, "obj", len(data))
        ((_tid, rop),) = c.primary.read_ops.items()
        assert rop.hedge_shards, "expected a preemptive hedge"
        assert counters.get("ec_hedge_reads") == 1
        c.pump()
        assert out["obj"][0] == 0
        assert out["obj"][1][0] == data
        # in this harness the "laggy" peers answer instantly, so the
        # minimum set completes first and the hedge reply is a late
        # loser — reaped through the ledger, never double-counted
        assert not c.primary.read_ops
        assert not c.primary._late_sends
        assert len(out) == 1


class TestSubReadDeadlineShed:
    """Tentpole tier 1, shard side: an expired inherited deadline sheds
    the sub-read at the shard — counted, -ETIMEDOUT, store untouched —
    releasing the source instead of pinning it for a corpse."""

    def test_expired_subreads_shed_everywhere_and_fail(self):
        pool, profiles = ec_pool(2, 1)
        c = Cluster(pool, profiles)
        data = payload(pool.stripe_width)
        c.write("obj", 0, data)
        counters = attach_perf(c)
        out = start_read(c, "obj", len(data), deadline=time.monotonic() - 0.1)
        c.pump()
        # every source shed (k data shards + the escalation try): the
        # read fails without any shard touching its store
        assert counters.get("subread_deadline_shed") == 3
        assert out["obj"][0] == -EIO
        # replies carried -ETIMEDOUT per object, recorded as errors
        # (nothing left outstanding — the sources were released)
        assert not c.primary.read_ops

    def test_live_deadline_reads_normally(self):
        pool, profiles = ec_pool(2, 1)
        c = Cluster(pool, profiles)
        data = payload(pool.stripe_width)
        c.write("obj", 0, data)
        counters = attach_perf(c)
        out = start_read(c, "obj", len(data), deadline=time.monotonic() + 60.0)
        c.pump()
        assert out["obj"][0] == 0
        assert out["obj"][1][0] == data
        assert "subread_deadline_shed" not in counters


class TestAdmissionShedIntegration:
    """Tentpole tier 1 end to end: a real OSD sheds an op whose envelope
    deadline expired before dispatch — counted, -ETIMEDOUT mapped back
    to the client's TimeoutError, excluded from io-accounting."""

    def test_expired_op_shed_at_admission(self):
        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.msg.messages import MOSDOp

            from test_cluster import start_cluster, stop_cluster

            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("grayp", "replicated", size=3, pg_num=2)
            io = await client.open_ioctx("grayp")
            await io.write_full("obj", b"x" * 4096)
            assert await io.read("obj") == b"x" * 4096

            def accounted_reads():
                return sum(
                    cls.get("read", {}).get("ops", 0)
                    for o in osds
                    for cls in o.io_accountant.dump_pools().values()
                )

            before_acct = accounted_reads()
            # queue wait ate the budget: every op leaves the client with
            # its deadline already in the past
            ob = client.objecter
            orig_send = ob.msgr.send_to

            async def stale_send(addr, msg):
                if isinstance(msg, MOSDOp):
                    msg.deadline = time.monotonic() - 0.05
                await orig_send(addr, msg)

            ob.msgr.send_to = stale_send
            try:
                with pytest.raises(TimeoutError, match="shed at osd admission"):
                    await io.read("obj")
            finally:
                ob.msgr.send_to = orig_send
            shed = sum(o.perf.get("op_deadline_shed") for o in osds)
            assert shed >= 1, "no OSD counted an admission shed"
            # never executed -> never accounted (like the -EAGAIN bounce)
            assert accounted_reads() == before_acct
            # the object is untouched and serves normally afterwards
            assert await io.read("obj") == b"x" * 4096
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestLaggyDetectorIntegration:
    """Tentpole tier 3 end to end: inflated peer RTT flips the detector
    (with hysteresis), feeds the per-peer histograms satellite, and
    surfaces OSD_SLOW_PEER at the mon — clearing once the peer recovers."""

    def test_rtt_inflation_detects_surfaces_and_clears(self):
        async def run():
            from test_cluster import start_cluster, stop_cluster, wait_until

            monmap, mons, osds = await start_cluster(1, 4)
            o = osds[0]
            # a healthy mesh: everyone answers in ~1 ms but peer 1
            for _ in range(30):
                for peer in (1, 2, 3):
                    o._note_peer_rtt(peer, 0.5 if peer == 1 else 0.001)
            o._laggy_check(time.monotonic())
            assert o.laggy_peers() == {1}
            # satellite (c): the sample stream filled the aggregate AND
            # the lazily-declared per-peer RTT histograms on perf dump
            dump = o.perf.dump()
            assert "histogram" in dump["osd_heartbeat_rtt"]
            for peer in (1, 2, 3):
                hist = dump[f"osd_heartbeat_rtt_osd_{peer}"]["histogram"]
                assert hist["count"] >= 30, hist
            # the laggy report reaches the mon: OSD_SLOW_PEER with the
            # victim named in the detail, and the victim stays up/in.
            # The poll keeps feeding slow samples so the background
            # heartbeat loop's real (fast) pings can't decay the EWMA
            # under the exit threshold mid-wait.
            def still_slow():
                o._note_peer_rtt(1, 0.5)
                o._laggy_check(time.monotonic())
                return 1 in mons[0].osdmon.slow_peers()

            await wait_until(still_slow, 5.0, "mon slow_peers carries osd.1")
            checks, _detail = mons[0].health_checks()
            assert "OSD_SLOW_PEER" in checks
            assert "osd.1" in checks["OSD_SLOW_PEER"]
            assert mons[0].osdmon.osdmap.osds[1].up
            # recovery: fast samples decay the EWMA under the exit
            # threshold (hysteresis at enter/2) and the one-shot
            # laggy=2 retires the mon-side evidence
            for _ in range(200):
                o._note_peer_rtt(1, 0.001)
            o._laggy_check(time.monotonic())
            assert o.laggy_peers() == set()

            def retired():
                checks, _ = mons[0].health_checks()
                return (
                    1 not in mons[0].osdmon.slow_peers()
                    and "OSD_SLOW_PEER" not in checks
                )

            await wait_until(retired, 5.0, "OSD_SLOW_PEER retired")
            await stop_cluster(mons, osds)

        asyncio.run(run())
