"""RBD journaling + mirroring tests (src/journal + rbd_mirror coverage):
write-ahead journal records, replayer convergence across pools, torn-tail
tolerance, incremental positions, promote/demote."""

import asyncio

import pytest

from ceph_tpu.client import Rados, RadosError
from ceph_tpu.rbd import (
    RBD,
    JournaledImage,
    MirrorDaemon,
    RbdError,
    enable_journaling,
    promote,
)
from ceph_tpu.rbd.mirror import iter_events, journal_oid, pack_event

from test_cluster import start_cluster, stop_cluster, wait_until


async def _two_sites():
    monmap, mons, osds = await start_cluster(1, 3)
    rados = Rados(monmap)
    await rados.connect()
    await rados.pool_create("site_a", "replicated", size=2, pg_num=2)
    await rados.pool_create("site_b", "replicated", size=2, pg_num=2)
    a = await rados.open_ioctx("site_a")
    b = await rados.open_ioctx("site_b")
    return monmap, mons, osds, rados, a, b


class TestJournalFormat:
    def test_torn_tail_ignored(self):
        blob = pack_event(1, 1, 0, b"full") + pack_event(2, 1, 4, b"also")
        events = list(iter_events(blob + blob[: len(blob) // 3]))
        assert [e[0] for e in events] == [1, 2]  # torn third record dropped


class TestMirroring:
    def test_replay_converges_and_is_incremental(self):
        async def run():
            monmap, mons, osds, rados, a, b = await _two_sites()
            rbd_a = RBD(a)
            await rbd_a.create("vol", 1 << 20, order=16)
            await enable_journaling(rbd_a, "vol")
            img = await JournaledImage.open(rbd_a, "vol")

            await img.write(0, b"first block " * 100)
            await img.write(200_000, b"far away bytes")

            mirror = MirrorDaemon(a, b)
            # bootstrap full-syncs the current bytes and records the
            # position — the pre-existing events are covered by the copy
            applied = await mirror.sync_once()
            assert applied["vol"] == 0

            rbd_b = RBD(b)
            img_b = await rbd_b.open("vol")
            assert img_b.size == img.image.size
            assert not img_b.header.get("primary", True)  # replica
            assert await img_b.read(0, 1200) == (b"first block " * 100)
            assert await img_b.read(200_000, 14) == b"far away bytes"

            # incremental: only NEW events replay on the next pass
            await img.write(5, b"update")
            assert (await mirror.sync_once())["vol"] == 1
            assert (await mirror.sync_once())["vol"] == 0  # nothing new
            img_b = await rbd_b.open("vol")
            assert (await img_b.read(0, 11)) == b"firstupdate"[:11]

            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_resize_and_snapshots_replicate(self):
        async def run():
            monmap, mons, osds, rados, a, b = await _two_sites()
            rbd_a = RBD(a)
            await rbd_a.create("vol", 1 << 18, order=16)
            await enable_journaling(rbd_a, "vol")
            img = await JournaledImage.open(rbd_a, "vol")

            v1 = b"v1" * 3000
            await img.write(0, v1)
            await img.snap_create("s1")
            await img.write(0, b"v2" * 3000)
            await img.resize(1 << 19)

            mirror = MirrorDaemon(a, b)
            await mirror.sync_once()

            img_b = await RBD(b).open("vol")
            assert img_b.size == 1 << 19
            assert await img_b.read(0, 6000) == b"v2" * 3000
            # the snapshot exists on the replica with the PRE-s1 content
            assert await img_b.read(0, 6000, snap_name="s1") == v1

            await img.snap_remove("s1")
            await mirror.sync_once()
            img_b = await RBD(b).open("vol")
            assert await img_b.snap_list() == []

            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_write_ahead_crash_window_converges(self):
        """An event journaled but never applied to the data objects
        (crash between append and write) applies on the primary's next
        open (librbd's journal replay) and reaches the replica — the
        write-ahead property the journal exists for."""

        async def run():
            monmap, mons, osds, rados, a, b = await _two_sites()
            rbd_a = RBD(a)
            await rbd_a.create("vol", 1 << 18, order=16)
            await enable_journaling(rbd_a, "vol")
            img = await JournaledImage.open(rbd_a, "vol")
            await img.write(0, b"applied everywhere")
            # simulate the crash window: journal the event, skip the data
            await img._append(1, 100, b"journal-only bytes")
            assert (await img.read(100, 18)) != b"journal-only bytes"

            # primary crash recovery: reopen replays its own journal
            img2 = await JournaledImage.open(rbd_a, "vol")
            assert await img2.read(100, 18) == b"journal-only bytes"

            mirror = MirrorDaemon(a, b)
            await mirror.sync_once()
            img_b = await RBD(b).open("vol")
            assert await img_b.read(100, 18) == b"journal-only bytes"

            # the same window AFTER bootstrap replays event-wise
            await img2._append(1, 300, b"late crash bytes!!")
            await mirror.sync_once()
            img_b = await RBD(b).open("vol")
            assert await img_b.read(300, 18) == b"late crash bytes!!"

            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_demote_refuses_writes_promote_restores(self):
        async def run():
            monmap, mons, osds, rados, a, b = await _two_sites()
            rbd_a = RBD(a)
            await rbd_a.create("vol", 1 << 18, order=16)
            await enable_journaling(rbd_a, "vol")
            img = await JournaledImage.open(rbd_a, "vol")
            await img.write(0, b"before failover")
            mirror = MirrorDaemon(a, b)
            await mirror.sync_once()

            await img.demote()
            with pytest.raises(RbdError):
                await img.write(0, b"must fail")

            # failover: promote the replica, write there, mirror back
            await promote(RBD(b), "vol")
            img_b = await JournaledImage.open(RBD(b), "vol")
            await img_b.write(0, b"after failover!")
            back = MirrorDaemon(b, a)
            await back.sync_once()
            img_a = await RBD(a).open("vol")
            assert await img_a.read(0, 15) == b"after failover!"

            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_bootstrap_full_syncs_pre_journal_bytes(self):
        """Data written BEFORE journaling was enabled exists only in the
        data objects; bootstrap must copy it (ImageReplayer image sync)."""

        async def run():
            monmap, mons, osds, rados, a, b = await _two_sites()
            rbd_a = RBD(a)
            await rbd_a.create("vol", 1 << 18, order=16)
            img_plain = await rbd_a.open("vol")
            await img_plain.write(0, b"pre-journal history")

            await enable_journaling(rbd_a, "vol")
            img = await JournaledImage.open(rbd_a, "vol")
            await img.write(50, b"post-journal")

            mirror = MirrorDaemon(a, b)
            await mirror.sync_once()
            img_b = await RBD(b).open("vol")
            assert await img_b.read(0, 19) == b"pre-journal history"
            assert await img_b.read(50, 12) == b"post-journal"

            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_promoted_replica_not_clobbered_by_stale_source(self):
        async def run():
            monmap, mons, osds, rados, a, b = await _two_sites()
            rbd_a = RBD(a)
            await rbd_a.create("vol", 1 << 18, order=16)
            await enable_journaling(rbd_a, "vol")
            img = await JournaledImage.open(rbd_a, "vol")
            await img.write(0, b"old-site data!")
            mirror = MirrorDaemon(a, b)
            await mirror.sync_once()

            # failover: replica promoted, gets new writes
            await promote(RBD(b), "vol")
            img_b = await JournaledImage.open(RBD(b), "vol")
            await img_b.write(0, b"new-site truth")
            # a stale mirror tick from the old direction must be a no-op
            await img.image._load_header()  # old primary still primary
            await img.write(0, b"late old data")
            assert (await mirror.sync_once())["vol"] == 0
            img_b2 = await RBD(b).open("vol")
            assert await img_b2.read(0, 14) == b"new-site truth"

            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_journal_trims_after_peer_commit(self):
        async def run():
            from ceph_tpu.rbd.mirror import journal_oid

            monmap, mons, osds, rados, a, b = await _two_sites()
            rbd_a = RBD(a)
            await rbd_a.create("vol", 1 << 18, order=16)
            await enable_journaling(rbd_a, "vol")
            img = await JournaledImage.open(rbd_a, "vol")
            for i in range(4):
                await img.write(i * 100, b"x" * 50)
            mirror = MirrorDaemon(a, b)
            await mirror.sync_once()

            before = len(await a.read(journal_oid(img.image.id)))
            await img.write(0, b"after commit")  # append trims first
            after = len(await a.read(journal_oid(img.image.id)))
            assert after < before  # old committed events reclaimed
            # and the replayer still converges with monotonic sequences
            await mirror.sync_once()
            img_b = await RBD(b).open("vol")
            assert await img_b.read(0, 12) == b"after commit"

            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_rejected_write_never_journaled(self):
        async def run():
            from ceph_tpu.rbd.mirror import journal_oid

            monmap, mons, osds, rados, a, b = await _two_sites()
            rbd_a = RBD(a)
            await rbd_a.create("vol", 1 << 16, order=16)
            await enable_journaling(rbd_a, "vol")
            img = await JournaledImage.open(rbd_a, "vol")
            with pytest.raises(RbdError):
                await img.write((1 << 16) - 2, b"past the end")
            # the refused mutation is absent from the event stream: the
            # replica can never diverge by applying it
            try:
                blob = await a.read(journal_oid(img.image.id))
            except Exception:
                blob = b""
            assert blob == b""

            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_continuous_daemon_loop(self):
        async def run():
            monmap, mons, osds, rados, a, b = await _two_sites()
            rbd_a = RBD(a)
            await rbd_a.create("vol", 1 << 18, order=16)
            await enable_journaling(rbd_a, "vol")
            img = await JournaledImage.open(rbd_a, "vol")

            mirror = MirrorDaemon(a, b)
            task = asyncio.create_task(mirror.run(interval=0.05))
            await img.write(0, b"streamed")

            async def replicated():
                try:
                    return (await RBD(b).open("vol")) is not None and (
                        await (await RBD(b).open("vol")).read(0, 8)
                    ) == b"streamed"
                except Exception:
                    return False

            deadline = asyncio.get_event_loop().time() + 5
            while not await replicated():
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.05)
            mirror.stop()
            await asyncio.sleep(0.1)
            task.cancel()

            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestExclusiveLock:
    def test_ownership_contention_and_break(self):
        """librbd exclusive-lock over the lock object class: a second
        client cannot acquire an owned image; after the owner dies, the
        failover path breaks the stale hold and takes over (ManagedLock /
        `rbd lock rm`)."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.rbd.rbd import RBD, RbdError

            monmap, mons, osds = await start_cluster(1, 3)
            owner = Rados(monmap, name="client.owner")
            await owner.connect()
            await owner.pool_create("rbdl", "replicated", pg_num=4)
            oio = await owner.open_ioctx("rbdl")
            rbd = RBD(oio)
            await rbd.create("disk", 4 << 20)
            img = await rbd.open("disk")
            await img.lock_acquire(cookie="c-owner")

            taker = Rados(monmap, name="client.taker")
            await taker.connect()
            tio = await taker.open_ioctx("rbdl")
            timg = await RBD(tio).open("disk")
            with pytest.raises(RbdError):
                await timg.lock_acquire(cookie="c-taker")
            holders = await timg.lock_owners()
            assert len(holders) == 1
            # entity is the owner's per-instance identity (name + nonce)
            assert holders[0]["entity"] == owner.objecter.reqid_name
            assert holders[0]["cookie"] == "c-owner"
            assert holders[0]["description"] == "rbd image disk"
            # the owner "dies" (no unlock); failover breaks + acquires
            await owner.shutdown()
            await timg.break_lock(holders[0]["entity"], cookie="c-owner")
            await timg.lock_acquire(cookie="c-taker")
            assert (
                (await timg.lock_owners())[0]["entity"]
                == taker.objecter.reqid_name
            )
            await timg.lock_release(cookie="c-taker")
            await taker.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestFencedPromotion:
    def test_promote_fence_blocklists_zombie_lock_holder(self):
        """Promotion with fencing (the reference's rbd-mirror promote
        flow): every exclusive-lock holder of the promoted image is
        BLOCKLISTED before its lock breaks, so a zombie writer cannot
        land bytes after the takeover — even writes already in flight
        bounce at the OSD."""

        async def run():
            monmap, mons, osds, rados, a, b = await _two_sites()
            rbd_b = RBD(b)
            await rbd_b.create("vol", 1 << 18, order=16)
            # a zombie client grabs the image's exclusive lock and stalls
            zombie = Rados(monmap, name="client.zombie")
            await zombie.connect()
            zb = await zombie.open_ioctx("site_b")
            zimg = await RBD(zb).open("vol")
            await zimg.lock_acquire(cookie="z1")
            entity = zombie.objecter.reqid_name

            await promote(rbd_b, "vol", fence=True)
            # the lock is broken and the zombie fenced cluster-wide
            img = await rbd_b.open("vol")
            assert await img.lock_owners() == []
            assert img.header.get("primary") is True
            await wait_until(
                lambda: all(entity in o.osdmap.blocklist for o in osds),
                10.0,
                "fence reaching the OSDs",
            )
            with pytest.raises((RadosError, TimeoutError)):
                await zimg.write(0, b"zombie bytes")
            # the promoted side writes freely
            await img.write(0, b"new primary")
            assert await img.read(0, 11) == b"new primary"
            await zombie.shutdown()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())
