"""Non-regression corpus: today's chunk bytes are frozen in tests/corpus.

Mirror of the reference's corpus gate
(/root/reference/src/test/erasure-code/ceph_erasure_code_non_regression.cc +
qa/workunits/erasure-code/encode-decode-non-regression.sh): each config's
content file and per-chunk encodings are checked in; `check` re-encodes and
fails on any byte difference, then decodes 1- and 2-erasure cases.  Any
future change to matrix math, padding, or kernel layout that alters a chunk
byte fails here — the regression baseline VERDICT round 1 asked for.

Foreign-byte parity vs ISA-L's math is covered by tests/test_isal_golden.py
(an independent scalar re-derivation of ec_base, since no ISA-L build
exists in this image); this corpus pins the full chunk layout on top.
Regenerate deliberately with:
  python -m ceph_tpu.tools.ec_corpus --create --standard --base tests/corpus
"""

import os

import pytest

from ceph_tpu.tools.ec_corpus import STANDARD_CONFIGS, check, corpus_dir

BASE = os.path.join(os.path.dirname(__file__), "corpus")


@pytest.mark.parametrize(
    "plugin,stripe_width,profile",
    STANDARD_CONFIGS,
    ids=[
        f"{p}-{prof.get('technique', '')}-k{prof.get('k', '')}"
        for p, _, prof in STANDARD_CONFIGS
    ],
)
def test_corpus_check(plugin, stripe_width, profile):
    directory = corpus_dir(BASE, plugin, stripe_width, profile)
    assert os.path.isdir(directory), (
        f"corpus missing for {plugin} {profile}; regenerate with "
        "python -m ceph_tpu.tools.ec_corpus --create --standard --base tests/corpus"
    )
    assert check(BASE, plugin, stripe_width, dict(profile)) == 0


def test_corpus_detects_byte_change(tmp_path):
    # the gate actually gates: flip one byte in a stored chunk -> check fails
    from ceph_tpu.tools.ec_corpus import create

    plugin, stripe_width, profile = STANDARD_CONFIGS[0]
    assert create(str(tmp_path), plugin, stripe_width, dict(profile)) == 0
    d = corpus_dir(str(tmp_path), plugin, stripe_width, profile)
    path = os.path.join(d, "chunk.1")
    blob = bytearray(open(path, "rb").read())
    blob[7] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    assert check(str(tmp_path), plugin, stripe_width, dict(profile)) == 1
