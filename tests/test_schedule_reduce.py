"""Schedule-reduction contracts (ISSUE 11 satellite): the reduced plane
programs (CSE + polynomial-ring constructions, ops/packed_gf.py) must be
byte-identical to the independent gf/bitslice.py bit-matrix host oracle
for EVERY registry matrix family and every erasure pattern, and the
chosen schedule's op count must never exceed the naive tower schedule —
strictly below it for the RS(8,3) headline matrix (the tier-1 XOR-count
regression bound)."""

import itertools

import numpy as np
import pytest

from ceph_tpu.codec.registry import ErasureCodePluginRegistry
from ceph_tpu.gf import isa_decode_matrix, isa_rs_vandermonde_matrix
from ceph_tpu.gf.bitslice import expand_matrix, xor_matmul_host
from ceph_tpu.ops.packed_gf import (
    PackedPlan,
    best_program,
    cse_program,
    naive_program,
    packed_code_host,
    program_cost,
    ring_program,
    run_program_host,
)


def oracle(gfm: np.ndarray, data: np.ndarray) -> np.ndarray:
    """The INDEPENDENT host oracle: bitsliced GF(2) matmul over the
    expanded bit-matrix — shares no code with the plane programs."""
    bm = expand_matrix(gfm)
    return np.stack([xor_matmul_host(bm, data[s]) for s in range(len(data))])


def rand_data(k: int, seed: int, stripes: int = 3, L: int = 64) -> np.ndarray:
    return np.random.default_rng(seed).integers(
        0, 256, (stripes, k, L), dtype=np.uint8
    )


def registry_matrices() -> list[tuple[str, np.ndarray]]:
    """Every matrix family the codec registry ships: (label, (k+m, k)
    systematic distribution matrix) — RS, jerasure variants, SHEC, each
    LRC layer's local code, and CLAY's inner MDS."""
    r = ErasureCodePluginRegistry.instance()
    out: list[tuple[str, np.ndarray]] = []
    out.append(("rs_4_2", r.factory(
        "tpu", {"k": "4", "m": "2"}).distribution_matrix()))
    out.append(("rs_8_3", r.factory(
        "tpu", {"k": "8", "m": "3"}).distribution_matrix()))
    for technique in ("reed_sol_van", "cauchy_orig"):
        ec = r.factory(
            "jerasure", {"k": "4", "m": "2", "technique": technique}
        )
        out.append((f"jerasure_{technique}", ec.distribution_matrix()))
    out.append(("shec_6_3_2", r.factory(
        "shec", {"k": "6", "m": "3", "c": "2"}).distribution_matrix()))
    lrc = r.factory("lrc", {"k": "4", "m": "2", "l": "3"})
    for i, layer in enumerate(lrc.layers):
        out.append((f"lrc_layer{i}", layer.erasure_code.distribution_matrix()))
    clay = r.factory("clay", {"k": "4", "m": "2"})
    out.append(("clay_inner", clay._inner.distribution_matrix()))
    return out


class TestByteIdentityAcrossFamilies:
    @pytest.mark.parametrize(
        "label,dist", registry_matrices(), ids=lambda v: v if isinstance(v, str) else ""
    )
    def test_encode_programs_match_bitmatrix_oracle(self, label, dist):
        k = dist.shape[1]
        gfm = dist[k:]
        data = rand_data(k, seed=sum(label.encode()) & 0xFFFF)
        want = oracle(gfm, data)
        for name, prog in (
            ("naive", naive_program(gfm)),
            ("cse", cse_program(gfm)),
            ("ring", ring_program(gfm)),
            ("best", best_program(gfm)),
        ):
            got = run_program_host(prog, data)
            assert np.array_equal(got, want), (label, name)
        # the packed_code_host oracle (the DEGRADED-mode fallback path)
        # and the compiled device plan agree too
        assert np.array_equal(packed_code_host(gfm, data), want), label
        assert np.array_equal(np.asarray(PackedPlan(gfm)(data)), want), label

    @pytest.mark.parametrize(
        "label,dist", registry_matrices(), ids=lambda v: v if isinstance(v, str) else ""
    )
    def test_reduced_cost_never_exceeds_naive(self, label, dist):
        """The tier-1 XOR-count regression bound: for every family the
        chosen schedule is at most the naive tower schedule's op count
        (CSE only factors shared pairs, ring only wins when cheaper)."""
        k = dist.shape[1]
        gfm = dist[k:]
        naive = program_cost(naive_program(gfm))
        assert program_cost(cse_program(gfm)) <= naive, label
        assert program_cost(best_program(gfm)) <= naive, label


class TestErasurePatterns:
    """Decode matrices for every erasure pattern ride the same reduced
    schedules: byte-identity + the cost bound per inverted matrix."""

    @pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
    def test_all_patterns_byte_identical_and_bounded(self, k, m):
        dist = isa_rs_vandermonde_matrix(k, m)
        n = k + m
        for r in range(1, m + 1):
            for pattern in itertools.combinations(range(n), r):
                plan = isa_decode_matrix(dist, list(pattern), k)
                assert plan is not None, pattern
                c, _idx = plan
                data = rand_data(k, seed=sum(pattern) + r, stripes=2)
                want = oracle(c, data)
                best = best_program(c)
                assert np.array_equal(
                    run_program_host(best, data), want
                ), (k, m, pattern)
                assert program_cost(best) <= program_cost(
                    naive_program(c)
                ), (k, m, pattern)


class TestHeadlineStrictReduction:
    def test_rs_8_3_strictly_below_naive(self):
        """The acceptance criterion: the reduced RS(8,3) encode schedule
        runs strictly fewer ops than the naive popcount schedule."""
        gfm = isa_rs_vandermonde_matrix(8, 3)[8:]
        naive = program_cost(naive_program(gfm))
        best = program_cost(best_program(gfm))
        assert best < naive, (best, naive)

    def test_ring_program_beats_towers_when_rows_are_few(self):
        """The ring construction's whole point: m < k matrices drop the
        per-chunk towers for per-row Horner chains."""
        gfm = isa_rs_vandermonde_matrix(8, 3)[8:]
        assert program_cost(ring_program(gfm)) < program_cost(
            naive_program(gfm)
        )

    def test_cse_factors_shared_pairs(self):
        """A matrix with identical rows is the CSE best case: the whole
        second row reuses the first row's chain."""
        gfm = np.array([[3, 5, 7], [3, 5, 7]], dtype=np.uint8)
        naive = program_cost(naive_program(gfm))
        cse = program_cost(cse_program(gfm))
        assert cse < naive, (cse, naive)
        data = rand_data(3, seed=1)
        assert np.array_equal(
            run_program_host(cse_program(gfm), data), oracle(gfm, data)
        )

    def test_zero_rows_and_zero_matrix(self):
        gfm = np.zeros((2, 3), dtype=np.uint8)
        for prog in (naive_program(gfm), cse_program(gfm),
                     ring_program(gfm), best_program(gfm)):
            got = run_program_host(prog, rand_data(3, seed=2))
            assert got.shape == (3, 2, 64)
            assert not got.any()
