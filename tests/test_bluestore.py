"""BlueStore-specific tests: allocator, WAL replay, per-block checksums,
torn-commit atomicity, COW vs deferred write classification.

Models the crash/corruption tiers of the reference's
src/test/objectstore/store_test.cc (BlueStore sections) — the generic
store matrix runs in test_objectstore.py.
"""

import asyncio
import os

import pytest

from ceph_tpu.os import BlueStore, StoreError, Transaction, make_store
from ceph_tpu.os.bluestore import BLOCK, DEFERRED_MAX, INITIAL_BLOCKS, SimulatedCrash


def mk(path):
    s = BlueStore(str(path))
    s.mount()
    return s


class TestPersistence:
    def test_everything_survives_remount(self, tmp_path):
        s = mk(tmp_path / "b")
        t = Transaction().create_collection("c")
        t.write("c", "o", 0, b"hello world" * 500)
        t.setattr("c", "o", "k", b"v")
        t.omap_setkeys("c", "o", {"m": b"n"})
        s.queue_transaction(t)
        s.umount()

        s2 = mk(tmp_path / "b")
        assert s2.read("c", "o") == b"hello world" * 500
        assert s2.getattr("c", "o", "k") == b"v"
        assert s2.omap_get("c", "o") == {"m": b"n"}
        assert s2.count_objects("c") == 1
        s2.umount()

    def test_freelist_rebuild_reuses_removed_space(self, tmp_path):
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "big", 0, b"x" * (100 * BLOCK))
        s.queue_transaction(t)
        free_with_obj = s.alloc.num_free()
        s.queue_transaction(Transaction().remove("c", "big"))
        assert s.alloc.num_free() == free_with_obj + 100
        s.umount()
        # Mount rebuilds the free list from onodes: the removed object's
        # blocks are free again (FreelistManager rebuild semantics).
        s2 = mk(tmp_path / "b")
        assert s2.alloc.num_free() >= free_with_obj + 100
        s2.umount()

    def test_device_grows_past_initial_capacity(self, tmp_path):
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        big = os.urandom((INITIAL_BLOCKS + 50) * BLOCK)
        t = Transaction()
        t.write("c", "huge", 0, big)
        s.queue_transaction(t)
        assert s.read("c", "huge") == big
        s.umount()
        s2 = mk(tmp_path / "b")
        assert s2.read("c", "huge") == big
        s2.umount()


class TestWritePaths:
    def test_small_overwrite_is_deferred_in_place(self, tmp_path):
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"A" * (4 * BLOCK))
        s.queue_transaction(t)
        before = dict(s._peek_onode("c", "o").blocks)
        t = Transaction()
        t.write("c", "o", 100, b"B" * 200)  # small overwrite -> WAL, no move
        s.queue_transaction(t)
        after = s._peek_onode("c", "o").blocks
        assert {b: pc[0] for b, pc in after.items()} == {
            b: pc[0] for b, pc in before.items()
        }
        assert after[0][1] != before[0][1]  # crc updated
        data = s.read("c", "o")
        assert data[100:300] == b"B" * 200 and data[:100] == b"A" * 100
        s.umount()

    def test_large_overwrite_is_cow(self, tmp_path):
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        n = DEFERRED_MAX // BLOCK + 4
        t = Transaction()
        t.write("c", "o", 0, b"A" * (n * BLOCK))
        s.queue_transaction(t)
        before = {b: pc[0] for b, pc in s._peek_onode("c", "o").blocks.items()}
        t = Transaction()
        t.write("c", "o", 0, b"B" * (n * BLOCK))  # big overwrite -> new blocks
        s.queue_transaction(t)
        after = {b: pc[0] for b, pc in s._peek_onode("c", "o").blocks.items()}
        assert all(before[b] != after[b] for b in before)
        assert s.read("c", "o") == b"B" * (n * BLOCK)
        s.umount()

    def test_write_then_clone_same_txn_sees_staged_bytes(self, tmp_path):
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "src", 0, b"fresh" * 1000)
        t.clone("c", "src", "dst")
        s.queue_transaction(t)
        assert s.read("c", "dst") == b"fresh" * 1000
        s.umount()

    def test_remove_is_idempotent(self, tmp_path):
        # Recovery's push handler removes unconditionally before recreate.
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        s.queue_transaction(Transaction().remove("c", "never-existed"))
        s.umount()

    def test_remove_then_recreate_in_one_txn_starts_empty(self, tmp_path):
        """The staged deletion must not resurrect the old onode from the KV
        DB (the _apply_pushes replace-stale-copy pattern)."""
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"X" * 9000)
        s.queue_transaction(t)
        free_before = s.alloc.num_free()
        t = Transaction()
        t.remove("c", "o")
        t.touch("c", "o")
        t.write("c", "o", 0, b"new")
        s.queue_transaction(t)
        assert s.read("c", "o") == b"new"
        assert s.stat("c", "o") == 3
        assert s.count_objects("c") == 1
        # old blocks freed, new ones allocated: net free grows
        assert s.alloc.num_free() > free_before
        s.umount()
        s2 = mk(tmp_path / "b")
        assert s2.read("c", "o") == b"new"
        s2.umount()

    def test_truncate_scrubs_kept_partial_block_cross_block(self, tmp_path):
        """Stale pre-truncate bytes must never reappear, even when the
        extension write lands in a DIFFERENT block."""
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"X" * 100)
        s.queue_transaction(t)
        s.queue_transaction(Transaction().truncate("c", "o", 10))
        t = Transaction()
        t.write("c", "o", 2 * BLOCK, b"Y")  # extend via another block
        s.queue_transaction(t)
        data = s.read("c", "o")
        assert data[:10] == b"X" * 10
        assert data[10:100] == b"\x00" * 90  # not stale Xs
        assert data[2 * BLOCK] == ord("Y")
        s.umount()
        s2 = mk(tmp_path / "b")  # and it holds across remount
        assert s2.read("c", "o")[10:100] == b"\x00" * 90
        s2.umount()

    def test_extend_after_truncate_zero_fills_gap(self, tmp_path):
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"Z" * 3000)
        s.queue_transaction(t)
        s.queue_transaction(Transaction().truncate("c", "o", 1000))
        t = Transaction()
        t.write("c", "o", 2000, b"E" * 100)
        s.queue_transaction(t)
        data = s.read("c", "o")
        # bytes beyond the truncate point must come back as zeros, not the
        # stale "Z"s still present in the physical block
        assert data[:1000] == b"Z" * 1000
        assert data[1000:2000] == b"\x00" * 1000
        assert data[2000:] == b"E" * 100
        s.umount()


class TestChecksums:
    def test_corrupt_extent_surfaces_as_eio(self, tmp_path):
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"precious" * 2048)
        s.queue_transaction(t)
        poff = s._peek_onode("c", "o").blocks[1][0]
        s.umount()

        # Flip one byte inside the second block on "disk".
        with open(tmp_path / "b" / "block", "r+b") as f:
            f.seek(poff + 17)
            byte = f.read(1)
            f.seek(poff + 17)
            f.write(bytes([byte[0] ^ 0xFF]))

        s2 = mk(tmp_path / "b")
        with pytest.raises(StoreError) as ei:
            s2.read("c", "o")
        assert ei.value.errno == -5  # EIO, not silent corruption
        # The undamaged first block is still readable.
        assert s2.read("c", "o", 0, 100) == (b"precious" * 2048)[:100]
        s2.umount()


class TestCrashWindows:
    def test_wal_replay_after_crash_between_commit_and_apply(self, tmp_path):
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"A" * (2 * BLOCK))
        s.queue_transaction(t)

        s._crash_point = "after_commit"
        t = Transaction()
        t.write("c", "o", 10, b"NEW")  # deferred path
        with pytest.raises(SimulatedCrash):
            s.queue_transaction(t)
        s._block_f.close()
        s.db.close()  # drop without applying the WAL

        s2 = mk(tmp_path / "b")  # mount replays the WAL
        data = s2.read("c", "o")
        assert data[10:13] == b"NEW" and data[:10] == b"A" * 10
        # replay consumed the records
        assert list(s2.db.iterate("W")) == []
        s2.umount()

    def test_torn_kv_batch_discards_whole_txn(self, tmp_path):
        n = DEFERRED_MAX + BLOCK  # force the COW path: the KV log's tail is
        # then exactly the metadata batch (no trailing WAL-cleanup records)
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"old" * n)
        t.setattr("c", "o", "ver", b"1")
        s.queue_transaction(t)
        t = Transaction()
        t.write("c", "o", 0, b"new" * n)
        t.setattr("c", "o", "ver", b"2")
        s.queue_transaction(t)
        s.umount()

        # Tear the tail of the KV log: the last committed batch loses its
        # crc -> replay must drop it entirely (no half-applied metadata).
        kv = tmp_path / "b" / "kv"
        with open(kv, "r+b") as f:
            f.truncate(os.path.getsize(kv) - 3)

        s2 = mk(tmp_path / "b")
        assert s2.read("c", "o") == b"old" * n
        assert s2.getattr("c", "o", "ver") == b"1"
        s2.umount()

    def test_unreferenced_direct_writes_are_invisible(self, tmp_path):
        """A crash after direct data writes but before the KV commit leaves
        only unreferenced blocks — old object state intact."""
        s = mk(tmp_path / "b")
        s.queue_transaction(Transaction().create_collection("c"))
        t = Transaction()
        t.write("c", "o", 0, b"old" * 2000)
        s.queue_transaction(t)
        old_free = s.alloc.num_free()

        real_apply = s.db.apply_batch

        def die(_ops):
            raise SimulatedCrash("before_commit")

        s.db.apply_batch = die
        t = Transaction()
        t.write("c", "o", 0, os.urandom(DEFERRED_MAX + BLOCK))  # COW path
        with pytest.raises(SimulatedCrash):
            s.queue_transaction(t)
        s.db.apply_batch = real_apply
        s._block_f.close()
        s.db.close()

        s2 = mk(tmp_path / "b")
        assert s2.read("c", "o") == b"old" * 2000
        # the orphaned blocks were reclaimed by the free-list rebuild
        assert s2.alloc.num_free() >= old_free
        s2.umount()


class TestOsdIntegration:
    def test_store_factory_selects_backend(self, tmp_path):
        from ceph_tpu.common.config import Config
        from ceph_tpu.os import FileStore, MemStore

        assert isinstance(make_store(Config(env=False)), MemStore)
        c = Config({"osd_objectstore": "bluestore", "osd_data": str(tmp_path / "d")}, env=False)
        assert isinstance(make_store(c), BlueStore)
        c = Config({"osd_objectstore": "filestore", "osd_data": str(tmp_path / "f")}, env=False)
        assert isinstance(make_store(c), FileStore)
        with pytest.raises(ValueError):
            make_store(Config({"osd_objectstore": "filestore"}, env=False))

    def test_ec_cluster_on_bluestore_survives_osd_restart(self, tmp_path):
        """EC I/O over OSDs configured with osd_objectstore=bluestore: data
        survives an OSD restart from its on-disk store and recovery
        converges — BlueStore as the OSD's store end to end."""
        from ceph_tpu.client import Rados
        from ceph_tpu.common.config import Config
        from ceph_tpu.mon import MonMap, Monitor
        from ceph_tpu.osd.osd import OSD

        from test_cluster import wait_until
        from test_mon import free_port_addrs

        def bconf(i):
            return Config(
                {
                    "name": f"osd.{i}",
                    "osd_heartbeat_interval": 0.1,
                    "osd_heartbeat_grace": 0.6,
                    "osd_objectstore": "bluestore",
                    "osd_data": str(tmp_path / f"osd{i}"),
                },
                env=False,
            )

        async def run():
            monmap = MonMap(addrs=free_port_addrs(1))
            mons = [Monitor(n, monmap, election_timeout=0.3) for n in monmap.addrs]
            for m in mons:
                await m.start()
                await m.wait_for_quorum()
            osds = [OSD(i, monmap, conf=bconf(i)) for i in range(3)]
            for o in osds:
                assert isinstance(o.store, BlueStore)
                await o.start()
            for o in osds:
                await o.wait_for_up()

            client = Rados(monmap)
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "bs21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("bsec", "erasure", profile="bs21", pg_num=2)
            ioctx = await client.open_ioctx("bsec")
            payload = bytes((i * 13 + 5) % 256 for i in range(3 * 8192 + 77))
            await ioctx.write_full("obj", payload)
            assert await ioctx.read("obj") == payload

            # Restart osd.2 from its on-disk BlueStore directory.
            await osds[2].stop()
            revived = OSD(2, monmap, conf=bconf(2))
            await revived.start()
            await revived.wait_for_up()
            osds[2] = revived

            def clean():
                return all(
                    pg.is_clean
                    for o in osds
                    if o._running
                    for pg in o.pgs.values()
                    if pg.peering.is_primary()
                )

            await wait_until(clean, 10.0, "recovery after bluestore restart")
            assert await ioctx.read("obj") == payload

            await client.shutdown()
            for o in osds:
                if o._running:
                    await o.stop()
            for m in mons:
                await m.stop()
            await asyncio.sleep(0.05)

        asyncio.run(run())


class TestBluestoreTool:
    """ceph-bluestore-tool analog: offline fsck + show-label
    (BlueStore::_fsck; tools/bluestore_tool.py)."""

    def _populate(self, path):
        s = mk(path)
        txn = Transaction().create_collection("1.0s0")
        for i in range(4):
            txn.touch("1.0s0", f"o{i}")
            txn.write("1.0s0", f"o{i}", 0, bytes([i]) * (BLOCK * 2))
        s.queue_transaction(txn)
        s.umount()

    def test_fsck_clean_and_show_label(self, tmp_path, capsys):
        from ceph_tpu.tools.bluestore_tool import main as bst_main

        self._populate(tmp_path / "b")
        assert bst_main(["--path", str(tmp_path / "b"), "--op", "fsck",
                         "--deep"]) == 0
        out = capsys.readouterr().out
        assert "4 onodes" in out and "0 error(s)" in out
        assert bst_main(["--path", str(tmp_path / "b"), "--op",
                         "show-label"]) == 0
        import json as _json

        label = _json.loads(capsys.readouterr().out)
        assert label["objects"] == 4 and label["block_size"] == BLOCK

    def test_deep_fsck_catches_bitrot(self, tmp_path, capsys):
        """Flip bytes in the block device: shallow fsck stays clean
        (structure intact), deep fsck pins the csum mismatch to the
        onode — the fsck/deep-fsck split of the reference."""
        from ceph_tpu.os.bluestore import _ONODE, Onode
        from ceph_tpu.tools.bluestore_tool import main as bst_main

        self._populate(tmp_path / "b")
        # find a data block of o2 and corrupt it on "disk"
        s = mk(tmp_path / "b")
        blob = s.db.get(_ONODE, "1.0s0\x00o2")
        poff = Onode.decode(blob).blocks[0][0]
        s.umount()
        with open(tmp_path / "b" / "block", "r+b") as f:
            f.seek(poff)
            f.write(b"BITROT")
        assert bst_main(["--path", str(tmp_path / "b"), "--op", "fsck"]) == 0
        capsys.readouterr()
        assert bst_main(["--path", str(tmp_path / "b"), "--op", "fsck",
                         "--deep"]) == 1
        out = capsys.readouterr().out
        assert "1 error(s)" in out and "1.0s0/o2" in out

    def test_objectstore_tool_reads_bluestore(self, tmp_path, capsys):
        """ceph-objectstore-tool --type bluestore: list/dump work against
        a BlueStore data path (the reference tool's backend selection)."""
        from ceph_tpu.tools.objectstore_tool import main as ost_main

        self._populate(tmp_path / "b")
        assert ost_main([
            "--data-path", str(tmp_path / "b"), "--type", "bluestore",
            "--op", "list",
        ]) == 0
        out = capsys.readouterr().out
        assert '["1.0s0", "o0"]' in out
        assert ost_main([
            "--data-path", str(tmp_path / "b"), "--type", "bluestore",
            "--op", "dump", "--coll", "1.0s0", "--oid", "o1",
        ]) == 0
        import json as _json

        dump = _json.loads(capsys.readouterr().out)
        assert dump["size"] == BLOCK * 2
