"""Recovery-storm hardening contracts (ISSUE 15), at their seams:

1. Reserver preemption — higher-priority recovery preempts a granted
   backfill reservation (callback exactly once), release is
   exactly-once, and re-grants are deterministic after the preemptor
   releases.
2. Mon flap dampening — the down→out grace grows exponentially with
   recent markdowns, the churn cap bounds auto-outs per sweep tick,
   and a genuinely dead OSD (one markdown) still goes out at the base
   interval.
3. RecoveryStormController — engage/disengage thresholds, wave-batched
   round-robin admission bounded by the in-flight cap, SLO-aware
   shed/ramp from local io-accounting burn, decode-window widening,
   backfill preemption, and the status/perf surfaces.
4. Recovery-path fault points — a dropped PushOp (ec.recover_push)
   self-heals through the stalled-push retry.
5. Surfaces — the recovery_wave flight records render as their own
   Perfetto row, and the mgr progress module aggregates storm slices
   into a whole-OSD rebuild bar.
"""

import time

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.osd.reserver import Reserver


class TestReserverPreemption:
    def test_higher_priority_preempts_lowest_holder_once(self):
        r = Reserver(lambda: 1)
        fired = []
        assert r.try_reserve("backfill", priority=0,
                             on_preempt=lambda: fired.append("bf"))
        assert r.try_reserve("storm", priority=3)
        assert fired == ["bf"]
        assert r.holders() == {"storm": 3}
        assert r.preemptions == 1
        # the preempted key's slot is gone: releasing it is a no-op
        # (exactly once — the callback already surrendered it)
        assert not r.release("backfill")
        assert r.holders() == {"storm": 3}

    def test_equal_priority_never_preempts(self):
        r = Reserver(lambda: 1)
        assert r.try_reserve("a", priority=2)
        assert not r.try_reserve("b", priority=2)
        assert not r.try_reserve("c", priority=1)
        assert r.holders() == {"a": 2}
        assert r.preemptions == 0

    def test_release_is_exactly_once(self):
        r = Reserver(lambda: 2)
        assert r.try_reserve("a")
        assert r.release("a")
        assert not r.release("a")  # second release: no-op, reported
        assert not r.release("never-held")
        assert r.held() == 0

    def test_regrant_is_deterministic_after_preemptor_releases(self):
        r = Reserver(lambda: 1)
        state = {"held": True}

        def on_preempt():
            state["held"] = False

        assert r.try_reserve("bf", priority=0, on_preempt=on_preempt)
        assert r.try_reserve("storm", priority=3)
        assert not state["held"]
        # while the storm holds the slot, the backfill's tick-retry is
        # denied (equal-or-lower priority never preempts)
        assert not r.try_reserve("bf", priority=0, on_preempt=on_preempt)
        assert r.release("storm")
        # the next retry re-grants — and a sibling at the same priority
        # cannot bounce it
        assert r.try_reserve("bf", priority=0, on_preempt=on_preempt)
        assert not r.try_reserve("bf2", priority=0)
        assert r.holders() == {"bf": 0}

    def test_preemption_picks_the_lowest_priority_victim(self):
        r = Reserver(lambda: 2)
        fired = []
        assert r.try_reserve("low", priority=1,
                             on_preempt=lambda: fired.append("low"))
        assert r.try_reserve("mid", priority=2,
                             on_preempt=lambda: fired.append("mid"))
        assert r.try_reserve("high", priority=5)
        assert fired == ["low"]
        assert set(r.holders()) == {"mid", "high"}

    def test_backfill_pg_surrenders_and_resumes_on_preemption(self):
        """The PG wiring: a preempted backfill releases its remote
        grants, stops walking at the chunk boundary, and re-reserves on
        a later tick once the slot frees."""
        from test_backfill import _backfilling_pg

        from ceph_tpu.msg.messages import MBackfillReserve

        pg, osd = _backfilling_pg(n_objects=6)
        pg._kick_backfill()
        assert pg._bf_local_reserved
        # a remote slot stands granted (without starting the chunk, so
        # the preemption — not an in-flight push — is what stops us)
        pg._bf_granted.add(1)
        # a storm-priority reservation preempts the backfill slot
        assert osd.local_reserver.try_reserve(("storm", 0), priority=3)
        assert not pg._bf_local_reserved
        # the surrender sent a RELEASE for the granted remote slot
        releases = [
            m for _osd, m in osd.sent
            if isinstance(m, MBackfillReserve)
            and m.op == MBackfillReserve.RELEASE
        ]
        assert releases, "preempted backfill kept its remote grant"
        # while the storm holds the slot, ticks cannot re-reserve
        pg._kick_backfill()
        assert not pg._bf_local_reserved
        # storm done: the next tick re-grants and backfill resumes
        osd.local_reserver.release(("storm", 0))
        pg._kick_backfill()
        assert pg._bf_local_reserved


class _FakeMon:
    """Just enough of Monitor for OSDMonitor: leader + instant paxos."""

    def __init__(self, conf=None):
        self.conf = conf or Config({"name": "mon.t"}, env=False)
        self.osdmon = None
        self.pg_digest = {}

    def is_leader(self):
        return True

    def propose(self, service, blob, on_done=None):
        self.osdmon.apply_commit(blob)
        if on_done is not None:
            on_done(1)

    def publish_osdmap(self):
        pass


def _mon_with_osds(n=4, conf=None):
    from ceph_tpu.mon.osd_monitor import OSDMonitor
    from ceph_tpu.msg.messages import MOSDBoot

    mon = _FakeMon(conf=conf)
    osdmon = OSDMonitor(mon, min_down_reporters=2)
    mon.osdmon = osdmon
    osdmon.on_active()
    for i in range(n):
        osdmon.prepare_boot(MOSDBoot(osd=i, addr=f"a{i}", epoch=0))
    return mon, osdmon


def _mark_down(osdmon, osd):
    from ceph_tpu.msg.messages import MOSDFailure

    for reporter in ("osd.8", "osd.9"):
        osdmon.prepare_failure(
            MOSDFailure(target=osd, target_addr="", failed_for=1.0,
                        epoch=1),
            reporter=reporter,
        )


class TestMonFlapDampening:
    def _conf(self, **over):
        base = {
            "name": "mon.t",
            "mon_osd_down_out_interval": 2.0,
            "mon_osd_flap_window": 300.0,
            "mon_osd_flap_backoff": 2.0,
            "mon_osd_flap_max_auto_out_per_tick": 4,
        }
        base.update(over)
        return Config(base, env=False)

    def test_markdown_history_grows_the_grace_exponentially(self):
        mon, osdmon = _mon_with_osds(conf=self._conf())
        now = time.monotonic()
        assert osdmon._down_out_grace(1, now) == 2.0  # no history
        osdmon._note_markdown(1, now)
        assert osdmon._down_out_grace(1, now) == 2.0  # first failure
        osdmon._note_markdown(1, now)
        assert osdmon._down_out_grace(1, now) == 4.0
        osdmon._note_markdown(1, now)
        assert osdmon._down_out_grace(1, now) == 8.0
        stats = osdmon.flap_stats()
        assert stats["osds"][1]["markdowns"] == 3
        assert stats["osds"][1]["grace_sec"] == 8.0

    def test_window_expiry_forgives_old_markdowns(self):
        mon, osdmon = _mon_with_osds(conf=self._conf(mon_osd_flap_window=10.0))
        now = time.monotonic()
        osdmon._recent_markdowns[1] = [now - 60.0, now - 30.0, now]
        assert osdmon._down_out_grace(1, now) == 2.0  # only 1 in window
        assert osdmon._recent_markdowns[1] == [now]

    def test_quorum_markdown_records_history(self):
        mon, osdmon = _mon_with_osds(conf=self._conf())
        _mark_down(osdmon, 2)
        assert not osdmon.osdmap.is_up(2)
        assert osdmon._recent_markdown_count(2, time.monotonic()) == 1

    def test_sweep_dampens_flapper_but_outs_dead_osd(self):
        mon, osdmon = _mon_with_osds(conf=self._conf())
        now = time.monotonic()
        # osd.1: flapper with 3 recent markdowns, down 3s (grace 8s)
        _mark_down(osdmon, 1)
        osdmon._recent_markdowns[1] = [now, now, now]
        osdmon._down_since[1] = now - 3.0
        # osd.2: genuinely dead, first markdown, down 3s (grace 2s)
        _mark_down(osdmon, 2)
        osdmon._down_since[2] = now - 3.0
        osdmon._tick_down_out()
        assert osdmon.osdmap.osds[1].in_, "dampening failed to hold"
        assert not osdmon.osdmap.osds[2].in_, "dead OSD never outed"
        assert osdmon.auto_outs_total == 1
        assert osdmon.dampened_holds >= 1
        # the flapper still goes out once its (longer) grace elapses
        osdmon._down_since[1] = now - 9.0
        osdmon._tick_down_out()
        assert not osdmon.osdmap.osds[1].in_

    def test_churn_cap_bounds_auto_outs_per_tick(self):
        mon, osdmon = _mon_with_osds(
            n=6, conf=self._conf(mon_osd_flap_max_auto_out_per_tick=2)
        )
        now = time.monotonic()
        for i in range(5):
            _mark_down(osdmon, i)
            osdmon._down_since[i] = now - 10.0
        osdmon._tick_down_out()
        outed = [i for i in range(5) if not osdmon.osdmap.osds[i].in_]
        assert len(outed) == 2, outed
        # the rest keep their down-clock and go out on later ticks
        osdmon._tick_down_out()
        outed = [i for i in range(5) if not osdmon.osdmap.osds[i].in_]
        assert len(outed) == 4
        osdmon._tick_down_out()
        assert sum(
            1 for i in range(5) if not osdmon.osdmap.osds[i].in_
        ) == 5
        assert osdmon.auto_outs_total == 5


@pytest.fixture(autouse=True)
def _clear_engaged_storms():
    """Stub controllers engaged-but-never-disengaged would otherwise
    pin the process-wide engaged refcount (the controller<->conf
    observer cycle delays their GC) and block the shared decode-window
    restore for later tests in the same process."""
    from ceph_tpu.osd import recovery_controller as rc

    for c in list(rc._ENGAGED):
        rc._ENGAGED.discard(c)
    yield
    for c in list(rc._ENGAGED):
        rc._ENGAGED.discard(c)


class _StormPeering:
    def __init__(self, missing):
        self.missing_oids = list(missing)

    def is_primary(self):
        return True

    def is_active(self):
        return True

    def all_missing_oids(self):
        return sorted(self.missing_oids)


class _StormPG:
    def __init__(self, oids):
        self.peering = _StormPeering(oids)
        self.recovering = set()
        self.admitted = []

    def _recover_one(self, oid):
        if oid in self.recovering:
            return
        self.recovering.add(oid)
        self.admitted.append(oid)

    def finish(self, oid):
        self.recovering.discard(oid)
        self.peering.missing_oids.remove(oid)


class _StormAggregator:
    def __init__(self):
        self.windows = []

    def configure(self, window=None, **_kw):
        self.windows.append(window)


class _StormOSD:
    def __init__(self, **conf_over):
        from ceph_tpu.common.io_accounting import IOAccountant

        base = {
            "name": "osd.0",
            "osd_recovery_storm_min_objects": 4,
            "osd_recovery_storm_wave_objects": 4,
            "osd_recovery_storm_min_wave_objects": 1,
            "osd_recovery_storm_max_inflight": 8,
            "osd_recovery_storm_slo_target_ms": 0.0,
        }
        base.update(conf_over)
        self.conf = Config(base, env=False)
        self.whoami = 0
        self.pgs = {}
        self.local_reserver = Reserver(lambda: 1)
        self.decode_aggregator = _StormAggregator()
        self.io_accountant = IOAccountant()


def _controller(**conf_over):
    from ceph_tpu.osd.recovery_controller import RecoveryStormController

    osd = _StormOSD(**conf_over)
    return osd, RecoveryStormController(osd)


class TestRecoveryStormController:
    def test_stays_idle_below_the_engage_threshold(self):
        osd, ctl = _controller()
        osd.pgs[(1, 0)] = _StormPG(["a", "b"])  # 2 < min 4
        ctl.tick()
        assert not ctl.engaged
        assert ctl.storms_started == 0
        assert osd.pgs[(1, 0)].admitted == []

    def test_engages_and_admits_waves_round_robin(self):
        osd, ctl = _controller()
        pg_a = osd.pgs[(1, 0)] = _StormPG([f"a{i}" for i in range(6)])
        pg_b = osd.pgs[(1, 1)] = _StormPG([f"b{i}" for i in range(6)])
        ctl.tick()
        assert ctl.engaged
        assert ctl.storms_started == 1
        assert ctl.waves == 1
        # wave of 4, round-robin: two objects from EACH pg, not four
        # from the first
        assert ctl.objects_admitted == 4
        assert len(pg_a.admitted) == 2 and len(pg_b.admitted) == 2
        # the decode window widened to the wave size on engage
        assert osd.decode_aggregator.windows[-1] >= 4

    def test_inflight_cap_bounds_admission_and_disengage_restores(self):
        osd, ctl = _controller(osd_recovery_storm_max_inflight=5)
        pg = osd.pgs[(1, 0)] = _StormPG([f"o{i}" for i in range(12)])
        ctl.tick()  # wave 1: 4 admitted
        ctl.tick()  # wave 2: capped at 5 total in flight -> 1 more
        assert len(pg.recovering) == 5
        assert ctl.objects_admitted == 5
        # nothing more until recoveries land
        ctl.tick()
        assert len(pg.recovering) == 5
        for oid in list(pg.recovering):
            pg.finish(oid)
        while pg.peering.missing_oids or pg.recovering:
            ctl.tick()
            for oid in list(pg.recovering):
                pg.finish(oid)
        ctl.tick()
        assert not ctl.engaged
        assert ctl.storms_completed == 1
        # the decode window restored to the configured default
        assert osd.decode_aggregator.windows[-1] == int(
            osd.conf.get("ec_tpu_decode_aggregate_window")
        )
        # ...and the reservation released
        assert osd.local_reserver.held() == 0

    def test_engage_preempts_a_granted_backfill_slot(self):
        osd, ctl = _controller()
        fired = []
        assert osd.local_reserver.try_reserve(
            ("bf", 1, 0), priority=0, on_preempt=lambda: fired.append(1)
        )
        osd.pgs[(1, 0)] = _StormPG([f"o{i}" for i in range(6)])
        ctl.tick()
        assert ctl.engaged
        assert fired == [1], "storm did not preempt the backfill slot"
        assert ("storm", 0) in osd.local_reserver.holders()
        assert ctl.preempted_backfills == 1

    def test_slo_burn_sheds_and_recovery_ramps(self):
        osd, ctl = _controller(
            osd_recovery_storm_slo_target_ms=10.0,
            osd_recovery_storm_slo_objective=0.5,
            osd_recovery_storm_burn_threshold=1.0,
            osd_recovery_storm_max_inflight=1000,
            osd_recovery_storm_wave_objects=8,
        )
        pg = osd.pgs[(1, 0)] = _StormPG([f"o{i}" for i in range(400)])

        def _tick_past_cadence():
            # burn evaluations are cadence-gated (completion-driven
            # ticks must not shrink the window); simulate elapsed time
            ctl._last_burn_eval -= 1.0
            ctl.tick()

        ctl.tick()  # engage; burn baseline snapshot
        assert ctl.engaged and ctl._wave == 8
        # between evaluations a completion-driven tick must NOT step
        # the wave (the stale-verdict guard)
        for _ in range(8):
            osd.io_accountant.account(1, "c", "read", 4096, 0.050)
        ctl.tick()
        assert ctl._wave == 8 and ctl.sheds == 0
        # a burn window full of slow client ops: every op 50 ms > the
        # 10 ms target -> bad fraction 1.0 / budget 0.5 = burn 2.0
        _tick_past_cadence()
        assert ctl._burn > 1.0
        assert ctl.sheds >= 1
        assert ctl._wave == 4
        for _ in range(8):
            osd.io_accountant.account(1, "c", "read", 4096, 0.050)
        _tick_past_cadence()
        assert ctl._wave == 2
        # idle window (no new ops): burn 0 -> ramp back toward ceiling
        _tick_past_cadence()
        assert ctl.ramps >= 1
        assert ctl._wave == 4
        _tick_past_cadence()
        assert ctl._wave == 8

    def test_last_storm_out_restores_the_shared_window(self):
        """The decode aggregator is process-wide: one OSD disengaging
        must not narrow a sibling's mid-storm window; the config
        default returns only when the LAST storm completes."""
        osd_a, ctl_a = _controller()
        osd_b, ctl_b = _controller()
        # both share "the" aggregator in production; the stubs record
        # their own configure calls, so assert via call absence/presence
        pg_a = osd_a.pgs[(1, 0)] = _StormPG([f"a{i}" for i in range(4)])
        pg_b = osd_b.pgs[(1, 0)] = _StormPG([f"b{i}" for i in range(4)])
        ctl_a.tick()
        ctl_b.tick()
        assert ctl_a.engaged and ctl_b.engaged
        widened_calls_b = len(osd_b.decode_aggregator.windows)
        # A finishes first: with B still engaged, NO restore happens
        for oid in list(pg_a.recovering):
            pg_a.finish(oid)
        ctl_a.tick()
        assert not ctl_a.engaged
        assert len(osd_a.decode_aggregator.windows) == 1  # widen only
        # B finishes: the last storm out restores from config
        for oid in list(pg_b.recovering):
            pg_b.finish(oid)
        ctl_b.tick()
        assert not ctl_b.engaged
        assert len(osd_b.decode_aggregator.windows) == widened_calls_b + 1
        assert osd_b.decode_aggregator.windows[-1] == int(
            osd_b.conf.get("ec_tpu_decode_aggregate_window")
        )

    def test_runtime_ceiling_shrink_clamps_live_wave(self):
        osd, ctl = _controller()
        osd.pgs[(1, 0)] = _StormPG([f"o{i}" for i in range(6)])
        ctl.tick()
        assert ctl._wave == 4
        osd.conf.set("osd_recovery_storm_wave_objects", 2)
        assert ctl._wave == 2  # observer clamped immediately

    def test_wave_commits_flight_records_and_perf_surfaces(self):
        from ceph_tpu.ops.flight_recorder import flight_recorder

        waves0 = sum(
            1 for r in flight_recorder().records()
            if r["kind"] == "recovery_wave"
        )
        osd, ctl = _controller()
        osd.pgs[(1, 0)] = _StormPG([f"o{i}" for i in range(6)])
        ctl.tick()
        recs = [
            r for r in flight_recorder().records()
            if r["kind"] == "recovery_wave"
        ]
        assert len(recs) == waves0 + 1
        assert recs[-1]["stripes"] == 4  # objects in the wave
        assert recs[-1]["sched_class"] == "recovery"
        assert recs[-1]["group"].startswith("storm:")
        perf = ctl.perf_dump()
        assert perf["waves"] == 1
        assert perf["objects_admitted"] == 4
        assert perf["engaged"] == 1
        assert perf["wave_objects"] == 4
        st = ctl.status()
        assert st["objects_total"] == 6
        assert st["engaged"] is True

    def test_final_status_reemits_then_clears(self):
        osd, ctl = _controller()
        pg = osd.pgs[(1, 0)] = _StormPG([f"o{i}" for i in range(4)])
        ctl.tick()
        for oid in list(pg.recovering):
            pg.finish(oid)
        ctl.tick()
        assert not ctl.engaged
        finals = [ctl.status() for _ in range(ctl.FINAL_REPORTS)]
        assert all(
            f["objects_done"] == f["objects_total"] == 4 for f in finals
        )
        assert ctl.status() == {}

    def test_note_osdmap_tracks_victims(self):
        class _Info:
            def __init__(self, up, in_):
                self.up, self.in_ = up, in_

        class _Map:
            def __init__(self, osds):
                self.osds = osds

        osd, ctl = _controller()
        old = _Map({1: _Info(True, True), 2: _Info(True, True)})
        new = _Map({1: _Info(False, True), 2: _Info(True, True)})
        ctl.note_osdmap(old, new)
        assert 1 in ctl.victims
        back = _Map({1: _Info(True, True), 2: _Info(True, True)})
        ctl.note_osdmap(new, back)
        assert 1 not in ctl.victims


class TestPushRetryFaultPoint:
    def test_dropped_push_self_heals_via_retry(self):
        """ec.recover_push drops a PushOp at the target; the primary's
        stalled-push retry re-sends and recovery completes."""
        from test_ec_backend import Cluster

        from ceph_tpu.common.fault_injector import global_injector
        from ceph_tpu.osd.osdmap import POOL_TYPE_ERASURE, PgPool

        pool = PgPool(
            id=1, name="ec", type=POOL_TYPE_ERASURE, size=3, min_size=2,
            erasure_code_profile="p", stripe_width=2 * 4096,
        )
        profiles = {"p": {"plugin": "tpu", "k": "2", "m": "1"}}
        c = Cluster(pool, profiles)
        c.write("obj", 0, b"x" * 5000)
        # the target loses its shard -> recovery pushes to it
        c.missing["obj"] = {2}
        inj = global_injector()
        inj.inject("ec.recover_push", 5, hits=1)
        done = []
        try:
            c.primary.recover_object("obj", {2}, done.append)
            c.pump()
            # the push was dropped: recovery is parked in WRITING
            assert not done
            rec = c.primary.recovery_ops["obj"]
            assert rec.pending_pushes == {2}
            time.sleep(0.02)
            assert c.primary.retry_stalled_pushes(0.01) == 1
            c.pump()
        finally:
            inj.clear("ec.recover_push")
        assert done == [0]
        assert c.primary.push_retries == 1

    def test_retry_disabled_with_nonpositive_grace(self):
        from test_ec_backend import Cluster

        from ceph_tpu.osd.osdmap import POOL_TYPE_ERASURE, PgPool

        pool = PgPool(
            id=1, name="ec", type=POOL_TYPE_ERASURE, size=3, min_size=2,
            erasure_code_profile="p", stripe_width=2 * 4096,
        )
        c = Cluster(pool, {"p": {"plugin": "tpu", "k": "2", "m": "1"}})
        c.write("obj", 0, b"y" * 4096)
        assert c.primary.retry_stalled_pushes(0.0) == 0


class TestStormTraceExport:
    def test_wave_records_render_their_own_perfetto_row(self):
        from ceph_tpu.ops.flight_recorder import FlightRecorder, new_record
        from ceph_tpu.tools.trace_export import (
            export_chrome_trace,
            validate_chrome_trace,
        )

        fr = FlightRecorder(capacity=16)
        wave = new_record("recovery_wave", group="storm:osd.2", tickets=3,
                          stripes=9, batch=9, sched_class="recovery")
        wave["dispatch_ts"] = wave["submit_ts"]
        wave["settle_ts"] = wave["submit_ts"] + 0.005
        fr.commit(wave)
        dec = new_record("decode", group="g", stripes=4, nbytes=4096)
        dec["h2d_s"] = 0.001
        dec["kernel_s"] = 0.001
        fr.commit(dec)
        trace = export_chrome_trace(fr.records())
        validate_chrome_trace(trace)
        storm_rows = [
            e for e in trace["traceEvents"]
            if e.get("pid") == "recovery storm"
        ]
        assert storm_rows, "no recovery-storm row in the export"
        assert storm_rows[0]["tid"] == "storm:osd.2"
        assert storm_rows[0]["args"]["objects"] == 9
        assert storm_rows[0]["args"]["pgs"] == 3
        # wave records stay OFF the device lanes (they are admission
        # spans, not device work)
        assert not any(
            e.get("pid") == "devices" and "recovery_wave" in e.get("name", "")
            for e in trace["traceEvents"]
        )


class TestProgressStormBars:
    def _mgr_module(self):
        import importlib
        import sys

        sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
        from test_progress import _FakeMgr

        from ceph_tpu.mgr.progress import ProgressModule

        mgr = _FakeMgr()
        mod = ProgressModule(stall_sec=60.0)
        mod.mgr = mgr
        return mgr, mod

    def test_storm_slices_aggregate_into_a_whole_osd_bar(self):
        mgr, mod = self._mgr_module()
        for daemon, done, total in (("osd.1", 3, 10), ("osd.2", 2, 6)):
            mgr.statuses[daemon] = {
                "recovery_storm": {
                    "engaged": True,
                    "victims": ["osd.0"],
                    "objects_done": done,
                    "objects_total": total,
                },
            }
        mod.tick()
        digest = mod.progress_digest()
        assert len(digest["storms"]) == 1
        bar = digest["storms"][0]
        assert bar["pgid"] == "osd.0"
        assert bar["kind"] == "storm"
        assert bar["objects_done"] == 5
        assert bar["objects_total"] == 16
        # the storm bar rides the progress gauge families labeled
        # kind="storm"
        fams = {name: rows for name, _t, _h, rows in mod.prometheus_metrics()}
        assert any(
            'kind="storm"' in row
            for row in fams["ceph_tpu_progress_fraction"]
        )
        # storms do NOT pollute the per-PG cluster aggregate (their
        # objects already count through per-PG recovery events)
        assert digest["cluster"]["objects_total"] == 0

    def test_completed_storm_expires_as_completed(self):
        mgr, mod = self._mgr_module()
        mgr.statuses["osd.1"] = {
            "recovery_storm": {
                "engaged": True, "victims": ["osd.0"],
                "objects_done": 4, "objects_total": 4,
            },
        }
        mod.tick()
        assert len(mod.storms) == 1
        mgr.statuses["osd.1"] = {}
        ev = next(iter(mod.storms.values()))
        ev.last_seen -= mod.EVENT_EXPIRE_SEC + 1
        completed0 = mod.completed
        mod.tick()
        assert not mod.storms
        assert mod.completed == completed0 + 1
