"""Perf smoke (tier-1): dispatch-shape invariants of the coding hot paths.

Runs small encode/decode chains on the CPU backend and asserts the
launch counters and plan-cache hit rates, so a regression back to
per-stripe dispatch or per-call plan rebuilds fails `pytest -m 'not
slow'` immediately instead of only dilating `python bench.py`
(ISSUE 3 / ISSUE 5 satellites).  The counters are python-dispatch
witnesses — see ceph_tpu/ops/dispatch.py for what they do and don't
count; DECODE_LAUNCHES isolates the recovery/degraded-read half."""

import numpy as np

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import PLAN_CACHE
from ceph_tpu.ops.dispatch import (
    DECODE_LAUNCHES,
    DEVICES_PER_LAUNCH,
    LAUNCHES,
    SHARDED_LAUNCHES,
    perf_dump,
)
from ceph_tpu.stripe import StripeInfo
from ceph_tpu.stripe import stripe as stripe_mod


def make_rs(k=4, m=2):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


class TestPerfSmoke:
    def test_batched_encode_is_one_dispatch(self):
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        stripes = 32
        obj = np.random.default_rng(0).integers(
            0, 256, stripes * sinfo.stripe_width, dtype=np.uint8
        )
        # warm coder + jit caches with one small stripe
        ec.encode_array(obj[: sinfo.stripe_width].reshape(1, 4, 4096))
        before = LAUNCHES.snapshot()
        shards = stripe_mod.encode(sinfo, ec, obj)
        after = LAUNCHES.snapshot()
        assert after["launches"] - before["launches"] == 1, (
            f"{stripes} stripes took {after['launches'] - before['launches']} "
            "device dispatches; the batched path regressed to per-stripe launches"
        )
        assert after["stripes"] - before["stripes"] == stripes
        assert len(shards) == 6

    def test_degraded_read_chain_dispatch_budget(self):
        """Encode + reconstruct chain: one dispatch for the encode, one
        for the decode — losing a shard must not fan out per stripe."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        obj = np.random.default_rng(1).integers(
            0, 256, 16 * sinfo.stripe_width, dtype=np.uint8
        )
        shards = stripe_mod.encode(sinfo, ec, obj)
        have = {i: shards[i] for i in range(6) if i != 2}
        before = LAUNCHES.snapshot()
        logical = stripe_mod.decode_concat(sinfo, ec, have)
        launches = LAUNCHES.snapshot()["launches"] - before["launches"]
        assert np.array_equal(logical, obj)
        assert launches == 1, launches

    def test_plan_cache_steady_state_hit_rate(self):
        """Re-encoding with the same geometry must hit the coder cache:
        misses stay flat while hits climb."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        obj = np.random.default_rng(2).integers(
            0, 256, 4 * sinfo.stripe_width, dtype=np.uint8
        )
        stripe_mod.encode(sinfo, ec, obj)  # ensure the coder exists
        s0 = PLAN_CACHE.stats()
        for _ in range(5):
            stripe_mod.encode(sinfo, ec, obj)
        s1 = PLAN_CACHE.stats()
        assert s1["hits"] - s0["hits"] == 5
        assert s1["misses"] == s0["misses"], "steady-state encode rebuilt a plan"

    def test_recovery_decode_is_one_decode_dispatch(self):
        """Rebuilding whole shards for a 16-stripe object must cost one
        DECODE dispatch (the ISSUE 5 decode launch-counter contract) —
        and that dispatch also lands on the global total."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        stripes = 16
        obj = np.random.default_rng(3).integers(
            0, 256, stripes * sinfo.stripe_width, dtype=np.uint8
        )
        shards = stripe_mod.encode(sinfo, ec, obj)
        have = {i: shards[i] for i in range(6) if i not in (1, 4)}
        before_d = DECODE_LAUNCHES.snapshot()
        before_t = LAUNCHES.snapshot()
        rebuilt = stripe_mod.decode_shards(sinfo, ec, have, {1, 4})
        after_d = DECODE_LAUNCHES.snapshot()
        after_t = LAUNCHES.snapshot()
        assert np.array_equal(rebuilt[1], shards[1])
        assert np.array_equal(rebuilt[4], shards[4])
        assert after_d["launches"] - before_d["launches"] == 1, (
            f"{stripes}-stripe recovery took "
            f"{after_d['launches'] - before_d['launches']} decode dispatches; "
            "the batched decode path regressed to per-stripe launches"
        )
        assert after_d["stripes"] - before_d["stripes"] == stripes
        assert after_t["launches"] - before_t["launches"] == 1

    def test_decode_plan_cache_steady_state_hit_rate(self):
        """Re-decoding the same erasure pattern must hit the decode coder
        LRU: misses stay flat while hits climb (a regression to per-call
        Gaussian inversions would only show up in recovery latency)."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        obj = np.random.default_rng(4).integers(
            0, 256, 4 * sinfo.stripe_width, dtype=np.uint8
        )
        shards = stripe_mod.encode(sinfo, ec, obj)
        have = {i: shards[i] for i in range(6) if i != 2}
        stripe_mod.decode_shards(sinfo, ec, have, {2})  # coder exists
        s0 = PLAN_CACHE.stats()
        for _ in range(5):
            stripe_mod.decode_shards(sinfo, ec, have, {2})
        s1 = PLAN_CACHE.stats()
        assert s1["hits"] - s0["hits"] == 5
        assert s1["misses"] == s0["misses"], "steady-state decode rebuilt a plan"


class TestShardedCounters:
    """Sharded-launch counter consistency (ISSUE 6 satellite): the
    counters feed asok perf dump and the mgr Prometheus scrape — these
    invariants keep them from silently rotting."""

    def test_sharded_launches_never_exceed_total(self):
        """By construction every sharded dispatch also lands on the
        global total: SHARDED_LAUNCHES <= LAUNCHES, always."""
        s, t = SHARDED_LAUNCHES.snapshot(), LAUNCHES.snapshot()
        assert s["launches"] <= t["launches"]
        assert s["stripes"] <= t["stripes"]
        assert s["bytes"] <= t["bytes"]
        assert DECODE_LAUNCHES.snapshot()["launches"] <= t["launches"]

    def test_devices_per_launch_histogram_consistent(self):
        """Occupancy distribution vs the launch counters: every dispatch
        records exactly one occupancy sample, multi-device samples equal
        the sharded-launch total, and a 1-device dispatch reports zero
        sharded launches."""
        from ceph_tpu.parallel import dispatch as shard_dispatch

        ec = make_rs()
        rng = np.random.default_rng(7)
        min_batch, devices = shard_dispatch.settings()
        try:
            # a guaranteed-sharded launch, then a guaranteed-single one
            shard_dispatch.configure(min_batch=16, devices=0)
            t0 = LAUNCHES.snapshot()["launches"]
            s0 = SHARDED_LAUNCHES.snapshot()["launches"]
            d0 = DEVICES_PER_LAUNCH.snapshot()
            ec.encode_array(rng.integers(0, 256, (32, 4, 4096), dtype=np.uint8))
            shard_dispatch.configure(devices=1)  # degenerate mesh: 1-device run
            ec.encode_array(rng.integers(0, 256, (32, 4, 4096), dtype=np.uint8))
        finally:
            shard_dispatch.configure(min_batch=min_batch, devices=devices)
        t1 = LAUNCHES.snapshot()["launches"]
        s1 = SHARDED_LAUNCHES.snapshot()["launches"]
        d1 = DEVICES_PER_LAUNCH.snapshot()
        assert t1 - t0 == 2
        assert s1 - s0 == 1, "exactly the wide launch lands on SHARDED_LAUNCHES"
        occ_delta = {
            n: d1.get(n, 0) - d0.get(n, 0) for n in set(d0) | set(d1)
        }
        assert sum(occ_delta.values()) == 2, "one occupancy sample per dispatch"
        assert occ_delta.get(1, 0) == 1, "the 1-device run must sample width 1"
        wide = sum(v for n, v in occ_delta.items() if n > 1)
        assert wide == s1 - s0, "multi-device samples must equal sharded total"

    def test_perf_dump_exports_sharded_dimension(self):
        """The asok/mgr export payload carries the sharded counters and
        the devices-per-launch distribution, internally consistent."""
        dump = perf_dump()
        for key in ("launches", "sharded_launches", "decode_launches",
                    "device_launches"):
            assert key in dump, f"missing {key} in ec_dispatch perf dump"
        assert dump["sharded_launches"] <= dump["launches"]
        occ = {
            int(k.split(".")[1]): v
            for k, v in dump.items()
            if k.startswith("devices_per_launch.")
        }
        assert sum(occ.values()) == dump["launches"]
        assert sum(v for n, v in occ.items() if n > 1) == dump["sharded_launches"]
        assert sum(n * v for n, v in occ.items()) == dump["device_launches"]


class TestFusionBacklog:
    """Super-launch fusion perf contract (ISSUE 18 satellite): under a
    4-submitter backlog the aggregator must fuse ring-full window trips
    instead of queueing per-window launches — fused_launches >= 1 and
    strictly fewer device launches than windows dispatched."""

    def test_fusion_fires_under_four_submitter_backlog(self):
        import threading

        from ceph_tpu.codec.matrix_codec import EncodeAggregator

        ec = make_rs()
        rng = np.random.default_rng(17)
        agg = EncodeAggregator(
            window=4,
            max_bytes=1 << 30,
            inflight_max_bytes=1 << 30,
            pipeline_depth=1,
            fuse_max_windows=4,
        )
        threads, per_thread = 4, 8
        l0 = agg.perf.get("launches")
        f0 = agg.perf.get("fused_launches")
        results, errs = [[] for _ in range(threads)], []

        def worker(t):
            try:
                for i in range(per_thread):
                    h = rng.integers(0, 256, (1, 4, 2048), dtype=np.uint8)
                    results[t].append((h, agg.submit(ec, h)))
            except Exception as e:  # surfaced below; a thread must not die silently
                errs.append(e)

        ths = [
            threading.Thread(target=worker, args=(t,)) for t in range(threads)
        ]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        agg.flush()
        assert not errs, errs
        for bucket in results:
            for h, ticket in bucket:
                assert np.array_equal(
                    np.asarray(ticket), ec.encode_array_host(h)
                )
        launches = agg.perf.get("launches") - l0
        windows_dispatched = threads * per_thread // 4
        assert agg.perf.get("fused_launches") - f0 >= 1, (
            "a 4-submitter backlog never produced a fused launch"
        )
        assert launches < windows_dispatched, (
            f"{launches} launches for {windows_dispatched} windows: "
            "fusion is not reducing dispatch count under backlog"
        )


class TestRmwDeltaSmoke:
    """RMW delta-path perf contract (ISSUE 18 satellite): a cache-hit
    partial overwrite commits a delta flight record that moved zero
    bytes over PCIe — h2d_s == 0 and d2h_s == 0."""

    def test_cache_hit_rmw_commits_zero_pcie_flight_record(self):
        from test_ec_backend import (
            FLAG_EC_OVERWRITES,
            Cluster,
            ec_pool,
            payload,
        )

        from ceph_tpu.common.options import OPTIONS
        from ceph_tpu.ops.device_cache import device_chunk_cache
        from ceph_tpu.ops.flight_recorder import flight_recorder

        cc = device_chunk_cache()
        cc.configure(max_bytes=1 << 24)
        cc.clear()
        try:
            pool, profiles = ec_pool(4, 2, flags=FLAG_EC_OVERWRITES)
            c = Cluster(pool, profiles)
            sw = pool.stripe_width
            base = payload(2 * sw, seed=19)
            c.write("obj", 0, base)  # seeds every chunk resident
            d0 = cc.perf_dump()["delta_updates"]
            patch = payload(600, seed=20)
            c.write("obj", 100, patch)
            assert cc.perf_dump()["delta_updates"] > d0, (
                "the cache-hit overwrite did not take the delta path"
            )
            deltas = [
                r for r in flight_recorder().records()
                if r["flags"].get("delta")
            ]
            assert deltas, "no delta flight record committed"
            rec = deltas[-1]
            assert rec["flags"].get("cache_hit")
            assert rec["h2d_s"] == 0.0, (
                f"delta path uploaded bytes (h2d_s={rec['h2d_s']}); "
                "the zero-PCIe contract regressed"
            )
            assert rec["d2h_s"] == 0.0, (
                f"delta path downloaded bytes (d2h_s={rec['d2h_s']}); "
                "the zero-PCIe contract regressed"
            )
            expect = bytearray(base)
            expect[100:700] = patch
            assert c.read("obj", 0, len(expect)) == bytes(expect)
        finally:
            cc.clear()
            cc.configure(
                max_bytes=int(OPTIONS["ec_tpu_device_cache_bytes"].default)
            )
