"""Perf smoke (tier-1): dispatch-shape invariants of the coding hot paths.

Runs small encode/decode chains on the CPU backend and asserts the
launch counters and plan-cache hit rates, so a regression back to
per-stripe dispatch or per-call plan rebuilds fails `pytest -m 'not
slow'` immediately instead of only dilating `python bench.py`
(ISSUE 3 / ISSUE 5 satellites).  The counters are python-dispatch
witnesses — see ceph_tpu/ops/dispatch.py for what they do and don't
count; DECODE_LAUNCHES isolates the recovery/degraded-read half."""

import numpy as np

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import PLAN_CACHE
from ceph_tpu.ops.dispatch import DECODE_LAUNCHES, LAUNCHES
from ceph_tpu.stripe import StripeInfo
from ceph_tpu.stripe import stripe as stripe_mod


def make_rs(k=4, m=2):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


class TestPerfSmoke:
    def test_batched_encode_is_one_dispatch(self):
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        stripes = 32
        obj = np.random.default_rng(0).integers(
            0, 256, stripes * sinfo.stripe_width, dtype=np.uint8
        )
        # warm coder + jit caches with one small stripe
        ec.encode_array(obj[: sinfo.stripe_width].reshape(1, 4, 4096))
        before = LAUNCHES.snapshot()
        shards = stripe_mod.encode(sinfo, ec, obj)
        after = LAUNCHES.snapshot()
        assert after["launches"] - before["launches"] == 1, (
            f"{stripes} stripes took {after['launches'] - before['launches']} "
            "device dispatches; the batched path regressed to per-stripe launches"
        )
        assert after["stripes"] - before["stripes"] == stripes
        assert len(shards) == 6

    def test_degraded_read_chain_dispatch_budget(self):
        """Encode + reconstruct chain: one dispatch for the encode, one
        for the decode — losing a shard must not fan out per stripe."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        obj = np.random.default_rng(1).integers(
            0, 256, 16 * sinfo.stripe_width, dtype=np.uint8
        )
        shards = stripe_mod.encode(sinfo, ec, obj)
        have = {i: shards[i] for i in range(6) if i != 2}
        before = LAUNCHES.snapshot()
        logical = stripe_mod.decode_concat(sinfo, ec, have)
        launches = LAUNCHES.snapshot()["launches"] - before["launches"]
        assert np.array_equal(logical, obj)
        assert launches == 1, launches

    def test_plan_cache_steady_state_hit_rate(self):
        """Re-encoding with the same geometry must hit the coder cache:
        misses stay flat while hits climb."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        obj = np.random.default_rng(2).integers(
            0, 256, 4 * sinfo.stripe_width, dtype=np.uint8
        )
        stripe_mod.encode(sinfo, ec, obj)  # ensure the coder exists
        s0 = PLAN_CACHE.stats()
        for _ in range(5):
            stripe_mod.encode(sinfo, ec, obj)
        s1 = PLAN_CACHE.stats()
        assert s1["hits"] - s0["hits"] == 5
        assert s1["misses"] == s0["misses"], "steady-state encode rebuilt a plan"

    def test_recovery_decode_is_one_decode_dispatch(self):
        """Rebuilding whole shards for a 16-stripe object must cost one
        DECODE dispatch (the ISSUE 5 decode launch-counter contract) —
        and that dispatch also lands on the global total."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        stripes = 16
        obj = np.random.default_rng(3).integers(
            0, 256, stripes * sinfo.stripe_width, dtype=np.uint8
        )
        shards = stripe_mod.encode(sinfo, ec, obj)
        have = {i: shards[i] for i in range(6) if i not in (1, 4)}
        before_d = DECODE_LAUNCHES.snapshot()
        before_t = LAUNCHES.snapshot()
        rebuilt = stripe_mod.decode_shards(sinfo, ec, have, {1, 4})
        after_d = DECODE_LAUNCHES.snapshot()
        after_t = LAUNCHES.snapshot()
        assert np.array_equal(rebuilt[1], shards[1])
        assert np.array_equal(rebuilt[4], shards[4])
        assert after_d["launches"] - before_d["launches"] == 1, (
            f"{stripes}-stripe recovery took "
            f"{after_d['launches'] - before_d['launches']} decode dispatches; "
            "the batched decode path regressed to per-stripe launches"
        )
        assert after_d["stripes"] - before_d["stripes"] == stripes
        assert after_t["launches"] - before_t["launches"] == 1

    def test_decode_plan_cache_steady_state_hit_rate(self):
        """Re-decoding the same erasure pattern must hit the decode coder
        LRU: misses stay flat while hits climb (a regression to per-call
        Gaussian inversions would only show up in recovery latency)."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        obj = np.random.default_rng(4).integers(
            0, 256, 4 * sinfo.stripe_width, dtype=np.uint8
        )
        shards = stripe_mod.encode(sinfo, ec, obj)
        have = {i: shards[i] for i in range(6) if i != 2}
        stripe_mod.decode_shards(sinfo, ec, have, {2})  # coder exists
        s0 = PLAN_CACHE.stats()
        for _ in range(5):
            stripe_mod.decode_shards(sinfo, ec, have, {2})
        s1 = PLAN_CACHE.stats()
        assert s1["hits"] - s0["hits"] == 5
        assert s1["misses"] == s0["misses"], "steady-state decode rebuilt a plan"
