"""Perf smoke (tier-1): dispatch-shape invariants of the encode hot path.

Runs a small encode/decode chain on the CPU backend and asserts the
launch counter and plan-cache hit rate, so a regression back to
per-stripe dispatch or per-call plan rebuilds fails `pytest -m 'not
slow'` immediately instead of only dilating `python bench.py`
(ISSUE 3 satellite).  The counter is a python-dispatch witness — see
ceph_tpu/ops/dispatch.py for what it does and doesn't count."""

import numpy as np

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import PLAN_CACHE
from ceph_tpu.ops.dispatch import LAUNCHES
from ceph_tpu.stripe import StripeInfo
from ceph_tpu.stripe import stripe as stripe_mod


def make_rs(k=4, m=2):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


class TestPerfSmoke:
    def test_batched_encode_is_one_dispatch(self):
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        stripes = 32
        obj = np.random.default_rng(0).integers(
            0, 256, stripes * sinfo.stripe_width, dtype=np.uint8
        )
        # warm coder + jit caches with one small stripe
        ec.encode_array(obj[: sinfo.stripe_width].reshape(1, 4, 4096))
        before = LAUNCHES.snapshot()
        shards = stripe_mod.encode(sinfo, ec, obj)
        after = LAUNCHES.snapshot()
        assert after["launches"] - before["launches"] == 1, (
            f"{stripes} stripes took {after['launches'] - before['launches']} "
            "device dispatches; the batched path regressed to per-stripe launches"
        )
        assert after["stripes"] - before["stripes"] == stripes
        assert len(shards) == 6

    def test_degraded_read_chain_dispatch_budget(self):
        """Encode + reconstruct chain: one dispatch for the encode, one
        for the decode — losing a shard must not fan out per stripe."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        obj = np.random.default_rng(1).integers(
            0, 256, 16 * sinfo.stripe_width, dtype=np.uint8
        )
        shards = stripe_mod.encode(sinfo, ec, obj)
        have = {i: shards[i] for i in range(6) if i != 2}
        before = LAUNCHES.snapshot()
        logical = stripe_mod.decode_concat(sinfo, ec, have)
        launches = LAUNCHES.snapshot()["launches"] - before["launches"]
        assert np.array_equal(logical, obj)
        assert launches == 1, launches

    def test_plan_cache_steady_state_hit_rate(self):
        """Re-encoding with the same geometry must hit the coder cache:
        misses stay flat while hits climb."""
        ec = make_rs()
        sinfo = StripeInfo(4 * 4096, 4096)
        obj = np.random.default_rng(2).integers(
            0, 256, 4 * sinfo.stripe_width, dtype=np.uint8
        )
        stripe_mod.encode(sinfo, ec, obj)  # ensure the coder exists
        s0 = PLAN_CACHE.stats()
        for _ in range(5):
            stripe_mod.encode(sinfo, ec, obj)
        s1 = PLAN_CACHE.stats()
        assert s1["hits"] - s0["hits"] == 5
        assert s1["misses"] == s0["misses"], "steady-state encode rebuilt a plan"
