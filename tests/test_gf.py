"""GF(2^8) core validation — field axioms, table integrity, matrix math.

Mirrors the reference's tier-1 strategy (SURVEY.md §4): validate the math from
first principles before any codec builds on it.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.gf import (
    GF_MUL_TABLE,
    bitslice_bytes,
    coeff_bitmatrix,
    expand_matrix,
    gf_inv,
    gf_invert_matrix,
    gf_matmul,
    gf_mul,
    gf_mul_slow,
    gf_pow,
    identity,
    isa_cauchy_matrix,
    isa_decode_matrix,
    isa_rs_vandermonde_matrix,
    jerasure_cauchy_good_matrix,
    jerasure_cauchy_orig_matrix,
    jerasure_r6_matrix,
    jerasure_vandermonde_matrix,
    unbitslice_bytes,
    vandermonde_mds_check,
    xor_matmul_host,
)


def test_mul_table_matches_first_principles():
    # Full 256x256 check against carry-less multiply mod 0x11d.
    for a in range(0, 256, 7):
        for b in range(256):
            assert GF_MUL_TABLE[a, b] == gf_mul_slow(a, b)
    # Spot the full diagonal and first/last rows exactly.
    for a in range(256):
        assert GF_MUL_TABLE[a, a] == gf_mul_slow(a, a)
        assert GF_MUL_TABLE[0, a] == 0
        assert GF_MUL_TABLE[255, a] == gf_mul_slow(255, a)


def test_field_axioms():
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(0, 256, 3))
        assert gf_mul(a, b) == gf_mul(b, a)
        assert gf_mul(a, gf_mul(b, c)) == gf_mul(gf_mul(a, b), c)
        assert gf_mul(a, b ^ c) == gf_mul(a, b) ^ gf_mul(a, c)
        assert gf_mul(a, 1) == a
    for a in range(1, 256):
        assert gf_mul(a, gf_inv(a)) == 1


def test_gf_pow():
    for a in (1, 2, 3, 0x53):
        acc = 1
        for n in range(10):
            assert gf_pow(a, n) == acc
            acc = gf_mul(acc, a)


def test_matrix_inverse_roundtrip():
    rng = np.random.default_rng(1)
    for n in (2, 4, 8, 16):
        for _ in range(5):
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            inv = gf_invert_matrix(m)
            if inv is None:
                continue  # singular draw
            assert np.array_equal(gf_matmul(m, inv), identity(n))
            assert np.array_equal(gf_matmul(inv, m), identity(n))


def test_singular_matrix_returns_none():
    m = np.zeros((3, 3), dtype=np.uint8)
    m[0] = [1, 2, 3]
    m[1] = [2, 4, 6]  # 2 * row0 in GF => dependent
    m[1] = GF_MUL_TABLE[2, m[0]]
    m[2] = [5, 6, 7]
    assert gf_invert_matrix(m) is None


def test_isa_vandermonde_structure():
    a = isa_rs_vandermonde_matrix(8, 3)
    assert np.array_equal(a[:8], identity(8))
    # Parity row 0 all ones; row i is powers of 2^i.
    assert (a[8] == 1).all()
    for i in range(3):
        g = gf_pow(2, i)
        expect = [gf_pow(g, j) for j in range(8)]
        assert list(a[8 + i]) == expect


def test_isa_cauchy_structure():
    k, m = 8, 3
    a = isa_cauchy_matrix(k, m)
    assert np.array_equal(a[:k], identity(k))
    for i in range(k, k + m):
        for j in range(k):
            assert gf_mul(int(a[i, j]), i ^ j) == 1


def test_isa_cauchy_always_mds():
    for k, m in [(4, 2), (6, 3), (8, 3), (5, 4)]:
        assert vandermonde_mds_check(k, m, isa_cauchy_matrix(k, m))


def test_isa_vandermonde_mds_envelope():
    # Inside the reference's safety envelope these must be MDS
    # (ErasureCodeIsa.cc:331-361).
    for k, m in [(4, 2), (8, 3), (10, 3), (6, 4)]:
        assert vandermonde_mds_check(k, m, isa_rs_vandermonde_matrix(k, m))


def test_jerasure_vandermonde_systematic_mds():
    for k, m in [(4, 2), (7, 3), (8, 3), (10, 4)]:
        a = jerasure_vandermonde_matrix(k, m)
        assert np.array_equal(a[:k], identity(k))
        assert (a[k] == 1).all()  # first parity row all ones
        assert vandermonde_mds_check(k, m, a)


def test_jerasure_r6():
    a = jerasure_r6_matrix(6)
    assert (a[6] == 1).all()
    assert list(a[7]) == [gf_pow(2, j) for j in range(6)]
    assert vandermonde_mds_check(6, 2, a)


def test_jerasure_cauchy():
    for k, m in [(4, 2), (8, 3)]:
        orig = jerasure_cauchy_orig_matrix(k, m)
        good = jerasure_cauchy_good_matrix(k, m)
        for a in (orig, good):
            assert np.array_equal(a[:k], identity(k))
            assert vandermonde_mds_check(k, m, a)
        assert (good[k] == 1).all()
        # cauchy_good must not be heavier than cauchy_orig in bit-matrix ones.
        assert expand_matrix(good[k:]).sum() <= expand_matrix(orig[k:]).sum()


def test_isa_decode_matrix_reconstructs():
    k, m = 8, 3
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, (k, 64)).astype(np.uint8)
    for mat in (isa_rs_vandermonde_matrix(k, m), isa_cauchy_matrix(k, m)):
        full = gf_matmul(mat, data)  # (k+m, L) all chunks
        for nerr in (1, 2, 3):
            for erasures in itertools.combinations(range(k + m), nerr):
                res = isa_decode_matrix(mat, list(erasures), k)
                assert res is not None
                c, decode_index = res
                survivors = full[decode_index, :]
                rec = gf_matmul(c, survivors)
                for p, e in enumerate(erasures):
                    assert np.array_equal(rec[p], full[e]), (erasures, e)


# ---------------------------------------------------------------------------
# Bitslicing
# ---------------------------------------------------------------------------

def test_coeff_bitmatrix_is_multiplication():
    rng = np.random.default_rng(3)
    for c in [0, 1, 2, 3, 0x1D, 0x8E, 255]:
        mc = coeff_bitmatrix(c)
        for x in rng.integers(0, 256, 32):
            x = int(x)
            bits = (x >> np.arange(8)) & 1
            out_bits = (mc.astype(int) @ bits) & 1
            y = int((out_bits << np.arange(8)).sum())
            assert y == gf_mul(c, x)


def test_bitslice_roundtrip():
    rng = np.random.default_rng(4)
    d = rng.integers(0, 256, (5, 37)).astype(np.uint8)
    assert np.array_equal(unbitslice_bytes(bitslice_bytes(d)), d)


def test_xor_matmul_host_equals_gf_matmul():
    rng = np.random.default_rng(5)
    for k, m in [(4, 2), (8, 3), (10, 4)]:
        mat = isa_cauchy_matrix(k, m)[k:]  # (m, k) parity rows
        data = rng.integers(0, 256, (k, 128)).astype(np.uint8)
        want = gf_matmul(mat, data)
        got = xor_matmul_host(expand_matrix(mat), data)
        assert np.array_equal(want, got)
