"""MDS daemon tests: journaled metadata, capabilities, client protocol.

Models the reference's MDS coverage (src/test/mds, qa/tasks/cephfs):
namespace ops over the wire, journal replay after an MDS crash, cap
revocation between competing clients, and data I/O bypassing the MDS.
"""

import asyncio
import json

import pytest

from ceph_tpu.client import Rados
from ceph_tpu.mds import MDS, CephFSClient, FsClientError
from ceph_tpu.mds.mds import JOURNAL_OID
from ceph_tpu.mon import MonMap, Monitor

from test_cluster import start_cluster, stop_cluster, wait_until


async def _fs_cluster():
    monmap, mons, osds = await start_cluster(1, 3)
    rados = Rados(monmap)
    await rados.connect()
    await rados.pool_create("fs_meta", "replicated", size=2, pg_num=2)
    await rados.pool_create("fs_data", "replicated", size=2, pg_num=2)
    meta = await rados.open_ioctx("fs_meta")
    data = await rados.open_ioctx("fs_data")
    mds = MDS(meta, data)
    await mds.start()
    return monmap, mons, osds, rados, meta, data, mds


class TestMdsNamespace:
    def test_namespace_and_file_io_over_the_wire(self):
        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            fsc = CephFSClient(mds.addr, data)

            await fsc.mkdir("/home")
            await fsc.mkdir("/home/user")
            assert await fsc.listdir("/") == ["home"]
            assert await fsc.listdir("/home") == ["user"]
            with pytest.raises(FsClientError):
                await fsc.mkdir("/home")  # EEXIST
            with pytest.raises(FsClientError):
                await fsc.listdir("/ghost")

            payload = b"filesystem bytes " * 5000  # multi-object via striper
            await fsc.write_file("/home/user/doc.txt", payload)
            assert await fsc.read_file("/home/user/doc.txt") == payload
            st = await fsc.stat("/home/user/doc.txt")
            assert st["type"] == "file" and st["size"] == len(payload)

            # overwrite smaller: truncate-then-write, no stale tail
            await fsc.write_file("/home/user/doc.txt", b"short")
            assert await fsc.read_file("/home/user/doc.txt") == b"short"

            await fsc.rename("/home/user/doc.txt", "/home/moved.txt")
            assert await fsc.read_file("/home/moved.txt") == b"short"
            assert await fsc.listdir("/home/user") == []

            await fsc.unlink("/home/moved.txt")
            with pytest.raises(FsClientError):
                await fsc.stat("/home/moved.txt")
            await fsc.rmdir("/home/user")
            assert await fsc.listdir("/home") == []
            with pytest.raises(FsClientError):
                await fsc.rmdir("/home/ghost")

            await fsc.shutdown()
            await mds.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_rename_guards(self):
        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            fsc = CephFSClient(mds.addr, data)
            await fsc.mkdir("/a")
            await fsc.write_file("/a/f", b"keep me")

            # self-rename is a no-op, never a delete
            await fsc.rename("/a/f", "/a/f")
            assert await fsc.read_file("/a/f") == b"keep me"

            # a directory cannot move into its own subtree
            await fsc.mkdir("/a/b")
            with pytest.raises(FsClientError):
                await fsc.rename("/a", "/a/b/c")
            assert await fsc.listdir("/a") == ["b", "f"]

            await fsc.shutdown()
            await mds.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_two_clients_share_namespace(self):
        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            a = CephFSClient(mds.addr, data, name="client.a")
            b = CephFSClient(mds.addr, data, name="client.b")

            await a.mkdir("/shared")
            await a.write_file("/shared/from_a", b"written by a")
            # b sees a's metadata immediately (single authoritative MDS)
            assert await b.listdir("/shared") == ["from_a"]
            assert await b.read_file("/shared/from_a") == b"written by a"

            for c in (a, b):
                await c.shutdown()
            await mds.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestMdsJournal:
    def test_crash_before_flush_replays_journal(self):
        """Acked metadata survives an MDS crash that never wrote back its
        dirty dirfrags (the MDLog write-ahead property)."""

        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            # stop the periodic flush FIRST: a tick between the ops and the
            # simulated crash would legitimately trim the journal and
            # invalidate the non-empty assertion below
            mds._flush_task.cancel()
            fsc = CephFSClient(mds.addr, data)
            await fsc.mkdir("/durable")
            await fsc.write_file("/durable/f", b"journal me")
            await fsc.shutdown()

            # crash: no flush, no clean stop — just drop the daemon
            mds._running = False
            mds._flush_task.cancel()
            mds._flush_task = None
            await mds.msgr.shutdown()
            # the journal object must hold unflushed events
            raw = await meta.read(JOURNAL_OID)
            assert raw.strip(), "journal unexpectedly empty before flush"

            # a fresh MDS replays and serves the namespace
            mds2 = MDS(meta, data)
            await mds2.start()
            fsc2 = CephFSClient(mds2.addr, data)
            assert await fsc2.listdir("/") == ["durable"]
            assert await fsc2.read_file("/durable/f") == b"journal me"

            # after a flush the journal trims
            await mds2._flush()
            assert (await meta.read(JOURNAL_OID)) == b""
            head = json.loads((await meta.read("mds_journal_head")).decode())
            assert head["flushed"] >= 1

            await fsc2.shutdown()
            await mds2.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestCapabilities:
    def test_conflicting_writer_revokes_first_holder(self):
        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            a = CephFSClient(mds.addr, data, name="client.a")
            b = CephFSClient(mds.addr, data, name="client.b")

            fh_a = await a.create("/contested")
            await fh_a.write(b"a was here")
            assert fh_a.caps == "w"

            # b wants to write too: the MDS revokes a's caps first
            fh_b = await b.open("/contested", "w")
            assert fh_b.caps == "w"
            await wait_until(lambda: not fh_a.valid, 3.0, "revoke reaches a")
            with pytest.raises(FsClientError):
                await fh_a.write(b"stale handle")

            await fh_b.write(b"b takes over")
            await fh_b.close()

            # a re-opens and proceeds (the reference's cap-wait loop)
            fh_a2 = await a.open("/contested", "r")
            assert (await fh_a2.read()).startswith(b"b takes over")
            await fh_a2.close()

            for c in (a, b):
                await c.shutdown()
            await mds.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_readers_share_writer_excludes(self):
        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            a = CephFSClient(mds.addr, data, name="client.a")
            b = CephFSClient(mds.addr, data, name="client.b")
            await a.write_file("/f", b"data")

            r1 = await a.open("/f", "r")
            r2 = await b.open("/f", "r")  # readers coexist
            assert r1.valid and r2.valid
            ino = r1.entry["ino"]
            assert len(mds.caps[ino]) == 2

            w = await b.open("/f", "w")  # writer revokes both readers
            await wait_until(lambda: not r1.valid, 3.0, "reader caps revoked")
            assert len(mds.caps[ino]) == 1
            await w.close()

            for c in (a, b):
                await c.shutdown()
            await mds.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_dead_client_session_reset_frees_caps(self):
        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            dead = CephFSClient(mds.addr, data, name="client.dead")
            live = CephFSClient(mds.addr, data, name="client.live")

            fh = await dead.create("/orphan")
            await fh.write(b"x")
            await dead.shutdown()  # connection drops WITHOUT releasing

            await wait_until(lambda: not mds.caps, 3.0, "caps freed on reset")
            fh2 = await live.open("/orphan", "w")  # no revoke wait needed
            await fh2.write(b"y")
            await fh2.close()

            await live.shutdown()
            await mds.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestSymlinks:
    def test_symlink_readlink_unlink(self):
        """Server::handle_client_symlink essence: symlink dentries hold
        their target; readlink resolves EXPLICITLY (the client follows,
        as in the reference's client-side symlink traversal); unlink
        removes them like files; they survive journal replay."""

        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            fsc = CephFSClient(mds.addr, data)
            await fsc.mkdir("/d")
            await fsc.write_file("/d/real.txt", b"pointed-at")
            await fsc.symlink("/d/real.txt", "/d/link")
            assert await fsc.readlink("/d/link") == "/d/real.txt"
            st = await fsc.stat("/d/link")
            assert st["type"] == "symlink"
            # readdirplus: stat records inline, one round trip
            plus = await fsc.listdir_plus("/d")
            assert set(plus) == {"link", "real.txt"}
            assert plus["real.txt"]["type"] == "file"
            assert plus["real.txt"]["size"] == len(b"pointed-at")
            assert plus["link"]["target"] == "/d/real.txt"
            # explicit client-side follow
            assert await fsc.read_file(await fsc.readlink("/d/link")) == b"pointed-at"
            assert sorted(await fsc.listdir("/d")) == ["link", "real.txt"]
            with pytest.raises(FsClientError):
                await fsc.readlink("/d/real.txt")  # not a symlink
            with pytest.raises(FsClientError):
                await fsc.symlink("/x", "/d/link")  # EEXIST
            # symlinks survive an MDS crash via journal replay
            await mds.stop(flush=False)
            mds2 = MDS(meta, data)
            await mds2.start()
            fsc2 = CephFSClient(mds2.addr, data, name="client.fs2")
            assert await fsc2.readlink("/d/link") == "/d/real.txt"
            # unlink removes the link, not the target
            await fsc2.unlink("/d/link")
            assert await fsc2.listdir("/d") == ["real.txt"]
            assert await fsc2.read_file("/d/real.txt") == b"pointed-at"
            await fsc.shutdown()
            await fsc2.shutdown()
            await mds2.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestExactlyOnceRetries:
    """ISSUE 7 satellite (ADVICE round-5 medium): retries of
    non-idempotent ops keep a STABLE (client, tid) reqid and the MDS
    journals completed results per reqid — a replayed request returns
    the ORIGINAL reply instead of re-executing (no spurious
    EEXIST/ENOENT after failover)."""

    @staticmethod
    async def _resend(fsc, mds_addr, tid, op, args):
        """Re-send a request with an already-used reqid, as the client's
        retry loop would after a lost reply."""
        from ceph_tpu.msg.messages import MClientRequest

        fut = asyncio.get_event_loop().create_future()
        fsc._replies[tid] = fut
        msg = MClientRequest(
            tid=tid, op=op, args=json.dumps(args).encode(),
            client=fsc.client_id,
        )
        await fsc.msgr.send_to(mds_addr, msg)
        try:
            return await asyncio.wait_for(fut, 5.0)
        finally:
            fsc._replies.pop(tid, None)

    def test_retried_mkdir_replays_original_result(self):
        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            fsc = CephFSClient(mds.addr, data)
            await fsc.mkdir("/once")  # allocated tid 1
            # the retry (same reqid) replays success — NOT EEXIST
            reply = await self._resend(
                fsc, mds.addr, 1, "mkdir", {"path": "/once"}
            )
            assert reply.result == 0
            # a genuinely NEW request for the same path still conflicts
            with pytest.raises(FsClientError):
                await fsc.mkdir("/once")
            await fsc.shutdown()
            await mds.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_retry_after_crash_replays_from_journal(self):
        """The completed-request record is write-ahead journaled: a crash
        before flush still lets the promoted MDS replay the original
        reply to a retried mkdir/unlink."""

        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            mds._flush_task.cancel()  # no flush: journal is the only record
            fsc = CephFSClient(mds.addr, data)
            await fsc.mkdir("/j")          # tid 1
            await fsc.mkdir("/j/sub")      # tid 2
            # crash without flush, promote a fresh daemon on the pools
            mds._running = False
            mds._flush_task = None
            await mds.msgr.shutdown()
            mds2 = MDS(meta, data)
            await mds2.start()
            # retried tids replay their original success
            for tid, path in ((1, "/j"), (2, "/j/sub")):
                reply = await self._resend(
                    fsc, mds2.addr, tid, "mkdir", {"path": path}
                )
                assert reply.result == 0, (tid, path, reply.result)
            # new requests see the real namespace state
            fsc2 = CephFSClient(mds2.addr, data, name="client.fs2")
            with pytest.raises(FsClientError):
                await fsc2.mkdir("/j")
            await fsc.shutdown()
            await fsc2.shutdown()
            await mds2.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_retry_after_flush_replays_from_completed_table(self):
        """A journal TRIM must not forget completed requests: the table
        persists in mds_completed at flush and reloads on promotion."""

        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            fsc = CephFSClient(mds.addr, data)
            await fsc.mkdir("/t")  # tid 1
            await mds._flush()     # journal trims; table persisted
            assert (await meta.read(JOURNAL_OID)) == b""
            await mds.stop(flush=False)
            mds2 = MDS(meta, data)
            await mds2.start()
            reply = await self._resend(
                fsc, mds2.addr, 1, "mkdir", {"path": "/t"}
            )
            assert reply.result == 0
            await fsc.shutdown()
            await mds2.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_replayed_create_regrants_caps(self):
        """A retried create must leave the retrying session holding the
        caps its recorded reply promises, or the next setattr bounces."""

        async def run():
            monmap, mons, osds, rados, meta, data, mds = await _fs_cluster()
            fsc = CephFSClient(mds.addr, data)
            fh = await fsc.create("/f.txt")  # tid 1: grants "w"
            ino = fh.entry["ino"]
            reply = await self._resend(
                fsc, mds.addr, 1, "create", {"path": "/f.txt", "caps": "w"}
            )
            assert reply.result == 0
            payload = json.loads(reply.payload.decode())
            assert payload["entry"]["ino"] == ino
            assert payload["caps"] == "w"
            # the session holds the re-granted caps: handle-held setattr
            # (the cap-checked op) succeeds
            await fh.truncate(0)
            await fh.close()
            await fsc.shutdown()
            await mds.stop()
            await rados.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())
