"""ConfigMonitor / LogMonitor / AuthMonitor paxos-service tests.

Models the reference's mon service coverage (src/test/mon/,
qa/workunits/mon): propose → commit → every quorum member converges;
subscribers receive pushes; daemons consume them at runtime.
"""

import asyncio
import base64
import json

from ceph_tpu.client import Rados
from ceph_tpu.mon import MonMap, Monitor

from test_cluster import fast_conf, start_cluster, stop_cluster, wait_until
from test_mon import free_port_addrs


async def start_mons(n: int):
    monmap = MonMap(addrs=free_port_addrs(n))
    mons = [Monitor(name, monmap, election_timeout=0.3) for name in monmap.addrs]
    for m in mons:
        await m.start()
    for m in mons:
        await m.wait_for_quorum()
    return monmap, mons


class TestConfigMonitor:
    def test_set_get_dump_rm_quorum_converges(self):
        async def run():
            monmap, mons = await start_mons(3)
            client = Rados(monmap)
            await client.connect()

            rv, rs, _ = await client.mon_command(
                {"prefix": "config set", "who": "osd", "name": "osd_max_backfills", "value": "7"}
            )
            assert rv == 0, rs
            rv, _, out = await client.mon_command(
                {"prefix": "config get", "who": "osd.1"}
            )
            assert rv == 0
            assert json.loads(out)["osd_max_backfills"] == "7"

            # Named-daemon layer wins over the type layer.
            rv, _, _ = await client.mon_command(
                {"prefix": "config set", "who": "osd.1", "name": "osd_max_backfills", "value": "2"}
            )
            assert rv == 0
            _, _, out = await client.mon_command({"prefix": "config get", "who": "osd.1"})
            assert json.loads(out)["osd_max_backfills"] == "2"
            _, _, out = await client.mon_command({"prefix": "config get", "who": "osd.2"})
            assert json.loads(out)["osd_max_backfills"] == "7"

            # Every quorum member holds the same committed store.
            await wait_until(
                lambda: all(m.configmon.version == mons[0].configmon.version for m in mons),
                3.0,
                "config versions converge",
            )
            assert all(m.configmon.sections == mons[0].configmon.sections for m in mons)

            rv, _, _ = await client.mon_command(
                {"prefix": "config rm", "who": "osd.1", "name": "osd_max_backfills"}
            )
            assert rv == 0
            _, _, out = await client.mon_command({"prefix": "config get", "who": "osd.1"})
            assert json.loads(out)["osd_max_backfills"] == "7"

            _, _, out = await client.mon_command({"prefix": "config dump"})
            dump = json.loads(out)
            assert dump["sections"]["osd"]["osd_max_backfills"] == "7"

            # Unknown options and type-invalid values are rejected at the
            # command, never committed (ConfigMonitor::prepare_command).
            rv, rs, _ = await client.mon_command(
                {"prefix": "config set", "who": "osd", "name": "osd_max_backfils", "value": "3"}
            )
            assert rv < 0 and "unrecognized" in rs
            rv, rs, _ = await client.mon_command(
                {"prefix": "config set", "who": "osd", "name": "osd_max_backfills", "value": "nope"}
            )
            assert rv < 0 and "invalid value" in rs

            await client.shutdown()
            await stop_cluster(mons, [])

        asyncio.run(run())

    def test_osd_consumes_pushed_config_at_runtime(self):
        """`config set osd ...` reaches a live OSD's runtime Config and
        fires its observers — the ConfigMonitor→MConfig→md_config_t path."""

        async def run():
            monmap, mons, osds = await start_cluster(1, 2)
            client = Rados(monmap)
            await client.connect()

            observed: list[tuple[str, object]] = []
            osds[0].conf.add_observer(
                ["osd_recovery_max_active"], lambda n, v: observed.append((n, v))
            )

            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "config set",
                    "who": "osd",
                    "name": "osd_recovery_max_active",
                    "value": "11",
                }
            )
            assert rv == 0, rs
            await wait_until(
                lambda: osds[0].conf.get("osd_recovery_max_active") == 11
                and osds[1].conf.get("osd_recovery_max_active") == 11,
                3.0,
                "config push to OSDs",
            )
            assert ("osd_recovery_max_active", 11) in observed

            # A named-daemon override targets exactly one OSD.
            rv, _, _ = await client.mon_command(
                {
                    "prefix": "config set",
                    "who": "osd.1",
                    "name": "osd_recovery_max_active",
                    "value": "3",
                }
            )
            assert rv == 0
            await wait_until(
                lambda: osds[1].conf.get("osd_recovery_max_active") == 3,
                3.0,
                "named config push",
            )
            assert osds[0].conf.get("osd_recovery_max_active") == 11

            # `config rm` of the last defining layer reverts live daemons to
            # the option default (md_config_t resets removed options).
            for who in ("osd.1", "osd"):
                rv, _, _ = await client.mon_command(
                    {"prefix": "config rm", "who": who, "name": "osd_recovery_max_active"}
                )
                assert rv == 0
            default = osds[0].conf.get_option("osd_recovery_max_active").default
            await wait_until(
                lambda: all(
                    o.conf.get("osd_recovery_max_active") == default for o in osds
                ),
                3.0,
                "config revert to default",
            )

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestLogMonitor:
    def test_clog_error_reaches_log_last(self):
        """An OSD clog_error lands in the committed cluster log, queryable
        via `log last` from any mon (the ECBackend CRC-mismatch sink)."""

        async def run():
            monmap, mons, osds = await start_cluster(3, 1)
            client = Rados(monmap)
            await client.connect()

            osds[0].clog_error("pg 1.0 scrub: oid inconsistent on shard 2")
            await wait_until(
                lambda: any("inconsistent" in e["msg"] for m in mons for e in m.logmon.entries),
                3.0,
                "clog entry committed",
            )
            # All quorum members converge on the same log version.
            await wait_until(
                lambda: all(m.logmon.version == mons[0].logmon.version for m in mons),
                3.0,
                "log versions converge",
            )

            rv, _, out = await client.mon_command({"prefix": "log last", "num": 10})
            assert rv == 0
            got = json.loads(out)
            assert any("inconsistent" in e["msg"] for e in got["entries"])
            entry = next(e for e in got["entries"] if "inconsistent" in e["msg"])
            assert entry["prio"] == "error"
            assert entry["who"] == "osd.0"

            # Level filter.
            rv, _, out = await client.mon_command(
                {"prefix": "log last", "num": 10, "level": "info"}
            )
            assert not any(
                "inconsistent" in e["msg"] for e in json.loads(out)["entries"]
            )

            # num=0 is a version probe, not "everything".
            rv, _, out = await client.mon_command({"prefix": "log last", "num": 0})
            probe = json.loads(out)
            assert probe["entries"] == [] and probe["version"] >= 1

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


def _slo_digest(*pools: str) -> dict:
    """A faked mgr PGMap digest slice that raises SLO_LATENCY_BREACH
    with one detail line per pool (the iostat module's breach shape)."""
    return {
        "slo": {
            "breaches": {
                str(7 + i): {
                    "pool": p,
                    "target_ms": 10.0,
                    "burn_fast": 2.0,
                    "burn_slow": 1.5,
                    "p99_ms": 50.0,
                }
                for i, p in enumerate(pools)
            }
        }
    }


class TestClusterEventTimeline:
    """ISSUE 16: LogMonitor paxos semantics — bounded committed tail,
    quorum convergence, election durability — plus health-event history
    and the mute lifecycle (TTL, worsen auto-unmute, sticky)."""

    def test_log_flood_bounded_and_quorum_identical(self):
        """Flooding 3x past `mon_log_max` keeps every member's committed
        tail bounded AND byte-identical across the quorum; `log last`
        channel/severity filters slice the same committed tail."""

        async def run():
            import time

            monmap, mons = await start_mons(3)
            # satellite: the keep bound is the registered option, not a
            # baked-in constant — lower it at runtime and flood past it
            assert mons[0].conf.get_option("mon_log_max").default == 500
            for m in mons:
                m.conf.set("mon_log_max", 40)
            client = Rados(monmap)
            await client.connect()
            monc = client.objecter.monc

            for i in range(120):
                await monc.send_log(
                    [
                        {
                            "prio": "error" if i % 3 == 0 else "info",
                            "channel": "audit" if i % 5 == 0 else "cluster",
                            "who": "client.flood",
                            "seq": i + 1,
                            "stamp": time.time(),
                            "msg": f"flood entry {i}",
                        }
                    ]
                )
            await wait_until(
                lambda: any(
                    "flood entry 119" in e["msg"] for e in mons[0].logmon.entries
                ),
                5.0,
                "flood committed",
            )
            await wait_until(
                lambda: all(
                    m.logmon.version == mons[0].logmon.version for m in mons
                ),
                3.0,
                "log versions converge",
            )
            lead = [(e["who"], e.get("seq"), e["msg"]) for e in mons[0].logmon.entries]
            for m in mons:
                assert 0 < len(m.logmon.entries) <= 40, len(m.logmon.entries)
                assert [
                    (e["who"], e.get("seq"), e["msg"]) for e in m.logmon.entries
                ] == lead

            # channel filter slices the committed tail
            rv, _, out = await client.mon_command(
                {"prefix": "log last", "num": 1000, "channel": "audit"}
            )
            assert rv == 0
            got = json.loads(out)["entries"]
            assert got and all(e["channel"] == "audit" for e in got)
            # severity filter is an exact match, not a floor
            rv, _, out = await client.mon_command(
                {"prefix": "log last", "num": 1000, "level": "error"}
            )
            got = json.loads(out)["entries"]
            assert got and all(e["prio"] == "error" for e in got)

            await client.shutdown()
            await stop_cluster(mons, [])

        asyncio.run(run())

    def test_election_preserves_committed_entries(self):
        """Committed clog entries survive losing the leader: the new
        quorum serves the same tail and keeps accepting appends."""

        async def run():
            import time

            monmap, mons = await start_mons(3)
            client = Rados(monmap)
            await client.connect()
            monc = client.objecter.monc
            for i in range(10):
                await monc.send_log(
                    [
                        {
                            "prio": "info",
                            "channel": "cluster",
                            "who": "client.pre",
                            "seq": i + 1,
                            "stamp": time.time(),
                            "msg": f"pre-election {i}",
                        }
                    ]
                )
            await wait_until(
                lambda: all(
                    any("pre-election 9" in e["msg"] for e in m.logmon.entries)
                    for m in mons
                ),
                5.0,
                "pre-election entries committed everywhere",
            )
            committed = [
                e["msg"] for e in mons[1].logmon.entries
                if e["msg"].startswith("pre-election")
            ]
            assert len(committed) == 10

            await mons[0].stop()
            mons[1].elector.start()
            await wait_until(
                lambda: any(m.is_leader() for m in mons[1:]),
                5.0,
                "re-election",
            )
            # every committed entry survived on both survivors (the new
            # leader's health tick may append MON_DOWN lines on top)
            for m in mons[1:]:
                msgs = [e["msg"] for e in m.logmon.entries]
                assert all(c in msgs for c in committed), msgs
            # ...and the new quorum keeps accepting appends (a command
            # first: send_log is best-effort at the monc's current
            # target, and that target just died — the command hunt
            # re-points the monc at a live mon)
            rv, _, _ = await client.mon_command({"prefix": "health"})
            assert rv == 0
            await monc.send_log(
                [
                    {
                        "prio": "info",
                        "channel": "cluster",
                        "who": "client.post",
                        "seq": 1,
                        "stamp": time.time(),
                        "msg": "post-election entry",
                    }
                ]
            )
            await wait_until(
                lambda: all(
                    any("post-election" in e["msg"] for e in m.logmon.entries)
                    for m in mons[1:]
                ),
                5.0,
                "post-election append committed",
            )

            await client.shutdown()
            await stop_cluster(mons[1:], [])

        asyncio.run(run())

    def test_health_mute_ttl_worsen_sticky(self):
        """The mute lifecycle on one mon: TTL expiry re-raises the
        banner, a non-sticky mute auto-clears when the check worsens, a
        sticky mute survives worsening, and the history command shows
        the raise/update transitions."""

        async def run():
            monmap, mons = await start_mons(1)
            mon = mons[0]
            mon.conf.set("mon_tick_interval", 0.05)
            client = Rados(monmap)
            await client.connect()

            mon.pg_digest = _slo_digest("cacheA")
            await wait_until(
                lambda: "SLO_LATENCY_BREACH" in mon.logmon.active_checks,
                5.0,
                "SLO check raised",
            )
            rv, _, out = await client.mon_command({"prefix": "health"})
            h = json.loads(out)
            assert h["status"] == "HEALTH_WARN"
            assert "SLO_LATENCY_BREACH" in h["checks"]

            # TTL mute: banner goes green, the raw check keeps being
            # evaluated underneath, and expiry re-raises the banner
            rv, rs, _ = await client.mon_command(
                {"prefix": "health mute", "code": "SLO_LATENCY_BREACH",
                 "ttl": "1s"}
            )
            assert rv == 0 and "muted" in rs, rs
            rv, _, out = await client.mon_command({"prefix": "health"})
            h = json.loads(out)
            assert h["status"] == "HEALTH_OK"
            assert "SLO_LATENCY_BREACH" in h["muted"]
            assert "SLO_LATENCY_BREACH" not in h["checks"]
            assert "SLO_LATENCY_BREACH" in mon.health_checks()[0]
            await wait_until(
                lambda: "SLO_LATENCY_BREACH" not in mon.logmon.mutes,
                5.0,
                "ttl expiry committed",
            )
            rv, _, out = await client.mon_command({"prefix": "health"})
            assert json.loads(out)["status"] == "HEALTH_WARN"
            assert any(
                "health mute SLO_LATENCY_BREACH expired" in e["msg"]
                for e in mon.logmon.entries
            )

            # non-sticky mute auto-clears when the check worsens
            rv, _, _ = await client.mon_command(
                {"prefix": "health mute", "code": "SLO_LATENCY_BREACH"}
            )
            assert rv == 0
            assert "SLO_LATENCY_BREACH" in mon.logmon.mutes
            mon.pg_digest = _slo_digest("cacheA", "cacheB")  # 1 -> 2 pools
            await wait_until(
                lambda: "SLO_LATENCY_BREACH" not in mon.logmon.mutes,
                5.0,
                "worsen auto-unmute",
            )
            assert any(
                "check worsened (1 -> 2)" in e["msg"]
                for e in mon.logmon.entries
            )
            rv, _, out = await client.mon_command({"prefix": "health"})
            assert json.loads(out)["status"] == "HEALTH_WARN"

            # a sticky mute survives the same worsening
            rv, _, _ = await client.mon_command(
                {"prefix": "health mute", "code": "SLO_LATENCY_BREACH",
                 "sticky": True}
            )
            assert rv == 0
            mon.pg_digest = _slo_digest("cacheA", "cacheB", "cacheC")
            await asyncio.sleep(0.4)  # several leader ticks
            assert "SLO_LATENCY_BREACH" in mon.logmon.mutes
            rv, _, out = await client.mon_command({"prefix": "health"})
            assert json.loads(out)["status"] == "HEALTH_OK"

            # the history shows the transitions and the live mute
            rv, _, out = await client.mon_command({"prefix": "health history"})
            body = json.loads(out)
            assert body["events_total"] >= 2
            kinds = {(ev["type"], ev["code"]) for ev in body["events"]}
            assert ("raise", "SLO_LATENCY_BREACH") in kinds
            assert ("update", "SLO_LATENCY_BREACH") in kinds
            assert "SLO_LATENCY_BREACH" in body["mutes"]
            assert body["mutes"]["SLO_LATENCY_BREACH"]["sticky"] is True

            # unmute; a second unmute is ENOENT, an empty code EINVAL
            rv, _, _ = await client.mon_command(
                {"prefix": "health unmute", "code": "SLO_LATENCY_BREACH"}
            )
            assert rv == 0
            rv, _, _ = await client.mon_command(
                {"prefix": "health unmute", "code": "SLO_LATENCY_BREACH"}
            )
            assert rv == -2
            rv, _, _ = await client.mon_command(
                {"prefix": "health mute", "code": ""}
            )
            assert rv == -22

            await client.shutdown()
            await stop_cluster(mons, [])

        asyncio.run(run())

    def test_muted_check_survives_election(self):
        """ISSUE 16 acceptance: mute SLO_LATENCY_BREACH, the banner goes
        HEALTH_OK while the raw check keeps being evaluated, the mute
        replicates to every quorum member via paxos, and it survives
        losing the leader."""

        async def run():
            monmap, mons = await start_mons(3)
            for m in mons:
                m.conf.set("mon_tick_interval", 0.05)
                m.pg_digest = _slo_digest("hotpool")
            client = Rados(monmap)
            await client.connect()

            await wait_until(
                lambda: "SLO_LATENCY_BREACH" in mons[0].logmon.active_checks,
                5.0,
                "SLO check raised",
            )
            rv, rs, _ = await client.mon_command(
                {"prefix": "health mute", "code": "SLO_LATENCY_BREACH"}
            )
            assert rv == 0, rs
            rv, _, out = await client.mon_command({"prefix": "health"})
            h = json.loads(out)
            assert h["status"] == "HEALTH_OK"
            assert "SLO_LATENCY_BREACH" in h["muted"]
            # the mute is committed state on EVERY member, and the raw
            # check is still evaluated (still scraped) underneath
            await wait_until(
                lambda: all(
                    "SLO_LATENCY_BREACH" in m.logmon.mutes for m in mons
                ),
                3.0,
                "mute replicated to quorum",
            )
            assert "SLO_LATENCY_BREACH" in mons[0].health_checks()[0]
            # the mutating command landed on the audit channel everywhere
            await wait_until(
                lambda: any(
                    e["channel"] == "audit" and "health mute" in e["msg"]
                    for e in mons[1].logmon.entries
                ),
                3.0,
                "mute audited",
            )

            # leader dies; survivors elect; the mute rode paxos
            await mons[0].stop()
            mons[1].elector.start()
            await wait_until(
                lambda: any(m.is_leader() for m in mons[1:]),
                5.0,
                "re-election",
            )
            for m in mons[1:]:
                assert "SLO_LATENCY_BREACH" in m.logmon.mutes
            rv, _, out = await client.mon_command({"prefix": "health"})
            h = json.loads(out)
            assert "SLO_LATENCY_BREACH" in h["muted"]
            assert "SLO_LATENCY_BREACH" not in h["checks"]

            await client.shutdown()
            await stop_cluster(mons[1:], [])

        asyncio.run(run())


class TestAuthMonitor:
    def test_key_crud_replicates(self):
        async def run():
            monmap, mons = await start_mons(3)
            client = Rados(monmap)
            await client.connect()

            rv, _, out = await client.mon_command(
                {"prefix": "auth get-or-create", "entity": "client.admin"}
            )
            assert rv == 0
            created = json.loads(out)
            key = base64.b64decode(created["key"])
            assert len(key) == 16

            # get-or-create is idempotent; get returns the same key.
            rv, _, out = await client.mon_command(
                {"prefix": "auth get-or-create", "entity": "client.admin"}
            )
            assert json.loads(out)["key"] == created["key"]
            rv, _, out = await client.mon_command(
                {"prefix": "auth get", "entity": "client.admin"}
            )
            assert json.loads(out)["key"] == created["key"]

            rv, rs, _ = await client.mon_command(
                {"prefix": "auth add", "entity": "osd.0"}
            )
            assert rv == 0, rs
            rv, rs, _ = await client.mon_command(
                {"prefix": "auth add", "entity": "osd.0"}
            )
            assert rv == -17  # EEXIST

            rv, _, out = await client.mon_command({"prefix": "auth ls"})
            assert set(json.loads(out)) == {"client.admin", "osd.0"}

            # Quorum members share the authoritative keyring byte-for-byte.
            await wait_until(
                lambda: all(
                    m.authmon.keyring.dumps() == mons[0].authmon.keyring.dumps()
                    and len(m.authmon.keyring) == 2
                    for m in mons
                ),
                3.0,
                "keyrings converge",
            )

            rv, _, _ = await client.mon_command(
                {"prefix": "auth del", "entity": "osd.0"}
            )
            assert rv == 0
            rv, _, _ = await client.mon_command(
                {"prefix": "auth get", "entity": "osd.0"}
            )
            assert rv == -2  # ENOENT

            await client.shutdown()
            await stop_cluster(mons, [])

        asyncio.run(run())


def test_osd_pool_get():
    """`osd pool get <pool> <var>|all` (OSDMonitor get variants)."""

    async def run():
        import json

        from ceph_tpu.client import Rados
        from test_cluster import start_cluster, stop_cluster

        monmap, mons, osds = await start_cluster(1, 3)
        client = Rados(monmap)
        await client.connect()
        await client.pool_create("gp", "replicated", size=2)
        rv, _, out = await client.mon_command(
            {"prefix": "osd pool get", "pool": "gp", "var": "size"}
        )
        assert rv == 0 and json.loads(out) == {"size": 2}
        rv, _, out = await client.mon_command(
            {"prefix": "osd pool get", "pool": "gp"}
        )
        allinfo = json.loads(out)
        assert allinfo["pg_num"] > 0 and allinfo["quota_max_objects"] == 0
        rv, _, _ = await client.mon_command(
            {"prefix": "osd pool get", "pool": "gp", "var": "bogus"}
        )
        assert rv != 0
        rv, _, _ = await client.mon_command(
            {"prefix": "osd pool get", "pool": "nope"}
        )
        assert rv != 0
        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())


def test_pool_application_and_health():
    """`osd pool application enable/get` tagging and the standalone
    `health` command (application_metadata + ClusterHealth essence)."""

    async def run():
        import json

        from ceph_tpu.client import Rados
        from test_cluster import start_cluster, stop_cluster

        monmap, mons, osds = await start_cluster(1, 3)
        client = Rados(monmap)
        await client.connect()
        await client.pool_create("appp", "replicated", size=2)
        rv, rs, _ = await client.mon_command(
            {"prefix": "osd pool application enable", "pool": "appp",
             "app": "rbd"}
        )
        assert rv == 0, rs
        rv, _, out = await client.mon_command(
            {"prefix": "osd pool application get", "pool": "appp"}
        )
        assert json.loads(out) == {"application": "rbd"}
        # retagging to a different app is refused
        rv, _, _ = await client.mon_command(
            {"prefix": "osd pool application enable", "pool": "appp",
             "app": "rgw"}
        )
        assert rv != 0
        # the tag propagates to clients through the map
        def tagged():
            p = client.objecter.osdmap.get_pool("appp")
            return p is not None and p.application == "rbd"
        from test_cluster import wait_until
        await wait_until(tagged, 5.0, "application tag in client map")
        # health: standalone check payload
        rv, _, out = await client.mon_command({"prefix": "health"})
        assert rv == 0
        h = json.loads(out)
        assert h["status"] in ("HEALTH_OK", "HEALTH_WARN")
        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())
