"""ConfigMonitor / LogMonitor / AuthMonitor paxos-service tests.

Models the reference's mon service coverage (src/test/mon/,
qa/workunits/mon): propose → commit → every quorum member converges;
subscribers receive pushes; daemons consume them at runtime.
"""

import asyncio
import base64
import json

from ceph_tpu.client import Rados
from ceph_tpu.mon import MonMap, Monitor

from test_cluster import fast_conf, start_cluster, stop_cluster, wait_until
from test_mon import free_port_addrs


async def start_mons(n: int):
    monmap = MonMap(addrs=free_port_addrs(n))
    mons = [Monitor(name, monmap, election_timeout=0.3) for name in monmap.addrs]
    for m in mons:
        await m.start()
    for m in mons:
        await m.wait_for_quorum()
    return monmap, mons


class TestConfigMonitor:
    def test_set_get_dump_rm_quorum_converges(self):
        async def run():
            monmap, mons = await start_mons(3)
            client = Rados(monmap)
            await client.connect()

            rv, rs, _ = await client.mon_command(
                {"prefix": "config set", "who": "osd", "name": "osd_max_backfills", "value": "7"}
            )
            assert rv == 0, rs
            rv, _, out = await client.mon_command(
                {"prefix": "config get", "who": "osd.1"}
            )
            assert rv == 0
            assert json.loads(out)["osd_max_backfills"] == "7"

            # Named-daemon layer wins over the type layer.
            rv, _, _ = await client.mon_command(
                {"prefix": "config set", "who": "osd.1", "name": "osd_max_backfills", "value": "2"}
            )
            assert rv == 0
            _, _, out = await client.mon_command({"prefix": "config get", "who": "osd.1"})
            assert json.loads(out)["osd_max_backfills"] == "2"
            _, _, out = await client.mon_command({"prefix": "config get", "who": "osd.2"})
            assert json.loads(out)["osd_max_backfills"] == "7"

            # Every quorum member holds the same committed store.
            await wait_until(
                lambda: all(m.configmon.version == mons[0].configmon.version for m in mons),
                3.0,
                "config versions converge",
            )
            assert all(m.configmon.sections == mons[0].configmon.sections for m in mons)

            rv, _, _ = await client.mon_command(
                {"prefix": "config rm", "who": "osd.1", "name": "osd_max_backfills"}
            )
            assert rv == 0
            _, _, out = await client.mon_command({"prefix": "config get", "who": "osd.1"})
            assert json.loads(out)["osd_max_backfills"] == "7"

            _, _, out = await client.mon_command({"prefix": "config dump"})
            dump = json.loads(out)
            assert dump["sections"]["osd"]["osd_max_backfills"] == "7"

            # Unknown options and type-invalid values are rejected at the
            # command, never committed (ConfigMonitor::prepare_command).
            rv, rs, _ = await client.mon_command(
                {"prefix": "config set", "who": "osd", "name": "osd_max_backfils", "value": "3"}
            )
            assert rv < 0 and "unrecognized" in rs
            rv, rs, _ = await client.mon_command(
                {"prefix": "config set", "who": "osd", "name": "osd_max_backfills", "value": "nope"}
            )
            assert rv < 0 and "invalid value" in rs

            await client.shutdown()
            await stop_cluster(mons, [])

        asyncio.run(run())

    def test_osd_consumes_pushed_config_at_runtime(self):
        """`config set osd ...` reaches a live OSD's runtime Config and
        fires its observers — the ConfigMonitor→MConfig→md_config_t path."""

        async def run():
            monmap, mons, osds = await start_cluster(1, 2)
            client = Rados(monmap)
            await client.connect()

            observed: list[tuple[str, object]] = []
            osds[0].conf.add_observer(
                ["osd_recovery_max_active"], lambda n, v: observed.append((n, v))
            )

            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "config set",
                    "who": "osd",
                    "name": "osd_recovery_max_active",
                    "value": "11",
                }
            )
            assert rv == 0, rs
            await wait_until(
                lambda: osds[0].conf.get("osd_recovery_max_active") == 11
                and osds[1].conf.get("osd_recovery_max_active") == 11,
                3.0,
                "config push to OSDs",
            )
            assert ("osd_recovery_max_active", 11) in observed

            # A named-daemon override targets exactly one OSD.
            rv, _, _ = await client.mon_command(
                {
                    "prefix": "config set",
                    "who": "osd.1",
                    "name": "osd_recovery_max_active",
                    "value": "3",
                }
            )
            assert rv == 0
            await wait_until(
                lambda: osds[1].conf.get("osd_recovery_max_active") == 3,
                3.0,
                "named config push",
            )
            assert osds[0].conf.get("osd_recovery_max_active") == 11

            # `config rm` of the last defining layer reverts live daemons to
            # the option default (md_config_t resets removed options).
            for who in ("osd.1", "osd"):
                rv, _, _ = await client.mon_command(
                    {"prefix": "config rm", "who": who, "name": "osd_recovery_max_active"}
                )
                assert rv == 0
            default = osds[0].conf.get_option("osd_recovery_max_active").default
            await wait_until(
                lambda: all(
                    o.conf.get("osd_recovery_max_active") == default for o in osds
                ),
                3.0,
                "config revert to default",
            )

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestLogMonitor:
    def test_clog_error_reaches_log_last(self):
        """An OSD clog_error lands in the committed cluster log, queryable
        via `log last` from any mon (the ECBackend CRC-mismatch sink)."""

        async def run():
            monmap, mons, osds = await start_cluster(3, 1)
            client = Rados(monmap)
            await client.connect()

            osds[0].clog_error("pg 1.0 scrub: oid inconsistent on shard 2")
            await wait_until(
                lambda: any("inconsistent" in e["msg"] for m in mons for e in m.logmon.entries),
                3.0,
                "clog entry committed",
            )
            # All quorum members converge on the same log version.
            await wait_until(
                lambda: all(m.logmon.version == mons[0].logmon.version for m in mons),
                3.0,
                "log versions converge",
            )

            rv, _, out = await client.mon_command({"prefix": "log last", "num": 10})
            assert rv == 0
            got = json.loads(out)
            assert any("inconsistent" in e["msg"] for e in got["entries"])
            entry = next(e for e in got["entries"] if "inconsistent" in e["msg"])
            assert entry["prio"] == "error"
            assert entry["who"] == "osd.0"

            # Level filter.
            rv, _, out = await client.mon_command(
                {"prefix": "log last", "num": 10, "level": "info"}
            )
            assert not any(
                "inconsistent" in e["msg"] for e in json.loads(out)["entries"]
            )

            # num=0 is a version probe, not "everything".
            rv, _, out = await client.mon_command({"prefix": "log last", "num": 0})
            probe = json.loads(out)
            assert probe["entries"] == [] and probe["version"] >= 1

            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestAuthMonitor:
    def test_key_crud_replicates(self):
        async def run():
            monmap, mons = await start_mons(3)
            client = Rados(monmap)
            await client.connect()

            rv, _, out = await client.mon_command(
                {"prefix": "auth get-or-create", "entity": "client.admin"}
            )
            assert rv == 0
            created = json.loads(out)
            key = base64.b64decode(created["key"])
            assert len(key) == 16

            # get-or-create is idempotent; get returns the same key.
            rv, _, out = await client.mon_command(
                {"prefix": "auth get-or-create", "entity": "client.admin"}
            )
            assert json.loads(out)["key"] == created["key"]
            rv, _, out = await client.mon_command(
                {"prefix": "auth get", "entity": "client.admin"}
            )
            assert json.loads(out)["key"] == created["key"]

            rv, rs, _ = await client.mon_command(
                {"prefix": "auth add", "entity": "osd.0"}
            )
            assert rv == 0, rs
            rv, rs, _ = await client.mon_command(
                {"prefix": "auth add", "entity": "osd.0"}
            )
            assert rv == -17  # EEXIST

            rv, _, out = await client.mon_command({"prefix": "auth ls"})
            assert set(json.loads(out)) == {"client.admin", "osd.0"}

            # Quorum members share the authoritative keyring byte-for-byte.
            await wait_until(
                lambda: all(
                    m.authmon.keyring.dumps() == mons[0].authmon.keyring.dumps()
                    and len(m.authmon.keyring) == 2
                    for m in mons
                ),
                3.0,
                "keyrings converge",
            )

            rv, _, _ = await client.mon_command(
                {"prefix": "auth del", "entity": "osd.0"}
            )
            assert rv == 0
            rv, _, _ = await client.mon_command(
                {"prefix": "auth get", "entity": "osd.0"}
            )
            assert rv == -2  # ENOENT

            await client.shutdown()
            await stop_cluster(mons, [])

        asyncio.run(run())


def test_osd_pool_get():
    """`osd pool get <pool> <var>|all` (OSDMonitor get variants)."""

    async def run():
        import json

        from ceph_tpu.client import Rados
        from test_cluster import start_cluster, stop_cluster

        monmap, mons, osds = await start_cluster(1, 3)
        client = Rados(monmap)
        await client.connect()
        await client.pool_create("gp", "replicated", size=2)
        rv, _, out = await client.mon_command(
            {"prefix": "osd pool get", "pool": "gp", "var": "size"}
        )
        assert rv == 0 and json.loads(out) == {"size": 2}
        rv, _, out = await client.mon_command(
            {"prefix": "osd pool get", "pool": "gp"}
        )
        allinfo = json.loads(out)
        assert allinfo["pg_num"] > 0 and allinfo["quota_max_objects"] == 0
        rv, _, _ = await client.mon_command(
            {"prefix": "osd pool get", "pool": "gp", "var": "bogus"}
        )
        assert rv != 0
        rv, _, _ = await client.mon_command(
            {"prefix": "osd pool get", "pool": "nope"}
        )
        assert rv != 0
        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())


def test_pool_application_and_health():
    """`osd pool application enable/get` tagging and the standalone
    `health` command (application_metadata + ClusterHealth essence)."""

    async def run():
        import json

        from ceph_tpu.client import Rados
        from test_cluster import start_cluster, stop_cluster

        monmap, mons, osds = await start_cluster(1, 3)
        client = Rados(monmap)
        await client.connect()
        await client.pool_create("appp", "replicated", size=2)
        rv, rs, _ = await client.mon_command(
            {"prefix": "osd pool application enable", "pool": "appp",
             "app": "rbd"}
        )
        assert rv == 0, rs
        rv, _, out = await client.mon_command(
            {"prefix": "osd pool application get", "pool": "appp"}
        )
        assert json.loads(out) == {"application": "rbd"}
        # retagging to a different app is refused
        rv, _, _ = await client.mon_command(
            {"prefix": "osd pool application enable", "pool": "appp",
             "app": "rgw"}
        )
        assert rv != 0
        # the tag propagates to clients through the map
        def tagged():
            p = client.objecter.osdmap.get_pool("appp")
            return p is not None and p.application == "rbd"
        from test_cluster import wait_until
        await wait_until(tagged, 5.0, "application tag in client map")
        # health: standalone check payload
        rv, _, out = await client.mon_command({"prefix": "health"})
        assert rv == 0
        h = json.loads(out)
        assert h["status"] in ("HEALTH_OK", "HEALTH_WARN")
        await client.shutdown()
        await stop_cluster(mons, osds)

    asyncio.run(run())
