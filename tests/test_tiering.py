"""Cache tiering: overlay redirect, promotion on miss, writeback dirty
tracking, flush/evict, delete forwarding, and the tier agent.

Models the reference's cache-tier coverage (PrimaryLogPG
maybe_handle_cache / promote_object, OSDMonitor `osd tier *` commands,
qa/workunits tiering suites) over live clusters.
"""

import asyncio

import pytest

from ceph_tpu.client import Rados, RadosError

from test_cluster import start_cluster, stop_cluster, wait_until


async def _tiered_cluster(cache_mode="writeback", target_max_objects=0):
    monmap, mons, osds = await start_cluster(1, 3)
    client = Rados(monmap)
    await client.connect()
    await client.pool_create("base", "replicated", pg_num=4)
    await client.pool_create("hot", "replicated", pg_num=4)
    for prefix, cmd in [
        ("osd tier add", {"pool": "base", "tierpool": "hot"}),
        ("osd tier cache-mode", {"pool": "hot", "mode": cache_mode}),
        ("osd tier set-overlay", {"pool": "base", "overlaypool": "hot"}),
    ]:
        rv, rs, _ = await client.mon_command({"prefix": prefix, **cmd})
        assert rv == 0, (prefix, rs)
    if target_max_objects:
        rv, rs, _ = await client.mon_command(
            {
                "prefix": "osd pool set",
                "pool": "hot",
                "var": "target_max_objects",
                "val": str(target_max_objects),
            }
        )
        assert rv == 0, rs

    def overlaid():
        base = client.objecter.osdmap.get_pool("base")
        hot = client.objecter.osdmap.get_pool("hot")
        return (
            base is not None
            and hot is not None
            and base.read_tier == hot.id
            and hot.tier_of == base.id
            and hot.cache_mode == cache_mode
        )

    await wait_until(overlaid, 5.0, "overlay visible to client")
    return monmap, mons, osds, client


async def _remove_overlay(client):
    rv, rs, _ = await client.mon_command(
        {"prefix": "osd tier remove-overlay", "pool": "base"}
    )
    assert rv == 0, rs
    await wait_until(
        lambda: client.objecter.osdmap.get_pool("base").read_tier < 0,
        5.0,
        "overlay removed",
    )


class TestTierCommands:
    def test_tier_lifecycle_and_validation(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("base", "replicated", pg_num=4)
            await client.pool_create("hot", "replicated", pg_num=4)
            # overlay before tier add: rejected
            rv, rs, _ = await client.mon_command(
                {"prefix": "osd tier set-overlay", "pool": "base",
                 "overlaypool": "hot"}
            )
            assert rv != 0
            rv, _, _ = await client.mon_command(
                {"prefix": "osd tier add", "pool": "base", "tierpool": "hot"}
            )
            assert rv == 0
            # double-tiering rejected
            await client.pool_create("hot2", "replicated", pg_num=4)
            rv, rs, _ = await client.mon_command(
                {"prefix": "osd tier add", "pool": "hot", "tierpool": "hot2"}
            )
            assert rv != 0, "stacked tiers must be rejected"
            # overlay still needs a cache mode
            rv, _, _ = await client.mon_command(
                {"prefix": "osd tier set-overlay", "pool": "base",
                 "overlaypool": "hot"}
            )
            assert rv != 0
            rv, _, _ = await client.mon_command(
                {"prefix": "osd tier cache-mode", "pool": "hot",
                 "mode": "writeback"}
            )
            assert rv == 0
            rv, _, _ = await client.mon_command(
                {"prefix": "osd tier set-overlay", "pool": "base",
                 "overlaypool": "hot"}
            )
            assert rv == 0
            # removal requires dropping the overlay first
            rv, _, _ = await client.mon_command(
                {"prefix": "osd tier remove", "pool": "base", "tierpool": "hot"}
            )
            assert rv != 0
            rv, _, _ = await client.mon_command(
                {"prefix": "osd tier remove-overlay", "pool": "base"}
            )
            assert rv == 0
            rv, _, _ = await client.mon_command(
                {"prefix": "osd tier remove", "pool": "base", "tierpool": "hot"}
            )
            assert rv == 0
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestWriteback:
    def test_write_lands_in_cache_and_flushes_to_base(self):
        async def run():
            monmap, mons, osds, client = await _tiered_cluster()
            base_io = await client.open_ioctx("base")  # redirects to hot
            hot_io = await client.open_ioctx("hot")
            await base_io.write_full("obj", b"hot bytes")
            assert await base_io.read("obj") == b"hot bytes"
            # the cache pool holds it...
            assert "obj" in await hot_io.list_objects()
            # ...and the base does not until a flush
            await _remove_overlay(client)
            assert "obj" not in await base_io.list_objects()
            # re-overlay, flush, verify base copy
            rv, _, _ = await client.mon_command(
                {"prefix": "osd tier set-overlay", "pool": "base",
                 "overlaypool": "hot"}
            )
            assert rv == 0
            await wait_until(
                lambda: client.objecter.osdmap.get_pool("base").read_tier >= 0,
                5.0,
            )
            await hot_io.cache_flush("obj")
            await _remove_overlay(client)
            assert await base_io.read("obj") == b"hot bytes"
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_promote_on_miss_and_evict(self):
        async def run():
            monmap, mons, osds, client = await _tiered_cluster()
            hot_io = await client.open_ioctx("hot")
            base_io = await client.open_ioctx("base")
            # seed the BASE directly (no overlay interference: write via
            # overlay, flush, evict leaves only the base copy)
            await base_io.write_full("cold", b"base bytes")
            await hot_io.cache_flush("cold")
            await hot_io.cache_evict("cold")
            assert "cold" not in await hot_io.list_objects()
            # a read through the overlay misses -> promotes -> serves
            assert await base_io.read("cold") == b"base bytes"
            assert "cold" in await hot_io.list_objects()
            # promoted copy is CLEAN: evict works without a flush
            await hot_io.cache_evict("cold")
            assert "cold" not in await hot_io.list_objects()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_evict_dirty_is_ebusy(self):
        async def run():
            monmap, mons, osds, client = await _tiered_cluster()
            hot_io = await client.open_ioctx("hot")
            await hot_io.write_full("d", b"dirty")
            with pytest.raises(RadosError):
                await hot_io.cache_evict("d")
            await hot_io.cache_flush("d")
            await hot_io.cache_evict("d")  # clean now
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_delete_forwards_to_base(self):
        async def run():
            monmap, mons, osds, client = await _tiered_cluster()
            base_io = await client.open_ioctx("base")
            hot_io = await client.open_ioctx("hot")
            await base_io.write_full("gone", b"x" * 64)
            await hot_io.cache_flush("gone")
            # delete through the overlay: must remove BOTH copies, so a
            # later miss can't resurrect from the base
            await base_io.remove("gone")
            assert "gone" not in await hot_io.list_objects()
            with pytest.raises(RadosError):
                await base_io.read("gone")
            await _remove_overlay(client)
            assert "gone" not in await base_io.list_objects()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_xattrs_and_cls_state_survive_flush_evict_promote(self):
        """Client xattrs AND object-class state (cls_lock holders — what
        RBD exclusive locking keys on) must ride writeback and promotion;
        a flush+evict cycle must not destroy acknowledged metadata."""

        async def run():
            monmap, mons, osds, client = await _tiered_cluster()
            base_io = await client.open_ioctx("base")
            hot_io = await client.open_ioctx("hot")
            await base_io.write_full("meta", b"payload")
            await base_io.setxattr("meta", "user.tag", b"v1")
            # cls state: take an exclusive lock (stored as a cls xattr)
            import json

            await base_io.exec(
                "meta",
                "lock",
                "lock",
                json.dumps(
                    {"name": "l1", "type": "exclusive", "cookie": "c1"}
                ).encode(),
            )
            # flush + evict: only the base copy remains
            await hot_io.cache_flush("meta")
            await hot_io.cache_evict("meta")
            assert "meta" not in await hot_io.list_objects()
            # promote on miss: bytes AND metadata must come back
            assert await base_io.read("meta") == b"payload"
            assert await base_io.getxattr("meta", "user.tag") == b"v1"
            info = json.loads(
                await base_io.exec(
                    "meta", "lock", "get_info", json.dumps({"name": "l1"}).encode()
                )
            )
            assert info["holders"], "cls_lock state lost across flush/evict/promote"
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_copy_from_carries_xattrs(self):
        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("p", "replicated", pg_num=4)
            io = await client.open_ioctx("p")
            await io.write_full("src", b"bytes")
            await io.setxattr("src", "color", b"blue")
            await io.copy_from("dst", "src")
            assert await io.read("dst") == b"bytes"
            assert await io.getxattr("dst", "color") == b"blue"
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_readonly_mode_rejects_writes(self):
        async def run():
            monmap, mons, osds, client = await _tiered_cluster(
                cache_mode="readonly"
            )
            base_io = await client.open_ioctx("base")
            with pytest.raises(RadosError):
                await base_io.write_full("ro", b"nope")
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestTierAgent:
    def test_agent_flushes_and_evicts_to_target(self):
        async def run():
            # pool-wide target 4 over pg_num=4 -> each PG keeps <= 1 head
            monmap, mons, osds, client = await _tiered_cluster(
                target_max_objects=4
            )
            base_io = await client.open_ioctx("base")
            hot_io = await client.open_ioctx("hot")
            for i in range(12):
                await base_io.write_full(f"o{i}", f"payload{i}".encode())

            async def count_hot():
                return len(await hot_io.list_objects())

            deadline = asyncio.get_event_loop().time() + 10.0
            while await count_hot() > 4:
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(
                        f"agent never reached target: {await count_hot()} left"
                    )
                await asyncio.sleep(0.1)
            # every object still readable (flushed copies promote back)
            for i in range(12):
                assert await base_io.read(f"o{i}") == f"payload{i}".encode()
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestFlushWriteRace:
    def test_write_racing_flush_stays_dirty(self):
        """A write landing while its object is mid-flush must not get its
        dirty mark cleared by the flush's completion (lost-write hazard:
        a clean object can be evicted, resurrecting the pre-write bytes
        from the base).  Writes are queued behind the flush
        (PrimaryLogPG wait_for_blocked_object), so afterwards the cache
        holds v2 AND still reports dirty."""

        async def run():
            monmap, mons, osds, client = await _tiered_cluster()
            base_io = await client.open_ioctx("base")
            hot_io = await client.open_ioctx("hot")
            await base_io.write_full("r", b"v1")
            # concurrent flush + overwrite
            await asyncio.gather(
                hot_io.cache_flush("r"),
                base_io.write_full("r", b"v2"),
            )
            assert await base_io.read("r") == b"v2"
            # v2 must still be flush-pending: evict refuses
            with pytest.raises(RadosError):
                await hot_io.cache_evict("r")
            # flush again -> now clean -> evict works, base serves v2
            await hot_io.cache_flush("r")
            await hot_io.cache_evict("r")
            assert await base_io.read("r") == b"v2"  # re-promoted from base
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())


class TestPreexistingObjects:
    def test_evict_refuses_object_with_no_base_copy(self):
        """An object written into the cache pool BEFORE the tier
        relationship has no dirty mark and no base copy; evicting it would
        be permanent loss (the reference refuses non-empty tier pools
        outright).  Evict verifies the base copy exists and answers EBUSY;
        a flush creates the base copy, after which evict proceeds."""

        async def run():
            monmap, mons, osds = await start_cluster(1, 3)
            client = Rados(monmap)
            await client.connect()
            await client.pool_create("base", "replicated", pg_num=4)
            await client.pool_create("hot", "replicated", pg_num=4)
            hot_io = await client.open_ioctx("hot")
            await hot_io.write_full("pre", b"precious")  # pre-tiering
            for prefix, cmd in [
                ("osd tier add", {"pool": "base", "tierpool": "hot"}),
                ("osd tier cache-mode", {"pool": "hot", "mode": "writeback"}),
                ("osd tier set-overlay", {"pool": "base", "overlaypool": "hot"}),
            ]:
                rv, rs, _ = await client.mon_command({"prefix": prefix, **cmd})
                assert rv == 0, rs
            await wait_until(
                lambda: client.objecter.osdmap.get_pool("hot").tier_of >= 0,
                5.0,
            )
            with pytest.raises(RadosError):
                await hot_io.cache_evict("pre")
            assert "pre" in await hot_io.list_objects()  # still there
            await hot_io.cache_flush("pre")  # clean AND base-backed now
            await hot_io.cache_evict("pre")
            base_io = await client.open_ioctx("base")
            assert await base_io.read("pre") == b"precious"  # re-promotes
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())

    def test_copy_from_cold_source_promotes(self):
        """COPY_FROM with a base-resident (evicted) source: the gate must
        promote the source before the internal fetch, which bypasses the
        tier gate."""

        async def run():
            monmap, mons, osds, client = await _tiered_cluster()
            base_io = await client.open_ioctx("base")
            hot_io = await client.open_ioctx("hot")
            await base_io.write_full("src", b"the source bytes")
            await hot_io.cache_flush("src")
            await hot_io.cache_evict("src")
            assert "src" not in await hot_io.list_objects()
            await base_io.copy_from("dst", "src")
            assert await base_io.read("dst") == b"the source bytes"
            await client.shutdown()
            await stop_cluster(mons, osds)

        asyncio.run(run())
