"""EncodeAggregator semantics (ISSUE 3 satellite contract).

Covers: ticket ordering, window/byte-budget/explicit flush triggers, the
"64 stripes across 8 submitters <= 2 device dispatches" launch-counter
invariant, padding correctness, the donation pool, flush-on-commit through
a full ECBackend write pipeline, and the prometheus export of the
occupancy/launch-size histograms."""

import numpy as np
import pytest

from ceph_tpu.codec import ErasureCodeTpuRs
from ceph_tpu.codec.matrix_codec import EncodeAggregator
from ceph_tpu.common.perf_counters import PerfCountersCollection
from ceph_tpu.gf.bitslice import expand_matrix, xor_matmul_host
from ceph_tpu.ops.dispatch import LAUNCHES
from ceph_tpu.stripe import StripeInfo
from ceph_tpu.stripe import stripe as stripe_mod


def make_rs(k=4, m=2):
    ec = ErasureCodeTpuRs()
    ec.init({"k": str(k), "m": str(m)})
    return ec


def payload(sinfo, stripes, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, stripes * sinfo.stripe_width, dtype=np.uint8)


def parity_oracle(ec, data, sinfo):
    bm = expand_matrix(ec.distribution_matrix()[ec.k :])
    shaped = data.reshape(-1, ec.k, sinfo.chunk_size)
    return np.stack([xor_matmul_host(bm, s) for s in shaped])


class TestAggregatorCore:
    def setup_method(self):
        self.ec = make_rs(4, 2)
        self.sinfo = StripeInfo(4 * 4096, 4096)

    def test_64_stripes_8_submitters_at_most_2_dispatches(self):
        agg = EncodeAggregator(window=8)
        pends = []
        before = LAUNCHES.snapshot()["launches"]
        for w in range(8):
            data = payload(self.sinfo, 8, seed=w)
            pends.append(
                (data, stripe_mod.encode_launch(self.sinfo, self.ec, data, aggregator=agg))
            )
        agg.flush()
        launches = LAUNCHES.snapshot()["launches"] - before
        assert launches <= 2, launches
        # every submitter gets ITS parity back, byte-exact
        for data, pend in pends:
            shards = pend.result()
            want = parity_oracle(self.ec, data, self.sinfo)
            for i in range(2):
                assert np.array_equal(
                    shards[4 + i].reshape(-1, 4096), want[:, i, :]
                )

    def test_window_trigger_and_pending(self):
        agg = EncodeAggregator(window=4)
        pends = [
            stripe_mod.encode_launch(
                self.sinfo, self.ec, payload(self.sinfo, 1, seed=i), aggregator=agg
            )
            for i in range(3)
        ]
        assert agg.pending() == 3
        assert not any(p.launched() for p in pends)
        assert not any(p.ready() for p in pends)
        # the 4th submission fills the window and launches everything
        p4 = stripe_mod.encode_launch(
            self.sinfo, self.ec, payload(self.sinfo, 1, seed=9), aggregator=agg
        )
        assert agg.pending() == 0
        assert all(p.launched() for p in pends) and p4.launched()
        assert agg.perf.get("flush_window") == 1

    def test_byte_budget_trigger(self):
        agg = EncodeAggregator(window=1000, max_bytes=3 * self.sinfo.stripe_width)
        stripe_mod.encode_launch(
            self.sinfo, self.ec, payload(self.sinfo, 1, seed=0), aggregator=agg
        )
        assert agg.pending() == 1
        stripe_mod.encode_launch(
            self.sinfo, self.ec, payload(self.sinfo, 2, seed=1), aggregator=agg
        )
        assert agg.pending() == 0
        assert agg.perf.get("flush_bytes") == 1

    def test_reap_forces_launch(self):
        """Materializing a windowed ticket must flush its group rather
        than deadlock (the commit path depends on this)."""
        agg = EncodeAggregator(window=100)
        data = payload(self.sinfo, 2, seed=3)
        pend = stripe_mod.encode_launch(self.sinfo, self.ec, data, aggregator=agg)
        assert not pend.launched()
        shards = pend.result()
        want = parity_oracle(self.ec, data, self.sinfo)
        assert np.array_equal(shards[4].reshape(-1, 4096), want[:, 0, :])
        assert agg.perf.get("flush_reap") == 1

    def test_ticket_ordering_across_interleaved_geometries(self):
        """Interleaved submissions of two geometries: each ticket resolves
        to its own submission's parity, in order."""
        ec2 = make_rs(2, 1)
        sinfo2 = StripeInfo(2 * 4096, 4096)
        agg = EncodeAggregator(window=100)
        subs = []
        for i in range(6):
            if i % 2:
                d = payload(sinfo2, 1, seed=100 + i)
                subs.append((ec2, sinfo2, d, stripe_mod.encode_launch(sinfo2, ec2, d, aggregator=agg)))
            else:
                d = payload(self.sinfo, 2, seed=100 + i)
                subs.append((self.ec, self.sinfo, d, stripe_mod.encode_launch(self.sinfo, self.ec, d, aggregator=agg)))
        agg.flush()
        for ec, sinfo, d, pend in subs:
            shards = pend.result()
            want = parity_oracle(ec, d, sinfo)
            assert np.array_equal(
                shards[ec.k].reshape(-1, sinfo.chunk_size), want[:, 0, :]
            )

    def test_padding_to_pow2_sliced_back(self):
        agg = EncodeAggregator(window=100)
        data = payload(self.sinfo, 3, seed=5)
        pend = stripe_mod.encode_launch(self.sinfo, self.ec, data, aggregator=agg)
        agg.flush()
        shards = pend.result()
        assert agg.perf.get("pad_stripes") == 1  # 3 -> 4
        want = parity_oracle(self.ec, data, self.sinfo)
        assert np.array_equal(shards[4].reshape(-1, 4096), want[:, 0, :])
        assert shards[4].size == 3 * 4096

    def test_donation_pool_recycled_across_launches(self):
        # multi-ticket groups go through the pooled (forced-copy) path;
        # two rounds at the same padded shape exercise buffer reuse
        agg = EncodeAggregator(window=100)
        for round_seed in (0, 2):
            pends = [
                (
                    d := payload(self.sinfo, 2, seed=round_seed + i),
                    stripe_mod.encode_launch(self.sinfo, self.ec, d, aggregator=agg),
                )
                for i in range(2)
            ]
            agg.flush()
            for data, pend in pends:
                shards = pend.result()  # materialization recycles the buffer
                want = parity_oracle(self.ec, data, self.sinfo)
                assert np.array_equal(shards[5].reshape(-1, 4096), want[:, 1, :])
        # pool holds exactly the one (4, 2, 4096) parity buffer shape
        assert list(agg._donate_pool) == [(4, 2, 4096)]

    def test_single_ticket_unpadded_group_skips_pool(self):
        """The default-path optimization: a lone submission's parity is
        handed through without the forced host copy or pool recycling."""
        agg = EncodeAggregator(window=0)
        data = payload(self.sinfo, 4, seed=7)
        pend = stripe_mod.encode_launch(self.sinfo, self.ec, data, aggregator=agg)
        shards = pend.result()
        want = parity_oracle(self.ec, data, self.sinfo)
        assert np.array_equal(shards[4].reshape(-1, 4096), want[:, 0, :])
        assert not agg._donate_pool

    def test_immediate_mode_still_counts_metrics(self):
        agg = EncodeAggregator(window=0)
        data = payload(self.sinfo, 2, seed=8)
        pend = stripe_mod.encode_launch(self.sinfo, self.ec, data, aggregator=agg)
        assert pend.launched()
        pend.result()
        assert agg.perf.get("submits") == 1
        assert agg.perf.get("launches") == 1
        assert agg.perf.get("flush_immediate") == 1
        # immediate mode must not pad: the direct path never did
        assert agg.perf.get("pad_stripes") == 0

    def test_prometheus_export_has_histogram_families(self):
        agg = EncodeAggregator(window=2)
        for i in range(2):
            stripe_mod.encode_launch(
                self.sinfo, self.ec, payload(self.sinfo, 1, seed=i), aggregator=agg
            )
        coll = PerfCountersCollection()
        coll.add(agg.perf)
        text = coll.prometheus_text()
        for family in ("stripes_per_launch", "tickets_per_launch", "launch_bytes"):
            assert f"ceph_tpu_ec_aggregator_{family}_bucket" in text
            assert f"ceph_tpu_ec_aggregator_{family}_count" in text


class TestFlushOnCommit:
    """The ECBackend commit barrier must drain the aggregation window:
    writes submitted into a wide-open window still commit, and their
    shard bytes land byte-exact."""

    def _cluster(self, window):
        from test_ec_backend import Cluster, ec_pool

        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        agg = EncodeAggregator(window=window)
        for b in c.backends:
            b.encode_aggregator = agg
        return c, agg

    def test_windowed_writes_commit_and_verify(self):
        from ceph_tpu.msg.messages import ReqId
        from ceph_tpu.osd.ec_transaction import PGTransaction

        c, agg = self._cluster(window=64)
        rng = np.random.default_rng(0)
        done = []
        datas = {}
        for i in range(5):
            oid = f"obj{i}"
            datas[oid] = rng.integers(
                0, 256, 2 * c.pool.stripe_width, dtype=np.uint8
            ).tobytes()
            pgt = PGTransaction(oid).write(0, datas[oid])
            c.primary.submit_transaction(
                pgt, ReqId("client", i), lambda i=i: done.append(i)
            )
        # encodes submitted but windowed: nothing committed yet
        assert agg.pending() > 0
        c.pump()  # flush_encodes drains the window (the commit barrier)
        assert sorted(done) == list(range(5))
        assert agg.pending() == 0
        for oid, data in datas.items():
            assert c.read(oid, 0, len(data)) == data

    def test_flush_encodes_drains_everything(self):
        from ceph_tpu.msg.messages import ReqId
        from ceph_tpu.osd.ec_transaction import PGTransaction

        c, agg = self._cluster(window=64)
        for i in range(3):
            pgt = PGTransaction(f"o{i}").write(0, bytes(c.pool.stripe_width))
            c.primary.submit_transaction(pgt, ReqId("cl", i), lambda: None)
        assert agg.pending() == 3
        c.primary.flush_encodes()
        assert agg.pending() == 0


class TestAggregatorRobustness:
    def setup_method(self):
        self.ec = make_rs(4, 2)
        self.sinfo = StripeInfo(4 * 4096, 4096)

    def test_pad_target_bucketing_is_capped(self):
        agg = EncodeAggregator(window=2)
        assert agg._pad_target(1) == 1
        assert agg._pad_target(3) == 4
        assert agg._pad_target(64) == 64
        assert agg._pad_target(65) == 128
        # beyond 64, multiples of 64 — never the up-to-2x of pure pow2
        assert agg._pad_target(260) == 320
        assert agg._pad_target(1000) == 1024

    def test_failed_launch_is_sticky_and_reported_to_coriders(self):
        from ceph_tpu.codec.interface import EcError

        agg = EncodeAggregator(window=2)
        data1 = payload(self.sinfo, 1, seed=0)
        pend1 = stripe_mod.encode_launch(self.sinfo, self.ec, data1, aggregator=agg)

        real = self.ec.encode_array
        real_host = self.ec.encode_array_host

        def boom(data, out=None):
            # both the device dispatch AND the host-oracle fallback fail:
            # only then is the error sticky (a device-only failure now
            # completes on the host, ISSUE 7)
            raise RuntimeError("injected device OOM")

        self.ec.encode_array = boom
        self.ec.encode_array_host = boom
        try:
            # second submission trips the window; its launch fails, but
            # submit must NOT raise into an arbitrary co-rider's write —
            # the error is sticky on the group and reported at reap
            pend2 = stripe_mod.encode_launch(
                self.sinfo, self.ec, payload(self.sinfo, 1, seed=1), aggregator=agg
            )
        finally:
            self.ec.encode_array = real
            self.ec.encode_array_host = real_host
        # every co-rider's reap reports the failure instead of crashing
        # on a half-torn group, and polling sees it as "ready" (reapable)
        for pend in (pend1, pend2):
            assert pend.ready()
            with pytest.raises(EcError):
                pend.result()

    def test_ecbackend_fails_ops_cleanly_on_launch_failure(self):
        """A failed aggregated launch must fail the affected write ops
        (on_failure fires, pins released, no in_flight leak) — not leak
        an exception out of the commit barrier."""
        from test_ec_backend import Cluster, ec_pool

        from ceph_tpu.msg.messages import ReqId
        from ceph_tpu.osd.ec_transaction import PGTransaction

        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        agg = EncodeAggregator(window=64)
        for b in c.backends:
            b.encode_aggregator = agg
        primary = c.primary
        real = primary.ec.encode_array
        real_host = primary.ec.encode_array_host

        def boom(data, out=None):
            # device AND host-oracle failure: the truly-unrecoverable case
            raise RuntimeError("injected launch failure")

        primary.ec.encode_array = boom
        primary.ec.encode_array_host = boom
        outcomes = []
        try:
            for i in range(2):
                pgt = PGTransaction(f"f{i}").write(0, bytes(pool.stripe_width))
                primary.submit_transaction(
                    pgt,
                    ReqId("cl", i),
                    lambda i=i: outcomes.append(("commit", i)),
                    on_failure=lambda err, i=i: outcomes.append(("fail", i, err)),
                )
            primary.flush_encodes()  # barrier must not throw
        finally:
            primary.ec.encode_array = real
            primary.ec.encode_array_host = real_host
        assert [(o[0], o[1]) for o in outcomes] == [("fail", 0), ("fail", 1)]
        assert all(o[2] < 0 for o in outcomes)  # negative errno convention
        assert not primary.in_flight
        assert not primary._projected
        # the backend recovers: the same objects write fine afterwards
        data = np.random.default_rng(1).integers(
            0, 256, pool.stripe_width, dtype=np.uint8
        ).tobytes()
        c.write("f0", 0, data)
        assert c.read("f0", 0, len(data)) == data

    def test_launch_failure_dooms_later_encoded_writes_same_object(self):
        """A later write on the same object may already be encoded against
        projected state embedding the failed write's bytes — committing it
        would persist a write the client was told failed, so the chain
        abort must doom it too."""
        from test_ec_backend import Cluster, ec_pool

        from ceph_tpu.msg.messages import ReqId
        from ceph_tpu.osd.ec_transaction import PGTransaction

        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        agg = EncodeAggregator(window=64)
        for b in c.backends:
            b.encode_aggregator = agg
        primary = c.primary
        real = primary.ec.encode_array
        real_host = primary.ec.encode_array_host

        def boom_two_stripes(data, out=None):
            if data.shape[0] == 2:  # only W1's 2-stripe group fails
                raise RuntimeError("injected launch failure")
            return real(data, out=out)

        def boom_two_stripes_host(data):
            if data.shape[0] == 2:  # the host fallback fails identically
                raise RuntimeError("injected launch failure")
            return real_host(data)

        sw = pool.stripe_width
        outcomes = []
        primary.ec.encode_array = boom_two_stripes
        primary.ec.encode_array_host = boom_two_stripes_host
        try:
            w1 = PGTransaction("fx").write(0, bytes(2 * sw))
            primary.submit_transaction(
                w1, ReqId("cl", 1),
                lambda: outcomes.append(("commit", 1)),
                on_failure=lambda err: outcomes.append(("fail", 1, err)),
            )
            # W1's group launches now and fails (sticky); W2 then encodes
            # into a NEW group that succeeds — only the chain abort at
            # W1's reap can stop W2's commit
            agg.flush()
            w2 = PGTransaction("fx").write(2 * sw, bytes(sw))
            primary.submit_transaction(
                w2, ReqId("cl", 2),
                lambda: outcomes.append(("commit", 2)),
                on_failure=lambda err: outcomes.append(("fail", 2, err)),
            )
            c.pump()
        finally:
            primary.ec.encode_array = real
            primary.ec.encode_array_host = real_host
        assert [(o[0], o[1]) for o in outcomes] == [("fail", 1), ("fail", 2)]
        assert not primary.in_flight and not primary._projected
        # neither write landed: the object does not exist on any shard
        assert primary.object_size("fx") == 0

    def test_stale_rmw_read_cannot_resurrect_doomed_op(self):
        """An op doomed by an earlier same-object encode failure while its
        RMW reads were in flight must stay dead when the read completes —
        not re-encode and persist bytes its client saw fail."""
        from test_ec_backend import Cluster, ec_pool, payload as mk_payload

        from ceph_tpu.msg.messages import ReqId
        from ceph_tpu.osd.ec_transaction import PGTransaction
        from ceph_tpu.osd.osdmap import FLAG_EC_OVERWRITES

        pool, profiles = ec_pool(4, 2, flags=FLAG_EC_OVERWRITES)
        c = Cluster(pool, profiles)
        agg = EncodeAggregator(window=64)
        for b in c.backends:
            b.encode_aggregator = agg
        primary = c.primary
        sw = pool.stripe_width
        base = mk_payload(2 * sw, seed=5)
        c.write("rx", 0, base)  # pre-existing 2-stripe object

        real = primary.ec.encode_array
        real_host = primary.ec.encode_array_host
        armed = [True]

        def boom_once(data, out=None):
            if armed[0]:
                raise RuntimeError("injected launch failure")
            return real(data, out=out)

        def boom_once_host(data):
            # the host fallback fails the same launch, then disarms: the
            # pair models ONE launch no path can compute
            if armed[0]:
                armed[0] = False
                raise RuntimeError("injected launch failure")
            return real_host(data)

        outcomes = []
        # W1: full-stripe overwrite (no RMW read); stays windowed
        primary.submit_transaction(
            PGTransaction("rx").write(0, bytes(sw)),
            ReqId("cl", 1),
            lambda: outcomes.append(("commit", 1)),
            on_failure=lambda err: outcomes.append(("fail", 1, err)),
        )
        # W2: partial overwrite of stripe 1 -> issues RMW reads (async)
        primary.submit_transaction(
            PGTransaction("rx").write(sw, b"\xAA" * 100),
            ReqId("cl", 2),
            lambda: outcomes.append(("commit", 2)),
            on_failure=lambda err: outcomes.append(("fail", 2, err)),
        )
        primary.ec.encode_array = boom_once
        primary.ec.encode_array_host = boom_once_host
        try:
            agg.flush()  # W1's group launches and fails, sticky
            primary.flush_encodes()  # W1 reap fails -> dooms W2 too
        finally:
            primary.ec.encode_array = real
            primary.ec.encode_array_host = real_host
        assert [(o[0], o[1]) for o in outcomes] == [("fail", 1), ("fail", 2)]
        c.pump()  # delivers W2's stale RMW read replies
        assert [(o[0], o[1]) for o in outcomes] == [("fail", 1), ("fail", 2)]
        assert not primary.in_flight
        # neither overwrite landed: the object still holds the base bytes
        assert c.read("rx", 0, 2 * sw) == base

    def test_failure_preserves_projection_for_dispatched_survivor(self):
        """When a later write's encode fails while an earlier write on the
        same object is dispatched-but-uncommitted, the next write must
        plan against the survivor's size, not the stale on-disk size."""
        from test_ec_backend import Cluster, ec_pool, payload as mk_payload

        from ceph_tpu.msg.messages import ReqId
        from ceph_tpu.osd.ec_transaction import PGTransaction

        pool, profiles = ec_pool(4, 2)
        c = Cluster(pool, profiles)
        agg = EncodeAggregator(window=64)
        for b in c.backends:
            b.encode_aggregator = agg
        primary = c.primary
        sw = pool.stripe_width
        real = primary.ec.encode_array
        real_host = primary.ec.encode_array_host
        armed = [False]

        def boom_when_armed(data, out=None):
            if armed[0]:
                raise RuntimeError("injected launch failure")
            return real(data, out=out)

        def boom_when_armed_host(data):
            if armed[0]:
                armed[0] = False  # one launch, failed on both paths
                raise RuntimeError("injected launch failure")
            return real_host(data)

        outcomes = []
        d1 = mk_payload(sw, seed=11)
        # W1 commits-in-progress: encode + dispatch sub-writes, but do NOT
        # deliver the commit replies yet (pending_commits stays non-empty)
        primary.submit_transaction(
            PGTransaction("px").write(0, d1),
            ReqId("cl", 1),
            lambda: outcomes.append("commit1"),
        )
        primary.flush_encodes()  # W1 dispatched; replies queued, undelivered
        assert primary.in_flight and not outcomes
        # W2 appends at sw (planned against projection size sw); its
        # launch fails at reap
        primary.ec.encode_array = boom_when_armed
        primary.ec.encode_array_host = boom_when_armed_host
        try:
            primary.submit_transaction(
                PGTransaction("px").write(sw, bytes(sw)),
                ReqId("cl", 2),
                lambda: outcomes.append("commit2"),
                on_failure=lambda err: outcomes.append(("fail2", err)),
            )
            armed[0] = True
            agg.flush()
            primary.flush_encodes()
        finally:
            primary.ec.encode_array = real
            primary.ec.encode_array_host = real_host
        assert ("fail2" in [o[0] if isinstance(o, tuple) else o for o in outcomes])
        # W1 survives: projection still reflects ITS planned size, so W3
        # (an append at sw) plans correctly even before W1's commits land
        assert primary._projected["px"]["size"] == sw
        d3 = mk_payload(sw, seed=12)
        primary.submit_transaction(
            PGTransaction("px").write(sw, d3),
            ReqId("cl", 3),
            lambda: outcomes.append("commit3"),
        )
        c.pump()  # delivers everything: W1 + W3 commit
        assert "commit1" in outcomes and "commit3" in outcomes
        assert c.read("px", 0, 2 * sw) == d1 + d3
